"""fluid.layers compatibility bridge — the remaining `__all__` names.

Closes the audited gap between the reference fluid.layers namespace
(/root/reference/python/paddle/fluid/layers/__init__.py, 305 names) and
paddle_tpu.static. Three mechanisms:

- graph-built LR schedules (reference learning_rate_scheduler.py): each
  decay builds a Variable from autoincreased_step_counter so the rate
  updates inside the compiled program; static optimizers accept that
  Variable directly.
- delegates over existing eager implementations (losses,
  sequence ops, detection ops) via layers_ext._register_delegate — one
  jnp implementation per op across eager/jit/static.
- RNN sweep ops (dynamic_lstm/dynamic_gru/lstm/gru_unit/lstm_unit) as
  parameter-creating facades over lax.scan kernels, plus hsigmoid,
  warpctc (optax.ctc_loss), hash, auc, and the distribution classes.

Documented non-goals stay out: LoD-mutation ops (lod_reset/append,
reorder_lod_tensor_by_rank), SelectedRows ops, the legacy py_reader
family (superseded by DataLoader), and Baidu-internal ops
(filter_by_instag/continuous_value_model) — see COVERAGE.md §2.4.
The two-stage detection family (rpn_target_assign, generate_proposals,
distribute_fpn_proposals, deformable_conv) lives in vision/rcnn.py and
is re-exported here (round 3), retinanet_target_assign included.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from .layers import LayerHelper, _append_simple, autoincreased_step_counter
from .layers_ext import _delegate, _register_delegate

# ---------------------------------------------------------------------------
# distributions (fluid.layers.Normal & co. re-export the distribution pkg)
# ---------------------------------------------------------------------------
from ..distribution import (  # noqa: F401
    Categorical, MultivariateNormalDiag, Normal, Uniform,
)

# ---------------------------------------------------------------------------
# graph-built LR schedules (learning_rate_scheduler.py): Variables derived
# from the in-program step counter, consumable as Optimizer learning_rate
# ---------------------------------------------------------------------------


def _step_counter():
    from ..utils import unique_name
    from . import layers as L

    # one PRIVATE counter per schedule: several schedules sharing the
    # reference's global @LR_DECAY_COUNTER@ would each append an
    # increment op and advance it N times per run
    return L.cast(autoincreased_step_counter(
        counter_name=unique_name.generate("@lr_decay_counter@")),
        "float32")


_floor = _delegate("floor_s", jnp.floor)
_elementwise_min_s = _delegate("elementwise_min_lr_s", jnp.minimum, n_in=2)


def noam_decay(d_model, warmup_steps, learning_rate=1.0):
    from . import layers as L
    from .layers_ext import pow as _pow

    step = _step_counter()
    a = _pow(step, -0.5)
    b = L.scale(step, scale=float(warmup_steps) ** -1.5)
    return L.scale(_elementwise_min_s(a, b),
                   scale=float(learning_rate) * float(d_model) ** -0.5)


def exponential_decay(learning_rate, decay_steps, decay_rate,
                      staircase=False):
    from . import layers as L

    t = L.scale(_step_counter(), scale=1.0 / float(decay_steps))
    if staircase:
        t = _floor(t)
    # lr * rate^t = lr * exp(t * ln(rate))
    from .layers_ext import pow as _pow  # noqa: F401

    expo = L.exp(L.scale(t, scale=math.log(decay_rate)))
    return L.scale(expo, scale=float(learning_rate))


def natural_exp_decay(learning_rate, decay_steps, decay_rate,
                      staircase=False):
    from . import layers as L

    t = L.scale(_step_counter(), scale=1.0 / float(decay_steps))
    if staircase:
        t = _floor(t)
    return L.scale(L.exp(L.scale(t, scale=-float(decay_rate))),
                   scale=float(learning_rate))


def inverse_time_decay(learning_rate, decay_steps, decay_rate,
                       staircase=False):
    from . import layers as L

    t = L.scale(_step_counter(), scale=1.0 / float(decay_steps))
    if staircase:
        t = _floor(t)
    denom = L.scale(t, scale=float(decay_rate), bias=1.0)
    return L.elementwise_div(
        L.fill_constant([1], "float32", float(learning_rate)), denom)


def polynomial_decay(learning_rate, decay_steps, end_learning_rate=0.0001,
                     power=1.0, cycle=False):
    from . import layers as L
    from .layers_ext import pow as _pow

    step = _step_counter()
    if cycle:
        div = _floor(L.scale(step, scale=1.0 / float(decay_steps)))
        # ceil for step>0: floor((step-1)/N)+1 approximated by max(div,1)
        div = L.elementwise_max(div, L.fill_constant([1], "float32", 1.0))
        ds = L.scale(div, scale=float(decay_steps))
    else:
        ds = L.fill_constant([1], "float32", float(decay_steps))
        step = _elementwise_min_s(step, ds)
    frac = _pow(L.scale(L.elementwise_div(step, ds), scale=-1.0, bias=1.0),
                float(power))
    return L.scale(frac, scale=float(learning_rate - end_learning_rate),
                   bias=float(end_learning_rate))


def piecewise_decay(boundaries, values):
    from . import layers as L

    step = _step_counter()
    lr = L.fill_constant([1], "float32", float(values[-1]))
    # build from the last boundary backwards: step < b -> values[i]
    for b, v in zip(reversed(boundaries), reversed(values[:-1])):
        cond = L.cast(L.less_than(
            step, L.fill_constant([1], "float32", float(b))), "float32")
        lr = L.elementwise_add(
            L.elementwise_mul(cond, L.fill_constant([1], "float32",
                                                    float(v))),
            L.elementwise_mul(L.scale(cond, scale=-1.0, bias=1.0), lr))
    return lr


_cos = _delegate("cos_s", jnp.cos)


def cosine_decay(learning_rate, step_each_epoch, epochs):
    from . import layers as L

    epoch = _floor(L.scale(_step_counter(),
                           scale=1.0 / float(step_each_epoch)))
    cos = _cos(L.scale(epoch, scale=math.pi / float(epochs)))
    return L.scale(cos, scale=0.5 * float(learning_rate),
                   bias=0.5 * float(learning_rate))


def linear_lr_warmup(learning_rate, warmup_steps, start_lr, end_lr):
    from . import layers as L

    step = _step_counter()
    warm = L.scale(step, scale=(float(end_lr) - float(start_lr))
                   / float(warmup_steps), bias=float(start_lr))
    if not isinstance(learning_rate, (int, float)):
        after = learning_rate            # a decay Variable composes
    else:
        after = L.fill_constant([1], "float32", float(learning_rate))
    cond = L.cast(L.less_than(
        step, L.fill_constant([1], "float32", float(warmup_steps))),
        "float32")
    return L.elementwise_add(
        L.elementwise_mul(cond, warm),
        L.elementwise_mul(L.scale(cond, scale=-1.0, bias=1.0), after))


# ---------------------------------------------------------------------------
# losses (delegates over nn.functional)
# ---------------------------------------------------------------------------
from ..nn import functional as F  # noqa: E402


def _loss2(op, fn, in_slots=("X", "Label")):
    build = _delegate(op, fn, in_slots=in_slots)

    def f(*xs, **kw):
        return build(*xs, **kw)

    return f


mse_loss = _loss2("mse_loss_s",
                  lambda x, y: F.mse_loss(x, y, reduction="mean"))
huber_loss = _loss2("huber_loss_s",
                    lambda x, y, delta=1.0:
                    F.huber_loss(x, y, delta, reduction="none"))
kldiv_loss = _loss2("kldiv_loss_s",
                    lambda x, target, reduction="mean":
                    F.kl_div(x, target, reduction))
bpr_loss = _loss2("bpr_loss_s", lambda x, label: F.bpr_loss(x, label))
sigmoid_cross_entropy_with_logits = _loss2(
    "sigmoid_ce_s",
    lambda x, label, ignore_index=-100, normalize=False:
    _sigmoid_ce(x, label, ignore_index, normalize))


def _sigmoid_ce(x, label, ignore_index, normalize):
    loss = jnp.maximum(x, 0) - x * label + jnp.log1p(jnp.exp(-jnp.abs(x)))
    keep = (label != ignore_index).astype(loss.dtype)
    loss = loss * keep
    if normalize:
        loss = loss / jnp.maximum(jnp.sum(keep), 1.0)
    return loss


def sigmoid_focal_loss(x, label, fg_num=None, gamma=2.0, alpha=0.25):
    """Focal loss for dense detection (sigmoid_focal_loss_op.cc).
    x (N, C) logits; label (N, 1) int class ids (0 = background);
    fg_num optional (1,) normalizer."""
    _register_delegate("sigmoid_focal_loss_s", _focal_fn,
                       in_slots=("X", "Label", "FgNum"))
    ins = {"X": [x.name], "Label": [label.name]}
    if fg_num is not None:
        ins["FgNum"] = [fg_num.name]
    return _append_simple("sigmoid_focal_loss_s", ins,
                          {"gamma": float(gamma), "alpha": float(alpha)})


def _focal_fn(x, label, fg_num=None, gamma=2.0, alpha=0.25):
    n, c = x.shape
    lbl = label.reshape(-1)
    # per-class one-vs-all targets; class ids are 1-based (0=background)
    t = (lbl[:, None] == (jnp.arange(c)[None, :] + 1)).astype(x.dtype)
    p = jax.nn.sigmoid(x)
    ce = jnp.maximum(x, 0) - x * t + jnp.log1p(jnp.exp(-jnp.abs(x)))
    pt = jnp.where(t > 0, p, 1.0 - p)
    a = jnp.where(t > 0, alpha, 1.0 - alpha)
    loss = a * (1.0 - pt) ** gamma * ce
    if fg_num is not None:
        loss = loss / jnp.maximum(fg_num.reshape(()).astype(x.dtype), 1.0)
    return loss


rank_loss = _loss2("rank_loss_s",
                   lambda label, left, right: F.rank_loss(label, left,
                                                          right),
                   in_slots=("Label", "Left", "Right"))
margin_rank_loss = _loss2(
    "margin_rank_loss_s",
    lambda label, left, right, margin=0.1:
    F.margin_rank_loss(label, left, right, margin),
    in_slots=("Label", "Left", "Right"))
npair_loss = _loss2("npair_loss_s",
                    lambda anchor, positive, labels, l2_reg=0.002:
                    F.npair_loss(anchor, positive, labels, l2_reg),
                    in_slots=("Anchor", "Positive", "Labels"))
teacher_student_sigmoid_loss = _loss2(
    "ts_sigmoid_loss_s",
    lambda x, label, soft_max_up_bound=15.0, soft_max_lower_bound=-15.0:
    F.teacher_student_sigmoid_loss(x, label, soft_max_up_bound,
                                   soft_max_lower_bound))


def center_loss(input, label, num_classes, alpha, param_attr=None,
                update_center=True):
    """Center loss with a learnable centers table (center_loss_op.cc)."""
    helper = LayerHelper("center_loss_s")
    d = int(input.shape[-1])
    centers = helper.create_parameter(shape=[int(num_classes), d],
                                      dtype="float32", attr=param_attr)
    _register_delegate(
        "center_loss_s",
        lambda x, label, centers, alpha=0.1:
        F.center_loss(x, label, centers, alpha),
        in_slots=("X", "Label", "Centers"))
    return _append_simple("center_loss_s",
                          {"X": [input.name], "Label": [label.name],
                           "Centers": [centers.name]},
                          {"alpha": float(alpha)})


# ---------------------------------------------------------------------------
# sequence ops (dense+lengths, ops/sequence.py)
# ---------------------------------------------------------------------------
from ..ops import sequence as SEQ  # noqa: E402

sequence_mask = _loss2("sequence_mask_s",
                       lambda lengths, maxlen=None, dtype="int64":
                       F.sequence_mask(lengths, maxlen, dtype),
                       in_slots=("X",))
sequence_expand_as = _loss2(
    "sequence_expand_as_s",
    lambda x, lengths: SEQ.sequence_expand_as(x, lengths),
    in_slots=("X", "Lengths"))
sequence_slice = _loss2(
    "sequence_slice_s",
    lambda x, lengths, offset, length:
    SEQ.sequence_slice(x, lengths, offset, length),
    in_slots=("X", "Lengths", "Offset", "Length"))
sequence_scatter = _loss2(
    "sequence_scatter_s",
    lambda x, index, updates: SEQ.sequence_scatter(x, index, updates),
    in_slots=("X", "Ids", "Updates"))


def sequence_enumerate(input, win_size, pad_value=0, name=None,
                       lengths=None):
    _register_delegate(
        "sequence_enumerate_s",
        lambda x, lengths=None, win_size=2, pad_value=0:
        SEQ.sequence_enumerate(
            x, lengths if lengths is not None else
            jnp.full((x.shape[0],), x.shape[1], jnp.int32),
            win_size, pad_value),
        in_slots=("X", "Lengths"))
    ins = {"X": [input.name]}
    if lengths is not None:
        ins["Lengths"] = [lengths.name]
    return _append_simple("sequence_enumerate_s", ins,
                          {"win_size": win_size, "pad_value": pad_value})


def sequence_concat(input, name=None, lengths_list=None):
    """Dense+lengths sequence concat: interleaves rows by sequence
    (reference sequence_concat_op). lengths_list: one lengths Variable
    per input; defaults to full lengths."""
    n = len(input)
    op = f"sequence_concat_{n}_s"
    _register_delegate(
        op,
        lambda *args: _seq_concat_fn(args[:n], args[n:]),
        in_slots=tuple(f"X{i}" for i in builtins_range(n)) +
        tuple(f"L{i}" for i in builtins_range(n)),
        out_slots=("Out", "Lengths"))
    ins = {f"X{i}": [v.name] for i, v in enumerate(input)}
    if lengths_list:
        for i, lv in enumerate(lengths_list):
            ins[f"L{i}"] = [lv.name]
    return _append_simple(op, ins, {}, out_slots=("Out", "Lengths"))


def _seq_concat_fn(xs, lens):
    if not lens:
        lens = [jnp.full((x.shape[0],), x.shape[1], jnp.int32) for x in xs]
    out, lengths = SEQ.sequence_concat(list(xs), list(lens))
    return out, lengths


def sequence_reshape(input, new_dim):
    """Dense rewrite: rows keep batch, the trailing dims re-chunk to
    new_dim (reference re-chunks the flattened LoD stream)."""
    build = _delegate("sequence_reshape_s",
                      lambda x, new_dim=1:
                      x.reshape(x.shape[0], -1, new_dim))
    return build(input, new_dim=int(new_dim))


# ---------------------------------------------------------------------------
# detection (delegates over vision.ops where jit-friendly; eager aliases
# for the host-materializing NMS family)
# ---------------------------------------------------------------------------
from ..vision import ops as VOPS  # noqa: E402

iou_similarity = _loss2("iou_similarity_s",
                        lambda x, y, box_normalized=True:
                        VOPS.iou_similarity(x, y, box_normalized),
                        in_slots=("X", "Y"))
box_clip = _loss2("box_clip_s",
                  lambda x, im_info: VOPS.box_clip(x, im_info),
                  in_slots=("Input", "ImInfo"))
yolo_box = None  # bound below (multi-output)


def _bind_yolo():
    global yolo_box

    def yolo_box_s(x, img_size, anchors, class_num, conf_thresh=0.01,
                   downsample_ratio=32, clip_bbox=True, scale_x_y=1.0,
                   name=None):
        _register_delegate(
            "yolo_box_s",
            lambda x, img_size, anchors=(), class_num=1, conf_thresh=0.01,
            downsample_ratio=32, clip_bbox=True, scale_x_y=1.0:
            VOPS.yolo_box(x, img_size, list(anchors), class_num,
                          conf_thresh, downsample_ratio, clip_bbox,
                          scale_x_y),
            in_slots=("X", "ImgSize"), out_slots=("Boxes", "Scores"))
        return _append_simple(
            "yolo_box_s", {"X": [x.name], "ImgSize": [img_size.name]},
            {"anchors": tuple(anchors), "class_num": class_num,
             "conf_thresh": conf_thresh,
             "downsample_ratio": downsample_ratio,
             "clip_bbox": clip_bbox, "scale_x_y": scale_x_y},
            out_slots=("Boxes", "Scores"))

    yolo_box = yolo_box_s


_bind_yolo()


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5,
              min_max_aspect_ratios_order=False, name=None):
    _register_delegate(
        "prior_box_s",
        lambda input, image, **kw: VOPS.prior_box(input, image, **kw),
        in_slots=("Input", "Image"), out_slots=("Boxes", "Variances"))
    return _append_simple(
        "prior_box_s", {"Input": [input.name], "Image": [image.name]},
        {"min_sizes": tuple(min_sizes),
         "max_sizes": tuple(max_sizes) if max_sizes else None,
         "aspect_ratios": tuple(aspect_ratios),
         "variance": tuple(variance), "flip": flip, "clip": clip,
         "steps": tuple(steps), "offset": offset,
         "min_max_aspect_ratios_order": min_max_aspect_ratios_order},
        out_slots=("Boxes", "Variances"))


def density_prior_box(input, image, densities=None, fixed_sizes=None,
                      fixed_ratios=None, variance=(0.1, 0.1, 0.2, 0.2),
                      clip=False, steps=(0.0, 0.0), offset=0.5,
                      flatten_to_2d=False, name=None):
    _register_delegate(
        "density_prior_box_s",
        lambda input, image, **kw: VOPS.density_prior_box(input, image,
                                                          **kw),
        in_slots=("Input", "Image"), out_slots=("Boxes", "Variances"))
    return _append_simple(
        "density_prior_box_s",
        {"Input": [input.name], "Image": [image.name]},
        {"densities": tuple(densities), "fixed_sizes": tuple(fixed_sizes),
         "fixed_ratios": tuple(fixed_ratios), "variance": tuple(variance),
         "clip": clip, "steps": tuple(steps), "offset": offset,
         "flatten_to_2d": flatten_to_2d},
        out_slots=("Boxes", "Variances"))


def anchor_generator(input, anchor_sizes, aspect_ratios,
                     variance=(0.1, 0.1, 0.2, 0.2), stride=(16.0, 16.0),
                     offset=0.5, name=None):
    """RPN anchors per feature-map cell (anchor_generator_op.cc)."""
    _register_delegate(
        "anchor_generator_s", _anchor_fn, in_slots=("Input",),
        out_slots=("Anchors", "Variances"))
    return _append_simple(
        "anchor_generator_s", {"Input": [input.name]},
        {"anchor_sizes": tuple(anchor_sizes),
         "aspect_ratios": tuple(aspect_ratios),
         "variance": tuple(variance), "stride": tuple(stride),
         "offset": offset},
        out_slots=("Anchors", "Variances"))


def _anchor_fn(x, anchor_sizes=(), aspect_ratios=(), variance=(),
               stride=(16.0, 16.0), offset=0.5):
    h, w = x.shape[2], x.shape[3]
    wh = []
    for s in anchor_sizes:
        for r in aspect_ratios:
            aw = s * math.sqrt(r)
            ah = s / math.sqrt(r)
            wh.append((aw, ah))
    tab = jnp.asarray(wh, jnp.float32)                   # (n, 2)
    n = tab.shape[0]
    cx = (jnp.arange(w, dtype=jnp.float32) + offset) * stride[0]
    cy = (jnp.arange(h, dtype=jnp.float32) + offset) * stride[1]
    cxg, cyg = jnp.meshgrid(cx, cy)
    cxg, cyg = cxg[..., None], cyg[..., None]
    bw, bh = tab[None, None, :, 0] / 2, tab[None, None, :, 1] / 2
    anchors = jnp.stack([cxg - bw, cyg - bh, cxg + bw, cyg + bh], -1)
    variances = jnp.broadcast_to(jnp.asarray(variance, jnp.float32),
                                 (h, w, n, 4))
    return anchors, variances


def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True, axis=0,
              name=None):
    _register_delegate(
        "box_coder_s",
        lambda pb, tb, pbv=None, code_type="encode_center_size",
        box_normalized=True, axis=0:
        VOPS.box_coder(pb, pbv, tb, code_type, box_normalized, axis),
        in_slots=("PriorBox", "TargetBox", "PriorBoxVar"))
    ins = {"PriorBox": [prior_box.name], "TargetBox": [target_box.name]}
    if prior_box_var is not None and hasattr(prior_box_var, "name"):
        ins["PriorBoxVar"] = [prior_box_var.name]
    return _append_simple("box_coder_s", ins,
                          {"code_type": code_type,
                           "box_normalized": box_normalized, "axis": axis})


def ssd_loss(location, confidence, gt_box, gt_label, prior_box,
             prior_box_var=None, background_label=0, overlap_threshold=0.5,
             neg_pos_ratio=3.0, neg_overlap=0.5, loc_loss_weight=1.0,
             conf_loss_weight=1.0, match_type="per_prediction",
             mining_type="max_negative", normalize=True, sample_size=None):
    _register_delegate(
        "ssd_loss_s",
        lambda loc, conf, gt_box, gt_label, pb, **kw:
        VOPS.ssd_loss(loc, conf, gt_box, gt_label, pb, **kw),
        in_slots=("Location", "Confidence", "GTBox", "GTLabel",
                  "PriorBox"))
    return _append_simple(
        "ssd_loss_s",
        {"Location": [location.name], "Confidence": [confidence.name],
         "GTBox": [gt_box.name], "GTLabel": [gt_label.name],
         "PriorBox": [prior_box.name]},
        {"background_label": background_label,
         "overlap_threshold": overlap_threshold,
         "neg_pos_ratio": neg_pos_ratio, "neg_overlap": neg_overlap,
         "loc_loss_weight": loc_loss_weight,
         "conf_loss_weight": conf_loss_weight, "normalize": normalize})


# host-materializing NMS family: eager functions (run them on fetched
# arrays; the reference's LoD outputs are inherently dynamic-shaped)
multiclass_nms = VOPS.multiclass_nms
matrix_nms = VOPS.matrix_nms
bipartite_match = VOPS.bipartite_match

# two-stage (Faster-RCNN) family — vision/rcnn.py; the proposal/target
# ops are host-materializing like the NMS family (LoD-shaped outputs),
# deformable_conv gets a parameter-creating facade below
from ..vision import rcnn as _RCNN  # noqa: E402

rpn_target_assign = _RCNN.rpn_target_assign
retinanet_target_assign = _RCNN.retinanet_target_assign
generate_proposals = _RCNN.generate_proposals
distribute_fpn_proposals = _RCNN.distribute_fpn_proposals
collect_fpn_proposals = _RCNN.collect_fpn_proposals
generate_proposal_labels = _RCNN.generate_proposal_labels
generate_mask_labels = _RCNN.generate_mask_labels

# single-stage / OCR / metric long tail (round 3) — vision/ops.py;
# target_assign & polygon_box_transform & box_decoder_and_assign &
# roi_perspective_transform jit onto TPU, the NMS-family ones are
# host-materializing like multiclass_nms above
target_assign = VOPS.target_assign
polygon_box_transform = VOPS.polygon_box_transform
box_decoder_and_assign = VOPS.box_decoder_and_assign
roi_perspective_transform = VOPS.roi_perspective_transform
locality_aware_nms = VOPS.locality_aware_nms
retinanet_detection_output = VOPS.retinanet_detection_output
detection_map = VOPS.detection_map


def deformable_conv(input, offset, mask, num_filters, filter_size,
                    stride=1, padding=0, dilation=1, groups=None,
                    deformable_groups=None, im2col_step=None,
                    param_attr=None, bias_attr=None, modulated=True,
                    name=None):
    """Deformable conv v1/v2 facade (reference fluid/layers/nn.py:14202);
    compute in vision/rcnn.deformable_conv2d."""
    groups = groups or 1
    deformable_groups = deformable_groups or 1
    cin = int(input.shape[1])
    k = (filter_size if isinstance(filter_size, (list, tuple))
         else [filter_size] * 2)
    helper = LayerHelper("deformable_conv_s")
    w = helper.create_parameter(
        shape=[num_filters, cin // groups] + [int(s) for s in k],
        attr=param_attr, dtype="float32")
    b = (helper.create_parameter(shape=[num_filters], attr=bias_attr,
                                 dtype="float32")
         if bias_attr is not False else None)
    _register_delegate(
        "deformable_conv_s",
        lambda x, off, msk, wt, bias=None, **kw:
        _RCNN.deformable_conv2d(x, off, msk, wt, bias, **kw),
        in_slots=("Input", "Offset", "Mask", "Filter", "Bias"))
    ins = {"Input": [input.name], "Offset": [offset.name],
           "Filter": [w.name]}
    if modulated:
        ins["Mask"] = [mask.name]
    else:
        ins["Mask"] = [offset.name]   # placeholder, ignored by kernel
    if b is not None:
        ins["Bias"] = [b.name]
    return _append_simple(
        "deformable_conv_s", ins,
        {"stride": stride, "padding": padding, "dilation": dilation,
         "groups": groups, "deformable_groups": deformable_groups,
         "modulated": modulated})


def detection_output(loc, scores, prior_box, prior_box_var,
                     background_label=0, nms_threshold=0.3, nms_top_k=400,
                     keep_top_k=200, score_threshold=0.01, nms_eta=1.0):
    """Decode + multiclass NMS (detection_output op): eager post-process
    over fetched arrays (decode via box_coder, then multiclass_nms)."""
    decoded = VOPS.box_coder(prior_box, prior_box_var, loc,
                             code_type="decode_center_size", axis=0)
    d = decoded.numpy() if hasattr(decoded, "numpy") else decoded
    return VOPS.multiclass_nms(
        np.asarray(d), scores, score_threshold=score_threshold,
        nms_top_k=nms_top_k, keep_top_k=keep_top_k,
        nms_threshold=nms_threshold, background_label=background_label)


# ---------------------------------------------------------------------------
# misc: hash, auc, chunk_eval, range, warpctc, hsigmoid
# ---------------------------------------------------------------------------
def hash(input, hash_size, num_hash=1, name=None):  # noqa: A001
    """Deterministic multiplicative int hash into [0, hash_size)
    (hash_op.cc uses xxhash; any fixed mixer satisfies the contract:
    stable, spread, seeded per hash slot)."""
    build = _delegate("hash_s", _hash_fn)
    return build(input, hash_size=int(hash_size), num_hash=int(num_hash))


def _hash_fn(x, hash_size=1, num_hash=1):
    x = x.astype(jnp.uint32)
    outs = []
    for i in builtins_range(num_hash):
        h = (x * jnp.uint32(2654435761) +
             jnp.uint32((0x9E3779B9 * (i + 1)) & 0xFFFFFFFF))
        h = h ^ (h >> 16)
        outs.append((h % jnp.uint32(hash_size)).astype(jnp.int64))
    return jnp.stack(outs, axis=-1)


def auc(input, label, curve="ROC", num_thresholds=200, topk=1,
        slide_steps=1):
    """Batch AUC from prediction/label arrays (auc_op.cc, stateless
    form): exact rank-statistic AUC over the fed batch."""
    build = _delegate("auc_s", _auc_fn, in_slots=("Predict", "Label"))
    return build(input, label)


def _auc_fn(pred, label):
    p = pred[:, -1] if pred.ndim == 2 else pred.reshape(-1)
    y = label.reshape(-1).astype(jnp.float32)
    order = jnp.argsort(p)
    ranks = jnp.argsort(order).astype(jnp.float32) + 1.0
    n_pos = jnp.sum(y)
    n_neg = y.shape[0] - n_pos
    auc_v = (jnp.sum(ranks * y) - n_pos * (n_pos + 1) / 2.0) / \
        jnp.maximum(n_pos * n_neg, 1.0)
    return auc_v.astype(jnp.float32)


def _extract_chunks(tags, scheme, num_types, excluded):
    """Chunk spans from an int tag sequence (chunk_eval_op.cc tag
    layout: IOB tag = type*2 + {0:B, 1:I}, IOE = type*2 + {0:I, 1:E},
    IOBES = type*4 + {B,I,E,S}, plain = one tag per type; the largest
    tag is Outside)."""
    chunks = set()
    start, ctype = None, None

    def flush(end):
        if start is not None and ctype is not None and \
                ctype not in (excluded or ()):
            chunks.add((start, end, ctype))

    for i, t in enumerate(tags):
        t = int(t)
        if scheme == "plain":
            typ = t if t < num_types else None
            begin = typ is not None and typ != ctype
        elif scheme == "IOB":
            typ = t // 2 if t < num_types * 2 else None
            begin = typ is not None and (t % 2 == 0 or typ != ctype)
        elif scheme == "IOE":
            typ = t // 2 if t < num_types * 2 else None
            begin = typ is not None and ctype is None
        elif scheme == "IOBES":
            typ = t // 4 if t < num_types * 4 else None
            pos = t % 4
            begin = typ is not None and pos in (0, 3)
        else:
            raise ValueError(f"unknown chunk scheme {scheme!r}")
        if typ is None:
            flush(i - 1)
            start, ctype = None, None
        elif begin:
            flush(i - 1)
            start, ctype = i, typ
        elif typ != ctype:
            flush(i - 1)
            start, ctype = i, typ
        if scheme == "IOE" and typ is not None and t % 2 == 1:
            flush(i)
            start, ctype = None, None
        if scheme == "IOBES" and typ is not None and pos in (2, 3):
            flush(i)
            start, ctype = None, None
    flush(len(tags) - 1)
    return chunks


def chunk_eval(input, label, chunk_scheme, num_chunk_types,
               excluded_chunk_types=None, seq_length=None):
    """Chunk precision/recall/F1 from tag sequences (chunk_eval_op.cc).
    Host-side eager function over fetched (B, T) int arrays — chunk
    extraction is per-row span logic. Returns (precision, recall, f1,
    num_infer, num_label, num_correct)."""
    pred = np.asarray(input.numpy() if hasattr(input, "numpy") else input)
    lbl = np.asarray(label.numpy() if hasattr(label, "numpy") else label)
    if pred.ndim == 1:
        pred, lbl = pred[None], lbl[None]
    lens = (np.asarray(seq_length.numpy() if hasattr(seq_length, "numpy")
                       else seq_length)
            if seq_length is not None
            else np.full(pred.shape[0], pred.shape[1]))
    n_infer = n_label = n_correct = 0
    for row in builtins_range(pred.shape[0]):
        L_ = int(lens[row])
        pc = _extract_chunks(pred[row][:L_], chunk_scheme,
                             num_chunk_types, excluded_chunk_types)
        lc = _extract_chunks(lbl[row][:L_], chunk_scheme,
                             num_chunk_types, excluded_chunk_types)
        n_infer += len(pc)
        n_label += len(lc)
        n_correct += len(pc & lc)
    precision = n_correct / n_infer if n_infer else 0.0
    recall = n_correct / n_label if n_label else 0.0
    f1 = (2 * precision * recall / (precision + recall)
          if precision + recall else 0.0)
    return precision, recall, f1, n_infer, n_label, n_correct


def range(start, end, step, dtype="int64", name=None):  # noqa: A001
    from .layers import fill_constant  # noqa: F401

    build = _delegate(
        "range_s",
        lambda start=0, end=0, step=1, dtype="int64":
        jnp.arange(start, end, step,
                   {"int64": jnp.int64, "int32": jnp.int32,
                    "float32": jnp.float32,
                    "float64": jnp.float32}[dtype]))
    return build(start=float(start) if "float" in dtype else int(start),
                 end=float(end) if "float" in dtype else int(end),
                 step=float(step) if "float" in dtype else int(step),
                 dtype=dtype)


def warpctc(input, label, blank=0, norm_by_times=False,
            input_length=None, label_length=None):
    """CTC loss (warpctc_op.cc) via optax.ctc_loss. Dense form: input
    (B, T, C) logits, label (B, L) int padded with `blank`. Returns
    (B, 1) losses."""
    _register_delegate(
        "warpctc_s",
        lambda logits, labels, in_len=None, lb_len=None, blank=0:
        _ctc_fn(logits, labels, in_len, lb_len, blank),
        in_slots=("Logits", "Label", "LogitsLength", "LabelLength"))
    ins = {"Logits": [input.name], "Label": [label.name]}
    if input_length is not None:
        ins["LogitsLength"] = [input_length.name]
    if label_length is not None:
        ins["LabelLength"] = [label_length.name]
    return _append_simple("warpctc_s", ins, {"blank": int(blank)})


def _ctc_fn(logits, labels, in_len, lb_len, blank):
    import optax

    b, t, _c = logits.shape
    L = labels.shape[1]
    tpos = jnp.arange(t)[None, :]
    lpos = jnp.arange(L)[None, :]
    logit_pad = (tpos >= (in_len.reshape(-1, 1) if in_len is not None
                          else jnp.full((b, 1), t))).astype(jnp.float32)
    label_pad = (lpos >= (lb_len.reshape(-1, 1) if lb_len is not None
                          else jnp.sum((labels != blank).astype(jnp.int32),
                                       1, keepdims=True))).astype(
        jnp.float32)
    loss = optax.ctc_loss(logits, logit_pad, labels, label_pad,
                          blank_id=blank)
    return loss[:, None]


def hsigmoid(input, label, num_classes, param_attr=None, bias_attr=None,
             name=None, path_table=None, path_code=None,
             is_custom=False, is_sparse=False):
    """Hierarchical sigmoid over a complete binary tree
    (hierarchical_sigmoid_op.cc, default non-custom tree): class id's
    binary path selects (num_classes-1) internal-node classifiers."""
    helper = LayerHelper("hsigmoid_s")
    d = int(input.shape[-1])
    w = helper.create_parameter(shape=[int(num_classes) - 1, d],
                                dtype="float32", attr=param_attr)
    from .initializer import Constant

    b = helper.create_parameter(shape=[int(num_classes) - 1],
                                dtype="float32", attr=bias_attr,
                                initializer=Constant(0.0))
    _register_delegate(
        "hsigmoid_s",
        lambda x, label, w, b, num_classes=2:
        _hsigmoid_fn(x, label, w, b, num_classes),
        in_slots=("X", "Label", "W", "Bias"))
    return _append_simple(
        "hsigmoid_s",
        {"X": [input.name], "Label": [label.name], "W": [w.name],
         "Bias": [b.name]},
        {"num_classes": int(num_classes)})


def _hsigmoid_fn(x, label, w, b, num_classes):
    # complete binary tree: internal node ids 1..num_classes-1 (heap
    # order); leaf for class c is node num_classes + c; walk up to root
    depth = int(math.ceil(math.log2(max(num_classes, 2))))
    node = label.reshape(-1) + num_classes          # leaf heap id
    losses = jnp.zeros((x.shape[0],), x.dtype)
    for _ in builtins_range(depth):
        parent = node // 2
        is_right = (node % 2).astype(x.dtype)       # 1 if right child
        valid = (parent >= 1) & (parent < num_classes)
        idx = jnp.clip(parent - 1, 0, num_classes - 2)
        logit = jnp.einsum("bd,bd->b", x, w[idx]) + b[idx]
        # right child -> target 1, left -> 0
        ce = jnp.maximum(logit, 0) - logit * is_right + \
            jnp.log1p(jnp.exp(-jnp.abs(logit)))
        losses = losses + jnp.where(valid, ce, 0.0)
        node = parent
    return losses[:, None]


builtins_range = __builtins__["range"] if isinstance(__builtins__, dict) \
    else __builtins__.range


# ---------------------------------------------------------------------------
# RNN sweep ops (dynamic_lstm/dynamic_gru/lstm + single-step units).
# Reference: dynamic_lstm_op.cc / dynamic_gru_op.cc run a C++ sequence
# loop over LoD batches; here one lax.scan per op, dense (B, T, ...) with
# optional lengths masking — gate math matches the reference equations
# (no peepholes; reference use_peepholes=True adds diagonal terms we
# document as not carried).
# ---------------------------------------------------------------------------


def _lstm_scan(xproj, h0, c0, w, lengths=None, is_reverse=False,
               gate_order="ifco"):
    """xproj (B, T, 4H) pre-projected input; w (H, 4H) recurrent."""
    b, t, four_h = xproj.shape
    hdim = four_h // 4
    if is_reverse:
        xproj = xproj[:, ::-1]

    def step(carry, xt):
        h, c, i_t = carry
        g = xt + h @ w                          # (B, 4H)
        parts = {k: g[:, j * hdim:(j + 1) * hdim]
                 for j, k in enumerate(gate_order)}
        i = jax.nn.sigmoid(parts["i"])
        f = jax.nn.sigmoid(parts["f"])
        o = jax.nn.sigmoid(parts["o"])
        cand = jnp.tanh(parts["c"])
        c_new = f * c + i * cand
        h_new = o * jnp.tanh(c_new)
        if lengths is not None:
            tpos = (t - 1 - i_t) if is_reverse else i_t
            keep = (tpos < lengths)[:, None].astype(h.dtype)
            h_new = keep * h_new + (1 - keep) * h
            c_new = keep * c_new + (1 - keep) * c
        return (h_new, c_new, i_t + 1), (h_new, c_new)

    (_, _, _), (hs, cs) = jax.lax.scan(
        step, (h0, c0, jnp.asarray(0)), jnp.swapaxes(xproj, 0, 1))
    hs = jnp.swapaxes(hs, 0, 1)
    cs = jnp.swapaxes(cs, 0, 1)
    if is_reverse:
        hs, cs = hs[:, ::-1], cs[:, ::-1]
    return hs, cs


def dynamic_lstm(input, size, h_0=None, c_0=None, param_attr=None,
                 bias_attr=None, use_peepholes=False, is_reverse=False,
                 gate_activation="sigmoid", cell_activation="tanh",
                 candidate_activation="tanh", dtype="float32", name=None,
                 lengths=None):
    """LSTM over a pre-projected sequence (dynamic_lstm_op.cc). input
    (B, T, 4H); returns (hidden (B, T, H), cell (B, T, H)). Dense form:
    pass `lengths` (B,) for padded batches. use_peepholes is not carried
    (documented; the reference default adds diagonal peephole terms)."""
    if use_peepholes:
        raise NotImplementedError(
            "use_peepholes=True is not carried over (see COVERAGE.md); "
            "pass use_peepholes=False")
    helper = LayerHelper("dynamic_lstm_s")
    hdim = size // 4
    w = helper.create_parameter(shape=[hdim, size], dtype=dtype,
                                attr=param_attr)
    from .initializer import Constant

    bias = helper.create_parameter(shape=[size], dtype=dtype,
                                   attr=bias_attr,
                                   initializer=Constant(0.0))
    _register_delegate(
        "dynamic_lstm_s",
        lambda x, w, b, h0=None, c0=None, lengths=None, is_reverse=False:
        _lstm_scan(x + b, 
                   h0 if h0 is not None else
                   jnp.zeros((x.shape[0], w.shape[0]), x.dtype),
                   c0 if c0 is not None else
                   jnp.zeros((x.shape[0], w.shape[0]), x.dtype),
                   w, lengths, is_reverse),
        in_slots=("Input", "Weight", "Bias", "H0", "C0", "Lengths"),
        out_slots=("Hidden", "Cell"))
    ins = {"Input": [input.name], "Weight": [w.name], "Bias": [bias.name]}
    if h_0 is not None:
        ins["H0"] = [h_0.name]
    if c_0 is not None:
        ins["C0"] = [c_0.name]
    if lengths is not None:
        ins["Lengths"] = [lengths.name]
    return _append_simple("dynamic_lstm_s", ins,
                          {"is_reverse": is_reverse},
                          out_slots=("Hidden", "Cell"))


def _gru_scan(xproj, h0, w, lengths=None, is_reverse=False):
    """xproj (B, T, 3H) pre-projected [update, reset, candidate];
    w (H, 3H) recurrent (reference dynamic_gru_op.cc gate layout)."""
    b, t, three_h = xproj.shape
    hdim = three_h // 3
    if is_reverse:
        xproj = xproj[:, ::-1]
    wu, wr, wc = (w[:, :hdim], w[:, hdim:2 * hdim], w[:, 2 * hdim:])

    def step(carry, xt):
        h, i_t = carry
        xu = xt[:, :hdim]
        xr = xt[:, hdim:2 * hdim]
        xc = xt[:, 2 * hdim:]
        u = jax.nn.sigmoid(xu + h @ wu)
        r = jax.nn.sigmoid(xr + h @ wr)
        cand = jnp.tanh(xc + (r * h) @ wc)
        h_new = u * h + (1.0 - u) * cand
        if lengths is not None:
            tpos = (t - 1 - i_t) if is_reverse else i_t
            keep = (tpos < lengths)[:, None].astype(h.dtype)
            h_new = keep * h_new + (1 - keep) * h
        return (h_new, i_t + 1), h_new

    (_, _), hs = jax.lax.scan(step, (h0, jnp.asarray(0)),
                              jnp.swapaxes(xproj, 0, 1))
    hs = jnp.swapaxes(hs, 0, 1)
    if is_reverse:
        hs = hs[:, ::-1]
    return hs


def dynamic_gru(input, size, param_attr=None, bias_attr=None,
                is_reverse=False, gate_activation="sigmoid",
                candidate_activation="tanh", h_0=None, origin_mode=False,
                name=None, lengths=None):
    """GRU over a pre-projected sequence (dynamic_gru_op.cc). input
    (B, T, 3H); returns hidden (B, T, H)."""
    helper = LayerHelper("dynamic_gru_s")
    hdim = size
    w = helper.create_parameter(shape=[hdim, 3 * hdim], dtype="float32",
                                attr=param_attr)
    from .initializer import Constant

    bias = helper.create_parameter(shape=[3 * hdim], dtype="float32",
                                   attr=bias_attr,
                                   initializer=Constant(0.0))
    _register_delegate(
        "dynamic_gru_s",
        lambda x, w, b, h0=None, lengths=None, is_reverse=False:
        _gru_scan(x + b,
                  h0 if h0 is not None else
                  jnp.zeros((x.shape[0], w.shape[0]), x.dtype),
                  w, lengths, is_reverse),
        in_slots=("Input", "Weight", "Bias", "H0", "Lengths"),
        out_slots=("Hidden",))
    ins = {"Input": [input.name], "Weight": [w.name], "Bias": [bias.name]}
    if h_0 is not None:
        ins["H0"] = [h_0.name]
    if lengths is not None:
        ins["Lengths"] = [lengths.name]
    return _append_simple("dynamic_gru_s", ins,
                          {"is_reverse": is_reverse},
                          out_slots=("Hidden",))


def lstm(input, init_h, init_c, max_len, hidden_size, num_layers,
         dropout_prob=0.0, is_bidirec=False, is_test=False, name=None,
         default_initializer=None, seed=-1):
    """Multi-layer (optionally bidirectional) LSTM over raw input
    (cudnn_lstm_op.cu translation): per layer an input projection
    (D, 4H) + recurrent (H, 4H), built on the same scan kernel. input
    (B, T, D); init_h/init_c (num_layers*dirs, B, H). Returns
    (out (B, T, H*dirs), last_h, last_c)."""
    helper = LayerHelper("lstm_s")
    dirs = 2 if is_bidirec else 1
    from . import layers as L

    cur = input
    last_hs, last_cs = [], []
    for layer in builtins_range(num_layers):
        outs = []
        for d in builtins_range(dirs):
            din = int(cur.shape[-1])
            wx = helper.create_parameter(
                shape=[din, 4 * hidden_size], dtype="float32",
                initializer=default_initializer)
            wh = helper.create_parameter(
                shape=[hidden_size, 4 * hidden_size], dtype="float32",
                initializer=default_initializer)
            from .initializer import Constant

            b = helper.create_parameter(shape=[4 * hidden_size],
                                        dtype="float32",
                                        initializer=Constant(0.0))
            idx = layer * dirs + d
            h0 = L.squeeze(L.slice(init_h, axes=[0], starts=[idx],
                                   ends=[idx + 1]), axes=[0])
            c0 = L.squeeze(L.slice(init_c, axes=[0], starts=[idx],
                                   ends=[idx + 1]), axes=[0])
            _register_delegate(
                "lstm_layer_s",
                lambda x, wx, wh, b, h0, c0, is_reverse=False:
                _lstm_scan(jnp.einsum("btd,dh->bth", x, wx) + b, h0, c0,
                           wh, None, is_reverse),
                in_slots=("Input", "WX", "WH", "Bias", "H0", "C0"),
                out_slots=("Hidden", "Cell"))
            hs, cs = _append_simple(
                "lstm_layer_s",
                {"Input": [cur.name], "WX": [wx.name], "WH": [wh.name],
                 "Bias": [b.name], "H0": [h0.name], "C0": [c0.name]},
                {"is_reverse": d == 1},
                out_slots=("Hidden", "Cell"))
            outs.append(hs)
            last_hs.append(L.slice(hs, axes=[1],
                                   starts=[0 if d == 1 else -1],
                                   ends=[1 if d == 1 else 10 ** 9]))
            last_cs.append(L.slice(cs, axes=[1],
                                   starts=[0 if d == 1 else -1],
                                   ends=[1 if d == 1 else 10 ** 9]))
        cur = outs[0] if dirs == 1 else L.concat(outs, axis=-1)
        if dropout_prob > 0.0 and not is_test:
            cur = L.dropout(cur, dropout_prob)
    last_h = L.concat(last_hs, axis=1) if len(last_hs) > 1 else last_hs[0]
    last_c = L.concat(last_cs, axis=1) if len(last_cs) > 1 else last_cs[0]
    return cur, last_h, last_c


def gru_unit(input, hidden, size, param_attr=None, bias_attr=None,
             activation="tanh", gate_activation="sigmoid",
             origin_mode=False):
    """Single GRU step (gru_unit_op.cc). input (B, 3H) pre-projected,
    hidden (B, H). Returns (new_hidden, reset_hidden_prev, gate)."""
    helper = LayerHelper("gru_unit_s")
    hdim = size // 3
    w = helper.create_parameter(shape=[hdim, 3 * hdim], dtype="float32",
                                attr=param_attr)
    from .initializer import Constant

    b = helper.create_parameter(shape=[3 * hdim], dtype="float32",
                                attr=bias_attr, initializer=Constant(0.0))
    _register_delegate(
        "gru_unit_s", _gru_unit_fn,
        in_slots=("Input", "HiddenPrev", "Weight", "Bias"),
        out_slots=("Hidden", "ResetHiddenPrev", "Gate"))
    return _append_simple(
        "gru_unit_s",
        {"Input": [input.name], "HiddenPrev": [hidden.name],
         "Weight": [w.name], "Bias": [b.name]}, {},
        out_slots=("Hidden", "ResetHiddenPrev", "Gate"))


def _gru_unit_fn(x, h, w, b):
    hdim = h.shape[-1]
    g = x + b
    wu, wr, wc = w[:, :hdim], w[:, hdim:2 * hdim], w[:, 2 * hdim:]
    u = jax.nn.sigmoid(g[:, :hdim] + h @ wu)
    r = jax.nn.sigmoid(g[:, hdim:2 * hdim] + h @ wr)
    rh = r * h
    cand = jnp.tanh(g[:, 2 * hdim:] + rh @ wc)
    h_new = u * h + (1.0 - u) * cand
    gate = jnp.concatenate([u, r, cand], axis=-1)
    return h_new, rh, gate


def lstm_unit(x_t, hidden_t_prev, cell_t_prev, forget_bias=0.0,
              param_attr=None, bias_attr=None, name=None):
    """Single LSTM step over raw input (lstm_unit_op.cc): fc([x, h]) ->
    gates. Returns (hidden, cell)."""
    helper = LayerHelper("lstm_unit_s")
    din = int(x_t.shape[-1])
    hdim = int(hidden_t_prev.shape[-1])
    w = helper.create_parameter(shape=[din + hdim, 4 * hdim],
                                dtype="float32", attr=param_attr)
    from .initializer import Constant

    b = helper.create_parameter(shape=[4 * hdim], dtype="float32",
                                attr=bias_attr, initializer=Constant(0.0))
    _register_delegate(
        "lstm_unit_s",
        lambda x, h, c, w, b, forget_bias=0.0:
        _lstm_unit_fn(x, h, c, w, b, forget_bias),
        in_slots=("X", "HiddenPrev", "CellPrev", "Weight", "Bias"),
        out_slots=("Hidden", "Cell"))
    return _append_simple(
        "lstm_unit_s",
        {"X": [x_t.name], "HiddenPrev": [hidden_t_prev.name],
         "CellPrev": [cell_t_prev.name], "Weight": [w.name],
         "Bias": [b.name]},
        {"forget_bias": float(forget_bias)},
        out_slots=("Hidden", "Cell"))


def _lstm_unit_fn(x, h, c, w, b, forget_bias):
    hdim = h.shape[-1]
    g = jnp.concatenate([x, h], axis=-1) @ w + b
    i, f, cand, o = (g[:, :hdim], g[:, hdim:2 * hdim],
                     g[:, 2 * hdim:3 * hdim], g[:, 3 * hdim:])
    c_new = jax.nn.sigmoid(f + forget_bias) * c + \
        jax.nn.sigmoid(i) * jnp.tanh(cand)
    h_new = jax.nn.sigmoid(o) * jnp.tanh(c_new)
    return h_new, c_new


# ---------------------------------------------------------------------------
# export into the static / fluid.layers namespace
# ---------------------------------------------------------------------------
__all__ = [n for n, v in list(globals().items())
           if not n.startswith("_") and
           (callable(v) or isinstance(v, type)) and
           getattr(v, "__module__", "").startswith("paddle_tpu")]


def _export_into_layers():
    # registry, NOT setattr: a module global named `range`/`sum`/... would
    # shadow the builtin for code inside layers.py (round-2 bug)
    from . import layers as _layers

    _layers._register_exports({_n: globals()[_n] for _n in __all__})


_export_into_layers()


# ---------------------------------------------------------------------------
# second sweep: cells, conv3d_transpose, dynamic_lstmp, nce, sampled
# softmax, inplace_abn, multi_box_head, yolov3_loss, doc passthroughs
# ---------------------------------------------------------------------------
from ..nn import GRUCell, LSTMCell  # noqa: F401,E402
from ..nn import RNNCellBase as RNNCell  # noqa: F401,E402


def conv3d_transpose(input, num_filters, output_size=None, filter_size=None,
                     padding=0, stride=1, dilation=1, groups=1,
                     param_attr=None, bias_attr=None, use_cudnn=True,
                     act=None, name=None, data_format="NCDHW"):
    helper = LayerHelper("conv3d_transpose_s")
    cin = int(input.shape[1])
    if filter_size is None:
        raise ValueError("filter_size required")
    k = (filter_size if isinstance(filter_size, (list, tuple))
         else [filter_size] * 3)
    w = helper.create_parameter(
        shape=[cin, num_filters // groups] + [int(s) for s in k],
        dtype="float32", attr=param_attr)
    ins = {"Input": [input.name], "Filter": [w.name]}
    if bias_attr is not False:
        from .initializer import Constant

        b = helper.create_parameter(shape=[num_filters], dtype="float32",
                                    attr=bias_attr,
                                    initializer=Constant(0.0))
        ins["Bias"] = [b.name]
    _register_delegate(
        "conv3d_transpose_s",
        lambda x, w, b=None, stride=1, padding=0, dilation=1, groups=1:
        F.conv3d_transpose(x, w, b, stride=stride, padding=padding,
                           dilation=dilation, groups=groups),
        in_slots=("Input", "Filter", "Bias"))
    out = _append_simple("conv3d_transpose_s", ins,
                         {"stride": stride, "padding": padding,
                          "dilation": dilation, "groups": groups})
    from .layers_ext import _apply_act

    return _apply_act(out, act)


def dynamic_lstmp(input, size, proj_size, h_0=None, c_0=None,
                  param_attr=None, bias_attr=None, use_peepholes=False,
                  is_reverse=False, gate_activation="sigmoid",
                  cell_activation="tanh", candidate_activation="tanh",
                  proj_activation="tanh", dtype="float32", name=None,
                  lengths=None):
    """LSTM with a recurrent projection (dynamic_lstmp_op.cc): the H-dim
    hidden is projected to proj_size before feeding back. input
    (B, T, 4H). Returns (projection (B, T, P), cell (B, T, H))."""
    if use_peepholes:
        raise NotImplementedError(
            "use_peepholes=True is not carried over (see COVERAGE.md)")
    helper = LayerHelper("dynamic_lstmp_s")
    hdim = size // 4
    w = helper.create_parameter(shape=[proj_size, size], dtype=dtype,
                                attr=param_attr)
    wp = helper.create_parameter(shape=[hdim, proj_size], dtype=dtype)
    from .initializer import Constant

    bias = helper.create_parameter(shape=[size], dtype=dtype,
                                   attr=bias_attr,
                                   initializer=Constant(0.0))
    _register_delegate(
        "dynamic_lstmp_s", _lstmp_fn,
        in_slots=("Input", "Weight", "ProjWeight", "Bias", "H0", "C0",
                  "Lengths"),
        out_slots=("Projection", "Cell"))
    ins = {"Input": [input.name], "Weight": [w.name],
           "ProjWeight": [wp.name], "Bias": [bias.name]}
    if h_0 is not None:
        ins["H0"] = [h_0.name]
    if c_0 is not None:
        ins["C0"] = [c_0.name]
    if lengths is not None:
        ins["Lengths"] = [lengths.name]
    return _append_simple("dynamic_lstmp_s", ins,
                          {"is_reverse": is_reverse},
                          out_slots=("Projection", "Cell"))


def _lstmp_fn(x, w, wp, b, h0=None, c0=None, lengths=None,
              is_reverse=False):
    bsz, t, four_h = x.shape
    hdim = four_h // 4
    p = wp.shape[1]
    x = x + b
    if is_reverse:
        x = x[:, ::-1]
    h0 = h0 if h0 is not None else jnp.zeros((bsz, p), x.dtype)
    c0 = c0 if c0 is not None else jnp.zeros((bsz, hdim), x.dtype)

    def step(carry, xt):
        hp, c, i_t = carry
        g = xt + hp @ w
        i = jax.nn.sigmoid(g[:, :hdim])
        f = jax.nn.sigmoid(g[:, hdim:2 * hdim])
        cand = jnp.tanh(g[:, 2 * hdim:3 * hdim])
        o = jax.nn.sigmoid(g[:, 3 * hdim:])
        c_new = f * c + i * cand
        h_new = o * jnp.tanh(c_new)
        proj = jnp.tanh(h_new @ wp)
        if lengths is not None:
            tpos = (t - 1 - i_t) if is_reverse else i_t
            keep = (tpos < lengths)[:, None].astype(x.dtype)
            proj = keep * proj + (1 - keep) * hp
            c_new = keep * c_new + (1 - keep) * c
        return (proj, c_new, i_t + 1), (proj, c_new)

    (_, _, _), (ps, cs) = jax.lax.scan(step, (h0, c0, jnp.asarray(0)),
                                       jnp.swapaxes(x, 0, 1))
    ps, cs = jnp.swapaxes(ps, 0, 1), jnp.swapaxes(cs, 0, 1)
    if is_reverse:
        ps, cs = ps[:, ::-1], cs[:, ::-1]
    return ps, cs


def nce(input, label, num_total_classes, sample_weight=None,
        param_attr=None, bias_attr=None, num_neg_samples=5, name=None,
        sampler="uniform", custom_dist=None, seed=0, is_sparse=False):
    """Noise-contrastive estimation loss with created class weights
    (nce_op.cc). Returns (B, 1) losses."""
    helper = LayerHelper("nce_s")
    d = int(input.shape[-1])
    w = helper.create_parameter(shape=[int(num_total_classes), d],
                                dtype="float32", attr=param_attr)
    from .initializer import Constant

    b = helper.create_parameter(shape=[int(num_total_classes)],
                                dtype="float32", attr=bias_attr,
                                initializer=Constant(0.0))

    def _nce_kernel(ins, attrs, ctx):
        from ..framework.random import rng_scope

        x = ins["Input"][0]
        lbl = ins["Label"][0]
        wv = ins["Weight"][0]
        bv = ins["Bias"][0]
        # the executor's per-run key keeps sampling traceable (the global
        # generator would leak a tracer out of the jit)
        with rng_scope(ctx.rng_key):
            out = F.nce(x, lbl, wv, bv,
                        num_neg_samples=attrs.get("num_neg_samples", 5))
        from ..framework.tensor import Tensor as _T

        return {"Cost": [out.value if isinstance(out, _T) else out]}

    from .kernels import KERNELS, kernel as _k

    if "nce_s" not in KERNELS:
        _k("nce_s")(_nce_kernel)
    return _append_simple(
        "nce_s",
        {"Input": [input.name], "Label": [label.name], "Weight": [w.name],
         "Bias": [b.name]},
        {"num_neg_samples": int(num_neg_samples), "seed": int(seed or 0)},
        out_slots=("Cost",))


def sampled_softmax_with_cross_entropy(logits, label, num_samples,
                                       num_true=1, remove_accidental_hits=True,
                                       use_customized_samples=False,
                                       customized_samples=None,
                                       customized_probabilities=None,
                                       seed=0):
    """Softmax CE over the true class + sampled negatives
    (sample_logits_op.cc + softmax_with_cross_entropy). logits (B, C);
    label (B, 1). Returns (B, 1) losses."""
    _register_delegate(
        "sampled_softmax_ce_s", _sampled_ce_fn,
        in_slots=("Logits", "Label"), needs_rng=True)
    return _append_simple(
        "sampled_softmax_ce_s",
        {"Logits": [logits.name], "Label": [label.name]},
        {"num_samples": int(num_samples), "seed": int(seed or 0)})


def _sampled_ce_fn(logits, label, num_samples=5, seed=0, _rng_key=None):
    b, c = logits.shape
    key = _rng_key if _rng_key is not None else jax.random.key(seed)
    neg = jax.random.randint(key, (b, num_samples), 0, c)
    lbl = label.reshape(-1, 1)
    cls = jnp.concatenate([lbl, neg], axis=1)          # (B, 1+S)
    picked = jnp.take_along_axis(logits, cls, axis=1)
    # mask accidental hits of the true class among the negatives
    hit = cls[:, 1:] == lbl
    picked = picked.at[:, 1:].set(
        jnp.where(hit, -1e9, picked[:, 1:]))
    return -jax.nn.log_softmax(picked, axis=1)[:, :1]


def inplace_abn(input, act=None, is_test=False, momentum=0.9,
                epsilon=1e-5, param_attr=None, bias_attr=None,
                data_layout="NCHW", name=None, moving_mean_name=None,
                moving_variance_name=None, do_model_average_for_mean_and_var=True,
                use_global_stats=False, act_alpha=1.0):
    """Activated batch norm (inplace_abn_op.cc). The reference fuses BN +
    activation in place to save memory; XLA owns buffer reuse here, so
    this is exactly batch_norm followed by the activation."""
    from . import layers as L

    out = L.batch_norm(input, act=None, is_test=is_test, momentum=momentum,
                       epsilon=epsilon, param_attr=param_attr,
                       bias_attr=bias_attr, data_layout=data_layout,
                       moving_mean_name=moving_mean_name,
                       moving_variance_name=moving_variance_name,
                       use_global_stats=use_global_stats)
    if act in ("leaky_relu",):
        from .layers import leaky_relu as _lrelu

        return _lrelu(out, alpha=act_alpha)
    if act == "elu":
        from .layers_ext import elu as _elu_f

        return _elu_f(out, alpha=act_alpha)
    from .layers_ext import _apply_act

    return _apply_act(out, act)


def multi_box_head(inputs, image, base_size, num_classes, aspect_ratios,
                   min_ratio=None, max_ratio=None, min_sizes=None,
                   max_sizes=None, steps=None, step_w=None, step_h=None,
                   offset=0.5, variance=(0.1, 0.1, 0.2, 0.2), flip=True,
                   clip=False, kernel_size=1, pad=0, stride=1, name=None,
                   min_max_aspect_ratios_order=False):
    """SSD multi-box head (reference detection.py multi_box_head): per
    feature map a prior_box + loc/conf convs; outputs concatenated over
    maps. Returns (mbox_locs (B, P, 4), mbox_confs (B, P, C),
    boxes (P, 4), variances (P, 4))."""
    from . import layers as L

    n = len(inputs)
    if min_sizes is None:
        # reference ratio interpolation
        num_layer = n
        min_sizes, max_sizes = [], []
        step = int(math.floor((max_ratio - min_ratio) /
                              (num_layer - 2))) if num_layer > 2 else 0
        ratios = list(builtins_range(min_ratio, max_ratio + 1,
                                     step if step else 1))[:num_layer - 1]
        for ratio in ratios:
            min_sizes.append(base_size * ratio / 100.0)
            max_sizes.append(base_size * (ratio + step) / 100.0)
        min_sizes = [base_size * 0.1] + min_sizes
        max_sizes = [base_size * 0.2] + max_sizes
    locs, confs, boxes_all, vars_all = [], [], [], []
    for i, feat in enumerate(inputs):
        ms = min_sizes[i]
        mx = max_sizes[i] if max_sizes else None
        ar = aspect_ratios[i] if isinstance(aspect_ratios[0],
                                            (list, tuple)) else aspect_ratios
        st = (steps[i] if steps else
              ((step_w[i] if step_w else 0.0),
               (step_h[i] if step_h else 0.0)))
        if not isinstance(st, (list, tuple)):
            st = (st, st)
        box, var = prior_box(feat, image, [ms], [mx] if mx else None,
                             ar, variance, flip, clip, st, offset,
                             min_max_aspect_ratios_order)
        nprior_dim = 1
        for s in box.shape[:-1]:
            nprior_dim *= int(s)
        boxes_all.append(L.reshape(box, [-1, 4]))
        vars_all.append(L.reshape(var, [-1, 4]))
        num_priors_per_cell = int(box.shape[2])
        loc = L.conv2d(feat, num_priors_per_cell * 4, kernel_size,
                       stride=stride, padding=pad)
        conf = L.conv2d(feat, num_priors_per_cell * num_classes,
                        kernel_size, stride=stride, padding=pad)
        locs.append(L.reshape(L.transpose(loc, [0, 2, 3, 1]),
                              [0, -1, 4]))
        confs.append(L.reshape(L.transpose(conf, [0, 2, 3, 1]),
                               [0, -1, num_classes]))
    mbox_locs = L.concat(locs, axis=1) if n > 1 else locs[0]
    mbox_confs = L.concat(confs, axis=1) if n > 1 else confs[0]
    boxes = L.concat(boxes_all, axis=0) if n > 1 else boxes_all[0]
    variances = L.concat(vars_all, axis=0) if n > 1 else vars_all[0]
    return mbox_locs, mbox_confs, boxes, variances


def yolov3_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
                ignore_thresh, downsample_ratio, gt_score=None,
                use_label_smooth=True, name=None, scale_x_y=1.0):
    """YOLOv3 training loss (yolov3_loss_op.cc): objectness + box +
    class terms against assigned anchors. Dense gt (B, G, 4) xywh
    relative coords, gt_label (B, G) padded with -1."""
    _register_delegate(
        "yolov3_loss_s", _yolov3_fn,
        in_slots=("X", "GTBox", "GTLabel", "GTScore"))
    ins = {"X": [x.name], "GTBox": [gt_box.name],
           "GTLabel": [gt_label.name]}
    if gt_score is not None:
        ins["GTScore"] = [gt_score.name]
    return _append_simple(
        "yolov3_loss_s", ins,
        {"anchors": tuple(anchors), "anchor_mask": tuple(anchor_mask),
         "class_num": int(class_num),
         "ignore_thresh": float(ignore_thresh),
         "downsample_ratio": int(downsample_ratio),
         "use_label_smooth": bool(use_label_smooth),
         "scale_x_y": float(scale_x_y)})


def _yolov3_fn(x, gt_box, gt_label, gt_score=None, anchors=(),
               anchor_mask=(), class_num=1, ignore_thresh=0.7,
               downsample_ratio=32, use_label_smooth=True, scale_x_y=1.0):
    b, _, h, w = x.shape
    an = len(anchor_mask)
    xv = x.reshape(b, an, 5 + class_num, h, w)
    input_size = downsample_ratio * h
    mask_anchors = jnp.asarray(
        [(anchors[2 * m], anchors[2 * m + 1]) for m in anchor_mask],
        jnp.float32)
    gx = (jnp.arange(w, dtype=jnp.float32))[None, None, None, :]
    gy = (jnp.arange(h, dtype=jnp.float32))[None, None, :, None]
    px = jax.nn.sigmoid(xv[:, :, 0])
    py = jax.nn.sigmoid(xv[:, :, 1])
    pw = xv[:, :, 2]
    ph = xv[:, :, 3]
    obj_logit = xv[:, :, 4]
    cls_logit = xv[:, :, 5:]

    valid = (gt_label >= 0)
    gwh = gt_box[:, :, 2:4]                       # (B, G, 2) rel w,h
    # best anchor per gt by IoU of (w, h) boxes centered at origin
    all_anchors = jnp.asarray(anchors, jnp.float32).reshape(-1, 2)
    gt_wh_abs = gwh * input_size                  # pixels
    inter = jnp.minimum(gt_wh_abs[:, :, None, 0], all_anchors[None, None, :, 0]) * \
        jnp.minimum(gt_wh_abs[:, :, None, 1], all_anchors[None, None, :, 1])
    union = gt_wh_abs[:, :, 0:1] * gt_wh_abs[:, :, 1:2] + \
        all_anchors[None, None, :, 0] * all_anchors[None, None, :, 1] - inter
    best_anchor = jnp.argmax(inter / jnp.maximum(union, 1e-9), axis=2)

    # cell assignment
    gi = jnp.clip((gt_box[:, :, 0] * w).astype(jnp.int32), 0, w - 1)
    gj = jnp.clip((gt_box[:, :, 1] * h).astype(jnp.int32), 0, h - 1)

    loss = jnp.zeros((b,), jnp.float32)
    # objectness target grid + per-gt losses via scatter-style gather
    obj_target = jnp.zeros((b, an, h, w), jnp.float32)
    batch_idx = jnp.arange(b)[:, None]
    for mi, m in enumerate(anchor_mask):
        sel = valid & (best_anchor == m)          # (B, G)
        self_ = sel.astype(jnp.float32)
        # gather predictions at assigned cells
        pxg = px[batch_idx, mi, gj, gi]
        pyg = py[batch_idx, mi, gj, gi]
        pwg = pw[batch_idx, mi, gj, gi]
        phg = ph[batch_idx, mi, gj, gi]
        tx = gt_box[:, :, 0] * w - gi
        ty = gt_box[:, :, 1] * h - gj
        tw = jnp.log(jnp.maximum(
            gt_wh_abs[:, :, 0] / mask_anchors[mi, 0], 1e-9))
        th = jnp.log(jnp.maximum(
            gt_wh_abs[:, :, 1] / mask_anchors[mi, 1], 1e-9))
        box_scale = 2.0 - gwh[:, :, 0] * gwh[:, :, 1]
        bce = lambda p_, t_: (jnp.maximum(p_, 0) * 0 + (p_ - t_) ** 2)  # noqa: E731
        lb = ((pxg - tx) ** 2 + (pyg - ty) ** 2 +
              (pwg - tw) ** 2 + (phg - th) ** 2) * box_scale
        loss = loss + jnp.sum(lb * self_, axis=1)
        # class loss at assigned cells
        clg = cls_logit[batch_idx, mi, :, gj, gi]  # (B, G, C)
        smooth = (1.0 / class_num if use_label_smooth and class_num > 1
                  else 0.0)
        tcls = jnp.where(
            (jnp.maximum(gt_label, 0)[:, :, None] ==
             jnp.arange(class_num)[None, None, :]),
            1.0 - smooth, smooth / max(class_num - 1, 1))
        ce = jnp.maximum(clg, 0) - clg * tcls + \
            jnp.log1p(jnp.exp(-jnp.abs(clg)))
        loss = loss + jnp.sum(jnp.sum(ce, -1) * self_, axis=1)
        obj_target = obj_target.at[batch_idx, mi, gj, gi].max(self_)
    # objectness loss everywhere (positives -> 1, rest -> 0)
    obj_ce = jnp.maximum(obj_logit, 0) - obj_logit * obj_target + \
        jnp.log1p(jnp.exp(-jnp.abs(obj_logit)))
    loss = loss + jnp.sum(obj_ce, axis=(1, 2, 3))
    return loss[:, None]


def autodoc(comment=""):
    """Doc passthrough (reference layer_function_generator.autodoc)."""
    def deco(fn):
        return fn

    return deco


def templatedoc(op_type=None):
    """Doc passthrough (reference layer_function_generator.templatedoc)."""
    def deco(fn):
        return fn

    return deco


def generate_layer_fn(op_type):
    raise NotImplementedError(
        "generate_layer_fn generated OpDesc facades from the C++ op "
        "registry; kernels here are jnp functions — add an op to "
        "static/kernels.py instead")


generate_activation_fn = generate_layer_fn

# refresh the export list with the second sweep
__all__ = [n for n, v in list(globals().items())
           if not n.startswith("_") and
           (callable(v) or isinstance(v, type)) and
           getattr(v, "__module__", "").startswith("paddle_tpu")]
_export_into_layers()


# ---------------------------------------------------------------------------
# round-3 export sweep: names the reference publishes under
# fluid.layers.__all__ whose implementations live in other paddle_tpu
# namespaces (audited mechanically against the 305-name reference list;
# the dense+lengths design's LoD/SelectedRows mutation ops stay
# documented non-goals in COVERAGE.md)
# ---------------------------------------------------------------------------

def _export_foreign_names():
    from .. import ops as _ops
    from . import layers as _layers
    from . import rnn_builder as _rnnb

    fwd = {}
    for _n in ("argmin", "argsort", "beam_search", "beam_search_decode",
               "diag", "edit_distance", "eye",
               "fill_constant_batch_size_like", "greater_equal",
               "has_inf", "has_nan", "is_empty", "isfinite", "less_equal",
               "linspace", "not_equal", "ones", "ones_like", "py_func",
               "reverse", "unique", "unique_with_counts", "zeros",
               "zeros_like", "sequence_conv", "sequence_expand",
               "sequence_first_step", "sequence_last_step",
               "sequence_pad", "sequence_pool", "sequence_reverse",
               "sequence_softmax", "sequence_unpad"):
        if hasattr(_ops, _n):
            fwd[_n] = getattr(_ops, _n)
    # ops.Print / ops.Assert (host-callback debug ops)
    for _n in ("Print", "Assert"):
        if hasattr(_ops, _n):
            fwd[_n] = getattr(_ops, _n)
    fwd["StaticRNN"] = _rnnb.StaticRNN
    fwd["DynamicRNN"] = _rnnb.DynamicRNN
    # seq2seq decoding family (nn/decode.py; reference rnn.py:585-1900)
    from ..nn import decode as _dec
    for _n in _dec.__all__:
        fwd[_n] = getattr(_dec, _n)

    def _rnn(cell, inputs, initial_states=None, sequence_length=None,
             time_major=False, is_reverse=False, **kwargs):
        """Scan a cell over time (reference rnn.py:433) — thin facade
        over nn.RNN."""
        from ..nn.rnn import RNN as _RNNLayer

        runner = _RNNLayer(cell, is_reverse=is_reverse,
                           time_major=time_major)
        return runner(inputs, initial_states=initial_states,
                      sequence_length=sequence_length)

    fwd["rnn"] = _rnn
    # fluid.layers.load (load_op facade, reference fluid/layers/io.py:907
    # `load(out, file_path, load_as_fp16)`): appends an assign into the
    # given variable from the file's array, run at executor time
    def _layers_load(out, file_path, load_as_fp16=None):
        import pickle

        try:
            arr = np.load(file_path, allow_pickle=False)
        except (ValueError, OSError):
            with open(file_path, "rb") as f:
                arr = np.asarray(pickle.load(f))
        if load_as_fp16:
            arr = arr.astype(np.float16)
        from .layers import assign as _assign

        return _assign(arr, output=out)

    fwd["load"] = _layers_load
    _layers._register_exports(fwd)


_export_foreign_names()


# ---------------------------------------------------------------------------
# CTR / focus long tail (round 3): continuous_value_model,
# filter_by_instag, similarity_focus
# ---------------------------------------------------------------------------


def continuous_value_model(input, cvm, use_cvm=True):
    """CTR show/click feature transform (cvm_op.h). input (B, D) whose
    first two columns are raw show/click; use_cvm=True rewrites them to
    (log(show+1), log(click+1)-log(show+1)) keeping D columns,
    use_cvm=False drops them (B, D-2). ``cvm`` is accepted for API
    parity — the reference kernel also reads the counts from X itself."""
    import jax.numpy as jnp

    from ..framework.tensor import Tensor, unwrap

    x = jnp.asarray(unwrap(input), jnp.float32)
    if use_cvm:
        show_log = jnp.log(x[:, 0] + 1.0)
        click_log = jnp.log(x[:, 1] + 1.0) - show_log
        return Tensor(jnp.concatenate(
            [show_log[:, None], click_log[:, None], x[:, 2:]], axis=1))
    return Tensor(x[:, 2:])


def filter_by_instag(ins, ins_tag, filter_tag, is_lod=True,
                     out_val_if_empty=0):
    """Keep instances whose tag set intersects filter_tag
    (filter_by_instag_op.h). ins (N, D) one row per instance; ins_tag
    (N, T) int64 padded with negatives; filter_tag (K,). Returns
    (out, loss_weight (M, 1), index_map (M, 2) [new, old]); when no
    instance matches, one row of ``out_val_if_empty`` with loss weight
    0 (the reference's empty-output guard)."""
    import jax.numpy as jnp

    from ..framework.tensor import Tensor, unwrap

    x = np.asarray(unwrap(ins))
    tags = np.asarray(unwrap(ins_tag)).reshape(len(x), -1)
    flt = set(np.asarray(unwrap(filter_tag)).reshape(-1).tolist())
    # NB: bare `range` resolves to the fluid op in this module
    keep = [int(i) for i in np.arange(len(x))
            if flt.intersection(t for t in tags[i].tolist() if t >= 0)]
    if keep:
        out = x[keep]
        lw = np.ones((len(keep), 1), np.float32)
        imap = np.stack([np.arange(len(keep)), np.asarray(keep)], axis=1)
    else:
        out = np.full((1,) + x.shape[1:], out_val_if_empty, x.dtype)
        lw = np.zeros((1, 1), np.float32)
        imap = np.zeros((1, 2), np.int64)
    return (Tensor(jnp.asarray(out)), Tensor(jnp.asarray(lw)),
            Tensor(jnp.asarray(imap.astype(np.int64))))


def similarity_focus(input, axis, indexes, name=None):
    """Similarity-focus mask (similarity_focus_op.cc, NAACL16): for each
    index along ``axis`` (rank-4 input, axis in {1, 2, 3}), greedily
    pick the largest entries of the selected 3-D slice such that each
    row/column is used at most once (min(B, C) picks), set those
    positions to 1, and broadcast the OR of all index masks back over
    ``axis``. Runs as a fixed-length lax.fori_loop per (batch, index) —
    greedy argmax with row/col knockout."""
    import jax
    import jax.numpy as jnp

    from ..framework.tensor import Tensor, unwrap

    x = jnp.asarray(unwrap(input), jnp.float32)
    if x.ndim != 4:
        raise ValueError("similarity_focus expects a rank-4 input")
    if axis not in (1, 2, 3):
        raise ValueError("axis must be 1, 2 or 3")

    def greedy_mask(t):
        """(B, C) slice -> (B, C) 0/1 mask with unique rows/cols."""
        b, c = t.shape
        k = min(b, c)

        def body(_, carry):
            mask, rused, cused = carry
            blocked = rused[:, None] | cused[None, :]
            cand = jnp.where(blocked, -jnp.inf, t)
            flat = jnp.argmax(cand)
            r, cc = flat // c, flat % c
            mask = mask.at[r, cc].set(1.0)
            return mask, rused.at[r].set(True), cused.at[cc].set(True)

        mask0 = jnp.zeros((b, c))
        m, _, _ = jax.lax.fori_loop(
            0, k, body, (mask0, jnp.zeros(b, bool), jnp.zeros(c, bool)))
        return m

    moved = jnp.moveaxis(x, axis, 1)            # (N, AXIS, B, C)
    sel = moved[:, jnp.asarray(indexes, jnp.int32)]
    masks = jax.vmap(jax.vmap(greedy_mask))(sel)   # (N, idx, B, C)
    merged = (jnp.sum(masks, axis=1) > 0).astype(x.dtype)
    out = jnp.broadcast_to(merged[:, None], moved.shape)
    return Tensor(jnp.moveaxis(out, 1, axis))


__all__ = __all__ + ["continuous_value_model", "filter_by_instag",
                     "similarity_focus"]

from . import layers as _layers_mod  # noqa: E402

_layers_mod._register_exports({
    "continuous_value_model": continuous_value_model,
    "filter_by_instag": filter_by_instag,
    "similarity_focus": similarity_focus,
})


# ---------------------------------------------------------------------------
# LoD / SelectedRows bridge ops (round 3): real implementations against
# the framework's LoDTensor container and a minimal SelectedRows value
# (the dense+lengths design carries LoD beside the data, so these ops
# manipulate that side-table rather than a fused runtime type)
# ---------------------------------------------------------------------------


class SelectedRows:
    """Sparse row-set value (framework/selected_rows.h): ``rows`` int
    indices into a conceptual (height, ...) dense tensor, ``value`` the
    corresponding rows. The framework-wide sparse-gradient answer lives
    in ps/table.py; this value type exists for the fluid op surface."""

    def __init__(self, rows, value, height):
        self.rows = np.asarray(rows, np.int64).reshape(-1)
        self.value = np.asarray(value)
        self.height = int(height)


def merge_selected_rows(x, name=None):
    """Sum duplicate rows (merge_selected_rows_op.cc), rows ascending."""
    uniq, inv = np.unique(x.rows, return_inverse=True)
    merged = np.zeros((len(uniq),) + x.value.shape[1:], x.value.dtype)
    np.add.at(merged, inv, x.value)
    return SelectedRows(uniq, merged, x.height)


def get_tensor_from_selected_rows(x, name=None):
    """Densify: scatter rows into a (height, ...) zero tensor
    (get_tensor_from_selected_rows_op.cc)."""
    import jax.numpy as jnp

    from ..framework.tensor import Tensor

    out = np.zeros((x.height,) + x.value.shape[1:], x.value.dtype)
    np.add.at(out, x.rows, x.value)
    return Tensor(jnp.asarray(out))


def lod_reset(x, y=None, target_lod=None):
    """Replace the outermost LoD level (lod_reset_op.cc). x: LoDTensor
    (or raw array); y: a LoDTensor donating its LoD, or a 1-D offsets
    array; target_lod: plain python offsets list."""
    from ..framework.lod import LoDTensor
    from ..framework.tensor import Tensor

    data = x.data if isinstance(x, LoDTensor) else \
        (x.value if isinstance(x, Tensor) else np.asarray(x))
    base = x.lod()[1:] if isinstance(x, LoDTensor) else []
    if y is not None:
        if isinstance(y, LoDTensor) and y.lod():
            new0 = y.lod()[0]
        else:
            new0 = np.asarray(
                y.value if isinstance(y, Tensor) else y).reshape(-1).tolist()
    elif target_lod is not None:
        new0 = list(target_lod)
    else:
        raise ValueError("lod_reset needs y or target_lod")
    return LoDTensor(data, [list(map(int, new0))] + base)


def lod_append(x, level):
    """Append an innermost LoD level (lod_append_op.cc). level: offsets
    list or 1-D array."""
    from ..framework.lod import LoDTensor
    from ..framework.tensor import Tensor

    data = x.data if isinstance(x, LoDTensor) else \
        (x.value if isinstance(x, Tensor) else np.asarray(x))
    base = x.lod() if isinstance(x, LoDTensor) else []
    lv = np.asarray(
        level.value if isinstance(level, Tensor) else level
    ).reshape(-1).tolist()
    return LoDTensor(data, base + [list(map(int, lv))])


# roi-pooling variants live in vision/ops.py (jit kernels)
psroi_pool = VOPS.psroi_pool
prroi_pool = VOPS.prroi_pool
deformable_roi_pooling = VOPS.deformable_roi_pooling

_layers_mod._register_exports({
    "SelectedRows": SelectedRows,
    "merge_selected_rows": merge_selected_rows,
    "get_tensor_from_selected_rows": get_tensor_from_selected_rows,
    "lod_reset": lod_reset, "lod_append": lod_append,
    "psroi_pool": psroi_pool, "prroi_pool": prroi_pool,
    "deformable_roi_pooling": deformable_roi_pooling,
})


class LoDRankTable:
    """Sequence ranking (lod_rank_table_op.cc): items (index, length)
    sorted by length descending, ties in original order."""

    def __init__(self, items):
        self.items = list(items)          # [(original_index, length)]


def lod_rank_table(x, level=0):
    from ..framework.lod import LoDTensor

    if not isinstance(x, LoDTensor) or not x.lod():
        raise ValueError("lod_rank_table needs a LoDTensor with LoD")
    lens = x.recursive_sequence_lengths()[level]
    # NB: bare `range` resolves to the fluid op in this module
    order = sorted(np.arange(len(lens)).tolist(),
                   key=lambda i: (-lens[i], i))
    return LoDRankTable([(int(i), int(lens[i])) for i in order])


def reorder_lod_tensor_by_rank(x, rank_table):
    """Permute x's outer sequences into rank-table order
    (reorder_lod_tensor_by_rank_op.cc — the old DynamicRNN
    sort-by-length preprocessing)."""
    from ..framework.lod import LoDTensor

    if not isinstance(x, LoDTensor):
        raise ValueError("reorder_lod_tensor_by_rank needs a LoDTensor")
    offsets = x.lod()[0]
    data = np.asarray(x.data)
    chunks, new_lens = [], []
    for idx, _ in rank_table.items:
        s, e = offsets[idx], offsets[idx + 1]
        chunks.append(data[s:e])
        new_lens.append(e - s)
    out = LoDTensor(np.concatenate(chunks, axis=0) if chunks else data)
    out.set_recursive_sequence_lengths([new_lens] +
                                       x.recursive_sequence_lengths()[1:])
    return out


_layers_mod._register_exports({
    "LoDRankTable": LoDRankTable, "lod_rank_table": lod_rank_table,
    "reorder_lod_tensor_by_rank": reorder_lod_tensor_by_rank,
})


# ---------------------------------------------------------------------------
# paddle.static stragglers: gradients, name_scope, ParallelExecutor,
# WeightNormParamAttr
# ---------------------------------------------------------------------------


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    """paddle.static.gradients (reference static/__init__ ->
    backward.gradients): d targets / d inputs inside the current
    program — the calc_gradient surface under its 2.0 name.
    Inputs named in no_grad_set get None in the result (the
    reference's stop-gradient contract), the rest flow through
    calc_gradient."""
    from .backward import calc_gradient

    inputs = list(inputs) if isinstance(inputs, (list, tuple)) \
        else [inputs]
    ng = {getattr(v, "name", str(v)) for v in (no_grad_set or ())}
    live = [v for v in inputs if v.name not in ng]
    grads = calc_gradient(targets, live, target_gradients)
    if not isinstance(grads, (list, tuple)):
        grads = [grads]
    it = iter(grads)
    return [None if v.name in ng else next(it) for v in inputs]


class _NameScope:
    def __init__(self, prefix):
        self.prefix = prefix

    def __enter__(self):
        from ..utils import unique_name

        unique_name._prefix_stack.append(self.prefix + "/")
        return self

    def __exit__(self, *exc):
        from ..utils import unique_name

        unique_name._prefix_stack.pop()
        return False


def name_scope(prefix=None):
    """paddle.static.name_scope: nest generated op/var names under a
    prefix (reference framework.py name_scope)."""
    return _NameScope(prefix or "scope")


class ParallelExecutor:
    """fluid.ParallelExecutor facade (reference parallel_executor.py):
    the multi-device engine behind CompiledProgram.with_data_parallel —
    here one sharded jit (static/compiler.py), so this class pairs a
    CompiledProgram with an Executor and keeps the old run() shape."""

    def __init__(self, use_cuda=False, loss_name=None, main_program=None,
                 share_vars_from=None, exec_strategy=None,
                 build_strategy=None, num_trainers=1, trainer_id=0,
                 scope=None):
        from .compiler import CompiledProgram
        from .executor import Executor, global_scope
        from .ir import default_main_program

        prog = main_program or default_main_program()
        self._compiled = CompiledProgram(prog).with_data_parallel(
            loss_name=loss_name, build_strategy=build_strategy,
            exec_strategy=exec_strategy)
        self._exe = Executor()
        self._scope = scope or global_scope()

    def run(self, fetch_list, feed=None, feed_dict=None,
            return_numpy=True):
        return self._exe.run(self._compiled, feed=feed or feed_dict,
                             fetch_list=fetch_list, scope=self._scope,
                             return_numpy=return_numpy)


from ..nn.layer import ParamAttr as _ParamAttr  # noqa: E402


class WeightNormParamAttr(_ParamAttr):
    """fluid.WeightNormParamAttr (reference param_attr.py:197): marks a
    parameter for g * v/||v|| reparametrization along ``dim``. Dygraph
    layers apply it through nn.weight_norm; static fc consumers read
    the ``dim`` attribute."""

    def __init__(self, dim=None, name=None, initializer=None,
                 learning_rate=1.0, regularizer=None, trainable=True,
                 do_model_average=False, need_clip=True):
        super().__init__(name=name, initializer=initializer,
                         learning_rate=learning_rate,
                         regularizer=regularizer, trainable=trainable,
                         do_model_average=do_model_average,
                         need_clip=need_clip)
        self.dim = dim


_layers_mod._register_exports({
    "gradients": gradients, "name_scope": name_scope,
    "ParallelExecutor": ParallelExecutor,
    "WeightNormParamAttr": WeightNormParamAttr,
})


# ---------------------------------------------------------------------------
# fluid.layers.ops activation tail (reference fluid/layers/ops.py __all__:
# __activations_noattr__ + __unary_func__ + the parameterized shrink/relu
# family). These complete the frozen fluid.layers surface audited by
# tests/test_namespace_freeze.py.
# ---------------------------------------------------------------------------

def _unary_layer(op_type):
    def f(x, name=None):
        return _append_simple(op_type, {"X": [x]})
    f.__name__ = op_type
    return f


logsigmoid = _unary_layer("logsigmoid")
tanh_shrink = _unary_layer("tanh_shrink")
atan = _unary_layer("atan")
acos = _unary_layer("acos")
asin = _unary_layer("asin")
sinh = _unary_layer("sinh")
cosh = _unary_layer("cosh")
erf = _unary_layer("erf")
softplus = _unary_layer("softplus")
softsign = _unary_layer("softsign")
rsqrt = _unary_layer("rsqrt")
reciprocal = _unary_layer("reciprocal")
_cos_layer = _unary_layer("cos")
_sin_layer = _unary_layer("sin")
_ceil_layer = _unary_layer("ceil")
_floor_layer = _unary_layer("floor")
_round_layer = _unary_layer("round")


def softshrink(x, alpha=0.5, name=None):
    return _append_simple("softshrink", {"X": [x]}, {"lambda": alpha})


def hard_shrink(x, threshold=0.5):
    return _append_simple("hard_shrink", {"X": [x]},
                          {"threshold": threshold})


def thresholded_relu(x, threshold=1.0):
    return _append_simple("thresholded_relu", {"X": [x]},
                          {"threshold": threshold})


def cumsum(x, axis=None, exclusive=None, reverse=None):
    attrs = {"axis": -1 if axis is None else axis,
             "flatten": axis is None,
             "exclusive": bool(exclusive), "reverse": bool(reverse)}
    return _append_simple("cumsum", {"X": [x]}, attrs)


_layers_mod._register_exports({
    "logsigmoid": logsigmoid, "tanh_shrink": tanh_shrink, "atan": atan,
    "acos": acos, "asin": asin, "sinh": sinh, "cosh": cosh, "erf": erf,
    "softplus": softplus, "softsign": softsign, "rsqrt": rsqrt,
    "reciprocal": reciprocal, "softshrink": softshrink,
    "hard_shrink": hard_shrink, "thresholded_relu": thresholded_relu,
    "cumsum": cumsum,
    # builtin-named / math ops must ride the PEP 562 registry so they
    # never shadow builtins inside layers.py
    "cos": _cos_layer, "sin": _sin_layer, "ceil": _ceil_layer,
    "floor": _floor_layer, "round": _round_layer,
})
