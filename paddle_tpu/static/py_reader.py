"""Static-graph reader facade: py_reader / create_py_reader_by_data /
read_file / double_buffer (reference fluid/layers/io.py py_reader :558,
operators/reader/create_py_reader_op.cc + buffered_reader.cc).

The reference feeds a C++ LoDTensorBlockingQueue that `read_op` pops
inside the executor. The TPU translation: the reader owns the static
``data`` variables and a Python batch source; ``Executor.run`` pulls the
next batch from every started reader of the program into the feed dict
(the dense equivalent of read_op), raising ``EOFException`` when the
source is exhausted — the reference's catch-EOF-then-reset() training
loop works verbatim. With ``use_double_buffer`` (the default) a started
reader runs a :class:`~paddle_tpu.static.prefetch.FeedPrefetcher`: the
next batch is pulled from the user generator AND device_put on a
background thread while the current step executes — the real
double-buffer semantics of buffered_reader.cc, not just jit dispatch
pipelining. ``double_buffer`` stays the identity (the reader itself
already buffers).
"""
from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

from ..framework.errors import EOFException
from ..utils import unique_name
from .prefetch import FeedPrefetcher, stage_feed

__all__ = ["py_reader", "create_py_reader_by_data", "read_file",
           "double_buffer", "PyReader"]


class PyReader:
    """Program-attached batch source feeding a fixed list of data vars."""

    def __init__(self, program, feed_vars, capacity=64,
                 use_double_buffer=True):
        self.program = program
        self.feed_vars = list(feed_vars)
        self.capacity = capacity
        self.use_double_buffer = use_double_buffer
        self._gen_fn = None
        self._it = None
        self._started = False
        readers = getattr(program, "_py_readers", None)
        if readers is None:
            readers = []
            program._py_readers = readers
        readers.append(self)

    # -- decoration (reference reader.py GeneratorLoader surface) -----

    def decorate_sample_list_generator(self, reader, places=None):
        """reader() yields a LIST of per-sample tuples per batch (the
        paddle.batch output format); each batch is stacked into one
        array per declared slot (reference GeneratorLoader
        set_sample_list_generator)."""
        def stacked():
            for sample_list in reader():
                yield tuple(np.stack([np.asarray(s[i]) for s in
                                      sample_list])
                            for i in range(len(sample_list[0])))

        self._gen_fn = stacked
        return self

    decorate_paddle_reader = decorate_sample_list_generator

    def decorate_batch_generator(self, reader, places=None):
        """reader() yields pre-batched per-slot arrays."""
        self._gen_fn = reader
        return self

    decorate_tensor_provider = decorate_batch_generator

    # -- run-time protocol --------------------------------------------

    def start(self):
        if self._gen_fn is None:
            raise RuntimeError("py_reader.start() before decorate_*()")
        if isinstance(self._it, FeedPrefetcher):
            self._it.close()   # re-start without reset(): don't orphan
                               # the old thread + its staged batches

        def feeds():
            for batch in self._gen_fn():
                yield self._to_feed(batch)

        if self.use_double_buffer:
            # depth beyond a couple of batches only holds extra device
            # memory; capacity still caps tiny-queue configs
            depth = max(1, min(int(self.capacity) or 1, 2))
            # env-driven AMP fallback for raw-Program runs: without a
            # stash (CompiledProgram/Executor.run set one) the first
            # `depth` batches would stage f32 and cost a recompile
            if not hasattr(self.program, "_amp_feed_dtypes"):
                from .passes import amp_feed_dtypes_cached, resolve_amp

                try:
                    self.program._amp_feed_dtypes = amp_feed_dtypes_cached(
                        self.program, resolve_amp(None))
                except ValueError:
                    self.program._amp_feed_dtypes = None
            # a CompiledProgram run stashes its feed sharding on the
            # program (Executor.run): batches stage straight into the
            # sharded layout instead of resharding every step. Resolved
            # per batch — the stash only appears at the first run, after
            # start() has already been called.
            # _amp_feed_dtypes (stashed by Executor.run like the
            # sharding) casts float32 feeds low on this thread, before
            # the h2d copy
            self._it = FeedPrefetcher(
                feeds(), depth=depth,
                stage=lambda feed: stage_feed(
                    feed, getattr(self.program, "_feed_sharding", None),
                    getattr(self.program, "_amp_feed_dtypes", None)))
        else:
            self._it = feeds()
        self._started = True

    def reset(self):
        if isinstance(self._it, FeedPrefetcher):
            self._it.close()
        self._it = None
        self._started = False

    def _to_feed(self, batch):
        if not isinstance(batch, (list, tuple)):
            batch = (batch,)
        if len(batch) != len(self.feed_vars):
            raise ValueError(
                f"py_reader batch arity {len(batch)} != declared "
                f"{len(self.feed_vars)} slots")
        return {v.name: np.asarray(b) for v, b in
                zip(self.feed_vars, batch)}

    def _next_feed(self):
        if not self._started:
            return {}
        try:
            # prefetched batches arrive device-resident (staged on the
            # reader thread); generator-path batches stay host arrays and
            # transfer inside the jit call — either way Executor.run
            # feeds them through unchanged
            return next(self._it)
        except StopIteration:
            self.reset()
            raise EOFException(
                "py_reader source exhausted — catch this and call "
                "reader.reset() (reference fluid.core.EOFException "
                "loop)") from None


def py_reader(capacity, shapes, dtypes, lod_levels=None, name=None,
              use_double_buffer=True):
    """Create a reader plus its data variables (fluid/layers/io.py:558).
    shapes use -1 for the batch axis."""
    from .ir import default_main_program
    from .layers import data as data_layer

    prog = default_main_program()
    feed_vars = []
    base = name or unique_name.generate("py_reader")
    for i, (shape, dtype) in enumerate(zip(shapes, dtypes)):
        feed_vars.append(data_layer(
            name=f"{base}.slot{i}", shape=list(shape), dtype=dtype))
    return PyReader(prog, feed_vars, capacity, use_double_buffer)


def create_py_reader_by_data(capacity, feed_list, name=None,
                             use_double_buffer=True):
    """Reader over EXISTING data vars (fluid/layers/io.py:755)."""
    from .ir import default_main_program

    return PyReader(default_main_program(), feed_list, capacity,
                    use_double_buffer)


def read_file(reader):
    """The data variables a reader feeds (read_op parity: in the
    reference this pops the queue; here the pull happens in
    Executor.run, so this just hands back the graph inputs)."""
    vars_ = reader.feed_vars
    return vars_[0] if len(vars_) == 1 else vars_


def double_buffer(reader, place=None, name=None):
    """Identity by design: a started PyReader already stages the next
    batch host->device on its prefetch thread (see module note), which
    is what buffered_reader.cc's second buffer bought."""
    return reader


def _register():
    from . import layers as _layers

    _layers._register_exports(
        {"py_reader": py_reader,
         "create_py_reader_by_data": create_py_reader_by_data,
         "read_file": read_file, "double_buffer": double_buffer})


_register()
