"""Analytic cost model over the *optimized* Program IR: per-op and
per-step ``{model_flops, hbm_bytes, comm_bytes}`` derived from the
OpDescs the pass pipeline actually compiles — not from hand-coded
per-model closed forms.

Accounting conventions (PaLM-style MFU numerator):

- ``model_flops`` counts matmul-class ops only (matmul/mul/conv) at
  2 FLOPs per MAC; elementwise/reduction/normalization ops contribute
  HBM bytes, not FLOPs — they are bandwidth-bound and excluded from the
  MFU numerator exactly like bench.py's closed forms exclude them.
- ``hbm_bytes`` is the dtype-aware payload traffic of every op: input
  reads + output writes from VarDesc shapes and dtypes. The AMP pass
  stamps rewritten vars bf16/fp16, so mixed-precision bytes halve with
  no extra bookkeeping here. Gather-class ops (lookup_table, gather)
  read the gathered rows, never the whole table.
- ``comm_bytes`` is cross-chip traffic from the shard_propagation
  stamps: an op carrying ``__psum_axes`` costs a ring all-reduce of its
  per-shard output over those axes (``2*(g-1)/g`` bytes per payload
  byte).

The executor's real step structure folds in on top of the per-op walk:

- a ``backward`` op multiplies every forward op by 3 (one forward + two
  backward passes, the PaLM train-step convention); ops stamped
  ``__remat_seg`` add one more forward (the recompute pass re-runs the
  segment in the backward)
- ``gradient_merge_k``: ops in the scanned region (forward + backward +
  an adjacent ``check_finite_and_unscale``) run per microbatch at
  ``B/k`` and are counted k times; the optimizer region runs once — the
  compiled ``lax.scan`` structure, mirrored
- sharding (``__sharding_spec`` stamps + the build's mesh axis sizes):
  an op's work divides by the product of the distinct mesh axes its
  operands are partitioned over — per-CHIP cost, matching per-chip MFU
- ``pipeline_stages`` is recorded (pipelining moves work in time, not
  in amount); with a schedule the report also carries the analytic
  bubble fraction (``parallel.pipeline.schedule_bubble_fraction``), so
  the roofline can discount idle slots per schedule
- ``zero`` (the engaged ZeRO stage): the gradient traffic decomposes
  into a ``comm_reduce_scatter`` of the ENCODED bucket (half the ring)
  plus a ``comm_all_gather`` of the updated params in RAW f32 — the
  exact wire structure static/stepplan.py's zero kind compiles —
  instead of the single ``comm_allreduce`` pseudo-op

Everything is static VarDesc arithmetic — no tracing, no device touch —
so a cost report for a BERT-sized program costs microseconds and can
run per compiled executable in the executor hot path (cached on the
executable's cache entry).
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

__all__ = ["OpCost", "CostReport", "program_cost", "paged_decode_cost",
           "kv_offload_page_bytes"]

# matmul-class ops: the MFU numerator (2 FLOPs per MAC)
_MATMUL_OPS = {"mul", "matmul", "matmul_v2"}
# conv ops as the IR actually emits them (layers.py conv2d,
# layers_ext/layers_compat "_s"-suffixed 3D + transpose forms). Weight
# layouts differ: forward convs carry (Co, Ci/g, k...) and cost per
# OUTPUT element; transpose convs carry (Ci, Co/g, k...) and cost per
# INPUT element — both are 2 * elements * prod(W.shape[1:]) FLOPs.
_CONV_OPS = {"conv2d", "conv3d_s"}
_CONV_TRANSPOSE_OPS = {"conv2d_transpose_s", "conv3d_transpose_s"}
# gather-class: read the gathered rows + indices, not the whole table
_GATHER_OPS = {"lookup_table", "lookup_table_v2", "gather", "gather_nd",
               "embedding"}
# layout-only ops XLA compiles away: no HBM traffic charged
_FREE_OPS = {"feed", "fetch", "backward", "reshape2", "assign",
             "share_data", "shape", "increment"}
# write-only producers: charge the output, there is no tensor input
_PRODUCER_OPS = {"fill_constant", "assign_value", "gaussian_random",
                 "uniform_random", "truncated_gaussian_random",
                 "uniform_random_batch_size_like", "randint", "range",
                 "eye", "one_hot", "one_hot_v2"}

_ITEMSIZE = {
    "float64": 8, "int64": 8, "uint64": 8,
    "float32": 4, "int32": 4, "uint32": 4,
    "float16": 2, "bfloat16": 2, "int16": 2, "uint16": 2,
    "int8": 1, "uint8": 1, "bool": 1,
}


def _itemsize(dtype) -> int:
    return _ITEMSIZE.get(str(dtype), 4)


def _prod(seq) -> int:
    out = 1
    for v in seq:
        out *= int(v)
    return out


def _spec_axes(entry) -> Tuple[str, ...]:
    if entry is None:
        return ()
    return tuple(entry) if isinstance(entry, (list, tuple)) else (entry,)


class OpCost:
    """One op's per-step cost after structure multipliers: ``flops``
    (model FLOPs), ``hbm_bytes``, ``comm_bytes``; ``mult`` is the step
    multiplier applied (fwd/bwd/remat × gradient-merge k),
    ``shard_factor`` the per-chip division."""

    __slots__ = ("index", "type", "out", "flops", "hbm_bytes",
                 "comm_bytes", "mult", "shard_factor")

    def __init__(self, index, type, out, flops, hbm_bytes, comm_bytes,
                 mult, shard_factor):
        self.index = index
        self.type = type
        self.out = out
        self.flops = flops
        self.hbm_bytes = hbm_bytes
        self.comm_bytes = comm_bytes
        self.mult = mult
        self.shard_factor = shard_factor

    @property
    def arith_intensity(self) -> float:
        return self.flops / self.hbm_bytes if self.hbm_bytes else 0.0

    def to_dict(self) -> dict:
        return {"index": self.index, "type": self.type, "out": self.out,
                "flops": self.flops, "hbm_bytes": self.hbm_bytes,
                "comm_bytes": self.comm_bytes, "mult": self.mult,
                "shard_factor": self.shard_factor,
                "arith_intensity": round(self.arith_intensity, 3)}


class CostReport:
    """Per-op costs plus step totals for one optimized program."""

    def __init__(self, ops: List[OpCost], gm_k: int = 1,
                 pp_stages: int = 1, n_shards: int = 1,
                 batch: int = 1, schedule: str = "gpipe",
                 interleave: int = 2, zero_stage: int = 0):
        self.ops = ops
        self.gm_k = gm_k
        self.pp_stages = pp_stages
        self.n_shards = n_shards
        self.batch = batch
        self.schedule = schedule or "gpipe"
        self.interleave = int(interleave or 2)
        self.zero_stage = int(zero_stage or 0)
        self.model_flops = sum(o.flops for o in ops)
        self.hbm_bytes = sum(o.hbm_bytes for o in ops)
        self.comm_bytes = sum(o.comm_bytes for o in ops)

    @property
    def arith_intensity(self) -> float:
        return (self.model_flops / self.hbm_bytes
                if self.hbm_bytes else 0.0)

    @property
    def moe_a2a_bytes(self) -> int:
        """Wire bytes of the explicit MoE dispatch/combine all_to_alls
        (moe ops stamped ``__moe_ep`` by shard propagation)."""
        return sum(o.comm_bytes for o in self.ops if o.type == "moe")

    @property
    def pp_bubble_frac(self) -> float:
        """Analytic idle fraction of the pipelined step under the
        compiled schedule — 0.0 when not pipelined (S <= 1 or a single
        microbatch leaves nothing to overlap)."""
        if self.pp_stages <= 1 or self.gm_k <= 1:
            return 0.0
        from ..parallel.pipeline import schedule_bubble_fraction

        return schedule_bubble_fraction(
            self.schedule, self.pp_stages, self.gm_k, self.interleave)

    def by_type(self, field: str = "flops") -> Dict[str, int]:
        out: Dict[str, int] = {}
        for o in self.ops:
            v = getattr(o, field)
            if v:
                out[o.type] = out.get(o.type, 0) + v
        return dict(sorted(out.items(), key=lambda kv: -kv[1]))

    def top_ops(self, k: int = 10, by: str = "flops") -> List[OpCost]:
        return sorted((o for o in self.ops if getattr(o, by)),
                      key=lambda o: -getattr(o, by))[:k]

    def to_dict(self, top: int = 20) -> dict:
        """JSON-able summary — what the executor stamps into the
        step-trace ``cost`` record and ``exe.cost_stats()`` returns."""
        return {
            "model_flops": self.model_flops,
            "hbm_bytes": self.hbm_bytes,
            "comm_bytes": self.comm_bytes,
            "moe_a2a_bytes": self.moe_a2a_bytes,
            "arith_intensity": round(self.arith_intensity, 3),
            "n_ops": len(self.ops),
            "batch": self.batch,
            "gm_k": self.gm_k,
            "pp_stages": self.pp_stages,
            "pp_schedule": self.schedule,
            "pp_bubble_frac": round(self.pp_bubble_frac, 4),
            "zero_stage": self.zero_stage,
            "n_shards": self.n_shards,
            "flops_by_type": self.by_type("flops"),
            "bytes_by_type": self.by_type("hbm_bytes"),
            "top_flops": [o.to_dict() for o in self.top_ops(top, "flops")],
            "top_bytes": [o.to_dict()
                          for o in self.top_ops(top, "hbm_bytes")],
        }


def _resolve_batch(block, feed_shapes: Optional[Dict[str, Sequence[int]]],
                   batch_size: Optional[int]) -> int:
    """The dynamic-dim substitution value: derived from the live feed
    shapes against the data VarDescs' ``-k`` sentinel dims (``-k`` means
    "dynamic batch times static k"), else ``batch_size``, else 1."""
    if feed_shapes:
        for name, shape in feed_shapes.items():
            v = block.vars.get(name)
            dshape = getattr(v, "shape", None)
            if not dshape or not shape:
                continue
            d0 = -1 if dshape[0] is None else int(dshape[0])
            if d0 < 0 and int(shape[0]) > 0:
                return max(1, int(shape[0]) // -d0)
    return max(1, int(batch_size or 1))


def program_cost(program, feed_shapes=None, batch_size=None, gm=None,
                 shard_cfg=None, pp=None, comm=None, schedule=None,
                 interleave=None, zero=None) -> CostReport:
    """Walk ``program``'s optimized global block into a CostReport.

    ``feed_shapes``: {data var name -> live array shape} — resolves the
    dynamic batch dim. ``gm``/``shard_cfg``/``pp`` are the executor's
    resolve_gradient_merge/resolve_sharding/resolve_pipeline results for
    the build (None each when off). ``comm`` is the resolve_comm result
    when the build compiled the EXPLICIT quantized DP gradient
    all-reduce (parallel/collectives.py): the gradient buckets then
    charge their ENCODED ring bytes (payload + per-block scales, the
    encoded_nbytes closed form) into comm_bytes as a ``comm_allreduce``
    pseudo-op — never the f32 bytes the escape leg would move, so
    step_comm_bytes and the perf_report roofline stay truthful under
    quantization. (With comm=None the DP grad reduce is XLA's implicit
    f32 psum, uncharged — the pre-quantization accounting, unchanged.)

    ``schedule``/``interleave`` are the resolve_pipeline_schedule
    result for a pipelined build (None otherwise): they pick the
    closed-form ``pp_bubble_frac`` the report exposes. ``zero`` is the
    ZeRO stage (2|3) WHEN THE STEP-PLAN ENGAGED it (None otherwise —
    the caller gates on the live zero plan, mirroring how ``comm``
    gates on comm_stats): the gradient ring then splits into a
    ``comm_reduce_scatter`` pseudo-op at the encoded half-ring bytes
    plus a ``comm_all_gather`` at the RAW f32 updated-param bytes (the
    optimizer consumes the unquantized reduced chunk and re-broadcasts
    params unencoded — stepplan.py's wire structure, both stages move
    one param gather per step)."""
    block = program.global_block
    comm_cfg = comm   # the per-op loop below reuses `comm` as a local
    batch = _resolve_batch(block, feed_shapes, batch_size)
    axis_sizes: Dict[str, int] = dict(shard_cfg[0]) if shard_cfg else {}
    n_shards = _prod(axis_sizes.values()) if axis_sizes else 1

    ops = block.ops
    first_bwd = next((i for i, op in enumerate(ops)
                      if op.type == "backward"), None)
    gm_k = int(gm[0]) if (gm and first_bwd is not None) else 1
    scan_end = len(ops)
    if first_bwd is not None:
        scan_end = first_bwd + 1
        if scan_end < len(ops) and \
                ops[scan_end].type == "check_finite_and_unscale":
            scan_end += 1

    def shape_of(name: str, b: int) -> Optional[Tuple[int, ...]]:
        v = block.vars.get(name)
        shape = getattr(v, "shape", None)
        if shape is None:
            return None
        # dynamic dims come as -k ("dynamic batch times k") or a bare
        # None (the Paddle 2.x [None, ...] spelling) — both resolve
        # through the batch substitution
        return tuple(int(-(d if d is not None else -1)) * b
                     if d is None or int(d) < 0 else int(d)
                     for d in shape)

    def nbytes_of(name: str, b: int) -> int:
        shape = shape_of(name, b)
        if shape is None:
            return 0
        v = block.vars.get(name)
        return _prod(shape) * _itemsize(getattr(v, "dtype", "float32"))

    def spec_of(name: str):
        v = block.vars.get(name)
        return (getattr(v, "attrs", None) or {}).get("__sharding_spec")

    def shard_axes_of(op) -> Tuple[str, ...]:
        """Distinct mesh axes partitioning any operand of ``op`` (or its
        psum stamp): the op's work divides by their size product —
        row-parallel matmuls shard the contracted (input) dim, column-
        parallel the output dim, dp the batch dim; the union covers all
        three."""
        axes = set(op.attrs.get("__psum_axes") or ())
        for name in list(op.input_names()) + list(op.output_names()):
            for entry in (spec_of(name) or ()):
                axes.update(a for a in _spec_axes(entry)
                            if a in axis_sizes)
        return tuple(a for a in axes if a in axis_sizes)

    out: List[OpCost] = []
    for i, op in enumerate(ops):
        t = op.type
        if t in ("feed", "fetch", "backward"):
            continue
        # region structure: forward ops run 1 fwd + 2 bwd passes when a
        # backward op exists (+1 recompute under remat); the scanned
        # region repeats per microbatch at B/k; the optimizer region
        # runs once on the merged gradient at full batch
        in_scan = first_bwd is not None and i < scan_end
        b = max(1, batch // gm_k) if (in_scan and gm_k > 1) else batch
        if first_bwd is not None and i < first_bwd:
            mult = 3 + (1 if "__remat_seg" in op.attrs else 0)
        else:
            mult = 1
        if in_scan and gm_k > 1:
            mult *= gm_k

        ins = [n for n in op.input_names()]
        outs = [n for n in op.output_names()]
        flops = 0
        moe_comm = 0
        if t == "mul":
            o = outs[0] if outs else None
            oshape = shape_of(o, b) if o else None
            xshape = shape_of((op.inputs.get("X") or [None])[0], b)
            ncol = int(op.attrs.get("x_num_col_dims", 1))
            if oshape and xshape:
                k_dim = _prod(xshape[ncol:])
                flops = 2 * _prod(oshape) * k_dim
        elif t in _MATMUL_OPS:
            o = outs[0] if outs else None
            oshape = shape_of(o, b) if o else None
            xshape = shape_of((op.inputs.get("X") or [None])[0], b)
            if oshape and xshape:
                # both attr spellings: "transpose_X" (matmul) and
                # "trans_x" (matmul_v2 from deserialized 2.x programs —
                # the shard pass defends against the same pair)
                trans_x = (op.attrs.get("transpose_X")
                           or op.attrs.get("trans_x"))
                k_dim = int(xshape[-2] if trans_x else xshape[-1])
                flops = 2 * _prod(oshape) * k_dim
        elif t in _CONV_OPS or t in _CONV_TRANSPOSE_OPS:
            if t in _CONV_TRANSPOSE_OPS:
                base_name = (op.inputs.get("Input") or [None])[0]
            else:
                base_name = outs[0] if outs else None
            bshape = shape_of(base_name, b) if base_name else None
            wshape = shape_of((op.inputs.get("Filter")
                               or op.inputs.get("W") or [None])[0], b)
            if bshape and wshape:
                flops = 2 * _prod(bshape) * _prod(wshape[1:])
        elif t == "moe":
            # gate matmul + dispatch/combine einsums over the (e, c, d)
            # capacity grid + the expert FFNs on their capacity blocks
            xshape = shape_of((op.inputs.get("X") or [None])[0], b)
            w1shape = shape_of((op.inputs.get("W1") or [None])[0], b)
            if xshape and w1shape:
                tkn, d = _prod(xshape[:-1]), int(xshape[-1])
                e, h = int(w1shape[0]), int(w1shape[-1])
                cf = float(op.attrs.get("capacity_factor", 2.0))
                cap = max(1, int(cf * tkn / e))
                flops = (2 * tkn * d * e          # gate logits
                         + 4 * tkn * e * cap * d  # dispatch + combine
                         + 4 * e * cap * d * h)   # two FFN matmuls
                ep = op.attrs.get("__moe_ep")
                if ep:
                    # explicit exchange plan: charge the two hand-
                    # placed all_to_alls (dispatch may ride int8)
                    from ..nn.moe import moe_a2a_nbytes

                    moe_comm = moe_a2a_nbytes(
                        e, cap, d, int(ep[1]),
                        op.attrs.get("dispatch_codec") or None)

        if t == "paged_attention":
            # ragged paged decode attention: only the GATHERED live
            # pages (page-table entries x page bytes, K and V) count
            # toward hbm_bytes — never the whole pool the KPages/VPages
            # operands declare. FLOPs are the two attention matmuls
            # (scores + values) over the table-bounded context, the
            # same accounting the bench closed forms use.
            q_name = (op.inputs.get("Q") or [None])[0]
            kp_name = (op.inputs.get("KPages") or [None])[0]
            pt_name = (op.inputs.get("PageTable") or [None])[0]
            qshape = shape_of(q_name, b) if q_name else None
            kshape = shape_of(kp_name, b) if kp_name else None
            tshape = shape_of(pt_name, b) if pt_name else None
            if qshape and kshape and tshape:
                h, d = qshape[-2], qshape[-1]
                page_size = kshape[-3]
                live_tokens = _prod(tshape) * page_size
                kp_dtype = str(getattr(block.vars.get(kp_name),
                                       "dtype", "float32"))
                if kp_dtype in ("int8", "uint8"):
                    # quantized pool (kv_codec="int8"): the DMA moves
                    # the ENCODED page — int8 payload + one f32 scale
                    # per token row (ps/codec blocked layout with
                    # block = H*D), the same closed form the wire
                    # codec and the engine gauges share
                    from ..ps.codec import encoded_nbytes

                    kv_bytes = 2 * live_tokens * encoded_nbytes(
                        h * d, "int8", block=h * d)
                else:
                    kv_bytes = (2 * live_tokens * h * d
                                * _itemsize(kp_dtype))
                flops = 4 * h * d * live_tokens   # 2 matmuls x 2 F/MAC
                hbm = (kv_bytes                         # live K+V pages
                       + sum(nbytes_of(n, b) for n in (q_name,) if n)
                       + sum(nbytes_of(n, b) for n in outs)
                       + (nbytes_of(pt_name, b) if pt_name else 0))
            else:
                hbm = 0
        elif t in _FREE_OPS:
            hbm = 0
        elif t in _PRODUCER_OPS:
            hbm = sum(nbytes_of(n, b) for n in outs)
        elif t in _GATHER_OPS:
            # reads gathered rows (== out bytes) + indices, writes out
            ids = (op.inputs.get("Ids") or op.inputs.get("Index")
                   or [None])[0]
            out_b = sum(nbytes_of(n, b) for n in outs)
            hbm = 2 * out_b + (nbytes_of(ids, b) if ids else 0)
        else:
            hbm = (sum(nbytes_of(n, b) for n in ins)
                   + sum(nbytes_of(n, b) for n in outs))

        shard_axes = shard_axes_of(op)
        factor = _prod(axis_sizes[a] for a in shard_axes) \
            if shard_axes else 1
        comm = 0
        psum_axes = [a for a in (op.attrs.get("__psum_axes") or ())
                     if a in axis_sizes]
        if psum_axes and outs:
            g = _prod(axis_sizes[a] for a in psum_axes)
            if g > 1:
                # ring all-reduce of the per-shard output block: the
                # output spec's axes give its partitioning BEFORE the
                # psum replicates it over the contracted axes
                out_axes = {a for n in outs
                            for entry in (spec_of(n) or ())
                            for a in _spec_axes(entry)
                            if a in axis_sizes}
                out_factor = _prod(axis_sizes[a] for a in out_axes) \
                    if out_axes else 1
                payload = sum(nbytes_of(n, b) for n in outs) // out_factor
                comm = int(2 * (g - 1) * payload // g) * mult
        if moe_comm:
            comm += int(moe_comm) * mult

        out.append(OpCost(
            index=i, type=t, out=(outs[0] if outs else ""),
            flops=flops * mult // factor,
            hbm_bytes=hbm * mult // factor,
            comm_bytes=comm, mult=mult, shard_factor=factor))

    if comm_cfg is not None and first_bwd is not None:
        from .passes import comm_bucket_plan, comm_data_axis

        axis = comm_data_axis(shard_cfg)
        plan = (comm_bucket_plan(block, comm_cfg, axis[1])
                if axis is not None else None)
        if plan and zero:
            # ZeRO decomposition: the grad moves as the encoded
            # reduce-scatter HALF of the ring; the optimizer updates
            # its local chunk and the params come back as a raw-f32
            # all-gather (stage 2 post-update, stage 3 pre-forward —
            # one per step either way). Both once per step, like the
            # all-reduce they replace.
            from ..parallel.collectives import (all_gather_nbytes,
                                                reduce_scatter_nbytes)

            g = axis[1]
            out.append(OpCost(
                index=first_bwd, type="comm_reduce_scatter", out="",
                flops=0, hbm_bytes=0,
                comm_bytes=sum(
                    reduce_scatter_nbytes(b["elems"], g, comm_cfg[0])
                    for b in plan),
                mult=1, shard_factor=1))
            out.append(OpCost(
                index=first_bwd, type="comm_all_gather", out="",
                flops=0, hbm_bytes=0,
                comm_bytes=sum(
                    all_gather_nbytes(b["elems"], g, "f32")
                    for b in plan),
                mult=1, shard_factor=1))
        elif plan:
            # the bucketed quantized all-reduce runs ONCE per step on
            # the merged gradient (no gm multiplier — the PR 5
            # quantize-once-per-step discipline)
            out.append(OpCost(
                index=first_bwd, type="comm_allreduce", out="",
                flops=0, hbm_bytes=0,
                comm_bytes=sum(b["ring_encoded"] for b in plan),
                mult=1, shard_factor=1))

    return CostReport(out, gm_k=gm_k, pp_stages=int(pp or 1),
                      n_shards=n_shards, batch=batch,
                      schedule=schedule or "gpipe",
                      interleave=interleave or 2,
                      zero_stage=int(zero or 0))


def paged_decode_cost(config, live_lens: Sequence[int], page_size: int,
                      itemsize: int = 4,
                      kv_codec: str = "off") -> Dict[str, float]:
    """Analytic cost of ONE ragged paged decode step — the decode
    engine's source for the ``step_model_flops`` / ``step_hbm_bytes``
    / ``mfu`` / ``arith_intensity`` gauges (PR 12 plane), kept truthful
    on decode: attention HBM counts the GATHERED LIVE PAGES of each
    sequence (``ceil(len/page_size) * page_size`` positions), never the
    whole pool.

    ``config`` carries the model dims (``DecodeModelConfig`` or
    anything with n_layers/n_heads/head_dim/ffn_dim/vocab_size);
    ``live_lens`` is the attended context length per LIVE slot this
    step.

    FLOPs (matmul-class only, the MFU numerator): per live token the
    qkv+out projections (8E²) + ffn pair (4EF) + vocab head (2EV), plus
    per layer the two attention matmuls over the live context (4·E·ctx).
    HBM: the weights stream once per step (decode is bandwidth-bound
    precisely because of this) + the live K/V pages read and the new
    token's K/V written.

    With ``kv_codec="int8"`` the K/V page traffic is charged at the
    ENCODED byte cost — ``ps.codec.encoded_nbytes(E, "int8", block=E)``
    per token row (int8 payload + one f32 scale), the exact layout the
    pool stores — while params/logits stay at ``itemsize``."""
    L = int(config.n_layers)
    H = int(config.n_heads)
    D = int(config.head_dim)
    E = H * D
    F = int(config.ffn_dim)
    V = int(config.vocab_size)
    n = len(live_lens)
    if kv_codec == "int8":
        from ..ps.codec import encoded_nbytes

        kv_row_bytes = encoded_nbytes(E, "int8", block=E)
    else:
        kv_row_bytes = E * itemsize
    flops = 0
    page_tokens = 0
    for ln in live_lens:
        flops += L * (8 * E * E + 4 * E * F + 4 * E * int(ln)) \
            + 2 * E * V
        page_tokens += -(-int(ln) // int(page_size)) * int(page_size)
    param_bytes = (L * (4 * E * E + 2 * E * F) + 2 * V * E) * itemsize
    hbm = (param_bytes
           + 2 * L * page_tokens * kv_row_bytes     # live K+V pages read
           + 2 * L * n * kv_row_bytes               # new K+V written
           + n * V * itemsize)                      # logits out
    return {"model_flops": int(flops), "hbm_bytes": int(hbm),
            "arith_intensity": flops / hbm if hbm else 0.0,
            "live_slots": n, "live_page_tokens": int(page_tokens),
            "kv_codec": kv_codec,
            "kv_row_bytes": int(kv_row_bytes)}


def kv_offload_page_bytes(config, page_size: int) -> int:
    """Encoded bytes ONE KV page costs in the host offload tier — the
    closed form behind ``HostKVPool.page_nbytes`` and the d2h/h2d
    traffic the ``kv_offload_bytes`` counter charges per spilled page.

    Host records are always int8 rows regardless of the device pool
    dtype (f32 pools pay one deterministic row quantize on the way
    out), so the cost is the ps/codec blocked layout with block = one
    token row: K and V planes, ``n_layers`` each, ``page_size`` rows of
    ``n_heads * head_dim`` int8 payload plus one f32 scale per row."""
    from ..ps.codec import encoded_nbytes

    row = int(config.n_heads) * int(config.head_dim)
    return 2 * int(config.n_layers) * encoded_nbytes(
        int(page_size) * row, "int8", block=row)
