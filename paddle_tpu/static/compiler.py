"""CompiledProgram: multi-device data-parallel execution of a Program.

Reference: /root/reference/python/paddle/fluid/compiler.py:87
CompiledProgram / :160 with_data_parallel — builds a ParallelExecutor that
clones the SSA graph per GPU and inserts NCCL allreduce op-handles
(parallel_executor.cc).

TPU-native design: no graph cloning, no comm-op insertion. The executor
jit-compiles the SAME lowered step function with jax.sharding annotations:
feeds are sharded over the mesh's "data" axis, persistables replicated,
and XLA's SPMD partitioner inserts the gradient all-reduces over ICI
(exactly the role of the reference's AllReduceOpHandle, but compiled).
"""
from __future__ import annotations

from typing import Optional

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ..framework.bringup import safe_devices as _safe_devices
from .ir import Program


class BuildStrategy:
    """Knob parity (reference details/build_strategy.h), now WIRED: the
    graph-rewrite knobs select which IR passes (static/passes.py) run
    before the Executor traces the program —

      fuse_elewise_add_act_ops  elementwise+activation fusion onto the
                                fused_elemwise_activation kernel
      memory_optimize           dead-op elimination + unused-VarDesc drop
      enable_inplace            identity elision (assign / scale-by-1)
      constant_folding          all-constant subgraph folding (new)
      cse                       common-subexpression elimination (new)

    Mixed-precision knobs (the auto_mixed_precision pass,
    static/passes.py; `PADDLE_AMP=bf16|fp16|0` env overrides them all):

      amp                   run white/black-list bf16 (or fp16) rewrite
                            of the forward region; params stay f32
                            master weights, float32 feeds go low
                            host-side (h2d bytes halve)
      amp_dtype             "bfloat16" (TPU default; no loss scaling
                            needed) or "float16"
      amp_level             "O1" white-list only; "O2" lowers gray ops
                            too (black list always stays f32)
      amp_init_loss_scale   static loss scale threaded through
                            check_finite_and_unscale under fp16

    Memory / microbatching knobs (ISSUE 5 — rematerialization + in-step
    gradient merge; `PADDLE_IR_PASSES=0` disables both with the rest of
    the pipeline):

      recompute             run the recompute_segmentation pass: the
                            forward region is split into checkpoint
                            segments and the executor wraps each
                            segment's backward re-trace in
                            jax.checkpoint — activations are recomputed
                            instead of stashed (XLA temp bytes drop;
                            exe.memory_stats() shows the movement)
      recompute_checkpoints var names marking segment boundaries (the
                            reference RecomputeConfig.checkpoints); empty
                            = automatic ~sqrt(#ops) split
      recompute_segments    override the automatic segment count (0 =
                            sqrt heuristic)
      gradient_merge_k      k > 1 compiles the train step as a lax.scan
                            over k microbatches (feed batch must be
                            divisible by k): f32 gradient accumulators,
                            ONE optimizer update and ONE dispatch per k
                            microbatches; fp16 FoundInfinite from any
                            microbatch gates the merged update
      gradient_merge_avg    divide the MERGED gradient by k once
                            (single-large-batch semantics); False sums

    GSPMD sharding knobs (the shard_propagation pass, static/passes.py;
    `PADDLE_IR_PASSES=0` disables them with the rest of the pipeline):

      mesh_shape            {'dp': 2, 'tp': 2}-style axis sizes; non-empty
                            turns on the shard_propagation pass and the
                            executor compiles the step over a real
                            jax.sharding.Mesh of that shape (the pjit
                            in/out_shardings pattern). Axes named 'dp' /
                            'data' carry the feed batch dim.
      sharding_hints        {var_name: PartitionSpec-like tuple} seed
                            specs, e.g. {'fc_w_0': (None, 'tp')} for a
                            column-parallel weight or ('tp', None) for a
                            row-parallel one (the pass counts the psum on
                            the contracted dim). Specs propagate across
                            every VarDesc through op-level rules; feeds
                            default to batch-over-'dp'.
      pipeline_stages       S > 1 splits the forward region into S
                            contiguous stages and composes the
                            gradient-merge microbatch loop into a
                            pipeline schedule (requires
                            gradient_merge_k > 1 — the k microbatches
                            are the pipeline's microbatches)
      pipeline_schedule     "gpipe" (fill-drain, the default and the
                            escape leg) | "1f1b" (one-forward-one-
                            backward: bounded activation stash, the
                            warmup bubble amortised over the full
                            forward+backward steady state) |
                            "interleaved" (1F1B over
                            pipeline_interleave virtual chunks per
                            worker). `PADDLE_PP_SCHEDULE` overrides.
      pipeline_interleave   virtual stages per worker for
                            pipeline_schedule="interleaved"
                            (pipeline_stages must divide by it)
      zero_stage            0 | 2 | 3: ZeRO sharded optimizer states
                            over the dp axis, riding the quantized
                            comm layer (requires comm_quant engaged —
                            the grad reduce decomposes into the same
                            ring's reduce-scatter + all-gather).
                            Stage 2 shards optimizer states; stage 3
                            also shards the params between steps.
                            `PADDLE_ZERO=0` is the escape leg.

    Communication-efficiency knobs (the comm_bucketing concern in
    static/passes.py + parallel/collectives.py; pure data-parallel
    meshes only — `PADDLE_QUANT_ALLREDUCE=0` is the bitwise escape):

      comm_quant            "int8" | "bf16" | "off": quantize the DP
                            gradient all-reduce (EQuARX-style blocked
                            encodings, f32 accumulation at every reduce
                            hop). The executor compiles an explicit
                            bucketed ring all-reduce into the step;
                            ineligible configs fall back to the XLA f32
                            path with a dispatch-counter reason.
      comm_bucket_bytes     target f32 payload bytes per gradient
                            bucket; buckets are ordered by backward
                            completion so bucket k's all-reduce is
                            issued while bucket k+1's is still forming
                            (reduce/compute overlap).
      comm_error_feedback   carry each device's local quantization
                            residual in DONATED executor state and fold
                            it into the next step's contribution
                            (compressed-gradient error feedback).

    Comm-layout knobs (reduce_strategy, fuse_all_reduce_ops) stay
    descriptive: XLA's SPMD partitioner owns cross-chip scheduling."""

    def __init__(self):
        self.reduce_strategy = "AllReduce"
        self.fuse_all_reduce_ops = True
        self.fuse_elewise_add_act_ops = True
        self.memory_optimize = True
        self.enable_inplace = True
        self.constant_folding = True
        self.cse = True
        self.amp = False
        self.amp_dtype = "bfloat16"
        self.amp_level = "O1"
        self.amp_init_loss_scale = 2.0 ** 15
        self.recompute = False
        self.recompute_checkpoints = ()
        self.recompute_segments = 0
        self.gradient_merge_k = 1
        self.gradient_merge_avg = True
        self.mesh_shape = {}
        self.sharding_hints = {}
        self.pipeline_stages = 1
        self.pipeline_schedule = "gpipe"
        self.pipeline_interleave = 2
        self.zero_stage = 0
        self.comm_quant = "off"
        self.comm_bucket_bytes = 4 << 20
        self.comm_error_feedback = False
        self.num_trainers = 1
        self.trainer_id = 0


class ExecutionStrategy:
    def __init__(self):
        self.num_threads = 1
        self.num_iteration_per_drop_scope = 10


class CompiledProgram:
    def __init__(self, program_or_graph, build_strategy: Optional[
            BuildStrategy] = None):
        self._program = program_or_graph
        self._build_strategy = build_strategy or BuildStrategy()
        self._exec_strategy = ExecutionStrategy()
        self._data_parallel = False
        self._mesh: Optional[Mesh] = None
        self._loss_name = None
        self._sharding_cache = None
        self._stash_amp_feed_dtypes()

    def _stash_amp_feed_dtypes(self):
        """Publish the AMP host-cast map on the program NOW, not at the
        first run: py_reader prefetch threads started before Executor.run
        would otherwise stage their first `depth` batches f32 and force
        a second compile of the training step."""
        from .passes import amp_feed_dtypes_cached, resolve_amp

        prog = self._program
        if hasattr(prog, "global_block"):
            prog._amp_feed_dtypes = amp_feed_dtypes_cached(
                prog, resolve_amp(self._build_strategy))

    def with_data_parallel(self, loss_name=None, build_strategy=None,
                           exec_strategy=None, places=None):
        self._data_parallel = True
        self._sharding_cache = None
        self._loss_name = loss_name
        if build_strategy is not None:
            self._build_strategy = build_strategy
            self._stash_amp_feed_dtypes()
        if exec_strategy is not None:
            self._exec_strategy = exec_strategy
        from ..parallel.mesh import DATA_AXIS_NAMES, create_mesh, get_mesh
        self._mesh = get_mesh()
        if self._mesh is None or not any(
                a in self._mesh.axis_names for a in DATA_AXIS_NAMES):
            n = len(places) if places else len(_safe_devices())
            self._mesh = create_mesh({"data": n})
        return self

    def _data_sharding(self):
        """Sharding map consumed by Executor._build: feed names -> sharding
        (batch split over the mesh's data-like axes — mesh.data_sharding
        derives them from the axis names, so a 'dp' mesh works as well as
        the classic 'data' one), "__param__" -> replicated. Built once
        and cached — the executor applies it when state is first uploaded
        (and via in/out_shardings on the compiled step), so chained steps
        never re-partition resident state."""
        if not self._data_parallel or self._mesh is None:
            return None
        # keyed on the program version: data vars added after the first
        # run (another py_reader, a late feed) still get batch-split
        version = getattr(self._program, "_version", 0)
        if self._sharding_cache is None or \
                self._sharding_cache[0] != version:
            from ..parallel.mesh import data_sharding
            shard = data_sharding(self._mesh)
            rep = NamedSharding(self._mesh, PartitionSpec())
            feeds = {v.name: shard for v in self._program.list_vars()
                     if v.desc.is_data}
            feeds["__param__"] = rep
            self._sharding_cache = (version, feeds)
        return self._sharding_cache[1]
