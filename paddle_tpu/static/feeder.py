"""DataFeeder: minibatch samples -> executor feed dict.

Parity with /root/reference/python/paddle/fluid/data_feeder.py
(DataFeeder :229, feed :331): converts an iterable of per-sample tuples
into the arrays the executor feeds, keyed by the data vars' names.

TPU-native handling of ragged slots: a sample field that is a variable-
length sequence becomes padded dense + a `<name>_lens` entry (the
dense+lengths LoD rewrite used by ops/sequence.py and
Executor.train_from_dataset) instead of a LoDTensor.
"""
from __future__ import annotations

from typing import Any, Dict, List, Sequence

import numpy as np

from ..framework.lod import LoDTensor
from .ir import Variable


class DataFeeder:
    def __init__(self, feed_list: Sequence, place=None, program=None):
        self.feed_names: List[str] = []
        self.feed_dtypes: List[str] = []
        for v in feed_list:
            if isinstance(v, Variable):
                self.feed_names.append(v.name)
                self.feed_dtypes.append(getattr(v, "dtype", "float32"))
            else:
                self.feed_names.append(str(v))
                self.feed_dtypes.append("float32")
        self.place = place

    def feed(self, iterable) -> Dict[str, Any]:
        """iterable: list of samples, each a tuple aligned with feed_list."""
        columns: List[List[Any]] = [[] for _ in self.feed_names]
        for sample in iterable:
            if len(sample) != len(self.feed_names):
                raise ValueError(
                    f"sample has {len(sample)} fields, feed_list expects "
                    f"{len(self.feed_names)}")
            for col, field in zip(columns, sample):
                col.append(field)
        out: Dict[str, Any] = {}
        for name, dtype, col in zip(self.feed_names, self.feed_dtypes,
                                    columns):
            out.update(self._present(name, dtype, col))
        # Mixed precision needs no cast here: this dict flows into
        # Executor.run, whose amp feed path (passes.amp_feed_dtypes)
        # casts float32 slots host-side before the h2d copy — one owner
        # for the cast keeps strategy- and env-driven AMP consistent.
        return out

    @staticmethod
    def _pad_rows(name: str, dtype: str, rows: List[np.ndarray]):
        lengths = np.asarray([r.shape[0] for r in rows], np.int64)
        maxlen = int(lengths.max()) if len(rows) else 0
        tail = rows[0].shape[1:] if rows else ()
        padded = np.zeros((len(rows), maxlen) + tail, rows[0].dtype)
        for i, r in enumerate(rows):
            padded[i, :r.shape[0]] = r
        return {name: padded.astype(dtype, copy=False),
                f"{name}_lens": lengths}

    @classmethod
    def _present(cls, name: str, dtype: str, col: List[Any]
                 ) -> Dict[str, Any]:
        if col and isinstance(col[0], LoDTensor):
            return cls._pad_rows(name, dtype,
                                 [np.asarray(s.numpy()) for s in col])
        arrs = [np.asarray(c) for c in col]
        ragged = arrs and any(a.shape != arrs[0].shape for a in arrs)
        if ragged:
            return cls._pad_rows(name, dtype, arrs)
        arr = np.stack(arrs) if arrs else np.zeros(0)
        return {name: arr.astype(dtype, copy=False)}
