"""Static-graph Executor + Scope.

TPU-native counterpart of the reference serial Executor
(/root/reference/paddle/fluid/framework/executor.cc:180 Run, hot loop :476)
and the Python front (python/paddle/fluid/executor.py:470/:911).

Design: the reference interprets the block op-by-op with per-op kernel
launches and a Scope of mutable Variables. Here `Executor.run` LOWERS the
whole block to one pure jax function (feed arrays + persistable state in,
fetches + updated state out) and jit-compiles it — XLA fuses what the
reference's 89 IR passes fuse by hand, and a training step (forward +
backward + optimizer ops) becomes a single device program. The Scope is a
host-side dict of jax arrays (functional state), not a mutable var tree.

Startup programs run through the same lowering (initializer ops write
persistables). Before lowering, the block is rewritten by the IR pass
pipeline (passes.py — dead-op elim, constant folding, CSE, identity
elision, elementwise+act fusion, gated by BuildStrategy knobs).

Compiled executables are cached CONTENT-ADDRESSED: the key is a sha256
of (optimized program dict, feed signature, fetch list, state signature,
sharding, donation), held in a process-global table — so
Program.clone()/parse_from_string() copies, and a second Executor in the
same process, all hit the same entry (the reference's
ExecutorPrepareContext cache was per-executor and identity-keyed). A
per-program weak-keyed fast path avoids re-hashing on every step. With
PADDLE_COMPILE_CACHE[_DIR] set, compilation additionally goes through
jax's disk-persistent cache (compile_cache.py), so a relaunched trainer
skips the cold compile; the executor AOT-splits jit into lower()
(trace_ms) and compile() (compile_ms) so both phases are measurable.
"""
from __future__ import annotations

import hashlib
import json
import time
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..framework import dtype as dtype_mod
from ..framework import random as random_mod
from ..framework.place import CPUPlace
from ..observability.flight_recorder import flight_recorder
from ..observability.step_trace import active_step_trace
from .ir import Block, Program, Variable, grad_var_name
from .kernels import KERNELS, ExecContext

_PHASE_HIST = None


def _phase_hist():
    """The executor_step_phase_ms histogram (feed/dispatch/fetch labels)
    — engine-side latency truth for the training hot path, scraped at
    /metrics and percentile-derivable from its buckets."""
    global _PHASE_HIST
    if _PHASE_HIST is None:
        from ..observability.metrics import default_registry

        _PHASE_HIST = default_registry().histogram(
            "executor_step_phase_ms", labels=("phase",))
    return _PHASE_HIST


_DEVICE_KIND: Optional[str] = None


def _device_kind() -> str:
    """The local chip's PJRT device_kind, resolved once — keys the
    device_peaks lookup behind the live mfu/arith_intensity gauges."""
    global _DEVICE_KIND
    if _DEVICE_KIND is None:
        try:
            _DEVICE_KIND = jax.devices()[0].device_kind
        except Exception:
            _DEVICE_KIND = "unknown"
    return _DEVICE_KIND


class Scope:
    """name -> jax.Array store (reference framework/scope.cc, but flat &
    functional: executors read a snapshot and write back results).

    Arrays handed out through the public accessors are marked *exposed*:
    the caller may hold a reference, so a donating executor must not let
    XLA invalidate that buffer in place — it copies exposed entries
    before donation (the copy is what gets donated; the caller's alias
    stays readable). The executor's own reads/writes go through the
    underscore accessors, which don't mark — and a write-back clears the
    mark, because the freshly produced array has no external aliases."""

    def __init__(self):
        self._vars: Dict[str, Any] = {}
        self._exposed: set = set()

    def find_var(self, name):
        v = self._vars.get(name)
        if v is not None:
            self._exposed.add(name)
        return v

    def var(self, name):
        v = self._vars.setdefault(name, None)
        if v is not None:
            self._exposed.add(name)
        return v

    def set(self, name, value):
        # the caller necessarily holds a reference to what it just set
        self._vars[name] = value
        self._exposed.add(name)

    def keys(self):
        return self._vars.keys()

    def items(self):
        self._exposed.update(self._vars.keys())
        return self._vars.items()

    def drop(self, name):
        self._vars.pop(name, None)
        self._exposed.discard(name)

    # -- executor-internal access (no exposure bookkeeping) ---------------
    def _peek(self, name):
        return self._vars.get(name)

    def _write_back(self, name, value):
        self._vars[name] = value
        self._exposed.discard(name)


_global_scope = Scope()


def global_scope() -> Scope:
    return _global_scope


def scope_guard(scope):
    import contextlib

    @contextlib.contextmanager
    def guard():
        global _global_scope
        saved = _global_scope
        _global_scope = scope
        try:
            yield scope
        finally:
            _global_scope = saved

    return guard()


# ---------------------------------------------------------------------------
# lowering: Block -> pure function(env) -> env
# ---------------------------------------------------------------------------
def run_block(block: Block, env: Dict[str, Any], ctx: ExecContext,
              stop_at: Optional[int] = None,
              post_writes: Optional[Dict[int, Dict[str, Any]]] = None,
              start: int = 0) -> Dict[str, Any]:
    """Interpret ops of a block over an env dict. Called under jit trace —
    this IS the compilation step, not the runtime (no per-op dispatch cost
    after compile).

    post_writes: {op_index: {var_name: value}} — after op i runs, override
    env entries (used by backward.py to treat an intermediate var as a free
    input for gradient computation w.r.t. it).

    start/stop_at bound the op range [start, stop_at): backward.py runs
    checkpoint segments through here, and the gradient-merge step runs
    the post-backward (optimizer) region separately — op indices stay
    ABSOLUTE so ``__rng_slot`` fallbacks and post_writes keys are stable
    whatever the entry point."""
    from .backward import run_backward_op  # local: avoids import cycle

    if not hasattr(ctx, "initial_env"):
        ctx.initial_env = dict(env)
    stop = len(block.ops) if stop_at is None else stop_at
    for i in range(start, stop):
        op = block.ops[i]
        # __rng_slot (stamped by passes.py) pins index-keyed random ops
        # to their pre-rewrite RNG stream: op removal must not shift a
        # surviving dropout/uniform/gaussian draw
        ctx.op_index = op.attrs.get("__rng_slot", i)
        # control-flow kernels (cond/while) recurse into sub-blocks and
        # need the program + a snapshot of the enclosing env
        ctx.program = block.program
        ctx.env = env
        if op.type == "backward":
            run_backward_op(block, i, op, env, ctx)
            continue
        if op.type in ("feed", "fetch"):
            continue  # handled natively by the executor
        fn = KERNELS.get(op.type)
        if fn is None:
            raise NotImplementedError(
                f"no static kernel registered for op {op.type!r}")
        ins = {slot: [env[n] for n in names]
               for slot, names in op.inputs.items()
               if all(n in env for n in names)}
        outs = fn(ins, op.attrs, ctx)
        for slot, names in op.outputs.items():
            produced = outs.get(slot)
            if produced is None:
                continue
            for name, arr in zip(names, produced):
                env[name] = arr
        if post_writes and i in post_writes:
            env.update(post_writes[i])
    return env


def _feed_signature(feed: Dict[str, np.ndarray]):
    # weak_type matters: executables are AOT-compiled, and a weak-typed
    # jax array has a different input aval than the same shape/dtype
    # strong-typed one
    return tuple(sorted((k, tuple(v.shape), str(v.dtype),
                         bool(getattr(v, "weak_type", False)))
                        for k, v in feed.items()))


def _state_signature(state) -> tuple:
    # weak_type included for the same reason as in _feed_signature: the
    # executable is AOT-compiled, and a weak-typed scope entry (e.g. a
    # python-scalar-derived lr) has a different input aval
    return tuple((tuple(a.shape) if hasattr(a, "shape") else None,
                  str(getattr(a, "dtype", type(a).__name__)),
                  bool(getattr(a, "weak_type", False)))
                 for a in state)


def _strategy_signature(strategy) -> tuple:
    if strategy is None:
        return ()
    # scalar knobs plus scalar tuples/lists and shallow dicts — bools
    # select passes, strings/numbers carry the amp dtype/level/loss-scale
    # and the gradient_merge_k, tuples the recompute checkpoint names,
    # dicts the mesh_shape/sharding_hints (all shape which executable is
    # built)
    out = []
    for k, v in vars(strategy).items():
        if isinstance(v, (bool, int, float, str)):
            out.append((k, str(v)))
        elif isinstance(v, (tuple, list)) and all(
                isinstance(x, (bool, int, float, str)) for x in v):
            out.append((k, str(tuple(v))))
        elif isinstance(v, dict):
            out.append((k, repr(sorted(
                (str(kk), repr(vv)) for kk, vv in v.items()))))
    return tuple(sorted(out))


class _ExecEntry:
    """One content-cache slot: the AOT executable plus the optimized
    program and pass report that produced it (dump/debug surface).
    ``is_gm`` records whether the step really compiled as a
    scan-over-microbatches (a gradient_merge_k strategy on a
    backward-less program falls back to the plain step — its dispatches
    must not count as merged). ``cost`` caches the analytic
    cost_model.CostReport for the executable (one walk per entry, the
    warm path pays an attribute read; ``False`` = computation failed,
    don't retry)."""

    __slots__ = ("compiled", "optimized_program", "pass_report", "is_gm",
                 "cost", "comm_stats", "plan_gauges")

    def __init__(self, compiled, optimized_program, pass_report,
                 is_gm=False):
        self.compiled = compiled
        self.optimized_program = optimized_program
        self.pass_report = pass_report
        self.is_gm = is_gm
        self.cost = None
        # per-step quantized-collective accounting when the executable
        # compiled with the explicit bucketed all-reduce (see
        # _comm_entry_stats): wire bytes sent/saved per dispatch plus
        # the comm_buckets / allreduce_overlap_frac gauges
        self.comm_stats = None
        # plan-layer gauges (pp_stages, pp_bubble_frac, zero_*) recorded
        # at build time and REPLAYED on every cache hit — a warm
        # executor reports the executable's schedule, not the last
        # built one's
        self.plan_gauges = {}


# process-global content-addressed executable cache: every Executor in
# the process shares it, so identical programs (clones, deserialized
# copies, or a second Executor) never recompile. Bounded LRU — evicted
# entries release their executables.
_EXEC_CACHE: "OrderedDict[str, _ExecEntry]" = OrderedDict()
_EXEC_CACHE_MAX = 128


def _exec_cache_get(key: str) -> Optional[_ExecEntry]:
    entry = _EXEC_CACHE.get(key)
    if entry is not None:
        _EXEC_CACHE.move_to_end(key)
    return entry


def _exec_cache_put(key: str, entry: _ExecEntry) -> None:
    _EXEC_CACHE[key] = entry
    _EXEC_CACHE.move_to_end(key)
    while len(_EXEC_CACHE) > _EXEC_CACHE_MAX:
        _EXEC_CACHE.popitem(last=False)


def _escape_env_signature() -> tuple:
    """Kernel escape hatches read the environment at TRACE time (fused
    optimizer / explicit MoE exchange), so two content-identical
    programs traced under different toggles are different executables —
    the toggles must join both cache keys or a cached leg silently
    defangs the env pin (the bench's dual fused-vs-xla legs hit exactly
    this)."""
    import os

    return tuple((k, os.environ.get(k, "")) for k in
                 ("PADDLE_FUSED_OPT", "PADDLE_FUSED_OPT_INTERPRET",
                  "PADDLE_MOE_A2A"))


def _content_key(opt_program, feed_sig, fetch_names, persist_names,
                 state_sig, sharding, donate, gm=None, pp=None,
                 comm=None, schedule=None, zero=None,
                 interleave=None) -> str:
    # gm (gradient merge), pp (pipeline stage count), the pipeline
    # schedule and the zero stage change the compiled step's STRUCTURE
    # (scan / pipeline slot order / sharded-optimizer regions over
    # microbatches) without touching the program content, so they must
    # join the hash; remat and sharding change the content itself
    # (__remat_seg / __sharding_spec / __pp_stage stamps) and the
    # sharding map additionally lands here via shard_desc (mesh shape +
    # per-name NamedShardings)
    shard_desc = None
    if sharding:
        shard_desc = sorted((k, str(v)) for k, v in sharding.items())
    env_desc = list(_escape_env_signature())
    blob = json.dumps(
        [opt_program.to_dict(), list(feed_sig), list(fetch_names),
         list(persist_names), list(state_sig), shard_desc, bool(donate),
         list(gm) if gm else None, pp,
         list(comm) if comm else None, schedule, zero, interleave,
         env_desc],
        sort_keys=True, default=str).encode("utf-8")
    return hashlib.sha256(blob).hexdigest()


def _nbytes(arr) -> int:
    """Array payload bytes; 0 for extended dtypes (typed PRNG keys raise
    on .nbytes) and non-arrays."""
    try:
        return int(arr.nbytes)
    except Exception:
        return 0


# per-dispatch quantized-collective accounting — lives with the plan
# layer now (stepplan.comm_entry_stats); re-exported for callers that
# imported it from here
from .stepplan import comm_entry_stats as _comm_entry_stats  # noqa: E402
from .stepplan import zero_entry_stats as _zero_entry_stats  # noqa: E402


class Executor:
    """exe = Executor(place); exe.run(program, feed=..., fetch_list=...).

    The step loop is allocation- and transfer-minimal: persistable state
    lives on device across steps (uploaded + sharded once, never bounced
    through host numpy), and the state/rng arguments are DONATED to XLA
    so parameter/optimizer buffers are updated in place. Arrays a caller
    obtained through the Scope's public API are copied before donation
    (see Scope) so stale references stay readable. ``donate_state=False``
    opts out entirely."""

    def __init__(self, place=None, donate_state: bool = True):
        import weakref
        self.place = place if place is not None else CPUPlace()
        # fast path: program object -> {step key -> content hash}. The
        # executables themselves live in the process-global
        # content-addressed _EXEC_CACHE; this weak map only avoids
        # re-running passes + re-hashing on every step.
        self._cache = weakref.WeakKeyDictionary()
        self._step = 0
        from .compile_cache import ensure_enabled
        ensure_enabled()  # PADDLE_COMPILE_CACHE[_DIR] disk cache, once
        self._donate = bool(donate_state)
        # last executable this executor dispatched — memory_stats() and
        # the xla_*_bytes gauges read its compiled.memory_analysis()
        self._last_entry: Optional[_ExecEntry] = None
        # per-executor view of the hot-path counters; the module-global
        # aggregate lives in the profiler's metrics registry (bench and
        # the /metrics endpoint read that one)
        import collections
        self._counters = collections.Counter()
        # trainer scrape surface: PADDLE_METRICS_PORT starts the
        # process-wide /metrics server once (no-op when unset)
        from ..observability.server import maybe_start_metrics_server
        maybe_start_metrics_server()

    def _bump(self, name: str, n: int = 1):
        from .. import profiler

        self._counters[name] += n
        profiler.bump_counter(name, n)

    @property
    def counters(self) -> Dict[str, int]:
        """This executor's hot-path counters (cache hits/misses, h2d
        bytes, donated bytes, steps) — cumulative since construction —
        plus the process-global fault-tolerance counters (retry_*,
        ckpt_*, faults_injected, trainer_relaunches): a retry or a
        checkpoint fallback is a process event, not a per-executor one,
        but operators read both off the same dashboard."""
        from .. import profiler

        out = dict(self._counters)
        snap = profiler.counters_snapshot()
        for name in (profiler.FAULT_COUNTER_NAMES
                     + profiler.COMPILE_COUNTER_NAMES
                     + profiler.ELASTIC_COUNTER_NAMES
                     + profiler.PS_COUNTER_NAMES
                     + profiler.COMM_COUNTER_NAMES):
            if name in snap:
                out[name] = snap[name]
        return out

    @staticmethod
    def _memory_analysis_dict(entry) -> Dict[str, int]:
        """compiled.memory_analysis() flattened to plain ints, {} when
        the backend doesn't expose the analysis. peak_bytes is the
        arguments + outputs + XLA temp working set (the quantity remat
        shrinks); CPU/TPU PJRT report no finer peak."""
        if entry is None:
            return {}
        try:
            ma = entry.compiled.memory_analysis()
            temp = int(getattr(ma, "temp_size_in_bytes", 0))
            arg = int(getattr(ma, "argument_size_in_bytes", 0))
            out = int(getattr(ma, "output_size_in_bytes", 0))
            gen = int(getattr(ma, "generated_code_size_in_bytes", 0))
            alias = int(getattr(ma, "alias_size_in_bytes", 0))
        except Exception:
            return {}
        return {"temp_bytes": temp, "argument_bytes": arg,
                "output_bytes": out, "generated_code_bytes": gen,
                "alias_bytes": alias, "peak_bytes": temp + arg + out}

    def memory_stats(self) -> Dict[str, int]:
        """XLA memory analysis of the LAST executable this executor ran:
        peak_bytes / temp_bytes / argument_bytes / output_bytes /
        generated_code_bytes / alias_bytes. The objective gate for the
        recompute pass — bench's remat probe asserts temp/peak strictly
        drop with BuildStrategy.recompute on. {} before the first run."""
        return self._memory_analysis_dict(self._last_entry)

    def cost_stats(self, top: int = 10) -> Dict[str, Any]:
        """Analytic cost breakdown of the LAST executable this executor
        dispatched (static/cost_model.py over the optimized Program IR,
        with the gm/remat/shard step structure folded in): per-op and
        per-step model_flops / hbm_bytes / comm_bytes, flops/bytes by op
        type, top ops, plus the device peaks and the live derived
        gauges (mfu, arith_intensity) from the last measured step.
        {} before the first run or when the model could not cost the
        program."""
        entry = self._last_entry
        cost = getattr(entry, "cost", None) if entry is not None else None
        if not cost:
            return {}
        from ..observability.device_peaks import machine_balance, peaks_for

        out = cost.to_dict(top=top)
        kind = _device_kind()
        out["device_kind"] = kind
        peaks = peaks_for(kind)
        if peaks is not None:
            out["peak_flops"] = peaks.flops
            out["peak_hbm_bytes_per_s"] = peaks.hbm_bytes_per_s
            mb = machine_balance(kind)
            if mb:
                out["machine_balance"] = round(mb, 3)
        for g in ("step_model_flops", "step_hbm_bytes",
                  "step_comm_bytes", "mfu", "arith_intensity"):
            if g in self._counters:
                out[g] = self._counters[g]
        return out

    def _publish_cost_gauges(self, cost, phases) -> Dict[str, Any]:
        """Land one step's cost-model totals + derived utilization in
        the gauges: step_model_flops / step_hbm_bytes / step_comm_bytes
        from the report, mfu from the MEASURED dispatch+fetch seconds
        against the device peak (fetch is included because jax dispatch
        is async — the host-side conversion is where the device step is
        actually awaited), arith_intensity = flops per HBM byte."""
        from .. import profiler
        from ..observability.device_peaks import peaks_for

        vals: Dict[str, Any] = {
            "step_model_flops": cost.model_flops,
            "step_hbm_bytes": cost.hbm_bytes,
            "step_comm_bytes": cost.comm_bytes,
            "arith_intensity": round(cost.arith_intensity, 3),
        }
        step_s = (phases.get("dispatch", 0.0)
                  + phases.get("fetch", 0.0)) / 1e3
        peaks = peaks_for(_device_kind())
        if peaks is not None and peaks.flops > 0 and step_s > 0 \
                and cost.model_flops:
            # 6 decimals: a tiny probe's true MFU can sit at 1e-5 — a
            # 4-decimal gauge would floor it to an indistinguishable 0
            vals["mfu"] = round(
                cost.model_flops / step_s / peaks.flops, 6)
        else:
            # not computable for THIS step (matmul-free program, or no
            # known peak): overwrite, never leave a previous program's
            # mfu standing next to step_model_flops=0
            vals["mfu"] = 0
        for name, v in vals.items():
            self._counters[name] = v
            profiler.set_counter(name, v)
        return vals

    def _clear_cost_gauges(self) -> None:
        """Zero the cost gauges unconditionally (another executor may
        have set the process-global ones): 5 dict writes per uncosted
        step, negligible next to the dispatch."""
        from .. import profiler

        for name in ("step_model_flops", "step_hbm_bytes",
                     "step_comm_bytes", "mfu", "arith_intensity"):
            self._counters[name] = 0
            profiler.set_counter(name, 0)

    def _update_memory_gauges(self, entry) -> None:
        """Mirror the last executable's memory analysis into the
        counters as GAUGES (assigned, not accumulated): xla_temp_bytes /
        xla_peak_bytes / xla_argument_bytes / xla_output_bytes."""
        from .. import profiler

        stats = self._memory_analysis_dict(entry)
        for key in ("temp_bytes", "peak_bytes", "argument_bytes",
                    "output_bytes"):
            if key in stats:
                self._counters[f"xla_{key}"] = stats[key]
                profiler.set_counter(f"xla_{key}", stats[key])

    def close(self):
        self._cache.clear()

    # -- main entry -------------------------------------------------------
    def run(self, program: Optional[Program] = None,
            feed: Optional[Dict[str, Any]] = None,
            fetch_list: Optional[Sequence] = None,
            scope: Optional[Scope] = None,
            return_numpy: bool = True,
            use_program_cache: bool = True):
        """One step. The hot path is phase-instrumented: feed (host prep
        + h2d, includes rare builds), dispatch (compiled XLA step), and
        fetch (write-back + host conversion) land in the
        ``executor_step_phase_ms`` histogram; with a StepTrace active
        (``PADDLE_STEP_TRACE``) each step also emits a JSONL record
        stamped ``paddle_step_<id>`` for XPlane correlation, and every
        step rides the crash flight recorder's bounded ring."""
        trace = active_step_trace()
        tr_scope = trace.step("executor") if trace is not None else None
        obs: Dict[str, Any] = {"t0": time.perf_counter()}
        if tr_scope is not None:
            tr_scope.__enter__()
        try:
            return self._run_impl(program, feed, fetch_list, scope,
                                  return_numpy, use_program_cache, obs)
        finally:
            self._finish_step_obs(obs, tr_scope)

    def _finish_step_obs(self, obs, tr_scope) -> None:
        """Close one step's observability: histogram observes, flight
        ring append, step-trace record (exception-safe — runs in run()'s
        finally with the in-flight exception, if any, via exc_info)."""
        import sys as _sys

        t_end = time.perf_counter()
        t_feed, t_disp = obs.get("t_feed"), obs.get("t_dispatch")
        phases: Dict[str, float] = {}
        cost_vals: Dict[str, Any] = {}
        if t_disp is not None:
            phases["feed"] = (t_feed - obs["t0"]) * 1e3
            phases["dispatch"] = (t_disp - t_feed) * 1e3
            phases["fetch"] = (t_end - t_disp) * 1e3
            h = _phase_hist()
            for name, ms in phases.items():
                h.observe(ms, phase=name)
            cost = obs.get("cost")
            if cost is not None:
                cost_vals = self._publish_cost_gauges(cost, phases)
            else:
                # an uncostable program must not leave the previous
                # program's flops/mfu on the dashboard: the gauges
                # describe the LAST DISPATCHED step, so zero them
                self._clear_cost_gauges()
            flight_recorder().record_step({
                "exe_step": self._step,
                "cache_hit": obs.get("cache_hit", False),
                "h2d_bytes": obs.get("h2d_bytes", 0),
                "phases": {k: round(v, 3) for k, v in phases.items()}})
        if tr_scope is not None:
            tr_scope._phases.update(phases)
            if t_disp is not None:
                tr_scope.set("exe_step", self._step)
                tr_scope.set("cache_hit", obs.get("cache_hit", False))
                tr_scope.set("h2d_bytes", obs.get("h2d_bytes", 0))
                for name, v in cost_vals.items():
                    tr_scope.set(name, v)
            tr_scope.__exit__(*_sys.exc_info())
            if obs.get("cost") is not None:
                # per-executable breakdown record (kind="cost"): totals,
                # per-op top tables, device peaks — the top-K/roofline
                # source tools/perf_report.py reads next to the per-step
                # rows (emitted AFTER the step record so file order
                # stays a single monotone step-id sequence; de-duped per
                # trace so warm steps don't repeat it)
                self._emit_cost_record(tr_scope._trace, obs["cost"])

    def _emit_cost_record(self, trace, cost) -> None:
        from ..observability.device_peaks import peaks_for

        # per-trace dedup: one record per REPORT OBJECT, not per step —
        # keyed by identity with the object held strongly (an id() alone
        # could be reused after a cache-evicted report is GC'd, silently
        # skipping a new executable), LRU-bounded so alternating
        # programs (train+eval) emit once each, not once per step
        seen = getattr(trace, "_cost_seen", None)
        if seen is None:
            seen = trace._cost_seen = OrderedDict()
        if id(cost) in seen:
            seen.move_to_end(id(cost))
            return
        seen[id(cost)] = cost
        while len(seen) > 64:
            seen.popitem(last=False)
        try:
            rec = cost.to_dict(top=20)
            kind = _device_kind()
            rec["device_kind"] = kind
            peaks = peaks_for(kind)
            if peaks is not None:
                rec["peak_flops"] = peaks.flops
                rec["peak_hbm_bytes_per_s"] = peaks.hbm_bytes_per_s
            trace.record("cost", rec)
        except Exception:
            pass  # tracing must never take down the step

    def _run_impl(self, program, feed, fetch_list, scope, return_numpy,
                  use_program_cache, obs):
        from .ir import default_main_program
        from .compiler import CompiledProgram

        sharding = None
        strategy = None
        if isinstance(program, CompiledProgram):
            sharding = program._data_sharding()
            strategy = program._build_strategy
            program = program._program
        if program is None:
            program = default_main_program()
        if strategy is None:
            # fleet.distributed_optimizer's static path stamps the
            # program with the BuildStrategy its DistributedStrategy
            # maps to (recompute/gradient_merge/amp knobs) — honored for
            # raw-Program runs so fleet users need no CompiledProgram
            strategy = getattr(program, "_fleet_build_strategy", None)
        # let the program's py_readers stage batches directly into the
        # feed layout on their prefetch thread; set unconditionally so a
        # later raw-Program run clears a stale data-parallel stash
        program._feed_sharding = sharding
        scope = scope or global_scope()
        if not feed and not fetch_list:
            # startup-program shape: run initializers eagerly into the scope
            return self.run_startup(program, scope)
        feed = {k: np.asarray(v) if not isinstance(v, jax.Array) else v
                for k, v in (feed or {}).items()}
        # started py_readers feed their data vars (read_op parity —
        # static/py_reader.py; raises EOFException when exhausted)
        for _rdr in getattr(program, "_py_readers", []):
            if _rdr._started:
                for k, v in _rdr._next_feed().items():
                    feed.setdefault(k, v)
        fetch_names = [v.name if isinstance(v, Variable) else str(v)
                       for v in (fetch_list or [])]

        block = program.global_block
        # mixed precision (BuildStrategy.amp / PADDLE_AMP): float32 feeds
        # are cast HOST-side to the low dtype — half the h2d bytes — and
        # the amp config joins the step key so flipping the env (or the
        # strategy) can never hit a stale executable. Stash the feed
        # dtype map on the program (like _feed_sharding) so py_reader
        # prefetch threads stage batches already low.
        from .passes import (amp_feed_dtypes_cached, resolve_amp,
                             resolve_comm, resolve_gradient_merge,
                             resolve_pipeline, resolve_pipeline_schedule,
                             resolve_sharding, resolve_zero)

        amp = resolve_amp(strategy)
        gm = resolve_gradient_merge(strategy)
        shard_cfg = resolve_sharding(strategy)
        pp = resolve_pipeline(strategy)
        comm = resolve_comm(strategy)
        zero = resolve_zero(strategy)
        if gm is None:
            # mirrors apply_passes: pipeline_stages without
            # gradient_merge_k > 1 has no microbatches to schedule
            pp = None
        schedule = interleave = None
        if pp is not None:
            # the schedule only shapes a pipelined step; resolving it
            # to None otherwise keeps non-pp step keys unchanged
            schedule, interleave = resolve_pipeline_schedule(strategy)
        fdt = amp_feed_dtypes_cached(program, amp)
        program._amp_feed_dtypes = fdt

        def _amp_fix_feed(k, v):
            if not isinstance(v, jax.Array):
                if fdt and k in fdt and v.dtype == np.float32:
                    return v.astype(fdt[k])
                return v
            # device-staged feeds must match the dtype this run traces
            # with: the program-level stash is shared, so a prefetch
            # thread serving a DIFFERENT amp config (amp-on train +
            # amp-off eval over one Program) can stage the wrong dtype —
            # a cheap on-device cast beats a silent wrong-graph feed or
            # a recompile ping-pong
            if fdt and k in fdt and v.dtype == jnp.float32:
                return v.astype(jnp.dtype(fdt[k]))
            if not fdt:
                dv = block.vars.get(k)
                if dv is not None and dv.is_data \
                        and dv.dtype == "float32" \
                        and v.dtype in (jnp.bfloat16, jnp.float16):
                    return v.astype(jnp.float32)
            return v

        feed = {k: _amp_fix_feed(k, v) for k, v in feed.items()}
        peek = getattr(scope, "_peek", scope.find_var)
        persist_names = sorted(
            n for n, v in block.vars.items()
            if v.persistable and peek(n) is not None)
        if shard_cfg is not None:
            # GSPMD static sharding (BuildStrategy.mesh_shape +
            # sharding_hints): build the real mesh and the jit-boundary
            # sharding map — it REPLACES any CompiledProgram
            # data-parallel map (mesh_shape is the more general spelling
            # of the same thing) and rides program._feed_sharding so
            # prefetch threads stage batches already partitioned.
            # Memoized on the shapes that decide it (spec fitting checks
            # divisibility against live shapes) — the warm path pays one
            # key comparison, not a NamedSharding rebuild per step.
            shard_key = (
                program._version, shard_cfg, tuple(persist_names),
                tuple(sorted((k, tuple(getattr(v, "shape", ())))
                             for k, v in feed.items())))
            cached = getattr(self, "_shard_map_cache", None)
            if cached is not None and cached[0] == shard_key:
                sharding = cached[1]
            else:
                from ..parallel.mesh import mesh_for_shape
                from .passes import shard_boundary_shardings

                mesh = mesh_for_shape(dict(shard_cfg[0]))
                sharding = shard_boundary_shardings(
                    mesh, block, feed, persist_names, shard_cfg, peek)
                self._shard_map_cache = (shard_key, sharding)
            program._feed_sharding = sharding
        # quantized DP collectives (BuildStrategy.comm_quant /
        # PADDLE_QUANT_ALLREDUCE): resolve eligibility + the gradient
        # bucket plan up front — the error-feedback residuals ride the
        # DONATED state, so they must join persist_names before the
        # state gather, and the comm tuple joins the step/content keys
        # so a codec/bucket flip can never hit a stale executable
        comm_plan = None
        if comm is not None:
            comm_plan = self._comm_eligibility(
                program, block, comm, shard_cfg, gm, feed, sharding,
                pp=pp)
            if comm_plan is not None and comm[2]:
                sharding = dict(sharding) if sharding else {}
                persist_names = list(persist_names)
                persist_names += self._ensure_ef_state(
                    scope, comm_plan, shard_cfg, sharding)
                program._feed_sharding = sharding
        # ZeRO sharded optimizer states (BuildStrategy.zero_stage /
        # PADDLE_ZERO): rides the SAME engaged comm plan — the grad
        # all-reduce decomposes into reduce-scatter + all-gather and the
        # optimizer runs on local (g, c) state rows, which join the
        # donated state exactly like the error-feedback residuals
        zero_plan = None
        if zero is not None:
            zero_plan = self._zero_eligibility(
                program, block, zero, comm, comm_plan, shard_cfg, gm,
                pp, fetch_names)
            if zero_plan is not None:
                sharding = dict(sharding) if sharding else {}
                added, dropped = self._ensure_zero_state(
                    scope, zero_plan, shard_cfg, sharding)
                persist_names = [n for n in persist_names
                                 if n not in dropped] + added
                program._feed_sharding = sharding
        if zero_plan is None and peek("__zero_layout__") is not None:
            # ZeRO turned off (or went ineligible) between steps while
            # the scope still holds sharded rows: flip the per-var
            # state back before the replicated step gathers it
            from .stepplan import zero_flip_back

            restored = zero_flip_back(scope)
            have = set(persist_names)
            persist_names = list(persist_names) + sorted(
                n for n in set(restored) - have
                if n in block.vars and block.vars[n].persistable)
        feed_keys = sorted(feed.keys())
        feed_vals = [feed[k] for k in feed_keys]
        state = self._gather_state(scope, persist_names, feed_vals,
                                   sharding)
        seed = program.random_seed or random_mod.default_generator().initial_seed()
        rng = jax.random.fold_in(random_mod.make_key(seed), self._step)
        # shape/dtype only — never materialize device arrays for the key
        feed_sig = _feed_signature(feed)
        state_sig = _state_signature(state)
        step_key = (program._version, feed_sig, tuple(fetch_names),
                    tuple(persist_names), state_sig, bool(sharding),
                    _strategy_signature(strategy), amp, gm, shard_cfg,
                    pp, comm, comm_plan is not None, schedule,
                    interleave if schedule == "interleaved" else None,
                    zero, zero_plan is not None,
                    _escape_env_signature())
        per_prog = self._cache.setdefault(program, {})
        entry = None
        if use_program_cache:
            ck = per_prog.get(step_key)
            if ck is not None:
                entry = _exec_cache_get(ck)
                if entry is not None:
                    self._bump("compile_cache_hits")
                    obs["cache_hit"] = True
        if entry is None:
            # rewrite the block through the IR pass pipeline, then look
            # up / build the executable by CONTENT — a cloned or
            # deserialized copy of a compiled program lands on the same
            # sha, as does any other Executor in this process
            from .passes import apply_passes

            opt_program, report = apply_passes(
                program, feed_keys, fetch_names, strategy)
            self._record_pass_report(report)
            ck = _content_key(opt_program, feed_sig, fetch_names,
                              persist_names, state_sig, sharding,
                              self._donate, gm, pp, comm,
                              schedule=schedule, zero=zero,
                              interleave=interleave
                              if schedule == "interleaved" else None)
            per_prog[step_key] = ck
            entry = _exec_cache_get(ck) if use_program_cache else None
            if entry is not None:
                self._bump("compile_cache_hits")
                obs["cache_hit"] = True
            else:
                is_gm = gm is not None and any(
                    op.type == "backward"
                    for op in opt_program.global_block.ops)
                compiled_fn = self._build(
                    opt_program.global_block, feed_keys, fetch_names,
                    persist_names, sharding, feed_vals, state, rng, gm,
                    pp, comm=comm, comm_plan=comm_plan,
                    schedule=schedule, zero=zero, zero_plan=zero_plan,
                    interleave=interleave)
                entry = _ExecEntry(compiled_fn, opt_program, report,
                                   is_gm)
                entry.plan_gauges = dict(
                    getattr(self, "_last_plan_gauges", {}) or {})
                if comm_plan is not None and any(
                        op.type == "backward"
                        for op in opt_program.global_block.ops):
                    entry.comm_stats = (
                        _zero_entry_stats(comm_plan)
                        if zero_plan is not None
                        else _comm_entry_stats(comm_plan))
                if use_program_cache:
                    _exec_cache_put(ck, entry)
                self._bump("compile_cache_misses")
        compiled = entry.compiled
        if entry is not getattr(self, "_last_entry", None):
            self._last_entry = entry
            self._update_memory_gauges(entry)
            for name, v in entry.plan_gauges.items():
                self._set_plan_gauge(name, v)
        if entry.cost is None:
            # one analytic walk per executable (VarDesc arithmetic, no
            # tracing); False = model couldn't cost this program, never
            # retried on the hot path
            try:
                from .cost_model import program_cost

                entry.cost = program_cost(
                    entry.optimized_program,
                    feed_shapes={k: tuple(getattr(v, "shape", ()) or ())
                                 for k, v in feed.items()},
                    gm=gm if entry.is_gm else None,
                    shard_cfg=shard_cfg, pp=pp,
                    comm=comm if getattr(entry, "comm_stats", None)
                    else None,
                    schedule=schedule, interleave=interleave,
                    zero=zero if zero_plan is not None else None)
            except Exception:
                entry.cost = False
        if entry.cost:
            obs["cost"] = entry.cost

        self._step += 1
        self._bump("executor_steps")
        if gm and entry.is_gm:
            # one dispatch covers gm[0] microbatches (one optimizer
            # update): the tokens-per-dispatch win gradient merge buys
            self._bump("gm_dispatches")
            self._bump("gm_microbatches", gm[0])
        if getattr(entry, "comm_stats", None):
            # collective wire accounting, per dispatch: cumulative byte
            # counters plus point-in-time bucket/overlap gauges. ZeRO
            # dispatches ride their own counter pair — their wire is an
            # encoded half-ring reduce-scatter + raw-f32 all-gather, a
            # different profile than the quantized all-reduce ring the
            # comm_quant_* counters (and their saved>sent codec
            # invariant) account for
            from .. import profiler

            cs = entry.comm_stats
            if cs.get("zero"):
                self._bump("zero_wire_bytes_sent", cs["bytes_sent"])
                self._bump("zero_wire_bytes_saved", cs["bytes_saved"])
            else:
                self._bump("comm_quant_bytes_sent", cs["bytes_sent"])
                self._bump("comm_quant_bytes_saved", cs["bytes_saved"])
            for name in ("comm_buckets", "allreduce_overlap_frac"):
                self._counters[name] = cs[name]
                profiler.set_counter(name, cs[name])
        feed_h2d = sum(_nbytes(v) for v in feed_vals
                       if not isinstance(v, jax.Array))
        if feed_h2d:
            self._bump("h2d_bytes", feed_h2d)
        if self._donate:
            self._bump("donated_bytes",
                       sum(_nbytes(a) for a in state) + _nbytes(rng))
        obs["h2d_bytes"] = feed_h2d
        obs["t_feed"] = time.perf_counter()
        fetches, new_state = compiled(feed_vals, state, rng)
        obs["t_dispatch"] = time.perf_counter()
        write_back = getattr(scope, "_write_back", scope.set)
        for n, v in zip(persist_names, new_state):
            write_back(n, v)
        if return_numpy:
            return [np.asarray(f) for f in fetches]
        # a fetched persistable may share its buffer with the state just
        # written back (same traced value — XLA may alias the outputs);
        # mark it exposed so the next donating step copies first
        if self._donate and hasattr(scope, "_exposed"):
            persist_set = set(persist_names)
            scope._exposed.update(n for n in fetch_names
                                  if n in persist_set)
        return list(fetches)

    def _gather_state(self, scope, persist_names, feed_vals, sharding):
        """Read persistable state for one step, keeping it device-resident:
        host entries (numpy — e.g. fresh from static.io.load) are uploaded
        ONCE, already laid out with the program's parameter sharding, and
        written back so every later step passes resident jax.Arrays —
        zero per-step host->device traffic for state. Under donation,
        caller-visible aliases are copied so donation can't invalidate a
        buffer the caller still holds (or hand XLA one buffer twice)."""
        peek = getattr(scope, "_peek", scope.find_var)
        write_back = getattr(scope, "_write_back", scope.set)
        exposed = getattr(scope, "_exposed", set())
        param_shard = sharding.get("__param__") if sharding else None
        state = []
        # a feed array doubling as state must not be donated out from
        # under the feed argument
        seen = {id(v) for v in feed_vals if isinstance(v, jax.Array)}
        from ..parallel.sharding import device_put_counted

        for n in persist_names:
            arr = peek(n)
            if not isinstance(arr, jax.Array):
                host = np.asarray(arr)
                # device_put_counted bumps the global h2d_bytes; the
                # state-specific slice (and this executor's view) are
                # tracked here. A per-name entry (shard_propagation's
                # hinted params) beats the blanket __param__ fallback —
                # the upload lands already tp/dp-partitioned.
                arr = device_put_counted(
                    host, sharding.get(n, param_shard)
                    if sharding else None)
                self._counters["h2d_bytes"] += host.nbytes
                self._bump("state_h2d_bytes", host.nbytes)
                write_back(n, arr)
            elif sharding is not None:
                # a resident array laid out for a DIFFERENT config (the
                # user flipped sharding_hints/mesh_shape between runs on
                # one scope) must be re-placed or the AOT step rejects
                # the arg; a matching layout costs one equality check,
                # and a reshard is device-to-device (no h2d)
                target = sharding.get(n, param_shard)
                if target is not None and \
                        getattr(arr, "sharding", None) != target:
                    arr = jax.device_put(arr, target)
                    write_back(n, arr)
            if self._donate:
                aliased = id(arr) in seen
                seen.add(id(arr))
                if aliased or n in exposed:
                    arr = jnp.array(arr)   # the copy is what gets donated
                    self._bump("donation_fallback_copies")
            state.append(arr)
        return state

    def _record_pass_report(self, report) -> None:
        """Land the pipeline's per-pass op deltas + wall time in the
        profiler counters (and this executor's view): ir_ops_before/
        ir_ops_after, ir_pass_ms, ir_vars_dropped, pass_<name>_*."""
        self._bump("ir_ops_before", report.ops_before)
        self._bump("ir_ops_after", report.ops_after)
        self._bump("ir_pass_ms", round(report.ms, 3))
        if report.vars_dropped:
            self._bump("ir_vars_dropped", report.vars_dropped)
        for s in report.stats:
            if s.removed:
                self._bump(f"pass_{s.name}_removed_ops", s.removed)
            self._bump(f"pass_{s.name}_ms", round(s.ms, 3))
        for name, v in getattr(report, "amp", {}).items():
            self._bump(name, v)
        for name, v in getattr(report, "remat", {}).items():
            self._bump(name, v)
        for name, v in getattr(report, "shard", {}).items():
            if name == "pp_stages":   # point-in-time, not cumulative
                from .. import profiler

                self._counters[name] = v
                profiler.set_counter(name, v)
            else:
                self._bump(name, v)

    def _build(self, block, feed_keys, fetch_names, persist_names,
               sharding, feed_vals, state, rng, gm=None, pp=None,
               comm=None, comm_plan=None, schedule=None, zero=None,
               zero_plan=None, interleave=None):
        """AOT-compile one step: jit -> lower() (trace_ms) -> compile()
        (compile_ms). The split makes trace vs XLA-compile time
        measurable, and compile() goes through jax's persistent
        compilation cache when PADDLE_COMPILE_CACHE[_DIR] is set — a
        relaunched trainer's cold build becomes a disk read
        (disk_cache_hits in exe.counters).

        The step's SHAPE — plain forward, gm scan, pipeline schedule
        (gpipe/1f1b/interleaved), explicit quantized comm, or ZeRO
        sharded-optimizer — is the step-plan layer's job
        (static/stepplan.py): ``build_plan`` selects the registered
        plan kind and ``build_step_fn`` produces the traced callable.
        This method only wires the plan's boundary shardings + donation
        into substrate.aot_compile — the ONE compiled-step build path
        this executor shares with the decode engine (inference/decode)
        and, through Executor.run, the serving predictor."""
        from . import stepplan

        plan = stepplan.build_plan(
            block, gm=gm, pp=pp, comm=comm, comm_plan=comm_plan,
            schedule=schedule, zero=zero, zero_plan=zero_plan,
            sharding=sharding, donate=self._donate)
        if interleave is not None:
            plan.meta["interleave"] = interleave
        gauges = self._last_plan_gauges = {}

        def notify(name, value):
            gauges[name] = value   # replayed on cache hits (_ExecEntry)
            self._set_plan_gauge(name, value)

        step = stepplan.build_step_fn(
            plan, block, feed_keys, fetch_names, persist_names,
            feed_vals, notify=notify)
        in_shardings, out_shardings = plan.boundary_shardings(
            feed_keys, persist_names, fetch_names)
        from .substrate import aot_compile

        cs = aot_compile(
            step, (feed_vals, state, rng),
            donate_argnums=plan.donate_argnums,
            in_shardings=in_shardings, out_shardings=out_shardings,
            bump=self._bump)
        return cs.compiled

    def _set_plan_gauge(self, name, value):
        """Plan-layer gauge sink (pp_stages, pp_bubble_frac,
        pp_stash_depth, zero_*): point-in-time values set at step-plan
        build time — assigned, not accumulated."""
        from .. import profiler

        self._counters[name] = value
        profiler.set_counter(name, value)

    # -- quantized DP collectives (ISSUE 15: EQuARX-style comm layer) ------
    def _comm_eligibility(self, program, block, comm, shard_cfg, gm,
                          feed, sharding, pp=None):
        """Gate + plan for the explicit quantized-collective DP step —
        the logic lives in stepplan.comm_eligibility; this wrapper only
        keeps the per-executor memo (the warm step pays one key
        comparison, and counters bump once per verdict, not per step)."""
        from .stepplan import comm_eligibility

        self._comm_elig_cache = comm_eligibility(
            program, block, comm, shard_cfg, gm, feed, sharding, pp=pp,
            memo=getattr(self, "_comm_elig_cache", None))
        return self._comm_elig_cache[1]

    def _ensure_ef_state(self, scope, comm_plan, shard_cfg, sharding):
        from .stepplan import ensure_ef_state

        return ensure_ef_state(scope, comm_plan, shard_cfg, sharding)

    def _zero_eligibility(self, program, block, zero, comm, comm_plan,
                          shard_cfg, gm, pp, fetch_names):
        """Gate + layout plan for ZeRO sharded optimizer states — the
        logic lives in stepplan.zero_eligibility; the wrapper keeps the
        per-executor memo so counters bump once per verdict."""
        from .stepplan import zero_eligibility

        self._zero_elig_cache = zero_eligibility(
            program, block, zero, comm, comm_plan, shard_cfg, gm, pp,
            fetch_names, memo=getattr(self, "_zero_elig_cache", None))
        return self._zero_elig_cache[1]

    def _ensure_zero_state(self, scope, zero_plan, shard_cfg, sharding):
        from .stepplan import ensure_zero_state

        return ensure_zero_state(scope, zero_plan, shard_cfg, sharding)

    # -- dataset-driven training (reference executor.py:1593) -------------
    def train_from_dataset(self, program=None, dataset=None, scope=None,
                           thread=0, debug=False, fetch_list=None,
                           fetch_info=None, print_period=100):
        """Train over an entire Dataset (reference Executor.train_from_dataset
        executor.py:1593 → C++ MultiTrainer/HogwildWorker TrainFiles,
        hogwild_worker.cc:191).

        TPU-native shape: the reference spawns one op-loop thread per core
        because each CPU thread is a compute unit; on TPU the chip runs one
        XLA program at a time, so `thread` buys input overlap instead —
        batches are parsed/padded on host threads and prefetched into a
        bounded queue while the device executes the previous step. Sparse
        slots arrive as (values, lod) pairs and are padded to power-of-two
        buckets (static shapes — each bucket compiles once); a program var
        named `<slot>_lens` receives the true lengths (the dense+lengths
        LoD rewrite used across ops/sequence.py).
        """
        import queue as queue_mod
        import threading

        from .compiler import CompiledProgram
        from .ir import default_main_program

        if dataset is None:
            raise ValueError("train_from_dataset requires a dataset")
        run_target = program if program is not None else \
            default_main_program()
        # a CompiledProgram trains data-parallel: steps run through
        # self.run (which applies its sharding to the compiled step) and
        # the prefetcher stages each batch DIRECTLY into the feed's
        # sharded layout — no per-step re-partition
        sharding = None
        strategy = None
        program = run_target
        if isinstance(program, CompiledProgram):
            sharding = program._data_sharding()
            strategy = program._build_strategy
            program = program._program
        scope = scope or global_scope()
        block = program.global_block
        fetch_list = fetch_list or []
        fetch_info = fetch_info or [
            getattr(v, "name", str(v)) for v in fetch_list]

        q: queue_mod.Queue = queue_mod.Queue(maxsize=max(2, int(thread) * 2))
        _END = object()
        producer_error = []

        # multi-worker ingestion: `thread` producers over per-file dataset
        # shards (reference thread-per-DeviceWorker DataFeed channels);
        # batch->feed padding runs in the producer threads so the device
        # never waits on host-side parse/pad
        shards = (dataset.ingest_shards(int(thread))
                  if hasattr(dataset, "ingest_shards") and int(thread) > 1
                  else [dataset])

        def producer(shard):
            try:
                for batch in shard:
                    q.put(self._dataset_batch_to_feed(batch, block))
            except BaseException as e:  # surfaced in the consumer
                producer_error.append(e)
            finally:
                q.put(_END)

        producers = [threading.Thread(target=producer, args=(s,),
                                      daemon=True)
                     for s in shards]
        for t in producers:
            t.start()

        from .prefetch import FeedPrefetcher

        def host_feeds():
            ended = 0
            while ended < len(producers):
                item = q.get()
                if item is _END:
                    ended += 1
                elif item:          # skip empty feed dicts
                    yield item

        # second pipeline stage: while the device executes step N, the
        # prefetch thread device_puts batch N+1 (the producers above
        # keep parsing/padding N+2...). Depth scales with ingestion
        # parallelism but stays bounded — each slot pins device memory.
        # Under AMP, float32 feeds are cast low on the prefetch thread
        # BEFORE the h2d copy (half the transfer, amp_feed_dtypes).
        from .passes import (amp_feed_dtypes, resolve_amp,
                             resolve_sharding, shard_boundary_shardings)

        feed_dtypes = amp_feed_dtypes(block, resolve_amp(strategy))
        shard_cfg = resolve_sharding(strategy)
        if shard_cfg is not None:
            # BuildStrategy.mesh_shape (GSPMD) beats the classic
            # CompiledProgram data-parallel map, exactly as in _run_impl:
            # batches must stage into the SAME layout the AOT step's
            # in_shardings expect, or the dispatch rejects the committed
            # arrays. Derived per batch (stage_feed runs on the prefetch
            # thread) because divisibility is checked against the live
            # batch shapes.
            from ..parallel.mesh import mesh_for_shape
            from .prefetch import stage_feed

            shard_mesh = mesh_for_shape(dict(shard_cfg[0]))

            def _stage(item):
                m = shard_boundary_shardings(shard_mesh, block, item, (),
                                             shard_cfg)
                return stage_feed(item, m, feed_dtypes)

            prefetcher = FeedPrefetcher(host_feeds(),
                                        depth=max(2, int(thread)),
                                        stage=_stage)
        else:
            prefetcher = FeedPrefetcher(host_feeds(),
                                        depth=max(2, int(thread)),
                                        sharding=sharding,
                                        feed_dtypes=feed_dtypes)
        step = 0
        last_fetch = None
        try:
            # one-batch lookahead so the final step is known (it always
            # fetches, like the reference's end-of-epoch metric read)
            pending = next(prefetcher, None)
            while pending is not None:
                feed = pending
                pending = next(prefetcher, None)
                final_step = pending is None
                want_fetch = fetch_list and (
                    debug or final_step or step % print_period == 0)
                out = self.run(run_target, feed=feed,
                               fetch_list=fetch_list if want_fetch else None,
                               scope=scope)
                if want_fetch:
                    last_fetch = out
                    if debug:
                        msg = ", ".join(f"{n}={np.asarray(v).ravel()[:4]}"
                                        for n, v in zip(fetch_info, out))
                        print(f"[train_from_dataset] step {step}: {msg}")
                step += 1
        finally:
            # teardown order matters: signal the prefetch thread FIRST
            # (no join yet — it may be blocked on q.get while producers
            # are still filling q), then unblock/join the producers, then
            # re-seed the _END sentinels the drain may have eaten so
            # host_feeds() always reaches its exit count, and only then
            # join the prefetch thread.
            prefetcher.stop()
            while any(t.is_alive() for t in producers):
                try:
                    q.get(timeout=0.1)
                except queue_mod.Empty:
                    pass
            for t in producers:
                t.join()
            for _ in producers:
                try:
                    q.put_nowait(_END)
                except queue_mod.Full:
                    # q full ⇒ the worker is past q.get (it consumed a
                    # batch) and will see the stop flag, not block again
                    break
            prefetcher.close()
        if producer_error:
            raise producer_error[0]
        return last_fetch

    def infer_from_dataset(self, program=None, dataset=None, scope=None,
                           thread=0, debug=False, fetch_list=None,
                           fetch_info=None, print_period=100):
        """Same loop as train_from_dataset over an inference program
        (reference executor.py:1491)."""
        return self.train_from_dataset(program, dataset, scope, thread,
                                       debug, fetch_list, fetch_info,
                                       print_period)

    @staticmethod
    def _dataset_batch_to_feed(batch, block):
        """Map a Dataset batch (slot -> dense array | (values, lod)) onto
        the program's data vars, padding ragged slots to pow-2 buckets."""
        feed = {}
        for name, val in batch.items():
            if isinstance(val, tuple):
                vals, lod = val
                rows = len(lod) - 1
                lens = np.diff(lod).astype(np.int64)
                longest = int(lens.max()) if rows else 1
                maxlen = 1 << max(0, int(longest - 1).bit_length())
                if np.issubdtype(vals.dtype, np.unsignedinteger):
                    vals = vals.astype(np.int64)
                dense = np.zeros((rows, maxlen), vals.dtype)
                for i in range(rows):
                    dense[i, :lens[i]] = vals[lod[i]:lod[i + 1]]
                if name in block.vars:
                    feed[name] = dense
                if f"{name}_lens" in block.vars:
                    feed[f"{name}_lens"] = lens
            elif name in block.vars:
                if np.issubdtype(getattr(val, "dtype", np.float32),
                                 np.unsignedinteger):
                    val = val.astype(np.int64)
                feed[name] = val
        return feed

    # -- startup-program path --------------------------------------------
    def run_startup(self, program: Program, scope: Optional[Scope] = None):
        """Run initializer ops eagerly, writing persistables to scope.
        (Executor.run on a startup program delegates here.)"""
        scope = scope or global_scope()
        seed = program.random_seed or random_mod.default_generator().initial_seed()
        ctx = ExecContext(rng_key=random_mod.make_key(seed))
        peek = getattr(scope, "_peek", scope.find_var)
        write_back = getattr(scope, "_write_back", scope.set)
        env = {n: peek(n) for n in program.global_block.vars
               if peek(n) is not None}
        env = run_block(program.global_block, env, ctx)
        for name, desc in program.global_block.vars.items():
            if desc.persistable and name in env and env[name] is not None:
                write_back(name, env[name])
        return []
