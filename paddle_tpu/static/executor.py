"""Static-graph Executor + Scope.

TPU-native counterpart of the reference serial Executor
(/root/reference/paddle/fluid/framework/executor.cc:180 Run, hot loop :476)
and the Python front (python/paddle/fluid/executor.py:470/:911).

Design: the reference interprets the block op-by-op with per-op kernel
launches and a Scope of mutable Variables. Here `Executor.run` LOWERS the
whole block to one pure jax function (feed arrays + persistable state in,
fetches + updated state out) and jit-compiles it — XLA fuses what the
reference's 89 IR passes fuse by hand, and a training step (forward +
backward + optimizer ops) becomes a single device program. The Scope is a
host-side dict of jax arrays (functional state), not a mutable var tree.

Startup programs run through the same lowering (initializer ops write
persistables). Before lowering, the block is rewritten by the IR pass
pipeline (passes.py — dead-op elim, constant folding, CSE, identity
elision, elementwise+act fusion, gated by BuildStrategy knobs).

Compiled executables are cached CONTENT-ADDRESSED: the key is a sha256
of (optimized program dict, feed signature, fetch list, state signature,
sharding, donation), held in a process-global table — so
Program.clone()/parse_from_string() copies, and a second Executor in the
same process, all hit the same entry (the reference's
ExecutorPrepareContext cache was per-executor and identity-keyed). A
per-program weak-keyed fast path avoids re-hashing on every step. With
PADDLE_COMPILE_CACHE[_DIR] set, compilation additionally goes through
jax's disk-persistent cache (compile_cache.py), so a relaunched trainer
skips the cold compile; the executor AOT-splits jit into lower()
(trace_ms) and compile() (compile_ms) so both phases are measurable.
"""
from __future__ import annotations

import hashlib
import json
import time
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..framework import dtype as dtype_mod
from ..framework import random as random_mod
from ..framework.place import CPUPlace
from ..observability.flight_recorder import flight_recorder
from ..observability.step_trace import active_step_trace
from .ir import Block, Program, Variable, grad_var_name
from .kernels import KERNELS, ExecContext

_PHASE_HIST = None


def _phase_hist():
    """The executor_step_phase_ms histogram (feed/dispatch/fetch labels)
    — engine-side latency truth for the training hot path, scraped at
    /metrics and percentile-derivable from its buckets."""
    global _PHASE_HIST
    if _PHASE_HIST is None:
        from ..observability.metrics import default_registry

        _PHASE_HIST = default_registry().histogram(
            "executor_step_phase_ms", labels=("phase",))
    return _PHASE_HIST


_DEVICE_KIND: Optional[str] = None


def _device_kind() -> str:
    """The local chip's PJRT device_kind, resolved once — keys the
    device_peaks lookup behind the live mfu/arith_intensity gauges."""
    global _DEVICE_KIND
    if _DEVICE_KIND is None:
        try:
            _DEVICE_KIND = jax.devices()[0].device_kind
        except Exception:
            _DEVICE_KIND = "unknown"
    return _DEVICE_KIND


class Scope:
    """name -> jax.Array store (reference framework/scope.cc, but flat &
    functional: executors read a snapshot and write back results).

    Arrays handed out through the public accessors are marked *exposed*:
    the caller may hold a reference, so a donating executor must not let
    XLA invalidate that buffer in place — it copies exposed entries
    before donation (the copy is what gets donated; the caller's alias
    stays readable). The executor's own reads/writes go through the
    underscore accessors, which don't mark — and a write-back clears the
    mark, because the freshly produced array has no external aliases."""

    def __init__(self):
        self._vars: Dict[str, Any] = {}
        self._exposed: set = set()

    def find_var(self, name):
        v = self._vars.get(name)
        if v is not None:
            self._exposed.add(name)
        return v

    def var(self, name):
        v = self._vars.setdefault(name, None)
        if v is not None:
            self._exposed.add(name)
        return v

    def set(self, name, value):
        # the caller necessarily holds a reference to what it just set
        self._vars[name] = value
        self._exposed.add(name)

    def keys(self):
        return self._vars.keys()

    def items(self):
        self._exposed.update(self._vars.keys())
        return self._vars.items()

    def drop(self, name):
        self._vars.pop(name, None)
        self._exposed.discard(name)

    # -- executor-internal access (no exposure bookkeeping) ---------------
    def _peek(self, name):
        return self._vars.get(name)

    def _write_back(self, name, value):
        self._vars[name] = value
        self._exposed.discard(name)


_global_scope = Scope()


def global_scope() -> Scope:
    return _global_scope


def scope_guard(scope):
    import contextlib

    @contextlib.contextmanager
    def guard():
        global _global_scope
        saved = _global_scope
        _global_scope = scope
        try:
            yield scope
        finally:
            _global_scope = saved

    return guard()


# ---------------------------------------------------------------------------
# lowering: Block -> pure function(env) -> env
# ---------------------------------------------------------------------------
def run_block(block: Block, env: Dict[str, Any], ctx: ExecContext,
              stop_at: Optional[int] = None,
              post_writes: Optional[Dict[int, Dict[str, Any]]] = None,
              start: int = 0) -> Dict[str, Any]:
    """Interpret ops of a block over an env dict. Called under jit trace —
    this IS the compilation step, not the runtime (no per-op dispatch cost
    after compile).

    post_writes: {op_index: {var_name: value}} — after op i runs, override
    env entries (used by backward.py to treat an intermediate var as a free
    input for gradient computation w.r.t. it).

    start/stop_at bound the op range [start, stop_at): backward.py runs
    checkpoint segments through here, and the gradient-merge step runs
    the post-backward (optimizer) region separately — op indices stay
    ABSOLUTE so ``__rng_slot`` fallbacks and post_writes keys are stable
    whatever the entry point."""
    from .backward import run_backward_op  # local: avoids import cycle

    if not hasattr(ctx, "initial_env"):
        ctx.initial_env = dict(env)
    stop = len(block.ops) if stop_at is None else stop_at
    for i in range(start, stop):
        op = block.ops[i]
        # __rng_slot (stamped by passes.py) pins index-keyed random ops
        # to their pre-rewrite RNG stream: op removal must not shift a
        # surviving dropout/uniform/gaussian draw
        ctx.op_index = op.attrs.get("__rng_slot", i)
        # control-flow kernels (cond/while) recurse into sub-blocks and
        # need the program + a snapshot of the enclosing env
        ctx.program = block.program
        ctx.env = env
        if op.type == "backward":
            run_backward_op(block, i, op, env, ctx)
            continue
        if op.type in ("feed", "fetch"):
            continue  # handled natively by the executor
        fn = KERNELS.get(op.type)
        if fn is None:
            raise NotImplementedError(
                f"no static kernel registered for op {op.type!r}")
        ins = {slot: [env[n] for n in names]
               for slot, names in op.inputs.items()
               if all(n in env for n in names)}
        outs = fn(ins, op.attrs, ctx)
        for slot, names in op.outputs.items():
            produced = outs.get(slot)
            if produced is None:
                continue
            for name, arr in zip(names, produced):
                env[name] = arr
        if post_writes and i in post_writes:
            env.update(post_writes[i])
    return env


def _feed_signature(feed: Dict[str, np.ndarray]):
    # weak_type matters: executables are AOT-compiled, and a weak-typed
    # jax array has a different input aval than the same shape/dtype
    # strong-typed one
    return tuple(sorted((k, tuple(v.shape), str(v.dtype),
                         bool(getattr(v, "weak_type", False)))
                        for k, v in feed.items()))


def _state_signature(state) -> tuple:
    # weak_type included for the same reason as in _feed_signature: the
    # executable is AOT-compiled, and a weak-typed scope entry (e.g. a
    # python-scalar-derived lr) has a different input aval
    return tuple((tuple(a.shape) if hasattr(a, "shape") else None,
                  str(getattr(a, "dtype", type(a).__name__)),
                  bool(getattr(a, "weak_type", False)))
                 for a in state)


def _strategy_signature(strategy) -> tuple:
    if strategy is None:
        return ()
    # scalar knobs plus scalar tuples/lists and shallow dicts — bools
    # select passes, strings/numbers carry the amp dtype/level/loss-scale
    # and the gradient_merge_k, tuples the recompute checkpoint names,
    # dicts the mesh_shape/sharding_hints (all shape which executable is
    # built)
    out = []
    for k, v in vars(strategy).items():
        if isinstance(v, (bool, int, float, str)):
            out.append((k, str(v)))
        elif isinstance(v, (tuple, list)) and all(
                isinstance(x, (bool, int, float, str)) for x in v):
            out.append((k, str(tuple(v))))
        elif isinstance(v, dict):
            out.append((k, repr(sorted(
                (str(kk), repr(vv)) for kk, vv in v.items()))))
    return tuple(sorted(out))


class _ExecEntry:
    """One content-cache slot: the AOT executable plus the optimized
    program and pass report that produced it (dump/debug surface).
    ``is_gm`` records whether the step really compiled as a
    scan-over-microbatches (a gradient_merge_k strategy on a
    backward-less program falls back to the plain step — its dispatches
    must not count as merged). ``cost`` caches the analytic
    cost_model.CostReport for the executable (one walk per entry, the
    warm path pays an attribute read; ``False`` = computation failed,
    don't retry)."""

    __slots__ = ("compiled", "optimized_program", "pass_report", "is_gm",
                 "cost", "comm_stats")

    def __init__(self, compiled, optimized_program, pass_report,
                 is_gm=False):
        self.compiled = compiled
        self.optimized_program = optimized_program
        self.pass_report = pass_report
        self.is_gm = is_gm
        self.cost = None
        # per-step quantized-collective accounting when the executable
        # compiled with the explicit bucketed all-reduce (see
        # _comm_entry_stats): wire bytes sent/saved per dispatch plus
        # the comm_buckets / allreduce_overlap_frac gauges
        self.comm_stats = None


# process-global content-addressed executable cache: every Executor in
# the process shares it, so identical programs (clones, deserialized
# copies, or a second Executor) never recompile. Bounded LRU — evicted
# entries release their executables.
_EXEC_CACHE: "OrderedDict[str, _ExecEntry]" = OrderedDict()
_EXEC_CACHE_MAX = 128


def _exec_cache_get(key: str) -> Optional[_ExecEntry]:
    entry = _EXEC_CACHE.get(key)
    if entry is not None:
        _EXEC_CACHE.move_to_end(key)
    return entry


def _exec_cache_put(key: str, entry: _ExecEntry) -> None:
    _EXEC_CACHE[key] = entry
    _EXEC_CACHE.move_to_end(key)
    while len(_EXEC_CACHE) > _EXEC_CACHE_MAX:
        _EXEC_CACHE.popitem(last=False)


def _content_key(opt_program, feed_sig, fetch_names, persist_names,
                 state_sig, sharding, donate, gm=None, pp=None,
                 comm=None) -> str:
    # gm (gradient merge) and pp (pipeline stage count) change the
    # compiled step's STRUCTURE (scan / GPipe schedule over
    # microbatches) without touching the program content, so they must
    # join the hash; remat and sharding change the content itself
    # (__remat_seg / __sharding_spec / __pp_stage stamps) and the
    # sharding map additionally lands here via shard_desc (mesh shape +
    # per-name NamedShardings)
    shard_desc = None
    if sharding:
        shard_desc = sorted((k, str(v)) for k, v in sharding.items())
    blob = json.dumps(
        [opt_program.to_dict(), list(feed_sig), list(fetch_names),
         list(persist_names), list(state_sig), shard_desc, bool(donate),
         list(gm) if gm else None, pp,
         list(comm) if comm else None],
        sort_keys=True, default=str).encode("utf-8")
    return hashlib.sha256(blob).hexdigest()


def _nbytes(arr) -> int:
    """Array payload bytes; 0 for extended dtypes (typed PRNG keys raise
    on .nbytes) and non-arrays."""
    try:
        return int(arr.nbytes)
    except Exception:
        return 0


def _comm_entry_stats(comm_plan) -> Dict[str, Any]:
    """Per-dispatch quantized-collective accounting for one compiled
    executable: encoded ring bytes actually moved per device per step
    (``bytes_sent``), the f32 bytes the codec saved (``bytes_saved``),
    the bucket count, and the analytic overlap fraction — with nb
    buckets emitted in completion order, nb-1 of them have a later
    bucket's work in flight behind them (the last one drains alone),
    the same analytic convention as pp_bubble_frac."""
    _axis, _g, plan = comm_plan
    sent = sum(b["ring_encoded"] for b in plan)
    f32 = sum(b["ring_f32"] for b in plan)
    nb = len(plan)
    return {
        "bytes_sent": int(sent),
        "bytes_saved": int(max(0, f32 - sent)),
        "comm_buckets": nb,
        "allreduce_overlap_frac": round((nb - 1) / nb, 4) if nb else 0.0,
    }


class Executor:
    """exe = Executor(place); exe.run(program, feed=..., fetch_list=...).

    The step loop is allocation- and transfer-minimal: persistable state
    lives on device across steps (uploaded + sharded once, never bounced
    through host numpy), and the state/rng arguments are DONATED to XLA
    so parameter/optimizer buffers are updated in place. Arrays a caller
    obtained through the Scope's public API are copied before donation
    (see Scope) so stale references stay readable. ``donate_state=False``
    opts out entirely."""

    def __init__(self, place=None, donate_state: bool = True):
        import weakref
        self.place = place if place is not None else CPUPlace()
        # fast path: program object -> {step key -> content hash}. The
        # executables themselves live in the process-global
        # content-addressed _EXEC_CACHE; this weak map only avoids
        # re-running passes + re-hashing on every step.
        self._cache = weakref.WeakKeyDictionary()
        self._step = 0
        from .compile_cache import ensure_enabled
        ensure_enabled()  # PADDLE_COMPILE_CACHE[_DIR] disk cache, once
        self._donate = bool(donate_state)
        # last executable this executor dispatched — memory_stats() and
        # the xla_*_bytes gauges read its compiled.memory_analysis()
        self._last_entry: Optional[_ExecEntry] = None
        # per-executor view of the hot-path counters; the module-global
        # aggregate lives in the profiler's metrics registry (bench and
        # the /metrics endpoint read that one)
        import collections
        self._counters = collections.Counter()
        # trainer scrape surface: PADDLE_METRICS_PORT starts the
        # process-wide /metrics server once (no-op when unset)
        from ..observability.server import maybe_start_metrics_server
        maybe_start_metrics_server()

    def _bump(self, name: str, n: int = 1):
        from .. import profiler

        self._counters[name] += n
        profiler.bump_counter(name, n)

    @property
    def counters(self) -> Dict[str, int]:
        """This executor's hot-path counters (cache hits/misses, h2d
        bytes, donated bytes, steps) — cumulative since construction —
        plus the process-global fault-tolerance counters (retry_*,
        ckpt_*, faults_injected, trainer_relaunches): a retry or a
        checkpoint fallback is a process event, not a per-executor one,
        but operators read both off the same dashboard."""
        from .. import profiler

        out = dict(self._counters)
        snap = profiler.counters_snapshot()
        for name in (profiler.FAULT_COUNTER_NAMES
                     + profiler.COMPILE_COUNTER_NAMES
                     + profiler.ELASTIC_COUNTER_NAMES
                     + profiler.PS_COUNTER_NAMES
                     + profiler.COMM_COUNTER_NAMES):
            if name in snap:
                out[name] = snap[name]
        return out

    @staticmethod
    def _memory_analysis_dict(entry) -> Dict[str, int]:
        """compiled.memory_analysis() flattened to plain ints, {} when
        the backend doesn't expose the analysis. peak_bytes is the
        arguments + outputs + XLA temp working set (the quantity remat
        shrinks); CPU/TPU PJRT report no finer peak."""
        if entry is None:
            return {}
        try:
            ma = entry.compiled.memory_analysis()
            temp = int(getattr(ma, "temp_size_in_bytes", 0))
            arg = int(getattr(ma, "argument_size_in_bytes", 0))
            out = int(getattr(ma, "output_size_in_bytes", 0))
            gen = int(getattr(ma, "generated_code_size_in_bytes", 0))
            alias = int(getattr(ma, "alias_size_in_bytes", 0))
        except Exception:
            return {}
        return {"temp_bytes": temp, "argument_bytes": arg,
                "output_bytes": out, "generated_code_bytes": gen,
                "alias_bytes": alias, "peak_bytes": temp + arg + out}

    def memory_stats(self) -> Dict[str, int]:
        """XLA memory analysis of the LAST executable this executor ran:
        peak_bytes / temp_bytes / argument_bytes / output_bytes /
        generated_code_bytes / alias_bytes. The objective gate for the
        recompute pass — bench's remat probe asserts temp/peak strictly
        drop with BuildStrategy.recompute on. {} before the first run."""
        return self._memory_analysis_dict(self._last_entry)

    def cost_stats(self, top: int = 10) -> Dict[str, Any]:
        """Analytic cost breakdown of the LAST executable this executor
        dispatched (static/cost_model.py over the optimized Program IR,
        with the gm/remat/shard step structure folded in): per-op and
        per-step model_flops / hbm_bytes / comm_bytes, flops/bytes by op
        type, top ops, plus the device peaks and the live derived
        gauges (mfu, arith_intensity) from the last measured step.
        {} before the first run or when the model could not cost the
        program."""
        entry = self._last_entry
        cost = getattr(entry, "cost", None) if entry is not None else None
        if not cost:
            return {}
        from ..observability.device_peaks import machine_balance, peaks_for

        out = cost.to_dict(top=top)
        kind = _device_kind()
        out["device_kind"] = kind
        peaks = peaks_for(kind)
        if peaks is not None:
            out["peak_flops"] = peaks.flops
            out["peak_hbm_bytes_per_s"] = peaks.hbm_bytes_per_s
            mb = machine_balance(kind)
            if mb:
                out["machine_balance"] = round(mb, 3)
        for g in ("step_model_flops", "step_hbm_bytes",
                  "step_comm_bytes", "mfu", "arith_intensity"):
            if g in self._counters:
                out[g] = self._counters[g]
        return out

    def _publish_cost_gauges(self, cost, phases) -> Dict[str, Any]:
        """Land one step's cost-model totals + derived utilization in
        the gauges: step_model_flops / step_hbm_bytes / step_comm_bytes
        from the report, mfu from the MEASURED dispatch+fetch seconds
        against the device peak (fetch is included because jax dispatch
        is async — the host-side conversion is where the device step is
        actually awaited), arith_intensity = flops per HBM byte."""
        from .. import profiler
        from ..observability.device_peaks import peaks_for

        vals: Dict[str, Any] = {
            "step_model_flops": cost.model_flops,
            "step_hbm_bytes": cost.hbm_bytes,
            "step_comm_bytes": cost.comm_bytes,
            "arith_intensity": round(cost.arith_intensity, 3),
        }
        step_s = (phases.get("dispatch", 0.0)
                  + phases.get("fetch", 0.0)) / 1e3
        peaks = peaks_for(_device_kind())
        if peaks is not None and peaks.flops > 0 and step_s > 0 \
                and cost.model_flops:
            # 6 decimals: a tiny probe's true MFU can sit at 1e-5 — a
            # 4-decimal gauge would floor it to an indistinguishable 0
            vals["mfu"] = round(
                cost.model_flops / step_s / peaks.flops, 6)
        else:
            # not computable for THIS step (matmul-free program, or no
            # known peak): overwrite, never leave a previous program's
            # mfu standing next to step_model_flops=0
            vals["mfu"] = 0
        for name, v in vals.items():
            self._counters[name] = v
            profiler.set_counter(name, v)
        return vals

    def _clear_cost_gauges(self) -> None:
        """Zero the cost gauges unconditionally (another executor may
        have set the process-global ones): 5 dict writes per uncosted
        step, negligible next to the dispatch."""
        from .. import profiler

        for name in ("step_model_flops", "step_hbm_bytes",
                     "step_comm_bytes", "mfu", "arith_intensity"):
            self._counters[name] = 0
            profiler.set_counter(name, 0)

    def _update_memory_gauges(self, entry) -> None:
        """Mirror the last executable's memory analysis into the
        counters as GAUGES (assigned, not accumulated): xla_temp_bytes /
        xla_peak_bytes / xla_argument_bytes / xla_output_bytes."""
        from .. import profiler

        stats = self._memory_analysis_dict(entry)
        for key in ("temp_bytes", "peak_bytes", "argument_bytes",
                    "output_bytes"):
            if key in stats:
                self._counters[f"xla_{key}"] = stats[key]
                profiler.set_counter(f"xla_{key}", stats[key])

    def close(self):
        self._cache.clear()

    # -- main entry -------------------------------------------------------
    def run(self, program: Optional[Program] = None,
            feed: Optional[Dict[str, Any]] = None,
            fetch_list: Optional[Sequence] = None,
            scope: Optional[Scope] = None,
            return_numpy: bool = True,
            use_program_cache: bool = True):
        """One step. The hot path is phase-instrumented: feed (host prep
        + h2d, includes rare builds), dispatch (compiled XLA step), and
        fetch (write-back + host conversion) land in the
        ``executor_step_phase_ms`` histogram; with a StepTrace active
        (``PADDLE_STEP_TRACE``) each step also emits a JSONL record
        stamped ``paddle_step_<id>`` for XPlane correlation, and every
        step rides the crash flight recorder's bounded ring."""
        trace = active_step_trace()
        tr_scope = trace.step("executor") if trace is not None else None
        obs: Dict[str, Any] = {"t0": time.perf_counter()}
        if tr_scope is not None:
            tr_scope.__enter__()
        try:
            return self._run_impl(program, feed, fetch_list, scope,
                                  return_numpy, use_program_cache, obs)
        finally:
            self._finish_step_obs(obs, tr_scope)

    def _finish_step_obs(self, obs, tr_scope) -> None:
        """Close one step's observability: histogram observes, flight
        ring append, step-trace record (exception-safe — runs in run()'s
        finally with the in-flight exception, if any, via exc_info)."""
        import sys as _sys

        t_end = time.perf_counter()
        t_feed, t_disp = obs.get("t_feed"), obs.get("t_dispatch")
        phases: Dict[str, float] = {}
        cost_vals: Dict[str, Any] = {}
        if t_disp is not None:
            phases["feed"] = (t_feed - obs["t0"]) * 1e3
            phases["dispatch"] = (t_disp - t_feed) * 1e3
            phases["fetch"] = (t_end - t_disp) * 1e3
            h = _phase_hist()
            for name, ms in phases.items():
                h.observe(ms, phase=name)
            cost = obs.get("cost")
            if cost is not None:
                cost_vals = self._publish_cost_gauges(cost, phases)
            else:
                # an uncostable program must not leave the previous
                # program's flops/mfu on the dashboard: the gauges
                # describe the LAST DISPATCHED step, so zero them
                self._clear_cost_gauges()
            flight_recorder().record_step({
                "exe_step": self._step,
                "cache_hit": obs.get("cache_hit", False),
                "h2d_bytes": obs.get("h2d_bytes", 0),
                "phases": {k: round(v, 3) for k, v in phases.items()}})
        if tr_scope is not None:
            tr_scope._phases.update(phases)
            if t_disp is not None:
                tr_scope.set("exe_step", self._step)
                tr_scope.set("cache_hit", obs.get("cache_hit", False))
                tr_scope.set("h2d_bytes", obs.get("h2d_bytes", 0))
                for name, v in cost_vals.items():
                    tr_scope.set(name, v)
            tr_scope.__exit__(*_sys.exc_info())
            if obs.get("cost") is not None:
                # per-executable breakdown record (kind="cost"): totals,
                # per-op top tables, device peaks — the top-K/roofline
                # source tools/perf_report.py reads next to the per-step
                # rows (emitted AFTER the step record so file order
                # stays a single monotone step-id sequence; de-duped per
                # trace so warm steps don't repeat it)
                self._emit_cost_record(tr_scope._trace, obs["cost"])

    def _emit_cost_record(self, trace, cost) -> None:
        from ..observability.device_peaks import peaks_for

        # per-trace dedup: one record per REPORT OBJECT, not per step —
        # keyed by identity with the object held strongly (an id() alone
        # could be reused after a cache-evicted report is GC'd, silently
        # skipping a new executable), LRU-bounded so alternating
        # programs (train+eval) emit once each, not once per step
        seen = getattr(trace, "_cost_seen", None)
        if seen is None:
            seen = trace._cost_seen = OrderedDict()
        if id(cost) in seen:
            seen.move_to_end(id(cost))
            return
        seen[id(cost)] = cost
        while len(seen) > 64:
            seen.popitem(last=False)
        try:
            rec = cost.to_dict(top=20)
            kind = _device_kind()
            rec["device_kind"] = kind
            peaks = peaks_for(kind)
            if peaks is not None:
                rec["peak_flops"] = peaks.flops
                rec["peak_hbm_bytes_per_s"] = peaks.hbm_bytes_per_s
            trace.record("cost", rec)
        except Exception:
            pass  # tracing must never take down the step

    def _run_impl(self, program, feed, fetch_list, scope, return_numpy,
                  use_program_cache, obs):
        from .ir import default_main_program
        from .compiler import CompiledProgram

        sharding = None
        strategy = None
        if isinstance(program, CompiledProgram):
            sharding = program._data_sharding()
            strategy = program._build_strategy
            program = program._program
        if program is None:
            program = default_main_program()
        if strategy is None:
            # fleet.distributed_optimizer's static path stamps the
            # program with the BuildStrategy its DistributedStrategy
            # maps to (recompute/gradient_merge/amp knobs) — honored for
            # raw-Program runs so fleet users need no CompiledProgram
            strategy = getattr(program, "_fleet_build_strategy", None)
        # let the program's py_readers stage batches directly into the
        # feed layout on their prefetch thread; set unconditionally so a
        # later raw-Program run clears a stale data-parallel stash
        program._feed_sharding = sharding
        scope = scope or global_scope()
        if not feed and not fetch_list:
            # startup-program shape: run initializers eagerly into the scope
            return self.run_startup(program, scope)
        feed = {k: np.asarray(v) if not isinstance(v, jax.Array) else v
                for k, v in (feed or {}).items()}
        # started py_readers feed their data vars (read_op parity —
        # static/py_reader.py; raises EOFException when exhausted)
        for _rdr in getattr(program, "_py_readers", []):
            if _rdr._started:
                for k, v in _rdr._next_feed().items():
                    feed.setdefault(k, v)
        fetch_names = [v.name if isinstance(v, Variable) else str(v)
                       for v in (fetch_list or [])]

        block = program.global_block
        # mixed precision (BuildStrategy.amp / PADDLE_AMP): float32 feeds
        # are cast HOST-side to the low dtype — half the h2d bytes — and
        # the amp config joins the step key so flipping the env (or the
        # strategy) can never hit a stale executable. Stash the feed
        # dtype map on the program (like _feed_sharding) so py_reader
        # prefetch threads stage batches already low.
        from .passes import (amp_feed_dtypes_cached, resolve_amp,
                             resolve_comm, resolve_gradient_merge,
                             resolve_pipeline, resolve_sharding)

        amp = resolve_amp(strategy)
        gm = resolve_gradient_merge(strategy)
        shard_cfg = resolve_sharding(strategy)
        pp = resolve_pipeline(strategy)
        comm = resolve_comm(strategy)
        if gm is None:
            # mirrors apply_passes: pipeline_stages without
            # gradient_merge_k > 1 has no microbatches to schedule
            pp = None
        fdt = amp_feed_dtypes_cached(program, amp)
        program._amp_feed_dtypes = fdt

        def _amp_fix_feed(k, v):
            if not isinstance(v, jax.Array):
                if fdt and k in fdt and v.dtype == np.float32:
                    return v.astype(fdt[k])
                return v
            # device-staged feeds must match the dtype this run traces
            # with: the program-level stash is shared, so a prefetch
            # thread serving a DIFFERENT amp config (amp-on train +
            # amp-off eval over one Program) can stage the wrong dtype —
            # a cheap on-device cast beats a silent wrong-graph feed or
            # a recompile ping-pong
            if fdt and k in fdt and v.dtype == jnp.float32:
                return v.astype(jnp.dtype(fdt[k]))
            if not fdt:
                dv = block.vars.get(k)
                if dv is not None and dv.is_data \
                        and dv.dtype == "float32" \
                        and v.dtype in (jnp.bfloat16, jnp.float16):
                    return v.astype(jnp.float32)
            return v

        feed = {k: _amp_fix_feed(k, v) for k, v in feed.items()}
        peek = getattr(scope, "_peek", scope.find_var)
        persist_names = sorted(
            n for n, v in block.vars.items()
            if v.persistable and peek(n) is not None)
        if shard_cfg is not None:
            # GSPMD static sharding (BuildStrategy.mesh_shape +
            # sharding_hints): build the real mesh and the jit-boundary
            # sharding map — it REPLACES any CompiledProgram
            # data-parallel map (mesh_shape is the more general spelling
            # of the same thing) and rides program._feed_sharding so
            # prefetch threads stage batches already partitioned.
            # Memoized on the shapes that decide it (spec fitting checks
            # divisibility against live shapes) — the warm path pays one
            # key comparison, not a NamedSharding rebuild per step.
            shard_key = (
                program._version, shard_cfg, tuple(persist_names),
                tuple(sorted((k, tuple(getattr(v, "shape", ())))
                             for k, v in feed.items())))
            cached = getattr(self, "_shard_map_cache", None)
            if cached is not None and cached[0] == shard_key:
                sharding = cached[1]
            else:
                from ..parallel.mesh import mesh_for_shape
                from .passes import shard_boundary_shardings

                mesh = mesh_for_shape(dict(shard_cfg[0]))
                sharding = shard_boundary_shardings(
                    mesh, block, feed, persist_names, shard_cfg, peek)
                self._shard_map_cache = (shard_key, sharding)
            program._feed_sharding = sharding
        # quantized DP collectives (BuildStrategy.comm_quant /
        # PADDLE_QUANT_ALLREDUCE): resolve eligibility + the gradient
        # bucket plan up front — the error-feedback residuals ride the
        # DONATED state, so they must join persist_names before the
        # state gather, and the comm tuple joins the step/content keys
        # so a codec/bucket flip can never hit a stale executable
        comm_plan = None
        if comm is not None:
            comm_plan = self._comm_eligibility(
                program, block, comm, shard_cfg, gm, feed, sharding,
                pp=pp)
            if comm_plan is not None and comm[2]:
                sharding = dict(sharding) if sharding else {}
                persist_names = list(persist_names)
                persist_names += self._ensure_ef_state(
                    scope, comm_plan, shard_cfg, sharding)
                program._feed_sharding = sharding
        feed_keys = sorted(feed.keys())
        feed_vals = [feed[k] for k in feed_keys]
        state = self._gather_state(scope, persist_names, feed_vals,
                                   sharding)
        seed = program.random_seed or random_mod.default_generator().initial_seed()
        rng = jax.random.fold_in(random_mod.make_key(seed), self._step)
        # shape/dtype only — never materialize device arrays for the key
        feed_sig = _feed_signature(feed)
        state_sig = _state_signature(state)
        step_key = (program._version, feed_sig, tuple(fetch_names),
                    tuple(persist_names), state_sig, bool(sharding),
                    _strategy_signature(strategy), amp, gm, shard_cfg,
                    pp, comm, comm_plan is not None)
        per_prog = self._cache.setdefault(program, {})
        entry = None
        if use_program_cache:
            ck = per_prog.get(step_key)
            if ck is not None:
                entry = _exec_cache_get(ck)
                if entry is not None:
                    self._bump("compile_cache_hits")
                    obs["cache_hit"] = True
        if entry is None:
            # rewrite the block through the IR pass pipeline, then look
            # up / build the executable by CONTENT — a cloned or
            # deserialized copy of a compiled program lands on the same
            # sha, as does any other Executor in this process
            from .passes import apply_passes

            opt_program, report = apply_passes(
                program, feed_keys, fetch_names, strategy)
            self._record_pass_report(report)
            ck = _content_key(opt_program, feed_sig, fetch_names,
                              persist_names, state_sig, sharding,
                              self._donate, gm, pp, comm)
            per_prog[step_key] = ck
            entry = _exec_cache_get(ck) if use_program_cache else None
            if entry is not None:
                self._bump("compile_cache_hits")
                obs["cache_hit"] = True
            else:
                is_gm = gm is not None and any(
                    op.type == "backward"
                    for op in opt_program.global_block.ops)
                compiled_fn = self._build(
                    opt_program.global_block, feed_keys, fetch_names,
                    persist_names, sharding, feed_vals, state, rng, gm,
                    pp, comm=comm, comm_plan=comm_plan)
                entry = _ExecEntry(compiled_fn, opt_program, report,
                                   is_gm)
                if comm_plan is not None and any(
                        op.type == "backward"
                        for op in opt_program.global_block.ops):
                    entry.comm_stats = _comm_entry_stats(comm_plan)
                if use_program_cache:
                    _exec_cache_put(ck, entry)
                self._bump("compile_cache_misses")
        compiled = entry.compiled
        if entry is not getattr(self, "_last_entry", None):
            self._last_entry = entry
            self._update_memory_gauges(entry)
        if entry.cost is None:
            # one analytic walk per executable (VarDesc arithmetic, no
            # tracing); False = model couldn't cost this program, never
            # retried on the hot path
            try:
                from .cost_model import program_cost

                entry.cost = program_cost(
                    entry.optimized_program,
                    feed_shapes={k: tuple(getattr(v, "shape", ()) or ())
                                 for k, v in feed.items()},
                    gm=gm if entry.is_gm else None,
                    shard_cfg=shard_cfg, pp=pp,
                    comm=comm if getattr(entry, "comm_stats", None)
                    else None)
            except Exception:
                entry.cost = False
        if entry.cost:
            obs["cost"] = entry.cost

        self._step += 1
        self._bump("executor_steps")
        if gm and entry.is_gm:
            # one dispatch covers gm[0] microbatches (one optimizer
            # update): the tokens-per-dispatch win gradient merge buys
            self._bump("gm_dispatches")
            self._bump("gm_microbatches", gm[0])
        if getattr(entry, "comm_stats", None):
            # quantized-collective accounting, per dispatch: the wire
            # bytes this step's bucketed all-reduce moved (and saved vs
            # f32) are cumulative counters; the bucket count and the
            # analytic overlap fraction are point-in-time gauges
            from .. import profiler

            cs = entry.comm_stats
            self._bump("comm_quant_bytes_sent", cs["bytes_sent"])
            self._bump("comm_quant_bytes_saved", cs["bytes_saved"])
            for name in ("comm_buckets", "allreduce_overlap_frac"):
                self._counters[name] = cs[name]
                profiler.set_counter(name, cs[name])
        feed_h2d = sum(_nbytes(v) for v in feed_vals
                       if not isinstance(v, jax.Array))
        if feed_h2d:
            self._bump("h2d_bytes", feed_h2d)
        if self._donate:
            self._bump("donated_bytes",
                       sum(_nbytes(a) for a in state) + _nbytes(rng))
        obs["h2d_bytes"] = feed_h2d
        obs["t_feed"] = time.perf_counter()
        fetches, new_state = compiled(feed_vals, state, rng)
        obs["t_dispatch"] = time.perf_counter()
        write_back = getattr(scope, "_write_back", scope.set)
        for n, v in zip(persist_names, new_state):
            write_back(n, v)
        if return_numpy:
            return [np.asarray(f) for f in fetches]
        # a fetched persistable may share its buffer with the state just
        # written back (same traced value — XLA may alias the outputs);
        # mark it exposed so the next donating step copies first
        if self._donate and hasattr(scope, "_exposed"):
            persist_set = set(persist_names)
            scope._exposed.update(n for n in fetch_names
                                  if n in persist_set)
        return list(fetches)

    def _gather_state(self, scope, persist_names, feed_vals, sharding):
        """Read persistable state for one step, keeping it device-resident:
        host entries (numpy — e.g. fresh from static.io.load) are uploaded
        ONCE, already laid out with the program's parameter sharding, and
        written back so every later step passes resident jax.Arrays —
        zero per-step host->device traffic for state. Under donation,
        caller-visible aliases are copied so donation can't invalidate a
        buffer the caller still holds (or hand XLA one buffer twice)."""
        peek = getattr(scope, "_peek", scope.find_var)
        write_back = getattr(scope, "_write_back", scope.set)
        exposed = getattr(scope, "_exposed", set())
        param_shard = sharding.get("__param__") if sharding else None
        state = []
        # a feed array doubling as state must not be donated out from
        # under the feed argument
        seen = {id(v) for v in feed_vals if isinstance(v, jax.Array)}
        from ..parallel.sharding import device_put_counted

        for n in persist_names:
            arr = peek(n)
            if not isinstance(arr, jax.Array):
                host = np.asarray(arr)
                # device_put_counted bumps the global h2d_bytes; the
                # state-specific slice (and this executor's view) are
                # tracked here. A per-name entry (shard_propagation's
                # hinted params) beats the blanket __param__ fallback —
                # the upload lands already tp/dp-partitioned.
                arr = device_put_counted(
                    host, sharding.get(n, param_shard)
                    if sharding else None)
                self._counters["h2d_bytes"] += host.nbytes
                self._bump("state_h2d_bytes", host.nbytes)
                write_back(n, arr)
            elif sharding is not None:
                # a resident array laid out for a DIFFERENT config (the
                # user flipped sharding_hints/mesh_shape between runs on
                # one scope) must be re-placed or the AOT step rejects
                # the arg; a matching layout costs one equality check,
                # and a reshard is device-to-device (no h2d)
                target = sharding.get(n, param_shard)
                if target is not None and \
                        getattr(arr, "sharding", None) != target:
                    arr = jax.device_put(arr, target)
                    write_back(n, arr)
            if self._donate:
                aliased = id(arr) in seen
                seen.add(id(arr))
                if aliased or n in exposed:
                    arr = jnp.array(arr)   # the copy is what gets donated
                    self._bump("donation_fallback_copies")
            state.append(arr)
        return state

    def _record_pass_report(self, report) -> None:
        """Land the pipeline's per-pass op deltas + wall time in the
        profiler counters (and this executor's view): ir_ops_before/
        ir_ops_after, ir_pass_ms, ir_vars_dropped, pass_<name>_*."""
        self._bump("ir_ops_before", report.ops_before)
        self._bump("ir_ops_after", report.ops_after)
        self._bump("ir_pass_ms", round(report.ms, 3))
        if report.vars_dropped:
            self._bump("ir_vars_dropped", report.vars_dropped)
        for s in report.stats:
            if s.removed:
                self._bump(f"pass_{s.name}_removed_ops", s.removed)
            self._bump(f"pass_{s.name}_ms", round(s.ms, 3))
        for name, v in getattr(report, "amp", {}).items():
            self._bump(name, v)
        for name, v in getattr(report, "remat", {}).items():
            self._bump(name, v)
        for name, v in getattr(report, "shard", {}).items():
            if name == "pp_stages":   # point-in-time, not cumulative
                from .. import profiler

                self._counters[name] = v
                profiler.set_counter(name, v)
            else:
                self._bump(name, v)

    def _build(self, block, feed_keys, fetch_names, persist_names,
               sharding, feed_vals, state, rng, gm=None, pp=None,
               comm=None, comm_plan=None):
        """AOT-compile one step: jit -> lower() (trace_ms) -> compile()
        (compile_ms). The split makes trace vs XLA-compile time
        measurable, and compile() goes through jax's persistent
        compilation cache when PADDLE_COMPILE_CACHE[_DIR] is set — a
        relaunched trainer's cold build becomes a disk read
        (disk_cache_hits in exe.counters).

        With ``gm`` (resolve_gradient_merge result) and a backward op in
        the block, the step is compiled as a lax.scan over k microbatches
        instead (_gm_step_fn); with ``pp`` (resolve_pipeline stage count)
        on top, the microbatch loop runs on the GPipe fill-drain schedule
        over the ``__pp_stage``-stamped forward stages (_pp_step_fn).

        The jit/lower/compile mechanics live in substrate.aot_compile —
        the ONE compiled-step build path this executor shares with the
        decode engine (inference/decode) and, through Executor.run, the
        serving predictor."""

        gm_bwd = None
        if gm is not None:
            gm_bwd = next((i for i, op in enumerate(block.ops)
                           if op.type == "backward"), None)
        comm_bwd = None
        if comm_plan is not None:
            comm_bwd = next((i for i, op in enumerate(block.ops)
                             if op.type == "backward"), None)
        if comm_bwd is not None:
            # explicit quantized-collective DP step (shard_map over the
            # pure-dp mesh; composes the gm microbatch scan internally)
            step = self._comm_step_fn(block, feed_keys, fetch_names,
                                      persist_names, feed_vals, gm,
                                      comm_bwd, comm, comm_plan,
                                      sharding)
        elif gm_bwd is not None and pp is not None and pp > 1 and any(
                "__pp_stage" in op.attrs for op in block.ops):
            step = self._pp_step_fn(block, feed_keys, fetch_names,
                                    persist_names, feed_vals, gm, gm_bwd)
        elif gm_bwd is not None:
            step = self._gm_step_fn(block, feed_keys, fetch_names,
                                    persist_names, feed_vals, gm, gm_bwd)
        else:
            def step(feed_vals, state, rng):
                env = dict(zip(feed_keys, feed_vals))
                env.update(zip(persist_names, state))
                ctx = ExecContext(rng_key=rng)
                env = run_block(block, env, ctx)
                fetches = [env[n] for n in fetch_names]
                new_state = [env.get(n, s)
                             for n, s in zip(persist_names, state)]
                return fetches, new_state

        in_shardings = out_shardings = None
        if sharding is not None:
            param_shard = sharding.get("__param__")
            # per-name entries (the shard_propagation boundary map:
            # hinted tp/dp params) beat the blanket __param__ fallback;
            # the classic data-parallel map has no per-name entries so
            # this degenerates to the old [param_shard] * N
            state_shards = [sharding.get(n, param_shard)
                            for n in persist_names]
            in_shardings = (
                [sharding.get(k) for k in feed_keys],
                state_shards,
                sharding.get("__rng__"))
            # pin state OUTPUTS to the same layout: chained steps feed
            # new_state straight back in without re-partitioning
            out_shardings = (
                [None] * len(fetch_names),
                state_shards)
        from .substrate import aot_compile

        cs = aot_compile(
            step, (feed_vals, state, rng),
            # state + rng buffers are reused in place by XLA; feeds are
            # fresh per step and stay un-donated
            donate_argnums=(1, 2) if self._donate else None,
            in_shardings=in_shardings, out_shardings=out_shardings,
            bump=self._bump)
        return cs.compiled

    @staticmethod
    def _merge_region(block, feed_keys, feed_vals, persist_names,
                      fetch_names, k, bwd_idx):
        """Split one training block at the backward boundary for a
        k-microbatch merged step — shared by the gm scan and the GPipe
        schedule (their parity depends on agreeing on this split).
        Returns ``(scan_end, grad_names, found_name, state_carry,
        carry_out, post_outs)``: ops [0, scan_end) run per microbatch
        (forward + backward + an adjacent fp16 check_finite_and_unscale),
        ops [scan_end, ...) are the optimizer region run once on the
        merged gradient; state_carry is the per-microbatch persistable
        writes, carry_out everything else the post region or a fetch
        reads."""
        for key, v in zip(feed_keys, feed_vals):
            shp = tuple(getattr(v, "shape", ()))
            if not shp or shp[0] % k:
                raise ValueError(
                    f"gradient_merge_k={k}: feed {key!r} batch dim "
                    f"{shp[0] if shp else None} is not divisible by k")
        ops = block.ops
        scan_end = bwd_idx + 1
        if scan_end < len(ops) and \
                ops[scan_end].type == "check_finite_and_unscale":
            scan_end += 1
        grad_names = list(ops[bwd_idx].outputs.get("Grads", []))
        found_name = None
        if ops[scan_end - 1].type == "check_finite_and_unscale":
            fo = ops[scan_end - 1].outputs.get("FoundInfinite")
            found_name = fo[0] if fo else None
        produced: set = set()
        for op in ops[:scan_end]:
            produced.update(op.output_names())
        post_reads: set = set()
        post_outs: set = set()
        for op in ops[scan_end:]:
            post_reads.update(op.input_names())
            post_outs.update(op.output_names())
        special = set(grad_names) | {found_name} - {None}
        persist_set = set(persist_names)
        # state written per microbatch rides the carry; everything else
        # the post region or a fetch reads rides the stacked ys
        state_carry = sorted(produced & persist_set)
        carry_out = sorted(((post_reads | set(fetch_names)) & produced)
                           - special - persist_set)
        return (scan_end, grad_names, found_name, state_carry,
                carry_out, post_outs)

    def _gm_step_fn(self, block, feed_keys, fetch_names, persist_names,
                    feed_vals, gm, bwd_idx):
        """In-step gradient merge: compile the train step as ONE
        lax.scan over k microbatches (GPipe-style accumulation, inside a
        single dispatch).

        The op list splits at the backward boundary: ops [0, scan_end)
        (forward + backward + an adjacent fp16 check_finite_and_unscale)
        run PER MICROBATCH inside the scan; ops [scan_end, ...) — the
        optimizer update region — run ONCE on the merged gradient.
        Mechanics:

        - every feed is reshaped (B, ...) -> (k, B//k, ...) inside the
          trace (host layout untouched; B must divide by k)
        - gradients accumulate in f32 whatever the compute dtype (AMP
          bf16/fp16 microbatch grads are upcast before the add), and
          with avg=True the MERGED sum is divided by k once — never a
          per-microbatch lr rescale
        - the fp16 FoundInfinite flag is OR-reduced over microbatches:
          one bad microbatch skips the whole merged update
        - persistable state written inside the scanned region
          (batch_norm running stats, step counters) threads through the
          scan carry, so microbatch i sees microbatch i-1's updates
        - each microbatch folds its index into the step RNG key —
          dropout draws fresh masks per microbatch
        - float fetches produced inside the scanned region (the loss)
          are averaged over microbatches; non-float fetches report the
          last microbatch
        """
        import numpy as _np

        k, avg = gm
        (scan_end, grad_names, found_name, state_carry, carry_out,
         post_outs) = self._merge_region(block, feed_keys, feed_vals,
                                         persist_names, fetch_names, k,
                                         bwd_idx)

        def _micro(mb_feed, state_env, carried, key):
            env = dict(zip(feed_keys, mb_feed))
            env.update(state_env)
            env.update(carried)
            ctx = ExecContext(rng_key=key)
            return run_block(block, env, ctx, stop_at=scan_end)

        # grad avals (shape/dtype of ONE microbatch's grads): read from
        # the grad VarDescs when fully static — append_backward declares
        # them with the param's shape/dtype — falling back to an
        # abstract eval_shape trace only for dynamic shapes
        # (calc_gradient w.r.t. a batch-dim intermediate). The probe
        # re-interprets the whole scanned region, so skipping it halves
        # merged-build trace time in the common (param-grad) case.
        grad_avals = []
        for g in grad_names:
            desc = block.vars.get(g)
            shape = getattr(desc, "shape", None)
            if not shape or any(int(d) < 0 for d in shape):
                grad_avals = None
                break
            grad_avals.append(jax.ShapeDtypeStruct(
                tuple(int(d) for d in shape),
                jnp.dtype(dtype_mod.convert_dtype(desc.dtype))))

        mb_avals = [jax.ShapeDtypeStruct(
            (int(v.shape[0]) // k,) + tuple(int(d) for d in v.shape[1:]),
            getattr(v, "dtype", _np.asarray(v).dtype))
            for v in feed_vals]

        def _probe(mb_feed, state, rng):
            env = _micro(mb_feed, dict(zip(persist_names, state)), {},
                         rng)
            return [env[g] for g in grad_names]

        def step(feed_vals, state, rng):
            state_env0 = dict(zip(persist_names, state))
            avals = grad_avals if grad_avals is not None else \
                jax.eval_shape(_probe, mb_avals, state, rng)
            mbs = [v.reshape((k, v.shape[0] // k) + tuple(v.shape[1:]))
                   for v in feed_vals]

            def body(carry, xs):
                accum, carried, found = carry
                mb, mi = xs
                env = _micro(mb, state_env0, carried,
                             jax.random.fold_in(rng, mi))
                accum = [a + env[g].astype(jnp.float32)
                         for a, g in zip(accum, grad_names)]
                carried = {n: env[n] for n in state_carry}
                if found_name is not None:
                    found = found | jnp.reshape(
                        env[found_name], ()).astype(bool)
                ys = {n: env[n] for n in carry_out}
                return (accum, carried, found), ys

            init = ([jnp.zeros(a.shape, jnp.float32) for a in avals],
                    {n: state_env0[n] for n in state_carry},
                    jnp.zeros((), jnp.bool_))
            (accum, carried, found), ys = jax.lax.scan(
                body, init, (mbs, jnp.arange(k)))
            env = dict(zip(feed_keys, feed_vals))  # full batch for post
            env.update(state_env0)
            env.update(carried)
            env.update({n: ys[n][-1] for n in carry_out})
            for g, a, aval in zip(grad_names, accum, avals):
                merged = a / k if avg else a
                env[g] = merged.astype(aval.dtype)
            if found_name is not None:
                env[found_name] = jnp.reshape(found, (1,))
            ctx = ExecContext(rng_key=rng)
            env = run_block(block, env, ctx, start=scan_end)
            fetches = []
            for n in fetch_names:
                if n in ys and n not in post_outs:
                    stacked = ys[n]
                    if jnp.issubdtype(stacked.dtype, jnp.inexact):
                        fetches.append(jnp.mean(
                            stacked.astype(jnp.float32), axis=0
                        ).astype(stacked.dtype))
                    else:
                        fetches.append(stacked[-1])
                else:
                    fetches.append(env[n])
            new_state = [env.get(n, s)
                         for n, s in zip(persist_names, state)]
            return fetches, new_state

        return step

    # -- quantized DP collectives (ISSUE 15: EQuARX-style comm layer) ------
    def _comm_eligibility(self, program, block, comm, shard_cfg, gm,
                          feed, sharding, pp=None):
        """Gate + plan for the explicit quantized-collective DP step.

        Returns ``(axis_name, group, plan)`` when the build is eligible,
        else None after bumping the ``quant_allreduce.xla`` dispatch
        counter with the reason (the established kernel pattern — the
        XLA f32 GSPMD path is the fallback, bitwise-identical to the
        pre-quantization baseline). Memoized per (program, config, feed
        shapes): the warm step pays one key comparison.

        Eligible means: a PURE data-parallel mesh (exactly one
        'dp'/'data' axis, no sharding hints — tensor/pipeline layouts
        keep XLA's partitioner-owned collectives), one static
        ``backward`` gradient plan, no persistable writes inside the
        scanned region (per-device batch-norm style stats would diverge
        silently under a replicated-out shard_map), every dynamic-batch
        feed actually sharded over the axis, and local batches
        divisible by gradient_merge_k."""
        from ..ops.pallas.counters import bump
        from .passes import comm_bucket_plan, comm_data_axis

        key = (program._version, comm, shard_cfg, gm, pp,
               tuple(sorted((k, tuple(getattr(v, "shape", ())))
                            for k, v in feed.items())))
        cached = getattr(self, "_comm_elig_cache", None)
        if cached is not None and cached[0] == key:
            return cached[1]

        def verdict(result, reason=None):
            if result is None:
                bump("quant_allreduce", "xla", reason)
            else:
                bump("quant_allreduce", "quant")
            self._comm_elig_cache = (key, result)
            return result

        if shard_cfg is None:
            return verdict(None, "comm_quant set but no mesh_shape — "
                                 "quantized collectives need a dp mesh")
        if pp is not None:
            return verdict(None, "pipeline_stages > 1 — the GPipe "
                                 "schedule keeps XLA collectives")
        axis = comm_data_axis(shard_cfg)
        if axis is None:
            return verdict(None, "mesh is not pure data-parallel "
                                 f"(axes {shard_cfg[0]})")
        if shard_cfg[1]:
            return verdict(None, "sharding_hints present — tensor-"
                                 "parallel layouts keep XLA collectives")
        name, g = axis
        plan = comm_bucket_plan(block, comm, g)
        if plan is None:
            return verdict(None, "no static gradient plan (no backward "
                                 "op, or dynamic grad shapes)")
        ops = block.ops
        bwd_idx = next(i for i, op in enumerate(ops)
                       if op.type == "backward")
        persist = {n for n, v in block.vars.items() if v.persistable}
        written = {n for op in ops[:bwd_idx] for n in op.output_names()
                   if n in persist}
        if written:
            return verdict(None, f"persistable writes in the forward "
                                 f"region ({sorted(written)[:3]}) would "
                                 "diverge per-device")
        for k_, v in feed.items():
            dv = block.vars.get(k_)
            shape = getattr(dv, "shape", None)
            if not shape or shape[0] is None or int(shape[0]) >= 0:
                continue
            sh = sharding.get(k_) if sharding else None
            spec = getattr(sh, "spec", None)
            if not spec or not spec[0]:
                return verdict(None, f"feed {k_!r} batch dim not "
                                     f"sharded over {name!r} (size not "
                                     f"divisible by {g}?)")
            local_b = int(getattr(v, "shape", (0,))[0]) // g
            if gm is not None and local_b % gm[0]:
                return verdict(None, f"local batch {local_b} not "
                                     f"divisible by gradient_merge_k="
                                     f"{gm[0]}")
        return verdict((name, g, plan))

    def _ensure_ef_state(self, scope, comm_plan, shard_cfg, sharding):
        """Materialize the error-feedback residual buffers as DONATED
        executor state: one ``(g, padded)`` f32 array per bucket,
        sharded over the data axis so each device owns its row. Returns
        the names (appended to persist_names; XLA updates them in place
        step over step through the normal donation path)."""
        from jax.sharding import NamedSharding, PartitionSpec

        from ..parallel.collectives import padded_len
        from ..parallel.mesh import mesh_for_shape

        axis, g, plan = comm_plan
        mesh = mesh_for_shape(dict(shard_cfg[0]))
        shard = NamedSharding(mesh, PartitionSpec(axis, None))
        peek = getattr(scope, "_peek", scope.find_var)
        write_back = getattr(scope, "_write_back", scope.set)
        names = []
        for i, b in enumerate(plan):
            n = f"__comm_ef_{i}"
            padded = padded_len(b["elems"], g)
            arr = peek(n)
            if not isinstance(arr, jax.Array) or \
                    tuple(arr.shape) != (g, padded):
                arr = jax.device_put(np.zeros((g, padded), np.float32),
                                     shard)
                write_back(n, arr)
            sharding[n] = shard
            names.append(n)
        return names

    def _comm_step_fn(self, block, feed_keys, fetch_names, persist_names,
                      feed_vals, gm, bwd_idx, comm, comm_plan, sharding):
        """Compile the DP train step with an EXPLICIT bucketed,
        quantized gradient all-reduce instead of XLA's implicit f32
        psum: the whole step runs inside shard_map over the pure-dp
        mesh — each device traces the forward+backward on its LOCAL
        batch shard, the per-bucket gradients reduce through
        parallel.collectives' quantized ring (encode per hop, f32
        accumulation, deterministic decode → bitwise-replicated reduced
        values), and the optimizer region then runs replicated on
        every device (same grads + same params ⇒ same updates, so
        state out-specs are replicated by construction).

        Overlap: every bucket's reduce-scatter is ISSUED (in backward-
        completion order, the comm_bucketing plan) before any bucket's
        all-gather completes — XLA's latency-hiding scheduler is free
        to run them concurrently instead of one barrier-shaped reduce.

        Composition: with ``gradient_merge_k`` the local microbatch
        scan accumulates f32 grads exactly like ``_gm_step_fn`` and the
        MERGED gradient is reduced once per step (quantize once per
        step, the PR 5 accumulator discipline). ``avg=True`` on the
        collective turns sum-of-local-mean-grads into the global-mean
        gradient, matching the GSPMD leg's mean-loss semantics.

        Fetch assembly: dynamic-batch fetches gather over the axis
        (out-spec carries the batch dim), other float fetches are
        pmean'd (exact for replicated values, the global mean for
        per-shard losses), the rest report the local value.

        Error feedback (``comm_error_feedback``): each device adds its
        residual to its contribution, quantizes ONCE locally, carries
        the new residual out through the donated ``__comm_ef_<i>``
        state row, and feeds the dequantized contribution into the
        ring."""
        from jax.sharding import PartitionSpec as P

        from ..parallel.collectives import (
            allreduce_done, allreduce_start, padded_len, quant_decode,
            quant_encode, shard_map_nocheck)
        from ..parallel.mesh import mesh_for_shape

        axis, g, plan = comm_plan
        codec, _bucket_bytes, ef = comm
        k, avg_gm = gm if gm is not None else (1, True)
        (scan_end, grad_names, found_name, state_carry, carry_out,
         post_outs) = self._merge_region(block, feed_keys, feed_vals,
                                         persist_names, fetch_names, 1,
                                         bwd_idx)
        mesh = mesh_for_shape({axis: g})
        ef_names = [f"__comm_ef_{i}" for i in range(len(plan))] \
            if ef else []
        ef_set = set(ef_names)
        reg_names = [n for n in persist_names if n not in ef_set]

        grad_elems = {}
        grad_shapes = {}
        for gn in grad_names:
            desc = block.vars.get(gn)
            shape = tuple(int(d) for d in (desc.shape or ()))
            grad_shapes[gn] = shape
            e = 1
            for d in shape:
                e *= d
            grad_elems[gn] = e

        def spec_of(n):
            sh = sharding.get(n) if sharding else None
            spec = getattr(sh, "spec", None)
            return P(*spec) if spec is not None else P()

        # fetch modes: dynamic-batch fetches re-assemble over the axis;
        # float fetches pmean (global mean for shard-varying losses, a
        # no-op for replicated values); the rest report local
        fetch_modes = []
        for n in fetch_names:
            v = block.vars.get(n)
            shape = getattr(v, "shape", None)
            dt = str(getattr(v, "dtype", "float32"))
            if shape and (shape[0] is None or int(shape[0]) < 0):
                fetch_modes.append("gather")
            elif dt.startswith("float") or dt == "bfloat16":
                fetch_modes.append("pmean")
            else:
                fetch_modes.append("local")

        in_specs = ([spec_of(kk) for kk in feed_keys],
                    [P(axis, None) if n in ef_set else P()
                     for n in persist_names],
                    P())
        out_specs = ([P(axis) if m == "gather" else P()
                      for m in fetch_modes],
                     [P(axis, None) if n in ef_set else P()
                      for n in persist_names])

        def reduce_buckets(env, ef_rows):
            """Bucketed quantized all-reduce of env's grads, overlap-
            emitted; returns (env with reduced grads, new ef rows)."""
            xs, new_ef = [], []
            for i, b in enumerate(plan):
                flats = [env[gn].astype(jnp.float32).reshape(-1)
                         for gn in b["grads"]]
                flat = flats[0] if len(flats) == 1 else \
                    jnp.concatenate(flats)
                padded = padded_len(b["elems"], g)
                if padded != flat.shape[0]:
                    flat = jnp.concatenate(
                        [flat, jnp.zeros((padded - flat.shape[0],),
                                         jnp.float32)])
                if ef:
                    flat = flat + ef_rows[i]
                    q, sc = quant_encode(flat, codec)
                    dec = quant_decode(q, sc, codec)
                    new_ef.append(flat - dec)
                    flat = dec
                xs.append(flat)
            starts = [allreduce_start(x, axis, codec=codec, axis_size=g)
                      for x in xs]
            reduced = [allreduce_done(c, avg=True) for c in starts]
            for b, r in zip(plan, reduced):
                off = 0
                for gn in b["grads"]:
                    e = grad_elems[gn]
                    env[gn] = r[off:off + e].reshape(
                        grad_shapes[gn]).astype(env[gn].dtype)
                    off += e
            return env, new_ef

        def local_step(feed_local, state, rng):
            state_env = dict(zip(persist_names, state))
            ef_rows = [state_env[n][0] for n in ef_names]
            state_env0 = {n: state_env[n] for n in reg_names}
            found = jnp.zeros((), jnp.bool_)
            if k > 1:
                mbs = [v.reshape((k, v.shape[0] // k)
                                 + tuple(v.shape[1:]))
                       for v in feed_local]

                def body(carry, xs):
                    accum, found = carry
                    mb, mi = xs
                    env = dict(zip(feed_keys, mb))
                    env.update(state_env0)
                    ctx = ExecContext(
                        rng_key=jax.random.fold_in(rng, mi))
                    env = run_block(block, env, ctx, stop_at=scan_end)
                    accum = [a + env[gn].astype(jnp.float32)
                             for a, gn in zip(accum, grad_names)]
                    if found_name is not None:
                        found = found | jnp.reshape(
                            env[found_name], ()).astype(bool)
                    ys = {n: env[n] for n in carry_out}
                    return (accum, found), ys

                init = ([jnp.zeros((grad_elems[gn],), jnp.float32
                                   ).reshape(grad_shapes[gn])
                         for gn in grad_names],
                        jnp.zeros((), jnp.bool_))
                (accum, found), ys = jax.lax.scan(
                    body, init, (mbs, jnp.arange(k)))
                env = dict(zip(feed_keys, feed_local))
                env.update(state_env0)
                env.update({n: ys[n][-1] for n in carry_out})
                for gn, a in zip(grad_names, accum):
                    env[gn] = (a / k if avg_gm else a)
                scanned_ys = ys
            else:
                env = dict(zip(feed_keys, feed_local))
                env.update(state_env0)
                ctx = ExecContext(rng_key=rng)
                env = run_block(block, env, ctx, stop_at=scan_end)
                if found_name is not None:
                    found = jnp.reshape(env[found_name], ()).astype(bool)
                scanned_ys = None
            env, new_ef = reduce_buckets(env, ef_rows)
            if found_name is not None:
                # one non-finite microbatch on ANY device skips the
                # whole replicated update (pmax = cross-device OR)
                found = jax.lax.pmax(found.astype(jnp.int32), axis) > 0
                env[found_name] = jnp.reshape(found, (1,))
            ctx = ExecContext(rng_key=rng)
            env = run_block(block, env, ctx, start=scan_end)
            fetches = []
            for n, mode in zip(fetch_names, fetch_modes):
                if scanned_ys is not None and n in scanned_ys \
                        and n not in post_outs:
                    stacked = scanned_ys[n]
                    if jnp.issubdtype(stacked.dtype, jnp.inexact):
                        val = jnp.mean(stacked.astype(jnp.float32),
                                       axis=0).astype(stacked.dtype)
                    else:
                        val = stacked[-1]
                else:
                    val = env[n]
                if mode == "pmean" and jnp.issubdtype(
                        jnp.asarray(val).dtype, jnp.inexact):
                    val = jax.lax.pmean(
                        val.astype(jnp.float32), axis).astype(val.dtype)
                fetches.append(val)
            new_state = []
            ef_iter = iter(new_ef)
            for n, s in zip(persist_names, state):
                if n in ef_set:
                    new_state.append(next(ef_iter)[None, :]
                                     if ef else s)
                else:
                    new_state.append(env.get(n, s))
            return fetches, new_state

        sharded = shard_map_nocheck(local_step, mesh, in_specs,
                                    out_specs)

        def step(feed_vals, state, rng):
            return sharded(feed_vals, state, rng)

        return step

    def _pp_step_fn(self, block, feed_keys, fetch_names, persist_names,
                    feed_vals, gm, bwd_idx):
        """GPipe-composed gradient merge: the k microbatches of
        BuildStrategy.gradient_merge_k flow through the
        ``__pp_stage``-stamped forward stages on the GPipe fill-drain
        schedule (parallel.pipeline.gpipe_schedule), still as ONE
        compiled, donated, device-resident dispatch.

        Differences from the plain gm scan (_gm_step_fn):

        - the microbatch loop is schedule-ordered instead of sequential:
          at tick t, stage s advances microbatch t-s — within a tick
          every (stage, microbatch) pair is data-independent, which is
          the property that lets XLA overlap the stages across a 'pp'
          mesh axis (and on one chip compiles to the same math)
        - a microbatch's backward (+ fp16 finite check) runs when it
          retires from the last stage; f32 gradient accumulation happens
          in retirement order == microbatch order, so the merged
          gradient matches the scan's within reassociation roundoff
        - persistable state written INSIDE the forward region does not
          thread microbatch-to-microbatch (GPipe stages overlap, so
          there is no earlier-microbatch value to read); every
          microbatch sees the step-entry state and the LAST retired
          microbatch's writes carry out — bn running stats behave like
          classic GPipe, parameter updates are untouched (they live in
          the post region)

        Everything else (feed reshape, merged-gradient averaging,
        FoundInfinite OR-reduce, loss-fetch averaging, single optimizer
        region on the merged gradient) mirrors _gm_step_fn."""
        from .. import profiler
        from ..parallel.pipeline import gpipe_schedule

        k, avg = gm
        (scan_end, grad_names, found_name, state_carry, carry_out,
         post_outs) = self._merge_region(block, feed_keys, feed_vals,
                                         persist_names, fetch_names, k,
                                         bwd_idx)
        ops = block.ops

        # stage op ranges from the __pp_stage stamps: stage s covers the
        # absolute index range (start_s, end_s]; un-stamped prefix ops
        # (feeds) ride stage 0, un-stamped trailing forward ops ride the
        # last stage
        stage_last: Dict[int, int] = {}
        for i in range(bwd_idx):
            sid = ops[i].attrs.get("__pp_stage")
            if sid is not None:
                stage_last[int(sid)] = i
        n_stages = max(stage_last) + 1
        ranges = []
        start = 0
        for s in range(n_stages):
            end = bwd_idx if s == n_stages - 1 else stage_last[s] + 1
            ranges.append((start, end))
            start = end
        self._counters["pp_stages"] = n_stages
        profiler.set_counter("pp_stages", n_stages)

        def step(feed_vals, state, rng):
            state_env0 = dict(zip(persist_names, state))
            mbs = [v.reshape((k, v.shape[0] // k) + tuple(v.shape[1:]))
                   for v in feed_vals]
            accum = None
            grad_dtypes = None
            found = jnp.zeros((), jnp.bool_)
            carried: Dict[str, Any] = {}
            ys = {n: [None] * k for n in carry_out}
            live: Dict[int, tuple] = {}
            for _t, pairs in gpipe_schedule(n_stages, k):
                for s, m in pairs:
                    if s == 0:
                        env = dict(zip(feed_keys,
                                       [mb[m] for mb in mbs]))
                        env.update(state_env0)
                        # same per-microbatch key derivation as the gm
                        # scan: dropout masks match the scan leg bitwise
                        live[m] = (env, ExecContext(
                            rng_key=jax.random.fold_in(rng, m)))
                    env, ctx = live[m]
                    run_block(block, env, ctx,
                              start=ranges[s][0], stop_at=ranges[s][1])
                    if s == n_stages - 1:
                        # microbatch m retires: backward + fp16 finite
                        # check, then f32 accumulation
                        run_block(block, env, ctx,
                                  start=ranges[s][1], stop_at=scan_end)
                        if grad_dtypes is None:
                            grad_dtypes = [env[g].dtype
                                           for g in grad_names]
                        g = [env[gn].astype(jnp.float32)
                             for gn in grad_names]
                        accum = g if accum is None else \
                            [a + b for a, b in zip(accum, g)]
                        if found_name is not None:
                            found = found | jnp.reshape(
                                env[found_name], ()).astype(bool)
                        carried = {n: env[n] for n in state_carry}
                        for n in carry_out:
                            ys[n][m] = env[n]
                        del live[m]
            env = dict(zip(feed_keys, feed_vals))  # full batch for post
            env.update(state_env0)
            env.update(carried)
            env.update({n: ys[n][-1] for n in carry_out})
            for gname, a, dt in zip(grad_names, accum or (),
                                    grad_dtypes or ()):
                merged = a / k if avg else a
                env[gname] = merged.astype(dt)
            if found_name is not None:
                env[found_name] = jnp.reshape(found, (1,))
            ctx = ExecContext(rng_key=rng)
            env = run_block(block, env, ctx, start=scan_end)
            fetches = []
            for n in fetch_names:
                if n in ys and n not in post_outs:
                    stacked = jnp.stack(ys[n])
                    if jnp.issubdtype(stacked.dtype, jnp.inexact):
                        fetches.append(jnp.mean(
                            stacked.astype(jnp.float32), axis=0
                        ).astype(stacked.dtype))
                    else:
                        fetches.append(stacked[-1])
                else:
                    fetches.append(env[n])
            new_state = [env.get(n, s_)
                         for n, s_ in zip(persist_names, state)]
            return fetches, new_state

        return step

    # -- dataset-driven training (reference executor.py:1593) -------------
    def train_from_dataset(self, program=None, dataset=None, scope=None,
                           thread=0, debug=False, fetch_list=None,
                           fetch_info=None, print_period=100):
        """Train over an entire Dataset (reference Executor.train_from_dataset
        executor.py:1593 → C++ MultiTrainer/HogwildWorker TrainFiles,
        hogwild_worker.cc:191).

        TPU-native shape: the reference spawns one op-loop thread per core
        because each CPU thread is a compute unit; on TPU the chip runs one
        XLA program at a time, so `thread` buys input overlap instead —
        batches are parsed/padded on host threads and prefetched into a
        bounded queue while the device executes the previous step. Sparse
        slots arrive as (values, lod) pairs and are padded to power-of-two
        buckets (static shapes — each bucket compiles once); a program var
        named `<slot>_lens` receives the true lengths (the dense+lengths
        LoD rewrite used across ops/sequence.py).
        """
        import queue as queue_mod
        import threading

        from .compiler import CompiledProgram
        from .ir import default_main_program

        if dataset is None:
            raise ValueError("train_from_dataset requires a dataset")
        run_target = program if program is not None else \
            default_main_program()
        # a CompiledProgram trains data-parallel: steps run through
        # self.run (which applies its sharding to the compiled step) and
        # the prefetcher stages each batch DIRECTLY into the feed's
        # sharded layout — no per-step re-partition
        sharding = None
        strategy = None
        program = run_target
        if isinstance(program, CompiledProgram):
            sharding = program._data_sharding()
            strategy = program._build_strategy
            program = program._program
        scope = scope or global_scope()
        block = program.global_block
        fetch_list = fetch_list or []
        fetch_info = fetch_info or [
            getattr(v, "name", str(v)) for v in fetch_list]

        q: queue_mod.Queue = queue_mod.Queue(maxsize=max(2, int(thread) * 2))
        _END = object()
        producer_error = []

        # multi-worker ingestion: `thread` producers over per-file dataset
        # shards (reference thread-per-DeviceWorker DataFeed channels);
        # batch->feed padding runs in the producer threads so the device
        # never waits on host-side parse/pad
        shards = (dataset.ingest_shards(int(thread))
                  if hasattr(dataset, "ingest_shards") and int(thread) > 1
                  else [dataset])

        def producer(shard):
            try:
                for batch in shard:
                    q.put(self._dataset_batch_to_feed(batch, block))
            except BaseException as e:  # surfaced in the consumer
                producer_error.append(e)
            finally:
                q.put(_END)

        producers = [threading.Thread(target=producer, args=(s,),
                                      daemon=True)
                     for s in shards]
        for t in producers:
            t.start()

        from .prefetch import FeedPrefetcher

        def host_feeds():
            ended = 0
            while ended < len(producers):
                item = q.get()
                if item is _END:
                    ended += 1
                elif item:          # skip empty feed dicts
                    yield item

        # second pipeline stage: while the device executes step N, the
        # prefetch thread device_puts batch N+1 (the producers above
        # keep parsing/padding N+2...). Depth scales with ingestion
        # parallelism but stays bounded — each slot pins device memory.
        # Under AMP, float32 feeds are cast low on the prefetch thread
        # BEFORE the h2d copy (half the transfer, amp_feed_dtypes).
        from .passes import (amp_feed_dtypes, resolve_amp,
                             resolve_sharding, shard_boundary_shardings)

        feed_dtypes = amp_feed_dtypes(block, resolve_amp(strategy))
        shard_cfg = resolve_sharding(strategy)
        if shard_cfg is not None:
            # BuildStrategy.mesh_shape (GSPMD) beats the classic
            # CompiledProgram data-parallel map, exactly as in _run_impl:
            # batches must stage into the SAME layout the AOT step's
            # in_shardings expect, or the dispatch rejects the committed
            # arrays. Derived per batch (stage_feed runs on the prefetch
            # thread) because divisibility is checked against the live
            # batch shapes.
            from ..parallel.mesh import mesh_for_shape
            from .prefetch import stage_feed

            shard_mesh = mesh_for_shape(dict(shard_cfg[0]))

            def _stage(item):
                m = shard_boundary_shardings(shard_mesh, block, item, (),
                                             shard_cfg)
                return stage_feed(item, m, feed_dtypes)

            prefetcher = FeedPrefetcher(host_feeds(),
                                        depth=max(2, int(thread)),
                                        stage=_stage)
        else:
            prefetcher = FeedPrefetcher(host_feeds(),
                                        depth=max(2, int(thread)),
                                        sharding=sharding,
                                        feed_dtypes=feed_dtypes)
        step = 0
        last_fetch = None
        try:
            # one-batch lookahead so the final step is known (it always
            # fetches, like the reference's end-of-epoch metric read)
            pending = next(prefetcher, None)
            while pending is not None:
                feed = pending
                pending = next(prefetcher, None)
                final_step = pending is None
                want_fetch = fetch_list and (
                    debug or final_step or step % print_period == 0)
                out = self.run(run_target, feed=feed,
                               fetch_list=fetch_list if want_fetch else None,
                               scope=scope)
                if want_fetch:
                    last_fetch = out
                    if debug:
                        msg = ", ".join(f"{n}={np.asarray(v).ravel()[:4]}"
                                        for n, v in zip(fetch_info, out))
                        print(f"[train_from_dataset] step {step}: {msg}")
                step += 1
        finally:
            # teardown order matters: signal the prefetch thread FIRST
            # (no join yet — it may be blocked on q.get while producers
            # are still filling q), then unblock/join the producers, then
            # re-seed the _END sentinels the drain may have eaten so
            # host_feeds() always reaches its exit count, and only then
            # join the prefetch thread.
            prefetcher.stop()
            while any(t.is_alive() for t in producers):
                try:
                    q.get(timeout=0.1)
                except queue_mod.Empty:
                    pass
            for t in producers:
                t.join()
            for _ in producers:
                try:
                    q.put_nowait(_END)
                except queue_mod.Full:
                    # q full ⇒ the worker is past q.get (it consumed a
                    # batch) and will see the stop flag, not block again
                    break
            prefetcher.close()
        if producer_error:
            raise producer_error[0]
        return last_fetch

    def infer_from_dataset(self, program=None, dataset=None, scope=None,
                           thread=0, debug=False, fetch_list=None,
                           fetch_info=None, print_period=100):
        """Same loop as train_from_dataset over an inference program
        (reference executor.py:1491)."""
        return self.train_from_dataset(program, dataset, scope, thread,
                                       debug, fetch_list, fetch_info,
                                       print_period)

    @staticmethod
    def _dataset_batch_to_feed(batch, block):
        """Map a Dataset batch (slot -> dense array | (values, lod)) onto
        the program's data vars, padding ragged slots to pow-2 buckets."""
        feed = {}
        for name, val in batch.items():
            if isinstance(val, tuple):
                vals, lod = val
                rows = len(lod) - 1
                lens = np.diff(lod).astype(np.int64)
                longest = int(lens.max()) if rows else 1
                maxlen = 1 << max(0, int(longest - 1).bit_length())
                if np.issubdtype(vals.dtype, np.unsignedinteger):
                    vals = vals.astype(np.int64)
                dense = np.zeros((rows, maxlen), vals.dtype)
                for i in range(rows):
                    dense[i, :lens[i]] = vals[lod[i]:lod[i + 1]]
                if name in block.vars:
                    feed[name] = dense
                if f"{name}_lens" in block.vars:
                    feed[f"{name}_lens"] = lens
            elif name in block.vars:
                if np.issubdtype(getattr(val, "dtype", np.float32),
                                 np.unsignedinteger):
                    val = val.astype(np.int64)
                feed[name] = val
        return feed

    # -- startup-program path --------------------------------------------
    def run_startup(self, program: Program, scope: Optional[Scope] = None):
        """Run initializer ops eagerly, writing persistables to scope.
        (Executor.run on a startup program delegates here.)"""
        scope = scope or global_scope()
        seed = program.random_seed or random_mod.default_generator().initial_seed()
        ctx = ExecContext(rng_key=random_mod.make_key(seed))
        peek = getattr(scope, "_peek", scope.find_var)
        write_back = getattr(scope, "_write_back", scope.set)
        env = {n: peek(n) for n in program.global_block.vars
               if peek(n) is not None}
        env = run_block(program.global_block, env, ctx)
        for name, desc in program.global_block.vars.items():
            if desc.persistable and name in env and env[name] is not None:
                write_back(name, env[name])
        return []
