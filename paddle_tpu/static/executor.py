"""Static-graph Executor + Scope.

TPU-native counterpart of the reference serial Executor
(/root/reference/paddle/fluid/framework/executor.cc:180 Run, hot loop :476)
and the Python front (python/paddle/fluid/executor.py:470/:911).

Design: the reference interprets the block op-by-op with per-op kernel
launches and a Scope of mutable Variables. Here `Executor.run` LOWERS the
whole block to one pure jax function (feed arrays + persistable state in,
fetches + updated state out) and jit-compiles it — XLA fuses what the
reference's 89 IR passes fuse by hand, and a training step (forward +
backward + optimizer ops) becomes a single device program. The Scope is a
host-side dict of jax arrays (functional state), not a mutable var tree.

Startup programs run through the same lowering (initializer ops write
persistables). Compiled executables are cached on (program version, feed
signature, fetch list) like the reference's ExecutorPrepareContext cache.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..framework import dtype as dtype_mod
from ..framework import random as random_mod
from ..framework.place import CPUPlace
from .ir import Block, Program, Variable, grad_var_name
from .kernels import KERNELS, ExecContext


class Scope:
    """name -> jax.Array store (reference framework/scope.cc, but flat &
    functional: executors read a snapshot and write back results)."""

    def __init__(self):
        self._vars: Dict[str, Any] = {}

    def find_var(self, name):
        return self._vars.get(name)

    def var(self, name):
        return self._vars.setdefault(name, None)

    def set(self, name, value):
        self._vars[name] = value

    def keys(self):
        return self._vars.keys()

    def items(self):
        return self._vars.items()

    def drop(self, name):
        self._vars.pop(name, None)


_global_scope = Scope()


def global_scope() -> Scope:
    return _global_scope


def scope_guard(scope):
    import contextlib

    @contextlib.contextmanager
    def guard():
        global _global_scope
        saved = _global_scope
        _global_scope = scope
        try:
            yield scope
        finally:
            _global_scope = saved

    return guard()


# ---------------------------------------------------------------------------
# lowering: Block -> pure function(env) -> env
# ---------------------------------------------------------------------------
def run_block(block: Block, env: Dict[str, Any], ctx: ExecContext,
              stop_at: Optional[int] = None,
              post_writes: Optional[Dict[int, Dict[str, Any]]] = None
              ) -> Dict[str, Any]:
    """Interpret ops of a block over an env dict. Called under jit trace —
    this IS the compilation step, not the runtime (no per-op dispatch cost
    after compile).

    post_writes: {op_index: {var_name: value}} — after op i runs, override
    env entries (used by backward.py to treat an intermediate var as a free
    input for gradient computation w.r.t. it)."""
    from .backward import run_backward_op  # local: avoids import cycle

    if not hasattr(ctx, "initial_env"):
        ctx.initial_env = dict(env)
    for i, op in enumerate(block.ops):
        if stop_at is not None and i >= stop_at:
            break
        ctx.op_index = i
        # control-flow kernels (cond/while) recurse into sub-blocks and
        # need the program + a snapshot of the enclosing env
        ctx.program = block.program
        ctx.env = env
        if op.type == "backward":
            run_backward_op(block, i, op, env, ctx)
            continue
        if op.type in ("feed", "fetch"):
            continue  # handled natively by the executor
        fn = KERNELS.get(op.type)
        if fn is None:
            raise NotImplementedError(
                f"no static kernel registered for op {op.type!r}")
        ins = {slot: [env[n] for n in names]
               for slot, names in op.inputs.items()
               if all(n in env for n in names)}
        outs = fn(ins, op.attrs, ctx)
        for slot, names in op.outputs.items():
            produced = outs.get(slot)
            if produced is None:
                continue
            for name, arr in zip(names, produced):
                env[name] = arr
        if post_writes and i in post_writes:
            env.update(post_writes[i])
    return env


def _feed_signature(feed: Dict[str, np.ndarray]):
    return tuple(sorted((k, tuple(v.shape), str(v.dtype))
                        for k, v in feed.items()))


class Executor:
    """exe = Executor(place); exe.run(program, feed=..., fetch_list=...)."""

    def __init__(self, place=None):
        import weakref
        self.place = place if place is not None else CPUPlace()
        # per-program compiled cache: entries die with their Program (no
        # id() aliasing, no pinning of dead programs)
        self._cache = weakref.WeakKeyDictionary()
        self._step = 0

    def close(self):
        self._cache.clear()

    # -- main entry -------------------------------------------------------
    def run(self, program: Optional[Program] = None,
            feed: Optional[Dict[str, Any]] = None,
            fetch_list: Optional[Sequence] = None,
            scope: Optional[Scope] = None,
            return_numpy: bool = True,
            use_program_cache: bool = True):
        from .ir import default_main_program
        from .compiler import CompiledProgram

        sharding = None
        if isinstance(program, CompiledProgram):
            sharding = program._data_sharding()
            program = program._program
        if program is None:
            program = default_main_program()
        scope = scope or global_scope()
        if not feed and not fetch_list:
            # startup-program shape: run initializers eagerly into the scope
            return self.run_startup(program, scope)
        feed = {k: np.asarray(v) if not isinstance(v, jax.Array) else v
                for k, v in (feed or {}).items()}
        # started py_readers feed their data vars (read_op parity —
        # static/py_reader.py; raises EOFException when exhausted)
        for _rdr in getattr(program, "_py_readers", []):
            if _rdr._started:
                for k, v in _rdr._next_feed().items():
                    feed.setdefault(k, v)
        fetch_names = [v.name if isinstance(v, Variable) else str(v)
                       for v in (fetch_list or [])]

        block = program.global_block
        persist_names = sorted(
            n for n, v in block.vars.items()
            if v.persistable and scope.find_var(n) is not None)
        # shape/dtype only — never materialize device arrays for the key
        key = (program._version, _feed_signature(feed),
               tuple(fetch_names), tuple(persist_names), bool(sharding))
        per_prog = self._cache.setdefault(program, {})
        if not use_program_cache or key not in per_prog:
            per_prog[key] = self._build(program, block, feed, fetch_names,
                                        persist_names, sharding)
        compiled = per_prog[key]

        state = [scope.find_var(n) for n in persist_names]
        seed = program.random_seed or random_mod.default_generator().initial_seed()
        rng = jax.random.fold_in(random_mod.make_key(seed), self._step)
        self._step += 1
        feed_vals = [feed[k] for k in sorted(feed.keys())]
        fetches, new_state = compiled(feed_vals, state, rng)
        for n, v in zip(persist_names, new_state):
            scope.set(n, v)
        if return_numpy:
            return [np.asarray(f) for f in fetches]
        return list(fetches)

    def _build(self, program, block, feed, fetch_names, persist_names,
               sharding):
        feed_keys = sorted(feed.keys())

        def step(feed_vals, state, rng):
            env = dict(zip(feed_keys, feed_vals))
            env.update(zip(persist_names, state))
            ctx = ExecContext(rng_key=rng)
            env = run_block(block, env, ctx)
            fetches = [env[n] for n in fetch_names]
            new_state = [env.get(n, s)
                         for n, s in zip(persist_names, state)]
            return fetches, new_state

        jit_kwargs = {}
        if sharding is not None:
            in_shardings = (
                [sharding.get(k) for k in feed_keys],
                [sharding.get("__param__")] * len(persist_names),
                None)
            jit_kwargs["in_shardings"] = in_shardings
        return jax.jit(step, **jit_kwargs)

    # -- dataset-driven training (reference executor.py:1593) -------------
    def train_from_dataset(self, program=None, dataset=None, scope=None,
                           thread=0, debug=False, fetch_list=None,
                           fetch_info=None, print_period=100):
        """Train over an entire Dataset (reference Executor.train_from_dataset
        executor.py:1593 → C++ MultiTrainer/HogwildWorker TrainFiles,
        hogwild_worker.cc:191).

        TPU-native shape: the reference spawns one op-loop thread per core
        because each CPU thread is a compute unit; on TPU the chip runs one
        XLA program at a time, so `thread` buys input overlap instead —
        batches are parsed/padded on host threads and prefetched into a
        bounded queue while the device executes the previous step. Sparse
        slots arrive as (values, lod) pairs and are padded to power-of-two
        buckets (static shapes — each bucket compiles once); a program var
        named `<slot>_lens` receives the true lengths (the dense+lengths
        LoD rewrite used across ops/sequence.py).
        """
        import queue as queue_mod
        import threading

        from .ir import default_main_program

        if dataset is None:
            raise ValueError("train_from_dataset requires a dataset")
        program = program or default_main_program()
        scope = scope or global_scope()
        block = program.global_block
        fetch_list = fetch_list or []
        fetch_info = fetch_info or [
            getattr(v, "name", str(v)) for v in fetch_list]

        q: queue_mod.Queue = queue_mod.Queue(maxsize=max(2, int(thread) * 2))
        _END = object()
        producer_error = []

        # multi-worker ingestion: `thread` producers over per-file dataset
        # shards (reference thread-per-DeviceWorker DataFeed channels);
        # batch->feed padding runs in the producer threads so the device
        # never waits on host-side parse/pad
        shards = (dataset.ingest_shards(int(thread))
                  if hasattr(dataset, "ingest_shards") and int(thread) > 1
                  else [dataset])

        def producer(shard):
            try:
                for batch in shard:
                    q.put(self._dataset_batch_to_feed(batch, block))
            except BaseException as e:  # surfaced in the consumer
                producer_error.append(e)
            finally:
                q.put(_END)

        producers = [threading.Thread(target=producer, args=(s,),
                                      daemon=True)
                     for s in shards]
        for t in producers:
            t.start()
        step = 0
        last_fetch = None
        pending = None  # one-batch lookahead so the final step is known
        ended = 0
        try:
            while True:
                feed = q.get()
                if feed is _END:
                    ended += 1
                    if ended < len(producers):
                        continue   # other shards still producing
                at_end = feed is _END
                feed, pending = pending, (None if at_end else feed)
                if feed is None or not feed:
                    if at_end:
                        break
                    continue
                final_step = at_end
                want_fetch = fetch_list and (
                    debug or final_step or step % print_period == 0)
                out = self.run(program, feed=feed,
                               fetch_list=fetch_list if want_fetch else None,
                               scope=scope)
                if want_fetch:
                    last_fetch = out
                    if debug:
                        msg = ", ".join(f"{n}={np.asarray(v).ravel()[:4]}"
                                        for n, v in zip(fetch_info, out))
                        print(f"[train_from_dataset] step {step}: {msg}")
                step += 1
                if at_end:
                    break
        finally:
            # unblock the producers (bounded queue) before joining, even
            # when a step raised mid-epoch
            while any(t.is_alive() for t in producers):
                try:
                    q.get(timeout=0.1)
                except queue_mod.Empty:
                    pass
            for t in producers:
                t.join()
        if producer_error:
            raise producer_error[0]
        return last_fetch

    def infer_from_dataset(self, program=None, dataset=None, scope=None,
                           thread=0, debug=False, fetch_list=None,
                           fetch_info=None, print_period=100):
        """Same loop as train_from_dataset over an inference program
        (reference executor.py:1491)."""
        return self.train_from_dataset(program, dataset, scope, thread,
                                       debug, fetch_list, fetch_info,
                                       print_period)

    @staticmethod
    def _dataset_batch_to_feed(batch, block):
        """Map a Dataset batch (slot -> dense array | (values, lod)) onto
        the program's data vars, padding ragged slots to pow-2 buckets."""
        feed = {}
        for name, val in batch.items():
            if isinstance(val, tuple):
                vals, lod = val
                rows = len(lod) - 1
                lens = np.diff(lod).astype(np.int64)
                longest = int(lens.max()) if rows else 1
                maxlen = 1 << max(0, int(longest - 1).bit_length())
                if np.issubdtype(vals.dtype, np.unsignedinteger):
                    vals = vals.astype(np.int64)
                dense = np.zeros((rows, maxlen), vals.dtype)
                for i in range(rows):
                    dense[i, :lens[i]] = vals[lod[i]:lod[i + 1]]
                if name in block.vars:
                    feed[name] = dense
                if f"{name}_lens" in block.vars:
                    feed[f"{name}_lens"] = lens
            elif name in block.vars:
                if np.issubdtype(getattr(val, "dtype", np.float32),
                                 np.unsignedinteger):
                    val = val.astype(np.int64)
                feed[name] = val
        return feed

    # -- startup-program path --------------------------------------------
    def run_startup(self, program: Program, scope: Optional[Scope] = None):
        """Run initializer ops eagerly, writing persistables to scope.
        (Executor.run on a startup program delegates here.)"""
        scope = scope or global_scope()
        seed = program.random_seed or random_mod.default_generator().initial_seed()
        ctx = ExecContext(rng_key=random_mod.make_key(seed))
        env = {n: scope.find_var(n) for n in program.global_block.vars
               if scope.find_var(n) is not None}
        env = run_block(program.global_block, env, ctx)
        for name, desc in program.global_block.vars.items():
            if desc.persistable and name in env and env[name] is not None:
                scope.set(name, env[name])
        return []
