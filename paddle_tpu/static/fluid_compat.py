"""fluid top-level long tail (reference python/paddle/fluid/__init__.py
__all__): place helpers, device_guard, the deprecated memory passes,
Generator, DataFeedDesc, trainer-desc facades, and version checks. Each
is the real capability under its fluid name — not a stub — wired to the
TPU-native subsystems (framework.place, framework.random, io.dataset,
executor.train_from_dataset)."""
from __future__ import annotations

import contextlib
import warnings
from typing import List, Optional

from ..framework.place import (CPUPlace, CUDAPinnedPlace, CUDAPlace,
                               TPUPlace)

__all__ = [
    "cpu_places", "cuda_places", "cuda_pinned_places", "xpu_places",
    "device_guard", "memory_optimize", "release_memory", "Generator",
    "DataFeedDesc", "TrainerDesc", "MultiTrainer", "DistMultiTrainer",
    "PipelineTrainer", "require_version", "load_op_library",
    "is_compiled_with_xpu",
]


def cpu_places(device_count: Optional[int] = None) -> List[CPUPlace]:
    if device_count is None:
        import os

        device_count = int(os.environ.get("CPU_NUM", 1))
    return [CPUPlace()] * device_count


def cuda_places(device_ids=None) -> List[CUDAPlace]:
    """On this framework an accelerator place IS the TPU chip
    (CUDAPlace subclasses TPUPlace for fluid-API parity)."""
    import jax

    if device_ids is None:
        try:
            device_ids = range(len(jax.devices()))
        except Exception:
            device_ids = [0]
    return [CUDAPlace(int(i)) for i in device_ids]


def cuda_pinned_places(device_count: Optional[int] = None):
    return [CUDAPinnedPlace()] * (device_count or 1)


def xpu_places(device_ids=None):
    return [TPUPlace(int(i)) for i in (device_ids or [0])]


def is_compiled_with_xpu() -> bool:
    return False


@contextlib.contextmanager
def device_guard(device: Optional[str] = None):
    """reference framework.py device_guard: ops appended inside the
    scope carry an `op_device` attr (the pipeline transpiler's stage
    assignment mechanism). The attr is recorded on the OpDesc; on a
    single chip execution ignores it, and the pipeline builder reads it
    back for stage splits."""
    from . import ir

    prev = getattr(ir, "_current_op_device", None)
    ir._current_op_device = device
    try:
        yield
    finally:
        ir._current_op_device = prev


def memory_optimize(input_program=None, skip_opt_set=None,
                    print_log=False, level=0, skip_grads=True):
    """Deprecated no-op, matching the reference v1.8 exactly
    (fluid/transpiler/memory_optimization_transpiler.py warns and
    returns): XLA buffer liveness analysis performs this role."""
    warnings.warn(
        "memory_optimize is deprecated and performs nothing; buffer "
        "reuse is handled by the XLA compiler", DeprecationWarning)


def release_memory(input_program=None, skip_opt_set=None):
    warnings.warn(
        "release_memory is deprecated and performs nothing",
        DeprecationWarning)


class Generator:
    """RNG generator handle (reference framework/generator.cc): seeds
    the framework PRNG stream."""

    def __init__(self, place=None):
        self.place = place
        self._seed = 0

    def manual_seed(self, seed: int):
        from ..framework import random as random_mod

        self._seed = int(seed)
        random_mod.seed(self._seed)
        return self

    def initial_seed(self) -> int:
        return self._seed

    seed = manual_seed


class DataFeedDesc:
    """reference fluid/data_feed_desc.py: wraps the protobuf-text slot
    description consumed by the C++ DataFeed. Parses the proto text
    into the SlotSpec list io.dataset uses, so a fluid-era desc file
    drives the same native MultiSlot parser."""

    def __init__(self, proto_file: str):
        self.proto_desc = open(proto_file).read() if proto_file else ""
        self._slots = self._parse(self.proto_desc)
        self._batch = 32
        self._pipe_command = ""

    @staticmethod
    def _parse(text: str):
        from ..io.dataset import SlotSpec

        slots, cur = [], {}
        for raw in text.splitlines():
            line = raw.strip()
            if line.startswith("slots {") or line.startswith("slot {"):
                cur = {}
            elif line.startswith("name:"):
                cur["name"] = line.split(":", 1)[1].strip().strip('"')
            elif line.startswith("type:"):
                cur["type"] = line.split(":", 1)[1].strip().strip('"')
            elif line.startswith("is_dense:"):
                cur["dense"] = "true" in line.split(":", 1)[1].lower()
            elif line.startswith("shape:"):
                cur.setdefault("shape", []).append(
                    int(line.split(":", 1)[1]))
            elif line.startswith("}") and cur.get("name"):
                t = cur.get("type", "uint64")
                dense_dim = (cur.get("shape") or [1])[0] \
                    if cur.get("dense") else None
                slots.append(SlotSpec(
                    cur["name"],
                    slot_type="float" if "float" in t else "uint64",
                    dense_dim=dense_dim))
                cur = {}
        return slots

    def slots(self):
        use = getattr(self, "_use", None)
        if use is not None:
            return [s for s in self._slots if s.name in use]
        return list(self._slots)

    def set_batch_size(self, batch_size: int):
        self._batch = batch_size

    def set_pipe_command(self, cmd: str):
        self._pipe_command = cmd

    def set_dense_slots(self, names):
        for s in self._slots:
            if s.name in names and s.dense_dim is None:
                s.dense_dim = 1

    def set_use_slots(self, names):
        self._use = list(names)

    def desc(self) -> str:
        return self.proto_desc


class TrainerDesc:
    """Trainer configuration facade (reference trainer_desc.py): the
    thread/device knobs executor.train_from_dataset consumes. The C++
    thread-per-DeviceWorker machinery is subsumed by the compiled step
    + ingestion producers (COVERAGE §2.1), so the desc carries the
    run configuration rather than an op-loop program."""

    _kind = "MultiTrainer"

    def __init__(self):
        self.thread_num = 1
        self.device_worker = "Hogwild"
        self.fleet_desc = None

    def set_thread(self, n: int):
        self.thread_num = int(n)

    def set_device_worker(self, name: str):
        self.device_worker = name

    def set_fleet_desc(self, desc):
        self.fleet_desc = desc


class MultiTrainer(TrainerDesc):
    _kind = "MultiTrainer"


class DistMultiTrainer(TrainerDesc):
    _kind = "DistMultiTrainer"


class PipelineTrainer(TrainerDesc):
    _kind = "PipelineTrainer"


def require_version(min_version: str, max_version: Optional[str] = None):
    """reference fluid/framework.py require_version: compare against
    the installed version, raising on mismatch."""
    import paddle_tpu

    def parse(v):
        import re

        out = []
        for p in str(v).split(".")[:3]:
            m = re.match(r"\d+", p)   # '1rc0' / '1-dev' -> 1
            if m:
                out.append(int(m.group()))
        return tuple(out)

    cur = parse(getattr(paddle_tpu, "__version__", "0.0.0"))
    if parse(min_version) > cur:
        raise RuntimeError(
            f"paddle_tpu {cur} does not satisfy minimum required "
            f"version {min_version}")
    if max_version is not None and parse(max_version) < cur:
        raise RuntimeError(
            f"paddle_tpu {cur} exceeds maximum supported version "
            f"{max_version}")


def load_op_library(lib_path: str):
    """reference fluid/framework.py load_op_library (custom C++ op .so).
    Custom native code plugs in through the C extension path here: the
    library is dlopened for its side effects; kernels it registers via
    the CPython API become visible to the op registry."""
    import ctypes

    return ctypes.CDLL(lib_path)
