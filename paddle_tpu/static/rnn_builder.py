"""StaticRNN / DynamicRNN step-graph builders.

Reference: /root/reference/python/paddle/fluid/layers/control_flow.py:449
(StaticRNN: a sub-block executed per time step by recurrent_op) and :2939
(DynamicRNN: the LoD-aware variant run by a C++ while loop over shrinking
batches). TPU-native design: the step body is CAPTURED as op descs once,
then REPLAYED per time step into the main program with systematic value
renaming — a statically unrolled graph that XLA fuses across steps (no
recurrent_op interpreter, no per-step kernel launches). DynamicRNN keeps
the dense+lengths rewrite used across ops/sequence.py: instead of LoD
batch shrinking, memories freeze and outputs zero out past each row's
length, which is bit-equivalent for the surviving positions.
"""
from __future__ import annotations

from contextlib import contextmanager
from typing import Dict, List, Optional

from ..utils import unique_name
from .ir import OpDesc, Variable, default_main_program
from .layers import _infer_outputs


class _Memory:
    def __init__(self, ph_name, init_name):
        self.ph = ph_name
        self.init = init_name
        self.update = None


class StaticRNN:
    """Build a step block once; unroll it over time at build time.

    Usage (reference control_flow.py StaticRNN docstring):

        rnn = StaticRNN()
        with rnn.step():
            xt = rnn.step_input(x)            # x: (T, B, D)
            prev = rnn.memory(init=h0)        # or shape=/batch_ref=
            h = ...ops over (xt, prev)...
            rnn.update_memory(prev, h)
            rnn.step_output(h)
        out = rnn()                            # (T, B, H)
    """

    def __init__(self, name: Optional[str] = None):
        self._program = default_main_program()
        self._inputs: List[tuple] = []      # (ph_name, source Variable)
        self._memories: List[_Memory] = []
        self._out_names: List[str] = []
        self._captured: Optional[List[OpDesc]] = None
        self._T: Optional[int] = None
        self._in_step = False
        self._init_ops: List[OpDesc] = []   # run once, before the unroll

    @property
    def _block(self):
        return self._program.current_block()

    @contextmanager
    def step(self):
        start = len(self._block.ops)
        self._step_start = start
        self._in_step = True
        try:
            yield self
        finally:
            self._in_step = False
            # lift the step body out of the program; rnn() replays it.
            # Memory-init chains (built by memory(batch_ref=...)) run
            # ONCE: splice them back in ahead of the unroll instead of
            # replaying dead copies every timestep.
            body = list(self._block.ops[start:])
            init_set = {id(op) for op in self._init_ops}
            self._captured = [op for op in body if id(op) not in init_set]
            del self._block.ops[start:]
            self._block.ops.extend(
                op for op in body if id(op) in init_set)

    def _require_step(self):
        if not self._in_step:
            raise RuntimeError("call inside `with rnn.step():`")

    def step_input(self, x: Variable) -> Variable:
        """Per-step slice of x along time (dim 0): (T, B, ...) -> (B, ...)."""
        self._require_step()
        T = int(x.shape[0])
        if self._T is None:
            self._T = T
        elif self._T != T:
            raise ValueError(f"step inputs disagree on T: {self._T} vs {T}")
        ph = unique_name.generate("srnn_in")
        self._block.create_var(name=ph, shape=tuple(x.shape[1:]),
                               dtype=x.desc.dtype)
        self._inputs.append((ph, x))
        return self._block.var(ph)

    def memory(self, init: Optional[Variable] = None, shape=None,
               batch_ref: Optional[Variable] = None, init_value=0.0,
               init_batch_dim_idx=0, ref_batch_dim_idx=0) -> Variable:
        """Recurrent state: `init` Variable, or (shape, batch_ref) with a
        constant init_value (reference StaticRNN.memory)."""
        self._require_step()
        if init is None:
            if shape is None or batch_ref is None:
                raise ValueError("memory() needs init= or shape=+batch_ref=")
            from . import layers as L

            mark = len(self._block.ops)
            # a step-input placeholder has no pre-loop value: derive the
            # batch dim from its SOURCE's t=0 slice so the init chain can
            # run once before the unroll
            ref = batch_ref
            hoistable = True
            matched = False
            for ph, src_v in self._inputs:
                if ph == batch_ref.name:
                    ref = L.squeeze(L.slice(src_v, axes=[0], starts=[0],
                                            ends=[1]), axes=[0])
                    matched = True
                    break
            if not matched:
                # batch_ref produced INSIDE the step body? Then the init
                # chain must stay in the body (replayed per step and
                # resolved through the rename map) — it cannot run before
                # the unroll
                step_outputs = {
                    n for op in self._block.ops[self._step_start:mark]
                    for names in op.outputs.values() for n in names}
                hoistable = batch_ref.name not in step_outputs
            # (B, 1) zeros derived from the ref, broadcast to shape[1:]
            # — keeps the dynamic batch dim symbolic
            feat = [int(s) for s in shape[1:]] if len(shape) > 1 else [1]
            zero = L.reduce_sum(
                L.scale(ref, scale=0.0), dim=list(
                    range(1, len(ref.shape))), keep_dim=False)
            zero = L.reshape(zero, [-1] + [1] * len(feat))
            from .layers_ext import expand as _expand

            init_v = L.scale(_expand(zero, [1] + feat), scale=1.0,
                             bias=float(init_value))
            if hoistable:
                self._init_ops.extend(self._block.ops[mark:])
        else:
            init_v = init
        ph = unique_name.generate("srnn_mem")
        self._block.create_var(name=ph, shape=tuple(init_v.shape),
                               dtype=init_v.desc.dtype)
        mem = _Memory(ph, init_v.name)
        self._memories.append(mem)
        return self._block.var(ph)

    def update_memory(self, mem: Variable, new: Variable):
        self._require_step()
        for m in self._memories:
            if m.ph == mem.name:
                m.update = new.name
                return
        raise ValueError(f"{mem.name} is not a StaticRNN memory")

    def step_output(self, o: Variable):
        self._require_step()
        self._out_names.append(o.name)

    def output(self, *outputs):
        for o in outputs:
            self.step_output(o)

    # -- unroll -----------------------------------------------------------
    @staticmethod
    def _resolve(rename: Dict[str, str], n: str, depth: int = 8) -> str:
        """Follow the rename chain to a live name. A memory placeholder
        maps to its init value's ORIGINAL name; when the init ops are
        themselves part of the captured step body (memory(batch_ref=...)
        builds them inside the step), that original name is re-suffixed
        on replay — one extra hop."""
        while n in rename and depth > 0:
            nxt = rename[n]
            if nxt == n:
                break
            n = nxt
            depth -= 1
        return n

    def _replay_step(self, t: int, rename: Dict[str, str]):
        """Append one timestep's copy of the captured descs, renaming
        step-local values; returns the final rename map for this t."""
        block = self._block
        for op in self._captured:
            new_in = {slot: [self._resolve(rename, n) for n in names]
                      for slot, names in op.inputs.items()}
            new_out = {}
            for slot, names in op.outputs.items():
                outs = []
                for n in names:
                    nn = f"{n}@t{t}"
                    rename[n] = nn
                    outs.append(nn)
                new_out[slot] = outs
            new_op = block.append_op(type=op.type, inputs=new_in,
                                     outputs=new_out, attrs=dict(op.attrs))
            _infer_outputs(block, new_op, {})
        return rename

    def _step_gate(self, t, rename):
        """Hook for DynamicRNN length masking; identity here."""
        return rename

    def __call__(self):
        if self._captured is None:
            raise RuntimeError("StaticRNN: no step block was built")
        if self._T is None:
            raise RuntimeError("StaticRNN: step_input was never called")
        from . import layers as L

        cur_mem = {m.ph: m.init for m in self._memories}
        collected: Dict[str, List[str]] = {n: [] for n in self._out_names}
        for t in range(self._T):
            rename: Dict[str, str] = dict(cur_mem)
            for ph, src in self._inputs:
                xt = L.slice(src, axes=[0], starts=[t], ends=[t + 1])
                xt = L.squeeze(xt, axes=[0])
                rename[ph] = xt.name
            rename = self._replay_step(t, rename)
            rename = self._step_gate(t, rename)
            for m in self._memories:
                if m.update is None:
                    raise RuntimeError(
                        f"memory {m.ph} was never update_memory()'d")
                cur_mem[m.ph] = self._resolve(rename, m.update)
            for n in self._out_names:
                collected[n].append(self._resolve(rename, n))

        outs = []
        for n in self._out_names:
            vs = [self._block.var(nm) for nm in collected[n]]
            outs.append(L.stack(vs, axis=0))       # (T, B, ...)
        return outs[0] if len(outs) == 1 else tuple(outs)


class DynamicRNN(StaticRNN):
    """Variable-length step builder (reference control_flow.py:2939).

    The reference shrinks the batch per step following LoD; this build
    keeps the batch dense and uses the sequence's lengths: memories hold
    their previous value and outputs zero out at positions past each
    row's length — identical results for all valid positions, static
    shapes for XLA. step_input takes (x, lengths) with x (T, B, ...) and
    lengths (B,) int; `output` values come back (T, B, ...) zero-padded.
    """

    def __init__(self, name: Optional[str] = None):
        super().__init__(name)
        self._lengths: Optional[Variable] = None

    def step_input(self, x: Variable, lengths: Optional[Variable] = None,
                   level=0):
        if lengths is not None:
            self._lengths = lengths
        return super().step_input(x)

    def _mask_at(self, t):
        """(B, 1) float mask: 1 where t < length."""
        from . import layers as L

        tv = L.fill_constant([1], "int64", t)
        m = L.cast(L.less_than(tv, self._lengths), "float32")
        return L.reshape(m, [-1, 1])

    def _step_gate(self, t, rename):
        if self._lengths is None:
            return rename
        from . import layers as L

        mask = self._mask_at(t)
        one = L.fill_constant([1], "float32", 1.0)
        keep = L.elementwise_sub(one, mask)

        def fit(m2, value):
            # broadcast the (B, 1) mask against any-rank (B, ...) value
            rank = len(value.shape)
            if rank == 2:
                return m2
            if rank == 1:
                return L.reshape(m2, [-1])
            return L.reshape(m2, [-1] + [1] * (rank - 1))

        for m in self._memories:
            if m.update is None:
                raise RuntimeError(
                    f"memory {m.ph} was never update_memory()'d")
            new = self._block.var(self._resolve(rename, m.update))
            prev = self._block.var(self._resolve(rename, m.ph))
            mk = fit(mask, new)
            gated = L.elementwise_add(
                L.elementwise_mul(new, mk),
                L.elementwise_mul(prev, fit(keep, prev)))
            rename[m.update] = gated.name
        for n in self._out_names:
            ov = self._block.var(self._resolve(rename, n))
            rename[n] = L.elementwise_mul(ov, fit(mask, ov)).name
        return rename

    drnn_output = StaticRNN.output
