"""Composed static-graph building blocks (reference
python/paddle/fluid/nets.py: simple_img_conv_pool :29, img_conv_group
:141, sequence_conv_pool :256, glu :328, scaled_dot_product_attention
:372). Same compositions over this package's static layers; the
LoD-sequence input of sequence_conv_pool becomes dense (N, L, D) plus an
optional mask, per the framework-wide dense+lengths design.
"""
from __future__ import annotations

from . import layers


def simple_img_conv_pool(input, num_filters, filter_size, pool_size,
                         pool_stride, pool_padding=0, pool_type="max",
                         global_pooling=False, conv_stride=1,
                         conv_padding=0, conv_dilation=1, conv_groups=1,
                         param_attr=None, bias_attr=None, act=None,
                         use_cudnn=True):
    conv_out = layers.conv2d(input, num_filters, filter_size,
                             stride=conv_stride, padding=conv_padding,
                             dilation=conv_dilation, groups=conv_groups,
                             param_attr=param_attr, bias_attr=bias_attr,
                             act=act)
    return layers.pool2d(conv_out, pool_size, pool_type=pool_type,
                         pool_stride=pool_stride,
                         pool_padding=pool_padding,
                         global_pooling=global_pooling)


def img_conv_group(input, conv_num_filter, pool_size, conv_padding=1,
                   conv_filter_size=3, conv_act=None, param_attr=None,
                   conv_with_batchnorm=False, conv_batchnorm_drop_rate=0.0,
                   pool_stride=1, pool_type="max", use_cudnn=True):
    """Chain of conv(+bn)(+dropout) blocks followed by one pool — the VGG
    block builder (nets.py:141)."""
    if isinstance(conv_num_filter, int):
        conv_num_filter = [conv_num_filter]

    def per_conv(arg, default=None):
        if isinstance(arg, (list, tuple)):
            return list(arg)
        return [arg] * len(conv_num_filter)

    paddings = per_conv(conv_padding)
    fsizes = per_conv(conv_filter_size)
    acts = per_conv(conv_act)
    attrs = per_conv(param_attr)
    with_bn = per_conv(conv_with_batchnorm)
    drops = per_conv(conv_batchnorm_drop_rate)

    tmp = input
    for i, nf in enumerate(conv_num_filter):
        local_act = None if with_bn[i] else acts[i]
        tmp = layers.conv2d(tmp, nf, fsizes[i], padding=paddings[i],
                            param_attr=attrs[i], act=local_act)
        if with_bn[i]:
            tmp = layers.batch_norm(tmp, act=acts[i])
            if drops[i] > 0.0:
                tmp = layers.dropout(tmp, dropout_prob=drops[i])
    return layers.pool2d(tmp, pool_size, pool_type=pool_type,
                         pool_stride=pool_stride)


def sequence_conv_pool(input, num_filters, filter_size, param_attr=None,
                       act="sigmoid", pool_type="max", bias_attr=None,
                       mask=None):
    """1-D sequence conv + temporal pool (nets.py:256). `input` is dense
    (N, L, D); the conv is built from shifted slices + fc (small
    filter_size), replacing the LoD sequence_conv kernel; `mask`
    (N, L, 1) excludes padded steps from the pool."""
    shape = input.shape
    L, D = shape[1], shape[2]
    if L is None or L < 0:
        raise ValueError(
            "sequence_conv_pool needs a static time dimension; declare "
            "the input as data(name, [-1, L, D]) with concrete L")
    if mask is not None:
        # zero padded steps BEFORE windowing: the reference LoD conv never
        # reads past a sequence's end (zero boundary padding)
        input = layers.elementwise_mul(input, mask)
    half = (filter_size - 1) // 2
    # gather the filter_size-wide context at every step via shifted,
    # zero-padded slices along time — XLA fuses these into one window op
    ctx_parts = []
    for off in range(-half, filter_size - half):
        if off < 0:
            pad = layers.fill_constant([1], input.dtype, 0.0)
            body = layers.slice(input, axes=[1], starts=[0], ends=[L + off])
            zero = layers.elementwise_mul(
                layers.slice(input, axes=[1], starts=[0], ends=[-off]),
                pad)
            part = layers.concat([zero, body], axis=1)
        elif off > 0:
            pad = layers.fill_constant([1], input.dtype, 0.0)
            body = layers.slice(input, axes=[1], starts=[off], ends=[L])
            zero = layers.elementwise_mul(
                layers.slice(input, axes=[1], starts=[0], ends=[off]), pad)
            part = layers.concat([body, zero], axis=1)
        else:
            part = input
        ctx_parts.append(part)
    ctx = layers.concat(ctx_parts, axis=2)          # (N, L, fs*D)
    conv = layers.fc(ctx, num_filters, num_flatten_dims=2,
                     param_attr=param_attr, bias_attr=bias_attr, act=act)
    if mask is not None:
        if pool_type == "max":
            neg = layers.scale(
                layers.elementwise_sub(
                    layers.fill_constant([1], conv.dtype, 1.0), mask),
                scale=-1e9)
            conv = layers.elementwise_add(
                layers.elementwise_mul(conv, mask), neg)
        else:
            conv = layers.elementwise_mul(conv, mask)
    if pool_type == "max":
        return layers.reduce_max(conv, dim=[1])
    pooled = layers.reduce_sum(conv, dim=[1])
    if mask is not None:
        count = layers.elementwise_max(
            layers.reduce_sum(mask, dim=[1]),
            layers.fill_constant([1], conv.dtype, 1.0))
        pooled = layers.elementwise_div(pooled, count)
        return pooled
    return layers.scale(pooled, scale=1.0 / L)


def glu(input, dim=-1):
    """Gated linear unit: split in two along dim, a * sigmoid(b)
    (nets.py:328)."""
    a, b = layers.split(input, num_or_sections=2, dim=dim)
    return layers.elementwise_mul(a, layers.sigmoid(b))


def scaled_dot_product_attention(queries, keys, values, num_heads=1,
                                 dropout_rate=0.0):
    """Multi-head scaled dot-product attention over static Variables
    (nets.py:372): (N, L, D) q/k/v → (N, Lq, Dv)."""
    dk = queries.shape[-1]

    def split_heads(x):
        n, l, d = x.shape
        y = layers.reshape(x, [-1, l, num_heads, d // num_heads])
        return layers.transpose(y, [0, 2, 1, 3])    # (N, H, L, d)

    q, k, v = split_heads(queries), split_heads(keys), split_heads(values)
    scores = layers.matmul(q, layers.transpose(k, [0, 1, 3, 2]))
    scores = layers.scale(scores, scale=1.0 / (dk // num_heads) ** 0.5)
    weights = layers.softmax(scores)
    if dropout_rate > 0.0:
        weights = layers.dropout(weights, dropout_prob=dropout_rate)
    ctx = layers.matmul(weights, v)                  # (N, H, Lq, dv)
    ctx = layers.transpose(ctx, [0, 2, 1, 3])
    n, lq = ctx.shape[0], ctx.shape[1]
    dv = values.shape[-1]
    return layers.reshape(ctx, [-1, lq, dv])
