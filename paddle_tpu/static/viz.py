"""Program visualization.

Parity with the reference's graph tooling: ir/graph_viz_pass.cc (Graph →
Graphviz dot) and python/paddle/fluid/debugger.py draw_block_graphviz.
TPU-native addition: dump the compiled view too — `hlo_text` lowers a
jittable function and returns its StableHLO, which is the IR that actually
runs (the equivalent of inspecting the post-pass ir::Graph).
"""
from __future__ import annotations

from typing import Optional


def _esc(s: str) -> str:
    return s.replace('"', '\\"')


def program_to_dot(program, block_idx: int = 0,
                   max_var_label: int = 40) -> str:
    """Render one block of a Program as a Graphviz dot string.

    Ops are boxes, vars are ellipses (parameters shaded), edges follow
    data flow — the layout of graph_viz_pass.cc's marked nodes.
    """
    block = program.blocks[block_idx]
    lines = ["digraph G {", "  rankdir=TB;",
             '  node [fontsize=10, fontname="helvetica"];']
    var_nodes = {}

    def var_node(name):
        if name not in var_nodes:
            vid = f"var_{len(var_nodes)}"
            var_nodes[name] = vid
            desc = block.vars.get(name)
            label = name[:max_var_label]
            shape_info = ""
            style = ""
            if desc is not None:
                shape_info = f"\\n{getattr(desc, 'shape', ())}"
                if getattr(desc, "persistable", False):
                    style = ', style=filled, fillcolor="lightblue"'
            lines.append(
                f'  {vid} [label="{_esc(label)}{shape_info}", '
                f'shape=ellipse{style}];')
        return var_nodes[name]

    for i, op in enumerate(block.ops):
        oid = f"op_{i}"
        lines.append(
            f'  {oid} [label="{_esc(op.type)}", shape=box, '
            'style=filled, fillcolor="seagreen1"];')
        for name in op.input_names():
            lines.append(f"  {var_node(name)} -> {oid};")
        for name in op.output_names():
            lines.append(f"  {oid} -> {var_node(name)};")
    lines.append("}")
    return "\n".join(lines)


def save_dot(program, path: str, block_idx: int = 0) -> str:
    """Write the dot file (reference FLAGS_print_sub_graph_dir flavor);
    render with `dot -Tpng` out-of-band if graphviz is installed."""
    dot = program_to_dot(program, block_idx)
    with open(path, "w") as f:
        f.write(dot)
    return path


def hlo_text(fn, *example_args, static_argnums=(),
             stage: str = "stablehlo") -> str:
    """Lower a jittable callable and return its IR text.

    stage: "stablehlo" (jaxpr→StableHLO, pre-XLA-fusion) or "optimized"
    (post-compile HLO — what the TPU actually executes; the analogue of
    the reference's post-pass ir::Graph dump).
    """
    import jax

    lowered = jax.jit(fn, static_argnums=static_argnums).lower(*example_args)
    if stage == "optimized":
        return lowered.compile().as_text()
    return lowered.as_text()
