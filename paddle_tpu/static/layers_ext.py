"""Static-graph layer long tail (reference fluid/layers/nn.py breadth).

The reference hand-writes an OpDesc builder + C++ InferShape + CPU/CUDA
kernels per function; here each static op delegates to the SAME jnp
implementation the eager API uses (paddle_tpu.nn.functional /
paddle_tpu.ops), registered as a static kernel. Shape inference is
jax.eval_shape over that kernel (static/layers.py) and gradients come
from the traced-vjp append_backward — so one implementation serves
eager, jit, and static modes (the reference needed three).

Facades keep the reference fluid.layers signatures
(/root/reference/python/paddle/fluid/layers/nn.py) so static model code
ports unchanged.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.tensor import Tensor
from .initializer import Constant as _const
from .kernels import KERNELS, _out, _x, kernel
from .layers import LayerHelper, _append_simple


def _apply_act(out, act):
    if act:
        out = _append_simple(act, {"X": [out.name]})
    return out


def _unwrap(x):
    return x.value if isinstance(x, Tensor) else x


def _unwrap_tree(out):
    if isinstance(out, (tuple, list)):
        return [_unwrap(o) for o in out]
    return [_unwrap(out)]


def _register_delegate(op_type, fn, in_slots=("X",), out_slots=("Out",),
                       list_slot=None, needs_rng=False):
    """Register a static kernel that calls an eager jnp implementation.

    in_slots: input slot order passed positionally. A missing optional
    slot binds None at ITS OWN position (trailing Nones are trimmed) —
    skipping it would shift every later slot one position left and
    silently bind the wrong arrays. list_slot: this slot's full array
    LIST is the (single) positional argument. attrs become keyword
    arguments verbatim.
    """
    if op_type in KERNELS:
        return

    @kernel(op_type)
    def k(ins, attrs, ctx, _fn=fn):
        if list_slot is not None:
            args = [list(ins[list_slot])]
        else:
            args = [ins[s][0] if (s in ins and ins[s]) else None
                    for s in in_slots]
            while args and args[-1] is None:
                args.pop()
        kw = dict(attrs)
        if needs_rng:
            kw["_rng_key"] = ctx.rng_key
        out = _fn(*args, **kw)
        outs = _unwrap_tree(out)
        if len(out_slots) == 1:
            return {out_slots[0]: outs}
        return {s: [o] for s, o in zip(out_slots, outs)}


def _delegate(op_type, fn, n_in=1, in_slots=None, out_slots=("Out",),
              list_slot=None, needs_rng=False):
    """One-stop: register kernel + return a facade builder."""
    slots = in_slots or ("X", "Y", "Z")[:n_in]
    _register_delegate(op_type, fn, in_slots=slots, out_slots=out_slots,
                       list_slot=list_slot, needs_rng=needs_rng)

    def build(*xs, **attrs):
        if list_slot is not None:
            inputs = {list_slot: [v.name for v in xs[0]]}
        else:
            inputs = {s: [v.name] for s, v in zip(slots, xs)}
        return _append_simple(op_type, inputs, attrs, out_slots=out_slots)

    return build


# ---------------------------------------------------------------------------
# activations (reference nn.py elu:9212.., ops.py generated activations)
# ---------------------------------------------------------------------------
from ..nn import functional as F  # noqa: E402
from .. import ops as O  # noqa: E402


def _act(op_type, fn, n_in=1):
    return _delegate(op_type, fn, n_in=n_in)


_elu = _act("elu_s", lambda x, alpha=1.0: F.elu(x, alpha))
_relu6 = _act("relu6_s", lambda x, threshold=6.0: jnp.clip(x, 0, threshold))
_pow = _act("pow_s", lambda x, factor=1.0: jnp.power(x, factor))
_stanh = _act("stanh_s",
              lambda x, scale_a=0.67, scale_b=1.7159:
              scale_b * jnp.tanh(scale_a * x))
_hard_sigmoid = _act("hard_sigmoid_s",
                     lambda x, slope=0.2, offset=0.5:
                     jnp.clip(slope * x + offset, 0.0, 1.0))
_swish = _act("swish_s", lambda x, beta=1.0: x * jax.nn.sigmoid(beta * x))
_brelu = _act("brelu_s",
              lambda x, t_min=0.0, t_max=24.0: jnp.clip(x, t_min, t_max))
_soft_relu = _act("soft_relu_s",
                  lambda x, threshold=40.0:
                  jnp.log1p(jnp.exp(jnp.clip(x, -threshold, threshold))))
_hard_swish = _act("hard_swish_s",
                   lambda x, threshold=6.0, scale=6.0, offset=3.0:
                   x * jnp.clip(x + offset, 0, threshold) / scale)
_mish = _act("mish_s",
             lambda x, threshold=20.0: x * jnp.tanh(jax.nn.softplus(x)))
_selu = _act("selu_s",
             lambda x, scale=1.0507009873554805, alpha=1.6732632423543772:
             scale * jnp.where(x > 0, x, alpha * (jnp.exp(x) - 1)))
_sign = _act("sign_s", jnp.sign)


def elu(x, alpha=1.0, name=None):
    return _elu(x, alpha=alpha)


def relu6(x, threshold=6.0, name=None):
    return _relu6(x, threshold=threshold)


def pow(x, factor=1.0, name=None):
    return _pow(x, factor=factor)


def stanh(x, scale_a=0.67, scale_b=1.7159, name=None):
    return _stanh(x, scale_a=scale_a, scale_b=scale_b)


def hard_sigmoid(x, slope=0.2, offset=0.5, name=None):
    return _hard_sigmoid(x, slope=slope, offset=offset)


def swish(x, beta=1.0, name=None):
    return _swish(x, beta=beta)


def brelu(x, t_min=0.0, t_max=24.0, name=None):
    return _brelu(x, t_min=t_min, t_max=t_max)


def soft_relu(x, threshold=40.0, name=None):
    return _soft_relu(x, threshold=threshold)


def hard_swish(x, threshold=6.0, scale=6.0, offset=3.0, name=None):
    return _hard_swish(x, threshold=threshold, scale=scale, offset=offset)


def mish(x, threshold=20.0, name=None):
    return _mish(x, threshold=threshold)


def selu(x, scale=1.0507009873554805, alpha=1.6732632423543772, name=None):
    return _selu(x, scale=scale, alpha=alpha)


def sign(x, name=None):
    return _sign(x)


def prelu(x, mode="all", param_attr=None, name=None):
    """PReLU with a learnable alpha parameter (nn.py prelu)."""
    helper = LayerHelper("prelu_s")
    # alpha shape by mode: all -> 1, channel -> C, element -> x.shape[1:]
    if mode == "all":
        shape = [1]
    elif mode == "channel":
        shape = [int(x.shape[1])]
    else:
        shape = [int(s) for s in x.shape[1:]]
    alpha = helper.create_parameter(
        shape=shape, dtype="float32", attr=param_attr,
        initializer=_const(0.25))
    _register_delegate("prelu_s", _prelu_fn, in_slots=("X", "Alpha"))
    return _append_simple("prelu_s",
                          {"X": [x.name], "Alpha": [alpha.name]},
                          {"mode": mode})


def _prelu_fn(x, alpha, mode="all"):
    if mode == "channel":
        alpha = alpha.reshape((1, -1) + (1,) * (x.ndim - 2))
    elif mode == "element":
        alpha = alpha.reshape((1,) + x.shape[1:])
    return jnp.where(x > 0, x, alpha * x)


# ---------------------------------------------------------------------------
# elementwise / logical / reduce long tail
# ---------------------------------------------------------------------------
from .layers import _elementwise_binary  # noqa: E402


def elementwise_pow(x, y, axis=-1, act=None, name=None):
    return _elementwise_binary(x, y, "elementwise_pow")


def elementwise_mod(x, y, axis=-1, act=None, name=None):
    return _elementwise_binary(x, y, "elementwise_mod")


def elementwise_floordiv(x, y, axis=-1, act=None, name=None):
    return _elementwise_binary(x, y, "elementwise_floordiv")


_logical_or = _delegate("logical_or_s", jnp.logical_or, n_in=2)
_logical_xor = _delegate("logical_xor_s", jnp.logical_xor, n_in=2)


def logical_or(x, y, out=None, name=None):
    return _logical_or(x, y)


def logical_xor(x, y, out=None, name=None):
    return _logical_xor(x, y)


def _reduce(op_type, jfn):
    build = _delegate(op_type, lambda x, dim=None, keep_dim=False:
                      jfn(x, axis=None if dim is None else tuple(dim),
                          keepdims=keep_dim))

    def f(input, dim=None, keep_dim=False, name=None):
        if dim is not None and not isinstance(dim, (list, tuple)):
            dim = [dim]
        return build(input, dim=dim, keep_dim=keep_dim)

    return f


reduce_prod = _reduce("reduce_prod_s", jnp.prod)
reduce_all = _reduce("reduce_all_s", jnp.all)
reduce_any = _reduce("reduce_any_s", jnp.any)

_where_idx = _delegate("where_index_s",
                       lambda c: jnp.stack(
                           jnp.nonzero(c, size=int(np.prod(c.shape)),
                                       fill_value=-1), axis=1))


def where(condition, name=None):
    """Indices of true elements, padded with -1 rows to the static size
    (nonzero is dynamic in the reference; TPU needs fixed shapes)."""
    return _where_idx(condition)


import functools as _functools  # noqa: E402

# NB: builtins `sum`/`pow` are shadowed by the facades below — the kernel
# must not reference them
_sum_n = _delegate("sum_n_s",
                   lambda xs: _functools.reduce(jnp.add, xs),
                   list_slot="X")


def sum(x, name=None):
    xs = x if isinstance(x, (list, tuple)) else [x]
    return _sum_n(xs)


# ---------------------------------------------------------------------------
# shape / indexing / manipulation
# ---------------------------------------------------------------------------
_shape = _delegate("shape_s",
                   lambda x: jnp.asarray(x.shape, jnp.int32))
_rank = _delegate("rank_s", lambda x: jnp.asarray(x.ndim, jnp.int32))
_size = _delegate("size_s",
                  lambda x: jnp.asarray(int(np.prod(x.shape)), jnp.int64))


def shape(input):
    return _shape(input)


def rank(input):
    return _rank(input)


def size(input):
    return _size(input)


_unstack = None


def unstack(x, axis=0, num=None):
    n = num if num is not None else int(x.shape[axis])
    op_type = f"unstack_{n}_s"
    _register_delegate(
        op_type,
        lambda a, axis=0, num=1: [jnp.squeeze(s, axis)
                                  for s in jnp.split(a, num, axis)],
        out_slots=tuple(f"Y{i}" for i in range(n)))
    outs = _append_simple(op_type, {"X": [x.name]},
                          {"axis": axis, "num": n},
                          out_slots=tuple(f"Y{i}" for i in range(n)))
    return list(outs) if isinstance(outs, tuple) else [outs]


unbind = unstack


_expand = _delegate("expand_s",
                    lambda x, expand_times=(): jnp.tile(x, expand_times))


def expand(x, expand_times, name=None):
    return _expand(x, expand_times=tuple(int(t) for t in expand_times))


def expand_as(x, target_tensor, name=None):
    times = tuple(int(t) // int(s) for t, s in
                  zip(target_tensor.shape, x.shape))
    return _expand(x, expand_times=times)


_strided_slice = _delegate(
    "strided_slice_s",
    lambda x, axes=(), starts=(), ends=(), strides=():
    x[tuple(np.s_[s:e:st] if i in axes else np.s_[:]
            for i, (s, e, st) in enumerate(
                _expand_slice_args(x.ndim, axes, starts, ends, strides)))])


def _expand_slice_args(ndim, axes, starts, ends, strides):
    full = [(0, None, 1)] * ndim
    for ax, s, e, st in zip(axes, starts, ends, strides):
        full[ax] = (s, e, st)
    return full


def strided_slice(input, axes, starts, ends, strides, name=None):
    return _strided_slice(input, axes=tuple(axes), starts=tuple(starts),
                          ends=tuple(ends), strides=tuple(strides))


_gather_nd = _delegate("gather_nd_s",
                       lambda x, index: O.gather_nd(x, index),
                       in_slots=("X", "Index"))


def gather_nd(input, index, name=None):
    return _gather_nd(input, index)


_scatter = _delegate("scatter_s",
                     lambda x, ids, updates, overwrite=True:
                     O.scatter(x, ids, updates, overwrite=overwrite),
                     in_slots=("X", "Ids", "Updates"))


def scatter(input, index, updates, name=None, overwrite=True):
    return _scatter(input, index, updates, overwrite=overwrite)


_scatter_nd_add = _delegate("scatter_nd_add_s",
                            lambda x, index, updates:
                            O.scatter_nd_add(x, index, updates),
                            in_slots=("X", "Index", "Updates"))


def scatter_nd_add(ref, index, updates, name=None):
    return _scatter_nd_add(ref, index, updates)


def scatter_nd(index, updates, shape, name=None):
    helper = LayerHelper("scatter_nd_s")
    _register_delegate("scatter_nd_s",
                       lambda index, updates, shape=():
                       O.scatter_nd(index, updates, shape),
                       in_slots=("Index", "Updates"))
    return _append_simple("scatter_nd_s",
                          {"Index": [index.name], "Updates": [updates.name]},
                          {"shape": tuple(int(s) for s in shape)})


_gather_tree = _delegate("gather_tree_s",
                         lambda ids, parents: O.gather_tree(ids, parents),
                         in_slots=("Ids", "Parents"))


def gather_tree(ids, parents):
    return _gather_tree(ids, parents)


_shard_index = _delegate(
    "shard_index_s",
    lambda x, index_num=0, nshards=1, shard_id=0, ignore_value=-1:
    O.shard_index(x, index_num, nshards, shard_id, ignore_value))


def shard_index(input, index_num, nshards, shard_id, ignore_value=-1):
    return _shard_index(input, index_num=index_num, nshards=nshards,
                        shard_id=shard_id, ignore_value=ignore_value)


# ---------------------------------------------------------------------------
# padding / cropping
# ---------------------------------------------------------------------------
_pad = _delegate("pad_s",
                 lambda x, paddings=(), pad_value=0.0:
                 jnp.pad(x, [(paddings[2 * i], paddings[2 * i + 1])
                             for i in range(x.ndim)],
                         constant_values=pad_value))


def pad(x, paddings, pad_value=0.0, name=None):
    return _pad(x, paddings=tuple(int(p) for p in paddings),
                pad_value=float(pad_value))


_pad2d = _delegate(
    "pad2d_s",
    # fluid pad2d order is (top, bottom, left, right); F.pad wants
    # (left, right, top, bottom)
    lambda x, paddings=(0, 0, 0, 0), mode="constant", pad_value=0.0,
    data_format="NCHW": F.pad(
        x, [paddings[2], paddings[3], paddings[0], paddings[1]],
        mode={"constant": "constant", "reflect": "reflect",
              "edge": "replicate"}[mode],
        value=pad_value, data_format=data_format))


def pad2d(input, paddings=(0, 0, 0, 0), mode="constant", pad_value=0.0,
          data_format="NCHW", name=None):
    return _pad2d(input, paddings=tuple(int(p) for p in paddings),
                  mode=mode, pad_value=float(pad_value),
                  data_format=data_format)


_pad_constant_like = _delegate(
    "pad_constant_like_s",
    lambda x, y, pad_value=0.0: O.pad_constant_like(x, y, pad_value),
    in_slots=("X", "Y"))


def pad_constant_like(x, y, pad_value=0.0, name=None):
    return _pad_constant_like(x, y, pad_value=float(pad_value))


_crop_tensor = _delegate(
    "crop_tensor_s",
    lambda x, shape=(), offsets=():
    jax.lax.dynamic_slice(x, offsets, shape))


def crop_tensor(x, shape=None, offsets=None, name=None):
    if offsets is None:
        offsets = [0] * len(x.shape)
    return _crop_tensor(x, shape=tuple(int(s) for s in shape),
                        offsets=tuple(int(o) for o in offsets))


def crop(x, shape=None, offsets=None, name=None):
    return crop_tensor(x, shape=shape, offsets=offsets, name=name)


# ---------------------------------------------------------------------------
# normalization / feature ops
# ---------------------------------------------------------------------------
_l2_normalize = _delegate(
    "l2_normalize_s",
    lambda x, axis=-1, epsilon=1e-12:
    x / jnp.sqrt(jnp.maximum(jnp.sum(x * x, axis=axis, keepdims=True),
                             epsilon)))


def l2_normalize(x, axis, epsilon=1e-12, name=None):
    return _l2_normalize(x, axis=axis, epsilon=epsilon)


_label_smooth = _delegate(
    "label_smooth_s",
    lambda label, prior_dist=None, epsilon=0.1:
    F.label_smooth(label, prior_dist, epsilon),
    in_slots=("X", "PriorDist"))


def label_smooth(label, prior_dist=None, epsilon=0.1, dtype="float32",
                 name=None):
    if prior_dist is not None:
        return _label_smooth(label, prior_dist, epsilon=float(epsilon))
    return _label_smooth(label, epsilon=float(epsilon))


_clip_by_norm = _delegate(
    "clip_by_norm_s",
    lambda x, max_norm=1.0:
    x * jnp.minimum(1.0, max_norm /
                    jnp.maximum(jnp.sqrt(jnp.sum(x * x)), 1e-12)))


def clip_by_norm(x, max_norm, name=None):
    return _clip_by_norm(x, max_norm=float(max_norm))


_maxout = _delegate("maxout_s",
                    lambda x, groups=1, axis=1: F.maxout(x, groups, axis))


def maxout(x, groups, name=None, axis=1):
    return _maxout(x, groups=groups, axis=axis)


_space_to_depth = _delegate(
    "space_to_depth_s",
    lambda x, blocksize=2: O.space_to_depth(x, blocksize))


def space_to_depth(x, blocksize, name=None):
    return _space_to_depth(x, blocksize=blocksize)


_pixel_shuffle = _delegate(
    "pixel_shuffle_s",
    lambda x, upscale_factor=1: F.pixel_shuffle(x, upscale_factor))


def pixel_shuffle(x, upscale_factor):
    return _pixel_shuffle(x, upscale_factor=upscale_factor)


_shuffle_channel = _delegate(
    "shuffle_channel_s",
    lambda x, group=1: O.shuffle_channel(x, group))


def shuffle_channel(x, group, name=None):
    return _shuffle_channel(x, group=group)


_temporal_shift = _delegate(
    "temporal_shift_s",
    lambda x, seg_num=1, shift_ratio=0.25:
    F.temporal_shift(x, seg_num, shift_ratio))


def temporal_shift(x, seg_num, shift_ratio=0.25, name=None):
    return _temporal_shift(x, seg_num=seg_num, shift_ratio=shift_ratio)


_affine_channel = _delegate(
    "affine_channel_s",
    lambda x, scale, bias, data_layout="NCHW":
    F.affine_channel(x, scale, bias, data_layout),
    in_slots=("X", "Scale", "Bias"))


def affine_channel(x, scale=None, bias=None, data_layout="NCHW", name=None,
                   act=None):
    out = _affine_channel(x, scale, bias, data_layout=data_layout)
    return _apply_act(out, act)


_row_conv = _delegate("row_conv_s",
                      lambda x, w: F.row_conv(x, w),
                      in_slots=("X", "Filter"))


def row_conv(input, future_context_size, param_attr=None, act=None,
             name=None):
    helper = LayerHelper("row_conv_s")
    w = helper.create_parameter(
        shape=[future_context_size + 1, int(input.shape[-1])],
        dtype="float32", attr=param_attr)
    out = _append_simple("row_conv_s",
                         {"X": [input.name], "Filter": [w.name]})
    return _apply_act(out, act)


def multiplex(inputs, index, name=None):
    op_type = f"multiplex_{len(inputs)}_s"
    _register_delegate(
        op_type,
        lambda index, *xs: O.multiplex(list(xs), index),
        in_slots=("Ids",) + tuple(f"X{i}" for i in range(len(inputs))))
    ins = {"Ids": [index.name]}
    for i, v in enumerate(inputs):
        ins[f"X{i}"] = [v.name]
    return _append_simple(op_type, ins, {})


# ---------------------------------------------------------------------------
# losses / misc math
# ---------------------------------------------------------------------------
_smooth_l1 = _delegate(
    "smooth_l1_s",
    lambda x, y, sigma=1.0: _smooth_l1_fn(x, y, sigma),
    in_slots=("X", "Y"))


def _smooth_l1_fn(x, y, sigma):
    s2 = sigma * sigma
    diff = jnp.abs(x - y)
    loss = jnp.where(diff < 1.0 / s2, 0.5 * s2 * diff * diff,
                     diff - 0.5 / s2)
    return jnp.sum(loss.reshape(loss.shape[0], -1), axis=1, keepdims=True)


def smooth_l1(x, y, inside_weight=None, outside_weight=None, sigma=1.0):
    return _smooth_l1(x, y, sigma=float(sigma))


_dice_loss = _delegate("dice_loss_s",
                       lambda input, label, epsilon=1e-5:
                       F.dice_loss(input, label, epsilon),
                       in_slots=("X", "Label"))


def dice_loss(input, label, epsilon=1e-5, name=None):
    return _dice_loss(input, label, epsilon=epsilon)


_log_loss = _delegate("log_loss_s",
                      lambda input, label, epsilon=1e-4:
                      F.log_loss(input, label, epsilon),
                      in_slots=("Predicted", "Labels"))


def log_loss(input, label, epsilon=1e-4, name=None):
    return _log_loss(input, label, epsilon=epsilon)


_add_position_encoding = _delegate(
    "add_position_encoding_s",
    lambda x, alpha=1.0, beta=1.0: O.add_position_encoding(x, alpha, beta))


def add_position_encoding(input, alpha, beta, name=None):
    return _add_position_encoding(input, alpha=float(alpha),
                                  beta=float(beta))


def bilinear_tensor_product(x, y, size, act=None, name=None,
                            param_attr=None, bias_attr=None):
    """x^T W y bilinear form with learnable W (nn.py
    bilinear_tensor_product)."""
    helper = LayerHelper("bilinear_tp_s")
    dx, dy = int(x.shape[-1]), int(y.shape[-1])
    w = helper.create_parameter(shape=[size, dx, dy], dtype="float32",
                                attr=param_attr)
    b = helper.create_parameter(shape=[size], dtype="float32", attr=bias_attr,
                                initializer=_const(0.0))
    _register_delegate(
        "bilinear_tp_s",
        lambda x, y, w, b: jnp.einsum("bi,kij,bj->bk", x, w, y) + b,
        in_slots=("X", "Y", "Weight", "Bias"))
    out = _append_simple("bilinear_tp_s",
                         {"X": [x.name], "Y": [y.name],
                          "Weight": [w.name], "Bias": [b.name]})
    return _apply_act(out, act)


_fsp = _delegate("fsp_s", lambda x, y: F.fsp_matrix(x, y),
                 in_slots=("X", "Y"))


def fsp_matrix(x, y):
    return _fsp(x, y)


def _mean_iou_fn(pred, label, num_classes=2):
    # traceable mean-IoU (the eager vision.ops.mean_iou materializes on
    # host); confusion counts via scatter-add
    pred = pred.ravel()
    label = label.ravel()
    hit = (pred == label).astype(jnp.float32)
    inter = jnp.zeros((num_classes,)).at[label].add(hit, mode="drop")
    pc = jnp.zeros((num_classes,)).at[pred].add(1.0, mode="drop")
    lc = jnp.zeros((num_classes,)).at[label].add(1.0, mode="drop")
    union = pc + lc - inter
    valid = union > 0
    iou = jnp.where(valid, inter / jnp.maximum(union, 1.0), 0.0)
    mean = jnp.sum(iou) / jnp.maximum(jnp.sum(valid.astype(jnp.float32)),
                                      1.0)
    return (mean.astype(jnp.float32),
            (union - inter).astype(jnp.int32), inter.astype(jnp.int32))


_mean_iou = _delegate(
    "mean_iou_s", _mean_iou_fn,
    in_slots=("Predictions", "Labels"),
    out_slots=("OutMeanIou", "OutWrong", "OutCorrect"))


def mean_iou(input, label, num_classes, name=None):
    return _mean_iou(input, label, num_classes=num_classes)


_lrn = _delegate(
    "lrn_s",
    lambda x, n=5, k=1.0, alpha=1e-4, beta=0.75, data_format="NCHW":
    F.local_response_norm(x, n, alpha=alpha, beta=beta, k=k,
                          data_format=data_format))


def lrn(input, n=5, k=1.0, alpha=1e-4, beta=0.75, name=None,
        data_format="NCHW"):
    return _lrn(input, n=n, k=float(k), alpha=float(alpha),
                beta=float(beta), data_format=data_format)


_grid_sampler = _delegate("grid_sampler_s",
                          lambda x, grid: F.grid_sample(x, grid),
                          in_slots=("X", "Grid"))


def grid_sampler(x, grid, name=None):
    return _grid_sampler(x, grid)


_affine_grid = _delegate(
    "affine_grid_s",
    lambda theta, out_shape=(): F.affine_grid(theta, list(out_shape)),
    in_slots=("Theta",))


def affine_grid(theta, out_shape, name=None):
    return _affine_grid(theta, out_shape=tuple(int(s) for s in out_shape))


_unfold = _delegate(
    "unfold_s",
    lambda x, kernel_sizes=(3, 3), strides=1, paddings=0, dilations=1:
    O.unfold(x, list(kernel_sizes), strides, paddings, dilations))


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    if not isinstance(kernel_sizes, (list, tuple)):
        kernel_sizes = [kernel_sizes, kernel_sizes]
    return _unfold(x, kernel_sizes=tuple(kernel_sizes), strides=strides,
                   paddings=paddings, dilations=dilations)


def im2sequence(input, filter_size=1, stride=1, padding=0, input_image_size=None,
                out_stride=1, name=None):
    """Sliding-window patches flattened to a sequence (im2sequence_op.cc):
    unfold + transpose so each output row is one patch."""
    if not isinstance(filter_size, (list, tuple)):
        filter_size = [filter_size, filter_size]
    cols = unfold(input, list(filter_size), stride, padding)
    from .layers import reshape, transpose

    t = transpose(cols, [0, 2, 1])   # (b, L, C*kh*kw)
    return reshape(t, [-1, int(t.shape[-1])])


# ---------------------------------------------------------------------------
# resize family (interpolate_op.cc)
# ---------------------------------------------------------------------------
_interp = _delegate(
    "interpolate_s",
    lambda x, size=None, scale=None, mode="nearest", align_corners=False,
    align_mode=0, data_format="NCHW":
    F.interpolate(x, size=list(size) if size else None, scale_factor=scale,
                  mode=mode, align_corners=align_corners,
                  align_mode=align_mode, data_format=data_format))


def image_resize(input, out_shape=None, scale=None, name=None,
                 resample="BILINEAR", actual_shape=None, align_corners=True,
                 align_mode=1, data_format="NCHW"):
    mode = resample.lower()
    return _interp(input, size=tuple(int(s) for s in out_shape)
                   if out_shape else None,
                   scale=scale, mode=mode, align_corners=align_corners,
                   align_mode=align_mode, data_format=data_format)


def resize_bilinear(input, out_shape=None, scale=None, name=None,
                    actual_shape=None, align_corners=True, align_mode=1,
                    data_format="NCHW"):
    return image_resize(input, out_shape, scale, name, "BILINEAR",
                        actual_shape, align_corners, align_mode, data_format)


def resize_nearest(input, out_shape=None, scale=None, name=None,
                   actual_shape=None, align_corners=True,
                   data_format="NCHW"):
    return image_resize(input, out_shape, scale, name, "NEAREST",
                        actual_shape, align_corners, 0, data_format)


def resize_linear(input, out_shape=None, scale=None, name=None,
                  actual_shape=None, align_corners=True, align_mode=1,
                  data_format="NCW"):
    return image_resize(input, out_shape, scale, name, "LINEAR",
                        actual_shape, align_corners, align_mode, data_format)


def resize_trilinear(input, out_shape=None, scale=None, name=None,
                     actual_shape=None, align_corners=True, align_mode=1,
                     data_format="NCDHW"):
    return image_resize(input, out_shape, scale, name, "TRILINEAR",
                        actual_shape, align_corners, align_mode, data_format)


def image_resize_short(input, out_short_len, resample="BILINEAR"):
    h, w = int(input.shape[2]), int(input.shape[3])
    short = min(h, w)
    out = (int(h * out_short_len / short), int(w * out_short_len / short))
    return image_resize(input, out_shape=out, resample=resample)


# ---------------------------------------------------------------------------
# norm layers with parameters
# ---------------------------------------------------------------------------
def instance_norm(input, epsilon=1e-5, param_attr=None, bias_attr=None,
                  name=None):
    helper = LayerHelper("instance_norm_s")
    c = int(input.shape[1])
    scale = helper.create_parameter(shape=[c], dtype="float32",
                                    attr=param_attr,
                                    initializer=_const(1.0))
    bias = helper.create_parameter(shape=[c], dtype="float32", attr=bias_attr,
                                initializer=_const(0.0))
    _register_delegate(
        "instance_norm_s",
        lambda x, s, b, epsilon=1e-5: F.instance_norm(
            x, None, None, s, b, eps=epsilon),
        in_slots=("X", "Scale", "Bias"))
    return _append_simple("instance_norm_s",
                          {"X": [input.name], "Scale": [scale.name],
                           "Bias": [bias.name]},
                          {"epsilon": float(epsilon)})


def group_norm(input, groups, epsilon=1e-5, param_attr=None, bias_attr=None,
               act=None, data_layout="NCHW", name=None):
    helper = LayerHelper("group_norm_s")
    c = int(input.shape[1])
    scale = helper.create_parameter(shape=[c], dtype="float32",
                                    attr=param_attr,
                                    initializer=_const(1.0))
    bias = helper.create_parameter(shape=[c], dtype="float32", attr=bias_attr,
                                initializer=_const(0.0))
    _register_delegate(
        "group_norm_s",
        lambda x, s, b, groups=1, epsilon=1e-5, data_layout="NCHW":
        F.group_norm(x, groups, s, b, epsilon, data_layout),
        in_slots=("X", "Scale", "Bias"))
    out = _append_simple("group_norm_s",
                         {"X": [input.name], "Scale": [scale.name],
                          "Bias": [bias.name]},
                         {"groups": groups, "epsilon": float(epsilon),
                          "data_layout": data_layout})
    return _apply_act(out, act)


def spectral_norm(weight, dim=0, power_iters=1, eps=1e-12, name=None):
    """Spectral normalization via power iteration (spectral_norm_op.cc).
    The u/v vectors are non-trainable state approximated per call (the
    reference updates them in-place; one-shot iteration from a fixed
    start is deterministic under jit)."""
    _register_delegate(
        "spectral_norm_s",
        lambda w, dim=0, power_iters=1, eps=1e-12:
        _spectral_norm_fn(w, dim, power_iters, eps))
    return _append_simple("spectral_norm_s", {"X": [weight.name]},
                          {"dim": dim, "power_iters": power_iters,
                           "eps": float(eps)})


def _spectral_norm_fn(w, dim, power_iters, eps):
    mat = jnp.moveaxis(w, dim, 0).reshape(w.shape[dim], -1)
    u = jnp.ones((mat.shape[0],), w.dtype) / np.sqrt(mat.shape[0])
    v = None
    for _ in range(max(1, power_iters)):
        v = mat.T @ u
        v = v / jnp.maximum(jnp.linalg.norm(v), eps)
        u = mat @ v
        u = u / jnp.maximum(jnp.linalg.norm(u), eps)
    sigma = u @ (mat @ v)
    return w / jnp.maximum(sigma, eps)


def data_norm(input, act=None, epsilon=1e-5, param_attr=None, name=None,
              **kwargs):
    """Per-feature normalization from accumulated batch statistics
    (data_norm_op.cc). Statistics are learnable accumulators."""
    helper = LayerHelper("data_norm_s")
    c = int(input.shape[-1])
    size = helper.create_parameter(shape=[c], dtype="float32",
                                   name=None, initializer=_const(1.0))
    ssum = helper.create_parameter(shape=[c], dtype="float32",
                                   initializer=_const(0.0))
    sqsum = helper.create_parameter(shape=[c], dtype="float32",
                                    initializer=_const(1.0))
    _register_delegate(
        "data_norm_s",
        lambda x, n, s, sq, epsilon=1e-5: _data_norm_fn(x, n, s, sq,
                                                        epsilon),
        in_slots=("X", "BatchSize", "BatchSum", "BatchSquareSum"))
    out = _append_simple(
        "data_norm_s",
        {"X": [input.name], "BatchSize": [size.name],
         "BatchSum": [ssum.name], "BatchSquareSum": [sqsum.name]},
        {"epsilon": float(epsilon)})
    return _apply_act(out, act)


def _data_norm_fn(x, n, s, sq, epsilon):
    mean = s / jnp.maximum(n, 1e-4)
    var = sq / jnp.maximum(n, 1e-4) - mean * mean
    return (x - mean) / jnp.sqrt(jnp.maximum(var, epsilon))


# ---------------------------------------------------------------------------
# conv/pool long tail
# ---------------------------------------------------------------------------
def conv2d_transpose(input, num_filters, output_size=None, filter_size=None,
                     padding=0, stride=1, dilation=1, groups=1,
                     param_attr=None, bias_attr=None, use_cudnn=True,
                     act=None, name=None, data_format="NCHW"):
    helper = LayerHelper("conv2d_transpose_s")
    cin = int(input.shape[1])
    if filter_size is None:
        raise ValueError("filter_size required (output_size-only inference "
                         "not supported)")
    k = (filter_size if isinstance(filter_size, (list, tuple))
         else [filter_size, filter_size])
    # output_size fixes the ambiguous stride>1 transpose shape via
    # output_padding (reference nn.py conv2d_transpose output_size attr)
    output_padding = 0
    if output_size is not None:
        os_ = (output_size if isinstance(output_size, (list, tuple))
               else [output_size, output_size])
        st = (stride if isinstance(stride, (list, tuple))
              else [stride, stride])
        pd = (padding if isinstance(padding, (list, tuple))
              else [padding, padding])
        dl = (dilation if isinstance(dilation, (list, tuple))
              else [dilation, dilation])
        output_padding = tuple(
            int(os_[i]) - ((int(input.shape[2 + i]) - 1) * st[i]
                           - 2 * pd[i] + dl[i] * (int(k[i]) - 1) + 1)
            for i in range(2))
        if any(p < 0 for p in output_padding):
            raise ValueError(
                f"output_size {os_} unreachable: needs output_padding "
                f"{output_padding}")
    w = helper.create_parameter(
        shape=[cin, num_filters // groups, int(k[0]), int(k[1])],
        dtype="float32", attr=param_attr)
    ins = {"Input": [input.name], "Filter": [w.name]}
    if bias_attr is not False:
        b = helper.create_parameter(shape=[num_filters], dtype="float32", attr=bias_attr,
                                initializer=_const(0.0))
        ins["Bias"] = [b.name]
    _register_delegate(
        "conv2d_transpose_s",
        lambda x, w, b=None, stride=1, padding=0, dilation=1, groups=1,
        output_padding=0:
        F.conv2d_transpose(x, w, b, stride=stride, padding=padding,
                           output_padding=output_padding,
                           dilation=dilation, groups=groups),
        in_slots=("Input", "Filter", "Bias"))
    out = _append_simple("conv2d_transpose_s", ins,
                         {"stride": stride, "padding": padding,
                          "dilation": dilation, "groups": groups,
                          "output_padding": output_padding})
    return _apply_act(out, act)


def conv3d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=1, param_attr=None, bias_attr=None, use_cudnn=True,
           act=None, name=None, data_format="NCDHW"):
    helper = LayerHelper("conv3d_s")
    cin = int(input.shape[1])
    k = (filter_size if isinstance(filter_size, (list, tuple))
         else [filter_size] * 3)
    w = helper.create_parameter(
        shape=[num_filters, cin // groups] + [int(s) for s in k],
        dtype="float32", attr=param_attr)
    ins = {"Input": [input.name], "Filter": [w.name]}
    if bias_attr is not False:
        b = helper.create_parameter(shape=[num_filters], dtype="float32", attr=bias_attr,
                                initializer=_const(0.0))
        ins["Bias"] = [b.name]
    _register_delegate(
        "conv3d_s",
        lambda x, w, b=None, stride=1, padding=0, dilation=1, groups=1:
        F.conv3d(x, w, b, stride=stride, padding=padding, dilation=dilation,
                 groups=groups),
        in_slots=("Input", "Filter", "Bias"))
    out = _append_simple("conv3d_s", ins,
                         {"stride": stride, "padding": padding,
                          "dilation": dilation, "groups": groups})
    return _apply_act(out, act)


_pool3d = _delegate(
    "pool3d_s",
    lambda x, pool_size=2, pool_type="max", pool_stride=None,
    pool_padding=0: (F.max_pool3d(x, pool_size, pool_stride, pool_padding)
                     if pool_type == "max"
                     else F.avg_pool3d(x, pool_size, pool_stride,
                                       pool_padding)))


def pool3d(input, pool_size=2, pool_type="max", pool_stride=None,
           pool_padding=0, global_pooling=False, use_cudnn=True,
           ceil_mode=False, name=None, exclusive=True,
           data_format="NCDHW"):
    if global_pooling:
        pool_size = [int(s) for s in input.shape[2:]]
    return _pool3d(input, pool_size=pool_size, pool_type=pool_type,
                   pool_stride=pool_stride or pool_size,
                   pool_padding=pool_padding)


def _adaptive_pool_fn(nd):
    maxp = F.adaptive_max_pool2d if nd == 2 else F.adaptive_max_pool3d
    avgp = F.adaptive_avg_pool2d if nd == 2 else F.adaptive_avg_pool3d

    def fn(x, pool_size=1, pool_type="max", require_index=False):
        if pool_type == "max":
            out = maxp(x, pool_size, return_mask=require_index)
            return out if require_index else out
        return avgp(x, pool_size)

    return fn


_adaptive_pool2d = _delegate("adaptive_pool2d_s", _adaptive_pool_fn(2))
_adaptive_pool2d_idx = _delegate("adaptive_pool2d_idx_s",
                                 _adaptive_pool_fn(2),
                                 out_slots=("Out", "Mask"))
_adaptive_pool3d = _delegate("adaptive_pool3d_s", _adaptive_pool_fn(3))
_adaptive_pool3d_idx = _delegate("adaptive_pool3d_idx_s",
                                 _adaptive_pool_fn(3),
                                 out_slots=("Out", "Mask"))


def adaptive_pool2d(input, pool_size, pool_type="max",
                    require_index=False, name=None):
    ps = (tuple(pool_size) if isinstance(pool_size, (list, tuple))
          else pool_size)
    build = _adaptive_pool2d_idx if require_index else _adaptive_pool2d
    return build(input, pool_size=ps, pool_type=pool_type,
                 require_index=require_index)


def adaptive_pool3d(input, pool_size, pool_type="max",
                    require_index=False, name=None):
    ps = (tuple(pool_size) if isinstance(pool_size, (list, tuple))
          else pool_size)
    build = _adaptive_pool3d_idx if require_index else _adaptive_pool3d
    return build(input, pool_size=ps, pool_type=pool_type,
                 require_index=require_index)


_roi_align_s = _delegate(
    "roi_align_s2",
    lambda x, rois, pooled_height=1, pooled_width=1, spatial_scale=1.0,
    sampling_ratio=-1:
    __import__("paddle_tpu.vision.ops", fromlist=["roi_align"]).roi_align(
        x, rois, (pooled_height, pooled_width), spatial_scale,
        sampling_ratio),
    in_slots=("X", "ROIs"))


def roi_align(input, rois, pooled_height=1, pooled_width=1,
              spatial_scale=1.0, sampling_ratio=-1, name=None,
              rois_num=None):
    return _roi_align_s(input, rois, pooled_height=pooled_height,
                        pooled_width=pooled_width,
                        spatial_scale=float(spatial_scale),
                        sampling_ratio=sampling_ratio)


_roi_pool_s = _delegate(
    "roi_pool_s2",
    lambda x, rois, pooled_height=1, pooled_width=1, spatial_scale=1.0:
    __import__("paddle_tpu.vision.ops", fromlist=["roi_pool"]).roi_pool(
        x, rois, (pooled_height, pooled_width), spatial_scale),
    in_slots=("X", "ROIs"))


def roi_pool(input, rois, pooled_height=1, pooled_width=1,
             spatial_scale=1.0, rois_num=None, name=None):
    return _roi_pool_s(input, rois, pooled_height=pooled_height,
                       pooled_width=pooled_width,
                       spatial_scale=float(spatial_scale))


# ---------------------------------------------------------------------------
# random ops
# ---------------------------------------------------------------------------
def _rng_delegate(op_type, fn):
    """Delegate whose kernel consumes the executor's per-run rng key."""
    if op_type not in KERNELS:
        @kernel(op_type)
        def k(ins, attrs, ctx, _fn=fn):
            arrs = [ins[s][0] for s in ("X",) if s in ins and ins[s]]
            return _out(_fn(ctx.rng_key, *arrs, **attrs))

    def build(*xs, **attrs):
        ins = {"X": [xs[0].name]} if xs else {}
        return _append_simple(op_type, ins, attrs)

    return build


_uniform_random = _rng_delegate(
    "uniform_random_s2",
    lambda key, shape=(), min=-1.0, max=1.0:
    jax.random.uniform(key, shape, jnp.float32, min, max))


def uniform_random(shape, dtype="float32", min=-1.0, max=1.0, seed=0,
                   name=None):
    return _uniform_random(shape=tuple(int(s) for s in shape),
                           min=float(min), max=float(max))


def gaussian_random(shape, mean=0.0, std=1.0, seed=0, dtype="float32",
                    name=None):
    build = _rng_delegate(
        "gaussian_random_s2",
        lambda key, shape=(), mean=0.0, std=1.0:
        mean + std * jax.random.normal(key, shape, jnp.float32))
    return build(shape=tuple(int(s) for s in shape), mean=float(mean),
                 std=float(std))


def sampling_id(x, min=0.0, max=1.0, seed=0, dtype="float32"):
    build = _rng_delegate(
        "sampling_id_s",
        lambda key, probs: jax.random.categorical(
            key, jnp.log(jnp.maximum(probs, 1e-20)), axis=-1))
    return build(x)


def uniform_random_batch_size_like(input, shape, dtype="float32",
                                   input_dim_idx=0, output_dim_idx=0,
                                   min=-1.0, max=1.0, seed=0):
    shape = list(shape)
    shape[output_dim_idx] = int(input.shape[input_dim_idx])
    return uniform_random(shape, dtype, min, max, seed)


def gaussian_random_batch_size_like(input, shape, input_dim_idx=0,
                                    output_dim_idx=0, mean=0.0, std=1.0,
                                    seed=0, dtype="float32"):
    shape = list(shape)
    shape[output_dim_idx] = int(input.shape[input_dim_idx])
    return gaussian_random(shape, mean, std, seed, dtype)


def random_crop(x, shape, seed=None):
    build = _rng_delegate(
        "random_crop_s",
        lambda key, x, shape=(): _random_crop_fn(key, x, shape))
    return build(x, shape=tuple(int(s) for s in shape))


def _random_crop_fn(key, x, shape):
    # crop the trailing len(shape) dims at a random offset (batch kept)
    lead = x.ndim - len(shape)
    starts = []
    for i, s in enumerate(shape):
        limit = x.shape[lead + i] - s + 1
        key, sub = jax.random.split(key)
        starts.append(jax.random.randint(sub, (), 0, limit))
    full_start = [jnp.asarray(0)] * lead + starts
    full_size = list(x.shape[:lead]) + list(shape)
    return jax.lax.dynamic_slice(x, full_start, full_size)


# ---------------------------------------------------------------------------
# CRF / sequence decode (delegating to the eager nn.crf implementations)
# ---------------------------------------------------------------------------
def linear_chain_crf(input, label, param_attr=None, length=None):
    """Linear-chain CRF negative log-likelihood (linear_chain_crf_op.cc).
    Static wrapper over nn.crf.linear_chain_crf; transition is the
    learnable parameter (size (num_tags + 2, num_tags))."""
    from ..nn import crf as crf_mod

    helper = LayerHelper("linear_chain_crf_s")
    num_tags = int(input.shape[-1])
    trans = helper.create_parameter(shape=[num_tags + 2, num_tags],
                                    dtype="float32", attr=param_attr)
    _register_delegate(
        "linear_chain_crf_s",
        lambda emission, transition, label, length=None:
        crf_mod.linear_chain_crf(
            emission, transition, label,
            length if length is not None else
            jnp.full((emission.shape[0],), emission.shape[1], jnp.int32)),
        in_slots=("Emission", "Transition", "Label", "Length"))
    ins = {"Emission": [input.name], "Transition": [trans.name],
           "Label": [label.name]}
    if length is not None:
        ins["Length"] = [length.name]
    return _append_simple("linear_chain_crf_s", ins, {})


def crf_decoding(input, param_attr, label=None, length=None):
    from ..nn import crf as crf_mod

    _register_delegate(
        "crf_decoding_s",
        lambda emission, transition, length=None:
        crf_mod.crf_decoding(
            emission, transition,
            length if length is not None else
            jnp.full((emission.shape[0],), emission.shape[1], jnp.int32)),
        in_slots=("Emission", "Transition", "Length"))
    # param_attr here is the trained transition parameter Variable
    ins = {"Emission": [input.name], "Transition": [param_attr.name]}
    if length is not None:
        ins["Length"] = [length.name]
    return _append_simple("crf_decoding_s", ins, {})


def ctc_greedy_decoder(input, blank, input_length=None, padding_value=0,
                       name=None):
    _register_delegate(
        "ctc_greedy_decoder_s",
        lambda probs, blank=0, padding_value=0:
        O.ctc_greedy_decoder(probs, blank, padding_value=padding_value),
        in_slots=("Input",))
    return _append_simple("ctc_greedy_decoder_s", {"Input": [input.name]},
                          {"blank": blank, "padding_value": padding_value})


# ---------------------------------------------------------------------------
# mixture-of-experts (ISSUE 19): one static op wrapping nn.moe's
# expert-parallel apply. The shard_propagation pass stamps __moe_ep =
# [axis, n] when the mesh has an "ep" axis dividing the expert count;
# with the stamp the kernel compiles the explicit all_to_all
# dispatch/combine inside shard_map, otherwise it runs the dense
# single-device oracle (numerically identical — see nn/moe.py).
# ---------------------------------------------------------------------------
@kernel("moe")
def _moe_kernel(ins, attrs, ctx):
    from ..nn.moe import moe_apply_ep
    from ..parallel.mesh import mesh_for_shape

    params = {"gate_w": ins["GateW"][0],
              "experts_w1": ins["W1"][0], "experts_b1": ins["B1"][0],
              "experts_w2": ins["W2"][0], "experts_b2": ins["B2"][0]}
    mesh, axis = None, "ep"
    stamp = attrs.get("__moe_ep")
    if stamp:
        axis, n = str(stamp[0]), int(stamp[1])
        shape = ({str(a): int(s) for a, s in stamp[2]}
                 if len(stamp) > 2 else {axis: n})
        mesh = mesh_for_shape(shape)
    out, aux = moe_apply_ep(
        params, ins["X"][0], mesh=mesh, axis=axis,
        capacity_factor=float(attrs.get("capacity_factor", 2.0)),
        dispatch_codec=attrs.get("dispatch_codec") or None)
    return {"Out": [out], "AuxLoss": [aux.reshape((1,))]}


def moe(x, num_experts, d_hidden, capacity_factor=2.0, dispatch_codec=None,
        param_attr=None, name=None):
    """Static-graph MoE FFN: top-2 gate + num_experts expert FFNs with
    GShard static capacity (capacity_factor * tokens / experts). x must
    be 2-D (tokens, d_model) with a static token count — capacity is a
    compile-time shape. Returns (out, aux_loss): out keeps x's shape,
    aux_loss is the (1,) load-balancing loss to add to the objective.

    Under a mesh with an "ep" axis the shard_propagation pass stamps
    the op and the kernel runs the explicit expert-parallel all_to_all
    exchange; ``dispatch_codec="int8"`` additionally quantizes the
    dispatch payload on the wire (accuracy-gated by the caller)."""
    from .initializer import Xavier

    helper = LayerHelper("moe", name=name)
    d = int(x.shape[-1])
    e, h = int(num_experts), int(d_hidden)
    xav = Xavier(uniform=True)
    gate_w = helper.create_parameter([d, e], attr=param_attr,
                                     initializer=xav)
    w1 = helper.create_parameter([e, d, h], initializer=xav)
    b1 = helper.create_parameter([e, h], initializer=_const(0.0))
    w2 = helper.create_parameter([e, h, d], initializer=xav)
    b2 = helper.create_parameter([e, d], initializer=_const(0.0))
    attrs = {"capacity_factor": float(capacity_factor)}
    if dispatch_codec:
        attrs["dispatch_codec"] = str(dispatch_codec)
    return _append_simple(
        "moe",
        {"X": [x.name], "GateW": [gate_w.name], "W1": [w1.name],
         "B1": [b1.name], "W2": [w2.name], "B2": [b2.name]},
        attrs, out_slots=("Out", "AuxLoss"), helper=helper)


# ---------------------------------------------------------------------------
# export: public functions defined here join fluid.layers / static.nn
# ---------------------------------------------------------------------------
__all__ = [n for n, v in list(globals().items())
           if not n.startswith("_") and callable(v)
           and getattr(v, "__module__", "") == __name__]


def _export_into_layers():
    # registry, NOT setattr: a module global named `range`/`sum`/... would
    # shadow the builtin for code inside layers.py (round-2 bug)
    from . import layers as _layers

    _layers._register_exports({_n: globals()[_n] for _n in __all__})


_export_into_layers()
