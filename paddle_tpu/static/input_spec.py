"""InputSpec (reference python/paddle/static/input.py InputSpec): shape/
dtype/name signature for jit.save / to_static input binding."""
from __future__ import annotations


class InputSpec:
    def __init__(self, shape=None, dtype="float32", name=None):
        self.shape = tuple(shape) if shape is not None else None
        self.dtype = dtype
        self.name = name

    @classmethod
    def from_tensor(cls, tensor, name=None):
        return cls(tuple(tensor.shape), str(tensor.dtype), name)

    def __repr__(self):
        return (f"InputSpec(shape={self.shape}, dtype={self.dtype}, "
                f"name={self.name})")
