"""Static-graph optimizers: append_backward + per-param update ops.

Reference: /root/reference/python/paddle/fluid/optimizer.py:56 Optimizer
(minimize -> append_backward -> _create_accumulators -> apply_gradients
appending one update op per param). Same program-rewriting shape here;
the update ops are jnp kernels (kernels.py) so the whole train step
(fwd + vjp-backward + updates) compiles into one XLA program.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence

from ..utils import unique_name
from .backward import append_backward
from .initializer import Constant
from .ir import ParamDesc, Program, Variable, default_startup_program, \
    grad_var_name
from .layers import LayerHelper

OPTIMIZER_OP_TYPES = {"sgd", "momentum", "adam", "lamb", "increment"}

_global_grad_clip = [None]


def set_gradient_clip(clip, param_list=None, program=None):
    """Program-level default gradient clip (reference fluid/clip.py:766
    set_gradient_clip): applied by Optimizer.minimize when the optimizer
    itself was not given a grad_clip. param_list/program accepted for API
    parity; the clip applies to all minimized parameters."""
    _global_grad_clip[0] = clip


def resolve_grad_clip(optimizer):
    """The clip a static minimize must apply for ``optimizer``: its own
    grad_clip, else the program-level set_gradient_clip default. Every
    path that re-implements the append_backward -> clip ->
    apply_gradients body (RecomputeOptimizer, fleet's static minimize)
    resolves through here so the global fallback is never dropped."""
    return getattr(optimizer, "grad_clip", None) or _global_grad_clip[0]


class Optimizer:
    _update_op = None

    def __init__(self, learning_rate=0.001, regularization=None,
                 grad_clip=None, name=None):
        self.learning_rate = learning_rate
        self.regularization = regularization
        self.grad_clip = grad_clip
        self._name = name or type(self).__name__.lower()
        self._lr_var = None

    # -- helpers ----------------------------------------------------------
    def _create_lr_var(self, helper: LayerHelper):
        from .ir import Variable

        # a graph-built schedule (layers_compat exponential_decay & co.)
        # IS the lr var — the decay recomputes from the step counter
        # inside the program, like the reference's lr_scheduler ops
        if isinstance(self.learning_rate, Variable):
            return self.learning_rate
        # cached lr var is only valid within the program it was created in
        if self._lr_var is not None and \
                self._lr_var.block.program is helper.main_program:
            return self._lr_var
        name = unique_name.generate(f"{self._name}_lr")
        self._lr_var = self._create_persist(
            helper, name, (1,), float(self.learning_rate))
        return self._lr_var

    @staticmethod
    def _create_persist(helper, name, shape, value, dtype="float32"):
        from .ir import VarDesc
        desc = VarDesc(name, shape, dtype, persistable=True)
        helper.main_program.global_block.vars[name] = desc
        sb = helper.startup_program.global_block
        sb.vars[name] = VarDesc(name, shape, dtype, persistable=True)
        sb.append_op(type="fill_constant", inputs={},
                     outputs={"Out": [name]},
                     attrs={"shape": list(shape), "dtype": dtype,
                            "value": float(value)})
        return Variable(helper.main_program.global_block, desc)

    def _accumulator(self, helper, param, suffix, value=0.0, shape=None):
        name = f"{param.name}_{self._name}_{suffix}"
        return self._create_persist(
            helper, name, shape or param.shape, value, param.dtype)

    # -- public API (reference Optimizer.minimize) ------------------------
    def minimize(self, loss: Variable, startup_program=None,
                 parameter_list=None, no_grad_set=None):
        params_grads = append_backward(loss, parameter_list, no_grad_set)
        clip = resolve_grad_clip(self)
        if clip is not None:
            params_grads = clip(params_grads)
        self.apply_gradients(params_grads)
        return [], params_grads

    def apply_gradients(self, params_grads):
        helper = LayerHelper(self._name)
        params_grads = self._append_regularization_ops(params_grads)
        lr = self._create_lr_var(helper)
        for p, g in params_grads:
            self._append_update(helper, p, g, lr)
        return []

    def _append_regularization_ops(self, params_grads):
        """Append weight-decay ops onto the program (reference
        fluid/regularizer.py:36 append_regularization_ops): L2 adds
        scale(p)·coeff to the grad, L1 adds scale(sign(p))·coeff."""
        if self.regularization is None:
            return params_grads
        from ..regularizer import L1Decay
        from .layers import _append_simple

        reg = self.regularization
        out = []
        for p, g in params_grads:
            src = _append_simple("sign", {"X": [p]}) \
                if isinstance(reg, L1Decay) else p
            decay = _append_simple("scale", {"X": [src]},
                                   {"scale": float(reg.coeff)})
            g2 = _append_simple("elementwise_add", {"X": [g], "Y": [decay]})
            out.append((p, g2))
        return out

    def _append_update(self, helper, p, g, lr):
        raise NotImplementedError


class SGD(Optimizer):
    def _append_update(self, helper, p, g, lr):
        helper.block.append_op(
            type="sgd",
            inputs={"Param": [p], "Grad": [g], "LearningRate": [lr]},
            outputs={"ParamOut": [p.name]})


class Momentum(Optimizer):
    def __init__(self, learning_rate=0.001, momentum=0.9,
                 use_nesterov=False, **kw):
        super().__init__(learning_rate, **kw)
        self.momentum = momentum
        self.use_nesterov = use_nesterov

    def _append_update(self, helper, p, g, lr):
        vel = self._accumulator(helper, p, "velocity")
        helper.block.append_op(
            type="momentum",
            inputs={"Param": [p], "Grad": [g], "Velocity": [vel],
                    "LearningRate": [lr]},
            outputs={"ParamOut": [p.name], "VelocityOut": [vel.name]},
            attrs={"mu": self.momentum, "use_nesterov": self.use_nesterov})


class Adam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, **kw):
        super().__init__(learning_rate, **kw)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon

    def _append_update(self, helper, p, g, lr):
        m1 = self._accumulator(helper, p, "moment1")
        m2 = self._accumulator(helper, p, "moment2")
        b1p = self._accumulator(helper, p, "beta1pow", 1.0, (1,))
        b2p = self._accumulator(helper, p, "beta2pow", 1.0, (1,))
        helper.block.append_op(
            type="adam",
            inputs={"Param": [p], "Grad": [g], "Moment1": [m1],
                    "Moment2": [m2], "Beta1Pow": [b1p], "Beta2Pow": [b2p],
                    "LearningRate": [lr]},
            outputs={"ParamOut": [p.name], "Moment1Out": [m1.name],
                     "Moment2Out": [m2.name], "Beta1PowOut": [b1p.name],
                     "Beta2PowOut": [b2p.name]},
            attrs={"beta1": self.beta1, "beta2": self.beta2,
                   "epsilon": self.epsilon})


class Lamb(Optimizer):
    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01,
                 beta1=0.9, beta2=0.999, epsilon=1e-6, **kw):
        super().__init__(learning_rate, **kw)
        self.wd = lamb_weight_decay
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon

    def _append_update(self, helper, p, g, lr):
        m1 = self._accumulator(helper, p, "moment1")
        m2 = self._accumulator(helper, p, "moment2")
        b1p = self._accumulator(helper, p, "beta1pow", 1.0, (1,))
        b2p = self._accumulator(helper, p, "beta2pow", 1.0, (1,))
        helper.block.append_op(
            type="lamb",
            inputs={"Param": [p], "Grad": [g], "Moment1": [m1],
                    "Moment2": [m2], "Beta1Pow": [b1p], "Beta2Pow": [b2p],
                    "LearningRate": [lr]},
            outputs={"ParamOut": [p.name], "Moment1Out": [m1.name],
                     "Moment2Out": [m2.name], "Beta1PowOut": [b1p.name],
                     "Beta2PowOut": [b2p.name]},
            attrs={"beta1": self.beta1, "beta2": self.beta2,
                   "epsilon": self.epsilon, "weight_decay": self.wd})


SGDOptimizer = SGD
MomentumOptimizer = Momentum
AdamOptimizer = Adam
LambOptimizer = Lamb
