"""paddle_tpu.static — declarative (static-graph) mode.

Feature parity with the reference's Fluid core (Program/Block/Op IR,
Executor, append_backward, layers, save/load) re-designed for TPU: the
Program is a thin serializable IR that lowers to ONE jit-compiled XLA
program per (feed-signature, fetch-list); see ir.py / executor.py /
backward.py docstrings for the design mapping.

Typical use (reference book tests, e.g.
/root/reference/python/paddle/fluid/tests/book/test_recognize_digits.py):

    import paddle_tpu.static as static
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = static.data("x", [-1, 784])
        label = static.data("label", [-1, 1], dtype="int64")
        h = static.nn.fc(x, 128, act="relu")
        logits = static.nn.fc(h, 10)
        loss = static.mean(
            static.softmax_with_cross_entropy(logits, label))
        static.Adam(1e-3).minimize(loss)
    exe = static.Executor()
    exe.run(startup)
    out, = exe.run(main, feed={"x": ..., "label": ...},
                   fetch_list=[loss])
"""
from . import initializer  # noqa: F401
from .backward import append_backward, calc_gradient  # noqa: F401
from .input_spec import InputSpec  # noqa: F401
from .viz import hlo_text, program_to_dot, save_dot  # noqa: F401
from .feeder import DataFeeder  # noqa: F401
from .compiler import (BuildStrategy, CompiledProgram,  # noqa: F401
                       ExecutionStrategy)
from .executor import Executor, Scope, global_scope, scope_guard  # noqa: F401
from .passes import (PassReport, apply_passes, pass_names,  # noqa: F401
                     resolve_amp)
from .compile_cache import (cache_dir as compile_cache_dir,  # noqa: F401
                            ensure_enabled as enable_compile_cache)
from .io import load, save  # noqa: F401
from .io import (load_inference_model, load_params,  # noqa: F401
                 load_persistables, load_program, save_inference_model,
                 save_params, save_persistables, save_program,
                 save_train_program)
from .ir import (Block, OpDesc, Program, VarDesc, Variable,  # noqa: F401
                 default_main_program, default_startup_program,
                 program_guard)
from .layers import *  # noqa: F401,F403
from .layers import data  # noqa: F401
from .layers_ext import *  # noqa: F401,F403  (fluid.layers long tail)
from .layers_compat import *  # noqa: F401,F403  (fluid.layers bridge)
from .rnn_builder import DynamicRNN, StaticRNN  # noqa: F401
from .legacy_flow import IfElse, Switch, While  # noqa: F401
from .py_reader import (PyReader, create_py_reader_by_data,  # noqa: F401
                        double_buffer, py_reader, read_file)
from .layers import (ParallelExecutor, Print, WeightNormParamAttr,  # noqa: F401
                     gradients, name_scope, py_func)
from .checker import (check_program, compare_op_signatures,  # noqa: F401
                      validate_program, ProgramValidationError)
from .optimizer import (SGD, Adam, AdamOptimizer, Lamb,  # noqa: F401
                        LambOptimizer, Momentum, MomentumOptimizer,
                        Optimizer, SGDOptimizer, set_gradient_clip)
from ..nn.clip import (ErrorClipByValue, GradientClipByGlobalNorm,  # noqa: F401
                       GradientClipByNorm, GradientClipByValue)

from . import layers as nn  # noqa: F401  (static.nn.fc style access)
from . import nets  # noqa: F401

# fluid top-level long tail (audited by test_namespace_freeze "fluid")
from ..framework.lod import LoDTensorArray  # noqa: F401,E402
from ..framework.mode import (  # noqa: F401,E402
    disable_dygraph, disable_imperative, enable_dygraph,
    enable_imperative, in_dygraph_mode)
from ..framework.tensor import Tensor as VarBase  # noqa: F401,E402
from ..nn.layer import ParamAttr  # noqa: F401,E402
from .fluid_compat import (  # noqa: F401,E402
    DataFeedDesc, DistMultiTrainer, Generator, MultiTrainer,
    PipelineTrainer, TrainerDesc, cpu_places, cuda_pinned_places,
    cuda_places, device_guard, is_compiled_with_xpu, load_op_library,
    memory_optimize, release_memory, require_version, xpu_places)
from ..distributed.transpiler import HashName, RoundRobin  # noqa: F401,E402


def __getattr__(name):
    # fluid submodule addresses, resolved lazily: fluid.dygraph -> the
    # eager shim, fluid.contrib -> {mixed_precision: amp, slim:
    # quantization}, fluid.learning_rate_decay -> the schedule fns
    import importlib
    import sys
    import types

    if name == "dygraph":
        return importlib.import_module("paddle_tpu.dygraph")
    if name == "contrib":
        mod = types.ModuleType("paddle_tpu.static.contrib")
        mod.mixed_precision = importlib.import_module("paddle_tpu.amp")
        mod.slim = importlib.import_module("paddle_tpu.quantization")
        sys.modules[mod.__name__] = mod
        setattr(sys.modules[__name__], "contrib", mod)
        return mod
    if name == "learning_rate_decay":
        mod = types.ModuleType("paddle_tpu.static.learning_rate_decay")
        from . import layers as _L

        for n in ("exponential_decay", "natural_exp_decay",
                  "inverse_time_decay", "polynomial_decay",
                  "piecewise_decay", "noam_decay", "cosine_decay",
                  "linear_lr_warmup"):
            if hasattr(_L, n):
                setattr(mod, n, getattr(_L, n))
        sys.modules[mod.__name__] = mod
        setattr(sys.modules[__name__], "learning_rate_decay", mod)
        return mod
    raise AttributeError(name)
