"""Initializer specs for static parameters.

Reference: /root/reference/python/paddle/fluid/initializer.py — each
initializer appends a startup-program op (fill_constant /
gaussian_random / uniform_random / truncated_gaussian_random). Same
design here: an initializer resolves to (op_type, attrs) appended to the
startup program by LayerHelper.create_parameter.
"""
from __future__ import annotations

import math


class Initializer:
    def resolve(self, shape, dtype, fan_hint):
        raise NotImplementedError


class Constant(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def resolve(self, shape, dtype, fan_hint):
        return "fill_constant", {"shape": list(shape), "dtype": dtype,
                                 "value": float(self.value)}


class Normal(Initializer):
    def __init__(self, loc=0.0, scale=1.0):
        self.loc, self.scale = loc, scale

    def resolve(self, shape, dtype, fan_hint):
        return "gaussian_random", {"shape": list(shape), "dtype": dtype,
                                   "mean": self.loc, "std": self.scale}


class TruncatedNormal(Initializer):
    def __init__(self, loc=0.0, scale=1.0):
        self.loc, self.scale = loc, scale

    def resolve(self, shape, dtype, fan_hint):
        return "truncated_gaussian_random", {
            "shape": list(shape), "dtype": dtype, "mean": self.loc,
            "std": self.scale}


class Uniform(Initializer):
    def __init__(self, low=-1.0, high=1.0):
        self.low, self.high = low, high

    def resolve(self, shape, dtype, fan_hint):
        return "uniform_random", {"shape": list(shape), "dtype": dtype,
                                  "min": self.low, "max": self.high}


def _fans(shape):
    if len(shape) == 0:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        return shape[0], shape[1]
    receptive = 1
    for s in shape[2:]:
        receptive *= s
    return shape[1] * receptive, shape[0] * receptive


class Xavier(Initializer):
    """Glorot (reference initializer.py XavierInitializer)."""

    def __init__(self, uniform=True, fan_in=None, fan_out=None):
        self.uniform = uniform
        self.fan_in, self.fan_out = fan_in, fan_out

    def resolve(self, shape, dtype, fan_hint):
        fi, fo = _fans(shape)
        fi = self.fan_in or fi
        fo = self.fan_out or fo
        if self.uniform:
            limit = math.sqrt(6.0 / (fi + fo))
            return "uniform_random", {"shape": list(shape), "dtype": dtype,
                                      "min": -limit, "max": limit}
        std = math.sqrt(2.0 / (fi + fo))
        return "gaussian_random", {"shape": list(shape), "dtype": dtype,
                                   "mean": 0.0, "std": std}


class MSRA(Initializer):
    """Kaiming (reference initializer.py MSRAInitializer)."""

    def __init__(self, uniform=True, fan_in=None):
        self.uniform = uniform
        self.fan_in = fan_in

    def resolve(self, shape, dtype, fan_hint):
        fi, _ = _fans(shape)
        fi = self.fan_in or fi
        if self.uniform:
            limit = math.sqrt(6.0 / fi)
            return "uniform_random", {"shape": list(shape), "dtype": dtype,
                                      "min": -limit, "max": limit}
        std = math.sqrt(2.0 / fi)
        return "gaussian_random", {"shape": list(shape), "dtype": dtype,
                                   "mean": 0.0, "std": std}


class NumpyArrayInitializer(Initializer):
    """Initialize from a literal array (reference initializer.py
    NumpyArrayInitializer → assign_value op)."""

    def __init__(self, value):
        import numpy as np

        self.value = np.asarray(value)

    def resolve(self, shape, dtype, fan_hint):
        if tuple(self.value.shape) != tuple(shape):
            raise ValueError(
                f"NumpyArrayInitializer value shape {self.value.shape} "
                f"does not match parameter shape {tuple(shape)}")
        return "assign_value", {"shape": list(shape), "dtype": dtype,
                                "values": self.value.reshape(-1).tolist()}


class Bilinear(Initializer):
    """Bilinear upsampling kernel init for transposed convs (reference
    initializer.py BilinearInitializer); weight shape (C_out, C_in, H, W)."""

    def resolve(self, shape, dtype, fan_hint):
        import numpy as np

        if len(shape) != 4:
            raise ValueError("Bilinear initializer needs a 4-D weight")
        h, w = shape[2], shape[3]
        f_h, f_w = (h + 1) // 2, (w + 1) // 2
        c_h = (2 * f_h - 1 - f_h % 2) / (2.0 * f_h)
        c_w = (2 * f_w - 1 - f_w % 2) / (2.0 * f_w)
        y = np.arange(h)[:, None]
        x = np.arange(w)[None, :]
        filt = ((1 - np.abs(y / f_h - c_h)) *
                (1 - np.abs(x / f_w - c_w))).astype(np.float64)
        # reference BilinearInitializer writes the filter into EVERY
        # (out, in) channel pair (initializer.py, np.tile over C_out*C_in)
        weight = np.tile(filt, (shape[0], shape[1], 1, 1))
        return "assign_value", {"shape": list(shape), "dtype": dtype,
                                "values": weight.reshape(-1).tolist()}


KaimingUniform = MSRA
XavierInitializer = Xavier
ConstantInitializer = Constant
NormalInitializer = Normal
UniformInitializer = Uniform
BilinearInitializer = Bilinear

_global_initializer = [None, None]   # [weight_init, bias_init]


def set_global_initializer(weight_init, bias_init=None):
    """Default initializer for parameters that do not specify one
    (reference initializer.py set_global_initializer). Pass None, None
    to reset."""
    _global_initializer[0] = weight_init
    _global_initializer[1] = bias_init


def resolve_initializer(initializer, shape, dtype, fan_hint=None):
    if initializer is None:
        initializer = _global_initializer[0] or Xavier()
    return initializer.resolve(shape, dtype, fan_hint)
