"""Initializer specs for static parameters.

Reference: /root/reference/python/paddle/fluid/initializer.py — each
initializer appends a startup-program op (fill_constant /
gaussian_random / uniform_random / truncated_gaussian_random). Same
design here: an initializer resolves to (op_type, attrs) appended to the
startup program by LayerHelper.create_parameter.
"""
from __future__ import annotations

import math


class Initializer:
    def resolve(self, shape, dtype, fan_hint):
        raise NotImplementedError


class Constant(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def resolve(self, shape, dtype, fan_hint):
        return "fill_constant", {"shape": list(shape), "dtype": dtype,
                                 "value": float(self.value)}


class Normal(Initializer):
    def __init__(self, loc=0.0, scale=1.0):
        self.loc, self.scale = loc, scale

    def resolve(self, shape, dtype, fan_hint):
        return "gaussian_random", {"shape": list(shape), "dtype": dtype,
                                   "mean": self.loc, "std": self.scale}


class TruncatedNormal(Initializer):
    def __init__(self, loc=0.0, scale=1.0):
        self.loc, self.scale = loc, scale

    def resolve(self, shape, dtype, fan_hint):
        return "truncated_gaussian_random", {
            "shape": list(shape), "dtype": dtype, "mean": self.loc,
            "std": self.scale}


class Uniform(Initializer):
    def __init__(self, low=-1.0, high=1.0):
        self.low, self.high = low, high

    def resolve(self, shape, dtype, fan_hint):
        return "uniform_random", {"shape": list(shape), "dtype": dtype,
                                  "min": self.low, "max": self.high}


def _fans(shape):
    if len(shape) == 0:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        return shape[0], shape[1]
    receptive = 1
    for s in shape[2:]:
        receptive *= s
    return shape[1] * receptive, shape[0] * receptive


class Xavier(Initializer):
    """Glorot (reference initializer.py XavierInitializer)."""

    def __init__(self, uniform=True, fan_in=None, fan_out=None):
        self.uniform = uniform
        self.fan_in, self.fan_out = fan_in, fan_out

    def resolve(self, shape, dtype, fan_hint):
        fi, fo = _fans(shape)
        fi = self.fan_in or fi
        fo = self.fan_out or fo
        if self.uniform:
            limit = math.sqrt(6.0 / (fi + fo))
            return "uniform_random", {"shape": list(shape), "dtype": dtype,
                                      "min": -limit, "max": limit}
        std = math.sqrt(2.0 / (fi + fo))
        return "gaussian_random", {"shape": list(shape), "dtype": dtype,
                                   "mean": 0.0, "std": std}


class MSRA(Initializer):
    """Kaiming (reference initializer.py MSRAInitializer)."""

    def __init__(self, uniform=True, fan_in=None):
        self.uniform = uniform
        self.fan_in = fan_in

    def resolve(self, shape, dtype, fan_hint):
        fi, _ = _fans(shape)
        fi = self.fan_in or fi
        if self.uniform:
            limit = math.sqrt(6.0 / fi)
            return "uniform_random", {"shape": list(shape), "dtype": dtype,
                                      "min": -limit, "max": limit}
        std = math.sqrt(2.0 / fi)
        return "gaussian_random", {"shape": list(shape), "dtype": dtype,
                                   "mean": 0.0, "std": std}


KaimingUniform = MSRA
XavierInitializer = Xavier
ConstantInitializer = Constant
NormalInitializer = Normal
UniformInitializer = Uniform


def resolve_initializer(initializer, shape, dtype, fan_hint=None):
    if initializer is None:
        initializer = Xavier()
    return initializer.resolve(shape, dtype, fan_hint)
