"""Static-graph IR: Program / Block / OpDesc / VarDesc.

TPU-native counterpart of the reference's protobuf IR
(/root/reference/paddle/fluid/framework/framework.proto:42 OpDesc,
:104 VarType, :173 BlockDesc, :211 ProgramDesc and the mutable C++
wrappers program_desc.cc / block_desc.cc / op_desc.cc).

Design notes (deliberately NOT a port):
- The reference compiles nothing — its ProgramDesc is interpreted op-by-op
  by a C++ executor (executor.cc:476). Here the IR is a thin, serializable
  description whose only job is (a) API parity (clone/prune/serialize,
  feed/fetch targets, persistables) and (b) being lowerable to ONE pure
  jax function that XLA compiles whole (see executor.py). There is no
  per-op kernel dispatch at runtime.
- Shape inference runs `jax.eval_shape` over the op's kernel instead of
  hand-written InferShape per op (reference operator.cc InferShape). The
  dynamic batch dim (-1) is propagated by substituting a sentinel size.
- Serialization is JSON (versioned), not protobuf: the IR is tiny (op
  type + slots + attrs) and protobuf would add a build dep for no gain.
"""
from __future__ import annotations

import copy
import json
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from ..framework import dtype as dtype_mod

IR_VERSION = 1

# Sentinel substituted for -1 (dynamic batch) during eval_shape-based
# shape inference; inferred dims divisible by it map back to -1 (covers
# reshape-merged dims like batch*seq). A large prime keeps collisions with
# real layer sizes out of practical range; eval_shape is abstract, so the
# size costs nothing.
_DYN_SENTINEL = 1000003


class VarDesc:
    """Variable metadata in a block (reference framework.proto:164)."""

    def __init__(self, name, shape=None, dtype="float32", persistable=False,
                 stop_gradient=True, is_data=False, lod_level=0):
        self.name = name
        self.shape = tuple(shape) if shape is not None else None
        # tensor_array is a container type, not an element dtype
        # (framework.proto:151 LOD_TENSOR_ARRAY)
        self.dtype = dtype if dtype == "tensor_array" else \
            dtype_mod.dtype_name(dtype_mod.convert_dtype(dtype))
        self.persistable = persistable
        self.stop_gradient = stop_gradient
        self.is_data = is_data
        self.lod_level = lod_level
        # pass-stamped annotations (e.g. __sharding_spec from the
        # shard_propagation pass) — serialized only when present, so
        # un-stamped programs keep their exact dict/content-hash shape
        self.attrs: Dict[str, Any] = {}

    def to_dict(self):
        out = {
            "name": self.name,
            "shape": list(self.shape) if self.shape is not None else None,
            "dtype": self.dtype,
            "persistable": self.persistable,
            "stop_gradient": self.stop_gradient,
            "is_data": self.is_data,
            "lod_level": self.lod_level,
        }
        if self.attrs:
            out["attrs"] = dict(self.attrs)
        return out

    @staticmethod
    def from_dict(d):
        v = VarDesc(
            d["name"], d["shape"], d["dtype"], d["persistable"],
            d["stop_gradient"], d["is_data"], d.get("lod_level", 0))
        v.attrs = dict(d.get("attrs") or {})
        return v

    def __repr__(self):
        return (f"VarDesc(name={self.name!r}, shape={self.shape}, "
                f"dtype={self.dtype}, persistable={self.persistable})")


class OpDesc:
    """One op node: type + named input/output slots + attrs
    (reference framework.proto:42)."""

    def __init__(self, op_type: str,
                 inputs: Optional[Dict[str, List[str]]] = None,
                 outputs: Optional[Dict[str, List[str]]] = None,
                 attrs: Optional[Dict[str, Any]] = None):
        self.type = op_type
        self.inputs = {k: list(v) for k, v in (inputs or {}).items()}
        self.outputs = {k: list(v) for k, v in (outputs or {}).items()}
        self.attrs = dict(attrs or {})

    def input_names(self) -> List[str]:
        return [n for vs in self.inputs.values() for n in vs]

    def output_names(self) -> List[str]:
        return [n for vs in self.outputs.values() for n in vs]

    def to_dict(self):
        return {"type": self.type, "inputs": self.inputs,
                "outputs": self.outputs, "attrs": _attrs_to_json(self.attrs)}

    @staticmethod
    def from_dict(d):
        return OpDesc(d["type"], d["inputs"], d["outputs"],
                      _attrs_from_json(d["attrs"]))

    def __repr__(self):
        return f"OpDesc({self.type}: {self.inputs} -> {self.outputs})"


def _attrs_to_json(attrs):
    out = {}
    for k, v in attrs.items():
        if isinstance(v, np.ndarray):
            out[k] = {"__ndarray__": v.tolist(), "dtype": str(v.dtype)}
        elif isinstance(v, (np.integer,)):
            out[k] = int(v)
        elif isinstance(v, (np.floating,)):
            out[k] = float(v)
        else:
            out[k] = v
    return out


def _attrs_from_json(attrs):
    out = {}
    for k, v in attrs.items():
        if isinstance(v, dict) and "__ndarray__" in v:
            out[k] = np.asarray(v["__ndarray__"], dtype=v["dtype"])
        else:
            out[k] = v
    return out


class Block:
    """Ordered op list + var table (reference framework.proto:173).

    Sub-blocks (control flow) reference their parent by index like the
    reference's BlockDesc.parent_idx.
    """

    def __init__(self, program: "Program", idx: int, parent_idx: int = -1):
        self.program = program
        self.idx = idx
        self.parent_idx = parent_idx
        self.vars: Dict[str, VarDesc] = {}
        self.ops: List[OpDesc] = []

    # -- var management ---------------------------------------------------
    def create_var(self, name=None, shape=None, dtype="float32",
                   persistable=False, stop_gradient=True, is_data=False,
                   **kwargs) -> "Variable":
        if name is None:
            from ..utils import unique_name
            name = unique_name.generate("tmp")
        desc = VarDesc(name, shape, dtype, persistable, stop_gradient,
                       is_data)
        self.vars[name] = desc
        return Variable(self, desc)

    def var(self, name: str) -> "Variable":
        desc = self._find_var_recursive(name)
        if desc is None:
            raise KeyError(f"Variable {name!r} not found in block {self.idx}")
        return Variable(self, desc)

    def has_var(self, name: str) -> bool:
        return self._find_var_recursive(name) is not None

    def _find_var_recursive(self, name) -> Optional[VarDesc]:
        blk = self
        while blk is not None:
            if name in blk.vars:
                return blk.vars[name]
            blk = (self.program.blocks[blk.parent_idx]
                   if blk.parent_idx >= 0 else None)
        return None

    def all_parameters(self) -> List["Variable"]:
        return [Variable(self, v) for v in self.vars.values()
                if isinstance(v, ParamDesc)]

    # -- op management ----------------------------------------------------
    def append_op(self, type: str, inputs=None, outputs=None, attrs=None):
        op = OpDesc(type, _normalize_slots(inputs), _normalize_slots(outputs),
                    attrs)
        # fluid device_guard scope: record the stage/device assignment on
        # the desc (reference framework.py op_device attr — the pipeline
        # stage-split mechanism); single-chip execution ignores it
        dev = globals().get("_current_op_device")
        if dev is not None:
            op.attrs["op_device"] = dev
        self.ops.append(op)
        self.program._version += 1
        return op

    def prepend_op(self, type: str, inputs=None, outputs=None, attrs=None):
        op = OpDesc(type, _normalize_slots(inputs), _normalize_slots(outputs),
                    attrs)
        self.ops.insert(0, op)
        self.program._version += 1
        return op

    def to_dict(self):
        return {
            "idx": self.idx,
            "parent_idx": self.parent_idx,
            "vars": [v.to_dict() | (
                {"is_parameter": True,
                 "trainable": v.trainable,
                 "initializer": v.initializer_desc}
                if isinstance(v, ParamDesc) else {})
                for v in self.vars.values()],
            "ops": [o.to_dict() for o in self.ops],
        }

    def _load_dict(self, d):
        for vd in d["vars"]:
            if vd.get("is_parameter"):
                desc = ParamDesc(vd["name"], vd["shape"], vd["dtype"],
                                 trainable=vd.get("trainable", True))
                desc.initializer_desc = vd.get("initializer")
                desc.attrs = dict(vd.get("attrs") or {})
            else:
                desc = VarDesc.from_dict(vd)
            self.vars[desc.name] = desc
        self.ops = [OpDesc.from_dict(od) for od in d["ops"]]


class ParamDesc(VarDesc):
    """A persistable, trainable var (reference framework.py:5036 Parameter)."""

    def __init__(self, name, shape, dtype="float32", trainable=True):
        super().__init__(name, shape, dtype, persistable=True,
                         stop_gradient=not trainable)
        self.trainable = trainable
        self.initializer_desc = None  # (op_type, attrs) recorded for startup


def _normalize_slots(slots):
    """Accept {'X': var|name|[vars...]} and normalize to {'X': [names]}."""
    if slots is None:
        return {}
    out = {}
    for k, v in slots.items():
        if v is None:
            continue
        if not isinstance(v, (list, tuple)):
            v = [v]
        names = []
        for item in v:
            if isinstance(item, Variable):
                names.append(item.name)
            elif isinstance(item, VarDesc):
                names.append(item.name)
            else:
                names.append(str(item))
        out[k] = names
    return out


class Variable:
    """User-facing handle to a VarDesc in a block (reference
    framework.py:869 Variable). Supports python operators by appending
    elementwise ops to the block (math_op_patch parity)."""

    def __init__(self, block: Block, desc: VarDesc):
        self.block = block
        self.desc = desc

    # descriptor passthroughs
    name = property(lambda self: self.desc.name)
    shape = property(lambda self: self.desc.shape)
    dtype = property(lambda self: self.desc.dtype)
    persistable = property(lambda self: self.desc.persistable)

    @property
    def stop_gradient(self):
        return self.desc.stop_gradient

    @stop_gradient.setter
    def stop_gradient(self, v):
        self.desc.stop_gradient = v

    @property
    def grad_name(self):
        return grad_var_name(self.name)

    def astype(self, dtype):
        from .layers import cast
        return cast(self, dtype)

    def __repr__(self):
        return (f"static.Variable(name={self.name!r}, shape={self.shape}, "
                f"dtype={self.dtype})")

    # -- operator overloads (appended as graph ops) -----------------------
    def _binary(self, other, op_type, reverse=False):
        from .layers import _elementwise_binary
        return _elementwise_binary(self, other, op_type, reverse)

    def __add__(self, o):
        return self._binary(o, "elementwise_add")

    __radd__ = __add__

    def __sub__(self, o):
        return self._binary(o, "elementwise_sub")

    def __rsub__(self, o):
        return self._binary(o, "elementwise_sub", reverse=True)

    def __mul__(self, o):
        return self._binary(o, "elementwise_mul")

    __rmul__ = __mul__

    def __truediv__(self, o):
        return self._binary(o, "elementwise_div")

    def __rtruediv__(self, o):
        return self._binary(o, "elementwise_div", reverse=True)

    def __pow__(self, o):
        return self._binary(o, "elementwise_pow")

    def __neg__(self):
        from .layers import scale
        return scale(self, -1.0)

    def __matmul__(self, o):
        from .layers import matmul
        return matmul(self, o)


def grad_var_name(name: str) -> str:
    """Reference framework grad suffix (operators append @GRAD)."""
    return name + "@GRAD"


class Program:
    """A whole computation: list of blocks (reference framework.proto:211
    ProgramDesc / framework.py:3917 Program)."""

    def __init__(self):
        self.blocks: List[Block] = [Block(self, 0)]
        self._version = 0
        self._seed: Optional[int] = None
        self.random_seed = 0

    # -- structure --------------------------------------------------------
    @property
    def global_block(self) -> Block:
        return self.blocks[0]

    def current_block(self) -> Block:
        return self.blocks[_current_block_idx(self)]

    def create_block(self, parent_idx: Optional[int] = None) -> Block:
        parent = _current_block_idx(self) if parent_idx is None else parent_idx
        blk = Block(self, len(self.blocks), parent)
        self.blocks.append(blk)
        return blk

    def list_vars(self):
        for blk in self.blocks:
            for v in blk.vars.values():
                yield Variable(blk, v)

    def all_parameters(self):
        out = []
        for blk in self.blocks:
            out.extend(blk.all_parameters())
        return out

    def clone(self, for_test: bool = False) -> "Program":
        """Deep copy; for_test strips optimizer/backward ops and freezes
        dropout/bn to inference behavior (reference Program.clone)."""
        p = Program.from_dict(self.to_dict())
        if for_test:
            from .backward import BACKWARD_OP_TYPES
            from .optimizer import OPTIMIZER_OP_TYPES
            drop = BACKWARD_OP_TYPES | OPTIMIZER_OP_TYPES
            for blk in p.blocks:
                blk.ops = [op for op in blk.ops if op.type not in drop]
                for op in blk.ops:
                    if "is_test" in _TEST_MODE_OPS.get(op.type, ()):
                        op.attrs["is_test"] = True
        return p

    # -- serialization ----------------------------------------------------
    def to_dict(self):
        return {"ir_version": IR_VERSION,
                "random_seed": self.random_seed,
                "blocks": [b.to_dict() for b in self.blocks]}

    @staticmethod
    def from_dict(d) -> "Program":
        assert d["ir_version"] == IR_VERSION, "incompatible IR version"
        p = Program()
        p.random_seed = d.get("random_seed", 0)
        p.blocks = []
        for bd in d["blocks"]:
            blk = Block(p, bd["idx"], bd["parent_idx"])
            blk._load_dict(bd)
            p.blocks.append(blk)
        return p

    def serialize_to_string(self) -> bytes:
        return json.dumps(self.to_dict()).encode("utf-8")

    @staticmethod
    def parse_from_string(s: bytes) -> "Program":
        return Program.from_dict(json.loads(s.decode("utf-8")))

    def __repr__(self):
        n_ops = sum(len(b.ops) for b in self.blocks)
        return f"static.Program({len(self.blocks)} blocks, {n_ops} ops)"

    # pruning (save_inference_model path)
    def prune(self, feed_names: Sequence[str], fetch_names: Sequence[str]):
        """Keep only ops needed to compute fetches from feeds + persistables
        (reference Program._prune, inference/analysis ir_graph_build).

        VarDescs no surviving op references are dropped, and sub-blocks
        reachable only from pruned control-flow ops are emptied (their
        indices stay stable so surviving sub_block attrs keep resolving)
        — save_inference_model blobs carry no dead weight."""
        blk = self.global_block
        needed = set(fetch_names)
        kept = []
        for op in reversed(blk.ops):
            if set(op.output_names()) & needed:
                kept.append(op)
                needed |= set(op.input_names())
        kept.reverse()
        p = Program.from_dict(self.to_dict())
        nb = p.global_block
        nb.ops = [OpDesc.from_dict(o.to_dict()) for o in kept]
        # drop sub-blocks only pruned ops referenced (dead While/cond
        # branches used to ride along whole into the inference blob)
        reachable = {0}
        frontier = [nb]
        while frontier:
            b = frontier.pop()
            for op in b.ops:
                for key in ("sub_block", "sub_block_t", "sub_block_f"):
                    idx = op.attrs.get(key)
                    if isinstance(idx, int) and idx not in reachable:
                        reachable.add(idx)
                        frontier.append(p.blocks[idx])
        for b in p.blocks:
            if b.idx not in reachable:
                b.ops = []
                b.vars = {}
        used = set(feed_names) | set(fetch_names)
        for b in p.blocks:
            for op in b.ops:
                used |= set(op.input_names()) | set(op.output_names())
        nb.vars = {k: v for k, v in nb.vars.items() if k in used}
        return p


# ops whose behavior flips under clone(for_test=True)
_TEST_MODE_OPS = {
    "dropout": ("is_test",),
    "batch_norm": ("is_test",),
}

# -- default program / guard stacks (reference framework.py default_main_
# program etc.) -----------------------------------------------------------
_main_program = Program()
_startup_program = Program()
_block_stack: Dict[int, List[int]] = {}


def _current_block_idx(program: Program) -> int:
    stack = _block_stack.get(id(program))
    return stack[-1] if stack else 0


class _BlockGuard:
    def __init__(self, program: Program, block: Block):
        self.program, self.block = program, block

    def __enter__(self):
        _block_stack.setdefault(id(self.program), []).append(self.block.idx)
        return self.block

    def __exit__(self, *exc):
        _block_stack[id(self.program)].pop()


def default_main_program() -> Program:
    return _main_program


def default_startup_program() -> Program:
    return _startup_program


class program_guard:
    """with program_guard(main, startup): layer calls build into `main`."""

    def __init__(self, main_program: Program,
                 startup_program: Optional[Program] = None):
        self.main = main_program
        self.startup = startup_program

    def __enter__(self):
        global _main_program, _startup_program
        self._saved = (_main_program, _startup_program)
        _main_program = self.main
        if self.startup is not None:
            _startup_program = self.startup
        return self.main

    def __exit__(self, *exc):
        global _main_program, _startup_program
        _main_program, _startup_program = self._saved
