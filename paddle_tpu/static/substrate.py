"""Shared AOT compiled-step substrate.

The ONE build path for compiled device steps, extracted from
``Executor._build`` (ROADMAP-flagged: executor.py had absorbed the
whole build/dispatch stack, and the serving predictor and the LLM
decode engine were each about to grow a near-duplicate of it). Every
compiled-step consumer — the training ``Executor``, the serving
``AnalysisPredictor`` (through ``Executor.run``), and the decode
engine's prefill/decode executables — funnels through
:func:`aot_compile`:

- jit with optional DONATION (state buffers reused in place by XLA)
  and explicit in/out shardings (GSPMD boundary maps, PR 10)
- the lower()/compile() AOT split, so trace time and XLA-compile time
  stay separately measurable (``trace_ms`` / ``compile_ms`` counters)
- the persistent disk compile cache (``PADDLE_COMPILE_CACHE[_DIR]``,
  compile_cache.py) armed before the first compile, so a relaunched
  process pays a disk read instead of a cold build
"""
from __future__ import annotations

import time
from typing import Any, Callable, Optional, Sequence, Tuple

__all__ = ["CompiledStep", "aot_compile"]


class CompiledStep:
    """One AOT-compiled executable plus its build timings.

    ``compiled`` is the raw jax ``Compiled`` object (kept accessible:
    the executor's memory/cost planes read ``compiled.memory_analysis()``
    off it); calling the ``CompiledStep`` dispatches it.

    ``jitted`` is the ``jax.jit`` wrapper the executable was lowered
    from. For per-call-latency-critical loops (the decode engine's
    tick) it is the better dispatch handle: the jit wrapper's C++
    fast path skips the Python argument processing every
    ``Compiled.__call__`` pays, and its own first call recompiles
    through the XLA compilation cache the AOT build just populated —
    same executable, cheaper dispatch."""

    __slots__ = ("compiled", "jitted", "trace_ms", "compile_ms")

    def __init__(self, compiled, trace_ms: float, compile_ms: float,
                 jitted=None):
        self.compiled = compiled
        self.jitted = jitted
        self.trace_ms = trace_ms
        self.compile_ms = compile_ms

    def __call__(self, *args):
        return self.compiled(*args)

    def memory_analysis(self):
        try:
            return self.compiled.memory_analysis()
        except Exception:
            return None


def aot_compile(step_fn: Callable, example_args: Tuple[Any, ...], *,
                donate_argnums: Optional[Sequence[int]] = None,
                in_shardings=None, out_shardings=None,
                bump: Optional[Callable[[str, float], None]] = None
                ) -> CompiledStep:
    """AOT-compile ``step_fn`` against ``example_args``.

    ``donate_argnums``: argument indices whose buffers XLA may reuse in
    place (device-resident state — params, KV pages, rng). Donation is
    a liveness contract, not just an optimization: a donated input is
    dead the moment the step dispatches, so any buffer a caller must
    read back later — e.g. the decode engine's token chain, where the
    previous tick's output feeds the next tick's input while a lagged
    harvest still wants to fetch it — must stay OUT of the donate set.
    ``in_/out_shardings``: jit boundary shardings (omit to let jax
    infer from the committed arguments). ``bump(name, value)``: counter
    sink for the ``trace_ms`` / ``compile_ms`` build timings (the
    executor passes its ``_bump``; pass None to skip accounting)."""
    import jax

    from .compile_cache import ensure_enabled

    ensure_enabled()  # PADDLE_COMPILE_CACHE[_DIR] disk cache, idempotent
    jit_kwargs = {}
    if donate_argnums:
        jit_kwargs["donate_argnums"] = tuple(donate_argnums)
    if in_shardings is not None:
        jit_kwargs["in_shardings"] = in_shardings
    if out_shardings is not None:
        jit_kwargs["out_shardings"] = out_shardings
    jitted = jax.jit(step_fn, **jit_kwargs)
    t0 = time.perf_counter()
    lowered = jitted.lower(*example_args)
    t1 = time.perf_counter()
    compiled = lowered.compile()
    t2 = time.perf_counter()
    trace_ms = round((t1 - t0) * 1e3, 3)
    compile_ms = round((t2 - t1) * 1e3, 3)
    if bump is not None:
        bump("trace_ms", trace_ms)
        bump("compile_ms", compile_ms)
    return CompiledStep(compiled, trace_ms, compile_ms, jitted=jitted)
