"""Async host->device feed pipeline.

The reference overlaps input with compute through C++ double buffering
(operators/reader/buffered_reader.cc: a background thread copies the
next LoDTensor batch to the device while the op loop consumes the
current one). The TPU-native equivalent: a ``FeedPrefetcher`` drives any
batch iterator from a daemon thread, STAGES each batch host->device
(``jax.device_put``, honoring the feed's sharding) into a bounded queue
of configurable depth, and the training loop pops device-resident
batches — the h2d copy of batch N+1 runs while XLA executes step N.

EOF and failure semantics match the queue protocol the reference's
BlockingQueue gives readers: exhaustion surfaces as ``StopIteration``
(py_reader translates it to ``EOFException``), a worker exception is
re-raised in the consumer with the original traceback, and ``close()``
is always safe — it stops the thread, drains the queue, and closes the
source iterator so upstream resources (e.g. DataLoader worker
processes) wind down.
"""
from __future__ import annotations

import queue as queue_mod
import threading
from typing import Any, Callable, Dict, Iterable, Iterator, Optional

import jax
import numpy as np

__all__ = ["FeedPrefetcher", "stage_feed"]


def stage_feed(feed: Dict[str, Any],
               sharding: Optional[Dict[str, Any]] = None,
               feed_dtypes: Optional[Dict[str, Any]] = None
               ) -> Dict[str, Any]:
    """Device-put every host array of a feed dict (per-name sharding when
    given), counting the transferred bytes. Arrays already on device pass
    through untouched. ``feed_dtypes`` (name -> numpy dtype, from
    passes.amp_feed_dtypes) casts float32 feeds HOST-side before the
    copy — under bf16 mixed precision the h2d transfer itself halves."""
    from ..parallel.sharding import device_put_counted

    staged = {}
    for name, val in feed.items():
        if isinstance(val, jax.Array):
            staged[name] = val
            continue
        arr = np.asarray(val)
        if feed_dtypes is not None and name in feed_dtypes \
                and arr.dtype == np.float32:
            arr = arr.astype(feed_dtypes[name])
        staged[name] = device_put_counted(
            arr, sharding.get(name) if sharding else None)
    return staged


class FeedPrefetcher:
    """Iterator adapter: pulls from ``source`` on a daemon thread,
    applies ``stage`` (default :func:`stage_feed`) to each item, and
    buffers up to ``depth`` staged items.

    ``depth`` bounds device memory held by in-flight batches; 1 already
    buys full overlap of one step's h2d with compute, larger depths ride
    out jittery sources. Iteration raises the worker's exception at the
    point of failure and ends cleanly at source exhaustion."""

    _END = object()

    def __init__(self, source: Iterable, depth: int = 2,
                 stage: Optional[Callable] = None,
                 sharding: Optional[Dict[str, Any]] = None,
                 feed_dtypes: Optional[Dict[str, Any]] = None):
        if depth < 1:
            raise ValueError(f"prefetch depth must be >= 1, got {depth}")
        self._source = iter(source)
        self._stage = stage if stage is not None else (
            lambda item: stage_feed(item, sharding, feed_dtypes))
        self._q: queue_mod.Queue = queue_mod.Queue(maxsize=depth)
        self._stop = threading.Event()
        self._err: Optional[BaseException] = None
        self._thread = threading.Thread(target=self._worker, daemon=True,
                                        name="feed-prefetch")
        self._thread.start()

    # -- worker -----------------------------------------------------------
    def _put(self, item) -> bool:
        """Bounded put that notices consumer abandonment (close() while
        the queue is full must not wedge the thread)."""
        while not self._stop.is_set():
            try:
                self._q.put(item, timeout=0.2)
                return True
            except queue_mod.Full:
                continue
        return False

    def _worker(self):
        try:
            for item in self._source:
                if not self._put(self._stage(item)):
                    return
        except BaseException as e:  # re-raised in the consumer
            self._err = e
        finally:
            self._put(self._END)
            # hand upstream resources back promptly (generator finally
            # blocks, DataLoader worker shutdown) instead of waiting for GC
            close = getattr(self._source, "close", None)
            if close is not None:
                try:
                    close()
                except Exception:
                    pass

    # -- consumer ---------------------------------------------------------
    def __iter__(self) -> Iterator:
        return self

    def __next__(self):
        if self._stop.is_set():
            raise StopIteration
        item = self._q.get()
        if item is self._END:
            self._stop.set()
            if self._err is not None:
                err, self._err = self._err, None
                raise err
            raise StopIteration
        return item

    def stop(self):
        """Signal the worker and drop buffered batches WITHOUT joining —
        for teardown paths that must first unblock whatever the worker's
        source is reading (see Executor.train_from_dataset). Idempotent."""
        self._stop.set()
        while True:
            try:
                self._q.get_nowait()
            except queue_mod.Empty:
                break

    def close(self):
        """Stop the worker and drop buffered batches. Idempotent."""
        self.stop()
        self._thread.join(timeout=5.0)

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
