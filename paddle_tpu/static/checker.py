"""Program / op-desc validation.

Reference: /root/reference/tools/check_op_desc.py + the per-op
OpDesc::CheckAttrs / InferShape validation the C++ operator registry ran
at build time. Here descs are JSON + eval_shape-inferred, so the checker
validates the graph-level invariants the reference enforced in C++:

- every op type has a registered kernel
- every op input names a var that exists (in scope) and was produced
  before use (feed/parameter/fetch-order discipline)
- no two ops write the same var name (single-assignment, which the
  executor env relies on)
- fetch targets name vars some op actually produces (pass
  fetch_names=)

`validate_program` raises ProgramValidationError with ALL findings (the
reference printed a batch report, not first-failure).
"""
from __future__ import annotations

from typing import List, Optional

from ..framework.errors import EnforceNotMet
from .ir import Program
from .kernels import KERNELS


class ProgramValidationError(EnforceNotMet):
    def __init__(self, findings: List[str]):
        self.findings = findings
        super().__init__(
            "program validation failed:\n  - " + "\n  - ".join(findings))


def validate_program(program: Program, check_order: bool = True,
                     extra_defined: Optional[set] = None,
                     fetch_names: Optional[List[str]] = None) -> List[str]:
    """Return the list of findings (empty = valid); see module doc.

    check_order=False skips the produced-before-use pass (startup
    programs legitimately read nothing, and some callers append ops out
    of order before a final reorder).
    extra_defined: var names provided externally (e.g. by a paired
    startup program or feed dict).
    """
    findings: List[str] = []
    block_final_produced = {}
    for block in program.blocks:
        produced = set(extra_defined or ())
        # a sub-block sees everything its ancestors produced
        parent = getattr(block, "parent_idx", -1)
        while parent not in (-1, None):
            produced |= block_final_produced.get(parent, set())
            parent = getattr(program.blocks[parent], "parent_idx", -1)
        # parameters + feed targets are live before any op runs
        for name, desc in block.vars.items():
            if getattr(desc, "initializer_desc", None) is not None \
                    or getattr(desc, "is_data", False) \
                    or getattr(desc, "persistable", False):
                produced.add(name)
        written = {}
        for i, op in enumerate(block.ops):
            # executor-native pseudo-ops with no kernel entry
            # (static/executor.py run_block): the backward region marker
            # and feed/fetch bookkeeping
            if op.type in ("backward", "feed", "fetch"):
                for slot, names in op.outputs.items():
                    produced.update(names)
                continue
            if op.type not in KERNELS:
                findings.append(
                    f"block {block.idx} op #{i}: no kernel registered "
                    f"for type {op.type!r}")
            for slot, names in op.inputs.items():
                for n in names:
                    if not block.has_var(n):
                        findings.append(
                            f"block {block.idx} op #{i} ({op.type}) input "
                            f"{slot}: var {n!r} does not exist")
                    elif check_order and n not in produced and \
                            n not in written:
                        findings.append(
                            f"block {block.idx} op #{i} ({op.type}) input "
                            f"{slot}: var {n!r} used before it is "
                            "produced (feed it, make it persistable, or "
                            "reorder ops)")
            for slot, names in op.outputs.items():
                for n in names:
                    if n in written and op.type not in (
                            "assign", "increment", "fill_constant"):
                        findings.append(
                            f"block {block.idx} op #{i} ({op.type}) "
                            f"output {slot}: var {n!r} already written by "
                            f"op #{written[n]} (single-assignment)")
                    written[n] = i
                    produced.add(n)
                    if not block.has_var(n):
                        findings.append(
                            f"block {block.idx} op #{i} ({op.type}) "
                            f"output {slot}: var {n!r} has no VarDesc")
        block_final_produced[block.idx] = produced
    if fetch_names:
        all_produced = set()
        for s in block_final_produced.values():
            all_produced |= s
        for n in fetch_names:
            name = getattr(n, "name", n)
            if name not in all_produced:
                findings.append(
                    f"fetch target {name!r} is never produced by any op")
    return findings


def check_program(program: Program, **kw) -> None:
    """Raise ProgramValidationError when validate_program finds issues."""
    findings = validate_program(program, **kw)
    if findings:
        raise ProgramValidationError(findings)


def compare_op_signatures(old_spec_path: str, new_spec_path: str):
    """Diff two API.spec dumps (reference check_op_desc.py printed an
    added/deleted/changed report for op protos across versions)."""
    def load(p):
        out = {}
        with open(p) as f:
            for line in f:
                line = line.strip()
                if not line or " " not in line:
                    continue
                name, sig = line.split(" ", 1)
                out[name] = sig
        return out

    old, new = load(old_spec_path), load(new_spec_path)
    return {
        "added": sorted(set(new) - set(old)),
        "deleted": sorted(set(old) - set(new)),
        "changed": sorted(n for n in set(old) & set(new)
                          if old[n] != new[n]),
    }
