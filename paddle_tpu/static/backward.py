"""append_backward: reverse-mode autodiff for static programs.

Reference: /root/reference/python/paddle/fluid/backward.py:1215
append_backward walks the op list and appends one hand-written grad op per
forward op (OpDesc rewriting, ~1.8K LoC + a grad-op maker per C++ op).

TPU-native design: gradients come from jax.vjp over the traced forward
section instead of per-op grad rewriting — one `backward` OpDesc marks the
boundary; at lowering time (executor.run_block) it re-traces ops [0, idx)
as a pure function of the trainable params and pulls all grads in a single
vjp. XLA CSEs the duplicated forward. Grad vars keep the reference's
`name@GRAD` convention so optimizer ops are wired identically.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Set

import jax
import jax.numpy as jnp

from .ir import Block, ParamDesc, Program, Variable, grad_var_name

BACKWARD_OP_TYPES = {"backward"}


def append_backward(loss: Variable,
                    parameter_list: Optional[Sequence] = None,
                    no_grad_set: Optional[Set[str]] = None,
                    checkpoints: Optional[Sequence] = None):
    """Append the backward op; returns [(param, grad_var), ...].

    checkpoints: variable names marking rematerialization boundaries
    (reference _append_backward_ops_with_checkpoints_ backward.py:629);
    lowered to jax.checkpoint over the forward section.
    """
    block = loss.block
    no_grad = {n if isinstance(n, str) else n.name
               for n in (no_grad_set or ())}
    if parameter_list is not None:
        params = [p if isinstance(p, str) else p.name
                  for p in parameter_list]
    else:
        params = [v.name for v in block.vars.values()
                  if isinstance(v, ParamDesc) and v.trainable]
    params = [p for p in params if p not in no_grad]
    if not params:
        raise ValueError("append_backward: no trainable parameters found")

    grad_names = []
    for p in params:
        pdesc = block.vars[p]
        gname = grad_var_name(p)
        block.create_var(name=gname, shape=pdesc.shape, dtype=pdesc.dtype,
                         stop_gradient=True)
        grad_names.append(gname)

    block.append_op(
        type="backward",
        inputs={"Loss": [loss.name], "Params": params},
        outputs={"Grads": grad_names},
        attrs={"use_checkpoint": bool(checkpoints),
               "checkpoints": [c if isinstance(c, str) else c.name
                               for c in (checkpoints or [])]},
    )
    return [(block.var(p), block.var(g)) for p, g in zip(params, grad_names)]


def _remat_plan(ops, idx):
    """Group ops [0, idx) into maximal runs of equal ``__remat_seg``
    stamp (the recompute_segmentation pass, static/passes.py). Returns
    [(start, end, wrapped), ...] covering the range, or None when no op
    is stamped (remat off)."""
    segs = []
    cur = None  # (seg_id_or_None, start)
    found = False
    for i in range(idx):
        sid = ops[i].attrs.get("__remat_seg")
        if sid is not None:
            found = True
        if cur is None:
            cur = (sid, i)
        elif sid != cur[0]:
            segs.append((cur[1], i, cur[0] is not None))
            cur = (sid, i)
    if cur is not None:
        segs.append((cur[1], idx, cur[0] is not None))
    return segs if found else None


def run_backward_op(block: Block, idx: int, op, env: Dict, ctx):
    """Lower the `backward` op inside run_block's trace (see executor.py).

    With ``__remat_seg`` stamps present (BuildStrategy.recompute), the
    forward re-trace runs segment by segment, each wrapped in
    ``jax.checkpoint``: only the env values LIVE at a segment boundary
    are saved for the backward pass (the env is pruned to the names
    later ops still read), interior activations are recomputed.
    jax.checkpoint replays the segment with the same closed-over RNG key
    and the kernels fold the same absolute ``__rng_slot``/op index, so a
    recomputed dropout draws the bitwise-identical mask — the invariant
    tests/test_recompute.py pins."""
    from .executor import run_block
    from .kernels import ExecContext

    params: List[str] = op.inputs["Params"]
    loss_name = op.inputs["Loss"][0]
    pset = set(params)
    base_env = {k: v for k, v in ctx.initial_env.items() if k not in pset}

    # For a param produced by an op in [0, idx) (calc_gradient w.r.t. an
    # intermediate var), injecting it at entry isn't enough — its producer
    # would overwrite it and disconnect it from the loss. Inject AFTER its
    # last producer runs instead, so all downstream consumers read the
    # traced free input.
    last_producer = {}
    for j, o in enumerate(block.ops[:idx]):
        for n in o.output_names():
            if n in pset:
                last_producer[n] = j

    segs = _remat_plan(block.ops, idx)

    def forward(pvals):
        pmap = dict(zip(params, pvals))
        env2 = dict(base_env)
        env2.update({p: v for p, v in pmap.items()
                     if p not in last_producer})
        post = {}
        for p, j in last_producer.items():
            post.setdefault(j, {})[p] = pmap[p]
        ctx2 = ExecContext(rng_key=ctx.rng_key, is_test=ctx.is_test)
        ctx2.initial_env = env2  # nested backward unsupported but harmless
        if segs is None:
            env2 = run_block(block, env2, ctx2, stop_at=idx,
                             post_writes=post)
            return env2[loss_name]
        live_at = _segment_liveness(block, segs, idx, loss_name)
        for start, end, wrapped in segs:
            def run_range(env_in, _s=start, _e=end):
                c = ExecContext(rng_key=ctx.rng_key, is_test=ctx.is_test)
                c.initial_env = ctx2.initial_env
                return run_block(block, dict(env_in), c, stop_at=_e,
                                 post_writes=post, start=_s)
            if wrapped:
                live = live_at[start]
                env_in = (env2 if live is None else
                          {n: v for n, v in env2.items() if n in live})
                env2 = jax.checkpoint(run_range)(env_in)
            else:
                env2 = run_range(env2)
        return env2[loss_name]

    fwd = forward
    if segs is None and op.attrs.get("use_checkpoint"):
        # legacy whole-forward checkpoint (append_backward checkpoints
        # without the segmentation pass, e.g. PADDLE_IR_PASSES=0)
        fwd = jax.checkpoint(forward)

    primal, vjp = jax.vjp(fwd, [env[p] for p in params])
    (grads,) = vjp(jnp.ones_like(primal))
    if segs is not None or op.attrs.get("use_checkpoint"):
        # hand the (bitwise-identical) checkpointed primal to the fetch
        # path: the outer un-checkpointed forward chain feeding the loss
        # becomes dead and XLA DCEs it instead of keeping its
        # activations alive next to the remat segments
        env[loss_name] = primal
    for gname, g in zip(op.outputs["Grads"], grads):
        env[gname] = g


def _segment_liveness(block, segs, idx, loss_name):
    """{segment start -> live name set (or None = keep all)}: the env
    entries a checkpointed segment must receive — names any op in
    [start, idx) still reads, plus the loss. Pruning the rest is what
    actually frees memory: an unpruned dict would thread every dead
    intermediate through every later checkpoint as a saved residual.
    Control flow in the remaining range keeps everything (cond/while
    kernels snapshot the whole env)."""
    reads_after: set = {loss_name}
    has_cf_after = False
    live_at = {}
    for start, end, _wrapped in reversed(segs):
        for i in range(end - 1, start - 1, -1):
            op = block.ops[i]
            if op.type in ("cond", "while"):
                has_cf_after = True
            reads_after.update(op.input_names())
        live_at[start] = None if has_cf_after else set(reads_after)
    return live_at


def calc_gradient(targets, inputs, target_gradients=None):
    """Reference backward.py:1665 calc_gradient parity: appends a backward
    op differentiating `targets` w.r.t. arbitrary `inputs` (not only
    params). Multiple targets / user cotangents are folded into one scalar
    loss  sum_i <t_i, tg_i>  (tg_i defaults to ones) so a single vjp
    yields the same gradients the reference accumulates per-op."""
    from . import layers

    if not isinstance(targets, (list, tuple)):
        targets = [targets]
    if not isinstance(inputs, (list, tuple)):
        inputs = [inputs]
    if target_gradients is None:
        target_gradients = [None] * len(targets)
    if not isinstance(target_gradients, (list, tuple)):
        target_gradients = [target_gradients]
    assert len(target_gradients) == len(targets), \
        "target_gradients must match targets"

    block = targets[0].block
    # the folding ops must land in the targets' program even when called
    # outside its program_guard
    from .ir import program_guard
    with program_guard(block.program):
        parts = []
        for t, tg in zip(targets, target_gradients):
            parts.append(layers.reduce_sum(
                t if tg is None else layers.elementwise_mul(t, tg)))
        total = parts[0] if len(parts) == 1 else layers.sums(parts)

    grad_names = []
    for v in inputs:
        gname = grad_var_name(v.name)
        # repeated differentiation w.r.t. the same var (double grad:
        # calc_gradient of a calc_gradient output) must not clobber the
        # earlier gradient — uniquify like the reference's _rename_grad_
        if gname in block.vars:
            k = 1
            while f"{gname}@{k}" in block.vars:
                k += 1
            gname = f"{gname}@{k}"
        block.create_var(name=gname, shape=v.shape, dtype=v.dtype,
                         stop_gradient=True)
        grad_names.append(gname)
    block.append_op(
        type="backward",
        inputs={"Loss": [total.name],
                "Params": [v.name for v in inputs]},
        outputs={"Grads": grad_names},
        attrs={"use_checkpoint": False, "checkpoints": []},
    )
    return [block.var(g) for g in grad_names]
