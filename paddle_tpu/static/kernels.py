"""Static-graph op kernels: op_type -> pure jnp function over named slots.

TPU-native counterpart of the reference kernel registry
(/root/reference/paddle/fluid/framework/op_registry.h:268
REGISTER_OP_CPU_KERNEL + operator.cc:1068 ChooseKernel). There is no
(place, dtype, layout) dispatch: one kernel per op, written in jnp, lowered
by XLA for whatever backend jit targets. Kernels are pure; stateful ops
(optimizers, batch_norm running stats) return their updated tensors and the
executor writes them back to the scope (functional state, no mutation).

Kernel signature: fn(ins: dict slot->list[jax.Array], attrs: dict,
ctx: ExecContext) -> dict slot->list[jax.Array].
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List

import jax
import jax.numpy as jnp

from ..framework import dtype as dtype_mod

KERNELS: Dict[str, Callable] = {}


@dataclass
class ExecContext:
    """Per-lowering context threaded to kernels that need RNG or step."""
    rng_key: Any = None          # jax PRNGKey (traced)
    op_index: int = 0            # position in block, folds into the key
    is_test: bool = False
    program: Any = None          # set by run_block: owning Program
    env: Any = None              # set by run_block: live name->array env
                                 # (control-flow kernels snapshot it)

    def key(self):
        return jax.random.fold_in(self.rng_key, self.op_index)


def kernel(op_type):
    def deco(fn):
        KERNELS[op_type] = fn
        fn.op_type = op_type
        return fn
    return deco


def _x(ins, slot="X"):
    return ins[slot][0]


def _dt(name):
    return dtype_mod.convert_dtype(name)


def _out(*arrays, slot="Out"):
    return {slot: list(arrays)}


# ---------------------------------------------------------------------------
# creation / initialization (startup-program ops; reference
# operators/fill_constant_op.cc, gaussian_random_op.cc, uniform_random_op.cc)
# ---------------------------------------------------------------------------
@kernel("fill_constant")
def _fill_constant(ins, attrs, ctx):
    shape = tuple(attrs["shape"])
    return _out(jnp.full(shape, attrs["value"], _dt(attrs["dtype"])))


@kernel("gaussian_random")
def _gaussian_random(ins, attrs, ctx):
    shape = tuple(attrs["shape"])
    out = attrs.get("mean", 0.0) + attrs.get("std", 1.0) * jax.random.normal(
        ctx.key(), shape, _dt(attrs.get("dtype", "float32")))
    return _out(out)


@kernel("uniform_random")
def _uniform_random(ins, attrs, ctx):
    shape = tuple(attrs["shape"])
    return _out(jax.random.uniform(
        ctx.key(), shape, _dt(attrs.get("dtype", "float32")),
        attrs.get("min", -1.0), attrs.get("max", 1.0)))


@kernel("truncated_gaussian_random")
def _trunc_gaussian(ins, attrs, ctx):
    shape = tuple(attrs["shape"])
    std = attrs.get("std", 1.0)
    out = attrs.get("mean", 0.0) + std * jax.random.truncated_normal(
        ctx.key(), -2.0, 2.0, shape, _dt(attrs.get("dtype", "float32")))
    return _out(out)


@kernel("assign_value")
def _assign_value(ins, attrs, ctx):
    import numpy as np
    vals = np.asarray(attrs["values"], dtype=attrs.get("dtype", "float32"))
    return _out(jnp.asarray(vals.reshape(tuple(attrs["shape"]))))


# ---------------------------------------------------------------------------
# elementwise (reference operators/elementwise/) — numpy broadcasting; the
# reference's `axis` attr aligns a lower-rank Y at a given axis
# ---------------------------------------------------------------------------
def _align(x, y, axis):
    if axis in (None, -1) or y.ndim == x.ndim:
        return y
    return y.reshape(y.shape + (1,) * (x.ndim - axis - y.ndim))


def _ew(op_type, fn):
    @kernel(op_type)
    def k(ins, attrs, ctx, _fn=fn):
        x, y = _x(ins), ins["Y"][0]
        return _out(_fn(x, _align(x, y, attrs.get("axis", -1))))
    return k


_ew("elementwise_add", jnp.add)
_ew("elementwise_sub", jnp.subtract)
_ew("elementwise_mul", jnp.multiply)
_ew("elementwise_div", jnp.divide)
_ew("elementwise_max", jnp.maximum)
_ew("elementwise_min", jnp.minimum)
_ew("elementwise_pow", jnp.power)
_ew("elementwise_mod", jnp.mod)
_ew("elementwise_floordiv", jnp.floor_divide)


@kernel("scale")
def _scale(ins, attrs, ctx):
    x = _x(ins)
    s, b = attrs.get("scale", 1.0), attrs.get("bias", 0.0)
    if attrs.get("bias_after_scale", True):
        return _out(x * s + b)
    return _out((x + b) * s)


@kernel("cast")
def _cast(ins, attrs, ctx):
    return _out(_x(ins).astype(_dt(attrs["out_dtype"])))


@kernel("clip")
def _clip(ins, attrs, ctx):
    return _out(jnp.clip(_x(ins), attrs.get("min"), attrs.get("max")))


# unary activations (reference operators/activation_op.cc)
def _unary(op_type, fn):
    @kernel(op_type)
    def k(ins, attrs, ctx, _fn=fn):
        return _out(_fn(_x(ins)))
    return k


_unary("relu", jax.nn.relu)
_unary("sigmoid", jax.nn.sigmoid)
_unary("tanh", jnp.tanh)
_unary("exp", jnp.exp)
_unary("log", jnp.log)
_unary("sqrt", jnp.sqrt)
_unary("rsqrt", jax.lax.rsqrt)
_unary("square", jnp.square)
_unary("abs", jnp.abs)
_unary("floor", jnp.floor)
_unary("ceil", jnp.ceil)
_unary("round", jnp.round)
_unary("reciprocal", jnp.reciprocal)
_unary("sign", jnp.sign)
_unary("softsign", jax.nn.soft_sign)
_unary("softplus", jax.nn.softplus)
_unary("cos", jnp.cos)
_unary("sin", jnp.sin)
_unary("acos", jnp.arccos)
_unary("asin", jnp.arcsin)
_unary("atan", jnp.arctan)
_unary("sinh", jnp.sinh)
_unary("cosh", jnp.cosh)
_unary("erf", jax.scipy.special.erf)
_unary("logsigmoid", jax.nn.log_sigmoid)
_unary("tanh_shrink", lambda x: x - jnp.tanh(x))


@kernel("cumsum")
def _cumsum(ins, attrs, ctx):
    x = _x(ins)
    axis = attrs.get("axis", -1)
    if attrs.get("flatten"):
        x, axis = x.reshape(-1), 0
    if attrs.get("reverse"):
        out = jnp.flip(jnp.cumsum(jnp.flip(x, axis), axis=axis), axis)
    else:
        out = jnp.cumsum(x, axis=axis)
    if attrs.get("exclusive"):
        out = out - x
    return _out(out)


@kernel("softshrink")
def _softshrink(ins, attrs, ctx):
    x = _x(ins)
    lam = attrs.get("lambda", 0.5)
    return _out(jnp.where(x > lam, x - lam,
                          jnp.where(x < -lam, x + lam, 0.0)).astype(x.dtype))


@kernel("hard_shrink")
def _hard_shrink(ins, attrs, ctx):
    x = _x(ins)
    t = attrs.get("threshold", 0.5)
    return _out(jnp.where(jnp.abs(x) > t, x, 0.0).astype(x.dtype))


@kernel("thresholded_relu")
def _thresholded_relu(ins, attrs, ctx):
    x = _x(ins)
    t = attrs.get("threshold", 1.0)
    return _out(jnp.where(x > t, x, 0.0).astype(x.dtype))


@kernel("gelu")
def _gelu(ins, attrs, ctx):
    return _out(jax.nn.gelu(_x(ins), approximate=attrs.get("approximate",
                                                           False)))


@kernel("leaky_relu")
def _leaky_relu(ins, attrs, ctx):
    return _out(jax.nn.leaky_relu(_x(ins), attrs.get("alpha", 0.02)))


@kernel("hard_swish")
def _hard_swish(ins, attrs, ctx):
    return _out(jax.nn.hard_swish(_x(ins)))


@kernel("swish")
def _swish(ins, attrs, ctx):
    x = _x(ins)
    return _out(x * jax.nn.sigmoid(attrs.get("beta", 1.0) * x))


@kernel("pow")
def _pow(ins, attrs, ctx):
    return _out(jnp.power(_x(ins), attrs.get("factor", 1.0)))


@kernel("fake_quantize_dequantize_abs_max")
def _fake_quantize_dequantize_abs_max(ins, attrs, ctx):
    """Simulated quantization (reference fake_quantize_op.cc
    FakeQuantizeDequantizeAbsMax): quantize to bit_length ints at the
    dynamic abs-max scale, dequantize back, straight-through gradient
    (the jax.vjp over this forward sees identity). Used by
    contrib.QuantizeTranspiler.training_transpile."""
    x = _x(ins)
    bits = int(attrs.get("bit_length", 8))
    qmax = float(2 ** (bits - 1) - 1)
    scale = jnp.maximum(jnp.max(jnp.abs(x)), 1e-8)
    if attrs.get("is_test", False) and "InScale" in ins:
        scale = ins["InScale"][0]
    # clip BEFORE rounding: values beyond the (frozen) scale must
    # saturate exactly like the deployed int8 model would
    q = jnp.round(jnp.clip(x / scale, -1.0, 1.0) * qmax) / qmax * scale
    out = x + jax.lax.stop_gradient(q - x)
    return {"Out": [out], "OutScale": [scale]}


@kernel("fused_elemwise_activation")
def _fused_elemwise_activation(ins, attrs, ctx):
    """Fused binary-elementwise + activation (reference
    operators/fused/fused_elemwise_activation_op.cc, emitted by
    fuse_elewise_add_act_pass). The IR fusion pass (static/passes.py)
    lowers matched elementwise->act chains onto this kernel; it
    delegates to the registered component kernels so the math stays
    bit-identical to the unfused pair."""
    functors = attrs["functor_list"]
    binary_t, act_t = functors[0], functors[1]
    mid = KERNELS[binary_t]({"X": ins["X"], "Y": ins["Y"]},
                            {"axis": attrs.get("axis", -1)}, ctx)["Out"]
    out = KERNELS[act_t]({"X": mid}, dict(attrs.get("act_attrs") or {}),
                         ctx)["Out"]
    return {"Out": out}


# ---------------------------------------------------------------------------
# matmul / fc (reference operators/matmul_op.cc, mul_op.cc, math/fc.cc)
# ---------------------------------------------------------------------------
@kernel("matmul")
def _matmul(ins, attrs, ctx):
    x, y = _x(ins), ins["Y"][0]
    if attrs.get("transpose_X", False):
        x = jnp.swapaxes(x, -1, -2)
    if attrs.get("transpose_Y", False):
        y = jnp.swapaxes(y, -1, -2)
    out = jnp.matmul(x, y)
    alpha = attrs.get("alpha", 1.0)
    if alpha != 1.0:
        out = out * alpha
    return _out(out)


@kernel("mul")
def _mul(ins, attrs, ctx):
    """Flattening matmul: x flattened to 2D at num_col_dims (reference
    mul_op.cc x_num_col_dims)."""
    x, y = _x(ins), ins["Y"][0]
    xnc = attrs.get("x_num_col_dims", 1)
    ync = attrs.get("y_num_col_dims", 1)
    xs, ys = x.shape, y.shape
    x2 = x.reshape((-1, _prod(xs[xnc:])))
    y2 = y.reshape((int(_prod(ys[:ync])), -1))
    out = x2 @ y2
    return _out(out.reshape(xs[:xnc] + ys[ync:]))


def _prod(t):
    r = 1
    for v in t:
        r *= int(v)
    return r


# ---------------------------------------------------------------------------
# reductions (reference operators/reduce_ops/)
# ---------------------------------------------------------------------------
def _reduce(op_type, fn):
    @kernel(op_type)
    def k(ins, attrs, ctx, _fn=fn):
        dims = attrs.get("dim")
        if attrs.get("reduce_all", False) or dims is None:
            axis = None
        else:
            axis = tuple(dims) if isinstance(dims, (list, tuple)) else (dims,)
        return _out(_fn(_x(ins), axis=axis,
                        keepdims=attrs.get("keep_dim", False)))
    return k


_reduce("reduce_sum", jnp.sum)
_reduce("reduce_mean", jnp.mean)
_reduce("reduce_max", jnp.max)
_reduce("reduce_min", jnp.min)
_reduce("reduce_prod", jnp.prod)
_reduce("reduce_any", jnp.any)
_reduce("reduce_all", jnp.all)


@kernel("mean")
def _mean(ins, attrs, ctx):
    return _out(jnp.mean(_x(ins)))


@kernel("sum")
def _sum_op(ins, attrs, ctx):
    xs = ins["X"]
    out = xs[0]
    for x in xs[1:]:
        out = out + x
    return _out(out)


# ---------------------------------------------------------------------------
# shape manipulation (reference reshape_op.cc, transpose_op.cc, concat_op.cc)
# ---------------------------------------------------------------------------
@kernel("reshape2")
def _reshape(ins, attrs, ctx):
    x = _x(ins)
    shape = [int(s) for s in attrs["shape"]]
    # paddle semantics: 0 means copy input dim, -1 inferred
    shape = [x.shape[i] if s == 0 else s for i, s in enumerate(shape)]
    return _out(jnp.reshape(x, shape))


@kernel("transpose2")
def _transpose(ins, attrs, ctx):
    return _out(jnp.transpose(_x(ins), attrs["axis"]))


@kernel("concat")
def _concat(ins, attrs, ctx):
    return _out(jnp.concatenate(ins["X"], axis=attrs.get("axis", 0)))


@kernel("split")
def _split(ins, attrs, ctx):
    x = _x(ins)
    axis = attrs.get("axis", 0)
    num = attrs.get("num", 0)
    sections = attrs.get("sections")
    if sections:
        idx, acc = [], 0
        for s in sections[:-1]:
            acc += int(s)
            idx.append(acc)
        outs = jnp.split(x, idx, axis=axis)
    else:
        outs = jnp.split(x, num, axis=axis)
    return _out(*outs)


@kernel("stack")
def _stack(ins, attrs, ctx):
    return _out(jnp.stack(ins["X"], axis=attrs.get("axis", 0)), slot="Y")


@kernel("squeeze2")
def _squeeze(ins, attrs, ctx):
    axes = attrs.get("axes") or None
    return _out(jnp.squeeze(_x(ins), axis=tuple(axes) if axes else None))


@kernel("unsqueeze2")
def _unsqueeze(ins, attrs, ctx):
    return _out(jnp.expand_dims(_x(ins), tuple(attrs["axes"])))


@kernel("slice")
def _slice(ins, attrs, ctx):
    x = ins.get("Input", ins.get("X"))[0]
    idx = [slice(None)] * x.ndim
    for ax, st, en in zip(attrs["axes"], attrs["starts"], attrs["ends"]):
        idx[ax] = slice(st, en if en < 2 ** 31 - 1 else None)
    return _out(x[tuple(idx)])


@kernel("expand_as")
def _expand_as(ins, attrs, ctx):
    return _out(jnp.broadcast_to(_x(ins), ins["target_tensor"][0].shape))


@kernel("expand")
def _expand(ins, attrs, ctx):
    x = _x(ins)
    times = attrs["expand_times"]
    return _out(jnp.tile(x, times))


@kernel("flatten2")
def _flatten(ins, attrs, ctx):
    x = _x(ins)
    ax = attrs.get("axis", 1)
    lead = _prod(x.shape[:ax])
    return _out(x.reshape((lead, -1)))


@kernel("shape")
def _shape(ins, attrs, ctx):
    x = ins.get("X", ins.get("Input"))[0]
    return _out(jnp.asarray(x.shape, jnp.int32))


@kernel("lookup_table_v2")
def _lookup_table(ins, attrs, ctx):
    w, ids = ins["W"][0], ins["Ids"][0]
    out = jnp.take(w, ids, axis=0)
    pad = attrs.get("padding_idx", -1)
    if pad is not None and pad >= 0:
        out = jnp.where((ids == pad)[..., None], 0.0, out)
    return _out(out)


@kernel("one_hot_v2")
def _one_hot(ins, attrs, ctx):
    return _out(jax.nn.one_hot(_x(ins), attrs["depth"], dtype=jnp.float32))


@kernel("arg_max")
def _arg_max(ins, attrs, ctx):
    # reference arg_max outputs int64 (truncates to int32 without x64)
    return _out(jnp.argmax(_x(ins), axis=attrs.get("axis", -1))
                .astype(jnp.int64))


@kernel("top_k_v2")
def _top_k(ins, attrs, ctx):
    vals, idx = jax.lax.top_k(_x(ins), attrs["k"])
    return {"Out": [vals], "Indices": [idx.astype(jnp.int32)]}


@kernel("gather")
def _gather(ins, attrs, ctx):
    return _out(jnp.take(_x(ins), ins["Index"][0],
                         axis=attrs.get("axis", 0)))


@kernel("where")
def _where(ins, attrs, ctx):
    return _out(jnp.where(ins["Condition"][0], _x(ins), ins["Y"][0]))


@kernel("masked_select_rows")
def _masked_select_rows(ins, attrs, ctx):
    """Row-wise merge for the IfElse construct (legacy_flow.py): rows
    where the (batch, 1) mask is true come from X, else from Y."""
    m = ins["Mask"][0].astype(bool).reshape(-1)
    x = _x(ins)
    while m.ndim < x.ndim:
        m = m[..., None]
    return _out(jnp.where(m, x, ins["Y"][0]))


@kernel("fill_zeros_like")
def _fill_zeros_like(ins, attrs, ctx):
    return _out(jnp.zeros_like(_x(ins)))


@kernel("assign")
def _assign(ins, attrs, ctx):
    return _out(_x(ins))


# comparison / logical (reference operators/controlflow/compare_op.cc)
for _t, _f in [("equal", jnp.equal), ("not_equal", jnp.not_equal),
               ("less_than", jnp.less), ("less_equal", jnp.less_equal),
               ("greater_than", jnp.greater),
               ("greater_equal", jnp.greater_equal)]:
    _ew(_t, _f)

_unary("logical_not", jnp.logical_not)
_ew("logical_and", jnp.logical_and)
_ew("logical_or", jnp.logical_or)
_ew("logical_xor", jnp.logical_xor)


# ---------------------------------------------------------------------------
# NN ops (reference softmax_op.cc, cross_entropy_op.cc, conv_op.cc,
# pool_op.cc, batch_norm_op.cc, layer_norm_op.cc, dropout_op.cc)
# ---------------------------------------------------------------------------
@kernel("softmax")
def _softmax(ins, attrs, ctx):
    return _out(jax.nn.softmax(_x(ins), axis=attrs.get("axis", -1)))


@kernel("log_softmax")
def _log_softmax(ins, attrs, ctx):
    return _out(jax.nn.log_softmax(_x(ins), axis=attrs.get("axis", -1)))


@kernel("cross_entropy")
def _cross_entropy(ins, attrs, ctx):
    x, label = _x(ins), ins["Label"][0]
    if attrs.get("soft_label", False):
        loss = -jnp.sum(label * jnp.log(x + 1e-12), axis=-1, keepdims=True)
    else:
        picked = jnp.take_along_axis(
            x, label.astype(jnp.int32).reshape(label.shape[:1] + (1,)),
            axis=-1)
        loss = -jnp.log(picked + 1e-12)
    return _out(loss, slot="Y")


@kernel("softmax_with_cross_entropy")
def _softmax_ce(ins, attrs, ctx):
    logits, label = ins["Logits"][0], ins["Label"][0]
    logp = jax.nn.log_softmax(logits, axis=-1)
    if attrs.get("soft_label", False):
        loss = -jnp.sum(label * logp, axis=-1, keepdims=True)
    else:
        lab = label.astype(jnp.int32)
        if lab.ndim == logits.ndim:
            lab = lab[..., 0]
        loss = -jnp.take_along_axis(logp, lab[..., None], axis=-1)
    return {"Softmax": [jnp.exp(logp)], "Loss": [loss]}


@kernel("accuracy")
def _accuracy(ins, attrs, ctx):
    pred, label = _x(ins, "Out"), ins["Label"][0]
    k = attrs.get("k", 1)
    _, topk_idx = jax.lax.top_k(pred, k)
    lab = label.reshape(pred.shape[0], 1).astype(topk_idx.dtype)
    hit = jnp.any(topk_idx == lab, axis=-1)
    correct = jnp.sum(hit)
    total = pred.shape[0]
    acc = correct.astype(jnp.float32) / total
    return {"Accuracy": [acc], "Correct": [correct.astype(jnp.int32)],
            "Total": [jnp.asarray(total, jnp.int32)]}


@kernel("dropout")
def _dropout(ins, attrs, ctx):
    x = _x(ins)
    p = attrs.get("dropout_prob", 0.5)
    if attrs.get("is_test", False) or ctx.is_test or p == 0.0:
        mask = jnp.ones_like(x)
        return {"Out": [x], "Mask": [mask]}
    keep = jax.random.bernoulli(ctx.key(), 1.0 - p, x.shape)
    impl = attrs.get("dropout_implementation", "upscale_in_train")
    if impl == "upscale_in_train":
        out = jnp.where(keep, x / (1.0 - p), 0.0)
    else:
        out = jnp.where(keep, x, 0.0)
    return {"Out": [out], "Mask": [keep.astype(x.dtype)]}


@kernel("conv2d")
def _conv2d(ins, attrs, ctx):
    x, w = ins["Input"][0], ins["Filter"][0]
    stride = tuple(attrs.get("strides", [1, 1]))
    pad = attrs.get("paddings", [0, 0])
    dil = tuple(attrs.get("dilations", [1, 1]))
    groups = attrs.get("groups", 1)
    if len(pad) == 2:
        pad = [(pad[0], pad[0]), (pad[1], pad[1])]
    else:
        pad = [(pad[0], pad[1]), (pad[2], pad[3])]
    # no preferred_element_type: MXU accumulates bf16 convs in f32
    # natively, and an f32 output breaks the conv transpose rule under
    # append_backward (f32 cotangent vs bf16 operands)
    out = jax.lax.conv_general_dilated(
        x, w, stride, pad, rhs_dilation=dil, feature_group_count=groups,
        dimension_numbers=("NCHW", "OIHW", "NCHW"))
    return _out(out, slot="Output")


@kernel("pool2d")
def _pool2d(ins, attrs, ctx):
    x = _x(ins)
    ptype = attrs.get("pooling_type", "max")
    if attrs.get("global_pooling", False):
        if ptype == "max":
            return _out(jnp.max(x, axis=(2, 3), keepdims=True))
        return _out(jnp.mean(x, axis=(2, 3), keepdims=True))
    k = tuple(attrs["ksize"])
    s = tuple(attrs.get("strides", k))
    p = attrs.get("paddings", [0, 0])
    pads = [(0, 0), (0, 0), (p[0], p[0]), (p[1], p[1])]
    window = (1, 1) + k
    strides = (1, 1) + s
    if ptype == "max":
        out = jax.lax.reduce_window(x, -jnp.inf, jax.lax.max, window,
                                    strides, pads)
    else:
        summed = jax.lax.reduce_window(x, 0.0, jax.lax.add, window, strides,
                                       pads)
        if attrs.get("exclusive", True) and any(v for pair in pads
                                                for v in pair):
            ones = jnp.ones_like(x)
            counts = jax.lax.reduce_window(ones, 0.0, jax.lax.add, window,
                                           strides, pads)
            out = summed / counts
        else:
            out = summed / (k[0] * k[1])
    return _out(out)


@kernel("batch_norm")
def _batch_norm(ins, attrs, ctx):
    x = _x(ins)
    scale, bias = ins["Scale"][0], ins["Bias"][0]
    mean, var = ins["Mean"][0], ins["Variance"][0]
    eps = attrs.get("epsilon", 1e-5)
    momentum = attrs.get("momentum", 0.9)
    axis = tuple(i for i in range(x.ndim) if i != 1)
    shape = (1, -1) + (1,) * (x.ndim - 2)
    if attrs.get("is_test", False) or ctx.is_test:
        y = (x - mean.reshape(shape)) * jax.lax.rsqrt(
            var.reshape(shape) + eps) * scale.reshape(shape) + \
            bias.reshape(shape)
        return {"Y": [y], "MeanOut": [mean], "VarianceOut": [var],
                "SavedMean": [mean], "SavedVariance": [var]}
    bmean = jnp.mean(x, axis=axis)
    bvar = jnp.var(x, axis=axis)
    y = (x - bmean.reshape(shape)) * jax.lax.rsqrt(
        bvar.reshape(shape) + eps) * scale.reshape(shape) + \
        bias.reshape(shape)
    new_mean = momentum * mean + (1 - momentum) * bmean
    new_var = momentum * var + (1 - momentum) * bvar
    return {"Y": [y], "MeanOut": [new_mean], "VarianceOut": [new_var],
            "SavedMean": [bmean], "SavedVariance": [bvar]}


@kernel("layer_norm")
def _layer_norm(ins, attrs, ctx):
    x = _x(ins)
    eps = attrs.get("epsilon", 1e-5)
    begin = attrs.get("begin_norm_axis", 1)
    axes = tuple(range(begin, x.ndim))
    mean = jnp.mean(x, axis=axes, keepdims=True)
    var = jnp.var(x, axis=axes, keepdims=True)
    y = (x - mean) * jax.lax.rsqrt(var + eps)
    if "Scale" in ins:
        y = y * ins["Scale"][0].reshape((1,) * begin + x.shape[begin:])
    if "Bias" in ins:
        y = y + ins["Bias"][0].reshape((1,) * begin + x.shape[begin:])
    return {"Y": [y], "Mean": [jnp.squeeze(mean)],
            "Variance": [jnp.squeeze(var)]}


# ---------------------------------------------------------------------------
# optimizer update ops (reference operators/optimizers/*.cc) — pure
# functional: outputs are the updated params/accumulators. An optional
# FoundInfinite input (wired by the fp16 auto_mixed_precision pass)
# gates the WHOLE update: on a non-finite step params, moments and
# beta-pow accumulators all keep their previous values — the
# GradScaler skip-step semantics, inside the compiled program.
# ---------------------------------------------------------------------------
def _gate_update(ins, outs):
    found = ins.get("FoundInfinite")
    if not found:
        return outs
    skip = found[0].reshape(())
    olds = {"ParamOut": "Param", "VelocityOut": "Velocity",
            "Moment1Out": "Moment1", "Moment2Out": "Moment2",
            "Beta1PowOut": "Beta1Pow", "Beta2PowOut": "Beta2Pow"}
    return {slot: [jnp.where(skip, ins[olds[slot]][0], new)
                   for new in vals]
            for slot, vals in outs.items()}


# sgd/momentum/adam/lamb delegate to the fused Pallas update (ISSUE
# 19): one grid pass reads grad+param+moments once and writes them
# once, instead of the 5-8 separate XLA elementwise ops each rule used
# to lower to. Every ineligible dispatch (non-f32, tiny param, pallas
# unavailable, PADDLE_FUSED_OPT=0) runs fused_optimizer's XLA
# reference, whose math is VERBATIM the pre-fusion bodies — bitwise.
@kernel("sgd")
def _sgd(ins, attrs, ctx):
    from ..ops.pallas.fused_optimizer import fused_op_update

    return fused_op_update("sgd", ins, attrs)


@kernel("momentum")
def _momentum(ins, attrs, ctx):
    from ..ops.pallas.fused_optimizer import fused_op_update

    return fused_op_update("momentum", ins, attrs)


@kernel("adam")
def _adam(ins, attrs, ctx):
    from ..ops.pallas.fused_optimizer import fused_op_update

    return fused_op_update("adam", ins, attrs)


@kernel("lamb")
def _lamb(ins, attrs, ctx):
    from ..ops.pallas.fused_optimizer import fused_op_update

    return fused_op_update("lamb", ins, attrs)


@kernel("check_finite_and_unscale")
def _check_finite_and_unscale(ins, attrs, ctx):
    """Reference operators/amp/check_finite_and_scale_op.cc: divide every
    grad by the loss scale and flag non-finite values. Inserted by the
    auto_mixed_precision pass under fp16 (static loss scaling); the pass
    also wires FoundInfinite into the downstream update ops, which skip
    the whole step (params, moments, beta-pows) when it fires — the
    static-graph equivalent of GradScaler skipping optimizer.step().
    Grads are zeroed too, as a belt-and-braces for update ops outside
    the gated set."""
    xs = list(ins.get("X", []))
    scale = ins["Scale"][0] if ins.get("Scale") else attrs.get("scale", 1.0)
    inv = 1.0 / scale
    found = jnp.zeros((), jnp.bool_)
    for x in xs:
        found = found | jnp.any(~jnp.isfinite(x))
    outs = [jnp.where(found, jnp.zeros_like(x), (x * inv).astype(x.dtype))
            for x in xs]
    return {"Out": outs, "FoundInfinite": [found.reshape((1,))]}


@kernel("increment")
def _increment(ins, attrs, ctx):
    x = _x(ins)
    return _out(x + jnp.asarray(attrs.get("step", 1.0), x.dtype))


# ---------------------------------------------------------------------------
# control flow (reference operators/controlflow/conditional_block_op.cc and
# while_op.cc: an inner Executor runs the sub-block; here the sub-block is
# traced into lax.cond / lax.while_loop so the whole construct compiles)
# ---------------------------------------------------------------------------


def _sub_ctx(ctx, block_idx, extra=None):
    """Context for a sub-block trace: distinct RNG stream per block (and
    per loop iteration via `extra`), so random ops inside control flow
    don't reuse the outer block's per-op keys."""
    from dataclasses import replace

    key = ctx.rng_key
    if key is not None:
        key = jax.random.fold_in(key, 7919 + block_idx)
        if extra is not None:
            key = jax.random.fold_in(key, extra)
    return replace(ctx, rng_key=key)


@kernel("cond")
def _cond(ins, attrs, ctx):
    from .executor import run_block

    pred = jnp.reshape(ins["Cond"][0], ()).astype(bool)
    prog = ctx.program
    outer_env = dict(ctx.env)

    def make_branch(block_idx, out_names):
        blk = prog.blocks[block_idx]

        def branch(_):
            env = dict(outer_env)
            env = run_block(blk, env, _sub_ctx(ctx, block_idx))
            return tuple(env[n] for n in out_names)

        return branch

    outs = jax.lax.cond(
        pred,
        make_branch(attrs["sub_block_t"], attrs["out_t"]),
        make_branch(attrs["sub_block_f"], attrs["out_f"]),
        None)
    return {"Out": list(outs)}


@kernel("while")
def _while(ins, attrs, ctx):
    from .executor import run_block

    prog = ctx.program
    blk = prog.blocks[attrs["sub_block"]]
    loop_in = attrs["loop_in"]          # parent names body ops read
    body_out = attrs["body_out"]        # names body ops write
    cond_out = attrs["cond_out"]        # recomputed condition name
    outer_env = dict(ctx.env)
    init_vals = tuple(ins["X"])
    init_cond = jnp.reshape(ins["Cond"][0], ()).astype(bool)

    def cond_fn(state):
        return state[0]

    def body_fn(state):
        _, it, vals = state
        env = dict(outer_env)
        env.update(zip(loop_in, vals))
        # fresh RNG stream per iteration (it rides the loop carry)
        env = run_block(blk, env, _sub_ctx(ctx, attrs["sub_block"], it))
        return (jnp.reshape(env[cond_out], ()).astype(bool), it + 1,
                tuple(env[n] for n in body_out))

    _, _, final = jax.lax.while_loop(
        cond_fn, body_fn, (init_cond, jnp.asarray(0, jnp.int32), init_vals))
    return {"Out": list(final)}


# -- tensor arrays (reference LoDTensorArray + lod_tensor_array ops:
# operators/controlflow/while_op + array_write/read; here an array is a
# python list flowing through the env, so structure is trace-static) -----
@kernel("create_array")
def _create_array(ins, attrs, ctx):
    return {"Out": [[]]}


@kernel("array_write")
def _array_write(ins, attrs, ctx):
    """Write at a concrete index (overwrite or append, fluid semantics).
    A traced index falls back to append — the only pattern that cannot
    restructure a trace-static list, and the ubiquitous one (loops write
    at i == len)."""
    arr = list(ins["Array"][0])
    val = _x(ins)
    i = attrs.get("static_index")
    if i is None:
        try:
            i = int(ins["I"][0])
        except (KeyError, TypeError, jax.errors.ConcretizationTypeError):
            arr.append(val)
            return {"Out": [arr]}
    if i < len(arr):
        arr[i] = val
    elif i == len(arr):
        arr.append(val)
    else:
        raise IndexError(
            f"array_write index {i} beyond array length {len(arr)}")
    return {"Out": [arr]}


@kernel("array_read")
def _array_read(ins, attrs, ctx):
    arr = ins["X"][0]
    if "static_index" in attrs:
        return {"Out": [arr[int(attrs["static_index"])]]}
    i = ins["I"][0]
    try:
        return {"Out": [arr[int(i)]]}
    except (TypeError, jax.errors.ConcretizationTypeError):
        # traced index: stack equal-shaped elements, dynamic-index
        stacked = jnp.stack(arr, axis=0)
        return {"Out": [jax.lax.dynamic_index_in_dim(
            stacked, jnp.reshape(i, ()).astype(jnp.int32), axis=0,
            keepdims=False)]}


@kernel("array_length")
def _array_length(ins, attrs, ctx):
    return {"Out": [jnp.asarray([len(ins["X"][0])], jnp.int32)]}


@kernel("tensor_array_to_tensor")
def _tensor_array_to_tensor(ins, attrs, ctx):
    arr = ins["X"][0]
    axis = attrs.get("axis", 0)
    if attrs.get("use_stack", False):
        out = jnp.stack(arr, axis=axis)
        idx = jnp.asarray([1] * len(arr), jnp.int32)
    else:
        out = jnp.concatenate(arr, axis=axis)
        idx = jnp.asarray([a.shape[axis] for a in arr], jnp.int32)
    return {"Out": [out], "OutIndex": [idx]}
