"""Disk-persistent XLA compile cache, gated by PADDLE_COMPILE_CACHE[_DIR].

The reference pays its 89 IR passes + kernel selection on every process
start; our executor pays an XLA compile instead. This module makes that
cost once-per-machine rather than once-per-process: it points jax's
persistent compilation cache at a directory, so a relaunched trainer
(launch.supervise restart, PR 2) resumes without the cold compile —
``lower()`` still traces, but ``compile()`` becomes a disk read.

Knobs:
  PADDLE_COMPILE_CACHE      "1"/"true" enables with the default dir,
                            "0"/"false"/"off" force-disables
  PADDLE_COMPILE_CACHE_DIR  cache directory (implies enable)

Default dir: ~/.cache/paddle_tpu/xla_cache.

Cache traffic is observable: a jax monitoring listener bumps the
profiler counters ``disk_cache_hits`` / ``disk_cache_misses``, which
Executor.counters merges (profiler.COMPILE_COUNTER_NAMES) and bench.py
reports per row.
"""
from __future__ import annotations

import os
from typing import Optional

_state = {"resolved": False, "enabled": False, "dir": None,
          "listener": False}

_DISABLE_VALUES = ("0", "false", "off", "no")


def cache_dir() -> Optional[str]:
    """The active cache directory, or None when the cache is off."""
    return _state["dir"] if _state["enabled"] else None


def is_enabled() -> bool:
    return bool(_state["enabled"])


def ensure_enabled() -> bool:
    """Resolve the env knobs once and (maybe) turn the cache on.

    Called from Executor/TrainStep construction — every jit compiled
    after the first executor benefits, including the dygraph TrainStep
    path. Returns whether the disk cache is active.
    """
    if _state["resolved"]:
        return _state["enabled"]
    _state["resolved"] = True
    flag = os.environ.get("PADDLE_COMPILE_CACHE")
    cdir = os.environ.get("PADDLE_COMPILE_CACHE_DIR")
    if flag is not None and flag.strip().lower() in _DISABLE_VALUES:
        return False
    if flag is None and not cdir:
        return False
    cdir = cdir or os.path.join(os.path.expanduser("~"), ".cache",
                                "paddle_tpu", "xla_cache")
    try:
        os.makedirs(cdir, exist_ok=True)
        import jax

        jax.config.update("jax_compilation_cache_dir", cdir)
        # default thresholds skip everything that compiles in under a
        # second — exactly the small-step regime tests and relaunch
        # drills live in; cache unconditionally
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    except Exception:
        return False
    _install_listener()
    _state.update(enabled=True, dir=cdir)
    return True


def _install_listener() -> None:
    """Bridge jax's /jax/compilation_cache/* monitoring events into the
    profiler counter table (secrets-free: event names only)."""
    if _state["listener"]:
        return
    try:
        from jax._src import monitoring
    except Exception:
        return
    from .. import profiler

    def _on_event(event: str, **kwargs) -> None:
        if event.endswith("/cache_hits"):
            profiler.bump_counter("disk_cache_hits")
        elif event.endswith("/cache_misses"):
            profiler.bump_counter("disk_cache_misses")

    monitoring.register_event_listener(_on_event)
    _state["listener"] = True


def _reset_for_tests() -> None:
    """Re-arm env resolution (tests flip PADDLE_COMPILE_CACHE* between
    cases; the listener stays — re-registering would double-count)."""
    _state["resolved"] = False
    _state["enabled"] = False
    _state["dir"] = None
