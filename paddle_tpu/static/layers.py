"""Graph-building layer functions for static programs.

Reference: /root/reference/python/paddle/fluid/layers/nn.py (fc :211,
conv2d, batch_norm, ...), layers/tensor.py, LayerHelper plumbing
(layer_helper.py). Facades append OpDescs to the current program and
return Variables.

Shape inference is NOT hand-written per op (reference InferShape in every
operator): each appended op's output shapes/dtypes come from
`jax.eval_shape` over its kernel — the compiler's abstract interpretation
is the single source of truth. Dynamic batch (-1) is threaded through with
a sentinel dim.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..framework import dtype as dtype_mod
from ..framework import random as random_mod
from ..utils import unique_name
from .ir import (Block, ParamDesc, Program, VarDesc, Variable,
                 default_main_program, default_startup_program,
                 _DYN_SENTINEL)
from .kernels import KERNELS, ExecContext


# ---------------------------------------------------------------------------
# shape inference via abstract evaluation
# ---------------------------------------------------------------------------
def _infer_outputs(block: Block, op, out_slots: Dict[str, int]):
    """Create output vars of `op` with shapes from jax.eval_shape."""
    kernel = KERNELS[op.type]

    concrete_ins = {}
    for slot, names in op.inputs.items():
        arrs = []
        for n in names:
            desc = block._find_var_recursive(n)
            # -k encodes "dynamic batch times static k" (see below), so a
            # flatten/gather/reshape round-trip keeps its static factor
            shape = tuple(_DYN_SENTINEL * (1 if s is None else -s)
                          if (s is None or s < 0) else s
                          for s in (desc.shape or ()))
            arrs.append(jax.ShapeDtypeStruct(
                shape, dtype_mod.convert_dtype(desc.dtype)))
        concrete_ins[slot] = arrs

    def absfn(ins):
        ctx = ExecContext(rng_key=random_mod.make_key(0))
        return kernel(ins, op.attrs, ctx)

    outs = jax.eval_shape(absfn, concrete_ins)
    created = {}
    for slot, names in op.outputs.items():
        structs = outs.get(slot, [])
        for name, st in zip(names, structs):
            shape = tuple(-(s // _DYN_SENTINEL) if (s >= _DYN_SENTINEL and
                                                    s % _DYN_SENTINEL == 0)
                          else s for s in st.shape)
            if not block.has_var(name):
                block.create_var(name=name, shape=shape,
                                 dtype=dtype_mod.dtype_name(st.dtype))
            created[name] = block.var(name)
    return created


class LayerHelper:
    """Append-op helper (reference layer_helper.py / layer_helper_base.py)."""

    def __init__(self, layer_type: str, **kwargs):
        self.layer_type = layer_type
        self.kwargs = kwargs
        self.main_program = default_main_program()
        self.startup_program = default_startup_program()

    @property
    def block(self) -> Block:
        return self.main_program.current_block()

    def create_tmp(self, dtype="float32") -> str:
        return unique_name.generate(f"{self.layer_type}_tmp")

    def append_op(self, type, inputs=None, outputs=None, attrs=None,
                  infer_shape=True):
        op = self.block.append_op(type=type, inputs=inputs, outputs=outputs,
                                  attrs=attrs)
        if infer_shape:
            _infer_outputs(self.block, op, {})
        return op

    def create_parameter(self, shape, dtype="float32", name=None,
                         initializer=None, trainable=True,
                         attr=None):
        """Create a ParamDesc in the main block AND its init op in the
        startup program (reference LayerHelperBase.create_parameter)."""
        from .initializer import resolve_initializer

        if attr is not None and getattr(attr, "name", None):
            name = attr.name
        if attr is not None and getattr(attr, "initializer", None) is not None:
            initializer = attr.initializer
        if attr is not None and getattr(attr, "trainable", None) is not None:
            trainable = attr.trainable
        name = name or unique_name.generate(f"{self.layer_type}_w")
        shape = tuple(int(s) for s in shape)
        desc = ParamDesc(name, shape, dtype_mod.dtype_name(
            dtype_mod.convert_dtype(dtype)), trainable=trainable)
        self.main_program.global_block.vars[name] = desc

        op_type, attrs = resolve_initializer(initializer, shape, desc.dtype,
                                             fan_hint=shape)
        desc.initializer_desc = [op_type, attrs]
        sb = self.startup_program.global_block
        sb.vars[name] = ParamDesc(name, shape, desc.dtype, trainable)
        sb.append_op(type=op_type, inputs={}, outputs={"Out": [name]},
                     attrs=attrs)
        return Variable(self.main_program.global_block, desc)

    def out_var(self, dtype="float32"):
        name = self.create_tmp()
        return name


def _append_simple(op_type, inputs, attrs=None, out_slots=("Out",),
                   helper=None):
    helper = helper or LayerHelper(op_type)
    outputs = {slot: [unique_name.generate(f"{op_type}.{slot.lower()}")]
               for slot in out_slots}
    op = helper.block.append_op(type=op_type, inputs=inputs,
                                outputs=outputs, attrs=attrs or {})
    created = _infer_outputs(helper.block, op, {})
    outs = [helper.block.var(outputs[s][0]) for s in out_slots]
    return outs[0] if len(outs) == 1 else tuple(outs)


# ---------------------------------------------------------------------------
# data & constants
# ---------------------------------------------------------------------------
def data(name: str, shape: Sequence[int], dtype="float32",
         lod_level=0, append_batch_size=False) -> Variable:
    """Feed placeholder (reference fluid/data.py / layers/io.py data)."""
    prog = default_main_program()
    shape = list(shape)
    if append_batch_size:
        shape = [-1] + shape
    v = prog.global_block.create_var(
        name=name, shape=shape, dtype=dtype, is_data=True,
        stop_gradient=True)
    return v


def fill_constant(shape, dtype, value, name=None):
    helper = LayerHelper("fill_constant")
    out_name = name or unique_name.generate("fill_constant.out")
    op = helper.block.append_op(
        type="fill_constant", inputs={},
        outputs={"Out": [out_name]},
        attrs={"shape": list(shape), "dtype": str(dtype), "value": value})
    _infer_outputs(helper.block, op, {})
    return helper.block.var(out_name)


def assign(input, output=None):
    helper = LayerHelper("assign")
    if isinstance(input, (np.ndarray, list, tuple, float, int)):
        arr = np.asarray(input)
        out_name = output.name if output is not None else \
            unique_name.generate("assign.out")
        op = helper.block.append_op(
            type="assign_value", inputs={}, outputs={"Out": [out_name]},
            attrs={"shape": list(arr.shape), "dtype": str(arr.dtype),
                   "values": arr.tolist()})
        _infer_outputs(helper.block, op, {})
        return helper.block.var(out_name)
    if output is not None:
        op = helper.block.append_op(type="assign", inputs={"X": [input]},
                                    outputs={"Out": [output.name]})
        _infer_outputs(helper.block, op, {})
        return output
    return _append_simple("assign", {"X": [input]})


# ---------------------------------------------------------------------------
# core NN layers
# ---------------------------------------------------------------------------
def _bias_default():
    """Bias initializer default: the set_global_initializer bias slot if
    set (reference initializer.py set_global_initializer), else zeros."""
    from .initializer import Constant, _global_initializer

    return _global_initializer[1] or Constant(0.0)


def fc(input, size, num_flatten_dims=1, param_attr=None, bias_attr=None,
       act=None, name=None):
    """Reference layers/nn.py:211 fc: flatten -> mul -> add bias -> act."""
    helper = LayerHelper("fc", name=name)
    in_shape = input.shape
    fan_in = 1
    for s in in_shape[num_flatten_dims:]:
        fan_in *= (s if s and s > 0 else 1)
    w = helper.create_parameter((fan_in, size), input.dtype, attr=param_attr,
                                initializer=None)
    out = _append_simple("mul", {"X": [input], "Y": [w]},
                         {"x_num_col_dims": num_flatten_dims,
                          "y_num_col_dims": 1}, helper=helper)
    if bias_attr is not False:
        from .initializer import Constant
        b = helper.create_parameter((size,), input.dtype, attr=bias_attr,
                                    initializer=_bias_default())
        out = _append_simple("elementwise_add", {"X": [out], "Y": [b]},
                             {"axis": len(out.shape) - 1}, helper=helper)
    if act:
        out = _append_simple(act, {"X": [out]}, helper=helper)
    return out


def embedding(input, size, padding_idx=None, param_attr=None,
              dtype="float32", is_sparse=False, name=None):
    helper = LayerHelper("embedding", name=name)
    w = helper.create_parameter(size, dtype, attr=param_attr)
    return _append_simple(
        "lookup_table_v2", {"W": [w], "Ids": [input]},
        {"padding_idx": -1 if padding_idx is None else padding_idx},
        helper=helper)


def conv2d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=1, param_attr=None, bias_attr=None, act=None, name=None):
    helper = LayerHelper("conv2d", name=name)
    if isinstance(filter_size, int):
        filter_size = (filter_size, filter_size)
    stride = (stride, stride) if isinstance(stride, int) else tuple(stride)
    padding = (padding, padding) if isinstance(padding, int) else tuple(padding)
    dilation = (dilation, dilation) if isinstance(dilation, int) \
        else tuple(dilation)
    c_in = input.shape[1]
    w = helper.create_parameter(
        (num_filters, c_in // groups) + tuple(filter_size), input.dtype,
        attr=param_attr)
    out = _append_simple(
        "conv2d", {"Input": [input], "Filter": [w]},
        {"strides": list(stride), "paddings": list(padding),
         "dilations": list(dilation), "groups": groups},
        out_slots=("Output",), helper=helper)
    if bias_attr is not False:
        from .initializer import Constant
        b = helper.create_parameter((num_filters,), input.dtype,
                                    attr=bias_attr,
                                    initializer=_bias_default())
        out = _append_simple("elementwise_add", {"X": [out], "Y": [b]},
                             {"axis": 1}, helper=helper)
    if act:
        out = _append_simple(act, {"X": [out]}, helper=helper)
    return out


def pool2d(input, pool_size=2, pool_type="max", pool_stride=None,
           pool_padding=0, global_pooling=False, exclusive=True, name=None):
    if isinstance(pool_size, int):
        pool_size = (pool_size, pool_size)
    pool_stride = pool_stride or pool_size
    if isinstance(pool_stride, int):
        pool_stride = (pool_stride, pool_stride)
    if isinstance(pool_padding, int):
        pool_padding = (pool_padding, pool_padding)
    return _append_simple(
        "pool2d", {"X": [input]},
        {"ksize": list(pool_size), "pooling_type": pool_type,
         "strides": list(pool_stride), "paddings": list(pool_padding),
         "global_pooling": global_pooling, "exclusive": exclusive})


def batch_norm(input, act=None, is_test=False, momentum=0.9, epsilon=1e-5,
               param_attr=None, bias_attr=None, name=None):
    helper = LayerHelper("batch_norm", name=name)
    c = input.shape[1]
    from .initializer import Constant
    scale = helper.create_parameter((c,), input.dtype, attr=param_attr,
                                    initializer=Constant(1.0))
    bias = helper.create_parameter((c,), input.dtype, attr=bias_attr,
                                   initializer=_bias_default())
    # running statistics, not biases: never subject to the global
    # bias initializer (mean starts at 0, variance at 1)
    mean = helper.create_parameter((c,), input.dtype,
                                   initializer=Constant(0.0),
                                   trainable=False)
    var = helper.create_parameter((c,), input.dtype,
                                  initializer=Constant(1.0),
                                  trainable=False)
    outs = {s: [unique_name.generate(f"bn.{s.lower()}")]
            for s in ("Y", "SavedMean", "SavedVariance")}
    outs["MeanOut"] = [mean.name]
    outs["VarianceOut"] = [var.name]
    op = helper.block.append_op(
        type="batch_norm",
        inputs={"X": [input], "Scale": [scale], "Bias": [bias],
                "Mean": [mean], "Variance": [var]},
        outputs=outs,
        attrs={"momentum": momentum, "epsilon": epsilon, "is_test": is_test})
    _infer_outputs(helper.block, op, {})
    out = helper.block.var(outs["Y"][0])
    if act:
        out = _append_simple(act, {"X": [out]}, helper=helper)
    return out


def layer_norm(input, scale=True, shift=True, begin_norm_axis=1,
               epsilon=1e-5, param_attr=None, bias_attr=None, name=None):
    helper = LayerHelper("layer_norm", name=name)
    norm_shape = tuple(input.shape[begin_norm_axis:])
    n = 1
    for s in norm_shape:
        n *= s
    inputs = {"X": [input]}
    from .initializer import Constant
    if scale:
        inputs["Scale"] = [helper.create_parameter(
            (n,), input.dtype, attr=param_attr, initializer=Constant(1.0))]
    if shift:
        inputs["Bias"] = [helper.create_parameter(
            (n,), input.dtype, attr=bias_attr, initializer=_bias_default())]
    out, mean, var = _append_simple(
        "layer_norm", inputs, {"epsilon": epsilon,
                               "begin_norm_axis": begin_norm_axis},
        out_slots=("Y", "Mean", "Variance"), helper=helper)
    return out


def dropout(x, dropout_prob=0.5, is_test=False,
            dropout_implementation="upscale_in_train", name=None):
    out, _ = _append_simple(
        "dropout", {"X": [x]},
        {"dropout_prob": dropout_prob, "is_test": is_test,
         "dropout_implementation": dropout_implementation},
        out_slots=("Out", "Mask"))
    return out


# ---------------------------------------------------------------------------
# math / tensor ops
# ---------------------------------------------------------------------------
def _elementwise_binary(x, y, op_type, reverse=False):
    block = x.block if isinstance(x, Variable) else y.block
    if not isinstance(y, Variable):
        y = fill_constant(shape=(1,), dtype=x.dtype, value=float(y))
    if not isinstance(x, Variable):
        x = fill_constant(shape=(1,), dtype=y.dtype, value=float(x))
    if reverse:
        x, y = y, x
    return _append_simple(op_type, {"X": [x], "Y": [y]}, {"axis": -1})


def matmul(x, y, transpose_x=False, transpose_y=False, alpha=1.0, name=None):
    return _append_simple("matmul", {"X": [x], "Y": [y]},
                          {"transpose_X": transpose_x,
                           "transpose_Y": transpose_y, "alpha": alpha})


def mul(x, y, x_num_col_dims=1, y_num_col_dims=1, name=None):
    return _append_simple("mul", {"X": [x], "Y": [y]},
                          {"x_num_col_dims": x_num_col_dims,
                           "y_num_col_dims": y_num_col_dims})


def elementwise_add(x, y, axis=-1, act=None, name=None):
    out = _append_simple("elementwise_add", {"X": [x], "Y": [y]},
                         {"axis": axis})
    return _append_simple(act, {"X": [out]}) if act else out


def elementwise_sub(x, y, axis=-1, act=None, name=None):
    return _append_simple("elementwise_sub", {"X": [x], "Y": [y]},
                          {"axis": axis})


def elementwise_mul(x, y, axis=-1, act=None, name=None):
    return _append_simple("elementwise_mul", {"X": [x], "Y": [y]},
                          {"axis": axis})


def elementwise_div(x, y, axis=-1, act=None, name=None):
    return _append_simple("elementwise_div", {"X": [x], "Y": [y]},
                          {"axis": axis})


def elementwise_max(x, y, axis=-1, act=None, name=None):
    return _append_simple("elementwise_max", {"X": [x], "Y": [y]},
                          {"axis": axis})


def elementwise_min(x, y, axis=-1, act=None, name=None):
    return _append_simple("elementwise_min", {"X": [x], "Y": [y]},
                          {"axis": axis})


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    out = _append_simple("scale", {"X": [x]},
                         {"scale": float(scale), "bias": float(bias),
                          "bias_after_scale": bias_after_scale})
    return _append_simple(act, {"X": [out]}) if act else out


def cast(x, dtype):
    return _append_simple("cast", {"X": [x]}, {"out_dtype": str(
        dtype_mod.dtype_name(dtype_mod.convert_dtype(dtype)))})


def clip(x, min, max, name=None):
    return _append_simple("clip", {"X": [x]}, {"min": min, "max": max})


def mean(x, name=None):
    return _append_simple("mean", {"X": [x]})


def sums(input, name=None):
    return _append_simple("sum", {"X": list(input)})


def reduce_sum(input, dim=None, keep_dim=False, name=None):
    return _append_simple("reduce_sum", {"X": [input]},
                          {"dim": dim, "keep_dim": keep_dim,
                           "reduce_all": dim is None})


def reduce_mean(input, dim=None, keep_dim=False, name=None):
    return _append_simple("reduce_mean", {"X": [input]},
                          {"dim": dim, "keep_dim": keep_dim,
                           "reduce_all": dim is None})


def reduce_max(input, dim=None, keep_dim=False, name=None):
    return _append_simple("reduce_max", {"X": [input]},
                          {"dim": dim, "keep_dim": keep_dim,
                           "reduce_all": dim is None})


def reduce_min(input, dim=None, keep_dim=False, name=None):
    return _append_simple("reduce_min", {"X": [input]},
                          {"dim": dim, "keep_dim": keep_dim,
                           "reduce_all": dim is None})


def reshape(x, shape, name=None):
    return _append_simple("reshape2", {"X": [x]}, {"shape": list(shape)})


def transpose(x, perm, name=None):
    return _append_simple("transpose2", {"X": [x]}, {"axis": list(perm)})


def concat(input, axis=0, name=None):
    return _append_simple("concat", {"X": list(input)}, {"axis": axis})


def split(input, num_or_sections, dim=-1, name=None):
    ndim = len(input.shape)
    axis = dim if dim >= 0 else dim + ndim
    if isinstance(num_or_sections, int):
        n = num_or_sections
        attrs = {"num": n, "axis": axis}
    else:
        n = len(num_or_sections)
        attrs = {"sections": list(num_or_sections), "axis": axis}
    helper = LayerHelper("split")
    names = [unique_name.generate("split.out") for _ in range(n)]
    op = helper.block.append_op(type="split", inputs={"X": [input]},
                                outputs={"Out": names}, attrs=attrs)
    _infer_outputs(helper.block, op, {})
    return [helper.block.var(n_) for n_ in names]


def squeeze(input, axes, name=None):
    return _append_simple("squeeze2", {"X": [input]}, {"axes": list(axes)})


def unsqueeze(input, axes, name=None):
    return _append_simple("unsqueeze2", {"X": [input]},
                          {"axes": list(axes)})


def stack(x, axis=0, name=None):
    return _append_simple("stack", {"X": list(x)}, {"axis": axis},
                          out_slots=("Y",))


def slice(input, axes, starts, ends):
    return _append_simple("slice", {"Input": [input], "X": [input]},
                          {"axes": list(axes), "starts": list(starts),
                           "ends": list(ends)})


def flatten(x, axis=1, name=None):
    return _append_simple("flatten2", {"X": [x]}, {"axis": axis})


def one_hot(input, depth, name=None):
    return _append_simple("one_hot_v2", {"X": [input]}, {"depth": depth})


def gather(input, index, axis=0):
    return _append_simple("gather", {"X": [input], "Index": [index]},
                          {"axis": axis})


def argmax(x, axis=-1):
    return _append_simple("arg_max", {"X": [x]}, {"axis": axis})


def topk(input, k, name=None):
    return _append_simple("top_k_v2", {"X": [input]}, {"k": k},
                          out_slots=("Out", "Indices"))


# activations as layer fns
def _act_layer(name):
    def f(x, **kwargs):
        return _append_simple(name, {"X": [x]})
    f.__name__ = name
    return f


relu = _act_layer("relu")
sigmoid = _act_layer("sigmoid")
tanh = _act_layer("tanh")
exp = _act_layer("exp")
log = _act_layer("log")
sqrt = _act_layer("sqrt")
square = _act_layer("square")
abs = _act_layer("abs")
softmax_ = None


def softmax(input, axis=-1, name=None):
    return _append_simple("softmax", {"X": [input]}, {"axis": axis})


def gelu(x, approximate=False):
    return _append_simple("gelu", {"X": [x]}, {"approximate": approximate})


def leaky_relu(x, alpha=0.02, name=None):
    return _append_simple("leaky_relu", {"X": [x]}, {"alpha": alpha})


# losses & metrics
def cross_entropy(input, label, soft_label=False, name=None):
    return _append_simple("cross_entropy",
                          {"X": [input], "Label": [label]},
                          {"soft_label": soft_label}, out_slots=("Y",))


def softmax_with_cross_entropy(logits, label, soft_label=False,
                               return_softmax=False, axis=-1):
    sm, loss = _append_simple(
        "softmax_with_cross_entropy",
        {"Logits": [logits], "Label": [label]},
        {"soft_label": soft_label}, out_slots=("Softmax", "Loss"))
    return (loss, sm) if return_softmax else loss


def square_error_cost(input, label):
    """(input - label)^2 per element (reference layers/loss.py
    square_error_cost; operators/squared_l2_distance is the fused form —
    composition keeps the kernel set minimal and XLA fuses it anyway)."""
    diff = _append_simple("elementwise_sub", {"X": [input], "Y": [label]})
    return _append_simple("square", {"X": [diff]})


def cos_sim(X, Y):
    """Row-wise cosine similarity, shape (N, 1) (reference layers/nn.py
    cos_sim / operators/cos_sim_op.cc)."""
    xy = _append_simple("reduce_sum",
                        {"X": [_append_simple("elementwise_mul",
                                              {"X": [X], "Y": [Y]})]},
                        {"dim": [-1], "keep_dim": True})
    xx = _append_simple("reduce_sum",
                        {"X": [_append_simple("square", {"X": [X]})]},
                        {"dim": [-1], "keep_dim": True})
    yy = _append_simple("reduce_sum",
                        {"X": [_append_simple("square", {"X": [Y]})]},
                        {"dim": [-1], "keep_dim": True})
    denom = _append_simple(
        "elementwise_max",
        {"X": [_append_simple("sqrt",
                              {"X": [_append_simple("elementwise_mul",
                                                    {"X": [xx], "Y": [yy]})]})],
         "Y": [fill_constant([1], X.dtype, 1e-8)]})
    return _append_simple("elementwise_div", {"X": [xy], "Y": [denom]})


def accuracy(input, label, k=1, name=None):
    acc, _, _ = _append_simple(
        "accuracy", {"Out": [input], "Label": [label]}, {"k": k},
        out_slots=("Accuracy", "Correct", "Total"))
    return acc


def increment(x, value=1.0, in_place=True):
    """x + value keeping dtype (reference layers/control_flow.py:increment,
    operators/increment_op.cc). in_place=True (the reference default)
    writes back to x's own variable, so later reads in the same block see
    the updated value."""
    helper = LayerHelper("increment")
    if in_place:
        helper.block.append_op(type="increment", inputs={"X": [x]},
                               outputs={"Out": [x.name]},
                               attrs={"step": value})
        return helper.block.var(x.name)
    return _append_simple("increment", {"X": [x]}, {"step": value})


# comparison layers (python scalars wrap into fill_constant like the
# reference's math_op_patch scalar promotion)
def _cmp_operand(x, y):
    if not hasattr(y, "name"):
        y = fill_constant(shape=(1,), dtype=x.dtype, value=float(y))
    return y


def equal(x, y):
    return _append_simple("equal", {"X": [x], "Y": [_cmp_operand(x, y)]},
                          {"axis": -1})


def less_than(x, y, cond=None):
    """x < y. ``cond`` (reference layers/control_flow.py:less_than):
    write the result into an existing variable — the fluid While
    pattern's condition refresh."""
    out = _append_simple("less_than",
                         {"X": [x], "Y": [_cmp_operand(x, y)]},
                         {"axis": -1})
    if cond is not None:
        return assign(out, output=cond)
    return out


def greater_than(x, y):
    return _append_simple("greater_than",
                          {"X": [x], "Y": [_cmp_operand(x, y)]},
                          {"axis": -1})


def logical_and(x, y):
    return _append_simple("logical_and", {"X": [x], "Y": [y]}, {"axis": -1})


def logical_not(x):
    return _append_simple("logical_not", {"X": [x]})


# ---------------------------------------------------------------------------
# control flow (reference fluid/layers/control_flow.py: cond :2117,
# While :1086, while_loop :1298, case/switch_case; executed by the
# conditional_block_op.cc / while_op.cc sub-block pattern — here compiled
# into lax.cond / lax.while_loop by the cond/while kernels)
# ---------------------------------------------------------------------------


def _as_var_list(x):
    if x is None:
        return []
    return list(x) if isinstance(x, (list, tuple)) else [x]


def cond(pred, true_fn=None, false_fn=None, name=None):
    """Static two-branch conditional. true_fn/false_fn build their ops in
    fresh sub-blocks; both must return the same structure of Variables
    with matching shapes/dtypes."""
    from .ir import _BlockGuard

    helper = LayerHelper("cond")
    prog = helper.main_program
    parent = prog.current_block()

    tb = prog.create_block()
    with _BlockGuard(prog, tb):
        t_out = true_fn() if true_fn is not None else None
    fb = prog.create_block()
    with _BlockGuard(prog, fb):
        f_out = false_fn() if false_fn is not None else None

    t_list, f_list = _as_var_list(t_out), _as_var_list(f_out)
    if len(t_list) != len(f_list):
        raise ValueError(
            f"cond branches must return the same number of outputs "
            f"({len(t_list)} vs {len(f_list)})")
    if not t_list:
        raise NotImplementedError(
            "cond branches returned no outputs; side-effect-only cond "
            "(writes into parent-block vars) is not supported — return "
            "the values you need and assign them after the cond")

    out_names = [unique_name.generate("cond.out") for _ in t_list]
    parent.append_op(
        type="cond",
        inputs={"Cond": [pred.name]},
        outputs={"Out": out_names},
        attrs={"sub_block_t": tb.idx, "sub_block_f": fb.idx,
               "out_t": [v.name for v in t_list],
               "out_f": [v.name for v in f_list]})
    outs = []
    for name_, tv in zip(out_names, t_list):
        parent.create_var(name=name_, shape=tv.shape, dtype=tv.dtype)
        outs.append(parent.var(name_))
    return outs[0] if len(outs) == 1 else tuple(outs)


def while_loop(cond_fn, body_fn, loop_vars, is_test=False, name=None):
    """`while cond_fn(*vars): vars = body_fn(*vars)` compiled to
    lax.while_loop (reference layers/control_flow.py:1298).

    Constraints vs the reference while_op: loop-carried shapes/dtypes must
    be invariant; ONLY the returned loop_vars are carried across
    iterations — body writes to other parent-block variables are local to
    one iteration and discarded (no scope write-back); is_test is accepted
    for API parity but has no effect (no test-mode caching to skip)."""
    from .ir import _BlockGuard

    helper = LayerHelper("while_loop")
    prog = helper.main_program
    parent = prog.current_block()
    loop_vars = list(loop_vars)

    pre_cond = cond_fn(*loop_vars)           # evaluated in the parent block

    sb = prog.create_block()
    with _BlockGuard(prog, sb):
        new_vars = body_fn(*loop_vars)
        new_vars = (list(new_vars) if isinstance(new_vars, (list, tuple))
                    else [new_vars])
        if len(new_vars) != len(loop_vars):
            raise ValueError("body_fn must return as many values as "
                             "loop_vars")
        new_cond = cond_fn(*new_vars)        # recomputed inside the block

    out_names = [unique_name.generate("while.out") for _ in loop_vars]
    parent.append_op(
        type="while",
        inputs={"X": [v.name for v in loop_vars],
                "Cond": [pre_cond.name]},
        outputs={"Out": out_names},
        attrs={"sub_block": sb.idx,
               "loop_in": [v.name for v in loop_vars],
               "body_out": [v.name for v in new_vars],
               "cond_out": new_cond.name})
    outs = []
    for name_, lv in zip(out_names, loop_vars):
        parent.create_var(name=name_, shape=lv.shape, dtype=lv.dtype)
        outs.append(parent.var(name_))
    return outs


def case(pred_fn_pairs, default=None, name=None):
    """First matching (pred, fn) wins (reference control_flow.py case)."""
    pairs = list(pred_fn_pairs)
    if default is None:
        if not pairs:
            raise ValueError("case()/switch_case() needs at least one "
                             "(pred, fn) pair or a default branch")
        default = pairs[-1][1]
        pairs = pairs[:-1]

    def build(i):
        if i >= len(pairs):
            return default
        pred, fn = pairs[i]
        return lambda: cond(pred, fn, build(i + 1))

    return build(0)()


def switch_case(branch_index, branch_fns, default=None, name=None):
    """Dispatch on an integer index (reference control_flow.py
    switch_case). branch_fns: dict index->fn or list of (index, fn)."""
    if isinstance(branch_fns, dict):
        items = sorted(branch_fns.items())
    else:
        items = [(i, fn) if not isinstance(fn, tuple) else fn
                 for i, fn in enumerate(branch_fns)]
    pairs = []
    for idx, fn in items:
        pred = _append_simple(
            "equal", {"X": [branch_index],
                      "Y": [fill_constant(branch_index.shape or [1],
                                          branch_index.dtype, idx)]})
        pairs.append((pred, fn))
    return case(pairs, default=default, name=name)


# -- fluid.layers tensor/array sugar ----------------------------------------
def create_tensor(dtype="float32", name=None, persistable=False):
    """Empty named var to assign into (layers/tensor.py create_tensor)."""
    prog = default_main_program()
    name = name or unique_name.generate("create_tensor")
    return prog.global_block.create_var(name=name, shape=(), dtype=dtype,
                                        persistable=persistable)


def create_global_var(shape, value, dtype, persistable=False,
                      force_cpu=False, name=None):
    """Persistable filled var in startup (layers/tensor.py
    create_global_var)."""
    helper = LayerHelper("global_var")
    name = name or unique_name.generate("global_var")
    desc = VarDesc(name, tuple(int(s) for s in shape),
                   dtype_mod.dtype_name(dtype_mod.convert_dtype(dtype)),
                   persistable=persistable)
    helper.main_program.global_block.vars[name] = desc
    sb = helper.startup_program.global_block
    sb.vars[name] = VarDesc(name, tuple(int(s) for s in shape),
                            desc.dtype, persistable=persistable)
    sb.append_op(type="fill_constant", inputs={},
                 outputs={"Out": [name]},
                 attrs={"shape": list(shape), "dtype": desc.dtype,
                        "value": float(value)})
    return Variable(helper.main_program.global_block, desc)


def create_parameter(shape, dtype="float32", name=None, attr=None,
                     is_bias=False, default_initializer=None):
    """Standalone parameter (layers/tensor.py create_parameter)."""
    helper = LayerHelper("create_parameter")
    return helper.create_parameter(shape, dtype, name=name,
                                   initializer=default_initializer,
                                   attr=attr)


def autoincreased_step_counter(counter_name=None, begin=1, step=1):
    """Global step counter incremented every run (layers/nn.py
    autoincreased_step_counter)."""
    name = counter_name or "@STEP_COUNTER@"
    prog = default_main_program()
    if name not in prog.global_block.vars:
        v = create_global_var([1], float(begin - step), "int64",
                              persistable=True, name=name)
    else:
        v = Variable(prog.global_block, prog.global_block.vars[name])
    helper = LayerHelper("step_counter")
    helper.append_op(type="increment", inputs={"X": [v]},
                     outputs={"Out": [v.name]},
                     attrs={"step": float(step)})
    return v


# -- tensor arrays (LoDTensorArray parity; layers/control_flow.py).
# Shape inference is bypassed: an array's value is a trace-static python
# list, so element shape/dtype are tracked on its VarDesc instead.
def create_array(dtype="float32", initialized_list=None):
    helper = LayerHelper("array")
    name = unique_name.generate("array")
    desc = VarDesc(name, (), "tensor_array")
    desc.elem_shape = None
    desc.elem_dtype = str(dtype)
    helper.block.vars[name] = desc
    helper.block.append_op(type="create_array", inputs={},
                           outputs={"Out": [name]},
                           attrs={"dtype": str(dtype)})
    return helper.block.var(name)


def _literal_index(block, i):
    """Resolve a graph-build-time constant index: a python int, or a var
    produced by fill_constant (the executor traces the block, so runtime
    values are tracers — write positions must be known when the trace is
    built, exactly like the reference's compile-time LoDTensorArray
    slots)."""
    if isinstance(i, (int, np.integer)):
        return int(i)
    name = getattr(i, "name", None)
    lit = None
    for op in block.ops:  # last writer wins: increment etc. invalidate
        if name in op.output_names():
            lit = (op.attrs.get("value", 0)
                   if op.type == "fill_constant" else None)
    return int(lit) if lit is not None else None


def array_write(x, i, array=None):
    if array is None:
        array = create_array()
    helper = LayerHelper("array_write")
    attrs = {}
    lit = _literal_index(helper.block, i)
    if lit is not None:
        attrs["static_index"] = lit
    inputs = {"X": [x], "Array": [array]}
    if not isinstance(i, (int, np.integer)):
        inputs["I"] = [i]
    helper.block.append_op(type="array_write", inputs=inputs,
                           outputs={"Out": [array.name]}, attrs=attrs)
    xdesc = helper.block._find_var_recursive(
        x.name if hasattr(x, "name") else str(x))
    adesc = helper.block._find_var_recursive(array.name)
    if xdesc is not None:
        adesc.elem_shape = tuple(xdesc.shape or ())
        adesc.elem_dtype = xdesc.dtype
    return array


def array_read(array, i):
    helper = LayerHelper("array_read")
    out = unique_name.generate("array_read.out")
    adesc = helper.block._find_var_recursive(array.name)
    helper.block.create_var(name=out,
                            shape=tuple(adesc.elem_shape or ()),
                            dtype=adesc.elem_dtype or "float32")
    attrs = {}
    lit = _literal_index(helper.block, i)
    if lit is not None:
        attrs["static_index"] = lit
    inputs = {"X": [array]}
    if not isinstance(i, (int, np.integer)):
        inputs["I"] = [i]
    helper.block.append_op(type="array_read", inputs=inputs,
                           outputs={"Out": [out]}, attrs=attrs)
    return helper.block.var(out)


def array_length(array):
    helper = LayerHelper("array_length")
    out = unique_name.generate("array_length.out")
    helper.block.create_var(name=out, shape=(1,), dtype="int32")
    helper.block.append_op(type="array_length", inputs={"X": [array]},
                           outputs={"Out": [out]})
    return helper.block.var(out)


def tensor_array_to_tensor(input, axis=0, use_stack=False, name=None):
    helper = LayerHelper("array_to_tensor")
    out = unique_name.generate("array_concat.out")
    idx = unique_name.generate("array_concat.index")
    adesc = helper.block._find_var_recursive(input.name)
    elem = tuple(adesc.elem_shape or ())
    if use_stack:
        oshape = (-1,) + elem
    else:
        oshape = ((-1,) + elem[1:]) if elem else (-1,)
    helper.block.create_var(name=out, shape=oshape,
                            dtype=adesc.elem_dtype or "float32")
    helper.block.create_var(name=idx, shape=(-1,), dtype="int32")
    helper.block.append_op(
        type="tensor_array_to_tensor", inputs={"X": [input]},
        outputs={"Out": [out], "OutIndex": [idx]},
        attrs={"axis": int(axis), "use_stack": bool(use_stack)})
    return helper.block.var(out), helper.block.var(idx)


# ---------------------------------------------------------------------------
# extension-op registry (PEP 562 module __getattr__)
#
# layers_ext / layers_compat contribute the fluid.layers long tail. They
# must NOT setattr into this module: several fluid ops share a name with a
# Python builtin (`range`, `sum`, `pow`, `hash`, ...), and a module global
# shadows the builtin for every bare use *inside this file* (globals are
# consulted before builtins during name resolution). Attribute access from
# outside (`layers.range`, `static.nn.sum`) instead resolves through
# __getattr__, which only fires when normal lookup fails — so registered
# names are visible to callers but can never leak into this module's
# namespace.
# ---------------------------------------------------------------------------
_EXTRA_EXPORTS: Dict[str, Any] = {}


def _register_exports(mapping: Dict[str, Any]) -> None:
    """Expose extension ops as attributes of this module.

    First registration wins; names already defined in this module are
    never overridden."""
    g = globals()
    for name, value in mapping.items():
        if name not in g and name not in _EXTRA_EXPORTS:
            _EXTRA_EXPORTS[name] = value


def __getattr__(name: str):
    try:
        return _EXTRA_EXPORTS[name]
    except KeyError:
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}") from None


def __dir__():
    return sorted(set(globals()) | set(_EXTRA_EXPORTS))
