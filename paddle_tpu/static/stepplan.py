"""Compiled step-plan layer: how one training step is SHAPED.

Extracted from ``static/executor.py`` (ROADMAP-flagged: the executor
had absorbed the ``_gm_step_fn``/``_pp_step_fn``/``_comm_step_fn``
step-function zoo plus the plan/eligibility logic, and 1F1B + ZeRO
were each about to add another method on top). The split mirrors the
PR 13 substrate extraction: ``substrate.aot_compile`` owns HOW a step
compiles, this module owns WHAT the step computes — the executor keeps
only feed/fetch/state plumbing and dispatch.

A :class:`StepPlan` is built once per executable from the optimized
Program + resolved BuildStrategy knobs: the plan KIND (plain / gm /
pipeline:<schedule> / comm / zero), the microbatch count, the comm
bucket plan, the boundary shardings and the donation map. Each
step-function builder is a registered plan kind (:func:`plan_kind`),
so new schedules land as registry entries instead of executor methods:

- ``plain``            one forward(+backward+optimizer) pass
- ``gm``               lax.scan over k microbatches (gradient merge)
- ``pipeline:gpipe``   gm microbatches on the GPipe fill-drain schedule
- ``pipeline:1f1b``    one-forward-one-backward schedule: warmup of
                       S-1-s forwards per stage, then strict F/B
                       alternation — ≤S live microbatch activations by
                       construction instead of GPipe's fill-phase stash
- ``pipeline:interleaved``  1F1B with v virtual stages per chip
- ``comm``             explicit bucketed quantized DP all-reduce
                       (shard_map over the pure-dp mesh)
- ``zero``             the comm step with ZeRO-2/3 sharded optimizer
                       states: bucketed quantized reduce-scatter, the
                       optimizer region on LOCAL shards only, and a
                       post-update param all-gather

Parity contracts the kinds agree on (tested): every kind derives a
microbatch's RNG key as ``fold_in(step_key, m)`` (dropout replays
bitwise across gm/gpipe/1f1b/comm), f32 gradient accumulation in
ascending-microbatch order (gpipe and 1f1b merge bitwise-identical
gradients), and the fp16 FoundInfinite flag OR-reduces across
microbatches (and devices, on the comm/zero kinds).
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..framework import dtype as dtype_mod
from .kernels import KERNELS, ExecContext

__all__ = [
    "StepPlan", "build_plan", "build_step_fn", "plan_kind", "PLAN_KINDS",
    "merge_region", "comm_eligibility", "comm_entry_stats",
    "ensure_ef_state", "zero_eligibility", "ensure_zero_state",
    "zero_flip_back", "zero_state_layout", "ZERO_OPT_OPS",
]


# ---------------------------------------------------------------------------
# the plan object + kind registry
# ---------------------------------------------------------------------------

PLAN_KINDS: Dict[str, Callable] = {}


def plan_kind(name: str):
    """Register a step-function builder under a plan kind name. The
    builder signature is ``fn(plan, block, feed_keys, fetch_names,
    persist_names, feed_vals, notify) -> step`` where ``step(feed_vals,
    state, rng) -> (fetches, new_state)`` is what gets AOT-compiled."""

    def deco(fn):
        PLAN_KINDS[name] = fn
        return fn

    return deco


class StepPlan:
    """Everything that shapes ONE compiled training step, resolved
    once per executable: the schedule kind, the microbatch count, the
    comm bucket plan, the ZeRO layout, the jit boundary shardings and
    the donation map. ``meta`` carries kind-specific extras (stage
    count, stash depth, bubble fraction) for gauges and dump tools."""

    __slots__ = ("kind", "gm", "pp", "schedule", "comm", "comm_plan",
                 "zero", "zero_plan", "bwd_idx", "sharding", "donate",
                 "meta")

    def __init__(self, kind, *, gm=None, pp=None, schedule=None,
                 comm=None, comm_plan=None, zero=None, zero_plan=None,
                 bwd_idx=None, sharding=None, donate=True):
        self.kind = kind
        self.gm = gm
        self.pp = pp
        self.schedule = schedule
        self.comm = comm
        self.comm_plan = comm_plan
        self.zero = zero
        self.zero_plan = zero_plan
        self.bwd_idx = bwd_idx
        self.sharding = sharding
        self.donate = donate
        self.meta: Dict[str, Any] = {}

    @property
    def microbatches(self) -> int:
        return self.gm[0] if self.gm is not None else 1

    @property
    def donate_argnums(self):
        # state + rng buffers are reused in place by XLA; feeds are
        # fresh per step and stay un-donated
        return (1, 2) if self.donate else None

    def boundary_shardings(self, feed_keys, persist_names, fetch_names):
        """The jit in/out sharding maps for this plan's step signature
        ``(feed_vals, state, rng) -> (fetches, new_state)``."""
        if self.sharding is None:
            return None, None
        sharding = self.sharding
        param_shard = sharding.get("__param__")
        # per-name entries (the shard_propagation boundary map: hinted
        # tp/dp params, __comm_ef_*/__zero_* rows) beat the blanket
        # __param__ fallback; the classic data-parallel map has no
        # per-name entries so this degenerates to [param_shard] * N
        state_shards = [sharding.get(n, param_shard)
                        for n in persist_names]
        in_shardings = (
            [sharding.get(k) for k in feed_keys],
            state_shards,
            sharding.get("__rng__"))
        # pin state OUTPUTS to the same layout: chained steps feed
        # new_state straight back in without re-partitioning
        out_shardings = (
            [None] * len(fetch_names),
            state_shards)
        return in_shardings, out_shardings


def build_plan(block, *, gm=None, pp=None, comm=None, comm_plan=None,
               schedule=None, zero=None, zero_plan=None, sharding=None,
               donate=True) -> StepPlan:
    """Select the plan kind for one optimized block + resolved config.

    Selection order mirrors the pre-refactor ``Executor._build``: an
    engaged comm plan on a backward block wins (zero variant when the
    ZeRO layout engaged too), then the pipeline schedule when gm+pp and
    ``__pp_stage`` stamps are present, then the gm scan, else plain."""
    bwd_idx = next((i for i, op in enumerate(block.ops)
                    if op.type == "backward"), None)
    if comm_plan is not None and bwd_idx is not None:
        kind = "zero" if zero_plan is not None else "comm"
    elif gm is not None and bwd_idx is not None and pp is not None \
            and pp > 1 and any("__pp_stage" in op.attrs
                               for op in block.ops):
        kind = f"pipeline:{schedule or 'gpipe'}"
    elif gm is not None and bwd_idx is not None:
        kind = "gm"
    else:
        kind = "plain"
    return StepPlan(kind, gm=gm, pp=pp, schedule=schedule, comm=comm,
                    comm_plan=comm_plan, zero=zero, zero_plan=zero_plan,
                    bwd_idx=bwd_idx, sharding=sharding, donate=donate)


def build_step_fn(plan: StepPlan, block, feed_keys, fetch_names,
                  persist_names, feed_vals,
                  notify: Optional[Callable[[str, Any], None]] = None):
    """Build the traced step callable for ``plan`` through its
    registered kind. ``notify(name, value)`` is the executor's gauge
    sink (pp_stages, pp_bubble_frac, ...); pass None to skip."""
    base = plan.kind.split(":", 1)[0]
    builder = PLAN_KINDS.get(base)
    if builder is None:
        raise KeyError(f"no step-plan kind registered for {plan.kind!r}")
    if notify is None:
        def notify(_name, _value):
            pass
    return builder(plan, block, feed_keys, fetch_names, persist_names,
                   feed_vals, notify)


# ---------------------------------------------------------------------------
# shared region split (the gm scan / pipeline schedules / comm step all
# agree on this boundary — their parity depends on it)
# ---------------------------------------------------------------------------


def merge_region(block, feed_keys, feed_vals, persist_names,
                 fetch_names, k, bwd_idx):
    """Split one training block at the backward boundary for a
    k-microbatch merged step — shared by the gm scan, the pipeline
    schedules and the comm/zero steps (their parity depends on
    agreeing on this split). Returns ``(scan_end, grad_names,
    found_name, state_carry, carry_out, post_outs)``: ops
    [0, scan_end) run per microbatch (forward + backward + an adjacent
    fp16 check_finite_and_unscale), ops [scan_end, ...) are the
    optimizer region run once on the merged gradient; state_carry is
    the per-microbatch persistable writes, carry_out everything else
    the post region or a fetch reads."""
    for key, v in zip(feed_keys, feed_vals):
        shp = tuple(getattr(v, "shape", ()))
        if not shp or shp[0] % k:
            raise ValueError(
                f"gradient_merge_k={k}: feed {key!r} batch dim "
                f"{shp[0] if shp else None} is not divisible by k")
    ops = block.ops
    scan_end = bwd_idx + 1
    if scan_end < len(ops) and \
            ops[scan_end].type == "check_finite_and_unscale":
        scan_end += 1
    grad_names = list(ops[bwd_idx].outputs.get("Grads", []))
    found_name = None
    if ops[scan_end - 1].type == "check_finite_and_unscale":
        fo = ops[scan_end - 1].outputs.get("FoundInfinite")
        found_name = fo[0] if fo else None
    produced: set = set()
    for op in ops[:scan_end]:
        produced.update(op.output_names())
    post_reads: set = set()
    post_outs: set = set()
    for op in ops[scan_end:]:
        post_reads.update(op.input_names())
        post_outs.update(op.output_names())
    special = set(grad_names) | {found_name} - {None}
    persist_set = set(persist_names)
    # state written per microbatch rides the carry; everything else
    # the post region or a fetch reads rides the stacked ys
    state_carry = sorted(produced & persist_set)
    carry_out = sorted(((post_reads | set(fetch_names)) & produced)
                       - special - persist_set)
    return (scan_end, grad_names, found_name, state_carry,
            carry_out, post_outs)


def comm_entry_stats(comm_plan) -> Dict[str, Any]:
    """Per-dispatch quantized-collective accounting for one compiled
    executable: encoded ring bytes actually moved per device per step
    (``bytes_sent``), the f32 bytes the codec saved (``bytes_saved``),
    the bucket count, and the analytic overlap fraction — with nb
    buckets emitted in completion order, nb-1 of them have a later
    bucket's work in flight behind them (the last one drains alone),
    the same analytic convention as pp_bubble_frac."""
    _axis, _g, plan = comm_plan
    sent = sum(b["ring_encoded"] for b in plan)
    f32 = sum(b["ring_f32"] for b in plan)
    nb = len(plan)
    return {
        "bytes_sent": int(sent),
        "bytes_saved": int(max(0, f32 - sent)),
        "comm_buckets": nb,
        "allreduce_overlap_frac": round((nb - 1) / nb, 4) if nb else 0.0,
    }


def zero_entry_stats(comm_plan) -> Dict[str, Any]:
    """Per-dispatch wire accounting for a ZeRO step: the sharded
    optimizer replaces the bucketed all-reduce ring with a half-ring
    reduce-scatter of the ENCODED grads plus a raw-f32 all-gather of
    the updated values, so ``bytes_sent`` is that rs+ag profile and
    ``bytes_saved`` is measured against the f32 all-reduce ring. Kept
    out of the ``comm_quant_*`` counters: the all-gather leg moves raw
    f32, and folding its bytes into the quantized-ring counters would
    break their saved>sent codec invariant (ride
    ``zero_wire_bytes_*`` instead — see the executor's dispatch
    bump)."""
    _axis, _g, plan = comm_plan
    rs = sum(b["ring_encoded"] // 2 for b in plan)
    ag = sum(b["ring_f32"] - b["ring_f32"] // 2 for b in plan)
    f32 = sum(b["ring_f32"] for b in plan)
    nb = len(plan)
    return {
        "zero": True,
        "bytes_sent": int(rs + ag),
        "bytes_saved": int(max(0, f32 - (rs + ag))),
        "comm_buckets": nb,
        "allreduce_overlap_frac": round((nb - 1) / nb, 4) if nb else 0.0,
    }


# ---------------------------------------------------------------------------
# plain + gm kinds
# ---------------------------------------------------------------------------


@plan_kind("plain")
def _plain_step_fn(plan, block, feed_keys, fetch_names, persist_names,
                   feed_vals, notify):
    from .executor import run_block

    def step(feed_vals, state, rng):
        env = dict(zip(feed_keys, feed_vals))
        env.update(zip(persist_names, state))
        ctx = ExecContext(rng_key=rng)
        env = run_block(block, env, ctx)
        fetches = [env[n] for n in fetch_names]
        new_state = [env.get(n, s)
                     for n, s in zip(persist_names, state)]
        return fetches, new_state

    return step


@plan_kind("gm")
def _gm_step_fn(plan, block, feed_keys, fetch_names, persist_names,
                feed_vals, notify):
    """In-step gradient merge: compile the train step as ONE lax.scan
    over k microbatches (GPipe-style accumulation, inside a single
    dispatch).

    The op list splits at the backward boundary: ops [0, scan_end)
    (forward + backward + an adjacent fp16 check_finite_and_unscale)
    run PER MICROBATCH inside the scan; ops [scan_end, ...) — the
    optimizer update region — run ONCE on the merged gradient.
    Mechanics:

    - every feed is reshaped (B, ...) -> (k, B//k, ...) inside the
      trace (host layout untouched; B must divide by k)
    - gradients accumulate in f32 whatever the compute dtype (AMP
      bf16/fp16 microbatch grads are upcast before the add), and
      with avg=True the MERGED sum is divided by k once — never a
      per-microbatch lr rescale
    - the fp16 FoundInfinite flag is OR-reduced over microbatches:
      one bad microbatch skips the whole merged update
    - persistable state written inside the scanned region
      (batch_norm running stats, step counters) threads through the
      scan carry, so microbatch i sees microbatch i-1's updates
    - each microbatch folds its index into the step RNG key —
      dropout draws fresh masks per microbatch
    - float fetches produced inside the scanned region (the loss)
      are averaged over microbatches; non-float fetches report the
      last microbatch
    """
    from .executor import run_block

    k, avg = plan.gm
    bwd_idx = plan.bwd_idx
    (scan_end, grad_names, found_name, state_carry, carry_out,
     post_outs) = merge_region(block, feed_keys, feed_vals,
                               persist_names, fetch_names, k, bwd_idx)

    def _micro(mb_feed, state_env, carried, key):
        env = dict(zip(feed_keys, mb_feed))
        env.update(state_env)
        env.update(carried)
        ctx = ExecContext(rng_key=key)
        return run_block(block, env, ctx, stop_at=scan_end)

    # grad avals (shape/dtype of ONE microbatch's grads): read from
    # the grad VarDescs when fully static — append_backward declares
    # them with the param's shape/dtype — falling back to an
    # abstract eval_shape trace only for dynamic shapes
    # (calc_gradient w.r.t. a batch-dim intermediate). The probe
    # re-interprets the whole scanned region, so skipping it halves
    # merged-build trace time in the common (param-grad) case.
    grad_avals = []
    for g in grad_names:
        desc = block.vars.get(g)
        shape = getattr(desc, "shape", None)
        if not shape or any(int(d) < 0 for d in shape):
            grad_avals = None
            break
        grad_avals.append(jax.ShapeDtypeStruct(
            tuple(int(d) for d in shape),
            jnp.dtype(dtype_mod.convert_dtype(desc.dtype))))

    mb_avals = [jax.ShapeDtypeStruct(
        (int(v.shape[0]) // k,) + tuple(int(d) for d in v.shape[1:]),
        getattr(v, "dtype", np.asarray(v).dtype))
        for v in feed_vals]

    def _probe(mb_feed, state, rng):
        env = _micro(mb_feed, dict(zip(persist_names, state)), {},
                     rng)
        return [env[g] for g in grad_names]

    def step(feed_vals, state, rng):
        state_env0 = dict(zip(persist_names, state))
        avals = grad_avals if grad_avals is not None else \
            jax.eval_shape(_probe, mb_avals, state, rng)
        mbs = [v.reshape((k, v.shape[0] // k) + tuple(v.shape[1:]))
               for v in feed_vals]

        def body(carry, xs):
            accum, carried, found = carry
            mb, mi = xs
            env = _micro(mb, state_env0, carried,
                         jax.random.fold_in(rng, mi))
            accum = [a + env[g].astype(jnp.float32)
                     for a, g in zip(accum, grad_names)]
            carried = {n: env[n] for n in state_carry}
            if found_name is not None:
                found = found | jnp.reshape(
                    env[found_name], ()).astype(bool)
            ys = {n: env[n] for n in carry_out}
            return (accum, carried, found), ys

        init = ([jnp.zeros(a.shape, jnp.float32) for a in avals],
                {n: state_env0[n] for n in state_carry},
                jnp.zeros((), jnp.bool_))
        (accum, carried, found), ys = jax.lax.scan(
            body, init, (mbs, jnp.arange(k)))
        env = dict(zip(feed_keys, feed_vals))  # full batch for post
        env.update(state_env0)
        env.update(carried)
        env.update({n: ys[n][-1] for n in carry_out})
        for g, a, aval in zip(grad_names, accum, avals):
            merged = a / k if avg else a
            env[g] = merged.astype(aval.dtype)
        if found_name is not None:
            env[found_name] = jnp.reshape(found, (1,))
        ctx = ExecContext(rng_key=rng)
        env = run_block(block, env, ctx, start=scan_end)
        fetches = []
        for n in fetch_names:
            if n in ys and n not in post_outs:
                stacked = ys[n]
                if jnp.issubdtype(stacked.dtype, jnp.inexact):
                    fetches.append(jnp.mean(
                        stacked.astype(jnp.float32), axis=0
                    ).astype(stacked.dtype))
                else:
                    fetches.append(stacked[-1])
            else:
                fetches.append(env[n])
        new_state = [env.get(n, s)
                     for n, s in zip(persist_names, state)]
        return fetches, new_state

    return step


# ---------------------------------------------------------------------------
# pipeline kinds (gpipe / 1f1b / interleaved — one executor body, the
# schedule decides the slot order)
# ---------------------------------------------------------------------------


@plan_kind("pipeline")
def _pipeline_step_fn(plan, block, feed_keys, fetch_names,
                      persist_names, feed_vals, notify):
    """Pipeline-composed gradient merge: the k microbatches of
    BuildStrategy.gradient_merge_k flow through the
    ``__pp_stage``-stamped forward stages on the resolved schedule
    (``parallel.pipeline``), still as ONE compiled, donated,
    device-resident dispatch.

    Differences from the plain gm scan:

    - the microbatch loop is schedule-ordered instead of sequential —
      within a tick every (stage, microbatch) pair is data-independent,
      which is the property that lets XLA overlap the stages across a
      'pp' mesh axis (and on one chip compiles to the same math)
    - a microbatch's backward (+ fp16 finite check) runs when it
      retires from the last stage; f32 gradient accumulation happens
      in retirement order == microbatch order, so the merged gradient
      matches the scan's within reassociation roundoff — and matches
      BITWISE across schedules (gpipe/1f1b/interleaved retire
      microbatches in the same ascending order)
    - persistable state written INSIDE the forward region does not
      thread microbatch-to-microbatch (stages overlap, so there is no
      earlier-microbatch value to read); every microbatch sees the
      step-entry state and the LAST retired microbatch's writes carry
      out — bn running stats behave like classic GPipe, parameter
      updates are untouched (they live in the post region)

    Schedules: ``gpipe`` drives the fill-drain ``gpipe_schedule``
    exactly as before; ``1f1b``/``interleaved`` drive the
    ``pipeline_timeline`` slot stream — same per-microbatch math, a
    different emission order, and a bounded modeled stash depth (the
    ``pp_stash_depth`` gauge). Everything else (feed reshape,
    merged-gradient averaging, FoundInfinite OR-reduce, loss-fetch
    averaging, single optimizer region on the merged gradient) mirrors
    the gm scan."""
    from ..parallel.pipeline import (
        gpipe_schedule, pipeline_timeline, schedule_bubble_fraction)
    from .executor import run_block

    k, avg = plan.gm
    bwd_idx = plan.bwd_idx
    schedule = plan.schedule or "gpipe"
    interleave = plan.meta.get("interleave", 2)
    (scan_end, grad_names, found_name, state_carry, carry_out,
     post_outs) = merge_region(block, feed_keys, feed_vals,
                               persist_names, fetch_names, k, bwd_idx)
    ops = block.ops

    # stage op ranges from the __pp_stage stamps: stage s covers the
    # absolute index range (start_s, end_s]; un-stamped prefix ops
    # (feeds) ride stage 0, un-stamped trailing forward ops ride the
    # last stage
    stage_last: Dict[int, int] = {}
    for i in range(bwd_idx):
        sid = ops[i].attrs.get("__pp_stage")
        if sid is not None:
            stage_last[int(sid)] = i
    n_stages = max(stage_last) + 1
    ranges = []
    start = 0
    for s in range(n_stages):
        end = bwd_idx if s == n_stages - 1 else stage_last[s] + 1
        ranges.append((start, end))
        start = end
    notify("pp_stages", n_stages)
    if schedule == "interleaved" and n_stages % interleave:
        # the stamped stage count (which can be smaller than the
        # requested pipeline_stages on shallow nets) must divide by the
        # virtual-chunk factor; degrade to plain 1f1b instead of
        # refusing the step — same math, same retirement order
        plan.meta["schedule_fallback"] = (
            f"interleaved: {n_stages} stages not divisible by "
            f"interleave {interleave} — running 1f1b")
        schedule = "1f1b"
        notify("pp_schedule_fallback", 1)
    if schedule != "gpipe":
        # the slot stream for the non-gpipe schedules; gpipe keeps its
        # original generator below (bitwise-stable trace order)
        slots = [(kind_, s, m) for _t, tick in pipeline_timeline(
            schedule, n_stages, k, interleave=interleave)
            for kind_, s, m in tick]
        stash = plan.meta["stash_depth"] = _modeled_stash_depth(
            pipeline_timeline(schedule, n_stages, k,
                              interleave=interleave), k)
        notify("pp_stash_depth", stash)
    bubble = schedule_bubble_fraction(schedule, n_stages, k,
                                      interleave=interleave)
    plan.meta.update(n_stages=n_stages, bubble_frac=bubble)
    notify("pp_bubble_frac", round(bubble, 4))

    def _retire(env, ctx, s, accum, grad_dtypes, found, carried, ys, m):
        # microbatch m retires: backward + fp16 finite check, then
        # f32 accumulation (ascending-m retirement order on every
        # schedule — the cross-schedule bitwise-parity invariant)
        run_block(block, env, ctx, start=ranges[s][1], stop_at=scan_end)
        if grad_dtypes is None:
            grad_dtypes = [env[g].dtype for g in grad_names]
        g = [env[gn].astype(jnp.float32) for gn in grad_names]
        accum = g if accum is None else \
            [a + b for a, b in zip(accum, g)]
        if found_name is not None:
            found = found | jnp.reshape(
                env[found_name], ()).astype(bool)
        carried = {n: env[n] for n in state_carry}
        for n in carry_out:
            ys[n][m] = env[n]
        return accum, grad_dtypes, found, carried

    def step(feed_vals, state, rng):
        state_env0 = dict(zip(persist_names, state))
        mbs = [v.reshape((k, v.shape[0] // k) + tuple(v.shape[1:]))
               for v in feed_vals]
        accum = None
        grad_dtypes = None
        found = jnp.zeros((), jnp.bool_)
        carried: Dict[str, Any] = {}
        ys = {n: [None] * k for n in carry_out}
        live: Dict[int, tuple] = {}

        def _enter(m):
            env = dict(zip(feed_keys, [mb[m] for mb in mbs]))
            env.update(state_env0)
            # same per-microbatch key derivation as the gm scan:
            # dropout masks match the scan leg bitwise
            live[m] = (env, ExecContext(
                rng_key=jax.random.fold_in(rng, m)))

        if schedule == "gpipe":
            for _t, pairs in gpipe_schedule(n_stages, k):
                for s, m in pairs:
                    if s == 0:
                        _enter(m)
                    env, ctx = live[m]
                    run_block(block, env, ctx,
                              start=ranges[s][0], stop_at=ranges[s][1])
                    if s == n_stages - 1:
                        accum, grad_dtypes, found, carried = _retire(
                            env, ctx, s, accum, grad_dtypes, found,
                            carried, ys, m)
                        del live[m]
        else:
            for kind_, s, m in slots:
                if kind_ != "F":
                    continue  # the backward op is monolithic: it runs
                    # at retirement (the last-stage F slot below)
                if s == 0:
                    _enter(m)
                env, ctx = live[m]
                run_block(block, env, ctx,
                          start=ranges[s][0], stop_at=ranges[s][1])
                if s == n_stages - 1:
                    accum, grad_dtypes, found, carried = _retire(
                        env, ctx, s, accum, grad_dtypes, found,
                        carried, ys, m)
                    del live[m]
        env = dict(zip(feed_keys, feed_vals))  # full batch for post
        env.update(state_env0)
        env.update(carried)
        env.update({n: ys[n][-1] for n in carry_out})
        for gname, a, dt in zip(grad_names, accum or (),
                                grad_dtypes or ()):
            merged = a / k if avg else a
            env[gname] = merged.astype(dt)
        if found_name is not None:
            env[found_name] = jnp.reshape(found, (1,))
        ctx = ExecContext(rng_key=rng)
        env = run_block(block, env, ctx, start=scan_end)
        fetches = []
        for n in fetch_names:
            if n in ys and n not in post_outs:
                stacked = jnp.stack(ys[n])
                if jnp.issubdtype(stacked.dtype, jnp.inexact):
                    fetches.append(jnp.mean(
                        stacked.astype(jnp.float32), axis=0
                    ).astype(stacked.dtype))
                else:
                    fetches.append(stacked[-1])
            else:
                fetches.append(env[n])
        new_state = [env.get(n, s_)
                     for n, s_ in zip(persist_names, state)]
        return fetches, new_state

    return step


def _modeled_stash_depth(timeline, n_micro: int) -> int:
    """Max simultaneously-live microbatch activations a schedule
    timeline implies: a microbatch is live from its first F slot to its
    LAST B slot (stage-0 backward frees the stash)."""
    first_f: Dict[int, int] = {}
    last_b: Dict[int, int] = {}
    for t, tick in timeline:
        for kind_, _s, m in tick:
            if kind_ == "F":
                first_f.setdefault(m, t)
            else:
                last_b[m] = t
    depth = 0
    for t in range(max(last_b.values(), default=0) + 1):
        live = sum(1 for m in first_f
                   if first_f[m] <= t <= last_b.get(m, first_f[m]))
        depth = max(depth, live)
    return depth


# ---------------------------------------------------------------------------
# comm kind (ISSUE 15: EQuARX-style quantized DP collectives) + the
# eligibility gate and error-feedback state the executor wires up
# ---------------------------------------------------------------------------


def comm_eligibility(program, block, comm, shard_cfg, gm, feed,
                     sharding, pp=None, memo=None, bump=None):
    """Gate + plan for the explicit quantized-collective DP step.

    Returns ``(key, result)`` where ``result`` is ``(axis_name, group,
    plan)`` when the build is eligible, else None after bumping the
    ``quant_allreduce.xla`` dispatch counter with the reason (the
    established kernel pattern — the XLA f32 GSPMD path is the
    fallback, bitwise-identical to the pre-quantization baseline).
    Pass the previous return as ``memo`` to reuse the warm verdict
    without re-bumping counters (the executor keeps it per-instance:
    the warm step pays one key comparison).

    Eligible means: a PURE data-parallel mesh (exactly one 'dp'/'data'
    axis, no sharding hints — tensor/pipeline layouts keep XLA's
    partitioner-owned collectives), one static ``backward`` gradient
    plan, no persistable writes inside the scanned region (per-device
    batch-norm style stats would diverge silently under a
    replicated-out shard_map), every dynamic-batch feed actually
    sharded over the axis, and local batches divisible by
    gradient_merge_k."""
    from ..ops.pallas.counters import bump as _bump
    from .passes import comm_bucket_plan, comm_data_axis

    if bump is None:
        bump = _bump
    key = (program._version, comm, shard_cfg, gm, pp,
           tuple(sorted((k, tuple(getattr(v, "shape", ())))
                        for k, v in feed.items())))
    if memo is not None and memo[0] == key:
        return memo

    def verdict(result, reason=None):
        if result is None:
            bump("quant_allreduce", "xla", reason)
        else:
            bump("quant_allreduce", "quant")
        return (key, result)

    if shard_cfg is None:
        return verdict(None, "comm_quant set but no mesh_shape — "
                             "quantized collectives need a dp mesh")
    if pp is not None:
        return verdict(None, "pipeline_stages > 1 — the pipeline "
                             "schedule keeps XLA collectives")
    axis = comm_data_axis(shard_cfg)
    if axis is None:
        return verdict(None, "mesh is not pure data-parallel "
                             f"(axes {shard_cfg[0]})")
    if shard_cfg[1]:
        return verdict(None, "sharding_hints present — tensor-"
                             "parallel layouts keep XLA collectives")
    name, g = axis
    plan = comm_bucket_plan(block, comm, g)
    if plan is None:
        return verdict(None, "no static gradient plan (no backward "
                             "op, or dynamic grad shapes)")
    ops = block.ops
    bwd_idx = next(i for i, op in enumerate(ops)
                   if op.type == "backward")
    persist = {n for n, v in block.vars.items() if v.persistable}
    written = {n for op in ops[:bwd_idx] for n in op.output_names()
               if n in persist}
    if written:
        return verdict(None, f"persistable writes in the forward "
                             f"region ({sorted(written)[:3]}) would "
                             "diverge per-device")
    for k_, v in feed.items():
        dv = block.vars.get(k_)
        shape = getattr(dv, "shape", None)
        if not shape or shape[0] is None or int(shape[0]) >= 0:
            continue
        sh = sharding.get(k_) if sharding else None
        spec = getattr(sh, "spec", None)
        if not spec or not spec[0]:
            return verdict(None, f"feed {k_!r} batch dim not "
                                 f"sharded over {name!r} (size not "
                                 f"divisible by {g}?)")
        local_b = int(getattr(v, "shape", (0,))[0]) // g
        if gm is not None and local_b % gm[0]:
            return verdict(None, f"local batch {local_b} not "
                                 f"divisible by gradient_merge_k="
                                 f"{gm[0]}")
    return verdict((name, g, plan))


def ensure_ef_state(scope, comm_plan, shard_cfg, sharding):
    """Materialize the error-feedback residual buffers as DONATED
    executor state: one ``(g, padded)`` f32 array per bucket, sharded
    over the data axis so each device owns its row. Returns the names
    (appended to persist_names; XLA updates them in place step over
    step through the normal donation path)."""
    from jax.sharding import NamedSharding, PartitionSpec

    from ..parallel.collectives import padded_len
    from ..parallel.mesh import mesh_for_shape

    axis, g, plan = comm_plan
    mesh = mesh_for_shape(dict(shard_cfg[0]))
    shard = NamedSharding(mesh, PartitionSpec(axis, None))
    peek = getattr(scope, "_peek", scope.find_var)
    write_back = getattr(scope, "_write_back", scope.set)
    names = []
    for i, b in enumerate(plan):
        n = f"__comm_ef_{i}"
        padded = padded_len(b["elems"], g)
        arr = peek(n)
        if not isinstance(arr, jax.Array) or \
                tuple(arr.shape) != (g, padded):
            arr = jax.device_put(np.zeros((g, padded), np.float32),
                                 shard)
            write_back(n, arr)
        sharding[n] = shard
        names.append(n)
    return names


@plan_kind("comm")
def _comm_step_fn(plan, block, feed_keys, fetch_names, persist_names,
                  feed_vals, notify):
    """Compile the DP train step with an EXPLICIT bucketed, quantized
    gradient all-reduce instead of XLA's implicit f32 psum: the whole
    step runs inside shard_map over the pure-dp mesh — each device
    traces the forward+backward on its LOCAL batch shard, the
    per-bucket gradients reduce through parallel.collectives'
    quantized ring (encode per hop, f32 accumulation, deterministic
    decode → bitwise-replicated reduced values), and the optimizer
    region then runs replicated on every device (same grads + same
    params ⇒ same updates, so state out-specs are replicated by
    construction).

    Overlap: every bucket's reduce-scatter is ISSUED (in backward-
    completion order, the comm_bucketing plan) before any bucket's
    all-gather completes — XLA's latency-hiding scheduler is free
    to run them concurrently instead of one barrier-shaped reduce.

    Composition: with ``gradient_merge_k`` the local microbatch
    scan accumulates f32 grads exactly like the gm kind and the
    MERGED gradient is reduced once per step (quantize once per
    step, the PR 5 accumulator discipline). ``avg=True`` on the
    collective turns sum-of-local-mean-grads into the global-mean
    gradient, matching the GSPMD leg's mean-loss semantics.

    Fetch assembly: dynamic-batch fetches gather over the axis
    (out-spec carries the batch dim), other float fetches are
    pmean'd (exact for replicated values, the global mean for
    per-shard losses), the rest report the local value.

    Error feedback (``comm_error_feedback``): each device adds its
    residual to its contribution, quantizes ONCE locally, carries
    the new residual out through the donated ``__comm_ef_<i>``
    state row, and feeds the dequantized contribution into the
    ring."""
    from jax.sharding import PartitionSpec as P

    from ..parallel.collectives import (
        allreduce_done, allreduce_start, padded_len, quant_decode,
        quant_encode, shard_map_nocheck)
    from ..parallel.mesh import mesh_for_shape
    from .executor import run_block

    sharding = plan.sharding
    gm = plan.gm
    bwd_idx = plan.bwd_idx
    axis, g, cplan = plan.comm_plan
    codec, _bucket_bytes, ef = plan.comm
    k, avg_gm = gm if gm is not None else (1, True)
    (scan_end, grad_names, found_name, state_carry, carry_out,
     post_outs) = merge_region(block, feed_keys, feed_vals,
                               persist_names, fetch_names, 1, bwd_idx)
    mesh = mesh_for_shape({axis: g})
    ef_names = [f"__comm_ef_{i}" for i in range(len(cplan))] \
        if ef else []
    ef_set = set(ef_names)
    reg_names = [n for n in persist_names if n not in ef_set]

    grad_elems = {}
    grad_shapes = {}
    for gn in grad_names:
        desc = block.vars.get(gn)
        shape = tuple(int(d) for d in (desc.shape or ()))
        grad_shapes[gn] = shape
        e = 1
        for d in shape:
            e *= d
        grad_elems[gn] = e

    def spec_of(n):
        sh = sharding.get(n) if sharding else None
        spec = getattr(sh, "spec", None)
        return P(*spec) if spec is not None else P()

    # fetch modes: dynamic-batch fetches re-assemble over the axis;
    # float fetches pmean (global mean for shard-varying losses, a
    # no-op for replicated values); the rest report local
    fetch_modes = []
    for n in fetch_names:
        v = block.vars.get(n)
        shape = getattr(v, "shape", None)
        dt = str(getattr(v, "dtype", "float32"))
        if shape and (shape[0] is None or int(shape[0]) < 0):
            fetch_modes.append("gather")
        elif dt.startswith("float") or dt == "bfloat16":
            fetch_modes.append("pmean")
        else:
            fetch_modes.append("local")

    in_specs = ([spec_of(kk) for kk in feed_keys],
                [P(axis, None) if n in ef_set else P()
                 for n in persist_names],
                P())
    out_specs = ([P(axis) if m == "gather" else P()
                  for m in fetch_modes],
                 [P(axis, None) if n in ef_set else P()
                  for n in persist_names])

    def reduce_buckets(env, ef_rows):
        """Bucketed quantized all-reduce of env's grads, overlap-
        emitted; returns (env with reduced grads, new ef rows)."""
        xs, new_ef = [], []
        for i, b in enumerate(cplan):
            flats = [env[gn].astype(jnp.float32).reshape(-1)
                     for gn in b["grads"]]
            flat = flats[0] if len(flats) == 1 else \
                jnp.concatenate(flats)
            padded = padded_len(b["elems"], g)
            if padded != flat.shape[0]:
                flat = jnp.concatenate(
                    [flat, jnp.zeros((padded - flat.shape[0],),
                                     jnp.float32)])
            if ef:
                flat = flat + ef_rows[i]
                q, sc = quant_encode(flat, codec)
                dec = quant_decode(q, sc, codec)
                new_ef.append(flat - dec)
                flat = dec
            xs.append(flat)
        starts = [allreduce_start(x, axis, codec=codec, axis_size=g)
                  for x in xs]
        reduced = [allreduce_done(c, avg=True) for c in starts]
        for b, r in zip(cplan, reduced):
            off = 0
            for gn in b["grads"]:
                e = grad_elems[gn]
                env[gn] = r[off:off + e].reshape(
                    grad_shapes[gn]).astype(env[gn].dtype)
                off += e
        return env, new_ef

    def local_step(feed_local, state, rng):
        state_env = dict(zip(persist_names, state))
        ef_rows = [state_env[n][0] for n in ef_names]
        state_env0 = {n: state_env[n] for n in reg_names}
        found = jnp.zeros((), jnp.bool_)
        if k > 1:
            mbs = [v.reshape((k, v.shape[0] // k)
                             + tuple(v.shape[1:]))
                   for v in feed_local]

            def body(carry, xs):
                accum, found = carry
                mb, mi = xs
                env = dict(zip(feed_keys, mb))
                env.update(state_env0)
                ctx = ExecContext(
                    rng_key=jax.random.fold_in(rng, mi))
                env = run_block(block, env, ctx, stop_at=scan_end)
                accum = [a + env[gn].astype(jnp.float32)
                         for a, gn in zip(accum, grad_names)]
                if found_name is not None:
                    found = found | jnp.reshape(
                        env[found_name], ()).astype(bool)
                ys = {n: env[n] for n in carry_out}
                return (accum, found), ys

            init = ([jnp.zeros((grad_elems[gn],), jnp.float32
                               ).reshape(grad_shapes[gn])
                     for gn in grad_names],
                    jnp.zeros((), jnp.bool_))
            (accum, found), ys = jax.lax.scan(
                body, init, (mbs, jnp.arange(k)))
            env = dict(zip(feed_keys, feed_local))
            env.update(state_env0)
            env.update({n: ys[n][-1] for n in carry_out})
            for gn, a in zip(grad_names, accum):
                env[gn] = (a / k if avg_gm else a)
            scanned_ys = ys
        else:
            env = dict(zip(feed_keys, feed_local))
            env.update(state_env0)
            ctx = ExecContext(rng_key=rng)
            env = run_block(block, env, ctx, stop_at=scan_end)
            if found_name is not None:
                found = jnp.reshape(env[found_name], ()).astype(bool)
            scanned_ys = None
        env, new_ef = reduce_buckets(env, ef_rows)
        if found_name is not None:
            # one non-finite microbatch on ANY device skips the
            # whole replicated update (pmax = cross-device OR)
            found = jax.lax.pmax(found.astype(jnp.int32), axis) > 0
            env[found_name] = jnp.reshape(found, (1,))
        ctx = ExecContext(rng_key=rng)
        env = run_block(block, env, ctx, start=scan_end)
        fetches = []
        for n, mode in zip(fetch_names, fetch_modes):
            if scanned_ys is not None and n in scanned_ys \
                    and n not in post_outs:
                stacked = scanned_ys[n]
                if jnp.issubdtype(stacked.dtype, jnp.inexact):
                    val = jnp.mean(stacked.astype(jnp.float32),
                                   axis=0).astype(stacked.dtype)
                else:
                    val = stacked[-1]
            else:
                val = env[n]
            if mode == "pmean" and jnp.issubdtype(
                    jnp.asarray(val).dtype, jnp.inexact):
                val = jax.lax.pmean(
                    val.astype(jnp.float32), axis).astype(val.dtype)
            fetches.append(val)
        new_state = []
        ef_iter = iter(new_ef)
        for n, s in zip(persist_names, state):
            if n in ef_set:
                new_state.append(next(ef_iter)[None, :]
                                 if ef else s)
            else:
                new_state.append(env.get(n, s))
        return fetches, new_state

    sharded = shard_map_nocheck(local_step, mesh, in_specs,
                                out_specs)

    def step(feed_vals, state, rng):
        return sharded(feed_vals, state, rng)

    return step


# ---------------------------------------------------------------------------
# zero kind (ISSUE 18: ZeRO-2/3 sharded optimizer states riding the
# engaged comm plan) + its eligibility gate, state layout and flip-back
# ---------------------------------------------------------------------------

# optimizer ops that run on a (chunk,) shard. sgd/momentum/adam are
# ELEMENTWISE, so they commute with the concat/pad/chunk reshuffle
# unchanged. lamb (ISSUE 19) rides the fused kernel's TWO-PHASE trust
# plan: per-chunk partial per-param sq-norms -> one tiny psum over the
# dp axis -> the elementwise finish consumes the global norms — so its
# global-param-norm trust ratio no longer blocks sharding (it is
# tolerance-parity vs the unsharded op: the norm sum reassociates
# across devices).
ZERO_OPT_OPS = ("sgd", "momentum", "adam", "lamb")

# per-op state slots that shard into (g, chunk) rows, and the scalar
# accumulators that stay replicated per-var (the fused kernel call
# updates them through its own gated Beta*PowOut rule)
_ZERO_ROLES = {"sgd": (), "momentum": ("Velocity",),
               "adam": ("Moment1", "Moment2"),
               "lamb": ("Moment1", "Moment2")}
_ZERO_SCALARS = {"sgd": (), "momentum": (),
                 "adam": ("Beta1Pow", "Beta2Pow"),
                 "lamb": ("Beta1Pow", "Beta2Pow")}


def _zero_row_sources(stage, bucket):
    """role -> source var names for one bucket's sharded rows (params
    join the rows at stage 3)."""
    src = {role: names for role, names in bucket["roles"].items()}
    if stage >= 3:
        src["Param"] = bucket["params"]
    return src


def zero_eligibility(program, block, zero, comm, comm_plan, shard_cfg,
                     gm, pp, fetch_names, memo=None, bump=None):
    """Gate + plan for ZeRO-2/3 sharded optimizer states.

    Returns ``(key, result)`` where ``result`` is the zero_plan dict
    when eligible, else None after bumping the ``zero.xla`` dispatch
    counter with the reason (the same counted-fallback pattern as
    :func:`comm_eligibility` — the replicated comm/GSPMD step is the
    fallback). Pass the previous return as ``memo`` for the warm path.

    ZeRO rides the ENGAGED quantized comm plan: the bucketed all-reduce
    decomposes into reduce-scatter + all-gather and the optimizer
    region collapses to one fused elementwise kernel call per bucket on
    this device's (chunk,) shard. Eligible means: the comm plan is
    engaged, every bucket's params are updated by allowlisted
    chunk-shardable optimizer ops (:data:`ZERO_OPT_OPS`; lamb via the
    fused kernel's two-phase trust-ratio plan) with ONE uniform
    type/attrs/lr/gate per bucket (the fused call synthesizes a single
    op), params and grads are f32 (a chunked f32 update of a bf16
    param would drift from the reference kernel's native-dtype math),
    no surviving post-region op reads the merged gradient / sharded
    moments / stage-3 params (never materialized), and no fetch asks
    for absorbed state."""
    from ..ops.pallas.counters import bump as _bump
    from ..parallel.collectives import padded_len

    if bump is None:
        bump = _bump
    key = (program._version, zero, comm, comm_plan is not None,
           shard_cfg, gm, pp, tuple(fetch_names))
    if memo is not None and memo[0] == key:
        return memo

    def verdict(result, reason=None):
        if result is None:
            bump("zero", "xla", reason)
        else:
            bump("zero", "zero")
        return (key, result)

    if comm_plan is None:
        return verdict(None, "zero_stage set but the quantized comm "
                             "plan is not engaged — ZeRO rides its "
                             "bucketed ring (set comm_quant; the "
                             "quant_allreduce.xla counter has that "
                             "refusal)")
    axis, g, cplan = comm_plan
    ops = block.ops
    bwd_idx = next((i for i, op in enumerate(ops)
                    if op.type == "backward"), None)
    if bwd_idx is None:
        return verdict(None, "no backward op")
    scan_end = bwd_idx + 1
    if scan_end < len(ops) and \
            ops[scan_end].type == "check_finite_and_unscale":
        scan_end += 1
    bwd = ops[bwd_idx]
    g2p = dict(zip(bwd.outputs.get("Grads", ()),
                   bwd.inputs.get("Params", ())))
    opt_at = {}
    for i in range(scan_end, len(ops)):
        op = ops[i]
        pn = op.inputs.get("Param")
        if pn and op.inputs.get("Grad"):
            opt_at[pn[0]] = (i, op)

    def _f32(name):
        v = block.vars.get(name)
        return v is not None and jnp.dtype(
            dtype_mod.convert_dtype(v.dtype)) == jnp.float32

    buckets = []
    absorbed: List[str] = []
    replaced: set = set()
    for bi, b in enumerate(cplan):
        params, idxs = [], []
        sig = None
        for gn in b["grads"]:
            pn = g2p.get(gn)
            if pn is None or pn not in opt_at:
                return verdict(None, f"param for grad {gn!r} has no "
                                     "optimizer op in the update "
                                     "region")
            i, op = opt_at[pn]
            if op.type not in ZERO_OPT_OPS:
                return verdict(None, f"optimizer {op.type!r} is not "
                                     "chunk-shardable; allowlist: "
                                     f"{ZERO_OPT_OPS}")
            if not _f32(pn) or not _f32(gn):
                return verdict(None, f"param/grad for {pn!r} is not "
                                     "f32 — the chunked f32 update "
                                     "would drift from the reference "
                                     "kernel's native-dtype math")
            lr = op.inputs.get("LearningRate")
            if not lr:
                return verdict(None, f"{op.type} op for {pn!r} has "
                                     "no LearningRate input")
            attrs = {a: v for a, v in sorted(op.attrs.items())
                     if not a.startswith("__")}
            s = (op.type, repr(attrs), lr[0],
                 op.inputs.get("FoundInfinite", [None])[0])
            if sig is None:
                sig = s
            elif s != sig:
                return verdict(None, f"mixed optimizer configs inside "
                                     f"comm bucket {bi} — the fused "
                                     "chunk update needs one uniform "
                                     "type/attrs/lr per bucket")
            params.append(pn)
            idxs.append(i)
        op0 = ops[idxs[0]]
        roles = {r: [ops[i].inputs[r][0] for i in idxs]
                 for r in _ZERO_ROLES[op0.type]}
        scalars = {r: [ops[i].inputs[r][0] for i in idxs]
                   for r in _ZERO_SCALARS[op0.type]}
        padded = padded_len(b["elems"], g)
        shapes = [tuple(int(d) for d in (block.vars[pn].shape or ()))
                  for pn in params]
        buckets.append({
            "grads": list(b["grads"]), "params": params,
            "elems": int(b["elems"]), "padded": int(padded),
            "chunk": int(padded) // g, "op_type": op0.type,
            "attrs": dict(op0.attrs), "lr": sig[2], "found": sig[3],
            "roles": roles, "scalars": scalars,
            "op_idxs": sorted(idxs), "param_shapes": shapes,
        })
        replaced.update(idxs)
        for names in roles.values():
            absorbed.extend(names)
        if zero >= 3:
            absorbed.extend(params)
    grads_all = set(g2p)
    moments_all = {n for b_ in buckets
                   for ns in b_["roles"].values() for n in ns}
    params_s3 = set(g2p.values()) if zero >= 3 else set()
    for i in range(scan_end, len(ops)):
        if i in replaced:
            continue
        reads = {n for ns in ops[i].inputs.values() for n in ns}
        for bad, what in ((reads & grads_all, "the merged gradient"),
                          (reads & moments_all,
                           "sharded optimizer state"),
                          (reads & params_s3, "stage-3 params")):
            if bad:
                return verdict(
                    None, f"post-region op {ops[i].type!r} reads "
                          f"{what} ({sorted(bad)[:2]}) which is never "
                          f"materialized under zero_stage={zero}")
    bad = set(fetch_names) & set(absorbed)
    if bad:
        return verdict(None, f"fetch of sharded state "
                             f"{sorted(bad)[:2]} under "
                             f"zero_stage={zero}")
    rep = sh = 0
    for b_ in buckets:
        nrows = len(b_["roles"]) + (1 if zero >= 3 else 0)
        rep += b_["elems"] * 4 * nrows
        sh += b_["chunk"] * 4 * nrows
    plan = {"stage": int(zero), "axis": axis, "group": int(g),
            "buckets": buckets, "scan_end": scan_end,
            "absorbed": tuple(sorted(set(absorbed))),
            "bytes_replicated": int(rep), "bytes_sharded": int(sh)}
    return verdict(plan)


def zero_state_layout(zero_plan):
    """``[(row_name, role, bucket_idx, (g, chunk))]`` — the donated
    state rows the plan owns. Row storage is RING-PLACED: row r holds
    flat chunk ``(r+1) % g`` of the bucket's padded concat buffer, so
    device r's local row lines up exactly with the reduced chunk
    :func:`parallel.collectives.reduce_scatter` hands it (no extra
    permute hop per step; flip-back un-rolls once)."""
    g = zero_plan["group"]
    out = []
    for i, b in enumerate(zero_plan["buckets"]):
        for role in _zero_row_sources(zero_plan["stage"], b):
            out.append((f"__zero_{role.lower()}_{i}", role, i,
                        (g, b["chunk"])))
    return out


def ensure_zero_state(scope, zero_plan, shard_cfg, sharding):
    """Materialize the sharded state rows as DONATED executor state:
    one ``(g, chunk)`` f32 row buffer per (bucket, role), sharded
    ``P(axis, None)`` so each device owns its row. Existing per-var
    state (warm start: momentum already accumulated, adam moments
    mid-run) is ABSORBED — concat, pad, ring-roll — and the per-var
    scope entries are cleared so they drop out of persist_names; the
    ``__zero_layout__`` scope marker (not a block var, never persisted)
    records enough to :func:`zero_flip_back` when ZeRO turns off.
    Returns ``(added_names, dropped_names)``."""
    from jax.sharding import NamedSharding, PartitionSpec

    from ..parallel.mesh import mesh_for_shape

    g = zero_plan["group"]
    mesh = mesh_for_shape(dict(shard_cfg[0]))
    shard = NamedSharding(mesh, PartitionSpec(zero_plan["axis"], None))
    peek = getattr(scope, "_peek", scope.find_var)
    write_back = getattr(scope, "_write_back", scope.set)
    added = []
    for i, b in enumerate(zero_plan["buckets"]):
        for role, names in _zero_row_sources(zero_plan["stage"],
                                             b).items():
            rn = f"__zero_{role.lower()}_{i}"
            arr = peek(rn)
            if not isinstance(arr, jax.Array) or \
                    tuple(arr.shape) != (g, b["chunk"]):
                flats = []
                for n, shp in zip(names, b["param_shapes"]):
                    v = peek(n)
                    flats.append(
                        np.zeros(int(np.prod(shp or (1,))), np.float32)
                        if v is None
                        else np.asarray(v, np.float32).reshape(-1))
                flat = np.concatenate(flats) if len(flats) > 1 \
                    else flats[0]
                flat = np.pad(flat, (0, b["padded"] - flat.size))
                rows = np.roll(flat.reshape(g, b["chunk"]), -1, axis=0)
                arr = jax.device_put(rows, shard)
                write_back(rn, arr)
            sharding[rn] = shard
            added.append(rn)
    for n in zero_plan["absorbed"]:
        if peek(n) is not None:
            write_back(n, None)
    write_back("__zero_layout__", {
        "stage": zero_plan["stage"], "group": g,
        "buckets": [{"roles": dict(b["roles"]), "params": b["params"],
                     "param_shapes": b["param_shapes"],
                     "elems": b["elems"], "chunk": b["chunk"]}
                    for b in zero_plan["buckets"]]})
    return added, set(zero_plan["absorbed"])


def zero_flip_back(scope):
    """Reconstruct the per-var optimizer state (and stage-3 params)
    from the sharded row buffers when ZeRO turns OFF between steps:
    un-roll the ring placement, strip the padding, split per var.
    Clears the rows and the layout marker; returns the restored names
    (the executor splices them back into persist_names)."""
    peek = getattr(scope, "_peek", scope.find_var)
    write_back = getattr(scope, "_write_back", scope.set)
    layout = peek("__zero_layout__")
    if not isinstance(layout, dict):
        return []
    restored = []
    for i, b in enumerate(layout["buckets"]):
        for role, names in _zero_row_sources(layout["stage"],
                                             b).items():
            rn = f"__zero_{role.lower()}_{i}"
            rows = peek(rn)
            if rows is None:
                continue
            flat = np.roll(np.asarray(rows, np.float32), 1,
                           axis=0).reshape(-1)[:b["elems"]]
            off = 0
            for n, shp in zip(names, b["param_shapes"]):
                e = int(np.prod(shp or (1,)))
                write_back(n, jnp.asarray(
                    flat[off:off + e].reshape(shp)))
                restored.append(n)
                off += e
            write_back(rn, None)
    write_back("__zero_layout__", None)
    return restored


@plan_kind("zero")
def _zero_step_fn(plan, block, feed_keys, fetch_names, persist_names,
                  feed_vals, notify):
    """The comm step with ZeRO-2/3 sharded optimizer states: the
    bucketed quantized all-reduce DECOMPOSES into its two ring halves
    and the optimizer region runs on per-device shards between them.

    Per bucket (backward-completion order, overlap preserved):

    - grads concat/pad (+error feedback) → quantized ring
      reduce-scatter: each device keeps ONLY its owned reduced f32
      chunk — the full merged gradient is never materialized (the
      ZeRO-2 gradient shard), and the optimizer consumes the chunk
      UN-quantized (one fewer encode than the all-reduce path; with
      codec='f32' the step is bitwise the replicated comm step for
      the elementwise rules — lamb is tolerance-parity: its segment
      norms psum across devices, which reassociates the sum)
    - ONE fused elementwise kernel call per bucket updates the param
      chunk (stage 2: sliced from the replicated param concat at the
      ring-owned position; stage 3: this device's param row) against
      the moment rows — eligibility guaranteed uniform op
      type/attrs/lr per bucket, so the synthesized call IS the op
    - stage 2: the updated param chunks all-gather RAW F32 (the codec
      applies to gradients only — sharded-update results must come
      back exact) and unpack into the replicated params; stage 3
      skips that gather entirely and the NEXT step's pre-forward
      gather serves the params
    - scalar accumulators (adam beta-pows) stay replicated per var,
      updated through the kernel's own gated Beta*PowOut rule
    - surviving post-region ops (lr schedules, counters) run in
      original op order around the replaced optimizer ops, each
      bucket's fused update firing at its first replaced index
    """
    from jax.sharding import PartitionSpec as P

    from ..ops.pallas.fused_optimizer import fused_chunk_update
    from ..parallel.collectives import (
        all_gather, quant_decode, quant_encode, reduce_scatter,
        shard_map_nocheck)
    from ..parallel.mesh import mesh_for_shape
    from .executor import run_block

    sharding = plan.sharding
    gm = plan.gm
    bwd_idx = plan.bwd_idx
    axis, g, cplan = plan.comm_plan
    codec, _bucket_bytes, ef = plan.comm
    zplan = plan.zero_plan
    stage = zplan["stage"]
    zbuckets = zplan["buckets"]
    k, avg_gm = gm if gm is not None else (1, True)
    (scan_end, grad_names, found_name, state_carry, carry_out,
     post_outs) = merge_region(block, feed_keys, feed_vals,
                               persist_names, fetch_names, 1, bwd_idx)
    mesh = mesh_for_shape({axis: g})
    ef_names = [f"__comm_ef_{i}" for i in range(len(cplan))] \
        if ef else []
    ef_set = set(ef_names)
    row_names = [rn for rn, _r, _i, _s in zero_state_layout(zplan)]
    row_set = ef_set | set(row_names)
    reg_names = [n for n in persist_names if n not in row_set]

    # locate the optimizer ops in THIS block: the plan's op_idxs refer
    # to the pre-pass program, and the IR pipeline may have shifted
    # indices — param names are the stable join key
    opt_idx = {}
    for i in range(scan_end, len(block.ops)):
        op = block.ops[i]
        pn = op.inputs.get("Param")
        if pn and op.inputs.get("Grad"):
            opt_idx[pn[0]] = i
    replaced: set = set()
    first_op = {}
    for bi, b in enumerate(zbuckets):
        idxs = [opt_idx[pn] for pn in b["params"]]
        replaced.update(idxs)
        first_op[min(idxs)] = bi

    grad_elems = {}
    grad_shapes = {}
    for gn in grad_names:
        desc = block.vars.get(gn)
        shape = tuple(int(d) for d in (desc.shape or ()))
        grad_shapes[gn] = shape
        e = 1
        for d in shape:
            e *= d
        grad_elems[gn] = e
    pdtypes = {pn: jnp.dtype(dtype_mod.convert_dtype(
        block.vars[pn].dtype))
        for b in zbuckets for pn in b["params"]}

    notify("zero_stage_active", stage)
    notify("zero_buckets", len(zbuckets))
    notify("zero_state_bytes_replicated", zplan["bytes_replicated"])
    notify("zero_state_bytes_sharded", zplan["bytes_sharded"])
    rep = zplan["bytes_replicated"]
    notify("zero_state_bytes_saved_pct",
           round(100.0 * (1.0 - zplan["bytes_sharded"] / rep), 2)
           if rep else 0.0)

    def spec_of(n):
        sh = sharding.get(n) if sharding else None
        spec = getattr(sh, "spec", None)
        return P(*spec) if spec is not None else P()

    fetch_modes = []
    for n in fetch_names:
        v = block.vars.get(n)
        shape = getattr(v, "shape", None)
        dt = str(getattr(v, "dtype", "float32"))
        if shape and (shape[0] is None or int(shape[0]) < 0):
            fetch_modes.append("gather")
        elif dt.startswith("float") or dt == "bfloat16":
            fetch_modes.append("pmean")
        else:
            fetch_modes.append("local")

    in_specs = ([spec_of(kk) for kk in feed_keys],
                [P(axis, None) if n in row_set else P()
                 for n in persist_names],
                P())
    out_specs = ([P(axis) if m == "gather" else P()
                  for m in fetch_modes],
                 [P(axis, None) if n in row_set else P()
                  for n in persist_names])

    def local_step(feed_local, state, rng):
        state_env = dict(zip(persist_names, state))
        ef_rows = [state_env[n][0] for n in ef_names]
        rows = {n: state_env[n][0] for n in row_names}
        state_env0 = {n: state_env[n] for n in reg_names}
        if stage >= 3:
            # params live only as sharded rows: all-gather raw f32
            # before the forward (the post-update gather is skipped —
            # next step's pre-forward gather serves it)
            for bi, b in enumerate(zbuckets):
                full = all_gather(rows[f"__zero_param_{bi}"], axis,
                                  codec="f32", axis_size=g)
                off = 0
                for pn, shp in zip(b["params"], b["param_shapes"]):
                    e = 1
                    for d in shp:
                        e *= d
                    state_env0[pn] = full[off:off + e].reshape(
                        shp).astype(pdtypes[pn])
                    off += e
        found = jnp.zeros((), jnp.bool_)
        if k > 1:
            mbs = [v.reshape((k, v.shape[0] // k)
                             + tuple(v.shape[1:]))
                   for v in feed_local]

            def body(carry, xs):
                accum, found = carry
                mb, mi = xs
                env = dict(zip(feed_keys, mb))
                env.update(state_env0)
                ctx = ExecContext(
                    rng_key=jax.random.fold_in(rng, mi))
                env = run_block(block, env, ctx, stop_at=scan_end)
                accum = [a + env[gn].astype(jnp.float32)
                         for a, gn in zip(accum, grad_names)]
                if found_name is not None:
                    found = found | jnp.reshape(
                        env[found_name], ()).astype(bool)
                ys = {n: env[n] for n in carry_out}
                return (accum, found), ys

            init = ([jnp.zeros((grad_elems[gn],), jnp.float32
                               ).reshape(grad_shapes[gn])
                     for gn in grad_names],
                    jnp.zeros((), jnp.bool_))
            (accum, found), ys = jax.lax.scan(
                body, init, (mbs, jnp.arange(k)))
            env = dict(zip(feed_keys, feed_local))
            env.update(state_env0)
            env.update({n: ys[n][-1] for n in carry_out})
            for gn, a in zip(grad_names, accum):
                env[gn] = (a / k if avg_gm else a)
            scanned_ys = ys
        else:
            env = dict(zip(feed_keys, feed_local))
            env.update(state_env0)
            ctx = ExecContext(rng_key=rng)
            env = run_block(block, env, ctx, stop_at=scan_end)
            if found_name is not None:
                found = jnp.reshape(env[found_name], ()).astype(bool)
            scanned_ys = None
        # bucketed quantized ring reduce-scatter, overlap-emitted:
        # each device keeps only its owned reduced f32 chunk
        mine_chunks, new_ef = [], []
        for i, b in enumerate(zbuckets):
            flats = [env[gn].astype(jnp.float32).reshape(-1)
                     for gn in b["grads"]]
            flat = flats[0] if len(flats) == 1 else \
                jnp.concatenate(flats)
            if b["padded"] != flat.shape[0]:
                flat = jnp.concatenate(
                    [flat, jnp.zeros((b["padded"] - flat.shape[0],),
                                     jnp.float32)])
            if ef:
                flat = flat + ef_rows[i]
                q, sc = quant_encode(flat, codec)
                dec = quant_decode(q, sc, codec)
                new_ef.append(flat - dec)
                flat = dec
            mine_chunks.append(reduce_scatter(
                flat, axis, codec=codec, axis_size=g, avg=True))
        if found_name is not None:
            found = jax.lax.pmax(found.astype(jnp.int32), axis) > 0
            env[found_name] = jnp.reshape(found, (1,))
        ctx = ExecContext(rng_key=rng)
        idx = jax.lax.axis_index(axis)
        new_rows = {}

        def apply_bucket(bi):
            b = zbuckets[bi]
            c = b["chunk"]
            if stage >= 3:
                p_chunk = rows[f"__zero_param_{bi}"]
            else:
                flats = [env[pn].astype(jnp.float32).reshape(-1)
                         for pn in b["params"]]
                flat = flats[0] if len(flats) == 1 else \
                    jnp.concatenate(flats)
                if b["padded"] != flat.shape[0]:
                    flat = jnp.concatenate(
                        [flat,
                         jnp.zeros((b["padded"] - flat.shape[0],),
                                   jnp.float32)])
                p_chunk = jax.lax.dynamic_slice(
                    flat, (jnp.mod(idx + 1, g) * c,), (c,))
            ins = {"Param": [p_chunk], "Grad": [mine_chunks[bi]],
                   "LearningRate": [env[b["lr"]]]}
            for role in b["roles"]:
                ins[role] = [rows[f"__zero_{role.lower()}_{bi}"]]
            for srole, names in b["scalars"].items():
                ins[srole] = [env[names[0]]]
            if b["found"] is not None:
                ins["FoundInfinite"] = [env[b["found"]]]
            # ONE fused kernel call per bucket (ISSUE 19): the Pallas
            # grid pass reads the chunk's grad/param/moments once; the
            # ineligible path is the verbatim static-op math. lamb
            # threads the per-param element layout + this device's
            # ring position so its two-phase trust plan can psum the
            # segment norms over the dp axis.
            outs = fused_chunk_update(
                b["op_type"], ins, b["attrs"], axis=axis,
                param_elems=tuple(
                    int(np.prod(shp or (1,)))
                    for shp in b["param_shapes"]),
                position=jnp.mod(idx + 1, g) * c)
            for role in b["roles"]:
                new_rows[f"__zero_{role.lower()}_{bi}"] = \
                    outs[role + "Out"][0]
            for srole, names in b["scalars"].items():
                val = outs[srole + "Out"][0]
                for n in names:
                    env[n] = val
            new_p = outs["ParamOut"][0]
            if stage >= 3:
                new_rows[f"__zero_param_{bi}"] = new_p
            else:
                # raw f32 gather: codec applies to gradients only —
                # sharded-update results must come back exact
                full = all_gather(new_p, axis, codec="f32",
                                  axis_size=g)
                off = 0
                for pn in b["params"]:
                    old = env[pn]
                    e = old.size
                    env[pn] = full[off:off + e].reshape(
                        old.shape).astype(old.dtype)
                    off += e

        i = scan_end
        n_ops = len(block.ops)
        while i < n_ops:
            bi = first_op.get(i)
            if bi is not None:
                apply_bucket(bi)
            if i not in replaced:
                env = run_block(block, env, ctx, start=i,
                                stop_at=i + 1)
            i += 1
        fetches = []
        for n, mode in zip(fetch_names, fetch_modes):
            if scanned_ys is not None and n in scanned_ys \
                    and n not in post_outs:
                stacked = scanned_ys[n]
                if jnp.issubdtype(stacked.dtype, jnp.inexact):
                    val = jnp.mean(stacked.astype(jnp.float32),
                                   axis=0).astype(stacked.dtype)
                else:
                    val = stacked[-1]
            else:
                val = env[n]
            if mode == "pmean" and jnp.issubdtype(
                    jnp.asarray(val).dtype, jnp.inexact):
                val = jax.lax.pmean(
                    val.astype(jnp.float32), axis).astype(val.dtype)
            fetches.append(val)
        new_state = []
        ef_iter = iter(new_ef)
        for n, s in zip(persist_names, state):
            if n in new_rows:
                new_state.append(new_rows[n][None, :])
            elif n in ef_set:
                new_state.append(next(ef_iter)[None, :]
                                 if ef else s)
            else:
                new_state.append(env.get(n, s))
        return fetches, new_state

    sharded = shard_map_nocheck(local_step, mesh, in_specs,
                                out_specs)

    def step(feed_vals, state, rng):
        return sharded(feed_vals, state, rng)

    return step
