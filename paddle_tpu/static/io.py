"""Static-graph checkpoint & inference-model IO.

Reference: /root/reference/python/paddle/fluid/io.py — save_persistables
:598 (runs save ops via an executor), save_inference_model :1164 (prunes
program to feed/fetch targets + writes params), load_* counterparts.

Here persistables live in a host-side Scope of jax arrays, so saving is a
straight pickle of name->numpy (the reference's single-file `save :1669`
.pdparams format shape), and the program is serialized as versioned JSON
(ir.py). No executor round-trip needed.

Every write goes through io.serialization's atomic-replace protocol
(temp file + fsync + one os.replace), so a kill mid-save can never leave
a truncated .pdparams/.pdopt/__model__ behind; loads surface truncated
or missing files as ValueErrors naming the path.
"""
from __future__ import annotations

import os
from typing import List, Optional, Sequence

import numpy as np

from .executor import Executor, Scope, global_scope
from .ir import Program, Variable
from ..io.serialization import _atomic_write_bytes, _load_pickle, \
    atomic_pickle_dump

_PARAMS_SUFFIX = ".pdparams"
_MODEL_FILENAME = "__model__"
_BLOB_MANIFEST = "MANIFEST.json"


def _collect_persistables(program: Program, scope: Scope):
    out = {}
    for name, desc in program.global_block.vars.items():
        if desc.persistable:
            v = scope.find_var(name)
            if v is not None:
                out[name] = np.asarray(v)
    return out


def save_persistables(executor: Executor, dirname: str,
                      main_program: Optional[Program] = None,
                      filename: Optional[str] = None):
    from .ir import default_main_program
    program = main_program or default_main_program()
    os.makedirs(dirname, exist_ok=True)
    state = _collect_persistables(program, global_scope())
    path = os.path.join(dirname, filename or "params" + _PARAMS_SUFFIX)
    atomic_pickle_dump(state, path)
    return path


def load_persistables(executor: Executor, dirname: str,
                      main_program: Optional[Program] = None,
                      filename: Optional[str] = None):
    import jax.numpy as jnp
    path = os.path.join(dirname, filename or "params" + _PARAMS_SUFFIX)
    state = _load_pickle(path)
    scope = global_scope()
    for k, v in state.items():
        scope.set(k, jnp.asarray(v))


save_params = save_persistables
load_params = load_persistables


def save(program: Program, model_path: str):
    """paddle.static.save parity (reference fluid/io.py:1669): split the
    program's persistables into parameters -> {model_path}.pdparams and
    the remaining persistable (optimizer) state -> {model_path}.pdopt,
    plus the serialized program -> {model_path}.pdmodel."""
    d = os.path.dirname(model_path)
    if d:
        os.makedirs(d, exist_ok=True)
    scope = global_scope()
    state = _collect_persistables(program, scope)
    param_names = {p.name for p in program.all_parameters()}
    params = {k: v for k, v in state.items() if k in param_names}
    opt = {k: v for k, v in state.items() if k not in param_names}
    atomic_pickle_dump(params, model_path + ".pdparams")
    if opt:
        atomic_pickle_dump(opt, model_path + ".pdopt")
    save_program(program, model_path + ".pdmodel")


def load(program: Program, model_path: str, executor=None, var_list=None):
    """paddle.static.load parity (reference fluid/io.py:1730): restore
    .pdparams (+ .pdopt when present) into the global scope. ``var_list``
    restricts the restore to those variables' names."""
    import jax.numpy as jnp

    state = {}
    state.update(_load_pickle(model_path + ".pdparams"))
    if os.path.exists(model_path + ".pdopt"):
        state.update(_load_pickle(model_path + ".pdopt"))
    wanted = None
    if var_list is not None:
        wanted = {v.name if hasattr(v, "name") else v for v in var_list}
    scope = global_scope()
    for k, v in state.items():
        if wanted is None or k in wanted:
            scope.set(k, jnp.asarray(v))


def save_inference_model(dirname: str, feeded_var_names: Sequence[str],
                         target_vars: Sequence[Variable], executor: Executor,
                         main_program: Optional[Program] = None,
                         model_filename: Optional[str] = None,
                         params_filename: Optional[str] = None):
    """Prune to the inference subgraph and write model + params."""
    from .ir import default_main_program
    program = main_program or default_main_program()
    os.makedirs(dirname, exist_ok=True)
    fetch_names = [v.name if isinstance(v, Variable) else str(v)
                   for v in target_vars]
    pruned = program.clone(for_test=True).prune(feeded_var_names,
                                                fetch_names)
    # shrink the blob through the IR pass pipeline (fusion off: saved
    # artifacts keep canonical op types for tooling/inspection — the
    # executor re-fuses at load time anyway)
    from .compiler import BuildStrategy
    from .passes import apply_passes

    strategy = BuildStrategy()
    strategy.fuse_elewise_add_act_ops = False
    pruned, _ = apply_passes(pruned, feeded_var_names, fetch_names,
                             strategy)
    meta = {"feed_names": list(feeded_var_names),
            "fetch_names": fetch_names}
    blob = {"program": pruned.to_dict(), "meta": meta}
    model_name = model_filename or _MODEL_FILENAME
    params_name = params_filename or "params" + _PARAMS_SUFFIX
    atomic_pickle_dump(blob, os.path.join(dirname, model_name))
    state = _collect_persistables(pruned, global_scope())
    atomic_pickle_dump(state, os.path.join(dirname, params_name))
    # integrity manifest (io.snapshot schema): loaders — including the
    # serving AnalysisPredictor — sha256-verify the blob before
    # deserializing, so a torn copy fails loudly naming the file
    from ..io.snapshot import write_file_manifest

    write_file_manifest(
        os.path.join(dirname, _BLOB_MANIFEST),
        {name: os.path.join(dirname, name)
         for name in (model_name, params_name)})
    return fetch_names


def load_inference_model(dirname: str, executor: Executor,
                         model_filename: Optional[str] = None,
                         params_filename: Optional[str] = None):
    import jax.numpy as jnp
    from ..io.snapshot import verify_file_manifest

    verify_file_manifest(os.path.join(dirname, _BLOB_MANIFEST), dirname)
    blob = _load_pickle(
        os.path.join(dirname, model_filename or _MODEL_FILENAME))
    program = Program.from_dict(blob["program"])
    meta = blob["meta"]
    state = _load_pickle(
        os.path.join(dirname, params_filename or "params" + _PARAMS_SUFFIX))
    scope = global_scope()
    for k, v in state.items():
        scope.set(k, jnp.asarray(v))
    fetch_vars = [program.global_block.var(n) for n in meta["fetch_names"]]
    return program, meta["feed_names"], fetch_vars


def save_program(program: Program, path: str):
    """Serialize one program to a file (the reference C++ train demo's
    main_program/startup_program files — train/demo/demo_trainer.cc:41
    Load reads exactly such a pair)."""
    _atomic_write_bytes(path, program.serialize_to_string())


def load_program(path: str) -> Program:
    with open(path, "rb") as f:
        return Program.parse_from_string(f.read())


def save_train_program(dirname: str, main: Program, startup: Program):
    """Save the (main, startup) pair the C trainer API consumes
    (native/src/capi.cc PD_NewTrainer)."""
    os.makedirs(dirname, exist_ok=True)
    save_program(main, os.path.join(dirname, "main_program"))
    save_program(startup, os.path.join(dirname, "startup_program"))
