"""Static-graph checkpoint & inference-model IO.

Reference: /root/reference/python/paddle/fluid/io.py — save_persistables
:598 (runs save ops via an executor), save_inference_model :1164 (prunes
program to feed/fetch targets + writes params), load_* counterparts.

Here persistables live in a host-side Scope of jax arrays, so saving is a
straight pickle of name->numpy (the reference's single-file `save :1669`
.pdparams format shape), and the program is serialized as versioned JSON
(ir.py). No executor round-trip needed.
"""
from __future__ import annotations

import os
import pickle
from typing import List, Optional, Sequence

import numpy as np

from .executor import Executor, Scope, global_scope
from .ir import Program, Variable

_PARAMS_SUFFIX = ".pdparams"
_MODEL_FILENAME = "__model__"


def _collect_persistables(program: Program, scope: Scope):
    out = {}
    for name, desc in program.global_block.vars.items():
        if desc.persistable:
            v = scope.find_var(name)
            if v is not None:
                out[name] = np.asarray(v)
    return out


def save_persistables(executor: Executor, dirname: str,
                      main_program: Optional[Program] = None,
                      filename: Optional[str] = None):
    from .ir import default_main_program
    program = main_program or default_main_program()
    os.makedirs(dirname, exist_ok=True)
    state = _collect_persistables(program, global_scope())
    path = os.path.join(dirname, filename or "params" + _PARAMS_SUFFIX)
    with open(path, "wb") as f:
        pickle.dump(state, f, protocol=4)
    return path


def load_persistables(executor: Executor, dirname: str,
                      main_program: Optional[Program] = None,
                      filename: Optional[str] = None):
    import jax.numpy as jnp
    path = os.path.join(dirname, filename or "params" + _PARAMS_SUFFIX)
    with open(path, "rb") as f:
        state = pickle.load(f)
    scope = global_scope()
    for k, v in state.items():
        scope.set(k, jnp.asarray(v))


save_params = save_persistables
load_params = load_persistables


def save(program: Program, model_path: str):
    """paddle.static.save parity (reference fluid/io.py:1669): split the
    program's persistables into parameters -> {model_path}.pdparams and
    the remaining persistable (optimizer) state -> {model_path}.pdopt,
    plus the serialized program -> {model_path}.pdmodel."""
    d = os.path.dirname(model_path)
    if d:
        os.makedirs(d, exist_ok=True)
    scope = global_scope()
    state = _collect_persistables(program, scope)
    param_names = {p.name for p in program.all_parameters()}
    params = {k: v for k, v in state.items() if k in param_names}
    opt = {k: v for k, v in state.items() if k not in param_names}
    with open(model_path + ".pdparams", "wb") as f:
        pickle.dump(params, f, protocol=4)
    if opt:
        with open(model_path + ".pdopt", "wb") as f:
            pickle.dump(opt, f, protocol=4)
    save_program(program, model_path + ".pdmodel")


def load(program: Program, model_path: str, executor=None, var_list=None):
    """paddle.static.load parity (reference fluid/io.py:1730): restore
    .pdparams (+ .pdopt when present) into the global scope. ``var_list``
    restricts the restore to those variables' names."""
    import jax.numpy as jnp

    state = {}
    with open(model_path + ".pdparams", "rb") as f:
        state.update(pickle.load(f))
    if os.path.exists(model_path + ".pdopt"):
        with open(model_path + ".pdopt", "rb") as f:
            state.update(pickle.load(f))
    wanted = None
    if var_list is not None:
        wanted = {v.name if hasattr(v, "name") else v for v in var_list}
    scope = global_scope()
    for k, v in state.items():
        if wanted is None or k in wanted:
            scope.set(k, jnp.asarray(v))


def save_inference_model(dirname: str, feeded_var_names: Sequence[str],
                         target_vars: Sequence[Variable], executor: Executor,
                         main_program: Optional[Program] = None,
                         model_filename: Optional[str] = None,
                         params_filename: Optional[str] = None):
    """Prune to the inference subgraph and write model + params."""
    from .ir import default_main_program
    program = main_program or default_main_program()
    os.makedirs(dirname, exist_ok=True)
    fetch_names = [v.name if isinstance(v, Variable) else str(v)
                   for v in target_vars]
    pruned = program.clone(for_test=True).prune(feeded_var_names,
                                                fetch_names)
    meta = {"feed_names": list(feeded_var_names),
            "fetch_names": fetch_names}
    blob = {"program": pruned.to_dict(), "meta": meta}
    with open(os.path.join(dirname, model_filename or _MODEL_FILENAME),
              "wb") as f:
        pickle.dump(blob, f, protocol=4)
    state = _collect_persistables(pruned, global_scope())
    with open(os.path.join(dirname,
                           params_filename or "params" + _PARAMS_SUFFIX),
              "wb") as f:
        pickle.dump(state, f, protocol=4)
    return fetch_names


def load_inference_model(dirname: str, executor: Executor,
                         model_filename: Optional[str] = None,
                         params_filename: Optional[str] = None):
    import jax.numpy as jnp
    with open(os.path.join(dirname, model_filename or _MODEL_FILENAME),
              "rb") as f:
        blob = pickle.load(f)
    program = Program.from_dict(blob["program"])
    meta = blob["meta"]
    with open(os.path.join(dirname,
                           params_filename or "params" + _PARAMS_SUFFIX),
              "rb") as f:
        state = pickle.load(f)
    scope = global_scope()
    for k, v in state.items():
        scope.set(k, jnp.asarray(v))
    fetch_vars = [program.global_block.var(n) for n in meta["fetch_names"]]
    return program, meta["feed_names"], fetch_vars


def save_program(program: Program, path: str):
    """Serialize one program to a file (the reference C++ train demo's
    main_program/startup_program files — train/demo/demo_trainer.cc:41
    Load reads exactly such a pair)."""
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "wb") as f:
        f.write(program.serialize_to_string())


def load_program(path: str) -> Program:
    with open(path, "rb") as f:
        return Program.parse_from_string(f.read())


def save_train_program(dirname: str, main: Program, startup: Program):
    """Save the (main, startup) pair the C trainer API consumes
    (native/src/capi.cc PD_NewTrainer)."""
    os.makedirs(dirname, exist_ok=True)
    save_program(main, os.path.join(dirname, "main_program"))
    save_program(startup, os.path.join(dirname, "startup_program"))
