"""Fluid 1.x block-builder control flow: While / Switch / IfElse
(reference fluid/layers/control_flow.py: While :1086, Switch :2771,
IfElse :2547).

These are the mutation-style forms (the block writes back into existing
variables via assign(output=)/increment(in_place)); the functional forms
in static/layers.py (while_loop/cond/case/switch_case) are the preferred
TPU-native API and this module lowers onto their kernels:

- ``While``: the captured sub-block's writes to pre-existing variables
  become the loop carry of the same ``while`` op while_loop uses — the
  fluid contract (block must refresh the cond variable, e.g.
  ``layers.less_than(i, n, cond=cond)``) maps 1:1 onto its
  (loop_in, body_out, cond_out) attrs.
- ``Switch``: each case body is captured in a sub-block; cases chain
  into nested ``cond`` ops, else-branches re-emitting the previous
  value (the reference executes at most one case body — here exactly
  one branch of each lax.cond runs, same observable result).
- ``IfElse``: the reference splits the batch rows by the mask, runs
  each block on its slice, and merges; the TPU translation evaluates
  both blocks DENSE on the full batch and row-merges with where —
  identical results for row-wise computation (the reference's own
  documented use), divergent for cross-row reductions inside a branch
  (rejected: ``input()`` marks values; reductions over them inside a
  branch see all rows — documented contract).
"""
from __future__ import annotations

import contextlib

from ..utils import unique_name
from .ir import _BlockGuard
from .layers import LayerHelper, assign, default_main_program

__all__ = ["While", "Switch", "IfElse"]


def _parent_visible_names(block):
    """All names resolvable from `block` BEFORE entering a child."""
    names = set()
    blk = block
    prog = block.program
    while blk is not None:
        names.update(blk.vars.keys())
        blk = (prog.blocks[blk.parent_idx]
               if blk.parent_idx >= 0 else None)
    return names


def _written_parent_names(sub_block, pre_names):
    """Names a sub-block writes that already existed outside it, in
    first-write order (the loop-carry / merge set)."""
    seen, out = set(), []
    for op in sub_block.ops:
        for ns in op.outputs.values():
            for n in ns:
                if n in pre_names and n not in seen:
                    seen.add(n)
                    out.append(n)
    return out


class While:
    """``while cond:`` block builder (reference control_flow.py:1086).

    Usage (fluid 1.x pattern)::

        i = layers.fill_constant([1], "int64", 0)
        n = layers.fill_constant([1], "int64", 10)
        cond = layers.less_than(i, n)
        w = layers.While(cond)
        with w.block():
            layers.increment(i, value=1, in_place=True)
            layers.less_than(i, n, cond=cond)   # refresh the condition

    Every write into a pre-existing variable is loop-carried; the block
    MUST refresh the cond variable or the loop would never terminate
    (raised at build time).
    """

    def __init__(self, cond, is_test=False, name=None):
        self.cond_var = cond
        self.helper = LayerHelper(name or "while")

    @contextlib.contextmanager
    def block(self):
        prog = self.helper.main_program
        parent = prog.current_block()
        pre_names = _parent_visible_names(parent)
        sb = prog.create_block()
        with _BlockGuard(prog, sb):
            yield
        carried = _written_parent_names(sb, pre_names)
        if self.cond_var.name not in carried:
            raise ValueError(
                "While block never updates its condition variable "
                f"{self.cond_var.name!r} — the loop would not terminate. "
                "Refresh it inside the block, e.g. "
                "layers.less_than(i, n, cond=cond).")
        parent.append_op(
            type="while",
            inputs={"X": list(carried), "Cond": [self.cond_var.name]},
            outputs={"Out": list(carried)},
            attrs={"sub_block": sb.idx, "loop_in": list(carried),
                   "body_out": list(carried),
                   "cond_out": self.cond_var.name})


class Switch:
    """At-most-one-case dispatch (reference control_flow.py:2771), the
    fluid learning-rate-schedule staple::

        lr = layers.create_global_var([1], 0.0, "float32")
        with layers.Switch() as switch:
            with switch.case(step < warmup):
                layers.assign(warm_lr, lr)
            with switch.default():
                layers.assign(base_lr, lr)
    """

    def __init__(self, name=None):
        self.helper = LayerHelper(name or "switch")
        self._cases = []          # (pred_var_or_None, block, written)
        self._inside = False

    def __enter__(self):
        self._inside = True
        return self

    @contextlib.contextmanager
    def case(self, condition):
        if not self._inside:
            raise RuntimeError("Switch.case used outside 'with Switch()'")
        yield from self._capture(condition)

    @contextlib.contextmanager
    def default(self):
        if not self._inside:
            raise RuntimeError("Switch.default used outside "
                               "'with Switch()'")
        yield from self._capture(None)

    def _capture(self, condition):
        prog = self.helper.main_program
        parent = prog.current_block()
        pre = _parent_visible_names(parent)
        sb = prog.create_block()
        with _BlockGuard(prog, sb):
            yield
        self._cases.append((condition, sb, _written_parent_names(sb, pre)))

    def __exit__(self, exc_type, exc, tb):
        self._inside = False
        if exc_type is not None:
            return False
        prog = self.helper.main_program
        parent = prog.current_block()
        written = []
        for _, _, w in self._cases:
            for n in w:
                if n not in written:
                    written.append(n)
        if not written:
            return False
        defaults = [(sb, w) for c, sb, w in self._cases if c is None]
        cases = [(c, sb, w) for c, sb, w in self._cases if c is not None]
        if len(defaults) > 1:
            raise ValueError("Switch allows at most one default() block")
        if not cases:
            raise ValueError("Switch with only a default() block — use "
                             "plain assigns instead")
        # ONE cond per case over the union of written names (not one
        # per (name, case) pair — a case sub-block must execute once):
        # chain back to front; each else-branch re-emits whatever the
        # chain below produced, the base being the default block's
        # values (falling through to the originals for names it does
        # not write)
        current = {}                    # name -> source
        for name in written:
            if defaults and name in defaults[0][1]:
                current[name] = ("block", defaults[0][0].idx)
            else:
                current[name] = name
        for condition, sb, w in reversed(cases):
            # true branch: the case sub-block; names it does not write
            # are re-emitted inside it from the chain's current source
            t_names = {}
            with _BlockGuard(prog, sb):
                t_names = _materialize_sources(
                    prog, parent, current,
                    [n for n in written if n not in w])
                t_names.update({n: n for n in written if n in w})
            fb = prog.create_block()
            with _BlockGuard(prog, fb):
                f_names = _materialize_sources(prog, parent, current,
                                               written)
            out_names = [unique_name.generate("switch.out")
                         for _ in written]
            parent.append_op(
                type="cond",
                inputs={"Cond": [condition.name]},
                outputs={"Out": out_names},
                attrs={"sub_block_t": sb.idx, "sub_block_f": fb.idx,
                       "out_t": [t_names[n] for n in written],
                       "out_f": [f_names[n] for n in written]})
            for name, out_name in zip(written, out_names):
                v = parent.var(name)
                parent.create_var(name=out_name, shape=v.shape,
                                  dtype=v.dtype)
                current[name] = out_name
        for name in written:
            if current[name] != name:
                assign(parent.var(current[name]),
                       output=parent.var(name))
        return False


def _reemit_block(prog, src_block_idx, src_name):
    """Inside the current (false-)block, re-run the ops of a previously
    captured default block so its value for src_name materializes here."""
    src = prog.blocks[src_block_idx]
    cur = prog.current_block()
    for op in src.ops:
        cur.append_op(type=op.type, inputs=dict(op.inputs),
                      outputs=dict(op.outputs), attrs=dict(op.attrs))
        for ns in op.outputs.values():
            for n in ns:
                if n not in cur.vars:
                    sv = src.var(n)
                    cur.create_var(name=n, shape=sv.shape, dtype=sv.dtype)
    return cur.var(src_name)


def _source_value(prog, parent, source, name):
    """Materialize a chain source inside the current block: either
    re-emit the default block's ops (("block", idx) source) or assign
    from a parent-visible name."""
    if isinstance(source, tuple):
        return _reemit_block(prog, source[1], name)
    return assign(parent.var(source))


def _materialize_sources(prog, parent, current, names):
    """Materialize several chain sources inside the current block,
    re-emitting each distinct source BLOCK only once (a default block
    writing K variables must not have its op list duplicated K times)."""
    out = {}
    emitted_blocks = set()
    for name in names:
        src = current[name]
        if isinstance(src, tuple):
            if src[1] in emitted_blocks:
                out[name] = prog.current_block().var(name).name
            else:
                out[name] = _reemit_block(prog, src[1], name).name
                emitted_blocks.add(src[1])
        else:
            out[name] = assign(parent.var(src)).name
    return out


class IfElse:
    """Row-masked two-branch construct (reference control_flow.py:2547).

    cond: (batch, 1) bool. ``input(x)`` marks a value used inside a
    branch; ``output(*outs)`` registers branch results. Calling the
    instance merges both branches' outputs row-wise by the mask. Both
    branches run DENSE on the full batch (see module docstring).
    """

    def __init__(self, cond, name=None):
        self.cond = cond
        self.helper = LayerHelper(name or "ifelse")
        self._outs = {True: None, False: None}
        self._in_branch = None

    @contextlib.contextmanager
    def true_block(self):
        self._in_branch = True
        yield
        self._in_branch = None

    @contextlib.contextmanager
    def false_block(self):
        self._in_branch = False
        yield
        self._in_branch = None

    def input(self, x):
        if self._in_branch is None:
            raise RuntimeError("IfElse.input outside a branch block")
        return x

    def output(self, *outs):
        if self._in_branch is None:
            raise RuntimeError("IfElse.output outside a branch block")
        self._outs[self._in_branch] = list(outs)

    def __call__(self):
        t, f = self._outs[True], self._outs[False]
        if t is None or f is None:
            raise ValueError("IfElse needs output() in both branches")
        if len(t) != len(f):
            raise ValueError("IfElse branches registered different "
                             "output arities")
        from .layers import _append_simple

        merged = []
        for tv, fv in zip(t, f):
            merged.append(_append_simple(
                "masked_select_rows",
                {"Mask": [self.cond.name], "X": [tv.name],
                 "Y": [fv.name]}, {}))
        return merged


def _register():
    from . import layers as _layers

    _layers._register_exports(
        {"While": While, "Switch": Switch, "IfElse": IfElse})


_register()
