"""Program-IR optimization pass pipeline.

TPU-native counterpart of the reference's 89 hand-written IR passes
(/root/reference/paddle/fluid/framework/ir/: graph_pattern_detector.cc,
fuse_elewise_add_act_pass.cc, constant_folding, memory_optimize_pass,
build_strategy.cc wiring). The reference rewrites an SSA ir::Graph before
ParallelExecutor interprets it; here the rewrites happen on the thin
Program IR before the Executor traces it into ONE jit function — XLA
still does instruction-level fusion afterwards, so these passes exist to
shrink what the *Python trace* and the resulting HLO have to chew on
(trace time, HLO size, compile time) and to hit the hand-fused kernels
in kernels.py directly.

Passes (BuildStrategy knob in parentheses):
  constant_folding       (strategy.constant_folding)   all-constant
      subgraphs — fill_constant / shape-arithmetic chains — evaluated
      once at build and re-materialized as single constant ops
  elide_identities       (strategy.enable_inplace)     assign and
      scale(scale=1, bias=0) ops dropped, consumers rewired
  cse                    (strategy.cse)                duplicate OpDescs
      (same type+inputs+attrs) merged, later consumers rewired
  fuse_elemwise_act      (strategy.fuse_elewise_add_act_ops)
      elementwise binary -> activation chains lowered onto the
      fused_elemwise_activation kernel (kernels.py)
  dead_code_elimination  (strategy.memory_optimize)    ops whose outputs
      reach no fetch / persistable / sub-block read
  drop_unused_vars       (strategy.memory_optimize)    VarDescs no
      surviving op references (blob/content-hash shrink)

Safety invariants (why rewrites stay bitwise-exact):
- Random ops whose kernels fold ``op_index`` into their key (dropout,
  *_random) are stamped with ``__rng_slot`` = their pre-pass index, and
  run_block uses the stamp, so removals never shift a surviving op's RNG
  stream. Random ops are excluded from folding/CSE (two dropouts must
  draw independent masks).
- Names read anywhere inside sub-blocks are protected: cond/while
  kernels snapshot the whole enclosing env, so sub-block reads are
  invisible to block-0 def-use chains.
- A ``backward`` op re-traces the prefix of the (rewritten) block, so
  its implicit dependencies are exactly the surviving ops — removing an
  op that doesn't reach the loss/fetches/state is safe, reordering is
  not (no pass reorders).
- This IR permits name reassignment (e.g. legacy_flow's assign-into-
  loop-var); every renaming pass walks forward and kills an alias the
  moment the original name is redefined.

All passes run on a CLONE — the user's Program is never mutated. Set
``PADDLE_IR_PASSES=0`` to disable the whole pipeline.
"""
from __future__ import annotations

import json
import os
import time
from collections import defaultdict
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set

import numpy as np

from ..framework import dtype as dtype_mod
from .ir import OpDesc, Program, _attrs_to_json

# ops whose kernels fold ctx.op_index into their RNG key (kernels.py
# ctx.key() users) — these get a stable __rng_slot stamp
_INDEXED_RNG_OPS = {"gaussian_random", "uniform_random",
                    "truncated_gaussian_random", "dropout"}

_SIDE_EFFECT_OPS = {"feed", "fetch", "read", "py_func", "print", "assert",
                    "backward"}
_CONTROL_FLOW_OPS = {"cond", "while"}
_ARRAY_OPS = {"create_array", "array_write", "array_read", "array_length",
              "tensor_array_to_tensor"}

# attrs that reference other blocks by index (cond/while)
_SUB_BLOCK_ATTRS = ("sub_block", "sub_block_t", "sub_block_f")

_FOLD_MAX_ELEMS = 1 << 16

_FUSABLE_BINARY = {"elementwise_add", "elementwise_sub", "elementwise_mul",
                   "elementwise_div", "elementwise_max", "elementwise_min"}
_FUSABLE_ACTS = {"relu", "sigmoid", "tanh", "gelu", "leaky_relu",
                 "softplus", "softsign", "swish", "square", "sqrt", "exp"}

_FLOAT_DTYPES = {"float16", "bfloat16", "float32", "float64"}


def _is_random(op_type: str) -> bool:
    """Any kernel that draws from the RNG stream (explicit set plus a
    defensive substring net for delegate-registered random ops like
    uniform_random_s2 / sampling_id_s / sampled_*)."""
    return (op_type in _INDEXED_RNG_OPS or "random" in op_type
            or "dropout" in op_type or "sampl" in op_type)


def _rewrite_unsafe(op_type: str) -> bool:
    return (op_type in _SIDE_EFFECT_OPS or op_type in _CONTROL_FLOW_OPS
            or _is_random(op_type))


# ---------------------------------------------------------------------------
# report
# ---------------------------------------------------------------------------
@dataclass
class PassStat:
    name: str
    ops_before: int
    ops_after: int
    ms: float
    vars_dropped: int = 0

    @property
    def removed(self) -> int:
        return self.ops_before - self.ops_after


@dataclass
class PassReport:
    """What the pipeline did to one program: per-pass stats + totals."""
    stats: List[PassStat] = field(default_factory=list)
    ops_before: int = 0
    ops_after: int = 0
    ms: float = 0.0
    vars_dropped: int = 0

    @property
    def removed(self) -> int:
        return self.ops_before - self.ops_after

    def table(self) -> str:
        """Aligned text table (tools/dump_passes.py output)."""
        lines = [f"{'Pass':<24}{'ops before':>12}{'ops after':>12}"
                 f"{'removed':>10}{'ms':>10}"]
        for s in self.stats:
            lines.append(f"{s.name:<24}{s.ops_before:>12}{s.ops_after:>12}"
                         f"{s.removed:>10}{s.ms:>10.2f}")
        lines.append(f"{'TOTAL':<24}{self.ops_before:>12}"
                     f"{self.ops_after:>12}{self.removed:>10}"
                     f"{self.ms:>10.2f}")
        if self.vars_dropped:
            lines.append(f"(+ {self.vars_dropped} unused VarDescs dropped)")
        return "\n".join(lines)


# ---------------------------------------------------------------------------
# pass context
# ---------------------------------------------------------------------------
class _Ctx:
    def __init__(self, program: Program, feeds: Set[str],
                 fetches: Set[str]):
        self.program = program
        self.block = program.global_block
        self.feeds = set(feeds)
        self.fetches = set(fetches)
        self.persistable = {n for n, v in self.block.vars.items()
                            if v.persistable}
        self.data = {n for n, v in self.block.vars.items() if v.is_data}
        self.sub_reads = _sub_block_names(program)
        # names no rewrite may alias away: the executor (fetch/state/feed)
        # or a sub-block trace reads them by name
        self.protected = (self.feeds | self.fetches | self.persistable
                          | self.data | self.sub_reads)


def _sub_block_names(program: Program) -> Set[str]:
    """Every name referenced inside blocks[1:] or by control-flow attrs.
    cond/while kernels snapshot the WHOLE outer env, so any of these may
    be read by a sub-block trace regardless of block-0 def-use edges."""
    names: Set[str] = set()
    for blk in program.blocks[1:]:
        for op in blk.ops:
            names.update(op.input_names())
            names.update(op.output_names())
    for blk in program.blocks:
        for op in blk.ops:
            for key in ("loop_in", "body_out", "out_t", "out_f"):
                v = op.attrs.get(key)
                if isinstance(v, (list, tuple)):
                    names.update(str(n) for n in v)
            v = op.attrs.get("cond_out")
            if isinstance(v, str):
                names.add(v)
    return names


def _stamp_rng_slots(block) -> None:
    """Pin index-keyed RNG ops to their pre-pass stream so later
    removals can't shift a surviving op's random draw (bitwise parity
    between passes-on and passes-off)."""
    for i, op in enumerate(block.ops):
        if op.type in _INDEXED_RNG_OPS and "__rng_slot" not in op.attrs:
            op.attrs["__rng_slot"] = i


# ---------------------------------------------------------------------------
# constant folding
# ---------------------------------------------------------------------------
def _pass_constant_folding(ctx: _Ctx) -> None:
    from .kernels import KERNELS, ExecContext

    block = ctx.block
    const_env: Dict[str, np.ndarray] = {}
    fold_vals: Dict[int, Dict[str, np.ndarray]] = {}

    def _invalidate(op):
        for n in op.output_names():
            const_env.pop(n, None)

    for i, op in enumerate(block.ops):
        if (_rewrite_unsafe(op.type) or op.type in _ARRAY_OPS
                or any(n in ctx.protected for n in op.output_names())):
            _invalidate(op)
            continue
        fn = KERNELS.get(op.type)
        in_names = op.input_names()
        is_source = op.type in ("fill_constant", "assign_value") \
            and not in_names
        if fn is None or not (
                is_source or (in_names
                              and all(n in const_env for n in in_names))):
            _invalidate(op)
            continue
        try:
            ins = {slot: [const_env[n] for n in names]
                   for slot, names in op.inputs.items()}
            outs = fn(ins, op.attrs, ExecContext(rng_key=None))
            vals = {}
            for slot, names in op.outputs.items():
                produced = outs.get(slot)
                if produced is None or len(produced) != len(names):
                    raise ValueError("slot mismatch")
                for n, v in zip(names, produced):
                    arr = np.asarray(v)
                    if arr.size > _FOLD_MAX_ELEMS:
                        raise ValueError("too large to fold")
                    vals[n] = arr
        except Exception:
            _invalidate(op)
            continue
        fold_vals[i] = vals
        const_env.update(vals)

    if not fold_vals:
        return
    # a const needs materialization if a surviving op or a fetch reads it
    needed: Set[str] = set(ctx.fetches)
    consumed: Set[str] = set()
    for i, op in enumerate(block.ops):
        if i not in fold_vals:
            consumed.update(op.input_names())
    needed = (needed | consumed) & {n for vs in fold_vals.values()
                                    for n in vs}
    new_ops = []
    for i, op in enumerate(block.ops):
        if i not in fold_vals:
            new_ops.append(op)
            continue
        for slot, names in op.outputs.items():
            for n in names:
                if n in needed:
                    new_ops.append(_materialize_const(n, fold_vals[i][n]))
    block.ops = new_ops


def _materialize_const(name: str, arr: np.ndarray) -> OpDesc:
    dtype = dtype_mod.dtype_name(dtype_mod.convert_dtype(str(arr.dtype)))
    if arr.size and (arr == arr.flat[0]).all():
        val = arr.flat[0]
        val = bool(val) if arr.dtype == np.bool_ else (
            int(val) if np.issubdtype(arr.dtype, np.integer) else float(val))
        return OpDesc("fill_constant", {}, {"Out": [name]},
                      {"shape": [int(s) for s in arr.shape],
                       "dtype": dtype, "value": val})
    return OpDesc("assign_value", {}, {"Out": [name]},
                  {"values": arr.ravel().tolist(),
                   "shape": [int(s) for s in arr.shape], "dtype": dtype})


# ---------------------------------------------------------------------------
# identity elision
# ---------------------------------------------------------------------------
def _identity_source(op, block) -> Optional[str]:
    """Name this op's Out is a bit-exact alias of, or None."""
    if op.type == "assign":
        return (op.inputs.get("X") or [None])[0]
    if op.type == "scale" \
            and op.attrs.get("scale", 1.0) == 1.0 \
            and op.attrs.get("bias", 0.0) == 0.0:
        # x*1.0+0.0 promotes int arrays to float — only elide when the
        # input is declared floating
        src = (op.inputs.get("X") or [None])[0]
        desc = block.vars.get(src) if src else None
        if desc is not None and desc.dtype in _FLOAT_DTYPES:
            return src
    return None


def _def_counts(ctx: _Ctx) -> Dict[str, int]:
    """Definitions per name: op writes plus one implicit def for names
    the executor seeds into the env (feeds and scope-resident
    persistables). A name with >1 defs is reassigned somewhere — no
    rewrite may alias through it, because an alias captures the value
    at ONE point in time while the name's value changes."""
    counts: Dict[str, int] = defaultdict(int)
    for n in ctx.feeds | ctx.persistable:
        counts[n] += 1
    for op in ctx.block.ops:
        for n in op.output_names():
            counts[n] += 1
    return counts


def _pass_elide_identities(ctx: _Ctx) -> None:
    block = ctx.block
    defs = _def_counts(ctx)
    rename: Dict[str, str] = {}
    rev: Dict[str, Set[str]] = defaultdict(set)  # source -> aliases of it

    def res(n):
        while n in rename:
            n = rename[n]
        return n

    new_ops = []
    for op in block.ops:
        op.inputs = {s: [res(n) for n in names]
                     for s, names in op.inputs.items()}
        src = _identity_source(op, block)
        out = (op.outputs.get("Out") or [None])[0]
        if (src is not None and out is not None
                and out not in ctx.protected
                and defs.get(src, 0) <= 1):
            # single-def source: the alias is valid for the rest of the
            # block. A reassigned source would leave later readers of
            # `out` pointing at the WRONG (new) value — keep the op.
            if out != src:
                rename[out] = src
                rev[src].add(out)
            continue
        new_ops.append(op)
        for n in op.output_names():
            # redefinition kills aliases OF this name and (belt &
            # braces — unreachable under the single-def guard) aliases
            # pointing at it
            rename.pop(n, None)
            for alias in rev.pop(n, ()):
                rename.pop(alias, None)
    block.ops = new_ops


# ---------------------------------------------------------------------------
# common-subexpression elimination
# ---------------------------------------------------------------------------
def _pass_cse(ctx: _Ctx) -> None:
    block = ctx.block
    rename: Dict[str, str] = {}
    seen: Dict[str, OpDesc] = {}
    uses: Dict[str, Set[str]] = defaultdict(set)  # name -> keys touching it
    # Merging a duplicate UPSTREAM of a backward op restructures vjp
    # cotangent accumulation (two gradient paths collapse into one
    # doubled path) — mathematically equal, bitwise different. XLA owns
    # training-graph CSE; source-level CSE only merges past the last
    # backward op (and everywhere on inference programs), keeping the
    # passes-on/off bitwise-parity gate exact.
    last_bwd = max((i for i, op in enumerate(block.ops)
                    if op.type == "backward"), default=-1)
    defs = _def_counts(ctx)

    def res(n):
        while n in rename:
            n = rename[n]
        return n

    def _kill(name):
        rename.pop(name, None)
        for key in uses.pop(name, ()):
            seen.pop(key, None)

    new_ops = []
    for i, op in enumerate(block.ops):
        op.inputs = {s: [res(n) for n in names]
                     for s, names in op.inputs.items()}
        outs = op.output_names()
        mergeable = (i > last_bwd and not _rewrite_unsafe(op.type)
                     and outs
                     and not any(n in ctx.protected for n in outs))
        key = None
        if mergeable:
            key = json.dumps(
                [op.type,
                 sorted((s, ns) for s, ns in op.inputs.items()),
                 sorted(_attrs_to_json(op.attrs).items())],
                sort_keys=True, default=str)
            prev = seen.get(key)
            # merging aliases this op's outputs to prev's — only valid
            # when prev's outputs are single-def (a later reassignment
            # of a prev output would redirect the alias to the WRONG
            # value; see _def_counts)
            if prev is not None and all(
                    s in prev.outputs
                    and len(prev.outputs[s]) == len(ns)
                    and all(defs.get(pn, 0) <= 1
                            for pn in prev.outputs[s])
                    for s, ns in op.outputs.items()):
                for s, ns in op.outputs.items():
                    for n, pn in zip(ns, prev.outputs[s]):
                        if n != pn:
                            rename[n] = pn
                continue
        new_ops.append(op)
        # this op redefines its outputs: invalidate aliases and any
        # cached exprs reading/producing those names FIRST, then record
        # the op itself (its own entry must survive the kill)
        for n in op.output_names():
            _kill(n)
        if key is not None:
            seen[key] = op
            for n in set(op.input_names()) | set(outs):
                uses[n].add(key)
    block.ops = new_ops


# ---------------------------------------------------------------------------
# elementwise + activation fusion
# ---------------------------------------------------------------------------
def _pass_fuse_elemwise_act(ctx: _Ctx) -> None:
    block = ctx.block
    ops = block.ops
    readers: Dict[str, List[int]] = defaultdict(list)
    writers: Dict[str, List[int]] = defaultdict(list)
    for i, op in enumerate(ops):
        for n in op.input_names():
            readers[n].append(i)
        for n in op.output_names():
            writers[n].append(i)
    drop: Set[int] = set()
    for i, op in enumerate(ops):
        if op.type not in _FUSABLE_BINARY or i in drop:
            continue
        out = (op.outputs.get("Out") or [None])[0]
        if (out is None or out in ctx.protected
                or len(writers.get(out, ())) != 1
                or len(readers.get(out, ())) != 1):
            continue
        j = readers[out][0]
        if j <= i or j in drop:
            continue
        act = ops[j]
        if (act.type not in _FUSABLE_ACTS
                or act.inputs.get("X") != [out]
                or len(act.input_names()) != 1):
            continue
        act_out = (act.outputs.get("Out") or [None])[0]
        if act_out is None or len(writers.get(act_out, ())) != 1:
            continue
        # fusing moves the act_out write from j up to i; if act_out is
        # env-seeded (feed/persistable), a reader before j meant the
        # seeded value — don't move the write past it
        if act_out in (ctx.feeds | ctx.persistable) and any(
                k < j for k in readers.get(act_out, ())):
            continue
        act_attrs = {k: v for k, v in act.attrs.items()
                     if k != "__rng_slot"}
        ops[i] = OpDesc(
            "fused_elemwise_activation",
            inputs={"X": op.inputs["X"], "Y": op.inputs["Y"]},
            outputs={"Out": [act_out]},
            attrs={"functor_list": [op.type, act.type],
                   "axis": op.attrs.get("axis", -1),
                   "act_attrs": act_attrs})
        drop.add(j)
    if drop:
        block.ops = [op for k, op in enumerate(ops) if k not in drop]


# ---------------------------------------------------------------------------
# dead-code elimination + var-table cleanup
# ---------------------------------------------------------------------------
def _pass_dce(ctx: _Ctx) -> None:
    block = ctx.block
    live = set(ctx.fetches) | ctx.persistable | ctx.sub_reads
    keep: List[OpDesc] = []
    for op in reversed(block.ops):
        if op.type in _SIDE_EFFECT_OPS or set(op.output_names()) & live:
            keep.append(op)
            live |= set(op.input_names())
            # NOTE: defs are not killed — this IR allows name
            # reassignment, so earlier writers stay conservatively live
    keep.reverse()
    block.ops = keep


def _pass_drop_unused_vars(ctx: _Ctx) -> int:
    referenced = set(ctx.feeds) | set(ctx.fetches) | ctx.sub_reads
    for blk in ctx.program.blocks:
        for op in blk.ops:
            referenced.update(op.input_names())
            referenced.update(op.output_names())
    blk0 = ctx.block
    before = len(blk0.vars)
    blk0.vars = {n: v for n, v in blk0.vars.items()
                 if n in referenced or v.persistable or v.is_data}
    return before - len(blk0.vars)


# ---------------------------------------------------------------------------
# pipeline
# ---------------------------------------------------------------------------
# (name, BuildStrategy knob, fn) — run order matters: fold first so CSE
# sees canonical constants, elide/cse before fusion so fusion matches the
# slimmed chains, DCE last to sweep newly-orphaned producers
_PIPELINE = (
    ("constant_folding", "constant_folding", _pass_constant_folding),
    ("elide_identities", "enable_inplace", _pass_elide_identities),
    ("cse", "cse", _pass_cse),
    ("fuse_elemwise_act", "fuse_elewise_add_act_ops",
     _pass_fuse_elemwise_act),
    ("dead_code_elimination", "memory_optimize", _pass_dce),
)


def pass_names() -> List[str]:
    return [name for name, _, _ in _PIPELINE] + ["drop_unused_vars"]


def apply_passes(program: Program, feed_names: Sequence[str],
                 fetch_names: Sequence[str], strategy=None):
    """Run the enabled passes over a CLONE of ``program`` and return
    ``(optimized_program, PassReport)``.

    ``strategy`` is a compiler.BuildStrategy (defaults to all knobs on);
    ``PADDLE_IR_PASSES=0`` disables the pipeline entirely (the original
    program is returned untouched).
    """
    from .compiler import BuildStrategy

    strategy = strategy or BuildStrategy()
    n0 = len(program.global_block.ops)
    enabled = [(name, fn) for name, knob, fn in _PIPELINE
               if getattr(strategy, knob, True)]
    if os.environ.get("PADDLE_IR_PASSES") == "0" or not enabled:
        return program, PassReport([], n0, n0, 0.0)

    t_all = time.perf_counter()
    opt = Program.from_dict(program.to_dict())
    opt.random_seed = program.random_seed
    ctx = _Ctx(opt, set(feed_names), set(fetch_names))
    _stamp_rng_slots(opt.global_block)
    stats: List[PassStat] = []
    for name, fn in enabled:
        before = len(opt.global_block.ops)
        t0 = time.perf_counter()
        fn(ctx)
        ms = (time.perf_counter() - t0) * 1e3
        stats.append(PassStat(name, before, len(opt.global_block.ops), ms))
    vars_dropped = 0
    if getattr(strategy, "memory_optimize", True):
        n = len(opt.global_block.ops)
        t0 = time.perf_counter()
        vars_dropped = _pass_drop_unused_vars(ctx)
        stats.append(PassStat("drop_unused_vars", n, n,
                              (time.perf_counter() - t0) * 1e3,
                              vars_dropped=vars_dropped))
    total_ms = (time.perf_counter() - t_all) * 1e3
    report = PassReport(stats, n0, len(opt.global_block.ops), total_ms,
                        vars_dropped)
    return opt, report
