"""Program-IR optimization pass pipeline.

TPU-native counterpart of the reference's 89 hand-written IR passes
(/root/reference/paddle/fluid/framework/ir/: graph_pattern_detector.cc,
fuse_elewise_add_act_pass.cc, constant_folding, memory_optimize_pass,
build_strategy.cc wiring). The reference rewrites an SSA ir::Graph before
ParallelExecutor interprets it; here the rewrites happen on the thin
Program IR before the Executor traces it into ONE jit function — XLA
still does instruction-level fusion afterwards, so these passes exist to
shrink what the *Python trace* and the resulting HLO have to chew on
(trace time, HLO size, compile time) and to hit the hand-fused kernels
in kernels.py directly.

Passes (BuildStrategy knob in parentheses):
  auto_mixed_precision   (strategy.amp / PADDLE_AMP)   bf16/fp16 compute
      rewrite of the forward region: white-listed matmul-family ops get
      cast ops on their f32 inputs and emit low-precision outputs,
      black-listed (numerically sensitive) ops are pinned f32, gray ops
      follow their inputs; parameters stay f32 MASTER WEIGHTS (the cast
      materializes a low-precision copy inside the step, optimizer
      updates apply in f32); float32 feed vars flip to the low dtype
      (the executor/prefetcher cast host-side — h2d bytes halve); a
      cleanup sub-pass dedups identical casts and elides exact
      lowp->f32->lowp round trips. fp16 additionally threads static
      loss scaling through a check_finite_and_unscale kernel.
  constant_folding       (strategy.constant_folding)   all-constant
      subgraphs — fill_constant / shape-arithmetic chains — evaluated
      once at build and re-materialized as single constant ops
  elide_identities       (strategy.enable_inplace)     assign and
      scale(scale=1, bias=0) ops dropped, consumers rewired
  cse                    (strategy.cse)                duplicate OpDescs
      (same type+inputs+attrs) merged, later consumers rewired
  fuse_elemwise_act      (strategy.fuse_elewise_add_act_ops)
      elementwise binary -> activation chains lowered onto the
      fused_elemwise_activation kernel (kernels.py)
  dead_code_elimination  (strategy.memory_optimize)    ops whose outputs
      reach no fetch / persistable / sub-block read
  recompute_segmentation (strategy.recompute)          partitions the
      forward region into checkpoint segments (user checkpoint var
      names, else an every-N-ops sqrt split) by stamping ``__remat_seg``
      on each forward op; the executor's backward lowering wraps each
      segment in jax.checkpoint so interior activations are recomputed
      instead of stashed (Chen et al. sublinear memory)
  shard_propagation      (strategy.mesh_shape/sharding_hints)  GSPMD
      sharding annotation: user PartitionSpec hints (plus the
      batch-over-'dp' feed default) propagate across every VarDesc
      through op-level rules (matmul column/row parallel with psums
      counted on contracted dims, elementwise pass-through, reductions
      and losses resolve conflicts by replication) and are stamped as
      ``__sharding_spec`` attrs; the executor turns the boundary stamps
      into real NamedSharding in/out/state shardings on the compiled
      step (shard_boundary_shardings)
  pipeline_stages        (strategy.pipeline_stages)    forward region
      split into S contiguous stages (``__pp_stage`` stamps); the
      executor composes the gradient-merge microbatch loop with
      parallel.pipeline.gpipe_schedule into a GPipe fill-drain schedule
  drop_unused_vars       (strategy.memory_optimize)    VarDescs no
      surviving op references (blob/content-hash shrink)

Gradient merge is NOT a pass (no op rewrite): resolve_gradient_merge
reads BuildStrategy.gradient_merge_k and the executor compiles the
train step as a lax.scan over k microbatches with f32 gradient
accumulators (executor.py _gm_step_fn).

Safety invariants (why rewrites stay bitwise-exact):
- Random ops whose kernels fold ``op_index`` into their key (dropout,
  *_random) are stamped with ``__rng_slot`` = their pre-pass index, and
  run_block uses the stamp, so removals never shift a surviving op's RNG
  stream. Random ops are excluded from folding/CSE (two dropouts must
  draw independent masks).
- Names read anywhere inside sub-blocks are protected: cond/while
  kernels snapshot the whole enclosing env, so sub-block reads are
  invisible to block-0 def-use chains.
- A ``backward`` op re-traces the prefix of the (rewritten) block, so
  its implicit dependencies are exactly the surviving ops — removing an
  op that doesn't reach the loss/fetches/state is safe, reordering is
  not (no pass reorders).
- This IR permits name reassignment (e.g. legacy_flow's assign-into-
  loop-var); every renaming pass walks forward and kills an alias the
  moment the original name is redefined.

All passes run on a CLONE — the user's Program is never mutated. Set
``PADDLE_IR_PASSES=0`` to disable the whole pipeline.
"""
from __future__ import annotations

import json
import os
import time
from collections import defaultdict
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set

import numpy as np

from ..framework import dtype as dtype_mod
from .ir import OpDesc, Program, VarDesc, _attrs_to_json

# ops whose kernels fold ctx.op_index into their RNG key (kernels.py
# ctx.key() users) — these get a stable __rng_slot stamp
_INDEXED_RNG_OPS = {"gaussian_random", "uniform_random",
                    "truncated_gaussian_random", "dropout"}

_SIDE_EFFECT_OPS = {"feed", "fetch", "read", "py_func", "print", "assert",
                    "backward"}
_CONTROL_FLOW_OPS = {"cond", "while"}
_ARRAY_OPS = {"create_array", "array_write", "array_read", "array_length",
              "tensor_array_to_tensor"}

# attrs that reference other blocks by index (cond/while)
_SUB_BLOCK_ATTRS = ("sub_block", "sub_block_t", "sub_block_f")

_FOLD_MAX_ELEMS = 1 << 16

_FUSABLE_BINARY = {"elementwise_add", "elementwise_sub", "elementwise_mul",
                   "elementwise_div", "elementwise_max", "elementwise_min"}
_FUSABLE_ACTS = {"relu", "sigmoid", "tanh", "gelu", "leaky_relu",
                 "softplus", "softsign", "swish", "square", "sqrt", "exp"}

_FLOAT_DTYPES = {"float16", "bfloat16", "float32", "float64"}
_LOW_PRECISION = {"float16", "bfloat16"}

# update kernels that honor an optional FoundInfinite input (kernels.py):
# under fp16 loss scaling, a non-finite step skips the whole update
_AMP_GATED_UPDATE_OPS = {"sgd", "momentum", "adam", "lamb"}

# PADDLE_AMP env spellings -> canonical low dtype
_AMP_DTYPE_ALIASES = {"bf16": "bfloat16", "bfloat16": "bfloat16",
                      "1": "bfloat16", "true": "bfloat16", "on": "bfloat16",
                      "fp16": "float16", "float16": "float16"}


def resolve_amp(strategy=None):
    """Resolve the mixed-precision config for one build.

    Returns ``(low_dtype, level, init_loss_scale)`` or ``None`` (f32).
    ``PADDLE_AMP`` (bf16|fp16|0) overrides the BuildStrategy knobs
    (``amp``/``amp_dtype``/``amp_level``/``amp_init_loss_scale``);
    ``PADDLE_AMP=0`` forces bitwise-f32 behavior whatever the strategy
    says. The tuple is part of the executor's step cache key, so
    flipping the env between runs can never hit a stale executable.

    ``PADDLE_IR_PASSES=0`` resolves to None too: the graph rewrite and
    the host-side feed casts must switch together — a bf16 feed into an
    un-rewritten f32 graph would bypass the black-list pinning."""
    if os.environ.get("PADDLE_IR_PASSES") == "0":
        return None
    level = str(os.environ.get("PADDLE_AMP_LEVEL")
                or getattr(strategy, "amp_level", "O1") or "O1").upper()
    try:
        scale = float(getattr(strategy, "amp_init_loss_scale", 2.0 ** 15))
    except (TypeError, ValueError):
        scale = 2.0 ** 15
    env = os.environ.get("PADDLE_AMP")
    if env is not None:
        e = env.strip().lower()
        if e in ("", "0", "false", "off"):
            return None
        dt = _AMP_DTYPE_ALIASES.get(e)
        if dt is None:
            raise ValueError(
                f"PADDLE_AMP={env!r}: expected bf16|bfloat16|fp16|"
                f"float16|0")
        return (dt, level, scale)
    if strategy is not None and getattr(strategy, "amp", False):
        raw = str(getattr(strategy, "amp_dtype", "bfloat16")).lower()
        dt = _AMP_DTYPE_ALIASES.get(raw)
        if dt is None:
            raise ValueError(
                f"BuildStrategy.amp_dtype={raw!r}: expected bfloat16 or "
                f"float16")
        return (dt, level, scale)
    return None


def resolve_recompute(strategy=None):
    """Resolve the activation-rematerialization config for one build.

    Returns ``(checkpoint_names, num_segments)`` or ``None`` (no remat).
    ``checkpoint_names`` come from ``BuildStrategy.recompute_checkpoints``
    (user-chosen segment boundaries, à la the reference
    RecomputeConfig.checkpoints); ``num_segments`` is the
    ``recompute_segments`` knob for the automatic every-N-ops heuristic
    (0 = sqrt(#forward ops), the Chen et al. sublinear split).

    ``PADDLE_IR_PASSES=0`` resolves to None: the escape hatch disables
    every graph transform at once, so a passes-off run is the exact
    baseline."""
    if os.environ.get("PADDLE_IR_PASSES") == "0":
        return None
    if strategy is None or not getattr(strategy, "recompute", False):
        return None
    cps = tuple(str(getattr(c, "name", c))
                for c in (getattr(strategy, "recompute_checkpoints", ())
                          or ()))
    try:
        nseg = int(getattr(strategy, "recompute_segments", 0) or 0)
    except (TypeError, ValueError):
        nseg = 0
    return (cps, nseg)


def resolve_sharding(strategy=None):
    """Resolve the GSPMD sharding config for one build.

    Returns ``(mesh_axes, hints)`` or ``None`` (single chip):
    ``mesh_axes`` is a tuple of ``(axis_name, size)`` pairs in the
    strategy's ``mesh_shape`` order (axes of size <= 1 dropped — they
    select nothing) and ``hints`` a sorted tuple of
    ``(var_name, spec_tuple)`` seed PartitionSpecs from
    ``BuildStrategy.sharding_hints``. Spec entries are normalized to
    ``None`` / axis-name / tuple-of-axis-names; axis names absent from
    the mesh are dropped (the spec_for precedent), never an error.

    ``PADDLE_IR_PASSES=0`` resolves to None like resolve_amp /
    resolve_recompute: one escape restores the whole single-chip
    baseline, bitwise."""
    if os.environ.get("PADDLE_IR_PASSES") == "0":
        return None
    if strategy is None:
        return None
    shape = getattr(strategy, "mesh_shape", None) or {}
    try:
        axes = tuple((str(k), int(v)) for k, v in shape.items()
                     if int(v) > 1)
    except (TypeError, ValueError, AttributeError):
        # AttributeError covers the likeliest misuse — a string or a
        # pair list instead of a dict (no .items())
        raise ValueError(
            f"BuildStrategy.mesh_shape={shape!r}: expected "
            f"{{axis_name: int_size}}")
    if not axes:
        return None
    names = {k for k, _ in axes}

    def _entry(e):
        if e is None or e == "" or e == "None" or e == "-":
            return None
        if isinstance(e, (list, tuple)):
            kept = tuple(str(a) for a in e if str(a) in names)
            return kept if len(kept) > 1 else (kept[0] if kept else None)
        return str(e) if str(e) in names else None

    hints = []
    for name, spec in sorted(
            (getattr(strategy, "sharding_hints", None) or {}).items()):
        if spec is None:
            spec = ()
        if isinstance(spec, str):
            spec = (spec,)
        hints.append((str(name), tuple(_entry(e) for e in spec)))
    return (axes, tuple(hints))


def resolve_pipeline(strategy=None):
    """Resolve the pipeline-schedule config for one build.

    Returns the stage count ``S`` (> 1) or ``None``. With S > 1 and
    ``gradient_merge_k > 1`` the executor composes the gradient-merge
    microbatch loop with ``parallel.pipeline.gpipe_schedule`` into a
    GPipe fill-drain schedule over S contiguous forward stages
    (``__pp_stage`` stamps from the pipeline_stages pass).

    ``PADDLE_IR_PASSES=0`` resolves to None with the rest of the
    pipeline."""
    if os.environ.get("PADDLE_IR_PASSES") == "0":
        return None
    if strategy is None:
        return None
    try:
        s = int(getattr(strategy, "pipeline_stages", 1) or 1)
    except (TypeError, ValueError):
        s = 1
    return s if s > 1 else None


PIPELINE_SCHEDULES = ("gpipe", "1f1b", "interleaved")


def resolve_pipeline_schedule(strategy=None):
    """Resolve which pipeline schedule a pipelined step compiles with.

    Returns ``(schedule, interleave)`` with schedule in
    ``gpipe | 1f1b | interleaved`` — gpipe is the default and the
    escape leg (``pipeline_schedule="gpipe"`` restores the exact
    pre-1F1B trace). The env override ``PADDLE_PP_SCHEDULE`` follows
    the PADDLE_AMP pattern: a schedule name forces it on,
    ``0``/``off`` forces gpipe whatever the strategy says.
    ``interleave`` (BuildStrategy.pipeline_interleave) is the virtual
    stages per worker, only meaningful for "interleaved"."""
    try:
        interleave = int(getattr(strategy, "pipeline_interleave", 2)
                         or 2)
    except (TypeError, ValueError):
        interleave = 2
    env = os.environ.get("PADDLE_PP_SCHEDULE")
    if env is not None:
        e = env.strip().lower()
        if e in ("", "0", "false", "off", "gpipe"):
            return ("gpipe", interleave)
        if e in PIPELINE_SCHEDULES:
            return (e, interleave)
        raise ValueError(f"PADDLE_PP_SCHEDULE={env!r}: expected "
                         "gpipe|1f1b|interleaved|0")
    raw = str(getattr(strategy, "pipeline_schedule", "gpipe")
              or "gpipe").lower()
    if raw not in PIPELINE_SCHEDULES:
        raise ValueError(
            f"BuildStrategy.pipeline_schedule={raw!r}: expected "
            "gpipe|1f1b|interleaved")
    return (raw, interleave)


def resolve_zero(strategy=None):
    """Resolve the ZeRO sharded-optimizer stage for one build.

    Returns ``2`` or ``3`` (BuildStrategy.zero_stage), or ``None``
    (stage 0 — replicated optimizer states). The env override
    ``PADDLE_ZERO`` follows the PADDLE_AMP pattern: ``2``/``3``
    forces the stage on, ``0``/``off`` is the escape leg whatever the
    strategy says. ``PADDLE_IR_PASSES=0`` resolves to None with the
    rest of the pipeline.

    A resolved stage is a REQUEST, not a guarantee: the executor's
    zero_eligibility gate (static/stepplan.py) additionally needs an
    engaged quantized-comm plan (the reduce-scatter/all-gather
    decomposition rides that ring) and an allowlisted optimizer —
    ineligible builds fall back to the replicated path with a counted
    ``zero.xla`` dispatch reason."""
    if os.environ.get("PADDLE_IR_PASSES") == "0":
        return None
    env = os.environ.get("PADDLE_ZERO")
    if env is not None:
        e = env.strip().lower()
        if e in ("", "0", "false", "off"):
            return None
        if e in ("2", "3"):
            return int(e)
        raise ValueError(f"PADDLE_ZERO={env!r}: expected 2|3|0")
    if strategy is None:
        return None
    try:
        stage = int(getattr(strategy, "zero_stage", 0) or 0)
    except (TypeError, ValueError):
        stage = 0
    if stage == 0:
        return None
    if stage not in (2, 3):
        raise ValueError(
            f"BuildStrategy.zero_stage={stage!r}: expected 0|2|3")
    return stage


def resolve_gradient_merge(strategy=None):
    """Resolve the in-step gradient-merge config for one build.

    Returns ``(k, avg)`` or ``None`` (no merge). With k > 1 the executor
    compiles the train step as a ``lax.scan`` over k microbatches with
    f32 gradient accumulators — one dispatch + one optimizer update per
    k batches (executor.py ``_gm_step_fn``). ``avg`` divides the MERGED
    gradient by k once (never a per-microbatch lr rescale).

    ``PADDLE_IR_PASSES=0`` resolves to None, like resolve_amp /
    resolve_recompute: one escape restores the whole baseline."""
    if os.environ.get("PADDLE_IR_PASSES") == "0":
        return None
    if strategy is None:
        return None
    try:
        k = int(getattr(strategy, "gradient_merge_k", 1) or 1)
    except (TypeError, ValueError):
        k = 1
    if k <= 1:
        return None
    return (k, bool(getattr(strategy, "gradient_merge_avg", True)))


def resolve_comm(strategy=None):
    """Resolve the quantized-collective config for one build.

    Returns ``(codec, bucket_bytes, error_feedback)`` or ``None``
    (plain XLA f32 collectives). ``codec`` comes from
    ``BuildStrategy.comm_quant`` ("int8" | "bf16" | "f32" — f32 runs
    the same explicit bucketed ring with NO rounding, the exact leg
    the ZeRO bitwise-parity gate compares against); the env override
    ``PADDLE_QUANT_ALLREDUCE`` follows the PADDLE_AMP pattern —
    ``int8``/``bf16``/``f32`` forces the codec on, ``0``/``off`` is
    the bitwise escape leg whatever the strategy says.
    ``PADDLE_IR_PASSES=0`` resolves to None with the rest of the
    pipeline (the comm step is a graph-structure change like
    gm/sharding)."""
    if os.environ.get("PADDLE_IR_PASSES") == "0":
        return None
    try:
        bucket = int(getattr(strategy, "comm_bucket_bytes", 4 << 20)
                     or (4 << 20))
    except (TypeError, ValueError):
        bucket = 4 << 20
    ef = bool(getattr(strategy, "comm_error_feedback", False))
    env = os.environ.get("PADDLE_QUANT_ALLREDUCE")
    if env is not None:
        e = env.strip().lower()
        if e in ("", "0", "false", "off"):
            return None
        if e in ("int8", "bf16", "f32"):
            return (e, bucket, ef)
        raise ValueError(
            f"PADDLE_QUANT_ALLREDUCE={env!r}: expected int8|bf16|f32|0")
    raw = str(getattr(strategy, "comm_quant", "off") or "off").lower()
    if raw in ("off", "none", "false", "0", ""):
        return None
    if raw not in ("int8", "bf16", "f32"):
        raise ValueError(
            f"BuildStrategy.comm_quant={raw!r}: "
            "expected int8|bf16|f32|off")
    return (raw, bucket, ef)


def comm_data_axis(shard_cfg):
    """The single pure-DP mesh axis a quantized-collective step runs
    over: ``(axis_name, size)`` when the resolved mesh has EXACTLY one
    axis and it is data-like ('dp'/'data'), else ``None`` — tensor/
    pipeline axes mean XLA's SPMD partitioner owns the collectives and
    the quantized step is ineligible (dispatch-counter reason)."""
    from ..parallel.mesh import DATA_AXIS_NAMES

    if shard_cfg is None:
        return None
    axes = shard_cfg[0]
    if len(axes) != 1 or axes[0][0] not in DATA_AXIS_NAMES:
        return None
    name, size = axes[0]
    return (name, int(size)) if size > 1 else None


def comm_bucket_plan(block, comm, group: int):
    """Size-targeted gradient buckets ordered by BACKWARD COMPLETION.

    Walks the first ``backward`` op's (Params, Grads) pairs; a param's
    gradient completes when the backward reaches its LAST forward use,
    so grads sort by descending forward-consumer index (the deepest
    layer's grads are ready first) and pack greedily into buckets of
    ``comm_bucket_bytes`` f32 payload. Returns a list of dicts
    ``{"grads", "elems", "f32_bytes", "encoded_bytes", "ring_f32",
    "ring_encoded"}`` — or ``None`` when no backward op exists or any
    grad shape is dynamic (the plan must be static). Shared by the
    comm_bucketing pass (stamps), the executor (step structure + EF
    state sizes), and the cost model (comm_bytes rule) so all three
    agree by construction."""
    from ..parallel.collectives import encoded_nbytes, ring_nbytes

    codec, bucket_bytes, _ef = comm
    bwd = next((op for op in block.ops if op.type == "backward"), None)
    if bwd is None:
        return None
    params = list(bwd.inputs.get("Params", ()))
    grads = list(bwd.outputs.get("Grads", ()))
    if not grads or len(params) != len(grads):
        return None
    bwd_idx = block.ops.index(bwd)
    last_use = {}
    for i, op in enumerate(block.ops[:bwd_idx]):
        for n in op.input_names():
            last_use[n] = i
    pairs = []
    for j, (p, g) in enumerate(zip(params, grads)):
        v = block.vars.get(g)
        shape = getattr(v, "shape", None)
        if not shape or any(d is None or int(d) < 0 for d in shape):
            return None
        elems = 1
        for d in shape:
            elems *= int(d)
        pairs.append((-(last_use.get(p, -1)), j, g, elems))
    pairs.sort()   # descending last forward use == completion order
    buckets = []
    cur, cur_elems = [], 0
    for _, _, g, elems in pairs:
        if cur and (cur_elems + elems) * 4 > bucket_bytes:
            buckets.append((cur, cur_elems))
            cur, cur_elems = [], 0
        cur.append(g)
        cur_elems += elems
    if cur:
        buckets.append((cur, cur_elems))
    out = []
    for names, elems in buckets:
        out.append({
            "grads": names,
            "elems": elems,
            "f32_bytes": 4 * elems,
            "encoded_bytes": encoded_nbytes(elems, codec),
            "ring_f32": ring_nbytes(elems, group, "f32"),
            "ring_encoded": ring_nbytes(elems, group, codec),
        })
    return out


def _pass_comm_bucketing(ctx: _Ctx) -> None:
    """Stamp the gradient bucket plan onto the program: the backward op
    gets ``__comm_buckets`` (list of grad-name lists, completion order)
    and ``__comm_codec``, each grad VarDesc gets ``__comm_bucket`` —
    pure bookkeeping like the shard stamps, but it joins the content
    hash so a comm_quant/bucket-size flip can never reuse a stale
    executable. The executor and the cost model re-derive the same plan
    through :func:`comm_bucket_plan`."""
    block = ctx.block
    plan = comm_bucket_plan(block, ctx.comm, ctx.comm_group)
    if plan is None:
        return
    codec = ctx.comm[0]
    bwd = next(op for op in block.ops if op.type == "backward")
    bwd.attrs["__comm_buckets"] = [list(b["grads"]) for b in plan]
    bwd.attrs["__comm_codec"] = codec
    table = []
    for i, b in enumerate(plan):
        for g in b["grads"]:
            v = block.vars.get(g)
            if v is not None:
                v.attrs["__comm_bucket"] = i
        table.append({
            "bucket": i, "codec": codec, "grads": list(b["grads"]),
            "elems": b["elems"], "f32_bytes": b["f32_bytes"],
            "encoded_bytes": b["encoded_bytes"],
            "ring_f32": b["ring_f32"], "ring_encoded": b["ring_encoded"],
        })
    ctx.comm_stats["comm_buckets"] = len(plan)
    ctx.comm_table = table


def _lowp_feed_names(block) -> Set[str]:
    """float32 data vars that may flip to the low dtype: never consumed
    by a black-listed (f32-pinned) op in the forward region and not read
    inside a sub-block — quantizing a feed that flows straight into a
    pinned op would defeat the pinning at the graph input. The decision
    depends only on the block structure, so the executor's host-cast map
    (amp_feed_dtypes) and the pass always agree without the pass having
    run."""
    data = {n for n, v in block.vars.items()
            if v.is_data and v.dtype == "float32"}
    if not data:
        return data
    _, black = _amp_lists()
    data -= _sub_block_names(block.program)
    first_bwd = next((i for i, op in enumerate(block.ops)
                      if op.type == "backward"), len(block.ops))
    for op in block.ops[:first_bwd]:
        if op.type in black:
            data -= set(op.input_names())
        if not data:
            break
    return data


def amp_feed_dtypes(block, amp):
    """{float32 data-var name -> numpy dtype} for the low-precision feed
    path under ``amp`` (a resolve_amp result), or None. The executor and
    the prefetch paths (FeedPrefetcher/py_reader) cast these feeds
    HOST-side, so the h2d transfer itself halves."""
    if not amp:
        return None
    target = np.dtype(dtype_mod.convert_dtype(amp[0]))
    out = {n: target for n in _lowp_feed_names(block)}
    return out or None


def amp_feed_dtypes_cached(program, amp):
    """amp_feed_dtypes memoized on (program version, amp): the map only
    depends on the block structure, and the executor consults it every
    step — the O(ops) consumer scan must not ride the warm path."""
    version = getattr(program, "_version", None)
    cache = getattr(program, "_amp_feed_cache", None)
    if cache is not None and cache[0] == version and cache[1] == amp:
        return cache[2]
    out = amp_feed_dtypes(program.global_block, amp)
    program._amp_feed_cache = (version, amp, out)
    return out


def _amp_lists():
    """Static op-type white/black lists, derived from the dygraph amp
    module's lists plus the static-only spellings (fc lowers to `mul`;
    the plain `mean`/`sum`/`cross_entropy` kernels are loss-adjacent)."""
    from .. import amp as amp_mod

    white = set(amp_mod.WHITE_LIST) | {"mul"}
    black = set(amp_mod.BLACK_LIST) | {
        "mean", "sum", "cross_entropy", "batch_norm", "accuracy"}
    return white, black


def _is_random(op_type: str) -> bool:
    """Any kernel that draws from the RNG stream (explicit set plus a
    defensive substring net for delegate-registered random ops like
    uniform_random_s2 / sampling_id_s / sampled_*)."""
    return (op_type in _INDEXED_RNG_OPS or "random" in op_type
            or "dropout" in op_type or "sampl" in op_type)


def _rewrite_unsafe(op_type: str) -> bool:
    return (op_type in _SIDE_EFFECT_OPS or op_type in _CONTROL_FLOW_OPS
            or _is_random(op_type))


# ---------------------------------------------------------------------------
# report
# ---------------------------------------------------------------------------
@dataclass
class PassStat:
    name: str
    ops_before: int
    ops_after: int
    ms: float
    vars_dropped: int = 0

    @property
    def removed(self) -> int:
        return self.ops_before - self.ops_after


@dataclass
class PassReport:
    """What the pipeline did to one program: per-pass stats + totals.
    ``amp`` carries the mixed-precision counters (amp_casts_inserted/
    elided, amp_ops_lowprec, amp_master_params, ...) when the
    auto_mixed_precision pass ran."""
    stats: List[PassStat] = field(default_factory=list)
    ops_before: int = 0
    ops_after: int = 0
    ms: float = 0.0
    vars_dropped: int = 0
    amp: Dict[str, int] = field(default_factory=dict)
    # recompute segmentation counters (remat_segments, remat_stash_vars,
    # remat_recompute_vars, ...) + the per-segment table dump_passes
    # --remat prints
    remat: Dict[str, int] = field(default_factory=dict)
    remat_table: List[dict] = field(default_factory=list)
    # sharding-propagation counters (shard_vars_annotated,
    # shard_conflicts_replicated, shard_psums_inserted, pp_stages) + the
    # per-var spec table dump_passes --sharding prints
    shard: Dict[str, int] = field(default_factory=dict)
    shard_table: List[dict] = field(default_factory=list)
    # comm_bucketing counters (comm_buckets) + the per-bucket
    # size/order/codec table dump_passes --comm prints
    comm: Dict[str, int] = field(default_factory=dict)
    comm_table: List[dict] = field(default_factory=list)

    @property
    def removed(self) -> int:
        return self.ops_before - self.ops_after

    def table(self) -> str:
        """Aligned text table (tools/dump_passes.py output)."""
        lines = [f"{'Pass':<24}{'ops before':>12}{'ops after':>12}"
                 f"{'removed':>10}{'ms':>10}"]
        for s in self.stats:
            lines.append(f"{s.name:<24}{s.ops_before:>12}{s.ops_after:>12}"
                         f"{s.removed:>10}{s.ms:>10.2f}")
        lines.append(f"{'TOTAL':<24}{self.ops_before:>12}"
                     f"{self.ops_after:>12}{self.removed:>10}"
                     f"{self.ms:>10.2f}")
        if self.vars_dropped:
            lines.append(f"(+ {self.vars_dropped} unused VarDescs dropped)")
        if self.amp:
            lines.append("amp: " + "  ".join(
                f"{k}={v}" for k, v in sorted(self.amp.items())))
        if self.remat:
            lines.append("remat: " + "  ".join(
                f"{k}={v}" for k, v in sorted(self.remat.items())))
        if self.shard:
            lines.append("shard: " + "  ".join(
                f"{k}={v}" for k, v in sorted(self.shard.items())))
        if self.comm:
            lines.append("comm: " + "  ".join(
                f"{k}={v}" for k, v in sorted(self.comm.items())))
        return "\n".join(lines)

    def comm_bucket_table(self) -> str:
        """Aligned per-bucket table (tools/dump_passes.py --comm): the
        reduce order, member grads, element count, f32 vs encoded ring
        bytes per device."""
        if not self.comm_table:
            return "(no comm buckets)"
        lines = [f"{'bucket':>6}  {'codec':<6}{'elems':>10}"
                 f"{'ring f32':>12}{'ring enc':>12}{'saved':>8}  grads"]
        for row in self.comm_table:
            saved = (1 - row["ring_encoded"] / row["ring_f32"]
                     if row["ring_f32"] else 0.0)
            names = ", ".join(row["grads"][:4])
            if len(row["grads"]) > 4:
                names += f", … +{len(row['grads']) - 4}"
            lines.append(
                f"{row['bucket']:>6}  {row['codec']:<6}"
                f"{row['elems']:>10}{row['ring_f32']:>12}"
                f"{row['ring_encoded']:>12}{saved:>7.1%}  {names}")
        return "\n".join(lines)

    def shard_spec_table(self) -> str:
        """Aligned per-var PartitionSpec table (tools/dump_passes.py
        --sharding): the user hint, the propagated spec, and how it was
        resolved (hint / data batch default / propagated /
        conflict-replicated)."""
        if not self.shard_table:
            return "(no sharded vars)"

        def fmt(spec):
            if spec is None:
                return "-"
            return "(" + ", ".join(
                "+".join(e) if isinstance(e, (list, tuple)) else
                (str(e) if e is not None else "None")
                for e in spec) + ")"

        lines = [f"{'var':<38}{'hint':<16}{'spec':<22}resolution"]
        for row in self.shard_table:
            lines.append(f"{row['var']:<38}{fmt(row['hint']):<16}"
                         f"{fmt(row['spec']):<22}{row['src']}")
        if self.shard:
            lines.append("shard counters: " + "  ".join(
                f"{k}={v}" for k, v in sorted(self.shard.items())))
        return "\n".join(lines)

    def remat_segment_table(self) -> str:
        """Aligned per-segment table (tools/dump_passes.py --remat):
        ops per segment, stashed (boundary) vs recomputed (interior) var
        counts and their estimated bytes (batch dim -1 counted as 1, so
        the numbers are per-sample)."""
        if not self.remat_table:
            return "(no recompute segments)"
        lines = [f"{'seg':>4}{'ops':>6}{'stash_vars':>12}"
                 f"{'stash_bytes':>13}{'recomp_vars':>13}"
                 f"{'recomp_bytes':>14}  boundary"]
        for row in self.remat_table:
            lines.append(
                f"{row['seg']:>4}{row['ops']:>6}{row['stash_vars']:>12}"
                f"{row['stash_bytes']:>13}{row['recompute_vars']:>13}"
                f"{row['recompute_bytes']:>14}  "
                f"{row['boundary'] or '-'}")
        return "\n".join(lines)


# ---------------------------------------------------------------------------
# pass context
# ---------------------------------------------------------------------------
class _Ctx:
    def __init__(self, program: Program, feeds: Set[str],
                 fetches: Set[str]):
        self.program = program
        self.block = program.global_block
        self.feeds = set(feeds)
        self.fetches = set(fetches)
        self.persistable = {n for n, v in self.block.vars.items()
                            if v.persistable}
        self.data = {n for n, v in self.block.vars.items() if v.is_data}
        self.sub_reads = _sub_block_names(program)
        # names no rewrite may alias away: the executor (fetch/state/feed)
        # or a sub-block trace reads them by name
        self.protected = (self.feeds | self.fetches | self.persistable
                          | self.data | self.sub_reads)


def _sub_block_names(program: Program) -> Set[str]:
    """Every name referenced inside blocks[1:] or by control-flow attrs.
    cond/while kernels snapshot the WHOLE outer env, so any of these may
    be read by a sub-block trace regardless of block-0 def-use edges."""
    names: Set[str] = set()
    for blk in program.blocks[1:]:
        for op in blk.ops:
            names.update(op.input_names())
            names.update(op.output_names())
    for blk in program.blocks:
        for op in blk.ops:
            for key in ("loop_in", "body_out", "out_t", "out_f"):
                v = op.attrs.get(key)
                if isinstance(v, (list, tuple)):
                    names.update(str(n) for n in v)
            v = op.attrs.get("cond_out")
            if isinstance(v, str):
                names.add(v)
    return names


def _stamp_rng_slots(block) -> None:
    """Pin index-keyed RNG ops to their pre-pass stream so later
    removals can't shift a surviving op's random draw (bitwise parity
    between passes-on and passes-off)."""
    for i, op in enumerate(block.ops):
        if op.type in _INDEXED_RNG_OPS and "__rng_slot" not in op.attrs:
            op.attrs["__rng_slot"] = i


# ---------------------------------------------------------------------------
# constant folding
# ---------------------------------------------------------------------------
def _pass_constant_folding(ctx: _Ctx) -> None:
    from .kernels import KERNELS, ExecContext

    block = ctx.block
    const_env: Dict[str, np.ndarray] = {}
    fold_vals: Dict[int, Dict[str, np.ndarray]] = {}

    def _invalidate(op):
        for n in op.output_names():
            const_env.pop(n, None)

    for i, op in enumerate(block.ops):
        if (_rewrite_unsafe(op.type) or op.type in _ARRAY_OPS
                or any(n in ctx.protected for n in op.output_names())):
            _invalidate(op)
            continue
        fn = KERNELS.get(op.type)
        in_names = op.input_names()
        is_source = op.type in ("fill_constant", "assign_value") \
            and not in_names
        if fn is None or not (
                is_source or (in_names
                              and all(n in const_env for n in in_names))):
            _invalidate(op)
            continue
        try:
            ins = {slot: [const_env[n] for n in names]
                   for slot, names in op.inputs.items()}
            outs = fn(ins, op.attrs, ExecContext(rng_key=None))
            vals = {}
            for slot, names in op.outputs.items():
                produced = outs.get(slot)
                if produced is None or len(produced) != len(names):
                    raise ValueError("slot mismatch")
                for n, v in zip(names, produced):
                    arr = np.asarray(v)
                    if arr.size > _FOLD_MAX_ELEMS:
                        raise ValueError("too large to fold")
                    vals[n] = arr
        except Exception:
            _invalidate(op)
            continue
        fold_vals[i] = vals
        const_env.update(vals)

    if not fold_vals:
        return
    # a const needs materialization if a surviving op or a fetch reads it
    needed: Set[str] = set(ctx.fetches)
    consumed: Set[str] = set()
    for i, op in enumerate(block.ops):
        if i not in fold_vals:
            consumed.update(op.input_names())
    needed = (needed | consumed) & {n for vs in fold_vals.values()
                                    for n in vs}
    new_ops = []
    for i, op in enumerate(block.ops):
        if i not in fold_vals:
            new_ops.append(op)
            continue
        for slot, names in op.outputs.items():
            for n in names:
                if n in needed:
                    new_ops.append(_materialize_const(n, fold_vals[i][n]))
    block.ops = new_ops


def _materialize_const(name: str, arr: np.ndarray) -> OpDesc:
    dtype = dtype_mod.dtype_name(dtype_mod.convert_dtype(str(arr.dtype)))
    if arr.size and (arr == arr.flat[0]).all():
        val = arr.flat[0]
        val = bool(val) if arr.dtype == np.bool_ else (
            int(val) if np.issubdtype(arr.dtype, np.integer) else float(val))
        return OpDesc("fill_constant", {}, {"Out": [name]},
                      {"shape": [int(s) for s in arr.shape],
                       "dtype": dtype, "value": val})
    return OpDesc("assign_value", {}, {"Out": [name]},
                  {"values": arr.ravel().tolist(),
                   "shape": [int(s) for s in arr.shape], "dtype": dtype})


# ---------------------------------------------------------------------------
# identity elision
# ---------------------------------------------------------------------------
def _identity_source(op, block) -> Optional[str]:
    """Name this op's Out is a bit-exact alias of, or None."""
    if op.type == "assign":
        return (op.inputs.get("X") or [None])[0]
    if op.type == "scale" \
            and op.attrs.get("scale", 1.0) == 1.0 \
            and op.attrs.get("bias", 0.0) == 0.0:
        # x*1.0+0.0 promotes int arrays to float — only elide when the
        # input is declared floating
        src = (op.inputs.get("X") or [None])[0]
        desc = block.vars.get(src) if src else None
        if desc is not None and desc.dtype in _FLOAT_DTYPES:
            return src
    return None


def _def_counts(ctx: _Ctx) -> Dict[str, int]:
    """Definitions per name: op writes plus one implicit def for names
    the executor seeds into the env (feeds and scope-resident
    persistables). A name with >1 defs is reassigned somewhere — no
    rewrite may alias through it, because an alias captures the value
    at ONE point in time while the name's value changes."""
    counts: Dict[str, int] = defaultdict(int)
    for n in ctx.feeds | ctx.persistable:
        counts[n] += 1
    for op in ctx.block.ops:
        for n in op.output_names():
            counts[n] += 1
    return counts


def _pass_elide_identities(ctx: _Ctx) -> None:
    block = ctx.block
    defs = _def_counts(ctx)
    rename: Dict[str, str] = {}
    rev: Dict[str, Set[str]] = defaultdict(set)  # source -> aliases of it

    def res(n):
        while n in rename:
            n = rename[n]
        return n

    new_ops = []
    for op in block.ops:
        op.inputs = {s: [res(n) for n in names]
                     for s, names in op.inputs.items()}
        src = _identity_source(op, block)
        out = (op.outputs.get("Out") or [None])[0]
        if (src is not None and out is not None
                and out not in ctx.protected
                and defs.get(src, 0) <= 1):
            # single-def source: the alias is valid for the rest of the
            # block. A reassigned source would leave later readers of
            # `out` pointing at the WRONG (new) value — keep the op.
            if out != src:
                rename[out] = src
                rev[src].add(out)
            continue
        new_ops.append(op)
        for n in op.output_names():
            # redefinition kills aliases OF this name and (belt &
            # braces — unreachable under the single-def guard) aliases
            # pointing at it
            rename.pop(n, None)
            for alias in rev.pop(n, ()):
                rename.pop(alias, None)
    block.ops = new_ops


# ---------------------------------------------------------------------------
# common-subexpression elimination
# ---------------------------------------------------------------------------
def _pass_cse(ctx: _Ctx) -> None:
    block = ctx.block
    rename: Dict[str, str] = {}
    seen: Dict[str, OpDesc] = {}
    uses: Dict[str, Set[str]] = defaultdict(set)  # name -> keys touching it
    # Merging a duplicate UPSTREAM of a backward op restructures vjp
    # cotangent accumulation (two gradient paths collapse into one
    # doubled path) — mathematically equal, bitwise different. XLA owns
    # training-graph CSE; source-level CSE only merges past the last
    # backward op (and everywhere on inference programs), keeping the
    # passes-on/off bitwise-parity gate exact.
    last_bwd = max((i for i, op in enumerate(block.ops)
                    if op.type == "backward"), default=-1)
    defs = _def_counts(ctx)

    def res(n):
        while n in rename:
            n = rename[n]
        return n

    def _kill(name):
        rename.pop(name, None)
        for key in uses.pop(name, ()):
            seen.pop(key, None)

    new_ops = []
    for i, op in enumerate(block.ops):
        op.inputs = {s: [res(n) for n in names]
                     for s, names in op.inputs.items()}
        outs = op.output_names()
        mergeable = (i > last_bwd and not _rewrite_unsafe(op.type)
                     and outs
                     and not any(n in ctx.protected for n in outs))
        key = None
        if mergeable:
            key = json.dumps(
                [op.type,
                 sorted((s, ns) for s, ns in op.inputs.items()),
                 sorted(_attrs_to_json(op.attrs).items())],
                sort_keys=True, default=str)
            prev = seen.get(key)
            # merging aliases this op's outputs to prev's — only valid
            # when prev's outputs are single-def (a later reassignment
            # of a prev output would redirect the alias to the WRONG
            # value; see _def_counts)
            if prev is not None and all(
                    s in prev.outputs
                    and len(prev.outputs[s]) == len(ns)
                    and all(defs.get(pn, 0) <= 1
                            for pn in prev.outputs[s])
                    for s, ns in op.outputs.items()):
                for s, ns in op.outputs.items():
                    for n, pn in zip(ns, prev.outputs[s]):
                        if n != pn:
                            rename[n] = pn
                continue
        new_ops.append(op)
        # this op redefines its outputs: invalidate aliases and any
        # cached exprs reading/producing those names FIRST, then record
        # the op itself (its own entry must survive the kill)
        for n in op.output_names():
            _kill(n)
        if key is not None:
            seen[key] = op
            for n in set(op.input_names()) | set(outs):
                uses[n].add(key)
    block.ops = new_ops


# ---------------------------------------------------------------------------
# elementwise + activation fusion
# ---------------------------------------------------------------------------
def _pass_fuse_elemwise_act(ctx: _Ctx) -> None:
    block = ctx.block
    ops = block.ops
    readers: Dict[str, List[int]] = defaultdict(list)
    writers: Dict[str, List[int]] = defaultdict(list)
    for i, op in enumerate(ops):
        for n in op.input_names():
            readers[n].append(i)
        for n in op.output_names():
            writers[n].append(i)
    drop: Set[int] = set()
    for i, op in enumerate(ops):
        if op.type not in _FUSABLE_BINARY or i in drop:
            continue
        out = (op.outputs.get("Out") or [None])[0]
        if (out is None or out in ctx.protected
                or len(writers.get(out, ())) != 1
                or len(readers.get(out, ())) != 1):
            continue
        j = readers[out][0]
        if j <= i or j in drop:
            continue
        act = ops[j]
        if (act.type not in _FUSABLE_ACTS
                or act.inputs.get("X") != [out]
                or len(act.input_names()) != 1):
            continue
        act_out = (act.outputs.get("Out") or [None])[0]
        if act_out is None or len(writers.get(act_out, ())) != 1:
            continue
        # fusing moves the act_out write from j up to i; if act_out is
        # env-seeded (feed/persistable), a reader before j meant the
        # seeded value — don't move the write past it
        if act_out in (ctx.feeds | ctx.persistable) and any(
                k < j for k in readers.get(act_out, ())):
            continue
        act_attrs = {k: v for k, v in act.attrs.items()
                     if k != "__rng_slot"}
        ops[i] = OpDesc(
            "fused_elemwise_activation",
            inputs={"X": op.inputs["X"], "Y": op.inputs["Y"]},
            outputs={"Out": [act_out]},
            attrs={"functor_list": [op.type, act.type],
                   "axis": op.attrs.get("axis", -1),
                   "act_attrs": act_attrs})
        drop.add(j)
    if drop:
        block.ops = [op for k, op in enumerate(ops) if k not in drop]


# ---------------------------------------------------------------------------
# dead-code elimination + var-table cleanup
# ---------------------------------------------------------------------------
def _pass_dce(ctx: _Ctx) -> None:
    block = ctx.block
    live = set(ctx.fetches) | ctx.persistable | ctx.sub_reads
    keep: List[OpDesc] = []
    for op in reversed(block.ops):
        if op.type in _SIDE_EFFECT_OPS or set(op.output_names()) & live:
            keep.append(op)
            live |= set(op.input_names())
            # NOTE: defs are not killed — this IR allows name
            # reassignment, so earlier writers stay conservatively live
    keep.reverse()
    block.ops = keep


def _pass_drop_unused_vars(ctx: _Ctx) -> int:
    referenced = set(ctx.feeds) | set(ctx.fetches) | ctx.sub_reads
    for blk in ctx.program.blocks:
        for op in blk.ops:
            referenced.update(op.input_names())
            referenced.update(op.output_names())
    blk0 = ctx.block
    before = len(blk0.vars)
    blk0.vars = {n: v for n, v in blk0.vars.items()
                 if n in referenced or v.persistable or v.is_data}
    return before - len(blk0.vars)


# ---------------------------------------------------------------------------
# recompute segmentation (activation rematerialization)
# ---------------------------------------------------------------------------
def _var_nbytes(block, name) -> int:
    """Estimated payload bytes of a var from its VarDesc (dynamic -1
    dims counted as 1 — the estimate is per-sample, good enough for the
    stash-vs-recompute segment table)."""
    v = block.vars.get(name)
    shape = getattr(v, "shape", None)
    if not shape:
        return 0
    n = 1
    for d in shape:
        n *= max(1, int(d))
    try:
        item = np.dtype(dtype_mod.convert_dtype(v.dtype)).itemsize
    except Exception:
        item = 4
    return n * item


def _pass_recompute(ctx: _Ctx) -> None:
    """Partition the forward region (ops before the first `backward` op)
    into checkpoint segments and stamp each op with ``__remat_seg``.

    The executor's backward lowering (backward.py run_backward_op) wraps
    each stamped segment's re-trace in ``jax.checkpoint``: only segment
    BOUNDARY values are stashed for the backward pass, interior
    activations are recomputed — Chen et al. sublinear memory, compiled.

    Boundaries come from user checkpoint var names (the reference
    RecomputeConfig.checkpoints: a segment ends after the op producing a
    checkpoint var) or, when none are given, from an every-N-ops split
    into ~sqrt(#ops) segments (``recompute_segments`` overrides the
    count). The stamp is pure bookkeeping — no op is added, removed or
    reordered, so passes-on/off stays bitwise (RNG streams are pinned by
    ``__rng_slot`` independently); jax.checkpoint replays random kernels
    with identical fold_in keys, which is what makes recomputed dropout
    draw the same mask (the tested invariant).

    The stamps change the program's content hash, so remat-on and -off
    can never share an executable."""
    block = ctx.block
    first_bwd = next((i for i, op in enumerate(block.ops)
                      if op.type == "backward"), None)
    if first_bwd is None:
        return
    bwd_op = block.ops[first_bwd]
    cps = set(ctx.remat_checkpoints)
    cps.update(str(c) for c in (bwd_op.attrs.get("checkpoints") or ()))
    fwd = [i for i in range(first_bwd)
           if block.ops[i].type not in ("feed", "fetch")]
    if len(fwd) < 2:
        return
    seg_of: Dict[int, int] = {}
    boundary_after: Dict[int, str] = {}
    if cps:
        seg = 0
        for i in fwd:
            seg_of[i] = seg
            hit = set(block.ops[i].output_names()) & cps
            if hit:
                boundary_after[seg] = sorted(hit)[0]
                seg += 1
    else:
        n = len(fwd)
        nseg = ctx.remat_nseg or max(2, int(round(n ** 0.5)))
        nseg = max(1, min(nseg, n))
        per = -(-n // nseg)  # ceil
        for j, i in enumerate(fwd):
            seg_of[i] = j // per
    for i, s in seg_of.items():
        block.ops[i].attrs["__remat_seg"] = s
    nseg = max(seg_of.values()) + 1

    # stash vs recompute accounting: a segment's output consumed by a
    # LATER segment (or live at the backward boundary) is a stashed
    # residual; one consumed only inside its segment is recomputed
    consumers: Dict[str, List[int]] = defaultdict(list)
    for i in fwd:
        for name in block.ops[i].input_names():
            consumers[name].append(i)
    loss_name = (bwd_op.inputs.get("Loss") or [None])[0]
    stats = ctx.remat_stats
    stats["remat_segments"] = nseg
    table = []
    for s in range(nseg):
        seg_ops = [i for i in fwd if seg_of[i] == s]
        stash, recomp = set(), set()
        for i in seg_ops:
            for name in block.ops[i].output_names():
                later = any(seg_of.get(j, nseg) > s
                            for j in consumers.get(name, ()))
                crosses = later or name == loss_name or s < nseg - 1 and (
                    name in ctx.protected)
                (stash if crosses else recomp).add(name)
        stats["remat_stash_vars"] += len(stash)
        stats["remat_recompute_vars"] += len(recomp)
        table.append({
            "seg": s, "ops": len(seg_ops),
            "stash_vars": len(stash),
            "stash_bytes": sum(_var_nbytes(block, n) for n in stash),
            "recompute_vars": len(recomp),
            "recompute_bytes": sum(_var_nbytes(block, n) for n in recomp),
            "boundary": boundary_after.get(s, ""),
        })
    ctx.remat_table = table


# ---------------------------------------------------------------------------
# GSPMD sharding propagation (PartitionSpec annotation over VarDescs)
# ---------------------------------------------------------------------------
# op families with dedicated propagation rules; anything else stops
# propagation (outputs replicated) without counting a conflict
_MATMUL_OPS = {"mul", "matmul", "matmul_v2"}
_SHARD_UNARY = (_FUSABLE_ACTS
                | {"cast", "scale", "assign", "dropout", "abs", "log",
                   "log_softmax_none", "clip", "pow"})
_SHARD_BINARY = _FUSABLE_BINARY | {"elementwise_pow",
                                   "fused_elemwise_activation"}
_SHARD_REDUCE = {"reduce_mean", "reduce_sum", "reduce_max", "reduce_min",
                 "reduce_prod"}
_SHARD_FULL_REDUCE = {"mean"}
_SHARD_LOSSES = {"softmax_with_cross_entropy", "cross_entropy",
                 "sigmoid_cross_entropy_with_logits"}


def _spec_to_json(spec):
    """Spec tuple -> JSON-safe list (axis tuples become lists)."""
    return [list(e) if isinstance(e, tuple) else e for e in spec]


def _spec_from_json(spec):
    """Inverse of _spec_to_json; None stays None."""
    if spec is None:
        return None
    return tuple(tuple(e) if isinstance(e, list) else e for e in spec)


def _spec_axes(entry):
    """Axis names of one spec entry as a tuple (None -> ())."""
    if entry is None:
        return ()
    return tuple(entry) if isinstance(entry, tuple) else (entry,)


def _fit_spec(spec, shape, axis_sizes):
    """Clip/pad ``spec`` to ``shape``'s rank and drop entries whose axis
    product does not divide the dim (the shard_params rule — an uneven
    split would change numerics, replication never does). Dynamic dims
    (-1/None shape) keep their entry: the executor re-checks against the
    live array."""
    nd = len(shape) if shape is not None else len(spec)
    spec = tuple(spec[:nd]) + (None,) * (nd - len(spec))
    fixed = []
    for i, entry in enumerate(spec):
        axes = tuple(a for a in _spec_axes(entry) if a in axis_sizes)
        if not axes:
            fixed.append(None)
            continue
        size = 1
        for a in axes:
            size *= axis_sizes[a]
        dim = shape[i] if shape is not None and i < len(shape) else None
        if dim is not None and int(dim) >= 0 and int(dim) % size != 0:
            fixed.append(None)
        else:
            fixed.append(axes if len(axes) > 1 else axes[0])
    return tuple(fixed)


def _batch_entry(axis_sizes):
    """The default batch-dim spec entry: every data-like mesh axis."""
    from ..parallel.mesh import DATA_AXIS_NAMES

    axes = tuple(a for a in DATA_AXIS_NAMES if a in axis_sizes)
    return axes if len(axes) > 1 else (axes[0] if axes else None)


def _pass_shard_propagation(ctx: _Ctx) -> None:
    """Propagate PartitionSpecs from the user's sharding hints (plus the
    batch-over-'dp' feed default) across every VarDesc and stamp the
    result as ``__sharding_spec`` attrs — the cross-chip sibling of the
    AMP/remat stamps (pure bookkeeping, no op added or reordered, but
    the stamps join the program's content hash so hint flips can never
    hit a stale executable).

    Op-level rules (the naive-sharding-tree / pjit in_shardings pattern):

    - matmul/fc (`mul`): a column-parallel weight hint ``(None, 'tp')``
      shards the output's feature dim; a row-parallel hint
      ``('tp', None)`` shards the CONTRACTED dim — the output needs a
      psum over 'tp', counted in ``shard_psums_inserted`` and stamped as
      ``__psum_axes`` on the op (XLA's SPMD partitioner materializes it)
    - elementwise / activation / cast pass specs through; binary ops
      merge per-dim, disagreeing non-replicated dims resolve to
      replication (``shard_conflicts_replicated``)
    - reductions and losses drop the reduced dims' sharding (a sharded
      reduced dim is itself a psum) and keep surviving batch dims
    - the batch dim of every data var rides the mesh's data axes
      ('dp'/'data'); `backward` hands each param's spec to its grad, and
      optimizer update ops keep the param's spec on the updated output

    The interior specs are annotations (XLA propagates from the jit
    boundary); the executor turns the BOUNDARY stamps — feeds and hinted
    persistables — into real NamedSharding in/out/state shardings via
    :func:`shard_boundary_shardings`, which derives the same specs by
    construction."""
    block = ctx.block
    axis_sizes = dict(ctx.shard_axes)
    hints = dict(ctx.shard_hints)
    stats = ctx.shard_stats
    specs: Dict[str, tuple] = {}
    source: Dict[str, str] = {}

    def shape_of(n):
        v = block.vars.get(n)
        return getattr(v, "shape", None)

    def set_spec(n, spec, src):
        spec = _fit_spec(spec, shape_of(n), axis_sizes)
        if any(e is not None for e in spec):
            specs[n] = spec
            source.setdefault(n, src)
        else:
            specs.pop(n, None)

    def merge(a, b):
        """Per-dim join of two specs; disagreement replicates that dim.
        Broadcasting aligns trailing dims (numpy rule), so the shorter
        spec is right-aligned."""
        if not a:
            return b, 0
        if not b:
            return a, 0
        la, lb = len(a), len(b)
        n = max(la, lb)
        a = (None,) * (n - la) + tuple(a)
        b = (None,) * (n - lb) + tuple(b)
        out, conflicts = [], 0
        for ea, eb in zip(a, b):
            if ea == eb or eb is None:
                out.append(ea)
            elif ea is None:
                out.append(eb)
            else:
                out.append(None)
                conflicts += 1
        return tuple(out), conflicts

    # seeds: user hints, then the batch default on data (feed) vars
    for name, spec in hints.items():
        if name in block.vars:
            set_spec(name, spec, "hint")
    batch = _batch_entry(axis_sizes)
    if batch is not None:
        for name, v in block.vars.items():
            if v.is_data and name not in hints and v.shape:
                set_spec(name, (batch,) + (None,) * (len(v.shape) - 1),
                         "data")

    for op in block.ops:
        t = op.type
        if t in ("feed", "fetch"):
            continue
        if t == "backward":
            for p, g in zip(op.inputs.get("Params", ()),
                            op.outputs.get("Grads", ())):
                sp = specs.get(p)
                if sp:
                    set_spec(g, sp, "propagated")
            continue
        if t in _AMP_GATED_UPDATE_OPS or t == "adamw":
            # the updated param (and any same-shaped slot outputs) keep
            # the param's spec — state residency must not flip layouts
            psp = specs.get((op.inputs.get("Param") or [None])[0])
            for n in op.output_names():
                if psp and shape_of(n) == shape_of(
                        (op.inputs.get("Param") or [None])[0]):
                    set_spec(n, psp, "propagated")
            continue
        if t in _MATMUL_OPS:
            x = (op.inputs.get("X") or [None])[0]
            y = (op.inputs.get("Y") or [None])[0]
            sx, sy = specs.get(x), specs.get(y)
            if t == "mul":
                ncol = int(op.attrs.get("x_num_col_dims", 1))
                contracted = list(_spec_axes(e) for e in (sx or ())[ncol:])
                lead = tuple((sx or ())[:ncol]) + (None,) * (
                    ncol - len((sx or ())[:ncol]))
                tail = (sy[-1],) if sy else (None,)
                if sy and len(sy) > 1:
                    contracted.extend(_spec_axes(e) for e in sy[:-1])
            else:
                if op.attrs.get("transpose_X") or \
                        op.attrs.get("transpose_Y") or \
                        op.attrs.get("trans_x") or op.attrs.get("trans_y"):
                    for n in op.output_names():
                        specs.pop(n, None)
                    continue
                lead = tuple((sx or ())[:-1]) if sx else ()
                tail = (sy[-1],) if sy else (None,)
                contracted = [_spec_axes((sx or (None,))[-1])]
                if sy and len(sy) > 1:
                    contracted.append(_spec_axes(sy[-2]))
            psum_axes = sorted({a for axes in contracted for a in axes})
            for n in op.output_names():
                # LEFT-pad to the output's rank: the tail entry belongs
                # to the LAST (feature) dim — _fit_spec right-pads, and
                # with an untracked X (lead shorter than rank-1) that
                # would drift the feature axis onto a batch dim
                spec = lead + tail
                nd = len(shape_of(n) or ())
                if nd and len(spec) < nd:
                    spec = (None,) * (nd - len(spec)) + spec
                set_spec(n, spec, "propagated")
            if psum_axes:
                op.attrs["__psum_axes"] = psum_axes
                stats["shard_psums_inserted"] += 1
            continue
        if t in _SHARD_BINARY:
            x = (op.inputs.get("X") or [None])[0]
            y = (op.inputs.get("Y") or [None])[0]
            out_spec, conflicts = merge(specs.get(x), specs.get(y))
            if conflicts:
                stats["shard_conflicts_replicated"] += conflicts
                for n in op.output_names():
                    source.setdefault(n, "conflict")
            for n in op.output_names():
                set_spec(n, out_spec or (), "propagated")
            continue
        if t in _SHARD_UNARY:
            x = (op.inputs.get("X") or [None])[0]
            sp = specs.get(x)
            for n in op.output_names():
                if sp and shape_of(n) is not None and \
                        len(shape_of(n)) != len(sp):
                    specs.pop(n, None)   # rank change (e.g. dropout Mask)
                else:
                    set_spec(n, sp or (), "propagated")
            continue
        if t in _SHARD_REDUCE or t in _SHARD_FULL_REDUCE:
            x = (op.inputs.get("X") or [None])[0]
            sp = specs.get(x)
            if not sp:
                for n in op.output_names():
                    specs.pop(n, None)
                continue
            if t in _SHARD_FULL_REDUCE:
                reduced = range(len(sp))
                kept: list = []
            else:
                dims = op.attrs.get("dim")
                if dims is None:
                    reduced = range(len(sp))
                else:
                    dims = [dims] if isinstance(dims, int) else list(dims)
                    reduced = {d % len(sp) for d in dims}
                keep_dim = bool(op.attrs.get("keep_dim"))
                kept = [None if i in reduced else e
                        for i, e in enumerate(sp)] if keep_dim else \
                    [e for i, e in enumerate(sp) if i not in reduced]
            if any(_spec_axes(sp[i]) for i in reduced):
                # reducing a sharded dim IS a cross-device psum
                stats["shard_psums_inserted"] += 1
                op.attrs["__psum_axes"] = sorted(
                    {a for i in reduced for a in _spec_axes(sp[i])})
            for n in op.output_names():
                set_spec(n, tuple(kept), "propagated")
            continue
        if t in _SHARD_LOSSES:
            lg = (op.inputs.get("Logits") or op.inputs.get("X")
                  or [None])[0]
            sp = specs.get(lg)
            if sp and _spec_axes(sp[-1]):
                stats["shard_psums_inserted"] += 1
                op.attrs["__psum_axes"] = sorted(_spec_axes(sp[-1]))
            out_spec = (tuple(sp[:-1]) + (None,)) if sp else ()
            for n in op.output_names():
                set_spec(n, out_spec, "propagated")
            continue
        if t == "moe":
            # expert parallelism (ISSUE 19): when the mesh carries an
            # "ep" axis that divides the expert count, stamp the
            # exchange plan — the moe kernel compiles the explicit
            # all_to_all dispatch/combine from it and the cost model
            # charges both exchanges into comm_bytes. Out keeps the
            # token spec of X; AuxLoss is a replicated scalar.
            w1 = (op.inputs.get("W1") or [None])[0]
            e = int((shape_of(w1) or (0,))[0] or 0)
            n_ep = int(axis_sizes.get("ep", 0) or 0)
            if n_ep > 1 and e and e % n_ep == 0:
                # carry the FULL mesh shape: the kernel's shard_map
                # must run on the executor's own mesh (mesh_for_shape
                # caches, so the same shape returns the same Mesh)
                op.attrs["__moe_ep"] = [
                    "ep", n_ep,
                    [[str(a), int(s)] for a, s in axis_sizes.items()]]
                stats["moe_ep_stamped"] += 1
            x = (op.inputs.get("X") or [None])[0]
            sp = specs.get(x)
            out_n = (op.outputs.get("Out") or [None])[0]
            if out_n is not None:
                set_spec(out_n, sp or (), "propagated")
            aux_n = (op.outputs.get("AuxLoss") or [None])[0]
            if aux_n is not None:
                specs.pop(aux_n, None)
            continue
        # unknown op: propagation stops, outputs replicated
        for n in op.output_names():
            specs.pop(n, None)

    table = []
    for name in sorted(set(specs) | set(hints)):
        spec = specs.get(name)
        if spec is not None and name in block.vars:
            block.vars[name].attrs["__sharding_spec"] = _spec_to_json(spec)
        table.append({
            "var": name,
            "hint": _spec_to_json(tuple(hints[name]))
            if name in hints else None,
            "spec": _spec_to_json(spec) if spec else None,
            "src": source.get(name,
                              "replicated" if spec is None else
                              "propagated"),
        })
    stats["shard_vars_annotated"] += sum(
        1 for name in specs if name in block.vars)
    ctx.shard_table = table


def _pass_pipeline_stages(ctx: _Ctx) -> None:
    """Split the forward region into ``pipeline_stages`` contiguous
    stages and stamp each forward op with ``__pp_stage`` — the remat
    pass's even-split mechanics, reused as GPipe stage boundaries. The
    executor's ``_pp_step_fn`` drives gpipe_schedule over the stamped op
    ranges; the stamps join the content hash so stage-count flips
    recompile."""
    block = ctx.block
    n_stages = ctx.pp_stages
    first_bwd = next((i for i, op in enumerate(block.ops)
                      if op.type == "backward"), None)
    if first_bwd is None:
        return
    fwd = [i for i in range(first_bwd)
           if block.ops[i].type not in ("feed", "fetch")]
    if len(fwd) < n_stages:
        return
    per = -(-len(fwd) // n_stages)  # ceil
    for j, i in enumerate(fwd):
        block.ops[i].attrs["__pp_stage"] = j // per
    ctx.shard_stats["pp_stages"] = max(
        block.ops[i].attrs["__pp_stage"] for i in fwd) + 1


def shard_boundary_shardings(mesh, block, feed, persist_names,
                             shard_cfg, peek=None):
    """The jit-boundary sharding map for one sharded build: ``{feed name
    -> NamedSharding, persistable name -> NamedSharding, '__param__' ->
    replicated fallback}`` — what Executor._build installs as
    in/out/state shardings and _gather_state uses for the one-time state
    upload.

    Specs derive from the SAME seeds the shard_propagation pass stamps
    (hints for persistables, hints-else-batch-axes for feeds), checked
    against the live array shapes for divisibility — so the map agrees
    with the stamped program by construction, and a cache-hit step (no
    pass run) still shards identically."""
    from jax.sharding import NamedSharding, PartitionSpec

    axes, hints_t = shard_cfg
    axis_sizes = dict(axes)
    hints = dict(hints_t)

    def named(spec):
        return NamedSharding(mesh, PartitionSpec(*spec))

    out = {"__param__": named(()), "__rng__": named(())}
    batch = _batch_entry(axis_sizes)
    for k, v in feed.items():
        shape = tuple(getattr(v, "shape", ()) or ())
        spec = hints.get(k)
        if spec is None:
            spec = ((batch,) + (None,) * (len(shape) - 1)
                    if batch is not None and shape else ())
        out[k] = named(_fit_spec(spec, shape, axis_sizes))
    for n in persist_names:
        spec = hints.get(n)
        if not spec:
            continue
        arr = peek(n) if peek is not None else None
        shape = tuple(getattr(arr, "shape", None)
                      or getattr(block.vars.get(n), "shape", None) or ())
        out[n] = named(_fit_spec(spec, shape, axis_sizes))
    return out


# ---------------------------------------------------------------------------
# auto mixed precision (bf16/fp16 compute, f32 master weights)
# ---------------------------------------------------------------------------
def _pass_auto_mixed_precision(ctx: _Ctx) -> None:
    """Rewrite the forward region (ops before the first `backward` op —
    the same boundary rule CSE respects) for low-precision compute:

    - white-listed ops (matmul family — the MXU win) get `cast` ops on
      their float32 inputs and emit low-precision outputs
    - black-listed ops (softmax/norm/reductions/loss) are pinned f32:
      low-precision inputs are cast back up
    - gray ops follow their inputs: once any float input is low
      precision the op runs low (remaining f32 float inputs cast down);
      pure-f32 gray ops are untouched under O1. O2 lowers gray ops too.
    - parameters stay f32 MASTER WEIGHTS: the inserted cast materializes
      a low-precision copy inside the compiled step, the param buffer in
      the executor's device-resident state is untouched and optimizer
      ops keep updating it in f32
    - float32 feed (data) vars flip to the low dtype — the executor and
      prefetch paths cast host-side, halving h2d bytes
    - protected names (fetches, persistables, sub-block reads, feeds)
      keep their declared dtype: the producing op writes a low-precision
      alias and a cast-up restores the original name
    - under fp16, the loss is scaled before `backward` and the grads run
      through a check_finite_and_unscale kernel (static loss scaling;
      bf16 needs none — dygraph GradScaler stays the dynamic-scale path)

    A cleanup sub-pass dedups identical casts (CSE-style, but valid in
    the forward region because a cast is deterministic and random-free)
    and elides exact lowp->f32->lowp round trips.
    """
    from .kernels import KERNELS

    block = ctx.block
    lowp = ctx.amp_dtype
    level = ctx.amp_level
    scale = ctx.amp_scale if lowp == "float16" else 0.0
    tag = "bf16" if lowp == "bfloat16" else "fp16"
    white, black = _amp_lists()
    stats = ctx.amp_stats
    first_bwd = next((i for i, op in enumerate(block.ops)
                      if op.type == "backward"), len(block.ops))
    masters: Set[str] = set()
    cur: Dict[str, str] = {}

    def declared(n):
        v = block.vars.get(n)
        return getattr(v, "dtype", None)

    def dtype_of(n):
        d = cur.get(n)
        return d if d is not None else declared(n)

    # low-precision feed path: the executor/prefetchers cast these
    # host-side (amp_feed_dtypes — same consumer-aware rule), so the
    # trace sees them low already; feeds reaching a black-listed op
    # stay f32 (the pinning contract holds at graph inputs too)
    for n in sorted(_lowp_feed_names(block)):
        block.vars[n].dtype = lowp
        cur[n] = lowp
        stats["amp_lowprec_feeds"] += 1

    new_ops: List[OpDesc] = []
    cast_cache: Dict[tuple, str] = {}
    cache_by_src: Dict[str, List[tuple]] = defaultdict(list)

    def _kill_src(name):
        # (re)definition of `name`: cached casts of it are stale
        for key in cache_by_src.pop(name, ()):
            cast_cache.pop(key, None)

    def emit_cast(src, dt):
        key = (src, dt)
        alias = cast_cache.get(key)
        if alias is not None:
            return alias
        alias = f"{src}@amp.{'f32' if dt == 'float32' else tag}"
        sdesc = block.vars.get(src)
        block.vars[alias] = VarDesc(alias, getattr(sdesc, "shape", None),
                                    dt)
        new_ops.append(OpDesc("cast", {"X": [src]}, {"Out": [alias]},
                              {"out_dtype": dt}))
        cast_cache[key] = alias
        cache_by_src[src].append(key)
        cur[alias] = dt
        stats["amp_casts_inserted"] += 1
        return alias

    def cast_inputs(op, want, only_from):
        for s, ns in list(op.inputs.items()):
            row = []
            for n in ns:
                d = dtype_of(n)
                if d in only_from and d != want:
                    v = block.vars.get(n)
                    if getattr(v, "persistable", False) \
                            and want in _LOW_PRECISION:
                        masters.add(n)  # f32 master, lowp copy in-step
                    row.append(emit_cast(n, want))
                else:
                    row.append(n)
            op.inputs[s] = row

    def lower_outputs(op):
        """Mark op outputs low-precision; protected names keep their
        declared dtype through a cast-up under the original name."""
        post = []
        for s, ns in op.outputs.items():
            for j, n in enumerate(ns):
                d0 = declared(n)
                if d0 not in _FLOAT_DTYPES:
                    continue  # int/bool/undeclared outputs untouched
                if n in ctx.protected:
                    keep = d0   # guaranteed float by the guard above
                    alias = f"{n}@amp.{tag}.out"
                    block.vars[alias] = VarDesc(
                        alias, getattr(block.vars.get(n), "shape", None),
                        lowp)
                    ns[j] = alias
                    cur[alias] = lowp
                    post.append(OpDesc("cast", {"X": [alias]},
                                       {"Out": [n]}, {"out_dtype": keep}))
                    stats["amp_casts_inserted"] += 1
                    cur[n] = keep
                else:
                    cur[n] = lowp
                    v = block.vars.get(n)
                    if v is not None and v.dtype in _FLOAT_DTYPES:
                        v.dtype = lowp
        return post

    found_inf_name = None
    for i, op in enumerate(block.ops):
        t = op.type
        if i == first_bwd and t == "backward" and scale > 0:
            # fp16 loss scaling: grads = S * dL/dp survive the fp16
            # cotangent range; check_finite_and_unscale divides by S
            # (exact for pow-2 S) and zeroes non-finite grads so the
            # optimizer update degrades to a no-op for that step
            loss_name = (op.inputs.get("Loss") or [None])[0]
            grads = list(op.outputs.get("Grads", []))
            if loss_name is not None and grads:
                sname = f"{loss_name}@amp.scaled"
                ldesc = block.vars.get(loss_name)
                block.vars[sname] = VarDesc(
                    sname, getattr(ldesc, "shape", None), "float32")
                new_ops.append(OpDesc("scale", {"X": [loss_name]},
                                      {"Out": [sname]},
                                      {"scale": float(scale)}))
                op.inputs = dict(op.inputs, Loss=[sname])
                new_ops.append(op)
                fi = "found_inf@amp"
                block.vars[fi] = VarDesc(fi, (1,), "bool")
                new_ops.append(OpDesc(
                    "check_finite_and_unscale", {"X": grads},
                    {"Out": list(grads), "FoundInfinite": [fi]},
                    {"scale": float(scale)}))
                found_inf_name = fi
                stats["amp_loss_scaled"] += 1
                continue
        if i >= first_bwd or t in ("feed", "fetch"):
            # found_inf gates the update ops: a non-finite step must not
            # decay Adam/momentum accumulators or advance beta-pows —
            # the GradScaler skip-step semantics, compiled
            if found_inf_name is not None and t in _AMP_GATED_UPDATE_OPS:
                op.inputs = dict(op.inputs,
                                 FoundInfinite=[found_inf_name])
            new_ops.append(op)
            continue
        if (t in _SIDE_EFFECT_OPS or t in _CONTROL_FLOW_OPS
                or t in _ARRAY_OPS):
            new_ops.append(op)
            for n in op.output_names():
                cur.pop(n, None)
                _kill_src(n)
            continue
        if t == "cast":
            new_ops.append(op)
            od = op.attrs.get("out_dtype")
            for n in op.output_names():
                if od in _FLOAT_DTYPES:
                    cur[n] = od
                _kill_src(n)
            continue
        if _is_random(t) or t in ("fill_constant", "assign_value"):
            # bookkeeping only: random ops must not gain cast inputs
            # (their draw is keyed, not their operands) and constants
            # keep their attr dtype — a white consumer casts them, and
            # constant folding then folds the pair into a low constant
            new_ops.append(op)
            in_f = [d for d in (dtype_of(n) for n in op.input_names())
                    if d in _FLOAT_DTYPES]
            out_d = in_f[0] if in_f else op.attrs.get("dtype")
            for n in op.output_names():
                # float-declared outputs only: stamping an int output
                # (dropout Mask, random int fills) would draw spurious
                # casts onto its consumers
                if out_d in _FLOAT_DTYPES and declared(n) in _FLOAT_DTYPES:
                    cur[n] = out_d
                _kill_src(n)
            continue
        in_f = [d for d in (dtype_of(n) for n in op.input_names())
                if d in _FLOAT_DTYPES]
        if t in black:
            cast_inputs(op, "float32", _LOW_PRECISION)
            new_ops.append(op)
            for n in op.output_names():
                if declared(n) in _FLOAT_DTYPES or \
                        cur.get(n) in _FLOAT_DTYPES:
                    cur[n] = "float32"
                    v = block.vars.get(n)
                    if v is not None and v.dtype in _LOW_PRECISION:
                        v.dtype = "float32"
                _kill_src(n)
            continue
        lower = bool(in_f) and t in KERNELS and (
            t in white or level == "O2" or lowp in in_f)
        if lower:
            cast_inputs(op, lowp, {"float32"})
            stats["amp_ops_lowprec"] += 1
            post = lower_outputs(op)
            new_ops.append(op)
            new_ops.extend(post)
            for n in op.output_names():
                _kill_src(n)
            for c in post:
                _kill_src(c.outputs["Out"][0])
        else:
            new_ops.append(op)
            for n in op.output_names():
                # declared-float outputs only — an op with float inputs
                # can still emit ints (arg_max/top_k indices, shape),
                # and a float stamp there would cast indices downstream
                if in_f and declared(n) in _FLOAT_DTYPES:
                    cur[n] = ("float32"
                              if "float32" in in_f or "float64" in in_f
                              else in_f[0])
                _kill_src(n)
    block.ops = new_ops
    stats["amp_master_params"] += len(masters)
    _amp_cast_cleanup(ctx, cur)


def _amp_cast_cleanup(ctx: _Ctx, cur: Dict[str, str]) -> None:
    """Dedup identical casts and elide exact round trips.

    Valid rewrites (all restricted to single-def names, see _def_counts,
    and never touching protected names):
    - no-op cast (out_dtype == source dtype): alias away
    - duplicate (source, out_dtype) cast: alias to the first one
    - lowp -> f32 -> lowp round trip: widening then narrowing back is
      bit-exact, alias the final cast to the original low var
    """
    block = ctx.block
    stats = ctx.amp_stats
    defs = _def_counts(ctx)
    rename: Dict[str, str] = {}
    seen: Dict[tuple, str] = {}
    origin: Dict[str, tuple] = {}  # cast out -> (src, src_declared_dtype)

    def res(n):
        while n in rename:
            n = rename[n]
        return n

    def _declared(n):
        # runtime dtype where tracked (random/gray outputs keep their
        # declared VarDesc dtype but run in whatever flowed in — `cur`
        # holds the truth); positional staleness is excluded by the
        # single-def guards below
        d = cur.get(n)
        if d is not None:
            return d
        v = block.vars.get(n)
        return getattr(v, "dtype", None)

    new_ops = []
    for op in block.ops:
        op.inputs = {s: [res(n) for n in ns]
                     for s, ns in op.inputs.items()}
        if op.type == "cast":
            out = (op.outputs.get("Out") or [None])[0]
            src = (op.inputs.get("X") or [None])[0]
            od = op.attrs.get("out_dtype")
            tracked = (out is not None and src is not None
                       and defs.get(out, 0) <= 1
                       and defs.get(src, 0) <= 1)
            # protected outputs are read by name (fetch/state/sub-block)
            # and must keep their producing op; provenance is still
            # recorded so a later re-narrowing can skip the round trip
            if tracked and out not in ctx.protected:
                if _declared(src) == od:
                    rename[out] = src
                    stats["amp_casts_elided"] += 1
                    continue
                prev = origin.get(src)
                if (prev is not None and prev[1] == od
                        and od in _LOW_PRECISION
                        and defs.get(prev[0], 0) <= 1):
                    rename[out] = prev[0]
                    stats["amp_casts_elided"] += 1
                    continue
                dup = seen.get((src, od))
                if dup is not None:
                    rename[out] = dup
                    stats["amp_casts_elided"] += 1
                    continue
            if tracked:
                seen.setdefault((src, od), out)
                origin[out] = (src, _declared(src))
        new_ops.append(op)
    block.ops = new_ops


# ---------------------------------------------------------------------------
# pipeline
# ---------------------------------------------------------------------------
# (name, BuildStrategy knob, fn) — run order matters: fold first so CSE
# sees canonical constants, elide/cse before fusion so fusion matches the
# slimmed chains, DCE last to sweep newly-orphaned producers
_PIPELINE = (
    ("constant_folding", "constant_folding", _pass_constant_folding),
    ("elide_identities", "enable_inplace", _pass_elide_identities),
    ("cse", "cse", _pass_cse),
    ("fuse_elemwise_act", "fuse_elewise_add_act_ops",
     _pass_fuse_elemwise_act),
    ("dead_code_elimination", "memory_optimize", _pass_dce),
)


def pass_names() -> List[str]:
    return (["auto_mixed_precision"]
            + [name for name, _, _ in _PIPELINE]
            + ["recompute_segmentation", "shard_propagation",
               "pipeline_stages", "comm_bucketing",
               "drop_unused_vars"])


def apply_passes(program: Program, feed_names: Sequence[str],
                 fetch_names: Sequence[str], strategy=None):
    """Run the enabled passes over a CLONE of ``program`` and return
    ``(optimized_program, PassReport)``.

    ``strategy`` is a compiler.BuildStrategy (defaults to all knobs on);
    ``PADDLE_IR_PASSES=0`` disables the pipeline entirely — including
    the auto_mixed_precision pass — and returns the original program
    untouched. AMP runs FIRST so fusion/CSE/DCE see (and can clean up
    after) the inserted casts.
    """
    from .compiler import BuildStrategy

    strategy = strategy or BuildStrategy()
    n0 = len(program.global_block.ops)
    enabled = [(name, fn) for name, knob, fn in _PIPELINE
               if getattr(strategy, knob, True)]
    amp = resolve_amp(strategy)
    remat = resolve_recompute(strategy)
    shard = resolve_sharding(strategy)
    pp = resolve_pipeline(strategy)
    comm = resolve_comm(strategy)
    if comm is not None and comm_data_axis(shard) is None:
        # quantized collectives ride a pure data-parallel mesh; other
        # topologies keep XLA's partitioner-owned collectives (the
        # executor bumps the dispatch counter with the reason)
        comm = None
    if pp is not None and resolve_gradient_merge(strategy) is None:
        # the GPipe schedule's microbatches ARE the gradient-merge
        # microbatches — without gradient_merge_k > 1 there is nothing
        # to pipeline, so don't stamp __pp_stage (a content-hash flip)
        # or publish a pp_stages gauge for a schedule that never runs
        pp = None
    if os.environ.get("PADDLE_IR_PASSES") == "0" \
            or not (enabled or amp or remat or shard or pp):
        return program, PassReport([], n0, n0, 0.0)

    t_all = time.perf_counter()
    opt = Program.from_dict(program.to_dict())
    opt.random_seed = program.random_seed
    ctx = _Ctx(opt, set(feed_names), set(fetch_names))
    _stamp_rng_slots(opt.global_block)
    stats: List[PassStat] = []
    amp_counts: Dict[str, int] = {}
    if amp is not None:
        ctx.amp_dtype, ctx.amp_level, ctx.amp_scale = amp
        ctx.amp_stats = defaultdict(int)
        before = len(opt.global_block.ops)
        t0 = time.perf_counter()
        _pass_auto_mixed_precision(ctx)
        stats.append(PassStat("auto_mixed_precision", before,
                              len(opt.global_block.ops),
                              (time.perf_counter() - t0) * 1e3))
        amp_counts = {k: int(v) for k, v in ctx.amp_stats.items() if v}
    for name, fn in enabled:
        before = len(opt.global_block.ops)
        t0 = time.perf_counter()
        fn(ctx)
        ms = (time.perf_counter() - t0) * 1e3
        stats.append(PassStat(name, before, len(opt.global_block.ops), ms))
    remat_counts: Dict[str, int] = {}
    remat_table: List[dict] = []
    if remat is not None:
        # runs LAST among op-level passes: DCE has already settled the
        # op list, so segment sizes reflect what will actually trace
        ctx.remat_checkpoints, ctx.remat_nseg = remat
        ctx.remat_stats = defaultdict(int)
        ctx.remat_table = []
        n = len(opt.global_block.ops)
        t0 = time.perf_counter()
        _pass_recompute(ctx)
        stats.append(PassStat("recompute_segmentation", n, n,
                              (time.perf_counter() - t0) * 1e3))
        remat_counts = {k: int(v) for k, v in ctx.remat_stats.items() if v}
        remat_table = ctx.remat_table
    shard_counts: Dict[str, int] = {}
    shard_table: List[dict] = []
    if shard is not None or pp is not None:
        # runs after remat (stamps only, like remat — DCE has settled
        # the op list so the annotated vars are the ones that trace)
        ctx.shard_stats = defaultdict(int)
        ctx.shard_table = []
        if shard is not None:
            ctx.shard_axes, ctx.shard_hints = shard
            n = len(opt.global_block.ops)
            t0 = time.perf_counter()
            _pass_shard_propagation(ctx)
            stats.append(PassStat("shard_propagation", n, n,
                                  (time.perf_counter() - t0) * 1e3))
        if pp is not None:
            ctx.pp_stages = pp
            n = len(opt.global_block.ops)
            t0 = time.perf_counter()
            _pass_pipeline_stages(ctx)
            stats.append(PassStat("pipeline_stages", n, n,
                                  (time.perf_counter() - t0) * 1e3))
        shard_counts = {k: int(v) for k, v in ctx.shard_stats.items()
                        if v}
        shard_table = ctx.shard_table
    comm_counts: Dict[str, int] = {}
    comm_table: List[dict] = []
    if comm is not None and pp is None:
        # after shard_propagation (grads inherit their params' specs)
        # and never composed with the GPipe schedule
        ctx.comm = comm
        ctx.comm_group = comm_data_axis(shard)[1]
        ctx.comm_stats = defaultdict(int)
        ctx.comm_table = []
        n = len(opt.global_block.ops)
        t0 = time.perf_counter()
        _pass_comm_bucketing(ctx)
        stats.append(PassStat("comm_bucketing", n, n,
                              (time.perf_counter() - t0) * 1e3))
        comm_counts = {k: int(v) for k, v in ctx.comm_stats.items()
                       if v}
        comm_table = ctx.comm_table
    vars_dropped = 0
    if getattr(strategy, "memory_optimize", True):
        n = len(opt.global_block.ops)
        t0 = time.perf_counter()
        vars_dropped = _pass_drop_unused_vars(ctx)
        stats.append(PassStat("drop_unused_vars", n, n,
                              (time.perf_counter() - t0) * 1e3,
                              vars_dropped=vars_dropped))
    total_ms = (time.perf_counter() - t_all) * 1e3
    report = PassReport(stats, n0, len(opt.global_block.ops), total_ms,
                        vars_dropped, amp_counts, remat_counts,
                        remat_table, shard_counts, shard_table,
                        comm_counts, comm_table)
    return opt, report
