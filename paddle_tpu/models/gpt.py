"""GPT-style decoder-only causal LM.

The reference has no GPT (it predates the 2.0 model zoo's gpt); this is
the TPU-native flagship decoder: pre-LN blocks built from the same
MultiHeadAttention/Linear layers as the encoder stack, with
is_causal=True attention so the mask-free path composes with ring
attention (parallel/ring.py) for long-context training and with
TRANSFORMER_TP_RULES for tensor parallelism (q_proj/out_proj/linear1/2
naming preserved).
"""
from __future__ import annotations

from .. import ops
from ..nn import functional as F
from ..nn.common import Dropout, Embedding, Linear
from ..nn.layer import Layer
from ..nn.norm import LayerNorm
from ..nn.transformer import MultiHeadAttention


class GPTConfig:
    def __init__(self, vocab_size=50257, hidden_size=768,
                 num_hidden_layers=12, num_attention_heads=12,
                 intermediate_size=None, max_position_embeddings=1024,
                 hidden_dropout_prob=0.1, attention_probs_dropout_prob=0.1):
        self.vocab_size = vocab_size
        self.hidden_size = hidden_size
        self.num_hidden_layers = num_hidden_layers
        self.num_attention_heads = num_attention_heads
        self.intermediate_size = intermediate_size or 4 * hidden_size
        self.max_position_embeddings = max_position_embeddings
        self.hidden_dropout_prob = hidden_dropout_prob
        self.attention_probs_dropout_prob = attention_probs_dropout_prob

    @classmethod
    def tiny(cls):
        return cls(vocab_size=128, hidden_size=32, num_hidden_layers=2,
                   num_attention_heads=4, max_position_embeddings=64,
                   hidden_dropout_prob=0.0,
                   attention_probs_dropout_prob=0.0)


class GPTBlock(Layer):
    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.ln1 = LayerNorm(cfg.hidden_size)
        self.self_attn = MultiHeadAttention(
            cfg.hidden_size, cfg.num_attention_heads,
            dropout=cfg.attention_probs_dropout_prob, is_causal=True)
        self.ln2 = LayerNorm(cfg.hidden_size)
        self.linear1 = Linear(cfg.hidden_size, cfg.intermediate_size)
        self.linear2 = Linear(cfg.intermediate_size, cfg.hidden_size)
        self.dropout = Dropout(cfg.hidden_dropout_prob)

    def forward(self, x, cache=None):
        if cache is not None:
            attn, cache = self.self_attn(self.ln1(x), cache=cache)
        else:
            attn = self.self_attn(self.ln1(x))
        x = x + self.dropout(attn)
        h = self.linear2(F.gelu(self.linear1(self.ln2(x))))
        out = x + self.dropout(h)
        return (out, cache) if cache is not None else out

    def gen_cache(self, x):
        return self.self_attn.gen_cache(x)


class GPTModel(Layer):
    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.cfg = cfg
        self.word_embedding = Embedding(cfg.vocab_size, cfg.hidden_size)
        self.pos_embedding = Embedding(cfg.max_position_embeddings,
                                       cfg.hidden_size)
        self.dropout = Dropout(cfg.hidden_dropout_prob)
        from ..nn.container import LayerList

        self.layers = LayerList(
            [GPTBlock(cfg) for _ in range(cfg.num_hidden_layers)])
        self.ln_f = LayerNorm(cfg.hidden_size)

    def forward(self, input_ids, caches=None, pos_offset=0):
        b, l = input_ids.shape
        if pos_offset + l > self.cfg.max_position_embeddings:
            raise ValueError(
                f"sequence length {pos_offset + l} exceeds "
                f"max_position_embeddings "
                f"{self.cfg.max_position_embeddings}")
        pos = ops.arange(pos_offset, pos_offset + l, dtype="int32")
        x = self.word_embedding(input_ids) + self.pos_embedding(pos)
        x = self.dropout(x)
        if caches is None:
            for blk in self.layers:
                x = blk(x)
            return self.ln_f(x)
        new_caches = []
        for blk, c in zip(self.layers, caches):
            x, c = blk(x, cache=c)
            new_caches.append(c)
        return self.ln_f(x), new_caches

    def gen_caches(self, x):
        """Empty per-layer KV caches (MultiHeadAttention.gen_cache)."""
        return [blk.gen_cache(x) for blk in self.layers]


class GPTForCausalLM(Layer):
    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.gpt = GPTModel(cfg)
        # weight tying with the input embedding (standard GPT)
        self.cfg = cfg

    def forward(self, input_ids, caches=None, pos_offset=0):
        out = self.gpt(input_ids, caches=caches, pos_offset=pos_offset)
        h, caches = out if caches is not None else (out, None)
        w = self.gpt.word_embedding.weight          # (V, D)
        logits = ops.matmul(h, ops.transpose(w, [1, 0]))
        return (logits, caches) if caches is not None else logits

    def loss(self, input_ids, labels=None):
        """Next-token LM loss; labels default to input_ids shifted."""
        logits = self(input_ids)
        if labels is None:
            labels = input_ids
        shift_logits = logits[:, :-1, :]
        shift_labels = labels[:, 1:]
        v = shift_logits.shape[-1]
        flat = ops.reshape(shift_logits, [-1, v])
        return F.cross_entropy(flat, ops.reshape(shift_labels, [-1])).mean()

    def generate(self, input_ids, max_new_tokens=16, use_cache=True,
                 do_sample=False, top_k=0, top_p=1.0, temperature=1.0,
                 eos_token_id=None, seed=None):
        """Autoregressive decode with KV cache: prefill once on the
        prompt, then one single-token step per new token reusing the
        per-layer caches (the serving path of the reference's fused
        decoder, multihead_matmul_op + beam/topk sampling ops). Greedy by
        default; do_sample enables temperature / top-k / nucleus top-p.
        """
        import numpy as np

        from ..framework import no_grad
        from ..framework.tensor import Tensor

        rng = np.random.RandomState(seed)
        max_pos = self.cfg.max_position_embeddings
        with no_grad():
            ids = input_ids
            b = ids.shape[0]
            finished = np.zeros(b, bool)
            caches = None
            prompt = ids[:, -max_pos:]  # sliding-window truncation
            if use_cache and prompt.shape[1] < max_pos:
                logits, caches = self(
                    prompt, caches=self.gpt.gen_caches(prompt))
            else:
                logits = self(prompt)
            for step in range(max_new_tokens):
                last = logits[:, -1, :]
                nxt = self._pick_token(last, do_sample, top_k, top_p,
                                       temperature, rng)
                if eos_token_id is not None:
                    nxt = np.where(finished, eos_token_id, nxt)
                    finished |= nxt == eos_token_id
                nxt_t = Tensor(nxt.reshape(b, 1).astype("int32"))
                ids = ops.concat([ids, nxt_t], axis=1)
                if eos_token_id is not None and finished.all():
                    break
                if step == max_new_tokens - 1:
                    break
                if use_cache and caches is not None \
                        and ids.shape[1] < max_pos:
                    logits, caches = self(nxt_t, caches=caches,
                                          pos_offset=ids.shape[1] - 1)
                else:
                    # context full (or cacheless): slide the window and
                    # recompute; the absolute positions shift, so the old
                    # cache no longer applies
                    caches = None
                    logits = self(ids[:, -max_pos:])
            return ids

    @staticmethod
    def _pick_token(last_logits, do_sample, top_k, top_p, temperature, rng):
        """Greedy / temperature / top-k / top-p selection on host (the
        per-token control flow; the model step stays on device)."""
        import numpy as np

        logits = np.asarray(last_logits.numpy(), np.float32)
        if not do_sample or temperature is not None and temperature <= 1e-6:
            # temperature ~ 0 conventionally means deterministic decode
            return logits.argmax(-1)
        if temperature is not None and temperature != 1.0:
            logits = logits / float(temperature)
        if top_k:
            k = min(int(top_k), logits.shape[-1])
            kth = np.partition(logits, -k, axis=-1)[:, -k]
            logits = np.where(logits < kth[:, None], -np.inf, logits)
        if top_p < 1.0:
            order = np.argsort(-logits, axis=-1)
            sorted_logits = np.take_along_axis(logits, order, axis=-1)
            probs = np.exp(sorted_logits - sorted_logits.max(-1, keepdims=True))
            probs /= probs.sum(-1, keepdims=True)
            cum = np.cumsum(probs, axis=-1)
            cut = cum - probs >= top_p   # tokens past the nucleus
            sorted_logits[cut] = -np.inf
            logits = np.full_like(logits, -np.inf)
            np.put_along_axis(logits, order, sorted_logits, axis=-1)
        z = logits - logits.max(-1, keepdims=True)
        p = np.exp(z)
        p /= p.sum(-1, keepdims=True)
        return np.array([rng.choice(p.shape[-1], p=row) for row in p])
