"""GPT-style decoder-only causal LM.

The reference has no GPT (it predates the 2.0 model zoo's gpt); this is
the TPU-native flagship decoder: pre-LN blocks built from the same
MultiHeadAttention/Linear layers as the encoder stack, with
is_causal=True attention so the mask-free path composes with ring
attention (parallel/ring.py) for long-context training and with
TRANSFORMER_TP_RULES for tensor parallelism (q_proj/out_proj/linear1/2
naming preserved).
"""
from __future__ import annotations

from .. import ops
from ..nn import functional as F
from ..nn.common import Dropout, Embedding, Linear
from ..nn.layer import Layer
from ..nn.norm import LayerNorm
from ..nn.transformer import MultiHeadAttention


class GPTConfig:
    def __init__(self, vocab_size=50257, hidden_size=768,
                 num_hidden_layers=12, num_attention_heads=12,
                 intermediate_size=None, max_position_embeddings=1024,
                 hidden_dropout_prob=0.1, attention_probs_dropout_prob=0.1):
        self.vocab_size = vocab_size
        self.hidden_size = hidden_size
        self.num_hidden_layers = num_hidden_layers
        self.num_attention_heads = num_attention_heads
        self.intermediate_size = intermediate_size or 4 * hidden_size
        self.max_position_embeddings = max_position_embeddings
        self.hidden_dropout_prob = hidden_dropout_prob
        self.attention_probs_dropout_prob = attention_probs_dropout_prob

    @classmethod
    def tiny(cls):
        return cls(vocab_size=128, hidden_size=32, num_hidden_layers=2,
                   num_attention_heads=4, max_position_embeddings=64,
                   hidden_dropout_prob=0.0,
                   attention_probs_dropout_prob=0.0)


class GPTBlock(Layer):
    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.ln1 = LayerNorm(cfg.hidden_size)
        self.self_attn = MultiHeadAttention(
            cfg.hidden_size, cfg.num_attention_heads,
            dropout=cfg.attention_probs_dropout_prob, is_causal=True)
        self.ln2 = LayerNorm(cfg.hidden_size)
        self.linear1 = Linear(cfg.hidden_size, cfg.intermediate_size)
        self.linear2 = Linear(cfg.intermediate_size, cfg.hidden_size)
        self.dropout = Dropout(cfg.hidden_dropout_prob)

    def forward(self, x):
        x = x + self.dropout(self.self_attn(self.ln1(x)))
        h = self.linear2(F.gelu(self.linear1(self.ln2(x))))
        return x + self.dropout(h)


class GPTModel(Layer):
    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.cfg = cfg
        self.word_embedding = Embedding(cfg.vocab_size, cfg.hidden_size)
        self.pos_embedding = Embedding(cfg.max_position_embeddings,
                                       cfg.hidden_size)
        self.dropout = Dropout(cfg.hidden_dropout_prob)
        from ..nn.container import LayerList

        self.layers = LayerList(
            [GPTBlock(cfg) for _ in range(cfg.num_hidden_layers)])
        self.ln_f = LayerNorm(cfg.hidden_size)

    def forward(self, input_ids):
        b, l = input_ids.shape
        if l > self.cfg.max_position_embeddings:
            raise ValueError(
                f"sequence length {l} exceeds max_position_embeddings "
                f"{self.cfg.max_position_embeddings}")
        pos = ops.arange(0, l, dtype="int32")
        x = self.word_embedding(input_ids) + self.pos_embedding(pos)
        x = self.dropout(x)
        for blk in self.layers:
            x = blk(x)
        return self.ln_f(x)


class GPTForCausalLM(Layer):
    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.gpt = GPTModel(cfg)
        # weight tying with the input embedding (standard GPT)
        self.cfg = cfg

    def forward(self, input_ids):
        h = self.gpt(input_ids)
        w = self.gpt.word_embedding.weight          # (V, D)
        return ops.matmul(h, ops.transpose(w, [1, 0]))

    def loss(self, input_ids, labels=None):
        """Next-token LM loss; labels default to input_ids shifted."""
        logits = self(input_ids)
        if labels is None:
            labels = input_ids
        shift_logits = logits[:, :-1, :]
        shift_labels = labels[:, 1:]
        v = shift_logits.shape[-1]
        flat = ops.reshape(shift_logits, [-1, v])
        return F.cross_entropy(flat, ops.reshape(shift_labels, [-1])).mean()

    def generate(self, input_ids, max_new_tokens=16):
        """Greedy decode (eager; compile-friendly decode cache comes with
        the serving path)."""
        ids = input_ids
        for _ in range(max_new_tokens):
            window = ids[:, -self.cfg.max_position_embeddings:]
            logits = self(window)
            nxt = ops.argmax(logits[:, -1, :], axis=-1)
            ids = ops.concat([ids, ops.reshape(nxt, [-1, 1])], axis=1)
        return ids
