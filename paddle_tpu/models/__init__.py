"""Flagship model families (reference test-suite models: LeNet/ResNet in
paddle_tpu.vision.models; BERT/ERNIE, Transformer NMT, DeepFM/Wide&Deep
here — SURVEY.md §4 dist_transformer.py / dist_ctr.py parity)."""
from .bert import BertConfig, BertModel, BertForPretraining  # noqa: F401
from .transformer import TransformerNMT  # noqa: F401
from .ctr import DeepFM, WideDeep  # noqa: F401
from ..vision.models import (  # noqa: F401
    LeNet, ResNet, resnet18, resnet34, resnet50, resnet101, resnet152,
)
