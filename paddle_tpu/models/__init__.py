"""Flagship model families (reference test-suite models: LeNet/ResNet in
paddle_tpu.vision.models; BERT/ERNIE, Transformer NMT, DeepFM/Wide&Deep
here — SURVEY.md §4 dist_transformer.py / dist_ctr.py parity)."""
from .bert import BertConfig, BertModel, BertForPretraining  # noqa: F401
from .transformer import TransformerNMT  # noqa: F401
from .ctr import DeepFM, WideDeep  # noqa: F401
from .gpt import GPTConfig, GPTModel, GPTForCausalLM  # noqa: F401
from .word2vec import SkipGram, NGramLM  # noqa: F401
from .sentiment import SentimentLSTM  # noqa: F401
from ..vision.models import (  # noqa: F401
    LeNet, ResNet, resnet18, resnet34, resnet50, resnet101, resnet152,
    VGG, vgg16, vgg19, MobileNetV1, mobilenet_v1, MobileNetV2, mobilenet_v2,
    SEResNeXt, se_resnext50_32x4d,
)
