"""BERT (the flagship benchmark model — BASELINE.md "BERT-base pretraining").

Behavioral parity with the reference ERNIE/BERT stack built from
fluid.layers (multi-head attention via stacked fc + matmul ops; reference
fused path: /root/reference/paddle/fluid/operators/fused/
multihead_matmul_op.cu). TPU-native design: bf16-friendly shapes
(hidden/heads multiples of 128), attention through the Pallas flash kernel,
whole-model jit, TP shardings from parallel.sharding.TRANSFORMER_TP_RULES.
"""
from __future__ import annotations

import dataclasses

from .. import nn


@dataclasses.dataclass
class BertConfig:
    vocab_size: int = 30592  # multiple of 128 for clean TP sharding (239*128)
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    hidden_act: str = "gelu"
    hidden_dropout_prob: float = 0.1
    attention_probs_dropout_prob: float = 0.1
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    layer_norm_eps: float = 1e-12

    @staticmethod
    def base():
        return BertConfig()

    @staticmethod
    def tiny():
        return BertConfig(vocab_size=1024, hidden_size=128,
                          num_hidden_layers=2, num_attention_heads=2,
                          intermediate_size=256, max_position_embeddings=128)


class BertEmbeddings(nn.Layer):
    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.word_embeddings = nn.Embedding(cfg.vocab_size, cfg.hidden_size)
        self.position_embeddings = nn.Embedding(cfg.max_position_embeddings,
                                                cfg.hidden_size)
        self.token_type_embeddings = nn.Embedding(cfg.type_vocab_size,
                                                  cfg.hidden_size)
        self.layer_norm = nn.LayerNorm(cfg.hidden_size,
                                       epsilon=cfg.layer_norm_eps)
        self.dropout = nn.Dropout(cfg.hidden_dropout_prob)

    def forward(self, input_ids, token_type_ids=None):
        from .. import ops

        seq_len = input_ids.shape[1]
        pos = ops.arange(0, seq_len, 1, dtype="int32")
        emb = self.word_embeddings(input_ids)
        emb = emb + self.position_embeddings(pos)
        if token_type_ids is not None:
            emb = emb + self.token_type_embeddings(token_type_ids)
        return self.dropout(self.layer_norm(emb))


class BertModel(nn.Layer):
    def __init__(self, cfg: BertConfig = None):
        super().__init__()
        cfg = cfg or BertConfig()
        self.config = cfg
        self.embeddings = BertEmbeddings(cfg)
        enc_layer = nn.TransformerEncoderLayer(
            cfg.hidden_size, cfg.num_attention_heads, cfg.intermediate_size,
            dropout=cfg.hidden_dropout_prob, activation=cfg.hidden_act,
            attn_dropout=cfg.attention_probs_dropout_prob,
            normalize_before=False)
        self.encoder = nn.TransformerEncoder(enc_layer, cfg.num_hidden_layers)
        self.pooler = nn.Linear(cfg.hidden_size, cfg.hidden_size)
        self.pooler_act = nn.Tanh()

    def forward(self, input_ids, token_type_ids=None, attention_mask=None):
        emb = self.embeddings(input_ids, token_type_ids)
        seq = self.encoder(emb, attention_mask)
        pooled = self.pooler_act(self.pooler(seq[:, 0]))
        return seq, pooled


class BertForPretraining(nn.Layer):
    """MLM + NSP heads (matching the reference pretraining objective)."""

    def __init__(self, cfg: BertConfig = None):
        super().__init__()
        cfg = cfg or BertConfig()
        self.config = cfg
        self.bert = BertModel(cfg)
        self.mlm_transform = nn.Linear(cfg.hidden_size, cfg.hidden_size)
        self.mlm_norm = nn.LayerNorm(cfg.hidden_size,
                                     epsilon=cfg.layer_norm_eps)
        self.mlm_bias = self.create_parameter([cfg.vocab_size], is_bias=True)
        self.nsp = nn.Linear(cfg.hidden_size, 2)

    def _mlm_hidden(self, seq):
        """The MLM head pipeline up to (but not including) the tied
        vocab projection — shared by forward() and the fused loss path
        so the FLAGS_fused_vocab_xent A/B can never drift."""
        from ..nn import functional as F

        return self.mlm_norm(F.gelu(self.mlm_transform(seq)))

    def forward(self, input_ids, token_type_ids=None, attention_mask=None):
        from .. import ops

        seq, pooled = self.bert(input_ids, token_type_ids, attention_mask)
        h = self._mlm_hidden(seq)
        # tied decoder: share word embedding weights
        logits = ops.matmul(h, self.bert.embeddings.word_embeddings.weight,
                            transpose_y=True) + self.mlm_bias
        nsp_logits = self.nsp(pooled)
        return logits, nsp_logits

    def loss(self, input_ids, token_type_ids, mlm_labels, nsp_labels,
             attention_mask=None, ignore_index=-100):
        from ..framework.flags import get_flag
        from ..nn import functional as F
        from ..ops.pallas import fused_xent  # noqa: F401 (defines flag)

        if get_flag("fused_vocab_xent"):
            # fused path: the (B*S, vocab) logits never land in HBM
            # (ops/pallas/fused_xent.py; FLAGS_fused_vocab_xent=False
            # restores the materialised-logits path for A/B timing)
            seq, pooled = self.bert(input_ids, token_type_ids,
                                    attention_mask)
            h = self._mlm_hidden(seq)
            mlm = F.fused_linear_cross_entropy(
                h, self.bert.embeddings.word_embeddings.weight,
                self.mlm_bias, mlm_labels, ignore_index=ignore_index)
            nsp = F.cross_entropy(self.nsp(pooled), nsp_labels)
            return mlm + nsp
        logits, nsp_logits = self(input_ids, token_type_ids, attention_mask)
        mlm = F.cross_entropy(logits, mlm_labels, ignore_index=ignore_index)
        nsp = F.cross_entropy(nsp_logits, nsp_labels)
        return mlm + nsp
