"""Sentiment classification models (reference book test
/root/reference/python/paddle/fluid/tests/book/test_understand_sentiment.py:
conv + stacked-LSTM text classifiers over IMDB).

TPU-native shape: dense padded ids + lengths (no LoD), masked pooling, the
whole step jit-compiled.
"""
from __future__ import annotations

from .. import ops
from ..nn import functional as F
from ..nn.common import Dropout, Embedding, Linear
from ..nn.layer import Layer
from ..nn.norm import LayerNorm
from ..nn.rnn import LSTM


class SentimentLSTM(Layer):
    """Embedding -> (bi)LSTM -> masked max-pool -> FC (the stacked_lstm_net
    flavor of the book test)."""

    def __init__(self, vocab_size=5000, embed_dim=128, hidden_dim=128,
                 num_layers=1, num_classes=2, bidirectional=True,
                 dropout=0.1, pad_id=0):
        super().__init__()
        self.pad_id = pad_id
        self.embedding = Embedding(vocab_size, embed_dim)
        self.lstm = LSTM(embed_dim, hidden_dim, num_layers=num_layers,
                         direction="bidirectional" if bidirectional
                         else "forward")
        out_dim = hidden_dim * (2 if bidirectional else 1)
        self.norm = LayerNorm(out_dim)
        self.dropout = Dropout(dropout)
        self.fc = Linear(out_dim, num_classes)

    def forward(self, ids, lengths=None):
        """ids: (batch, maxlen) int; lengths: (batch,) valid counts
        (defaults to counting non-pad ids)."""
        if lengths is None:
            lengths = ops.sum((ids != self.pad_id).astype("int64"), axis=1)
        emb = self.embedding(ids)
        # lengths make the backward LSTM start at position len-1 instead
        # of reading pad embeddings (and zero outputs past len)
        seq, _ = self.lstm(emb, sequence_length=lengths)
        # masked max-pool over time (sequence_pool 'max' semantics)
        pooled = ops.sequence_pool(seq, lengths, pool_type="max")
        h = self.dropout(self.norm(pooled))
        return self.fc(h)

    def loss(self, ids, labels, lengths=None):
        logits = self(ids, lengths)
        return F.cross_entropy(logits, labels)
