"""CTR models: DeepFM and Wide&Deep (reference dist_ctr.py /
dist_fleet_ctr.py test models; the sparse side of BASELINE config 5).

TPU-native sparse design: fixed-slot dense gathers into embedding tables
(no dynamic-shape SelectedRows) — every slot contributes exactly one id per
example (MultiSlot padding upstream), so lookups are static-shape
jnp.take that XLA vectorizes; the huge-vocab path goes through
paddle_tpu.ps (host-RAM sharded tables).
"""
from __future__ import annotations

from .. import nn


class DeepFM(nn.Layer):
    def __init__(self, num_fields=26, vocab_sizes=None, embed_dim=16,
                 dense_dim=13, hidden_units=(400, 400, 400)):
        super().__init__()
        vocab_sizes = vocab_sizes or [100000] * num_fields
        self.num_fields = num_fields
        self.embed_dim = embed_dim
        # one embedding table per field (reference: per-slot lookup_table)
        self.embeddings = nn.LayerList(
            [nn.Embedding(v, embed_dim) for v in vocab_sizes])
        self.linear_embeds = nn.LayerList(
            [nn.Embedding(v, 1) for v in vocab_sizes])
        self.dense_linear = nn.Linear(dense_dim, 1)
        self.dense_embed = nn.Linear(dense_dim, embed_dim)
        dnn_in = (num_fields + 1) * embed_dim
        layers = []
        prev = dnn_in
        for h in hidden_units:
            layers += [nn.Linear(prev, h), nn.ReLU()]
            prev = h
        layers.append(nn.Linear(prev, 1))
        self.dnn = nn.Sequential(*layers)

    def forward(self, sparse_ids, dense_feats):
        """sparse_ids: (B, num_fields) int; dense_feats: (B, dense_dim)."""
        from .. import ops

        # first-order
        lin = self.dense_linear(dense_feats)
        for i, emb in enumerate(self.linear_embeds):
            lin = lin + emb(sparse_ids[:, i])
        # second-order FM over field embeddings + dense projection
        fields = [emb(sparse_ids[:, i])
                  for i, emb in enumerate(self.embeddings)]
        fields.append(self.dense_embed(dense_feats))
        stacked = ops.stack(fields, axis=1)  # (B, F+1, D)
        sum_sq = ops.square(ops.sum(stacked, axis=1))
        sq_sum = ops.sum(ops.square(stacked), axis=1)
        fm = 0.5 * ops.sum(sum_sq - sq_sum, axis=1, keepdim=True)
        # deep part
        flat = ops.reshape(stacked, [stacked.shape[0], -1])
        deep = self.dnn(flat)
        return lin + fm + deep

    def loss(self, sparse_ids, dense_feats, labels):
        from ..nn import functional as F

        logits = self(sparse_ids, dense_feats)
        return F.binary_cross_entropy_with_logits(
            logits, labels.reshape(logits.shape).astype(logits.dtype))


class WideDeep(nn.Layer):
    def __init__(self, num_fields=26, vocab_sizes=None, embed_dim=16,
                 dense_dim=13, hidden_units=(256, 128, 64)):
        super().__init__()
        vocab_sizes = vocab_sizes or [100000] * num_fields
        self.wide_embeds = nn.LayerList(
            [nn.Embedding(v, 1) for v in vocab_sizes])
        self.wide_dense = nn.Linear(dense_dim, 1)
        self.deep_embeds = nn.LayerList(
            [nn.Embedding(v, embed_dim) for v in vocab_sizes])
        prev = num_fields * embed_dim + dense_dim
        layers = []
        for h in hidden_units:
            layers += [nn.Linear(prev, h), nn.ReLU()]
            prev = h
        layers.append(nn.Linear(prev, 1))
        self.deep = nn.Sequential(*layers)

    def forward(self, sparse_ids, dense_feats):
        from .. import ops

        wide = self.wide_dense(dense_feats)
        for i, emb in enumerate(self.wide_embeds):
            wide = wide + emb(sparse_ids[:, i])
        deep_in = ops.concat(
            [emb(sparse_ids[:, i]) for i, emb in enumerate(self.deep_embeds)]
            + [dense_feats], axis=1)
        return wide + self.deep(deep_in)

    def loss(self, sparse_ids, dense_feats, labels):
        from ..nn import functional as F

        logits = self(sparse_ids, dense_feats)
        return F.binary_cross_entropy_with_logits(
            logits, labels.reshape(logits.shape).astype(logits.dtype))
