"""Transformer NMT (reference dist_transformer.py / machine-translation
book test parity): encoder-decoder with shared-dim embeddings, causal
decoding, and a greedy/beam inference path."""
from __future__ import annotations

import math

from .. import nn


class PositionalEncoding(nn.Layer):
    def __init__(self, d_model, max_len=1024, dropout=0.1):
        super().__init__()
        import numpy as np

        pe = np.zeros((max_len, d_model), np.float32)
        pos = np.arange(max_len)[:, None]
        div = np.exp(np.arange(0, d_model, 2) * (-math.log(10000.0) / d_model))
        pe[:, 0::2] = np.sin(pos * div)
        pe[:, 1::2] = np.cos(pos * div)
        self.register_buffer("pe", pe, persistable=False)
        self.dropout = nn.Dropout(dropout)

    def forward(self, x):
        return self.dropout(x + self.pe[: x.shape[1]])


class TransformerNMT(nn.Layer):
    def __init__(self, src_vocab_size=32000, tgt_vocab_size=32000,
                 d_model=512, nhead=8, num_encoder_layers=6,
                 num_decoder_layers=6, dim_feedforward=2048, dropout=0.1,
                 max_len=1024):
        super().__init__()
        self.d_model = d_model
        self.src_embed = nn.Embedding(src_vocab_size, d_model)
        self.tgt_embed = nn.Embedding(tgt_vocab_size, d_model)
        self.pos = PositionalEncoding(d_model, max_len, dropout)
        self.transformer = nn.Transformer(
            d_model, nhead, num_encoder_layers, num_decoder_layers,
            dim_feedforward, dropout)
        self.out_proj = nn.Linear(d_model, tgt_vocab_size)

    def _decode_hidden(self, src, tgt, src_mask=None):
        """Everything up to (not including) the vocab projection —
        shared by forward() and the fused-xent loss path."""
        scale = math.sqrt(self.d_model)
        src_e = self.pos(self.src_embed(src) * scale)
        tgt_e = self.pos(self.tgt_embed(tgt) * scale)
        tgt_mask = nn.Transformer.generate_square_subsequent_mask(tgt.shape[1])
        return self.transformer(src_e, tgt_e, src_mask=src_mask,
                                tgt_mask=tgt_mask)

    def forward(self, src, tgt, src_mask=None):
        return self.out_proj(self._decode_hidden(src, tgt, src_mask))

    def loss(self, src, tgt_in, tgt_out, pad_id=0):
        from .. import ops
        from ..framework.flags import get_flag
        from ..nn import functional as F
        from ..ops.pallas import fused_xent  # noqa: F401 (defines flag)

        if get_flag("fused_vocab_xent"):
            # streamed vocab xent: the (B*T, 32000) logits never land
            # in HBM (fused kernel wants (V, H) — one 65 MB weight
            # transpose buys back ~1 GB of logits traffic per step)
            h = self._decode_hidden(src, tgt_in)
            w_t = ops.transpose(self.out_proj.weight, [1, 0])
            return F.fused_linear_cross_entropy(
                h, w_t, self.out_proj.bias, tgt_out, ignore_index=pad_id)
        logits = self(src, tgt_in)
        return F.cross_entropy(logits, tgt_out, ignore_index=pad_id)

    def beam_search_decode(self, src, beam_size=4, bos_id=1, eos_id=2,
                           max_len=64, length_penalty=0.6):
        """Beam-search translation (reference layers/rnn.py
        BeamSearchDecoder + dynamic_decode). Encodes once, tiles the
        memory across beams, and recomputes the causal decoder on a
        fixed-size token buffer each step — static shapes, so XLA
        compiles the step once.

        Returns (ids, scores): (batch, beam, max_len) int32, best beam
        first, and length-normalised log-prob scores (batch, beam).
        """
        import jax.numpy as jnp

        from .. import ops
        from ..framework import no_grad
        from ..framework.tensor import Tensor

        was_training = self.training
        self.eval()
        try:
            with no_grad():
                b = src.shape[0]
                scale = math.sqrt(self.d_model)
                src_e = self.pos(self.src_embed(src) * scale)
                memory = self.transformer.encoder(src_e)
                mem = jnp.repeat(
                    memory.value if isinstance(memory, Tensor)
                    else memory, beam_size, axis=0)
                tgt_mask = nn.Transformer.generate_square_subsequent_mask(
                    max_len)

                def logits_fn(ids_buf, t, _state):
                    tgt_e = self.pos(
                        self.tgt_embed(Tensor(ids_buf)) * scale)
                    out = self.transformer.decoder(
                        tgt_e, Tensor(mem), tgt_mask=tgt_mask)
                    logits = self.out_proj(out)
                    return logits.value[:, t]

                ids, scores = ops.beam_search_decode(
                    logits_fn, batch_size=b, beam_size=beam_size,
                    max_len=max_len, bos_id=bos_id, eos_id=eos_id,
                    length_penalty=length_penalty)
                return Tensor(ids), Tensor(scores)
        finally:
            if was_training:
                self.train()

    def greedy_decode(self, src, bos_id=1, eos_id=2, max_len=64):
        import numpy as np

        from .. import ops
        from ..framework import no_grad
        from ..framework.tensor import to_tensor

        was_training = self.training
        self.eval()
        try:
            with no_grad():
                b = src.shape[0]
                ys = ops.full([b, 1], bos_id, dtype="int64")
                finished = np.zeros(b, bool)
                for _ in range(max_len - 1):
                    logits = self(src, ys)
                    nxt = logits[:, -1].argmax(-1).reshape([b, 1]).astype("int64")
                    # freeze sequences that already emitted eos
                    nxt_np = np.array(nxt.numpy()).reshape(b)
                    nxt_np[finished] = eos_id
                    finished |= nxt_np == eos_id
                    ys = ops.concat(
                        [ys, to_tensor(nxt_np.reshape(b, 1).astype("int64"))],
                        axis=1)
                    if finished.all():
                        break
                return ys
        finally:
            if was_training:
                self.train()
