"""Word2Vec skip-gram with negative sampling.

Parity with the reference book example
(/root/reference/python/paddle/fluid/tests/book/test_word2vec.py — there
an N-gram MLP; plus the large-scale PS variants under
tests/unittests/dist_word2vec.py). TPU-native: dense batched
embedding lookups + sampled softmax via negative sampling — no
dynamic-shape tables; the PS-backed variant swaps the Embedding for
ps.SparseEmbedding unchanged.
"""
from __future__ import annotations

from .. import ops
from ..nn import functional as F
from ..nn.common import Embedding
from ..nn.layer import Layer


class SkipGram(Layer):
    def __init__(self, vocab_size: int, embedding_dim: int = 128):
        super().__init__()
        self.vocab_size = vocab_size
        self.in_embed = Embedding(vocab_size, embedding_dim)
        self.out_embed = Embedding(vocab_size, embedding_dim)

    def forward(self, center, context, negatives):
        """center: (b,), context: (b,), negatives: (b, k). Returns the
        negative-sampling loss (Mikolov et al.)."""
        v_c = self.in_embed(center)                      # (b, d)
        u_o = self.out_embed(context)                    # (b, d)
        u_n = self.out_embed(negatives)                  # (b, k, d)
        pos = ops.sum(v_c * u_o, axis=-1)                # (b,)
        neg = ops.matmul(u_n, ops.reshape(v_c, list(v_c.shape) + [1]))
        neg = ops.reshape(neg, list(negatives.shape))    # (b, k)
        loss = -(ops.log_sigmoid(pos).mean() +
                 ops.log_sigmoid(-neg).sum(axis=-1).mean())
        return loss

    def embeddings(self):
        return self.in_embed.weight


class NGramLM(Layer):
    """The book test's N-gram neural LM (test_word2vec.py: 4 context
    words -> hidden -> softmax over vocab)."""

    def __init__(self, vocab_size: int, embedding_dim: int = 32,
                 context: int = 4, hidden: int = 256):
        super().__init__()
        from ..nn.common import Linear

        self.embed = Embedding(vocab_size, embedding_dim)
        self.fc1 = Linear(context * embedding_dim, hidden)
        self.fc2 = Linear(hidden, vocab_size)
        self.context = context

    def forward(self, words):
        """words: (b, context) int ids -> logits (b, vocab)."""
        e = self.embed(words)                            # (b, c, d)
        h = ops.reshape(e, [e.shape[0], -1])
        h = ops.tanh(self.fc1(h))
        return self.fc2(h)

    def loss(self, words, target):
        return F.cross_entropy(self(words), target).mean()
