"""Installation verifier (reference fluid/install_check.py:47 run_check):
run a tiny train step single-device and, when more devices exist, a
sharded step over a data-parallel mesh, then report."""
from __future__ import annotations

import numpy as np

from .framework.bringup import safe_devices

__all__ = ["run_check"]


def run_check():
    import jax

    from . import nn, optimizer, to_tensor
    from .jit import TrainStep

    print("Running verify paddle_tpu program ... ")
    devices = safe_devices()
    print(f"Found {len(devices)} device(s): "
          f"{[str(d) for d in devices[:4]]}"
          f"{' ...' if len(devices) > 4 else ''}")

    def tiny_step(mesh=None):
        from . import seed

        seed(0)
        model = nn.Linear(2, 1)
        opt = optimizer.SGD(learning_rate=0.01,
                            parameters=model.parameters())
        step = TrainStep(model, lambda m, x: (m(x) ** 2).mean(), opt,
                         mesh=mesh)
        rows = max(2, len(safe_devices()))
        x = to_tensor(np.tile(np.array([[1.0, 2.0], [3.0, 4.0]],
                                       np.float32), (rows // 2 + 1, 1))[:rows])
        first = float(step(x))
        for _ in range(3):
            last = float(step(x))
        if not last < first:
            raise AssertionError(
                f"loss did not decrease ({first} -> {last})")

    tiny_step()
    print("Your paddle_tpu works well on SINGLE device.")
    if len(devices) > 1:
        from .parallel.mesh import create_mesh

        tiny_step(mesh=create_mesh({"dp": len(devices)}))
        print(f"Your paddle_tpu works well on {len(devices)} devices "
              "(data parallel).")
    print("paddle_tpu is installed successfully! "
          "Let's start deep learning with paddle_tpu now.")
