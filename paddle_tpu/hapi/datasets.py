"""hapi datasets namespace (reference incubate/hapi/datasets/): the
vision and text dataset families under one roof. Implementations live
in paddle_tpu.vision.datasets and paddle_tpu.text (zero-egress
synthetic-fallback design)."""
from ..text import (  # noqa: F401
    Conll05st, Imdb, Imikolov, Movielens, UCIHousing, WMT14, WMT16)
from ..vision.datasets import (  # noqa: F401
    MNIST, Cifar10, Cifar100, FashionMNIST, Flowers, VOC2012)

__all__ = ["MNIST", "FashionMNIST", "Cifar10", "Cifar100", "Flowers",
           "VOC2012", "Conll05st", "Imdb", "Imikolov", "Movielens",
           "UCIHousing", "WMT14", "WMT16"]
