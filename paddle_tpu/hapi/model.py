"""High-level Model API (reference incubate/hapi/model.py: Model.prepare/
fit/evaluate/predict/save/load, train_batch/eval_batch/test_batch).

TPU-first: train batches run through a single fused jit step
(paddle_tpu.jit.TrainStep — forward+backward+update in one XLA program);
eval/predict run through a jit-compiled functional forward. Distributed
data parallelism comes from passing a mesh (params replicated, batch
sharded over 'dp') instead of the reference's per-process NCCL DataParallel.
"""
from __future__ import annotations

import os
import pickle
from typing import List, Optional, Sequence

import jax
import numpy as np

from ..framework.tensor import Tensor
from ..io.dataloader import DataLoader, Dataset
from ..jit import TrainStep, _FunctionalModel
from ..metric import Metric
from .callbacks import config_callbacks


class Input:
    """Input spec (reference hapi.Input / static.InputSpec parity)."""

    def __init__(self, shape=None, dtype="float32", name=None):
        self.shape = tuple(shape) if shape is not None else None
        self.dtype = dtype
        self.name = name

    def __repr__(self):
        return f"Input(shape={self.shape}, dtype={self.dtype}, name={self.name})"


def _to_list(x):
    if x is None:
        return []
    if isinstance(x, (list, tuple)):
        return list(x)
    return [x]


def _np_scalar(x):
    return np.asarray(x.numpy() if isinstance(x, Tensor) else x)


class Model:
    """Network wrapper with Keras-style train/eval/predict loops.

    Usage:
        model = hapi.Model(network)
        model.prepare(optimizer, loss, metrics)
        model.fit(train_dataset, eval_dataset, epochs=2, batch_size=64)
    """

    def __init__(self, network, inputs=None, labels=None):
        self.network = network
        self._inputs = _to_list(inputs)
        self._labels = _to_list(labels)
        self._optimizer = None
        self._loss = None
        self._metrics: List[Metric] = []
        self._train_step = None
        self._eval_compiled = None
        self._pred_compiled = None
        self._mesh = None
        self._param_rules = None
        self.stop_training = False
        self._save_dir = None

    # ------------------------------------------------------------- prepare
    def prepare(self, optimizer=None, loss=None, metrics=None,
                amp_configs=None, mesh=None, param_rules=None):
        self._optimizer = optimizer
        self._loss = loss
        ms = _to_list(metrics)
        for m in ms:
            if not isinstance(m, Metric):
                raise TypeError(f"metric {m} is not a paddle_tpu Metric")
        self._metrics = ms
        self._mesh = mesh
        self._param_rules = param_rules
        self._amp_configs = amp_configs
        # a new optimizer/loss/mesh invalidates previously compiled steps
        self._train_step = None
        self._eval_compiled = None
        self._pred_compiled = None
        return self

    def parameters(self, *args, **kwargs):
        return self.network.parameters(*args, **kwargs)

    # ------------------------------------------------------- batch methods
    def _split_batch(self, inputs, labels):
        ins = _to_list(inputs)
        labs = _to_list(labels)
        if not labs and self._loss is not None and len(ins) > 1:
            # convention: dataset yields (*inputs, label)
            n_lab = max(1, len(self._labels)) if self._labels else 1
            labs = ins[-n_lab:]
            ins = ins[:-n_lab]
        return ins, labs

    def _compute_loss(self, preds, labels):
        preds_l = preds if isinstance(preds, (list, tuple)) else [preds]
        if self._loss is None:
            return preds_l[0]
        return self._loss(*preds_l, *labels)

    def train_batch(self, inputs, labels=None):
        """One fused forward+backward+update step. Returns
        (loss_numpy, metric_results) like the reference when metrics are
        set, else loss_numpy."""
        if self._optimizer is None:
            raise RuntimeError("call prepare(optimizer, loss) before fit")
        self.network.train()
        ins, labs = self._split_batch(inputs, labels)

        if self._train_step is None:
            n_in = len(ins)

            def loss_fn(m, *batch):
                xs, ys = batch[:n_in], batch[n_in:]
                preds = m(*xs)
                loss = self._compute_loss(preds, ys)
                preds_t = preds if isinstance(preds, (tuple, list)) else (preds,)
                return (loss,) + tuple(preds_t)

            self._train_step = TrainStep(
                self.network, loss_fn, self._optimizer, mesh=self._mesh,
                param_rules=self._param_rules,
                # fleet sharding strategy (ZeRO): shard opt state over dp
                zero_stage=getattr(self._optimizer, "_zero_stage", 0))

        out = self._train_step(*(list(ins) + list(labs)))
        if isinstance(out, tuple):
            loss, preds = out[0], out[1:]
        else:
            loss, preds = out, ()
        metrics = self._update_metrics(preds, labs)
        loss_np = _np_scalar(loss)
        return (loss_np, metrics) if self._metrics else loss_np

    def _build_eval(self):
        fmodel = _FunctionalModel(self.network)
        compute_loss = self._compute_loss

        def pure_eval(params, buffers, ins, labs):
            preds, _ = fmodel(params, buffers, tuple(ins), {})
            preds_t = preds if isinstance(preds, (tuple, list)) else (preds,)
            labs_t = tuple(Tensor(l) if isinstance(l, jax.Array) else l
                           for l in labs)
            loss = compute_loss(
                tuple(Tensor(p) if isinstance(p, jax.Array) else p
                      for p in preds_t), labs_t)
            loss = loss.value if isinstance(loss, Tensor) else loss
            return loss, tuple(
                p.value if isinstance(p, Tensor) else p for p in preds_t)

        return jax.jit(pure_eval)

    def _build_predict(self):
        fmodel = _FunctionalModel(self.network)

        def pure_pred(params, buffers, ins):
            preds, _ = fmodel(params, buffers, tuple(ins), {})
            preds_t = preds if isinstance(preds, (tuple, list)) else (preds,)
            return tuple(p.value if isinstance(p, Tensor) else p
                         for p in preds_t)

        return jax.jit(pure_pred)

    def _arrays(self, xs):
        out = []
        for x in xs:
            if isinstance(x, Tensor):
                out.append(x.value)
            else:
                out.append(np.asarray(x))
        return tuple(out)

    def eval_batch(self, inputs, labels=None):
        self.network.eval()
        ins, labs = self._split_batch(inputs, labels)
        if self._eval_compiled is None:
            self._eval_compiled = self._build_eval()
        params = self.network.param_pytree()
        buffers = self.network.buffer_pytree()
        loss, preds = self._eval_compiled(
            params, buffers, self._arrays(ins), self._arrays(labs))
        metrics = self._update_metrics(preds, labs)
        loss_np = np.asarray(loss)
        return (loss_np, metrics) if self._metrics else loss_np

    def predict_batch(self, inputs):
        self.network.eval()
        ins = _to_list(inputs)
        if self._pred_compiled is None:
            self._pred_compiled = self._build_predict()
        params = self.network.param_pytree()
        buffers = self.network.buffer_pytree()
        preds = self._pred_compiled(params, buffers, self._arrays(ins))
        out = [np.asarray(p) for p in preds]
        return out if len(out) > 1 else out[0]

    test_batch = predict_batch  # reference name

    def _update_metrics(self, preds, labels):
        results = []
        preds = tuple(preds)
        for m in self._metrics:
            pred0 = preds[0] if preds else None
            lab0 = labels[0] if labels else None
            pv = Tensor(pred0) if isinstance(pred0, jax.Array) else pred0
            lv = Tensor(np.asarray(lab0.numpy() if isinstance(lab0, Tensor)
                                   else lab0)) if lab0 is not None else None
            state = m.compute(pv, lv)
            if isinstance(state, (tuple, list)):
                m.update(*[_np_scalar(s) for s in state])
            else:
                m.update(_np_scalar(state))
            results.append(m.accumulate())
        return results

    # --------------------------------------------------------------- loops
    def _make_loader(self, data, batch_size, shuffle, num_workers,
                     drop_last=False):
        if data is None:
            return None
        if isinstance(data, DataLoader):
            return data
        if isinstance(data, Dataset):
            return DataLoader(data, batch_size=batch_size, shuffle=shuffle,
                              num_workers=num_workers, drop_last=drop_last)
        return data  # any iterable of batches

    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1, verbose=2,
            drop_last=False, shuffle=True, num_workers=0, callbacks=None):
        loader = self._make_loader(train_data, batch_size, shuffle,
                                   num_workers, drop_last)
        eval_loader = self._make_loader(eval_data, batch_size, False,
                                        num_workers)
        self._save_dir = save_dir
        try:
            steps = len(loader)
        except TypeError:
            steps = None
        cbks = config_callbacks(
            callbacks, model=self, epochs=epochs, steps=steps,
            log_freq=log_freq, verbose=verbose, save_freq=save_freq,
            save_dir=save_dir, metrics=["loss"] + [m.name() for m in self._metrics])
        self.stop_training = False
        cbks.on_begin("train")
        logs = {}
        for epoch in range(epochs):
            cbks.on_epoch_begin(epoch)
            for m in self._metrics:
                m.reset()
            logs = {}
            for step, batch in enumerate(loader):
                cbks.on_batch_begin("train", step, logs)
                out = self.train_batch(batch)
                logs = self._logs(out)
                cbks.on_batch_end("train", step, logs)
                if self.stop_training:
                    break
            cbks.on_epoch_end(epoch, logs)
            if eval_loader is not None and (epoch + 1) % eval_freq == 0:
                self.evaluate(eval_loader, batch_size=batch_size,
                              verbose=verbose, callbacks=cbks,
                              num_workers=num_workers)
            if self.stop_training:
                break
        cbks.on_end("train", logs)
        return self

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 num_workers=0, callbacks=None):
        loader = self._make_loader(eval_data, batch_size, False, num_workers)
        own_cbks = callbacks is None
        cbks = callbacks if not own_cbks else config_callbacks(
            None, model=self, log_freq=log_freq, verbose=verbose,
            metrics=["loss"] + [m.name() for m in self._metrics])
        for m in self._metrics:
            m.reset()
        try:
            steps = len(loader)
        except TypeError:
            steps = None
        cbks.on_begin("eval", {"steps": steps})
        logs = {}
        loss_sum, n_sum = 0.0, 0
        for step, batch in enumerate(loader):
            cbks.on_batch_begin("eval", step, logs)
            out = self.eval_batch(batch)
            logs = self._logs(out)
            # sample-weighted mean loss across the whole set (the reference
            # hapi averages before logging; the last batch may be ragged)
            n = self._batch_len(batch)
            loss_sum += float(np.mean(logs["loss"])) * n
            n_sum += n
            cbks.on_batch_end("eval", step, logs)
        if n_sum:
            logs["loss"] = [loss_sum / n_sum]
        cbks.on_end("eval", logs)
        return logs

    @staticmethod
    def _batch_len(batch):
        arrs = _to_list(batch)
        try:
            return int(np.shape(arrs[0])[0])
        except (IndexError, TypeError):
            return 1

    def predict(self, test_data, batch_size=1, num_workers=0,
                stack_outputs=False, callbacks=None, verbose=0):
        loader = self._make_loader(test_data, batch_size, False, num_workers)
        try:
            steps = len(loader)
        except TypeError:
            steps = None
        cbks = callbacks if callbacks is not None and hasattr(
            callbacks, "on_begin") else config_callbacks(
            callbacks, model=self, steps=steps, verbose=verbose, metrics=[])
        cbks.on_begin("predict", {"steps": steps})
        outputs = []
        for step, batch in enumerate(loader):
            cbks.on_batch_begin("predict", step, {})
            ins = _to_list(batch)
            if self._labels:
                ins = ins[: len(ins) - len(self._labels)] or ins
            preds = self.predict_batch(ins)
            outputs.append(preds)
            cbks.on_batch_end("predict", step, {})
        cbks.on_end("predict", {})
        if stack_outputs and outputs:
            if isinstance(outputs[0], list):
                outputs = [np.concatenate([o[i] for o in outputs])
                           for i in range(len(outputs[0]))]
            else:
                outputs = np.concatenate(outputs)
        return outputs

    def _logs(self, out):
        if isinstance(out, tuple):
            loss, metrics = out
            logs = {"loss": np.asarray(loss).ravel().tolist()}
            for m, r in zip(self._metrics, metrics):
                logs[m.name()] = r
            return logs
        return {"loss": np.asarray(out).ravel().tolist()}

    # ----------------------------------------------------------- save/load
    def save(self, path, training=True):
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        from ..io.serialization import save as _save

        _save(self.network.state_dict(), path + ".pdparams")
        if training and self._optimizer is not None:
            _save(self._optimizer.state_dict(), path + ".pdopt")

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        from ..io.serialization import load as _load

        state = _load(path + ".pdparams")
        if skip_mismatch:
            own = self.network.state_dict()
            state = {k: v for k, v in state.items()
                     if k in own and tuple(np.shape(v)) == tuple(own[k].shape)}
        self.network.set_state_dict(state)
        if not reset_optimizer and self._optimizer is not None and \
                os.path.exists(path + ".pdopt"):
            self._optimizer.set_state_dict(_load(path + ".pdopt"))
        # a live TrainStep caches params + opt state on device; drop it so
        # the next train_batch rebuilds from the restored checkpoint
        self._train_step = None
        return self

    def summary(self, input_size=None, dtype=None):
        from .summary import summary as _summary

        return _summary(self.network, input_size, dtype)
