"""Training callbacks (reference incubate/hapi/callbacks.py:112 Callback,
:283 ProgBarLogger, :419 ModelCheckpoint; EarlyStopping added for 2.x
parity)."""
from __future__ import annotations

import numbers
import os
import time

import numpy as np


class Callback:
    """Base class. Subclasses override any of the on_* hooks."""

    def __init__(self):
        self.model = None
        self.params = {}

    def set_params(self, params):
        self.params = params or {}

    def set_model(self, model):
        self.model = model

    # mode-level
    def on_train_begin(self, logs=None): pass
    def on_train_end(self, logs=None): pass
    def on_eval_begin(self, logs=None): pass
    def on_eval_end(self, logs=None): pass
    def on_predict_begin(self, logs=None): pass
    def on_predict_end(self, logs=None): pass
    # epoch-level
    def on_epoch_begin(self, epoch, logs=None): pass
    def on_epoch_end(self, epoch, logs=None): pass
    # batch-level
    def on_train_batch_begin(self, step, logs=None): pass
    def on_train_batch_end(self, step, logs=None): pass
    def on_eval_batch_begin(self, step, logs=None): pass
    def on_eval_batch_end(self, step, logs=None): pass
    def on_predict_batch_begin(self, step, logs=None): pass
    def on_predict_batch_end(self, step, logs=None): pass


class CallbackList:
    def __init__(self, callbacks=None):
        self.callbacks = list(callbacks or [])

    def append(self, callback):
        self.callbacks.append(callback)

    def __iter__(self):
        return iter(self.callbacks)

    def set_params(self, params):
        for c in self.callbacks:
            c.set_params(params)

    def set_model(self, model):
        for c in self.callbacks:
            c.set_model(model)

    def _call(self, name, *args, **kwargs):
        for c in self.callbacks:
            getattr(c, name)(*args, **kwargs)

    def on_begin(self, mode, logs=None):
        self._call(f"on_{mode}_begin", logs)

    def on_end(self, mode, logs=None):
        self._call(f"on_{mode}_end", logs)

    def on_epoch_begin(self, epoch, logs=None):
        self._call("on_epoch_begin", epoch, logs)

    def on_epoch_end(self, epoch, logs=None):
        self._call("on_epoch_end", epoch, logs)

    def on_batch_begin(self, mode, step, logs=None):
        self._call(f"on_{mode}_batch_begin", step, logs)

    def on_batch_end(self, mode, step, logs=None):
        self._call(f"on_{mode}_batch_end", step, logs)


def _fmt(v):
    if isinstance(v, numbers.Number):
        return f"{v:.4f}" if isinstance(v, float) else str(v)
    if isinstance(v, (list, tuple, np.ndarray)):
        return " ".join(_fmt(x) for x in np.asarray(v).ravel().tolist())
    return str(v)


class ProgBarLogger(Callback):
    """Prints loss/metrics every `log_freq` steps and per epoch
    (reference callbacks.py:283)."""

    def __init__(self, log_freq=1, verbose=2):
        super().__init__()
        self.log_freq = log_freq
        self.verbose = verbose

    def on_train_begin(self, logs=None):
        self.epochs = self.params.get("epochs")
        self._t0 = time.time()

    def on_epoch_begin(self, epoch, logs=None):
        self.steps = self.params.get("steps")
        self.epoch = epoch
        self._seen = 0
        if self.verbose and self.epochs:
            print(f"Epoch {epoch + 1}/{self.epochs}")

    def _print(self, mode, step, logs):
        logs = logs or {}
        items = [f"{k}: {_fmt(v)}" for k, v in logs.items()]
        total = self.steps if self.steps else "?"
        print(f"{mode} step {step + 1}/{total} - " + " - ".join(items))

    def on_train_batch_end(self, step, logs=None):
        self._seen += 1
        if self.verbose == 2 and (step + 1) % self.log_freq == 0:
            self._print("train", step, logs)

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose:
            self._print("train(epoch end)", self._seen - 1, logs)

    def on_eval_begin(self, logs=None):
        self.eval_steps = (logs or {}).get("steps")
        if self.verbose:
            print("Eval begin...")

    def on_eval_batch_end(self, step, logs=None):
        if self.verbose == 2 and (step + 1) % self.log_freq == 0:
            self._print("eval", step, logs)

    def on_eval_end(self, logs=None):
        if self.verbose:
            items = [f"{k}: {_fmt(v)}" for k, v in (logs or {}).items()]
            print("Eval samples done - " + " - ".join(items))


class ModelCheckpoint(Callback):
    """Saves model+optimizer state every `save_freq` epochs
    (reference callbacks.py:419)."""

    def __init__(self, save_freq=1, save_dir=None):
        super().__init__()
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.save_dir and (epoch + 1) % self.save_freq == 0:
            path = os.path.join(self.save_dir, str(epoch))
            self.model.save(path)

    def on_train_end(self, logs=None):
        if self.save_dir:
            self.model.save(os.path.join(self.save_dir, "final"))


class EarlyStopping(Callback):
    """Stop training when a monitored metric stops improving."""

    def __init__(self, monitor="loss", mode="auto", patience=0, verbose=1,
                 min_delta=0, baseline=None, save_best_model=True):
        super().__init__()
        self.monitor = monitor
        self.patience = patience
        self.verbose = verbose
        self.min_delta = abs(min_delta)
        self.baseline = baseline
        self.save_best_model = save_best_model
        self.stopped_epoch = 0
        if mode not in ("auto", "min", "max"):
            mode = "auto"
        if mode == "auto":
            mode = "max" if "acc" in monitor else "min"
        self.mode = mode

    def on_train_begin(self, logs=None):
        self.wait = 0
        self.best = (-np.inf if self.mode == "max" else np.inf)
        if self.baseline is not None:
            self.best = self.baseline

    def _better(self, cur):
        if self.mode == "max":
            return cur > self.best + self.min_delta
        return cur < self.best - self.min_delta

    def on_eval_end(self, logs=None):
        logs = logs or {}
        cur = logs.get(self.monitor)
        if cur is None:
            return
        cur = float(np.asarray(cur).ravel()[0])
        if self._better(cur):
            self.best = cur
            self.wait = 0
            if self.save_best_model and getattr(self.model, "_save_dir", None):
                self.model.save(os.path.join(self.model._save_dir, "best_model"))
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.model.stop_training = True
                if self.verbose:
                    print(f"Early stopping: {self.monitor} did not improve "
                          f"for {self.patience} evals (best {self.best:.5f})")


class LRSchedulerCallback(Callback):
    """Steps the optimizer's LRScheduler each epoch (or each batch)."""

    def __init__(self, by_step=False, by_epoch=True):
        super().__init__()
        self.by_step = by_step
        self.by_epoch = by_epoch and not by_step

    def _sched(self):
        opt = getattr(self.model, "_optimizer", None)
        lr = getattr(opt, "_learning_rate", None)
        return lr if hasattr(lr, "step") else None

    def on_train_batch_end(self, step, logs=None):
        s = self._sched()
        if self.by_step and s is not None:
            s.step()

    def on_epoch_end(self, epoch, logs=None):
        s = self._sched()
        if self.by_epoch and s is not None:
            s.step()


def config_callbacks(callbacks=None, model=None, epochs=None, steps=None,
                     log_freq=2, verbose=2, save_freq=1, save_dir=None,
                     metrics=None):
    cbks = list(callbacks or [])
    if not any(isinstance(c, ProgBarLogger) for c in cbks) and verbose:
        cbks = [ProgBarLogger(log_freq, verbose=verbose)] + cbks
    if not any(isinstance(c, ModelCheckpoint) for c in cbks):
        cbks = cbks + [ModelCheckpoint(save_freq, save_dir)]
    if not any(isinstance(c, LRSchedulerCallback) for c in cbks):
        cbks = cbks + [LRSchedulerCallback()]
    lst = CallbackList(cbks)
    lst.set_model(model)
    lst.set_params({
        "epochs": epochs, "steps": steps, "verbose": verbose,
        "metrics": metrics or [],
    })
    return lst
