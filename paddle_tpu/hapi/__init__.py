"""hapi — high-level Keras-like training API.

Capability parity with the reference high-level API
(/root/reference/python/paddle/incubate/hapi/: model.py Model.fit/
evaluate/predict, callbacks.py, distributed.py DistributedBatchSampler),
re-designed TPU-first: train/eval batches run through one jit-compiled
functional step instead of per-op dygraph dispatch.
"""
from .callbacks import (  # noqa: F401
    Callback, CallbackList, EarlyStopping, LRSchedulerCallback,
    ModelCheckpoint, ProgBarLogger,
)
from .model import Input, Model  # noqa: F401
from .summary import summary  # noqa: F401

__all__ = [
    "Model", "summary", "Callback", "CallbackList", "ProgBarLogger",
    "ModelCheckpoint", "EarlyStopping", "LRSchedulerCallback",
]
