"""hapi — high-level Keras-like training API.

Capability parity with the reference high-level API
(/root/reference/python/paddle/incubate/hapi/: model.py Model.fit/
evaluate/predict, callbacks.py, distributed.py DistributedBatchSampler),
re-designed TPU-first: train/eval batches run through one jit-compiled
functional step instead of per-op dygraph dispatch.
"""
from .callbacks import (  # noqa: F401
    Callback, CallbackList, EarlyStopping, LRSchedulerCallback,
    ModelCheckpoint, ProgBarLogger,
)
from .model import Input, Model  # noqa: F401
from .summary import summary  # noqa: F401
from . import callbacks, distributed, download, utils  # noqa: F401
from ..framework.place import set_device  # noqa: F401
from .. import text, vision  # noqa: F401

__all__ = [
    "Model", "summary", "Callback", "CallbackList", "ProgBarLogger",
    "ModelCheckpoint", "EarlyStopping", "LRSchedulerCallback",
    "callbacks", "datasets", "distributed", "download", "vision", "text",
    "utils", "set_device",
]


def __getattr__(name):
    # hapi.datasets re-exports the vision+text dataset families; lazy so
    # importing hapi doesn't pay for the dataset modules
    if name == "datasets":
        # importlib, not `from . import`: the from-import form getattrs
        # the package first, which re-enters this __getattr__ forever
        import importlib

        return importlib.import_module(".datasets", __name__)
    raise AttributeError(name)
