"""paddle.summary — layer-by-layer parameter/output table
(reference hapi/model_summary.py capability)."""
from __future__ import annotations

import numpy as np

from ..framework.tensor import Tensor
from ..nn.layer import Layer


def _num_params(layer, include_sublayers=False):
    ps = layer.parameters(include_sublayers=include_sublayers)
    return int(sum(int(np.prod(p.shape)) for p in ps))


def summary(net: Layer, input_size=None, dtypes=None):
    """Prints a per-layer table; returns {'total_params', 'trainable_params'}.

    If input_size is given (tuple or list of tuples), runs a forward pass
    with zeros to record per-layer output shapes via forward hooks.
    """
    rows = []
    hooks = []

    def mk_hook(name):
        def hook(layer, inputs, outputs):
            out = outputs[0] if isinstance(outputs, (tuple, list)) else outputs
            shape = tuple(out.shape) if hasattr(out, "shape") else None
            rows.append((name, type(layer).__name__, shape,
                         _num_params(layer, include_sublayers=False)))
        return hook

    shapes_known = input_size is not None
    if shapes_known:
        for name, sub in net.named_sublayers():
            hooks.append(sub.register_forward_post_hook(mk_hook(name)))
        sizes = input_size if isinstance(input_size, list) else [input_size]
        dts = dtypes if isinstance(dtypes, (list, tuple)) else [dtypes] * len(sizes)
        args = []
        for s, dt in zip(sizes, dts):
            s = tuple(1 if d is None or d == -1 else d for d in s)
            args.append(Tensor(np.zeros(s, dtype=np.dtype(dt or "float32"))))
        was_training = net.training
        net.eval()
        try:
            net(*args)
        finally:
            if was_training:
                net.train()
            for h in hooks:
                h.remove()
    else:
        for name, sub in net.named_sublayers():
            rows.append((name, type(sub).__name__, None,
                         _num_params(sub, include_sublayers=False)))

    header = f"{'Layer (type)':<40}{'Output Shape':<24}{'Param #':>12}"
    line = "-" * len(header)
    print(line)
    print(header)
    print(line)
    for name, tname, shape, n in rows:
        print(f"{name + ' (' + tname + ')':<40}"
              f"{str(shape) if shape else '-':<24}{n:>12,}")
    print(line)
    total = _num_params(net, include_sublayers=True)
    trainable = int(sum(int(np.prod(p.shape))
                        for p in net.parameters() if p.trainable))
    print(f"Total params: {total:,}")
    print(f"Trainable params: {trainable:,}")
    print(f"Non-trainable params: {total - trainable:,}")
    print(line)
    return {"total_params": total, "trainable_params": trainable}
