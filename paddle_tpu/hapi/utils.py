"""hapi misc helpers (reference incubate/hapi/utils.py)."""
from __future__ import annotations

import numpy as np

__all__ = ["to_list", "to_numpy", "flatten_list", "restore_flatten_list"]


def to_list(value):
    if value is None:
        return value
    if isinstance(value, (list, tuple)):
        return list(value)
    return [value]


def to_numpy(var):
    if hasattr(var, "numpy"):
        return var.numpy()
    return np.asarray(var)


def flatten_list(nested):
    """[[a, b], [c]] -> ([a, b, c], [2, 1]) — layout for restore."""
    assert isinstance(nested, list), "input must be a list"
    flat, structure = [], []
    for sub in nested:
        if isinstance(sub, list):
            flat.extend(sub)
            structure.append(len(sub))
        else:
            flat.append(sub)
            structure.append(0)
    return flat, structure


def restore_flatten_list(flat, structure):
    out, i = [], 0
    for n in structure:
        if n == 0:
            out.append(flat[i])
            i += 1
        else:
            out.append(list(flat[i:i + n]))
            i += n
    return out
