"""hapi distributed helpers (reference incubate/hapi/distributed.py):
DistributedBatchSampler plus the env-derived rank/size getters. The
sampler implementation lives with the rest of the data pipeline in
paddle_tpu.io; this module is the hapi-surface re-export."""
from __future__ import annotations

import os

from ..io import DistributedBatchSampler  # noqa: F401

__all__ = ["DistributedBatchSampler", "get_nranks", "get_local_rank"]


def get_nranks() -> int:
    return int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))


def get_local_rank() -> int:
    return int(os.environ.get("PADDLE_TRAINER_ID", "0"))
