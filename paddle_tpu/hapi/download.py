"""Weight/archive path resolution (reference incubate/hapi/download.py:
get_weights_path_from_url / get_path_from_url).

Zero-egress design: this environment has no network, so URLs resolve
strictly against the local cache directory (~/.cache/paddle_tpu/weights
or PADDLE_TPU_WEIGHTS_HOME). A file someone pre-seeded resolves exactly
like a downloaded one; a missing file raises a clear error instead of
attempting a fetch.
"""
from __future__ import annotations

import os

__all__ = ["get_weights_path_from_url", "get_path_from_url"]

WEIGHTS_HOME = os.environ.get(
    "PADDLE_TPU_WEIGHTS_HOME",
    os.path.join(os.path.expanduser("~"), ".cache", "paddle_tpu",
                 "weights"))


def _cache_path(url: str, root_dir: str) -> str:
    return os.path.join(root_dir, os.path.basename(url.split("?")[0]))


def get_path_from_url(url: str, root_dir: str = WEIGHTS_HOME,
                      md5sum=None, check_exist: bool = True) -> str:
    """Resolve ``url`` to its local cache path. Local paths pass
    through; cached files resolve; anything else raises (no egress).

    Resolution retries transient OSErrors (a flaky NFS/gcsfuse cache
    mount mid-failover) with backoff via paddle_tpu.fault; a genuinely
    absent file (FileNotFoundError) is terminal and raises immediately.
    """
    from ..fault import injector as _fault
    from ..fault.retry import Retrier, env_backoff

    def _probe(p: str) -> bool:
        # os.path.exists swallows EIO/ESTALE as False — stat so a flaky
        # mount surfaces as a retryable OSError, not a bogus cache miss.
        # But a URL is probed as-is and may not even be a legal path
        # (NUL bytes, >NAME_MAX components): path-shaped errors are a
        # plain miss, only real I/O errors deserve the retry
        import errno

        try:
            os.stat(p)
            return True
        except (FileNotFoundError, NotADirectoryError, ValueError):
            return False
        except OSError as e:
            if e.errno in (errno.ENAMETOOLONG, errno.EINVAL):
                return False
            raise

    def resolve() -> str:
        _fault.point("download.resolve")
        if _probe(url):
            return url
        path = _cache_path(url, root_dir)
        if _probe(path):
            return path
        raise FileNotFoundError(
            f"{url!r} is not cached at {path!r} and this build performs "
            "no network downloads; pre-seed the file into "
            f"{root_dir!r} (or set PADDLE_TPU_WEIGHTS_HOME)")

    return Retrier(retry_on=(OSError,), giveup_on=(FileNotFoundError,),
                   backoff=env_backoff(0.1, 2.0),
                   name="hapi.download").call(resolve)


def get_weights_path_from_url(url: str, md5sum=None) -> str:
    return get_path_from_url(url, WEIGHTS_HOME, md5sum)
