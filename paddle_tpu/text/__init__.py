"""Text datasets (reference incubate/hapi/datasets/{imdb,imikolov,
uci_housing,conll05,movielens}.py).

Zero-egress design: each dataset loads from a local file when given a
path, else generates a deterministic synthetic corpus with the same
record schema — the pattern vision.datasets.MNIST established — so the
data pipeline, models, and tests exercise the exact interfaces without
downloads.
"""
from __future__ import annotations

import os
import tarfile
import zlib
from typing import List, Optional

import numpy as np

from ..io.dataloader import Dataset as _Dataset


def _stable_hash(word: str, mod: int) -> int:
    """Process-stable token hashing (python hash() is randomized per
    process via PYTHONHASHSEED, which would scramble saved embeddings)."""
    return zlib.crc32(word.encode("utf8")) % mod


class Imdb(_Dataset):
    """IMDB sentiment (imdb.py): records of (token_ids, label)."""

    def __init__(self, data_path: Optional[str] = None, mode="train",
                 cutoff=150, synthetic_size=512, vocab_size=5000,
                 max_len=64, seed=0):
        self.mode = mode
        self.vocab_size = vocab_size
        if data_path and os.path.exists(data_path):
            self._load_archive(data_path, mode, cutoff)
        else:
            rng = np.random.RandomState(seed + (0 if mode == "train" else 1))
            n = synthetic_size
            self.docs: List[np.ndarray] = []
            self.labels = np.zeros(n, np.int64)
            # synthetic rule: positive docs over-sample the top quarter of
            # the vocab, so the task is learnable
            lo = min(8, max(1, max_len - 1))
            for i in range(n):
                label = int(rng.randint(0, 2))
                length = int(rng.randint(lo, max_len + 1))
                if label:
                    ids = rng.randint(vocab_size // 4, vocab_size, length)
                else:
                    ids = rng.randint(1, (3 * vocab_size) // 4, length)
                self.docs.append(ids.astype(np.int64))
                self.labels[i] = label

    def _load_archive(self, path, mode, cutoff):
        pat = f"aclImdb/{mode}/"
        self.docs, labels = [], []
        with tarfile.open(path) as tf:
            for member in tf.getmembers():
                if not member.name.startswith(pat) or \
                        not member.name.endswith(".txt"):
                    continue
                if "/pos/" in member.name:
                    labels.append(1)
                elif "/neg/" in member.name:
                    labels.append(0)
                else:
                    continue
                text = tf.extractfile(member).read().decode(
                    "utf8", "ignore").lower().split()
                ids = np.asarray(
                    [_stable_hash(w, self.vocab_size) for w in text],
                    np.int64)
                self.docs.append(ids[:cutoff])
        self.labels = np.asarray(labels, np.int64)

    def __len__(self):
        return len(self.docs)

    def __getitem__(self, idx):
        return self.docs[idx], self.labels[idx]


class Imikolov(_Dataset):
    """PTB-style n-gram LM dataset (imikolov.py): n-gram windows."""

    def __init__(self, data_path: Optional[str] = None, data_type="NGRAM",
                 window_size=5, mode="train", min_word_freq=50,
                 synthetic_size=4096, vocab_size=2000, seed=0):
        if data_type not in ("NGRAM", "SKIPGRAM"):
            raise ValueError(f"unsupported data_type {data_type!r}")
        self.window_size = window_size
        self.data_type = data_type
        rng = np.random.RandomState(seed + (0 if mode == "train" else 1))
        if data_path and os.path.exists(data_path):
            with open(data_path) as f:
                words = f.read().split()
            counts = {}
            for w in words:
                counts[w] = counts.get(w, 0) + 1
            ids = np.asarray(
                [_stable_hash(w, vocab_size) for w in words
                 if counts[w] >= min_word_freq], np.int64)
        else:
            # Zipf-ish synthetic stream (imikolov's corpus statistics shape)
            ranks = np.arange(1, vocab_size + 1)
            p = (1.0 / ranks) / np.sum(1.0 / ranks)
            ids = rng.choice(vocab_size, size=synthetic_size, p=p)
        self.grams = np.lib.stride_tricks.sliding_window_view(
            ids, window_size).astype(np.int64)

    def __len__(self):
        if self.data_type == "SKIPGRAM":
            return len(self.grams) * (self.window_size - 1)
        return len(self.grams)

    def __getitem__(self, idx):
        if self.data_type == "SKIPGRAM":
            # (center, one context word) pairs; center = window middle
            g = self.grams[idx // (self.window_size - 1)]
            mid = self.window_size // 2
            ctx = [g[i] for i in range(self.window_size) if i != mid]
            return g[mid], ctx[idx % (self.window_size - 1)]
        g = self.grams[idx]
        return g[:-1], g[-1]


class UCIHousing(_Dataset):
    """Boston housing regression (uci_housing.py): 13 features, price."""

    FEATURE_DIM = 13

    def __init__(self, data_path: Optional[str] = None, mode="train",
                 synthetic_size=404, seed=0):
        if data_path and os.path.exists(data_path):
            raw = np.loadtxt(data_path).astype(np.float32)
            feats, target = raw[:, :-1], raw[:, -1:]
        else:
            # one shared ground-truth w across splits, disjoint samples
            w = np.random.RandomState(seed).randn(
                self.FEATURE_DIM, 1).astype(np.float32)
            rng = np.random.RandomState(
                seed + (1 if mode == "train" else 2))
            n = synthetic_size if mode == "train" else synthetic_size // 4
            feats = rng.randn(n, self.FEATURE_DIM).astype(np.float32)
            target = feats @ w + 0.1 * rng.randn(n, 1).astype(np.float32)
        mean, std = feats.mean(0), feats.std(0) + 1e-6
        self.features = ((feats - mean) / std).astype(np.float32)
        self.target = target.astype(np.float32)

    def __len__(self):
        return len(self.features)

    def __getitem__(self, idx):
        return self.features[idx], self.target[idx]


class Conll05st(_Dataset):
    """Semantic role labeling records (conll05.py): token ids, predicate
    position, BIO tag ids — the label_semantic_roles book-test schema."""

    def __init__(self, data_path: Optional[str] = None, mode="train",
                 vocab_size=3000, num_tags=9, max_len=30,
                 synthetic_size=256, seed=0):
        rng = np.random.RandomState(seed + (0 if mode == "train" else 1))
        self.records = []
        for _ in range(synthetic_size):
            n = int(rng.randint(5, max_len))
            words = rng.randint(1, vocab_size, n).astype(np.int64)
            pred_pos = int(rng.randint(0, n))
            tags = rng.randint(0, num_tags, n).astype(np.int64)
            self.records.append((words, np.int64(pred_pos), tags))

    def __len__(self):
        return len(self.records)

    def __getitem__(self, idx):
        return self.records[idx]
