"""Text datasets (reference incubate/hapi/datasets/{imdb,imikolov,
uci_housing,conll05,movielens}.py).

Zero-egress design: each dataset loads from a local file when given a
path, else generates a deterministic synthetic corpus with the same
record schema — the pattern vision.datasets.MNIST established — so the
data pipeline, models, and tests exercise the exact interfaces without
downloads.
"""
from __future__ import annotations

import os
import tarfile
import zlib
from typing import List, Optional

import numpy as np

from ..io.dataloader import Dataset as _Dataset


class _RecordsDataset(_Dataset):
    """Shared list-of-records base for the tuple-schema datasets."""

    records: list

    def __len__(self):
        return len(self.records)

    def __getitem__(self, idx):
        return self.records[idx]


def _stable_hash(word: str, mod: int) -> int:
    """Process-stable token hashing (python hash() is randomized per
    process via PYTHONHASHSEED, which would scramble saved embeddings)."""
    return zlib.crc32(word.encode("utf8")) % mod


class Imdb(_Dataset):
    """IMDB sentiment (imdb.py): records of (token_ids, label)."""

    def __init__(self, data_path: Optional[str] = None, mode="train",
                 cutoff=150, synthetic_size=512, vocab_size=5000,
                 max_len=64, seed=0):
        self.mode = mode
        self.vocab_size = vocab_size
        if data_path and os.path.exists(data_path):
            self._load_archive(data_path, mode, cutoff)
        else:
            rng = np.random.RandomState(seed + (0 if mode == "train" else 1))
            n = synthetic_size
            self.docs: List[np.ndarray] = []
            self.labels = np.zeros(n, np.int64)
            # synthetic rule: positive docs over-sample the top quarter of
            # the vocab, so the task is learnable
            lo = min(8, max(1, max_len - 1))
            for i in range(n):
                label = int(rng.randint(0, 2))
                length = int(rng.randint(lo, max_len + 1))
                if label:
                    ids = rng.randint(vocab_size // 4, vocab_size, length)
                else:
                    ids = rng.randint(1, (3 * vocab_size) // 4, length)
                self.docs.append(ids.astype(np.int64))
                self.labels[i] = label

    def _load_archive(self, path, mode, cutoff):
        pat = f"aclImdb/{mode}/"
        self.docs, labels = [], []
        with tarfile.open(path) as tf:
            for member in tf.getmembers():
                if not member.name.startswith(pat) or \
                        not member.name.endswith(".txt"):
                    continue
                if "/pos/" in member.name:
                    labels.append(1)
                elif "/neg/" in member.name:
                    labels.append(0)
                else:
                    continue
                text = tf.extractfile(member).read().decode(
                    "utf8", "ignore").lower().split()
                ids = np.asarray(
                    [_stable_hash(w, self.vocab_size) for w in text],
                    np.int64)
                self.docs.append(ids[:cutoff])
        self.labels = np.asarray(labels, np.int64)

    def __len__(self):
        return len(self.docs)

    def __getitem__(self, idx):
        return self.docs[idx], self.labels[idx]


class Imikolov(_Dataset):
    """PTB-style n-gram LM dataset (imikolov.py): n-gram windows."""

    def __init__(self, data_path: Optional[str] = None, data_type="NGRAM",
                 window_size=5, mode="train", min_word_freq=50,
                 synthetic_size=4096, vocab_size=2000, seed=0):
        if data_type not in ("NGRAM", "SKIPGRAM"):
            raise ValueError(f"unsupported data_type {data_type!r}")
        self.window_size = window_size
        self.data_type = data_type
        rng = np.random.RandomState(seed + (0 if mode == "train" else 1))
        if data_path and os.path.exists(data_path):
            with open(data_path) as f:
                words = f.read().split()
            counts = {}
            for w in words:
                counts[w] = counts.get(w, 0) + 1
            ids = np.asarray(
                [_stable_hash(w, vocab_size) for w in words
                 if counts[w] >= min_word_freq], np.int64)
        else:
            # Zipf-ish synthetic stream (imikolov's corpus statistics shape)
            ranks = np.arange(1, vocab_size + 1)
            p = (1.0 / ranks) / np.sum(1.0 / ranks)
            ids = rng.choice(vocab_size, size=synthetic_size, p=p)
        self.grams = np.lib.stride_tricks.sliding_window_view(
            ids, window_size).astype(np.int64)

    def __len__(self):
        if self.data_type == "SKIPGRAM":
            return len(self.grams) * (self.window_size - 1)
        return len(self.grams)

    def __getitem__(self, idx):
        if self.data_type == "SKIPGRAM":
            # (center, one context word) pairs; center = window middle
            g = self.grams[idx // (self.window_size - 1)]
            mid = self.window_size // 2
            ctx = [g[i] for i in range(self.window_size) if i != mid]
            return g[mid], ctx[idx % (self.window_size - 1)]
        g = self.grams[idx]
        return g[:-1], g[-1]


class UCIHousing(_Dataset):
    """Boston housing regression (uci_housing.py): 13 features, price."""

    FEATURE_DIM = 13

    def __init__(self, data_path: Optional[str] = None, mode="train",
                 synthetic_size=404, seed=0):
        if data_path and os.path.exists(data_path):
            raw = np.loadtxt(data_path).astype(np.float32)
            feats, target = raw[:, :-1], raw[:, -1:]
        else:
            # one shared ground-truth w across splits, disjoint samples
            w = np.random.RandomState(seed).randn(
                self.FEATURE_DIM, 1).astype(np.float32)
            rng = np.random.RandomState(
                seed + (1 if mode == "train" else 2))
            n = synthetic_size if mode == "train" else synthetic_size // 4
            feats = rng.randn(n, self.FEATURE_DIM).astype(np.float32)
            target = feats @ w + 0.1 * rng.randn(n, 1).astype(np.float32)
        mean, std = feats.mean(0), feats.std(0) + 1e-6
        self.features = ((feats - mean) / std).astype(np.float32)
        self.target = target.astype(np.float32)

    def __len__(self):
        return len(self.features)

    def __getitem__(self, idx):
        return self.features[idx], self.target[idx]


class Conll05st(_RecordsDataset):
    """Semantic role labeling records (conll05.py): token ids, predicate
    position, BIO tag ids — the label_semantic_roles book-test schema."""

    def __init__(self, data_path: Optional[str] = None, mode="train",
                 vocab_size=3000, num_tags=9, max_len=30,
                 synthetic_size=256, seed=0):
        rng = np.random.RandomState(seed + (0 if mode == "train" else 1))
        self.records = []
        for _ in range(synthetic_size):
            n = int(rng.randint(5, max_len))
            words = rng.randint(1, vocab_size, n).astype(np.int64)
            pred_pos = int(rng.randint(0, n))
            tags = rng.randint(0, num_tags, n).astype(np.int64)
            self.records.append((words, np.int64(pred_pos), tags))


class Movielens(_RecordsDataset):
    """MovieLens rating records (movielens.py): (user_id, gender, age,
    job, movie_id, categories, rating). With data_path, parses ml-1m
    style ratings.dat lines (UserID::MovieID::Rating::Timestamp);
    gender/age/job/categories are synthesized when no user/movie
    metadata accompanies the ratings file."""

    def __init__(self, data_path: Optional[str] = None, mode="train",
                 synthetic_size=1024, num_users=500, num_movies=800,
                 num_categories=18, seed=0):
        rng = np.random.RandomState(seed + (0 if mode == "train" else 1))
        self.records = []
        if data_path and os.path.exists(data_path):
            # file ids are remapped into [0, num_users/num_movies) so an
            # Embedding sized from the same constructor params never
            # overflows; user/movie attributes are derived from the raw id
            # (stable hash), so every record of a user agrees on them
            with open(data_path) as f:
                for line in f:
                    parts = line.strip().split("::")
                    if len(parts) < 3:
                        continue
                    u, m, r = int(parts[0]), int(parts[1]), float(parts[2])
                    uh = _stable_hash(f"user{u}", 1 << 30)
                    mh = _stable_hash(f"movie{m}", 1 << 30)
                    self.records.append((
                        np.int64(u % num_users), np.int64(uh % 2),
                        np.int64(uh // 2 % 7),
                        np.int64(uh // 14 % 21), np.int64(m % num_movies),
                        (np.array([mh, mh // 7, mh // 49])
                         % num_categories).astype(np.int64),
                        np.float32(r)))
            return
        # latent-factor synthetic ratings so recommenders can learn
        u_f = rng.randn(num_users, 4)
        m_f = rng.randn(num_movies, 4)
        for _ in range(synthetic_size):
            u = int(rng.randint(0, num_users))
            m = int(rng.randint(0, num_movies))
            rating = float(np.clip(2.5 + u_f[u] @ m_f[m], 1.0, 5.0))
            self.records.append((
                np.int64(u), np.int64(rng.randint(0, 2)),
                np.int64(rng.randint(0, 7)), np.int64(rng.randint(0, 21)),
                np.int64(m),
                rng.randint(0, num_categories, 3).astype(np.int64),
                np.float32(rating)))


class WMT16(_RecordsDataset):
    """Translation pairs (wmt16.py): (src_ids, trg_in, trg_out) with
    BOS/EOS framing. With data_path, reads tab-separated parallel lines
    ("source\ttarget", stable-hashed token ids). Synthetic mode emits an
    invertible toy mapping (target = source reversed, remapped into the
    non-reserved target id range) so seq2seq models can overfit it."""

    BOS, EOS, PAD = 1, 2, 0

    def __init__(self, data_path: Optional[str] = None, mode="train",
                 src_vocab_size=1000, trg_vocab_size=1000, max_len=16,
                 synthetic_size=512, seed=0):
        if trg_vocab_size < 4 or src_vocab_size < 4:
            raise ValueError("vocab sizes must be >= 4 (3 reserved ids)")
        rng = np.random.RandomState(seed + (0 if mode == "train" else 1))
        self.records = []

        def frame(src, trg):
            trg_in = np.concatenate([[self.BOS], trg]).astype(np.int64)
            trg_out = np.concatenate([trg, [self.EOS]]).astype(np.int64)
            self.records.append((src.astype(np.int64), trg_in, trg_out))

        if data_path and os.path.exists(data_path):
            with open(data_path, encoding="utf8", errors="ignore") as f:
                for line in f:
                    cols = line.rstrip("\n").split("\t")
                    if len(cols) < 2:
                        continue
                    src = np.asarray(
                        [3 + _stable_hash(w, src_vocab_size - 3)
                         for w in cols[0].split()[:max_len]], np.int64)
                    trg = np.asarray(
                        [3 + _stable_hash(w, trg_vocab_size - 3)
                         for w in cols[1].split()[:max_len]], np.int64)
                    if len(src) and len(trg):
                        frame(src, trg)
            return
        lo = min(3, max(1, max_len - 2))
        hi = max(lo + 1, max_len - 1)
        for _ in range(synthetic_size):
            n = int(rng.randint(lo, hi))
            src = rng.randint(3, src_vocab_size, n).astype(np.int64)
            # reversed + remapped into [3, trg_vocab) so reserved
            # PAD/BOS/EOS ids never appear mid-sequence
            trg = 3 + (src[::-1] - 3) % (trg_vocab_size - 3)
            frame(src, trg)


class WMT14(WMT16):
    """WMT'14 en→fr translation pairs (reference hapi/datasets/wmt14.py:41).
    Same (src_ids, trg_in, trg_out) triple schema as [WMT16]; the reference
    differs only in corpus + a single shared dict_size for both vocabs
    (wmt14.py:89 __init__(dict_size)), mirrored here."""

    def __init__(self, data_path=None, mode="train", dict_size=1000,
                 max_len=16, synthetic_size=512, seed=14):
        super().__init__(data_path, mode, src_vocab_size=dict_size,
                         trg_vocab_size=dict_size, max_len=max_len,
                         synthetic_size=synthetic_size, seed=seed)


class MovieReviews(_RecordsDataset):
    """NLTK movie-review sentiment records (reference
    hapi/datasets/movie_reviews.py:39): (token_ids, label) with label
    0=negative 1=positive. File mode reads one `label<TAB>text` line per
    document; synthetic mode reuses the learnable Imdb rule."""

    def __init__(self, data_path: Optional[str] = None, mode="train",
                 vocab_size=5000, max_len=64, synthetic_size=512, seed=3):
        assert mode in ("train", "test")
        self.vocab_size = vocab_size
        self.records = []
        if data_path and os.path.exists(data_path):
            # deterministic 80/20 split by document index (the reference
            # splits the nltk corpus per category movie_reviews.py:100)
            docs = []
            with open(data_path, encoding="utf8", errors="ignore") as f:
                for line in f:
                    cols = line.rstrip("\n").split("\t", 1)
                    if len(cols) != 2:
                        continue
                    ids = np.asarray(
                        [1 + _stable_hash(w, vocab_size - 1)
                         for w in cols[1].split()[:max_len]], np.int64)
                    if len(ids):
                        docs.append((ids, np.int64(int(cols[0]))))
            if len(docs) < 5:       # too small to split meaningfully
                self.records = docs
            else:
                self.records = [d for i, d in enumerate(docs)
                                if (i % 5 == 4) == (mode == "test")]
            return
        inner = Imdb(None, mode, synthetic_size=synthetic_size,
                     vocab_size=vocab_size, max_len=max_len, seed=seed)
        for i in range(len(inner)):
            self.records.append(inner[i])
