"""Filesystem layer: local + HDFS shell-out.

Parity with /root/reference/paddle/fluid/framework/io/{fs.cc,shell.cc} and
python/paddle/fluid/incubate/fleet/utils/fs.py (FS/LocalFS/HDFSClient):
checkpoints and datasets address local paths or `hdfs://` URIs through one
interface. HDFS access shells out to `hadoop fs` exactly like the
reference; when no hadoop binary exists the client raises a clear error
at call time (construction stays cheap for config plumbing).
"""
from __future__ import annotations

import os
import shutil
import subprocess
from typing import List, Optional, Sequence, Tuple


class ExecuteError(Exception):
    pass


class FSFileExistsError(Exception):
    pass


class FSTimeOut(Exception):
    """Shell filesystem command exceeded its deadline (reference
    fleet/utils/fs.py FSTimeOut)."""


class FSShellCmdAborted(ExecuteError):
    """Shell filesystem command aborted (reference fleet/utils/fs.py
    FSShellCmdAborted)."""


class FSFileNotExistsError(Exception):
    pass


class FS:
    """Abstract interface (reference fs.py:52)."""

    def ls_dir(self, fs_path) -> Tuple[List[str], List[str]]:
        raise NotImplementedError

    def is_file(self, fs_path) -> bool:
        raise NotImplementedError

    def is_dir(self, fs_path) -> bool:
        raise NotImplementedError

    def is_exist(self, fs_path) -> bool:
        raise NotImplementedError

    def upload(self, local_path, fs_path):
        raise NotImplementedError

    def download(self, fs_path, local_path):
        raise NotImplementedError

    def mkdirs(self, fs_path):
        raise NotImplementedError

    def delete(self, fs_path):
        raise NotImplementedError

    def need_upload_download(self) -> bool:
        raise NotImplementedError

    def rename(self, fs_src_path, fs_dst_path):
        raise NotImplementedError

    def mv(self, fs_src_path, fs_dst_path, overwrite=False,
           test_exists=False):
        raise NotImplementedError

    def list_dirs(self, fs_path) -> List[str]:
        raise NotImplementedError

    def touch(self, fs_path, exist_ok=True):
        raise NotImplementedError


class LocalFS(FS):
    """Local filesystem (reference fs.py:110 LocalFS)."""

    def ls_dir(self, fs_path):
        if not self.is_exist(fs_path):
            return [], []
        dirs, files = [], []
        for name in sorted(os.listdir(fs_path)):
            if os.path.isdir(os.path.join(fs_path, name)):
                dirs.append(name)
            else:
                files.append(name)
        return dirs, files

    def mkdirs(self, fs_path):
        os.makedirs(fs_path, exist_ok=True)

    def rename(self, fs_src_path, fs_dst_path):
        os.rename(fs_src_path, fs_dst_path)

    def delete(self, fs_path):
        if os.path.isdir(fs_path):
            shutil.rmtree(fs_path)
        elif os.path.isfile(fs_path):
            os.remove(fs_path)

    def need_upload_download(self):
        return False

    def is_file(self, fs_path):
        return os.path.isfile(fs_path)

    def is_dir(self, fs_path):
        return os.path.isdir(fs_path)

    def is_exist(self, fs_path):
        return os.path.exists(fs_path)

    def touch(self, fs_path, exist_ok=True):
        if os.path.exists(fs_path):
            if not exist_ok:
                raise FSFileExistsError(fs_path)
            return
        with open(fs_path, "a"):
            pass

    def mv(self, src_path, dst_path, overwrite=False, test_exists=False):
        if test_exists:
            if not self.is_exist(src_path):
                raise FSFileNotExistsError(src_path)
            if not overwrite and self.is_exist(dst_path):
                raise FSFileExistsError(dst_path)
        if overwrite and self.is_exist(dst_path):
            self.delete(dst_path)
        os.rename(src_path, dst_path)

    def list_dirs(self, fs_path):
        if not self.is_exist(fs_path):
            return []
        return [d for d in sorted(os.listdir(fs_path))
                if os.path.isdir(os.path.join(fs_path, d))]

    def upload(self, local_path, fs_path):
        shutil.copy(local_path, fs_path)

    def download(self, fs_path, local_path):
        shutil.copy(fs_path, local_path)


class HDFSClient(FS):
    """HDFS via `hadoop fs` shell-out (reference fs.py HDFSClient /
    framework/io/fs.cc hdfs_* — the reference also shells out).

    configs: dict merged into the command as -D key=value (e.g.
    fs.default.name, hadoop.job.ugi).
    """

    def __init__(self, hadoop_home: Optional[str] = None, configs=None,
                 time_out=5 * 60 * 1000, sleep_inter=1000, _runner=None):
        self._hadoop_home = hadoop_home or os.environ.get("HADOOP_HOME", "")
        self._configs = dict(configs or {})
        self._time_out = time_out
        self._sleep_inter = sleep_inter  # ms between retries
        self._runner = _runner or self._run_cmd  # injectable for tests

    # -- command plumbing ---------------------------------------------------
    def _base_cmd(self) -> List[str]:
        exe = os.path.join(self._hadoop_home, "bin", "hadoop") \
            if self._hadoop_home else "hadoop"
        cmd = [exe, "fs"]
        for k, v in sorted(self._configs.items()):
            cmd += ["-D", f"{k}={v}"]
        return cmd

    def _run_cmd(self, args: Sequence[str]) -> Tuple[int, List[str]]:
        cmd = self._base_cmd() + list(args)
        if not (self._hadoop_home and os.path.exists(self._base_cmd()[0])) \
                and shutil.which("hadoop") is None:
            raise ExecuteError(
                "no hadoop binary found (set hadoop_home or HADOOP_HOME); "
                f"would run: {' '.join(cmd)}")
        try:
            proc = subprocess.run(cmd, capture_output=True, text=True,
                                  timeout=self._time_out / 1000.0)
        except subprocess.TimeoutExpired as e:
            raise ExecuteError(f"hadoop command timed out: {e}") from e
        except OSError as e:  # e.g. hadoop_home/bin/hadoop missing
            raise ExecuteError(f"failed to exec {cmd[0]}: {e}") from e
        return proc.returncode, proc.stdout.splitlines()

    # -- FS interface ---------------------------------------------------------
    def ls_dir(self, fs_path):
        rc, lines = self._runner(["-ls", fs_path])
        if rc != 0:
            return [], []
        dirs, files = [], []
        for ln in lines:
            fields = ln.split()
            if len(fields) < 8:
                continue
            name = fields[-1]
            (dirs if fields[0].startswith("d") else files).append(
                os.path.basename(name))
        return dirs, files

    def is_dir(self, fs_path):
        rc, _ = self._runner(["-test", "-d", fs_path])
        return rc == 0

    def is_file(self, fs_path):
        rc, _ = self._runner(["-test", "-f", fs_path])
        return rc == 0

    def is_exist(self, fs_path):
        rc, _ = self._runner(["-test", "-e", fs_path])
        return rc == 0

    def upload(self, local_path, fs_path):
        rc, out = self._runner(["-put", local_path, fs_path])
        if rc != 0:
            raise ExecuteError(f"hadoop -put failed: {out}")

    def download(self, fs_path, local_path):
        rc, out = self._runner(["-get", fs_path, local_path])
        if rc != 0:
            raise ExecuteError(f"hadoop -get failed: {out}")

    def mkdirs(self, fs_path):
        rc, out = self._runner(["-mkdir", "-p", fs_path])
        if rc != 0:
            raise ExecuteError(f"hadoop -mkdir failed: {out}")

    def delete(self, fs_path):
        rc, out = self._runner(["-rmr", fs_path])
        if rc != 0:
            raise ExecuteError(f"hadoop -rmr failed: {out}")

    def touch(self, fs_path, exist_ok=True):
        if self.is_exist(fs_path):
            if not exist_ok:
                raise FSFileExistsError(fs_path)
            return
        rc, out = self._runner(["-touchz", fs_path])
        if rc != 0:
            raise ExecuteError(f"hadoop -touchz failed: {out}")

    def mv(self, fs_src_path, fs_dst_path, overwrite=False,
           test_exists=False):
        if test_exists:
            if not self.is_exist(fs_src_path):
                raise FSFileNotExistsError(fs_src_path)
            if not overwrite and self.is_exist(fs_dst_path):
                raise FSFileExistsError(fs_dst_path)
        rc, out = self._runner(["-mv", fs_src_path, fs_dst_path])
        if rc != 0:
            raise ExecuteError(f"hadoop -mv failed: {out}")

    def list_dirs(self, fs_path):
        return self.ls_dir(fs_path)[0]

    def need_upload_download(self):
        return True
