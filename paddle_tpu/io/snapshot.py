"""Crash-safe snapshot directories with a sha256-manifest commit point.

The seed's checkpoint path committed state and meta via two separate
``os.replace`` calls with no fsync and no integrity check at load — a
kill between the two renames left state and meta describing different
epochs, and a torn write loaded as garbage. This module is the one
snapshot engine both auto-checkpoint and manual tooling use:

layout (one store root, versioned snapshot dirs)::

    <root>/
      epoch_7/
        state.pdparams          payload files (opaque bytes)
        meta.pkl
        MANIFEST.json           <- exists IFF the snapshot is committed
      epoch_8/                  newest committed snapshot wins at load
      epoch_9.tmp/              torn write-in-progress leftover (ignored)
      epoch_7.old/              same-tag rewrite crashed mid-write: the
                                moved-aside committed copy, healed (restored
                                or dropped) on the next save/load

commit protocol (``SnapshotStore.save``):

1. payloads are written into ``<dir>.tmp`` and fsync'd;
2. the manifest (per-file sha256 + byte count) is written to
   ``MANIFEST.json.tmp`` inside it and fsync'd;
3. the dir is renamed to its final name — still uncommitted: readers
   require ``MANIFEST.json``;
4. ``MANIFEST.json.tmp`` → ``MANIFEST.json`` via one atomic
   ``os.replace`` — the ONLY commit point — then the dir is fsync'd.

A crash anywhere before step 4 leaves a torn snapshot that loading
skips; a crash after it leaves a fully-verified snapshot. ``load_latest``
walks snapshots newest-first, sha256-verifies every payload against the
manifest, and falls back to the newest *valid* one, counting what it
skipped (``ckpt_corrupt_skipped``) and whether it fell back
(``ckpt_fallbacks``). Commits bump ``ckpt_commits`` and rotation keeps
the last ``keep_last`` committed snapshots.

Fault points (paddle_tpu.fault): ``ckpt.write``, ``ckpt.fsync``,
``ckpt.manifest``, ``ckpt.rename`` — arming ``ckpt.rename`` simulates a
crash at the commit instant with no real kill.
"""
from __future__ import annotations

import hashlib
import json
import os
import shutil
from typing import Dict, List, Optional, Tuple

from ..fault import injector as _fault
from ..fault.injector import _bump  # shared lazy counter shim

__all__ = ["SnapshotStore", "MANIFEST_NAME", "write_file_manifest",
           "verify_file_manifest"]

MANIFEST_NAME = "MANIFEST.json"
_TMP_SUFFIX = ".tmp"
_OLD_SUFFIX = ".old"


def _sha256_file(path: str) -> Tuple[str, int]:
    h = hashlib.sha256()
    nbytes = 0
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
            nbytes += len(chunk)
    return h.hexdigest(), nbytes


def write_file_manifest(manifest_path: str, files: Dict[str, str]) -> str:
    """Write a standalone integrity manifest (same schema as a
    SnapshotStore MANIFEST.json) over existing files: ``files`` maps the
    manifest-relative name to the on-disk path. Used by
    save_inference_model so a serving process can refuse a truncated or
    bit-flipped blob at load time instead of failing deep inside
    deserialization. The manifest itself commits via tmp+fsync+replace."""
    manifest = {"version": 1, "files": {}}
    for name, path in files.items():
        sha, nbytes = _sha256_file(path)
        manifest["files"][name] = {"sha256": sha, "bytes": nbytes}
    tmp = manifest_path + _TMP_SUFFIX
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(manifest, f, sort_keys=True)
        _fsync_fileobj(f)
    os.replace(tmp, manifest_path)
    _fsync_dir(os.path.dirname(manifest_path) or ".")
    return manifest_path


def verify_file_manifest(manifest_path: str, root: str) -> Optional[list]:
    """Check every file listed in ``manifest_path`` against its recorded
    sha256/size (names resolve under ``root``). Returns the list of
    verified names, or None when no manifest exists (nothing to check —
    older blobs stay loadable). Raises ValueError NAMING THE OFFENDING
    PATH on a missing, truncated, or corrupt file, and on an unreadable
    manifest."""
    if not os.path.exists(manifest_path):
        return None
    try:
        with open(manifest_path, encoding="utf-8") as f:
            entries = json.load(f)["files"]
    except (OSError, ValueError, KeyError, TypeError) as e:
        raise ValueError(
            f"integrity manifest {manifest_path!r} is unreadable "
            f"({type(e).__name__}: {e}); re-save the model or delete the "
            "manifest to skip verification") from e
    verified = []
    for name, meta in entries.items():
        path = os.path.join(root, name)
        if not os.path.exists(path):
            raise ValueError(
                f"model file {path!r} is missing but listed in "
                f"{manifest_path!r}; the blob is incomplete — re-save it")
        sha, nbytes = _sha256_file(path)
        if nbytes != meta.get("bytes") or sha != meta.get("sha256"):
            raise ValueError(
                f"model file {path!r} is truncated or corrupt "
                f"(got {nbytes} bytes / sha256 {sha[:12]}..., manifest "
                f"says {meta.get('bytes')} bytes / "
                f"{str(meta.get('sha256'))[:12]}...); the writer was "
                "likely interrupted — re-save the model")
        verified.append(name)
    return verified


class _HashingWriter:
    """File-object shim that sha256's and counts everything written, so
    streaming writers get manifest integrity without a second pass."""

    def __init__(self, f):
        self._f = f
        self._h = hashlib.sha256()
        self.nbytes = 0

    def write(self, b):
        b = bytes(b) if isinstance(b, (bytearray, memoryview)) else b
        self._h.update(b)
        self.nbytes += len(b)
        return self._f.write(b)

    def flush(self):
        self._f.flush()

    def hexdigest(self) -> str:
        return self._h.hexdigest()


def _fsync_fileobj(f) -> None:
    f.flush()
    os.fsync(f.fileno())


def _fsync_dir(path: str) -> None:
    """Make a rename durable: fsync the containing directory (no-op on
    platforms without O_DIRECTORY-style dir fds)."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


class SnapshotStore:
    """Versioned ``<prefix><tag>/`` snapshot dirs under one root."""

    def __init__(self, root: str, keep_last: int = 3,
                 prefix: str = "epoch_"):
        self.root = root
        self.keep_last = max(1, int(keep_last))
        self.prefix = prefix

    # -- naming -------------------------------------------------------------
    def _dir_for(self, tag: int) -> str:
        return os.path.join(self.root, f"{self.prefix}{int(tag)}")

    def _tag_of(self, dirname: str) -> Optional[int]:
        if not dirname.startswith(self.prefix):
            return None
        rest = dirname[len(self.prefix):]
        return int(rest) if rest.isdigit() or (
            rest.startswith("-") and rest[1:].isdigit()) else None

    # -- enumeration --------------------------------------------------------
    def snapshots(self) -> List[Tuple[int, str, bool]]:
        """All snapshot dirs as (tag, path, committed), tag-ascending.
        ``committed`` means a MANIFEST.json exists (content unverified)."""
        out = []
        try:
            entries = os.listdir(self.root)
        except FileNotFoundError:
            return out
        for name in entries:
            if name.endswith(_TMP_SUFFIX):
                continue
            tag = self._tag_of(name)
            path = os.path.join(self.root, name)
            if tag is None or not os.path.isdir(path):
                continue
            committed = os.path.exists(os.path.join(path, MANIFEST_NAME))
            out.append((tag, path, committed))
        out.sort(key=lambda t: t[0])
        return out

    # -- write path ---------------------------------------------------------
    def save(self, tag: int, files: Dict[str, object]) -> str:
        """Write one snapshot atomically; returns the committed dir.

        ``files`` values are bytes or streaming writers
        ``callable(fileobj) -> None`` (e.g. ``lambda f: pickle.dump(obj,
        f)``) — the sha256 is computed while streaming, so a multi-GB
        state dict is never materialized as one bytes object."""
        if not files:
            raise ValueError("snapshot must contain at least one file")
        os.makedirs(self.root, exist_ok=True)
        final = self._dir_for(tag)
        tmp = final + _TMP_SUFFIX
        old = final + _OLD_SUFFIX
        self._recover_aside()
        if os.path.exists(tmp):
            shutil.rmtree(tmp)
        if os.path.exists(final):
            # never delete a committed snapshot before its replacement
            # commits — move it aside (readers ignore non-<prefix><int>
            # names) and drop it only after the new commit succeeds
            if os.path.exists(os.path.join(final, MANIFEST_NAME)):
                os.rename(final, old)
            else:
                shutil.rmtree(final)        # torn leftover: no value
        os.makedirs(tmp)
        manifest = {"version": 1, "tag": int(tag), "files": {}}
        for name, data in files.items():
            if os.sep in name or name == MANIFEST_NAME:
                raise ValueError(f"bad snapshot file name {name!r}")
            _fault.point("ckpt.write")
            with open(os.path.join(tmp, name), "wb") as f:
                writer = _HashingWriter(f)
                if callable(data):
                    data(writer)
                else:
                    writer.write(data)
                _fault.point("ckpt.fsync")
                _fsync_fileobj(f)
            manifest["files"][name] = {"sha256": writer.hexdigest(),
                                       "bytes": writer.nbytes}
        _fault.point("ckpt.manifest")
        with open(os.path.join(tmp, MANIFEST_NAME + _TMP_SUFFIX),
                  "w", encoding="utf-8") as f:
            json.dump(manifest, f, sort_keys=True)
            _fsync_fileobj(f)
        # the dir becomes visible under its final name but is still torn:
        # readers require MANIFEST.json, which does not exist yet
        os.rename(tmp, final)
        _fsync_dir(self.root)
        _fault.point("ckpt.rename")
        # THE commit point: one atomic rename inside the snapshot dir
        os.replace(os.path.join(final, MANIFEST_NAME + _TMP_SUFFIX),
                   os.path.join(final, MANIFEST_NAME))
        _fsync_dir(final)
        if os.path.exists(old):
            shutil.rmtree(old, ignore_errors=True)
        _bump("ckpt_commits")
        self._rotate()
        return final

    def _rotate(self) -> None:
        """Keep the newest ``keep_last`` committed snapshots; drop older
        committed ones and any torn/.tmp dir older than the newest
        commit (a crash before the tmp->final rename must not leak a
        full-size .tmp dir forever)."""
        snaps = self.snapshots()
        committed = [s for s in snaps if s[2]]
        if not committed:
            return
        newest_tag = committed[-1][0]
        keep = {tag for tag, _, _ in committed[-self.keep_last:]}
        for tag, path, is_committed in snaps:
            stale_torn = not is_committed and tag < newest_tag
            evicted = is_committed and tag not in keep
            if stale_torn or evicted:
                shutil.rmtree(path, ignore_errors=True)
        for name in os.listdir(self.root):
            if not name.endswith(_TMP_SUFFIX):
                continue
            tag = self._tag_of(name[:-len(_TMP_SUFFIX)])
            if tag is not None and tag <= newest_tag:
                shutil.rmtree(os.path.join(self.root, name),
                              ignore_errors=True)

    def _recover_aside(self) -> None:
        """Heal ``<dir>.old`` leftovers of a same-tag rewrite that
        crashed: the aside copy is the committed snapshot unless the
        rewrite reached its own commit, so restore or drop accordingly.
        Runs before every save and load."""
        try:
            entries = os.listdir(self.root)
        except FileNotFoundError:
            return
        for name in entries:
            if not name.endswith(_OLD_SUFFIX):
                continue
            aside = os.path.join(self.root, name)
            if not os.path.isdir(aside):
                continue
            final = aside[:-len(_OLD_SUFFIX)]
            if self.verify(final, as_paths=True) is not None:
                shutil.rmtree(aside, ignore_errors=True)
            else:
                if os.path.exists(final):
                    shutil.rmtree(final)
                os.rename(aside, final)

    # -- read path ----------------------------------------------------------
    def verify(self, path: str, as_paths: bool = False):
        """Verify one snapshot dir (sha256 streamed per payload); None on
        any torn/corrupt condition (missing manifest, bad json, size or
        sha256 mismatch, unreadable payload). Returns name->bytes, or
        name->filepath with ``as_paths`` — the streaming option for
        multi-GB states that must not be materialized just to verify."""
        try:
            with open(os.path.join(path, MANIFEST_NAME),
                      encoding="utf-8") as f:
                manifest = json.load(f)
            entries = manifest["files"]
        except (OSError, ValueError, KeyError, TypeError):
            return None
        out: Dict[str, object] = {}
        for name, meta in entries.items():
            fpath = os.path.join(path, name)
            h = hashlib.sha256()
            nbytes = 0
            try:
                with open(fpath, "rb") as f:
                    if as_paths:
                        for chunk in iter(lambda: f.read(1 << 20), b""):
                            h.update(chunk)
                            nbytes += len(chunk)
                    else:
                        data = f.read()
                        h.update(data)
                        nbytes = len(data)
            except OSError:
                return None
            if nbytes != meta.get("bytes") or \
                    h.hexdigest() != meta.get("sha256"):
                return None
            out[name] = fpath if as_paths else data
        return out

    def load_latest(self, as_paths: bool = False):
        """Newest snapshot that verifies end-to-end as (tag, files), or
        None. ``as_paths`` returns verified file paths instead of bytes
        (callers stream-load them, e.g. pickle.load on the open file).

        Torn/corrupt snapshots newer than the winner are skipped (each
        bumps ``ckpt_corrupt_skipped``); returning anything after a skip
        bumps ``ckpt_fallbacks`` once."""
        self._recover_aside()
        skipped = 0
        for tag, path, committed in reversed(self.snapshots()):
            if committed:
                payload = self.verify(path, as_paths=as_paths)
                if payload is not None:
                    if skipped:
                        _bump("ckpt_fallbacks")
                    return tag, payload
            _bump("ckpt_corrupt_skipped")
            skipped += 1
        return None
