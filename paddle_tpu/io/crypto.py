"""Encrypted model io.

Parity with /root/reference/paddle/fluid/framework/io/crypto/ (cipher.cc:33
default "AES_CTR_NoPadding", cipher_utils.cc GenKey/GenKeyToFile): AES-CTR
over serialized checkpoints. The block cipher runs in native C++
(native/src/aes.cc) via ctypes, with a pure-python fallback implementing
the same FIPS-197 algorithm so files interoperate either way.
"""
from __future__ import annotations

import ctypes
import os
from typing import Optional

from ..native import load_library

_SBOX = None


def _sbox():
    global _SBOX
    if _SBOX is None:
        # generate the AES S-box (multiplicative inverse in GF(2^8) +
        # affine transform) instead of embedding the table again
        inv = [0] * 256
        p, q = 1, 1
        while True:
            p = p ^ ((p << 1) & 0xFF) ^ (0x1B if p & 0x80 else 0)
            q ^= q << 1
            q ^= q << 2
            q ^= q << 4
            q &= 0xFF
            if q & 0x80:
                q ^= 0x09
            inv[p] = q
            if p == 1:
                break
        sbox = [0] * 256
        sbox[0] = 0x63
        for i in range(1, 256):
            s = inv[i]
            x = s
            for _ in range(4):
                x = ((x << 1) | (x >> 7)) & 0xFF
                s ^= x
            sbox[i] = s ^ 0x63
        _SBOX = sbox
    return _SBOX


def _expand_key(key: bytes):
    nk = len(key) // 4
    nr = {4: 10, 6: 12, 8: 14}[nk]
    sbox = _sbox()
    w = [list(key[4 * i:4 * i + 4]) for i in range(nk)]
    rcon = 1
    for i in range(nk, 4 * (nr + 1)):
        t = list(w[i - 1])
        if i % nk == 0:
            t = [sbox[t[1]] ^ rcon, sbox[t[2]], sbox[t[3]], sbox[t[0]]]
            rcon = ((rcon << 1) ^ 0x11B) & 0xFF if rcon & 0x80 else rcon << 1
        elif nk > 6 and i % nk == 4:
            t = [sbox[b] for b in t]
        w.append([a ^ b for a, b in zip(w[i - nk], t)])
    return w, nr


def _encrypt_block_py(w, nr, block: bytes) -> bytes:
    sbox = _sbox()

    def xt(x):
        return ((x << 1) ^ 0x1B) & 0xFF if x & 0x80 else x << 1

    s = [block[i] ^ w[i // 4][i % 4] for i in range(16)]
    for rnd in range(1, nr + 1):
        t = [0] * 16
        for c in range(4):
            for r in range(4):
                t[4 * c + r] = sbox[s[4 * ((c + r) & 3) + r]]
        if rnd < nr:
            s = [0] * 16
            for c in range(4):
                a = t[4 * c:4 * c + 4]
                x = a[0] ^ a[1] ^ a[2] ^ a[3]
                for r in range(4):
                    s[4 * c + r] = a[r] ^ x ^ xt(a[r] ^ a[(r + 1) & 3])
        else:
            s = t
        rk = w[4 * rnd:4 * rnd + 4]
        s = [s[i] ^ rk[i // 4][i % 4] for i in range(16)]
    return bytes(s)


def _ctr_py(key: bytes, iv: bytes, data: bytes) -> bytes:
    w, nr = _expand_key(key)
    out = bytearray(data)
    counter = bytearray(iv)
    for off in range(0, len(data), 16):
        stream = _encrypt_block_py(w, nr, bytes(counter))
        for i in range(min(16, len(data) - off)):
            out[off + i] ^= stream[i]
        for i in range(15, -1, -1):
            counter[i] = (counter[i] + 1) & 0xFF
            if counter[i]:
                break
    return bytes(out)


class AESCipher:
    """AES-CTR cipher (reference AESCipher, aes_cipher.cc). Key must be
    16, 24, or 32 bytes. Output layout: 16-byte IV || ciphertext."""

    def __init__(self, key: bytes):
        if len(key) not in (16, 24, 32):
            raise ValueError("AES key must be 16/24/32 bytes, got "
                             f"{len(key)}")
        self._key = bytes(key)
        self._lib = load_library("aes")
        if self._lib is not None and not getattr(self._lib, "_pt_typed",
                                                 False):
            self._lib.pt_aes_ctr_crypt.restype = ctypes.c_int
            self._lib.pt_aes_ctr_crypt.argtypes = [
                ctypes.c_char_p, ctypes.c_int, ctypes.c_char_p,
                ctypes.c_char_p, ctypes.c_int64]
            self._lib.pt_aes_encrypt_block.restype = ctypes.c_int
            self._lib.pt_aes_encrypt_block.argtypes = [
                ctypes.c_char_p, ctypes.c_int, ctypes.c_char_p,
                ctypes.c_char_p]
            self._lib._pt_typed = True

    def _ctr(self, iv: bytes, data: bytes) -> bytes:
        if self._lib is not None:
            buf = ctypes.create_string_buffer(data, len(data))
            rc = self._lib.pt_aes_ctr_crypt(
                self._key, len(self._key), iv, buf, len(data))
            if rc != 0:
                raise RuntimeError("native AES rejected the key")
            return buf.raw
        return _ctr_py(self._key, iv, data)

    def encrypt(self, plaintext: bytes, iv: Optional[bytes] = None) -> bytes:
        iv = iv if iv is not None else os.urandom(16)
        if len(iv) != 16:
            raise ValueError("IV must be 16 bytes")
        return iv + self._ctr(iv, plaintext)

    def decrypt(self, payload: bytes) -> bytes:
        if len(payload) < 16:
            raise ValueError("payload too short to contain an IV")
        return self._ctr(bytes(payload[:16]), bytes(payload[16:]))

    def encrypt_file(self, in_path: str, out_path: str) -> None:
        with open(in_path, "rb") as f:
            data = f.read()
        with open(out_path, "wb") as f:
            f.write(self.encrypt(data))

    def decrypt_file(self, in_path: str, out_path: str) -> None:
        with open(in_path, "rb") as f:
            data = f.read()
        with open(out_path, "wb") as f:
            f.write(self.decrypt(data))


def gen_key(length: int = 32) -> bytes:
    """Random key (reference CipherUtils::GenKey)."""
    return os.urandom(length)


def gen_key_to_file(path: str, length: int = 32) -> bytes:
    """Random key persisted to disk (reference CipherUtils::GenKeyToFile)."""
    key = gen_key(length)
    fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
    try:
        os.write(fd, key)
    finally:
        os.close(fd)
    return key


def read_key_from_file(path: str) -> bytes:
    with open(path, "rb") as f:
        return f.read()
