"""Dataset API: MultiSlot ingestion for CTR/PS-style training.

Reference: /root/reference/python/paddle/fluid/dataset.py
(DatasetFactory, InMemoryDataset with load_into_memory/local_shuffle/
global_shuffle, QueueDataset) wrapping the C++ Dataset
(framework/data_set.h:43). Here the heavy path — parsing, shuffling,
batch assembly, prefetch queue — runs in the native library
(paddle_tpu/native/src/datafeed.cc) and falls back to pure python when
no toolchain exists. Batches come out as numpy: dense slots as
(batch, dim) arrays, sparse slots as (values, lod-offsets) pairs ready
for segment-sum embedding lookups.
"""
from __future__ import annotations

import ctypes
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np


class SlotSpec:
    def __init__(self, name: str, slot_type: str = "uint64",
                 dense_dim: Optional[int] = None):
        assert slot_type in ("float", "uint64"), slot_type
        self.name = name
        self.type = slot_type
        # dense_dim set => fixed-length slot reshaped to (batch, dim)
        self.dense_dim = dense_dim


class DatasetBase:
    def __init__(self):
        self._slots: List[SlotSpec] = []
        self._filelist: List[str] = []
        self._batch_size = 1
        self._thread_num = 1
        self._drop_last = False

    # -- reference-parity config setters ---------------------------------
    def set_batch_size(self, batch_size):
        self._batch_size = int(batch_size)

    def set_thread(self, thread_num):
        self._thread_num = int(thread_num)

    def set_filelist(self, filelist):
        self._filelist = list(filelist)

    def set_use_var(self, slots):
        """Accepts SlotSpec list (or (name, type[, dense_dim]) tuples)."""
        specs = []
        for s in slots:
            if isinstance(s, SlotSpec):
                specs.append(s)
            else:
                specs.append(SlotSpec(*s))
        self._slots = specs

    def slots(self):
        return list(self._slots)


class InMemoryDataset(DatasetBase):
    """load_into_memory -> local_shuffle -> iterate batches.

    Iteration yields {slot_name: array | (values, lod)} dicts.
    """

    def __init__(self):
        super().__init__()
        self._native = None
        self._handle = None
        self._py_records = None  # fallback storage

    def _types_str(self):
        return "".join("f" if s.type == "float" else "u"
                       for s in self._slots)

    # -- loading ----------------------------------------------------------
    def load_into_memory(self):
        from ..native import datafeed_lib

        lib = datafeed_lib()
        if lib is not None:
            self._native = lib
            if self._handle is None:
                self._handle = ctypes.c_void_p(
                    lib.pt_dataset_new(self._types_str().encode()))
            for path in self._filelist:
                n = lib.pt_dataset_load_file(self._handle, path.encode(),
                                             self._thread_num)
                if n < 0:
                    raise IOError(f"failed to parse MultiSlot file {path}")
        else:
            self._py_records = []
            if self._thread_num > 1 and len(self._filelist) > 1:
                # parse files in parallel processes (pure-Python parsing
                # is GIL-bound; the native path threads in C++ instead)
                import concurrent.futures as cf
                import multiprocessing as mp

                specs = [(s.name, s.type, s.dense_dim)
                         for s in self._slots]
                # spawn: fork after jax/XLA init can copy locked mutexes
                with cf.ProcessPoolExecutor(
                        max_workers=min(self._thread_num,
                                        len(self._filelist)),
                        mp_context=mp.get_context("spawn")) as ex:
                    for recs in ex.map(_parse_multislot_file,
                                       self._filelist,
                                       [specs] * len(self._filelist)):
                        self._py_records.extend(recs)
            else:
                for path in self._filelist:
                    self._py_records.extend(self._py_parse(path))

    def ingest_shards(self, n: int):
        """Split this dataset into independent per-file ingestion shards
        for multi-threaded train_from_dataset producers (the TPU-side
        translation of the reference's thread-per-DeviceWorker DataFeed
        channels, data_feed.cc). Only meaningful for streaming datasets
        with several files; in-memory datasets iterate as one shard."""
        return [self]

    def _py_parse(self, path):
        return _parse_multislot_file(
            path, [(s.name, s.type, s.dense_dim) for s in self._slots])

    # -- shuffle ----------------------------------------------------------
    def local_shuffle(self, seed=0):
        if self._native is not None:
            self._native.pt_dataset_shuffle(self._handle, seed)
        elif self._py_records is not None:
            np.random.RandomState(seed).shuffle(self._py_records)

    def global_shuffle(self, fleet=None, seed=0):
        """Single-host build: equivalent to local_shuffle. (The reference
        redistributes records across trainers over RPC, data_set.h:111;
        multi-host ingestion here shards files per host instead — see
        distributed.launch.)"""
        self.local_shuffle(seed)

    def get_memory_data_size(self):
        if self._native is not None:
            return int(self._native.pt_dataset_size(self._handle))
        return len(self._py_records or [])

    def release_memory(self):
        if self._native is not None:
            self._native.pt_dataset_clear(self._handle)
        self._py_records = None

    def _free_native(self):
        if self._native is not None and self._handle is not None:
            try:
                self._native.pt_dataset_free(self._handle)
            except Exception:
                pass
            self._handle = None
            self._native = None

    def __del__(self):
        # ephemeral ingestion shards (ingest_shards) allocate their own
        # C++ Dataset handles; without this they leak per epoch
        self._free_native()

    # -- iteration ---------------------------------------------------------
    def __iter__(self):
        if self._native is not None:
            return self._iter_native()
        return self._iter_py()

    def _iter_native(self):
        lib, h = self._native, self._handle
        lib.pt_dataset_start(h, self._batch_size, int(self._drop_last))
        while lib.pt_dataset_next(h):
            rows = lib.pt_batch_rows(h)
            out = {}
            for i, s in enumerate(self._slots):
                n = lib.pt_batch_slot_size(h, i)
                lod = np.empty(rows + 1, dtype=np.int64)
                lib.pt_batch_lod(h, i, lod.ctypes.data_as(
                    ctypes.POINTER(ctypes.c_int64)))
                if s.type == "float":
                    vals = np.empty(n, dtype=np.float32)
                    if n:
                        lib.pt_batch_slot_fvalues(h, i, vals.ctypes.data_as(
                            ctypes.POINTER(ctypes.c_float)))
                else:
                    vals = np.empty(n, dtype=np.uint64)
                    if n:
                        lib.pt_batch_slot_uvalues(h, i, vals.ctypes.data_as(
                            ctypes.POINTER(ctypes.c_uint64)))
                out[s.name] = self._present(s, vals, lod, rows)
            yield out

    def _iter_py(self):
        recs = self._py_records or []
        bs = self._batch_size
        for lo in range(0, len(recs), bs):
            chunk = recs[lo:lo + bs]
            if self._drop_last and len(chunk) < bs:
                break
            out = {}
            for i, s in enumerate(self._slots):
                vals = np.concatenate([r[i] for r in chunk]) if chunk \
                    else np.empty(0)
                lod = np.zeros(len(chunk) + 1, dtype=np.int64)
                for j, r in enumerate(chunk):
                    lod[j + 1] = lod[j] + len(r[i])
                out[s.name] = self._present(s, vals, lod, len(chunk))
            yield out

    @staticmethod
    def _present(spec: SlotSpec, vals, lod, rows):
        if spec.dense_dim is not None:
            return vals.reshape(rows, spec.dense_dim)
        return vals, lod


def _parse_multislot_file(path, specs):
    """Picklable MultiSlot parser for ProcessPoolExecutor workers."""
    records = []
    with open(path) as f:
        for line in f:
            toks = line.split()
            if not toks:
                continue
            i, rec = 0, []
            for _name, typ, _dd in specs:
                cnt = int(toks[i]); i += 1
                vals = toks[i:i + cnt]; i += cnt
                rec.append(np.array(vals, dtype=np.float32 if typ == "float"
                                    else np.uint64))
            records.append(rec)
    return records


class QueueDataset(InMemoryDataset):
    """Streaming flavor (reference QueueDataset): no global residence
    required. This build loads per-file lazily at iteration time."""

    def __iter__(self):
        if not self._filelist:
            return iter(())
        return self._stream()

    def _stream(self):
        files = self._filelist
        for path in files:
            self._filelist = [path]
            if self._native is not None and self._handle is not None:
                self._native.pt_dataset_clear(self._handle)
            self._py_records = None
            self.load_into_memory()
            yield from super().__iter__()
        self._filelist = files

    def ingest_shards(self, n: int):
        if n <= 1 or len(self._filelist) < 2:
            return [self]
        import copy

        shards = []
        n = min(n, len(self._filelist))
        for i in range(n):
            # copy keeps every config attribute; only the native handle
            # and the file shard are per-clone
            clone = copy.copy(self)
            clone._native = None
            clone._handle = None
            clone._py_records = None
            clone._thread_num = 1
            clone._filelist = self._filelist[i::n]
            shards.append(clone)
        return shards


class DatasetFactory:
    """Reference dataset.py DatasetFactory.create_dataset."""

    def create_dataset(self, datafeed_class="QueueDataset"):
        if datafeed_class == "InMemoryDataset":
            return InMemoryDataset()
        if datafeed_class == "QueueDataset":
            return QueueDataset()
        raise ValueError(f"unknown dataset class {datafeed_class}")
