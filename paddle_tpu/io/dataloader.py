"""Dataset / DataLoader.

Parity with the reference Python data pipeline
(/root/reference/python/paddle/fluid/reader.py:123 DataLoader,
fluid/dataloader/): Dataset/IterableDataset, BatchSampler, multi-worker
prefetch. The reference's C++ double-buffering to GPU
(operators/reader/buffered_reader.cc) maps to background-thread prefetch +
jax.device_put; for peak input rates see paddle_tpu.io.native (C++ feeder).
"""
from __future__ import annotations

import itertools
import multiprocessing
import os
import queue
import sys
import time
import traceback
import uuid
from typing import Optional

import numpy as np

from ..framework.random import default_generator
from ..framework.tensor import Tensor


class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError

    def __getitem__(self, idx):
        raise RuntimeError("IterableDataset has no __getitem__")

    def __len__(self):
        raise RuntimeError("IterableDataset has no __len__")


class TensorDataset(Dataset):
    def __init__(self, tensors):
        self.tensors = tensors

    def __getitem__(self, idx):
        return tuple(t[idx] for t in self.tensors)

    def __len__(self):
        return len(self.tensors[0])


class ComposeDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = datasets

    def __len__(self):
        return min(len(d) for d in self.datasets)

    def __getitem__(self, idx):
        out = []
        for d in self.datasets:
            item = d[idx]
            out.extend(item if isinstance(item, (tuple, list)) else [item])
        return tuple(out)


class ChainDataset(IterableDataset):
    def __init__(self, datasets):
        self.datasets = datasets

    def __iter__(self):
        for d in self.datasets:
            yield from d


class Subset(Dataset):
    def __init__(self, dataset, indices):
        self.dataset = dataset
        self.indices = indices

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


def random_split(dataset, lengths, generator=None):
    total = sum(lengths)
    perm = np.random.RandomState(
        default_generator().initial_seed()).permutation(total)
    out = []
    off = 0
    for n in lengths:
        out.append(Subset(dataset, perm[off:off + n].tolist()))
        off += n
    return out


class Sampler:
    def __init__(self, data_source=None):
        self.data_source = data_source

    def __iter__(self):
        raise NotImplementedError


class SequenceSampler(Sampler):
    def __iter__(self):
        return iter(range(len(self.data_source)))

    def __len__(self):
        return len(self.data_source)


class RandomSampler(Sampler):
    def __init__(self, data_source, replacement=False, num_samples=None,
                 generator=None):
        super().__init__(data_source)
        self.replacement = replacement
        self._num_samples = num_samples

    @property
    def num_samples(self):
        return self._num_samples or len(self.data_source)

    def __iter__(self):
        n = len(self.data_source)
        if self.replacement:
            return iter(np.random.randint(0, n, self.num_samples).tolist())
        return iter(np.random.permutation(n)[:self.num_samples].tolist())

    def __len__(self):
        return self.num_samples


class WeightedRandomSampler(Sampler):
    def __init__(self, weights, num_samples, replacement=True):
        self.weights = np.asarray(weights, np.float64)
        self.num_samples = num_samples
        self.replacement = replacement

    def __iter__(self):
        p = self.weights / self.weights.sum()
        idx = np.random.choice(len(self.weights), self.num_samples,
                               replace=self.replacement, p=p)
        return iter(idx.tolist())

    def __len__(self):
        return self.num_samples


class BatchSampler(Sampler):
    def __init__(self, dataset=None, sampler=None, shuffle=False,
                 batch_size=1, drop_last=False):
        self.batch_size = batch_size
        self.drop_last = drop_last
        if sampler is not None:
            self.sampler = sampler
        elif shuffle:
            self.sampler = RandomSampler(dataset)
        else:
            self.sampler = SequenceSampler(dataset)

    def __iter__(self):
        batch = []
        for idx in self.sampler:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        n = len(self.sampler)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size


class DistributedBatchSampler(BatchSampler):
    """Shards sample indices across data-parallel ranks
    (reference incubate/hapi/distributed.py DistributedBatchSampler)."""

    def __init__(self, dataset, batch_size, num_replicas=None, rank=None,
                 shuffle=False, drop_last=False):
        from ..distributed import get_world_size, get_rank

        self.dataset = dataset
        self.batch_size = batch_size
        self.nranks = num_replicas if num_replicas is not None else get_world_size()
        self.local_rank = rank if rank is not None else get_rank()
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.epoch = 0
        self.num_samples = int(np.ceil(len(dataset) / self.nranks))
        self.total_size = self.num_samples * self.nranks

    def __iter__(self):
        n = len(self.dataset)
        indices = np.arange(n)
        if self.shuffle:
            rng = np.random.RandomState(self.epoch)
            rng.shuffle(indices)
        indices = np.concatenate(
            [indices, indices[:self.total_size - n]])
        indices = indices[self.local_rank::self.nranks]
        batch = []
        for idx in indices:
            batch.append(int(idx))
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        if self.drop_last:
            return self.num_samples // self.batch_size
        return (self.num_samples + self.batch_size - 1) // self.batch_size

    def set_epoch(self, epoch):
        self.epoch = epoch


def default_collate_fn(batch):
    sample = batch[0]
    if isinstance(sample, (tuple, list)):
        return tuple(default_collate_fn([b[i] for b in batch])
                     for i in range(len(sample)))
    if isinstance(sample, dict):
        return {k: default_collate_fn([b[k] for b in batch]) for k in sample}
    if isinstance(sample, Tensor):
        return Tensor(np.stack([b.numpy() for b in batch]))
    if isinstance(sample, np.ndarray):
        return Tensor(np.stack(batch))
    return Tensor(np.asarray(batch))


class WorkerInfo:
    """Per-worker metadata inside a DataLoader worker process
    (reference fluid/dataloader/dataloader_iter.py get_worker_info)."""

    def __init__(self, id, num_workers, seed, dataset):
        self.id = id
        self.num_workers = num_workers
        self.seed = seed
        self.dataset = dataset


_worker_info: Optional[WorkerInfo] = None


def get_worker_info() -> Optional[WorkerInfo]:
    """Inside a worker process: this worker's (id, num_workers, seed,
    dataset); None in the main process. IterableDataset implementations
    use it to shard their stream across workers."""
    return _worker_info


def _np_collate(batch):
    """Numpy-only collate used inside worker processes. Workers must not
    touch jax: the TPU plugin must never be initialized host-side in a
    data worker, and XLA client thread pools do not survive fork."""
    sample = batch[0]
    if isinstance(sample, (tuple, list)):
        return tuple(_np_collate([b[i] for b in batch])
                     for i in range(len(sample)))
    if isinstance(sample, dict):
        return {k: _np_collate([b[k] for b in batch]) for k in sample}
    if isinstance(sample, Tensor):
        return np.stack([b.numpy() for b in batch])
    if isinstance(sample, np.ndarray):
        return np.stack(batch)
    return np.asarray(batch)


def _tree_to_np(tree):
    """Demote Tensor leaves (from a user collate_fn) to numpy for IPC."""
    if isinstance(tree, (tuple, list)):
        return tuple(_tree_to_np(x) for x in tree)
    if isinstance(tree, dict):
        return {k: _tree_to_np(v) for k, v in tree.items()}
    if isinstance(tree, Tensor):
        return tree.numpy()
    return tree


def _contains_tensor(tree) -> bool:
    if isinstance(tree, (tuple, list)):
        return any(_contains_tensor(x) for x in tree)
    if isinstance(tree, dict):
        return any(_contains_tensor(v) for v in tree.values())
    return isinstance(tree, Tensor)


def _tree_to_tensor(tree):
    """Promote ndarray leaves back to Tensors in the consumer process —
    Tensor.__init__'s jnp.asarray IS the h2d transfer, so count it (this
    runs on the DataLoader prefetch thread when buffering is on)."""
    if isinstance(tree, (tuple, list)):
        return tuple(_tree_to_tensor(x) for x in tree)
    if isinstance(tree, dict):
        return {k: _tree_to_tensor(v) for k, v in tree.items()}
    if isinstance(tree, np.ndarray):
        from .. import profiler

        profiler.bump_counter("h2d_bytes", tree.nbytes)
        return Tensor(tree)
    return tree


_SHM_MIN_BYTES = 1 << 15  # below this, pipe pickling beats a shm segment

#: every loader segment carries this prefix AND the pid of the CONSUMER
#: (the process that will unpack and unlink it) so orphans are
#: reclaimable: workers unregister segments from their resource_tracker
#: (ownership transfers to the consumer), so a consumer SIGKILLed before
#: unpacking leaves segments nothing owns (ADVICE r2) — the sweep below
#: reclaims exactly the segments whose consumer is dead. Age alone is
#: not a safe criterion: a prefetched batch can legitimately sit queued
#: for many minutes under slow training steps.
_SHM_PREFIX = f"ptu_shm_{os.getuid() if hasattr(os, 'getuid') else 0}_"
_SHM_ORPHAN_AGE_SEC = 600.0


def _shm_new_segment(nbytes: int):
    from multiprocessing import shared_memory

    # workers are children of the consumer, so getppid names it; in the
    # (single-process shm) edge case the creator is the consumer itself
    consumer = os.getppid() if get_worker_info() is not None else \
        os.getpid()
    for _ in range(8):
        name = f"{_SHM_PREFIX}{consumer}_{uuid.uuid4().hex[:8]}"
        try:
            return shared_memory.SharedMemory(name=name, create=True,
                                              size=nbytes)
        except FileExistsError:
            continue
    return shared_memory.SharedMemory(create=True, size=nbytes)


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
        return True
    except ProcessLookupError:
        return False
    except OSError:
        return True  # EPERM: exists but not ours — treat as alive


def _sweep_orphan_segments(max_age: float = _SHM_ORPHAN_AGE_SEC) -> int:
    """Unlink prefix-named segments whose consumer pid is dead.
    Live consumers are never touched (their prefetched batches may be
    arbitrarily old); unparseable names fall back to the age gate.
    Returns the number reclaimed."""
    shm_dir = "/dev/shm"
    if not os.path.isdir(shm_dir):
        return 0
    reclaimed = 0
    now = time.time()
    for fn in os.listdir(shm_dir):
        if not fn.startswith(_SHM_PREFIX):
            continue
        path = os.path.join(shm_dir, fn)
        try:
            pid_part = fn[len(_SHM_PREFIX):].split("_", 1)[0]
            if pid_part.isdigit():
                dead = not _pid_alive(int(pid_part))
            else:
                dead = now - os.stat(path).st_mtime > max_age
            if dead:
                os.unlink(path)
                reclaimed += 1
        except OSError:
            pass
    return reclaimed


def _shm_pack(tree):
    """Move large ndarray leaves into shared-memory segments so batches
    cross the worker->main pipe as (name, shape, dtype) descriptors
    instead of pickled buffers (reference memory/allocation/
    mmap_allocator.cc shared-memory path)."""
    from multiprocessing import shared_memory

    if isinstance(tree, (tuple, list)):
        return tuple(_shm_pack(x) for x in tree)
    if isinstance(tree, dict):
        return {k: _shm_pack(v) for k, v in tree.items()}
    if isinstance(tree, np.ndarray) and tree.nbytes >= _SHM_MIN_BYTES:
        try:
            seg = _shm_new_segment(tree.nbytes)
        except OSError:  # no /dev/shm: fall back to pipe transport
            return tree
        # count=: the OS may round the mapping up to a page multiple
        np.frombuffer(seg.buf, dtype=tree.dtype,
                      count=tree.size)[:] = tree.reshape(-1)
        desc = ("__shm__", seg.name, tree.shape, str(tree.dtype))
        seg.close()
        # ownership transfers to the consumer (which unlinks after copy);
        # keep this process's resource_tracker from double-unlinking at exit
        try:
            from multiprocessing import resource_tracker

            resource_tracker.unregister(seg._name, "shared_memory")
        except Exception:
            pass
        return desc
    return tree


def _shm_unpack(tree):
    from multiprocessing import shared_memory

    if isinstance(tree, tuple) and len(tree) == 4 and tree[0] == "__shm__":
        _, name, shape, dtype = tree
        seg = shared_memory.SharedMemory(name=name)
        try:
            count = int(np.prod(shape)) if shape else 1
            arr = np.frombuffer(seg.buf, dtype=np.dtype(dtype),
                                count=count).reshape(shape).copy()
        finally:
            seg.close()
            seg.unlink()
        return arr
    if isinstance(tree, (tuple, list)):
        return tuple(_shm_unpack(x) for x in tree)
    if isinstance(tree, dict):
        return {k: _shm_unpack(v) for k, v in tree.items()}
    return tree


def _worker_loop(dataset, is_iterable, batch_size, drop_last, collate_fn,
                 task_q, data_q, stop_event, wid, num_workers, seed,
                 worker_init_fn, use_shm, is_spawn):
    """Body of one DataLoader worker process (reference
    fluid/dataloader/dataloader_iter.py:335 _worker_loop)."""
    if is_spawn:
        # a spawned worker has a fresh interpreter: if sample code touches
        # jax (Tensor datasets), backend bring-up must pin cpu — never the
        # (possibly broken, possibly remote) accelerator plugin
        from ..framework.bringup import force_cpu

        force_cpu()
    global _worker_info
    _worker_info = WorkerInfo(wid, num_workers, seed + wid, dataset)
    np.random.seed((seed + wid) % (1 << 31))
    collate = (_np_collate if collate_fn is None
               else (lambda b: _tree_to_np(collate_fn(b))))
    pack = _shm_pack if use_shm else (lambda t: t)
    try:
        if worker_init_fn is not None:
            worker_init_fn(wid)
        if is_iterable:
            it = iter(dataset)
            while not stop_event.is_set():
                chunk = list(itertools.islice(it, batch_size))
                if not chunk or (len(chunk) < batch_size and drop_last):
                    break
                data_q.put(("data", None, pack(collate(chunk))))
            data_q.put(("done", wid, None))
        else:
            while not stop_event.is_set():
                task = task_q.get()
                if task is None:
                    break
                bid, indices = task
                batch = pack(collate([dataset[i] for i in indices]))
                data_q.put(("data", bid, batch))
    except KeyboardInterrupt:
        pass
    except Exception:
        try:
            data_q.put(("error", wid, traceback.format_exc()))
        except Exception:
            pass
    finally:
        # let the queue feeder flush before the process exits
        data_q.close()
        data_q.join_thread()


class _MultiprocessIter:
    """Multi-worker iteration: a shared task queue feeds worker processes,
    an out-of-order data queue comes back, and the main process reorders
    completed batches so the sampler's order is preserved (reference
    _DataLoaderIterMultiProcess: indices queues + reorder buffer +
    SIGCHLD watchdog; the watchdog here is an is_alive poll)."""

    _POLL_SEC = 1.0

    def __init__(self, loader, epoch: int = 0):
        self.loader = loader
        self.is_iterable = loader.batch_sampler is None
        ctx_name = loader.mp_context
        if ctx_name == "fork" and loader._needs_spawn is None:
            # fork is only safe while workers never touch jax; a dataset
            # yielding Tensors (jax-backed) forces a clean interpreter.
            # Probed once per loader and cached. IterableDatasets are NOT
            # probed (next(iter(ds)) would consume a sample / run __iter__
            # side effects in the parent): pass mp_context="spawn"
            # explicitly for Tensor-yielding iterable datasets.
            if self.is_iterable:
                loader._needs_spawn = False
            else:
                from ..framework.bringup import backends_initialized

                try:
                    jax_live_before = backends_initialized()
                    sample = loader.dataset[0]
                    probe = sample
                    if loader.collate_fn is not default_collate_fn:
                        # a user collate_fn runs worker-side and may
                        # build jax-backed Tensors the raw sample can't
                        # show (ADVICE r2): probe its output too. A
                        # blanket spawn would break local-closure
                        # collate fns (spawn pickles Process args).
                        probe = (sample, loader.collate_fn([sample]))
                    needs = _contains_tensor(probe)
                    if not needs and not jax_live_before and \
                            backends_initialized():
                        # the probe itself initialized jax in the parent
                        # (e.g. collate uses jnp but returns numpy):
                        # forking now IS the hazard — spawn
                        needs = True
                    loader._needs_spawn = needs
                except Exception:
                    loader._needs_spawn = False
        if ctx_name == "fork" and loader._needs_spawn:
            ctx_name = "spawn"
        if ctx_name == "fork" and not loader._needs_spawn:
            # fork-after-jax-init is the deadlock class itself (jax is
            # multithreaded; VERDICT r2 weak #8): once the parent's
            # backends are live, promote to FORKSERVER whenever the
            # worker payload survives pickling — workers then fork from
            # a clean helper process that preloaded this module but
            # never initialized a backend, so worker start stays
            # fork-cheap (spawn pays a full interpreter + jax import
            # per worker) with spawn-grade safety. An unpicklable
            # payload (local closures) keeps fork but gets an
            # actionable warning instead of a silent hazard.
            from ..framework.bringup import backends_initialized

            if backends_initialized() and hasattr(os, "fork") and \
                    not getattr(loader, "_mp_context_explicit", False):
                if loader._picklable is None:
                    # probed once per loader: re-serializing a multi-GB
                    # in-memory dataset every epoch would be absurd
                    import pickle

                    try:
                        pickle.dumps((loader.dataset, loader.collate_fn,
                                      loader.worker_init_fn))
                        loader._picklable = True
                    except Exception:
                        loader._picklable = False
                if loader._picklable:
                    ctx_name = "forkserver"
                else:
                    import warnings

                    warnings.warn(
                        "DataLoader is forking workers after JAX "
                        "initialized in this process, which can "
                        "deadlock (os.fork + multithreaded JAX). The "
                        "dataset/collate_fn are not picklable, so a "
                        "clean worker context cannot be used "
                        "automatically — make them module-level or "
                        "pass mp_context='spawn'.", RuntimeWarning,
                        stacklevel=3)
        self.ctx = multiprocessing.get_context(ctx_name)
        if ctx_name == "forkserver":
            # the server imports this module once (transitively jax, but
            # no backend init); workers inherit the warm modules by fork
            try:
                self.ctx.set_forkserver_preload(["paddle_tpu.io.dataloader"])
            except Exception:
                pass
        self.task_q = self.ctx.Queue()
        self.data_q = self.ctx.Queue()
        self.stop_event = self.ctx.Event()
        self.timeout = loader.timeout
        n = loader.num_workers
        # fresh per-epoch base seed: epoch-invariant seeds would replay
        # the same augmentation stream every epoch (reference
        # dataloader_iter.py draws a new base_seed per iterator)
        seed = default_generator().initial_seed() + 1000003 * epoch
        # user collate runs worker-side (numpy in/out); the default stays
        # None so workers use the jax-free _np_collate
        collate = (None if loader.collate_fn is default_collate_fn
                   else loader.collate_fn)
        if loader.use_shared_memory:
            _sweep_orphan_segments()  # reclaim segments from dead runs
        self.workers = []
        for wid in range(n):
            w = self.ctx.Process(
                target=_worker_loop,
                args=(loader.dataset, self.is_iterable, loader.batch_size
                      if self.is_iterable else 0, loader.drop_last
                      if self.is_iterable else False, collate, self.task_q,
                      self.data_q, self.stop_event, wid, n, seed,
                      loader.worker_init_fn, loader.use_shared_memory,
                      ctx_name in ("spawn", "forkserver")),
                daemon=True)
            w.start()
            self.workers.append(w)

    def _check_workers(self):
        for w in self.workers:
            if w.is_alive():
                continue
            if w.exitcode not in (0, None):
                self._shutdown()
                raise RuntimeError(
                    f"DataLoader worker (pid {w.pid}) exited unexpectedly "
                    f"with exitcode {w.exitcode}. This usually means the "
                    "worker was killed (OOM?) or called os._exit; rerun "
                    "with num_workers=0 to debug in-process.")
            if not self.is_iterable and not getattr(self, "_closed", False):
                # map-mode workers only exit at shutdown (stop event /
                # None sentinel); a clean mid-run exit means sample code
                # called sys.exit/os._exit(0) and took its in-flight
                # batch with it — the reorder buffer would wait on that
                # batch id forever. But a worker that RAISED also exits
                # 0 after putting its ("error", traceback) message:
                # surface that real traceback, not this diagnosis, if
                # it's still in flight (we abort either way, so data
                # payloads only need their shm segments reclaimed)
                err = None
                try:
                    while err is None:
                        msg = self.data_q.get(timeout=0.5)
                        if msg[0] == "error":
                            err = msg
                        elif msg[0] == "data":
                            _shm_unpack(msg[2])
                except queue.Empty:
                    pass
                if err is not None:
                    self._handle(err)  # shuts down + raises w/ traceback
                self._shutdown()
                raise RuntimeError(
                    f"DataLoader worker (pid {w.pid}) exited cleanly "
                    "mid-run (exitcode 0) with batches still pending — "
                    "dataset/collate code must not call sys.exit or "
                    "os._exit; rerun with num_workers=0 to debug "
                    "in-process.")

    def _get(self):
        deadline = time.time() + self.timeout if self.timeout else None
        while True:
            try:
                return self.data_q.get(timeout=self._POLL_SEC)
            except queue.Empty:
                self._check_workers()
                if self.workers and all(
                        not w.is_alive() for w in self.workers):
                    # every worker is gone with exitcode 0 (iterable-mode
                    # sample code os._exit(0) before its "done" marker):
                    # one final drain for messages already in flight,
                    # then surface instead of polling a queue nothing
                    # will ever feed again
                    try:
                        return self.data_q.get(timeout=self._POLL_SEC)
                    except queue.Empty:
                        self._shutdown()
                        raise RuntimeError(
                            "All DataLoader workers exited before "
                            "delivering the remaining batches (worker "
                            "code called os._exit?); rerun with "
                            "num_workers=0 to debug in-process.")
                if deadline is not None and time.time() > deadline:
                    self._shutdown()
                    raise RuntimeError(
                        f"DataLoader timed out after {self.timeout}s "
                        "waiting for a worker batch")

    def _handle(self, msg):
        tag, key, payload = msg
        if tag == "error":
            self._shutdown()
            raise RuntimeError(
                f"DataLoader worker {key} raised:\n{payload}")
        return tag, key, payload

    def __iter__(self):
        try:
            if self.is_iterable:
                yield from self._iter_iterable()
            else:
                yield from self._iter_map()
        finally:
            self._shutdown()

    def _iter_iterable(self):
        done = 0
        while done < len(self.workers):
            tag, _key, payload = self._handle(self._get())
            if tag == "done":
                done += 1
                continue
            yield _tree_to_tensor(_shm_unpack(payload))

    def _iter_map(self):
        batches = list(self.loader.batch_sampler)
        total = len(batches)
        inflight_cap = max(2, self.loader.prefetch) * len(self.workers)
        sent = 0
        while sent < min(inflight_cap, total):
            self.task_q.put((sent, batches[sent]))
            sent += 1
        buffered = {}
        next_bid = 0
        while next_bid < total:
            while next_bid in buffered:
                payload = buffered.pop(next_bid)
                next_bid += 1
                if sent < total:
                    self.task_q.put((sent, batches[sent]))
                    sent += 1
                yield _tree_to_tensor(_shm_unpack(payload))
            if next_bid >= total:
                break
            tag, bid, payload = self._handle(self._get())
            if tag == "data":
                buffered[bid] = payload

    def _drain_once(self):
        """Unpack (and so unlink) any shm-backed batches sitting in the
        data queue — the workers unregistered the segments, so an
        undrained queue would leak /dev/shm until reboot."""
        try:
            while True:
                msg = self.data_q.get_nowait()
                if msg[0] == "data":
                    _shm_unpack(msg[2])
        except Exception:
            pass

    def _shutdown(self):
        if getattr(self, "_closed", False):
            return
        self._closed = True
        self.stop_event.set()   # iterable workers have no task sentinel
        for _w in self.workers:
            try:
                self.task_q.put(None)
            except Exception:
                pass
        # keep draining while workers wind down: a worker mid-batch will
        # still put one more message after the stop signal
        deadline = time.time() + 5.0
        while time.time() < deadline and any(
                w.is_alive() for w in self.workers):
            self._drain_once()
            time.sleep(0.05)
        for w in self.workers:
            w.join(timeout=max(0.1, deadline - time.time()))
            if w.is_alive():
                w.terminate()
        self._drain_once()
        for q in (self.task_q, self.data_q):
            try:
                q.cancel_join_thread()
                q.close()
            except Exception:
                pass

    def __del__(self):
        try:
            self._shutdown()
        except Exception:
            pass


class DataLoader:
    """Iterates a Dataset into device-ready Tensor batches with background
    prefetch (replaces reference GeneratorLoader + buffered_reader).

    num_workers>0 preprocesses batches in that many OS processes
    (reference imperative/data_loader.cc + dataloader_iter.py
    _DataLoaderIterMultiProcess): a shared task queue, shared-memory
    batch transport (use_shared_memory), sampler-order-preserving
    reordering, and a watchdog that surfaces dead workers instead of
    hanging. Worker-side code must stay numpy-only — jax is deliberately
    never touched in workers (host preprocessing feeds the TPU; the
    device path belongs to the main process)."""

    def __init__(self, dataset, feed_list=None, places=None,
                 return_list=True, batch_sampler=None, batch_size=1,
                 shuffle=False, drop_last=False, collate_fn=None,
                 num_workers=0, use_buffer_reader=True, prefetch_factor=2,
                 use_shared_memory=True, timeout=0, worker_init_fn=None,
                 mp_context=None):
        self.dataset = dataset
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = int(num_workers)
        self.prefetch = max(2, prefetch_factor) if use_buffer_reader else 0
        self.return_list = return_list
        self.use_shared_memory = use_shared_memory
        self.timeout = timeout
        self.worker_init_fn = worker_init_fn
        # fork keeps worker startup cheap. An EXPLICIT mp_context="fork"
        # is honored by the fork-after-jax-init forkserver promotion;
        # Tensor-carrying payloads still promote to spawn even under
        # explicit fork (they cannot work forked — correctness beats
        # preference). The default is fully promotable. See
        # _MultiprocessIter.
        self._mp_context_explicit = mp_context is not None
        self.mp_context = mp_context or (
            "fork" if sys.platform.startswith("linux") else "spawn")
        self._epoch = 0
        self._needs_spawn = None   # lazily probed once per loader
        self._picklable = None     # lazily probed once per loader
        if isinstance(dataset, IterableDataset):
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
        else:
            self.batch_sampler = BatchSampler(
                dataset, shuffle=shuffle, batch_size=batch_size,
                drop_last=drop_last)

    def __len__(self):
        if self.batch_sampler is None:
            raise TypeError("IterableDataset has no len()")
        return len(self.batch_sampler)

    def _raw_iter(self):
        if self.batch_sampler is None:
            # iterable dataset: chunk into batches
            it = iter(self.dataset)
            while True:
                chunk = list(itertools.islice(it, self.batch_size))
                if not chunk:
                    return
                if len(chunk) < self.batch_size and self.drop_last:
                    return
                yield self.collate_fn(chunk)
        else:
            for batch_idx in self.batch_sampler:
                yield self.collate_fn([self.dataset[i] for i in batch_idx])

    def __iter__(self):
        from ..static.prefetch import FeedPrefetcher

        if self.num_workers > 0:
            epoch = self._epoch
            self._epoch += 1
            if self.prefetch > 0:
                # the prefetch thread DRIVES the worker iterator, and
                # _iter_map/_iter_iterable promote numpy payloads to
                # Tensors (jnp.asarray = the h2d transfer) as they yield
                # — so batches arrive device-resident and the training
                # thread never pays the copy; no extra staging needed
                pf = FeedPrefetcher(iter(_MultiprocessIter(self,
                                                           epoch=epoch)),
                                    depth=self.prefetch,
                                    stage=lambda batch: batch)
                try:
                    yield from pf
                finally:
                    pf.close()
            else:
                yield from _MultiprocessIter(self, epoch=epoch)
            return
        if self.prefetch <= 0:
            yield from self._raw_iter()
            return
        # single-process prefetch: same bounded-queue/sentinel/abandonment
        # protocol, one implementation (paddle_tpu.static.prefetch).
        # _raw_iter collates on the prefetch thread, so Tensor promotion
        # (= the h2d transfer) also overlaps the consumer's step.
        pf = FeedPrefetcher(self._raw_iter(), depth=self.prefetch,
                            stage=lambda batch: batch)
        try:
            yield from pf
        finally:
            pf.close()
