"""Dataset / DataLoader.

Parity with the reference Python data pipeline
(/root/reference/python/paddle/fluid/reader.py:123 DataLoader,
fluid/dataloader/): Dataset/IterableDataset, BatchSampler, multi-worker
prefetch. The reference's C++ double-buffering to GPU
(operators/reader/buffered_reader.cc) maps to background-thread prefetch +
jax.device_put; for peak input rates see paddle_tpu.io.native (C++ feeder).
"""
from __future__ import annotations

import itertools
import queue
import threading
from typing import Iterable, List, Optional

import numpy as np

from ..framework.random import default_generator
from ..framework.tensor import Tensor


class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError

    def __getitem__(self, idx):
        raise RuntimeError("IterableDataset has no __getitem__")

    def __len__(self):
        raise RuntimeError("IterableDataset has no __len__")


class TensorDataset(Dataset):
    def __init__(self, tensors):
        self.tensors = tensors

    def __getitem__(self, idx):
        return tuple(t[idx] for t in self.tensors)

    def __len__(self):
        return len(self.tensors[0])


class ComposeDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = datasets

    def __len__(self):
        return min(len(d) for d in self.datasets)

    def __getitem__(self, idx):
        out = []
        for d in self.datasets:
            item = d[idx]
            out.extend(item if isinstance(item, (tuple, list)) else [item])
        return tuple(out)


class ChainDataset(IterableDataset):
    def __init__(self, datasets):
        self.datasets = datasets

    def __iter__(self):
        for d in self.datasets:
            yield from d


class Subset(Dataset):
    def __init__(self, dataset, indices):
        self.dataset = dataset
        self.indices = indices

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


def random_split(dataset, lengths, generator=None):
    total = sum(lengths)
    perm = np.random.RandomState(
        default_generator().initial_seed()).permutation(total)
    out = []
    off = 0
    for n in lengths:
        out.append(Subset(dataset, perm[off:off + n].tolist()))
        off += n
    return out


class Sampler:
    def __init__(self, data_source=None):
        self.data_source = data_source

    def __iter__(self):
        raise NotImplementedError


class SequenceSampler(Sampler):
    def __iter__(self):
        return iter(range(len(self.data_source)))

    def __len__(self):
        return len(self.data_source)


class RandomSampler(Sampler):
    def __init__(self, data_source, replacement=False, num_samples=None,
                 generator=None):
        super().__init__(data_source)
        self.replacement = replacement
        self._num_samples = num_samples

    @property
    def num_samples(self):
        return self._num_samples or len(self.data_source)

    def __iter__(self):
        n = len(self.data_source)
        if self.replacement:
            return iter(np.random.randint(0, n, self.num_samples).tolist())
        return iter(np.random.permutation(n)[:self.num_samples].tolist())

    def __len__(self):
        return self.num_samples


class WeightedRandomSampler(Sampler):
    def __init__(self, weights, num_samples, replacement=True):
        self.weights = np.asarray(weights, np.float64)
        self.num_samples = num_samples
        self.replacement = replacement

    def __iter__(self):
        p = self.weights / self.weights.sum()
        idx = np.random.choice(len(self.weights), self.num_samples,
                               replace=self.replacement, p=p)
        return iter(idx.tolist())

    def __len__(self):
        return self.num_samples


class BatchSampler(Sampler):
    def __init__(self, dataset=None, sampler=None, shuffle=False,
                 batch_size=1, drop_last=False):
        self.batch_size = batch_size
        self.drop_last = drop_last
        if sampler is not None:
            self.sampler = sampler
        elif shuffle:
            self.sampler = RandomSampler(dataset)
        else:
            self.sampler = SequenceSampler(dataset)

    def __iter__(self):
        batch = []
        for idx in self.sampler:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        n = len(self.sampler)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size


class DistributedBatchSampler(BatchSampler):
    """Shards sample indices across data-parallel ranks
    (reference incubate/hapi/distributed.py DistributedBatchSampler)."""

    def __init__(self, dataset, batch_size, num_replicas=None, rank=None,
                 shuffle=False, drop_last=False):
        from ..distributed import get_world_size, get_rank

        self.dataset = dataset
        self.batch_size = batch_size
        self.nranks = num_replicas if num_replicas is not None else get_world_size()
        self.local_rank = rank if rank is not None else get_rank()
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.epoch = 0
        self.num_samples = int(np.ceil(len(dataset) / self.nranks))
        self.total_size = self.num_samples * self.nranks

    def __iter__(self):
        n = len(self.dataset)
        indices = np.arange(n)
        if self.shuffle:
            rng = np.random.RandomState(self.epoch)
            rng.shuffle(indices)
        indices = np.concatenate(
            [indices, indices[:self.total_size - n]])
        indices = indices[self.local_rank::self.nranks]
        batch = []
        for idx in indices:
            batch.append(int(idx))
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        if self.drop_last:
            return self.num_samples // self.batch_size
        return (self.num_samples + self.batch_size - 1) // self.batch_size

    def set_epoch(self, epoch):
        self.epoch = epoch


def default_collate_fn(batch):
    sample = batch[0]
    if isinstance(sample, (tuple, list)):
        return tuple(default_collate_fn([b[i] for b in batch])
                     for i in range(len(sample)))
    if isinstance(sample, dict):
        return {k: default_collate_fn([b[k] for b in batch]) for k in sample}
    if isinstance(sample, Tensor):
        return Tensor(np.stack([b.numpy() for b in batch]))
    if isinstance(sample, np.ndarray):
        return Tensor(np.stack(batch))
    return Tensor(np.asarray(batch))


class DataLoader:
    """Iterates a Dataset into device-ready Tensor batches with background
    prefetch (replaces reference GeneratorLoader + buffered_reader)."""

    def __init__(self, dataset, feed_list=None, places=None,
                 return_list=True, batch_sampler=None, batch_size=1,
                 shuffle=False, drop_last=False, collate_fn=None,
                 num_workers=0, use_buffer_reader=True, prefetch_factor=2,
                 use_shared_memory=False, timeout=0, worker_init_fn=None):
        self.dataset = dataset
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = num_workers
        self.prefetch = max(2, prefetch_factor) if use_buffer_reader else 0
        self.return_list = return_list
        if isinstance(dataset, IterableDataset):
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
        else:
            self.batch_sampler = BatchSampler(
                dataset, shuffle=shuffle, batch_size=batch_size,
                drop_last=drop_last)

    def __len__(self):
        if self.batch_sampler is None:
            raise TypeError("IterableDataset has no len()")
        return len(self.batch_sampler)

    def _raw_iter(self):
        if self.batch_sampler is None:
            # iterable dataset: chunk into batches
            it = iter(self.dataset)
            while True:
                chunk = list(itertools.islice(it, self.batch_size))
                if not chunk:
                    return
                if len(chunk) < self.batch_size and self.drop_last:
                    return
                yield self.collate_fn(chunk)
        else:
            for batch_idx in self.batch_sampler:
                yield self.collate_fn([self.dataset[i] for i in batch_idx])

    def __iter__(self):
        if self.prefetch <= 0:
            yield from self._raw_iter()
            return
        q: queue.Queue = queue.Queue(maxsize=self.prefetch)
        sentinel = object()
        err: List[BaseException] = []
        stop = threading.Event()

        def worker():
            try:
                for item in self._raw_iter():
                    # bounded put that notices consumer abandonment, so an
                    # early `break` in the training loop can't leak the
                    # thread blocked on a full queue
                    while not stop.is_set():
                        try:
                            q.put(item, timeout=0.5)
                            break
                        except queue.Full:
                            continue
                    if stop.is_set():
                        return
            except BaseException as e:  # propagate to consumer
                err.append(e)
            finally:
                # blocking put: a full queue must not swallow the sentinel
                # (the consumer would hang on q.get() forever); stays
                # abandonment-aware like the item puts above
                while not stop.is_set():
                    try:
                        q.put(sentinel, timeout=0.5)
                        break
                    except queue.Full:
                        continue

        t = threading.Thread(target=worker, daemon=True)
        t.start()
        try:
            while True:
                item = q.get()
                if item is sentinel:
                    break
                yield item
        finally:
            stop.set()
            while not q.empty():
                try:
                    q.get_nowait()
                except queue.Empty:
                    break
        if err:
            raise err[0]
