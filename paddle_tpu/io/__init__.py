"""IO: datasets, loaders, serialization (reference fluid/reader.py,
fluid/dataloader/, fluid/io.py)."""
from .dataloader import (  # noqa: F401
    Dataset, IterableDataset, TensorDataset, ComposeDataset, ChainDataset,
    Subset, random_split, Sampler, SequenceSampler, RandomSampler,
    WeightedRandomSampler, BatchSampler, DistributedBatchSampler, DataLoader,
    default_collate_fn, get_worker_info, WorkerInfo,
)
from .serialization import (  # noqa: F401
    save, load, save_dygraph, load_dygraph, save_inference_model,
    load_inference_model, save_persistables, load_persistables,
)
from . import fs  # noqa: F401
from . import crypto  # noqa: F401
from .fs import FS, LocalFS, HDFSClient  # noqa: F401
from .crypto import AESCipher, gen_key, gen_key_to_file  # noqa: F401

# reader decorators the reference publishes under paddle.io/paddle.reader
from ..reader import (  # noqa: F401,E402
    buffered, cache, chain, compose, firstn, map_readers, shuffle,
    xmap_readers,
)


def load_program_state(model_path, var_list=None):
    """reference paddle/io io.py load_program_state: read a saved
    persistables snapshot into a plain {name: ndarray} dict without
    touching any scope."""
    import os
    import pickle

    import numpy as np

    state = {}
    if os.path.isdir(model_path):
        for fname in sorted(os.listdir(model_path)):
            p = os.path.join(model_path, fname)
            if not os.path.isfile(p):
                continue
            try:
                state[fname] = np.load(p, allow_pickle=False)
                continue
            except (ValueError, OSError):
                pass
            try:
                with open(p, "rb") as f:
                    blob = pickle.load(f)
            except Exception:
                continue
            if isinstance(blob, dict):
                # combined snapshot (save_persistables params.pdparams)
                state.update({k: np.asarray(v) for k, v in blob.items()})
            else:
                state[fname] = np.asarray(blob)
    else:
        with open(model_path, "rb") as f:
            blob = pickle.load(f)
        if isinstance(blob, dict):
            state = {k: np.asarray(v) for k, v in blob.items()}
        else:
            import os as _os

            state = {_os.path.basename(model_path): np.asarray(blob)}
    if var_list is not None:
        keep = {getattr(v, "name", str(v)) for v in var_list}
        state = {k: v for k, v in state.items() if k in keep}
    return state


def set_program_state(program, state_dict):
    """reference set_program_state: write a {name: ndarray} dict into
    the program's persistable variables in the global scope."""
    import numpy as np

    from ..static.executor import global_scope

    scope = global_scope()
    prog_names = set(program.global_block.vars) if program is not None \
        else None
    missing = []
    for name, value in state_dict.items():
        if prog_names is not None and name not in prog_names:
            missing.append(name)
            continue
        scope.set(name, np.asarray(value))
    if missing:
        import warnings

        warnings.warn(f"set_program_state: variables not in scope: "
                      f"{missing}")


def __getattr__(name):
    # paddle.io.batch (reference python/paddle/io/__init__.py re-exports
    # the batching reader decorator) — lazy to keep reader import cost
    # out of package load
    if name == "batch":
        from ..reader import batch as _batch

        return _batch
    raise AttributeError(name)
