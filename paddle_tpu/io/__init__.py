"""IO: datasets, loaders, serialization (reference fluid/reader.py,
fluid/dataloader/, fluid/io.py)."""
from .dataloader import (  # noqa: F401
    Dataset, IterableDataset, TensorDataset, ComposeDataset, ChainDataset,
    Subset, random_split, Sampler, SequenceSampler, RandomSampler,
    WeightedRandomSampler, BatchSampler, DistributedBatchSampler, DataLoader,
    default_collate_fn, get_worker_info, WorkerInfo,
)
from .serialization import (  # noqa: F401
    save, load, save_dygraph, load_dygraph, save_inference_model,
    load_inference_model, save_persistables, load_persistables,
)
from . import fs  # noqa: F401
from . import crypto  # noqa: F401
from .fs import FS, LocalFS, HDFSClient  # noqa: F401
from .crypto import AESCipher, gen_key, gen_key_to_file  # noqa: F401
