"""Checkpoint save/load.

Parity with /root/reference/python/paddle/fluid/io.py (save :1669 /
load :1730 — single-file .pdparams/.pdopt pickles; save_inference_model
:1164) and dygraph/checkpoint.py save_dygraph/load_dygraph. State dicts of
numpy arrays are pickled; large sharded checkpoints can go through orbax
(paddle_tpu.io.orbax_ckpt) instead.
"""
from __future__ import annotations

import os
import pickle

import numpy as np

from ..framework.tensor import Tensor


def _to_numpy_state(obj):
    if isinstance(obj, Tensor):
        return np.asarray(obj.numpy())
    if isinstance(obj, dict):
        return {k: _to_numpy_state(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_to_numpy_state(v) for v in obj)
    if hasattr(obj, "state_dict") and callable(obj.state_dict):
        return _to_numpy_state(obj.state_dict())
    return obj


def save(obj, path, protocol=4, **configs):
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "wb") as f:
        pickle.dump(_to_numpy_state(obj), f, protocol=protocol)


def load(path, **configs):
    with open(path, "rb") as f:
        return pickle.load(f)


def save_dygraph(state_dict, model_path):
    suffix = ".pdparams"
    if any("moment" in k or k == "step" or "@" in k for k in state_dict):
        suffix = ".pdopt"
    save(state_dict, model_path + suffix)


def load_dygraph(model_path, **configs):
    params = None
    opt = None
    if os.path.exists(model_path + ".pdparams"):
        params = load(model_path + ".pdparams")
    if os.path.exists(model_path + ".pdopt"):
        opt = load(model_path + ".pdopt")
    return params, opt


def save_inference_model(path_prefix, layer, input_spec=None, **configs):
    """Persist params + model class info for predictor reload
    (reference io.py:1164 save_inference_model)."""
    os.makedirs(os.path.dirname(path_prefix) or ".", exist_ok=True)
    save(layer.state_dict(), path_prefix + ".pdiparams")
    meta = {"class": type(layer).__name__}
    with open(path_prefix + ".pdmodel", "wb") as f:
        pickle.dump(meta, f)


def load_inference_model(path_prefix, **configs):
    params = load(path_prefix + ".pdiparams")
    return params


def save_persistables(executor=None, dirname=None, main_program=None,
                      filename=None, layer=None):
    """Static-graph-style persistables save (reference io.py:598)."""
    if layer is not None:
        os.makedirs(dirname, exist_ok=True)
        save(layer.state_dict(), os.path.join(dirname, filename or "params"))


def load_persistables(executor=None, dirname=None, main_program=None,
                      filename=None, layer=None):
    if layer is not None:
        state = load(os.path.join(dirname, filename or "params"))
        layer.set_state_dict(state)
