"""Checkpoint save/load.

Parity with /root/reference/python/paddle/fluid/io.py (save :1669 /
load :1730 — single-file .pdparams/.pdopt pickles; save_inference_model
:1164) and dygraph/checkpoint.py save_dygraph/load_dygraph. State dicts of
numpy arrays are pickled; large sharded checkpoints can go through orbax
(paddle_tpu.io.orbax_ckpt) instead.
"""
from __future__ import annotations

import os
import pickle

import numpy as np

from ..framework.tensor import Tensor


def _to_numpy_state(obj):
    if isinstance(obj, Tensor):
        return np.asarray(obj.numpy())
    if isinstance(obj, dict):
        return {k: _to_numpy_state(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_to_numpy_state(v) for v in obj)
    if hasattr(obj, "state_dict") and callable(obj.state_dict):
        return _to_numpy_state(obj.state_dict())
    return obj


def _atomic_write(path, write_fn):
    """Torn-write-proof file replacement: ``write_fn(f)`` streams into a
    sibling temp file, which is fsync'd, then one atomic ``os.replace``
    (fault point "io.replace") and a directory fsync so a kill at any
    instant leaves either the old file or the complete new one — never a
    truncated mix."""
    from ..fault import injector as _fault
    from .snapshot import _fsync_dir

    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "wb") as f:
            write_fn(f)
            f.flush()
            os.fsync(f.fileno())
        _fault.point("io.replace")
        os.replace(tmp, path)
    except BaseException:
        try:
            os.remove(tmp)
        except OSError:
            pass
        raise
    _fsync_dir(d or ".")


def _atomic_write_bytes(path, data):
    _atomic_write(path, lambda f: f.write(data))


def atomic_pickle_dump(obj, path, protocol=4):
    """Pickle ``obj`` to ``path`` through the atomic-replace protocol.
    Streams pickle.dump into the temp file — a multi-GB state dict must
    not also be materialized as one bytes object at save time."""
    _atomic_write(path, lambda f: pickle.dump(obj, f, protocol=protocol))


def _load_pickle(path):
    """pickle.load with actionable failure modes: a missing or truncated/
    corrupt checkpoint file raises a ValueError naming the path instead
    of leaking a bare EOFError/UnpicklingError from deep inside pickle."""
    if not os.path.exists(path):
        raise ValueError(
            f"io.load: no checkpoint file at {path!r} (missing or "
            "never saved)")
    try:
        with open(path, "rb") as f:
            return pickle.load(f)
    except (EOFError, pickle.UnpicklingError) as e:
        raise ValueError(
            f"io.load: checkpoint file {path!r} is truncated or corrupt "
            f"({type(e).__name__}: {e}) — the writer was likely "
            "interrupted; re-save it or fall back to an older snapshot"
        ) from e


def save(obj, path, protocol=4, **configs):
    atomic_pickle_dump(_to_numpy_state(obj), path, protocol=protocol)


def load(path, **configs):
    return _load_pickle(path)


def save_dygraph(state_dict, model_path):
    suffix = ".pdparams"
    if any("moment" in k or k == "step" or "@" in k for k in state_dict):
        suffix = ".pdopt"
    save(state_dict, model_path + suffix)


def load_dygraph(model_path, **configs):
    # a suffixed path ({prefix}.pdparams / .pdopt) is accepted like the
    # reference (and like paddle_tpu.dygraph.load_dygraph)
    for suffix in (".pdparams", ".pdopt"):
        if model_path.endswith(suffix):
            model_path = model_path[:-len(suffix)]
    params = None
    opt = None
    if os.path.exists(model_path + ".pdparams"):
        params = load(model_path + ".pdparams")
    if os.path.exists(model_path + ".pdopt"):
        opt = load(model_path + ".pdopt")
    if params is None and opt is None:
        raise ValueError(
            f"load_dygraph: neither {model_path}.pdparams nor "
            f"{model_path}.pdopt exists")
    return params, opt


def save_inference_model(path_prefix, layer, input_spec=None, **configs):
    """Persist an inference artifact (reference io.py:1164
    save_inference_model). The .pdmodel file holds a serialized StableHLO
    export of forward (params baked in as constants — the TPU-native
    analogue of the pruned inference ProgramDesc) when input_spec is
    given; .pdiparams holds the state dict for set_state_dict flows."""
    os.makedirs(os.path.dirname(path_prefix) or ".", exist_ok=True)
    save(layer.state_dict(), path_prefix + ".pdiparams")
    meta = {"class": type(layer).__name__, "stablehlo": None,
            "in_shapes": None}
    if input_spec:
        import jax
        import jax.numpy as jnp
        from jax import export as jax_export

        from ..jit import _FunctionalModel

        was_training = getattr(layer, "training", False)
        if hasattr(layer, "eval"):
            layer.eval()
        fmodel = _FunctionalModel(layer)
        params = {n: p.value for n, p in layer.named_parameters()}
        buffers = {n: b.value for n, b in layer.named_buffers()}

        def fwd(*xs):
            out, _ = fmodel(params, buffers, xs, {})
            return out

        structs = []
        for i, spec in enumerate(input_spec):
            dims = tuple(spec.shape or ())
            if any(s is None or (isinstance(s, int) and s < 0)
                   for s in dims):
                # dynamic dims (None/-1) stay symbolic in the export
                expr = ",".join(
                    f"d{i}_{j}" if (s is None or s < 0) else str(s)
                    for j, s in enumerate(dims))
                shape = jax_export.symbolic_shape(expr)
            else:
                shape = dims
            structs.append(jax.ShapeDtypeStruct(shape, jnp.dtype(spec.dtype)))
        exported = jax_export.export(jax.jit(fwd))(*structs)
        meta["stablehlo"] = bytes(exported.serialize())
        # symbolic dims (_DimExpr) don't pickle; record them as None
        meta["in_shapes"] = [
            (tuple(d if isinstance(d, int) else None for d in s.shape),
             str(s.dtype)) for s in structs]
        if was_training and hasattr(layer, "train"):
            layer.train()
    _atomic_write(path_prefix + ".pdmodel",
                  lambda f: pickle.dump(meta, f))
    # per-file sha256 manifest: load_inference_model / Predictor verify
    # it (when present) and refuse a truncated or bit-flipped blob with
    # an error naming the path, instead of failing deep in pickle /
    # StableHLO deserialization
    from .snapshot import write_file_manifest

    base = os.path.basename(path_prefix)
    write_file_manifest(
        path_prefix + ".manifest.json",
        {base + suffix: path_prefix + suffix
         for suffix in (".pdmodel", ".pdiparams")})


class TranslatedLayer:
    """Loaded inference artifact: callable like the original layer's
    forward (reference dygraph jit.load TranslatedLayer)."""

    def __init__(self, exported, params, meta):
        self._exported = exported
        self._params = params
        self._meta = meta

    @property
    def in_shapes(self):
        return self._meta.get("in_shapes")

    def state_dict(self):
        return self._params

    def eval(self):
        return self

    def __call__(self, *args):
        import jax.numpy as jnp

        arrays = [a.value if isinstance(a, Tensor) else jnp.asarray(a)
                  for a in args]
        out = self._exported.call(*arrays)
        return (Tensor(out) if not isinstance(out, (tuple, list))
                else type(out)(Tensor(o) for o in out))

    forward = __call__


def load_inference_model(path_prefix, **configs):
    """Load an inference artifact. Returns a callable TranslatedLayer when
    a StableHLO export is present, else the raw params state dict.

    When a ``<prefix>.manifest.json`` integrity manifest exists (written
    by save_inference_model), every listed file is sha256-verified first;
    a truncated/corrupt blob raises ValueError naming the path."""
    from .snapshot import verify_file_manifest

    verify_file_manifest(path_prefix + ".manifest.json",
                         os.path.dirname(path_prefix) or ".")
    params = load(path_prefix + ".pdiparams")
    meta_path = path_prefix + ".pdmodel"
    if os.path.exists(meta_path):
        with open(meta_path, "rb") as f:
            meta = pickle.load(f)
        if meta.get("stablehlo"):
            from jax import export as jax_export

            exported = jax_export.deserialize(bytearray(meta["stablehlo"]))
            return TranslatedLayer(exported, params, meta)
    return params


def save_persistables(executor=None, dirname=None, main_program=None,
                      filename=None, layer=None):
    """Static-graph-style persistables save (reference io.py:598)."""
    if layer is not None:
        os.makedirs(dirname, exist_ok=True)
        save(layer.state_dict(), os.path.join(dirname, filename or "params"))


def load_persistables(executor=None, dirname=None, main_program=None,
                      filename=None, layer=None):
    if layer is not None:
        state = load(os.path.join(dirname, filename or "params"))
        layer.set_state_dict(state)
