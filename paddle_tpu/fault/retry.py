"""Retry with exponential backoff + jitter.

The repo-wide policy object for transient failures: network fetches
(incubate weights, fleet KV barriers), filesystem flakes (NFS-mounted
checkpoint roots), and the launch supervisor's relaunch pacing all share
this one implementation so budget/backoff semantics — and their
counters — stay uniform.

Defaults come from env knobs so an operator can harden a job without
code changes::

    PADDLE_RETRY_MAX_ATTEMPTS   total attempts incl. the first (default 3)
    PADDLE_RETRY_BASE_DELAY_S   first backoff delay (default 0.1)
    PADDLE_RETRY_MAX_DELAY_S    backoff cap (default 30.0)

Counters (paddle_tpu.profiler, surfaced via ``exe.counters`` and bench
rows): ``retry_attempts`` — re-attempts after a retryable failure;
``retry_giveups`` — exhaustions (budget/deadline spent, last error
re-raised).
"""
from __future__ import annotations

import functools
import os
import random
import time
from typing import Callable, Optional, Tuple, Type, Union

__all__ = ["Backoff", "Retrier", "retry", "env_backoff",
           "env_max_attempts"]


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except (TypeError, ValueError):
        return default


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except (TypeError, ValueError):
        return default


class Backoff:
    """Exponential backoff schedule with proportional jitter.

    ``delay(attempt)`` for attempt 0,1,2,... is
    ``min(cap, base * factor**attempt)`` with the last ``jitter``
    fraction of it randomized (jitter=0 → deterministic, for tests;
    jitter=1 → full jitter a la the AWS architecture blog).
    """

    def __init__(self, base: Optional[float] = None, factor: float = 2.0,
                 cap: Optional[float] = None, jitter: float = 0.5,
                 rng: Optional[random.Random] = None):
        self.base = (base if base is not None
                     else _env_float("PADDLE_RETRY_BASE_DELAY_S", 0.1))
        self.factor = float(factor)
        self.cap = (cap if cap is not None
                    else _env_float("PADDLE_RETRY_MAX_DELAY_S", 30.0))
        self.jitter = min(1.0, max(0.0, float(jitter)))
        self._rng = rng or random.Random()

    def delay(self, attempt: int) -> float:
        raw = min(self.cap, self.base * (self.factor ** max(0, attempt)))
        if self.jitter <= 0.0:
            return raw
        fixed = raw * (1.0 - self.jitter)
        return fixed + self._rng.random() * (raw - fixed)


def env_backoff(base: float, cap: float, **kwargs) -> Backoff:
    """A Backoff with site-specific defaults that the PADDLE_RETRY_*
    env knobs override — call sites that hard-code a schedule would
    otherwise make the documented operator knobs dead letters."""
    return Backoff(base=_env_float("PADDLE_RETRY_BASE_DELAY_S", base),
                   cap=_env_float("PADDLE_RETRY_MAX_DELAY_S", cap),
                   **kwargs)


def env_max_attempts(default: int) -> int:
    """Site default for attempt budget, overridable by
    PADDLE_RETRY_MAX_ATTEMPTS."""
    return _env_int("PADDLE_RETRY_MAX_ATTEMPTS", default)


_RetryOn = Union[Type[BaseException], Tuple[Type[BaseException], ...],
                 Callable[[BaseException], bool]]


class Retrier:
    """Callable retry policy: deadline, attempt budget, exception filter.

    Usable three ways::

        Retrier(max_attempts=5).call(fetch, url)     # imperative
        @Retrier(retry_on=(OSError,))                # decorator
        def fetch(url): ...
        retry(max_attempts=5)(fetch)                 # via the helper

    ``retry_on`` is an exception type/tuple or a predicate; ``giveup_on``
    types pass through immediately even when they match ``retry_on``
    (e.g. retry OSError but never FileNotFoundError). On exhaustion the
    LAST error is re-raised — no wrapper type to unwrap at call sites.
    """

    def __init__(self, max_attempts: Optional[int] = None,
                 deadline: Optional[float] = None,
                 backoff: Optional[Backoff] = None,
                 retry_on: _RetryOn = (OSError, ConnectionError,
                                       TimeoutError),
                 giveup_on: Tuple[Type[BaseException], ...] = (),
                 sleep: Callable[[float], None] = time.sleep,
                 name: Optional[str] = None):
        self.max_attempts = (max_attempts if max_attempts is not None
                             else _env_int("PADDLE_RETRY_MAX_ATTEMPTS", 3))
        if self.max_attempts < 1:
            raise ValueError("max_attempts must be >= 1")
        self.deadline = deadline
        self.backoff = backoff or Backoff()
        self.retry_on = retry_on
        self.giveup_on = tuple(giveup_on)
        self._sleep = sleep
        self.name = name

    def _retryable(self, exc: BaseException) -> bool:
        if self.giveup_on and isinstance(exc, self.giveup_on):
            return False
        if callable(self.retry_on) and not isinstance(self.retry_on, type):
            return bool(self.retry_on(exc))
        return isinstance(exc, self.retry_on)

    def call(self, fn: Callable, *args, **kwargs):
        from .. import profiler

        t0 = time.monotonic()
        attempt = 0
        while True:
            try:
                return fn(*args, **kwargs)
            except BaseException as e:  # noqa: B036 (filtered below)
                if not self._retryable(e):
                    raise
                attempt += 1
                out_of_budget = attempt >= self.max_attempts
                delay = self.backoff.delay(attempt - 1)
                past_deadline = (
                    self.deadline is not None
                    and time.monotonic() - t0 + delay > self.deadline)
                if out_of_budget or past_deadline:
                    profiler.bump_counter("retry_giveups")
                    try:
                        from ..observability.flight_recorder import \
                            flight_recorder

                        fr = flight_recorder()
                        fr.record("retry_giveup", name=self.name,
                                  attempts=attempt,
                                  error=type(e).__name__,
                                  message=str(e)[:500])
                        fr.dump(reason=f"retry_giveup:{self.name}")
                    except Exception:
                        pass   # postmortem writer must not mask the error
                    raise
                profiler.bump_counter("retry_attempts")
                self._sleep(delay)

    def __call__(self, fn: Callable) -> Callable:
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            return self.call(fn, *args, **kwargs)

        wrapper.retrier = self
        return wrapper

    wrap = __call__


def retry(fn: Optional[Callable] = None, **kwargs) -> Callable:
    """Decorator form: ``@retry``, ``@retry(max_attempts=5, ...)``, or
    direct ``retry(fn, max_attempts=5)`` -> wrapped callable.

    Keyword arguments are Retrier's.
    """
    if fn is None:
        return Retrier(**kwargs)
    if not callable(fn):
        raise TypeError(f"retry: first argument must be callable, "
                        f"got {fn!r}")
    return Retrier(**kwargs)(fn)
