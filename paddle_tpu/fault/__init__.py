"""paddle_tpu.fault — failure as a first-class, testable code path.

Two building blocks the rest of the framework composes:

- :mod:`retry` — ``Retrier``/``retry``: exponential backoff with jitter,
  attempt budget, wall-clock deadline, retryable-exception filter.
- :mod:`injector` — ``FaultInjector``/``fault.point(name)``: named fault
  points that tests or ``PADDLE_FAULT_SPEC`` arm to fail
  deterministically N times, so every recovery path (torn checkpoint
  commit, transient fetch failure, trainer relaunch) is exercisable in
  CI without real kills.

All activity lands in process-global profiler counters
(``retry_attempts``, ``retry_giveups``, ``faults_injected``, ...)
surfaced through ``Executor.counters`` and bench rows.
"""
from . import injector  # noqa: F401
from .injector import (  # noqa: F401
    FaultInjector, InjectedFault, arm, armed, default_injector, disarm,
    disarm_all, load_env_spec, point,
)
from .retry import Backoff, Retrier, retry  # noqa: F401
