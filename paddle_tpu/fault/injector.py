"""Deterministic fault injection at named code points.

Production fault tolerance is only trustworthy if the failure paths run
in CI without real kills. Every robustness feature in this repo
(crash-safe snapshot commit, retry wiring, supervised relaunch) passes
through a named ``fault.point("...")`` call on its critical transition;
tests — or the ``PADDLE_FAULT_SPEC`` env var — arm a point to fail
deterministically N times, after which it passes again. The reference
codebase has no equivalent; the design follows the failpoint idiom
(freebsd fail(9) / tikv fail-rs): zero cost unarmed, exact-name match
first, then fnmatch patterns.

Points in use (grep for ``point(`` to enumerate):

    ckpt.write        before each snapshot payload file is written
    ckpt.fsync        before each payload fsync
    ckpt.manifest     before the manifest temp file is written
    ckpt.rename       before the manifest commit rename (THE commit point)
    io.replace        before serialization's atomic os.replace
    launch.relaunch   before the supervisor re-execs a dead trainer
    http_kv.request   before each KV client HTTP round-trip
    download.resolve  before hapi download cache resolution
    download.fetch    before the incubate weights fetch
    serve.admit       serving-engine admission (inference/serving.py)
    serve.assemble    before a serving tick pops its batch
    serve.dispatch    before each compiled serving dispatch (retried)
    serve.respond     before each per-request result delivery
    serve.fallback    before each degraded batch-1 eager fallback
    ps.pull           before each PSClient pull/rows/keys RPC attempt
    ps.push           before each PSClient push/merge/assign RPC attempt
    ps.barrier        before each PSClient barrier RPC (single attempt)
    ps.save           before each PSClient save/snapshot RPC attempt
    ps.heartbeat      before each PSClient trainer heartbeat
    ps.apply          server-side, before a pserver applies a write —
                      the kill-a-primary chaos-drill point
                      (PADDLE_FAULT_SPEC="ps.apply:1@K:SystemExit")

``PADDLE_FAULT_SPEC`` grammar — comma-separated triggers::

    point:times[@after][:ExcName[:message]]
    e.g. PADDLE_FAULT_SPEC="ckpt.rename:2:OSError:injected,download.fetch:1"
         PADDLE_FAULT_SPEC="ckpt.rename:1@2"   # fail the 3rd hit only

ExcName resolves from builtins (OSError, TimeoutError, ...); default is
InjectedFault. Each injected raise bumps the process-global
``faults_injected`` counter (paddle_tpu.profiler). Note the spec re-arms
in every process that imports paddle_tpu — a relaunched trainer starts
with fresh hit counts, so ``@after`` is how a chaos drill lets the
retried incarnation get past the point it killed the previous one at.
"""
from __future__ import annotations

import fnmatch
import os
import threading
from typing import Dict, Optional

__all__ = ["InjectedFault", "FaultInjector", "arm", "disarm", "disarm_all",
           "point", "armed", "load_env_spec", "default_injector"]

_ENV_SPEC = "PADDLE_FAULT_SPEC"


class InjectedFault(RuntimeError):
    """Raised by an armed fault point (unless armed with another type)."""


def _bump(name: str, n: int = 1) -> None:
    # lazy: fault must stay importable without pulling jax via profiler
    from .. import profiler

    profiler.bump_counter(name, n)


class _Trigger:
    __slots__ = ("times", "exc_type", "message", "after", "hits", "fired")

    def __init__(self, times: int, exc_type: type, message: str,
                 after: int = 0):
        self.times = int(times)
        self.exc_type = exc_type
        self.message = message
        self.after = int(after)
        self.hits = 0
        self.fired = 0


class FaultInjector:
    """Named fault points armed to fail deterministically N times."""

    def __init__(self, env_spec: Optional[str] = None):
        self._lock = threading.Lock()
        self._triggers: Dict[str, _Trigger] = {}
        if env_spec:
            self.load_spec(env_spec)

    # -- arming -------------------------------------------------------------
    def arm(self, name: str, times: int = 1, exc: Optional[type] = None,
            message: Optional[str] = None, after: int = 0) -> None:
        """Make ``point(name)`` raise ``exc`` (a type; default
        InjectedFault) on ``times`` hits, skipping the first ``after``
        hits ("crash the 3rd commit" = after=2, times=1). ``name`` may
        be an fnmatch pattern ("ckpt.*")."""
        if exc is not None and not (isinstance(exc, type)
                                    and issubclass(exc, BaseException)):
            raise TypeError(f"exc must be an exception type, got {exc!r}")
        with self._lock:
            self._triggers[name] = _Trigger(
                times, exc or InjectedFault,
                message or f"injected fault at {name!r}", after=after)

    def disarm(self, name: str) -> None:
        with self._lock:
            self._triggers.pop(name, None)

    def disarm_all(self) -> None:
        with self._lock:
            self._triggers.clear()

    def armed(self, name: str) -> int:
        """Remaining failures the next hits of ``name`` will see."""
        with self._lock:
            t = self._find(name)
            return max(0, t.times - t.fired) if t else 0

    def load_spec(self, spec: str) -> None:
        """Parse a PADDLE_FAULT_SPEC string and arm its triggers."""
        for part in spec.split(","):
            part = part.strip()
            if not part:
                continue
            fields = part.split(":", 3)
            if len(fields) < 2:
                raise ValueError(
                    f"bad {_ENV_SPEC} entry {part!r}: want "
                    "point:times[@after][:ExcName[:message]]")
            name = fields[0]
            times_field, _, after_field = fields[1].partition("@")
            try:
                times = int(times_field)
                after = int(after_field) if after_field else 0
            except ValueError:
                raise ValueError(
                    f"bad {_ENV_SPEC} counts {fields[1]!r} in {part!r}: "
                    "want times[@after] as integers") from None
            exc: type = InjectedFault
            if len(fields) >= 3 and fields[2]:
                import builtins

                exc = getattr(builtins, fields[2], None)
                if not (isinstance(exc, type)
                        and issubclass(exc, BaseException)):
                    raise ValueError(
                        f"bad {_ENV_SPEC} exception {fields[2]!r} "
                        f"in {part!r}")
            message = (fields[3] if len(fields) == 4
                       else f"injected fault at {name!r} ({_ENV_SPEC})")
            self.arm(name, times=times, exc=exc, message=message,
                     after=after)

    # -- firing -------------------------------------------------------------
    def _find(self, name: str) -> Optional[_Trigger]:
        t = self._triggers.get(name)
        if t is not None:
            return t
        for pat, trig in self._triggers.items():
            if fnmatch.fnmatchcase(name, pat):
                return trig
        return None

    def point(self, name: str) -> None:
        """Fault point: no-op unless armed; armed, raises and consumes
        one failure."""
        with self._lock:
            t = self._find(name)
            if t is None:
                return
            t.hits += 1
            if t.hits <= t.after or t.fired >= t.times:
                return
            t.fired += 1
            exc = t.exc_type(t.message)
        _bump("faults_injected")
        # flight recorder, BEFORE the raise propagates: a chaos
        # SystemExit often dies via os._exit (no atexit, no teardown),
        # so the postmortem must hit disk here or never
        try:
            from ..observability.flight_recorder import flight_recorder

            fr = flight_recorder()
            fr.record("fault_injected", point=name,
                      error=type(exc).__name__, message=str(exc))
            fr.dump(reason=f"fault_injected:{name}")
        except Exception:
            pass   # the chaos knob must not mask its own fault
        raise exc


# -- module-level default injector (what production call sites use) ---------
try:
    default_injector = FaultInjector(os.environ.get(_ENV_SPEC))
except ValueError as _e:
    # a malformed job-wide spec must not brick `import paddle_tpu` for
    # every trainer/tool in the environment — the chaos knob cannot be
    # allowed to take down the process it exists to harden
    import warnings as _warnings

    _warnings.warn(f"ignoring malformed {_ENV_SPEC}: {_e}", RuntimeWarning)
    default_injector = FaultInjector()


def arm(name: str, times: int = 1, exc: Optional[type] = None,
        message: Optional[str] = None, after: int = 0) -> None:
    default_injector.arm(name, times=times, exc=exc, message=message,
                         after=after)


def disarm(name: str) -> None:
    default_injector.disarm(name)


def disarm_all() -> None:
    default_injector.disarm_all()


def armed(name: str) -> int:
    return default_injector.armed(name)


def point(name: str) -> None:
    default_injector.point(name)


def load_env_spec(spec: Optional[str] = None) -> None:
    """(Re)load triggers from ``spec`` or the live PADDLE_FAULT_SPEC."""
    spec = spec if spec is not None else os.environ.get(_ENV_SPEC, "")
    if spec:
        default_injector.load_spec(spec)
