"""Automatic mixed precision.

Parity with the reference AMP stack (/root/reference/python/paddle/fluid/
dygraph/amp/auto_cast.py:90 amp_guard, loss_scaler.py:27 AmpScaler,
contrib/mixed_precision/decorator.py, operators/amp/
amp_check_finite_and_scale_op.cc). On TPU the low-precision type is
bfloat16, which needs no loss scaling for convergence — GradScaler is kept
for API parity and for float16 experiments; auto_cast switches the op
white-list to bf16 inputs.
"""
from __future__ import annotations

import threading

import jax
import jax.numpy as jnp

from ..framework.tensor import Tensor

_state = threading.local()

# ops whose inputs are cast down under autocast (reference fp16_lists.py
# white_list) — matmul/conv ride the MXU in bf16.
WHITE_LIST = {"matmul", "conv1d", "conv2d", "conv3d", "linear", "bmm", "mv",
              "einsum"}
# numerically sensitive ops stay f32 (reference black_list)
BLACK_LIST = {"softmax_with_cross_entropy", "softmax", "log_softmax",
              "layer_norm", "reduce_mean", "reduce_sum", "exp", "log",
              "norm", "p_norm", "logsumexp"}


def amp_enabled():
    return getattr(_state, "amp_level", "O0") != "O0"


def amp_dtype():
    return getattr(_state, "amp_dtype", jnp.bfloat16)


class auto_cast:
    """with amp.auto_cast(): matmul-family ops run in bf16."""

    def __init__(self, enable=True, custom_white_list=None,
                 custom_black_list=None, level="O1", dtype="bfloat16"):
        self.enable = enable
        self.level = level if enable else "O0"
        self.dtype = jnp.bfloat16 if str(dtype) in ("bfloat16", "bf16") \
            else jnp.float16
        self.white = set(custom_white_list or ()) | WHITE_LIST
        self.black = set(custom_black_list or ()) | BLACK_LIST

    def __enter__(self):
        self._prev = (getattr(_state, "amp_level", "O0"),
                      getattr(_state, "amp_dtype", jnp.bfloat16),
                      getattr(_state, "amp_white", None),
                      getattr(_state, "amp_black", None))
        _state.amp_level = self.level
        _state.amp_dtype = self.dtype
        _state.amp_white = self.white
        _state.amp_black = self.black
        return self

    def __exit__(self, *exc):
        (_state.amp_level, _state.amp_dtype, _state.amp_white,
         _state.amp_black) = self._prev
        return False


amp_guard = auto_cast


def maybe_cast_inputs(op_name, arrays):
    """Called by the op bridge under autocast (white-list policy)."""
    level = getattr(_state, "amp_level", "O0")
    if level == "O0":
        return arrays
    white = getattr(_state, "amp_white", WHITE_LIST)
    black = getattr(_state, "amp_black", BLACK_LIST)
    dt = amp_dtype()
    if op_name in white or level == "O2" and op_name not in black:
        return [a.astype(dt) if hasattr(a, "dtype") and
                jnp.issubdtype(a.dtype, jnp.floating) else a for a in arrays]
    if op_name in black:
        return [a.astype(jnp.float32) if hasattr(a, "dtype") and
                a.dtype in (jnp.bfloat16, jnp.float16) else a for a in arrays]
    return arrays


class GradScaler:
    """Dynamic loss scaling (reference loss_scaler.py AmpScaler)."""

    def __init__(self, enable=True, init_loss_scaling=2.0 ** 15,
                 incr_ratio=2.0, decr_ratio=0.5, incr_every_n_steps=1000,
                 decr_every_n_nan_or_inf=2, use_dynamic_loss_scaling=True):
        self._enable = enable
        self._scale = float(init_loss_scaling) if enable else 1.0
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._incr_every = incr_every_n_steps
        self._decr_every = decr_every_n_nan_or_inf
        self._dynamic = use_dynamic_loss_scaling
        self._good = 0
        self._bad = 0
        self._found_inf = False
        self._unscaled = False

    def scale(self, loss):
        if not self._enable:
            return loss
        return loss * self._scale

    def unscale_(self, optimizer):
        if not self._enable or self._unscaled:
            return
        found = False
        for p in optimizer._params():
            if p.grad is not None:
                g = p.grad.value / self._scale
                if bool(jnp.any(~jnp.isfinite(g))):
                    found = True
                p.grad._value = g
        self._found_inf = found
        self._unscaled = True

    def step(self, optimizer):
        if not self._enable:
            optimizer.step()
            return
        self.unscale_(optimizer)  # no-op if the user already unscaled
        if self._found_inf:
            optimizer.clear_grad()
        else:
            optimizer.step()
        self._unscaled = False

    def update(self):
        if not self._enable or not self._dynamic:
            return
        if self._found_inf:
            self._bad += 1
            self._good = 0
            if self._bad >= self._decr_every:
                self._scale = max(self._scale * self._decr_ratio, 1.0)
                self._bad = 0
        else:
            self._good += 1
            self._bad = 0
            if self._good >= self._incr_every:
                self._scale *= self._incr_ratio
                self._good = 0
        self._found_inf = False

    def minimize(self, optimizer, scaled_loss):
        self.step(optimizer)
        self.update()

    def is_enable(self):
        return self._enable

    def get_loss_scaling(self):
        return self._scale

    def state_dict(self):
        return {"scale": self._scale, "good": self._good, "bad": self._bad}

    def set_state_dict(self, state):
        self._scale = state["scale"]
        self._good = state["good"]
        self._bad = state["bad"]


AmpScaler = GradScaler


def decorate(models=None, optimizers=None, level="O1", dtype="bfloat16",
             master_weight=None, save_dtype=None):
    """paddle.amp.decorate parity.

    O2 casts layer params to ``dtype`` (bf16/fp16 compute).
    ``master_weight`` (default on under O2, pass False to opt out)
    flips the optimizers to multi-precision: each low-precision param
    keeps an f32 master copy in its optimizer slot, the update rule runs
    in f32, and the compute param receives the cast-down of the master —
    so repeated tiny updates don't vanish into bf16 rounding.
    ``save_dtype`` pins ``model.state_dict()`` output to that dtype
    regardless of the live compute precision (checkpoint portability).
    """
    targets = [] if models is None else (
        list(models) if isinstance(models, (list, tuple)) else [models])
    opts = [] if optimizers is None else (
        list(optimizers) if isinstance(optimizers, (list, tuple))
        else [optimizers])
    if level == "O2":
        want_masters = master_weight is None or master_weight
        # snapshot the f32 params BEFORE the cast: the master must carry
        # the full-precision bits, not a bf16 round trip
        masters = {}
        if want_masters:
            for m in targets:
                for p in m.parameters():
                    if jnp.issubdtype(p.value.dtype, jnp.floating):
                        masters[id(p)] = p.value.astype(jnp.float32)
        for m in targets:
            m.to(dtype=dtype)
        if want_masters:
            for o in opts:
                if not hasattr(o, "_multi_precision"):
                    continue
                o._multi_precision = True
                # a cached jitted update traced the master-less slot
                # structure — retrace
                o._jit_update = None
                # upgrade slots that already exist (warmed-up optimizer
                # or restored checkpoint) and pre-seed the rest, so the
                # first post-decorate step takes the master path instead
                # of silently promoting the param back to f32
                for p in (o._parameter_list or []):
                    master = masters.get(id(p))
                    if master is None:
                        continue
                    slot = o._slots.get(id(p))
                    if slot is None:
                        slot = dict(o.init_slot(master))
                        o._slots[id(p)] = slot
                    slot.setdefault("__master__", master)
    if save_dtype is not None:
        for m in targets:
            m._amp_save_dtype = str(save_dtype)
    if optimizers is None:
        return models
    return models, optimizers
