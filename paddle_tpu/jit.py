"""jit: compiled execution of eager-defined models.

TPU-native replacement for the reference @to_static / dygraph_to_static AST
rewriter (/root/reference/python/paddle/fluid/dygraph/dygraph_to_static/)
and the static-graph Executor fast path: instead of rewriting Python into a
ProgramDesc, the layer's parameters/buffers are swapped for tracers and the
unchanged Python forward is traced by jax.jit into one XLA program.
TrainStep fuses forward+backward+optimizer into a single compiled step —
the moral equivalent of ParallelExecutor's build-once-run-many graph.
"""
from __future__ import annotations

import functools
from typing import Callable, Optional

import jax
import jax.numpy as jnp

from .framework import random as random_mod
from .framework import tape as tape_mod
from .framework.random import rng_scope
from .framework.tensor import Tensor
from .nn.layer import Layer

_tree = jax.tree_util


def _wrap_in(x):
    return Tensor(x) if isinstance(x, jax.Array) else x


def _unwrap_out(x):
    return x.value if isinstance(x, Tensor) else x


class _FunctionalModel:
    """Pure-function view of a Layer: (params, buffers, *args) -> out."""

    def __init__(self, layer: Layer):
        self.layer = layer

    def __call__(self, params, buffers, args, kwargs, rng_key=None):
        layer = self.layer
        saved_p = {n: p._value for n, p in layer.named_parameters()}
        saved_b = {n: b._value for n, b in layer.named_buffers()}
        layer.load_param_pytree(params)
        layer.load_buffer_pytree(buffers)
        try:
            with tape_mod.no_grad():
                if rng_key is not None:
                    with rng_scope(rng_key):
                        out = layer(*[_wrap_in(a) for a in args],
                                    **{k: _wrap_in(v) for k, v in kwargs.items()})
                else:
                    out = layer(*[_wrap_in(a) for a in args],
                                **{k: _wrap_in(v) for k, v in kwargs.items()})
            new_buffers = {n: b._value for n, b in layer.named_buffers()}
            out_arrays = _tree.tree_map(
                _unwrap_out, out, is_leaf=lambda x: isinstance(x, Tensor))
        finally:
            for n, p in layer.named_parameters():
                p._value = saved_p[n]
            for n, b in layer.named_buffers():
                b._value = saved_b[n]
        return out_arrays, new_buffers


_ast_cache = {}


def _maybe_ast(fn):
    """AST-rewrite tensor-dependent Python control flow (dy2static) when
    enabled; trace-only fallback otherwise. Mirrors the reference's
    ProgramTranslator default-on behavior (program_translator.py).
    Memoized per source function so repeated to_static(f) calls share one
    transformed function (and so one _fn_compiled jit cache entry)."""
    from . import dy2static

    if not dy2static.ast_enabled():
        return fn
    if fn in _ast_cache:
        return _ast_cache[fn]
    try:
        out = dy2static.ast_transform(fn)
    except (OSError, TypeError, ValueError, SyntaxError) as e:
        try:
            fn.__dy2static_fallback_reason__ = str(e)
        except (AttributeError, TypeError):
            pass
        out = fn
    _ast_cache[fn] = out
    return out


def to_static(layer_or_fn=None, input_spec=None, **jit_kwargs):
    """Compile a Layer's forward (or a function over Tensors) with jax.jit.
    Python `if`/`while`/`for range()` over traced Tensors are first
    AST-rewritten to lax control flow (see paddle_tpu.dy2static)."""
    if layer_or_fn is None:
        return functools.partial(to_static, input_spec=input_spec, **jit_kwargs)
    if isinstance(layer_or_fn, Layer):
        layer = layer_or_fn
        fwd = type(layer).forward
        converted = _maybe_ast(fwd)
        if converted is not fwd:
            layer.forward = converted.__get__(layer)
        return CompiledLayer(layer, **jit_kwargs)
    fn = _maybe_ast(layer_or_fn)

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        return _jit_fn(fn)(*args, **kwargs)

    return wrapper


@functools.lru_cache(maxsize=None)
def _fn_compiled(fn):
    def pure(arg_arrays, kw_arrays):
        args = _tree.tree_map(_wrap_in, arg_arrays)
        kwargs = _tree.tree_map(_wrap_in, kw_arrays)
        with tape_mod.no_grad():
            out = fn(*args, **kwargs)
        from .dy2static import UndefinedVarError, _Undefined

        for leaf in _tree.tree_leaves(
                out, is_leaf=lambda x: isinstance(x, (Tensor, _Undefined))):
            if isinstance(leaf, _Undefined):
                raise UndefinedVarError(
                    "the returned value is undefined on some branch path "
                    "— either a tensor-dependent `if` returns on one path "
                    "and falls through on the other, or a returned "
                    "variable was assigned on only one branch")
        return _tree.tree_map(_unwrap_out, out,
                              is_leaf=lambda x: isinstance(x, Tensor))

    return jax.jit(pure)


def _jit_fn(fn):
    compiled = _fn_compiled(fn)

    def run(*args, **kwargs):
        arg_arrays = _tree.tree_map(
            _unwrap_out, args, is_leaf=lambda x: isinstance(x, Tensor))
        kw_arrays = _tree.tree_map(
            _unwrap_out, kwargs, is_leaf=lambda x: isinstance(x, Tensor))
        out = compiled(arg_arrays, kw_arrays)
        return _tree.tree_map(_wrap_in, out)

    return run


class CompiledLayer:
    """jit-compiled inference wrapper around a Layer (AnalysisPredictor-ish)."""

    def __init__(self, layer: Layer, donate_buffers: bool = False):
        self.layer = layer
        self.fmodel = _FunctionalModel(layer)
        self._compiled = jax.jit(
            lambda params, buffers, args, kwargs:
            self.fmodel(params, buffers, args, kwargs),
            static_argnames=())

    def __call__(self, *args, **kwargs):
        params = self.layer.param_pytree()
        buffers = self.layer.buffer_pytree()
        arg_arrays = _tree.tree_map(
            _unwrap_out, args, is_leaf=lambda x: isinstance(x, Tensor))
        kw_arrays = _tree.tree_map(
            _unwrap_out, kwargs, is_leaf=lambda x: isinstance(x, Tensor))
        out, new_buffers = self._compiled(params, buffers, arg_arrays, kw_arrays)
        self.layer.load_buffer_pytree(new_buffers)
        return _tree.tree_map(_wrap_in, out)

    def forward(self, *args, **kwargs):
        return self(*args, **kwargs)


class TrainStep:
    """One fused XLA program: forward + backward + optimizer update.

    Replaces the reference's per-op executor hot loop (executor.cc:476) with
    a single compiled step. loss_fn(model, *batch) must return a scalar
    Tensor (or a tuple whose first element is the loss).
    """

    def __init__(self, model: Layer, loss_fn: Callable, optimizer,
                 seed: int = 0, donate: bool = True, mesh=None,
                 param_rules=None, data_axes=("dp", "data"),
                 data_spec=None, sequence_parallel=None, zero_stage=0,
                 zero_axis="dp"):
        self.model = model
        self.loss_fn = loss_fn
        self.optimizer = optimizer
        self.fmodel = _FunctionalModel(model)
        self._opt_state = None
        self._seed = seed
        self._compiled = None
        self._donate = bool(donate)
        self._seen_sigs = set()   # batch signatures already compiled
        self._donated_nbytes = None  # cached donated-set size
        self._lr_cache = None     # (float value, device scalar)
        self._mesh = mesh
        self._param_rules = param_rules
        self._data_axes = data_axes
        self._data_spec = data_spec  # explicit PartitionSpec for batch leaves
        # "sp" / (axis, impl): bake ring/Ulysses context-parallel attention
        # into the traced step (deterministic, unlike the dynamic
        # sequence_parallel() scope — see parallel/ring.py module note)
        if isinstance(sequence_parallel, str):
            sequence_parallel = (sequence_parallel, "ring")
        self._sequence_parallel = sequence_parallel
        # ZeRO: 0 = off, 1/2 = shard optimizer slots over zero_axis,
        # 3 = also shard the params themselves
        self._zero_stage = zero_stage
        self._zero_axis = zero_axis
        self._placed = False
        # PADDLE_COMPILE_CACHE[_DIR]: route this step's XLA compiles
        # through the disk-persistent cache too (no-op when unset)
        from .static.compile_cache import ensure_enabled
        ensure_enabled()

    def _batch_row_axes(self) -> tuple:
        """Mesh axes the batch's leading (row) dims shard over, from
        data_spec (axis names or tuples per dim) or data_axes."""
        if self._mesh is None:
            return ()
        axes = []
        if self._data_spec is not None:
            for entry in self._data_spec:
                if entry is None:
                    continue
                axes += (list(entry) if isinstance(entry, (tuple, list))
                         else [entry])
        elif self._data_axes:
            axes = list(self._data_axes)
        return tuple(a for a in axes if a in self._mesh.axis_names)

    def _place_spmd(self, params, buffers, batch_arrays):
        """First-call SPMD placement: params per TP rules (replicated over
        dp), batch sharded on the data axes. XLA's partitioner then inserts
        the gradient psum/collectives (replaces the reference's
        multi_devices_graph_pass + allreduce op handles)."""
        from jax.sharding import NamedSharding, PartitionSpec

        from .parallel.sharding import shard_params, zero_shardings

        mesh = self._mesh
        if not self._placed:
            if self._zero_stage:
                pshard, slot_sharding = zero_shardings(
                    params, mesh, axis=self._zero_axis,
                    stage=self._zero_stage, rules=self._param_rules)
            else:
                pshard = shard_params(params, mesh, self._param_rules)

                def slot_sharding(nn, arr):
                    return pshard[nn]
            for n in params:
                params[n] = jax.device_put(params[n], pshard[n])
            rep = NamedSharding(mesh, PartitionSpec())
            for n in buffers:
                buffers[n] = jax.device_put(buffers[n], rep)
            if self._opt_state is not None:
                slots = self._opt_state["slots"]
                for n in slots:
                    slots[n] = _tree.tree_map(
                        lambda a, nn=n: jax.device_put(
                            a, slot_sharding(nn, a)), slots[n])
            self._placed = True
        axes = tuple(a for a in self._data_axes if a in mesh.axis_names)
        if axes or self._data_spec is not None:
            def shard_batch(a):
                nd = getattr(a, "ndim", 0)
                if nd < 1:
                    return a
                if self._data_spec is not None:
                    cleaned = tuple(
                        ax if ax is None or ax in mesh.axis_names else None
                        for ax in self._data_spec[:nd])
                    spec = PartitionSpec(*cleaned)
                else:
                    spec = PartitionSpec(axes if len(axes) > 1 else axes[0])
                return jax.device_put(a, NamedSharding(mesh, spec))

            batch_arrays = tuple(
                _tree.tree_map(shard_batch, b) for b in batch_arrays)
        return params, buffers, batch_arrays

    def _build(self):
        loss_fn = self.loss_fn
        optimizer = self.optimizer
        model = self.model

        def pure_step(params, buffers, opt_state, lr, batch):
            step_idx = opt_state["step"]

            def loss_of(params):
                key = jax.random.fold_in(random_mod.make_key(self._seed), step_idx)
                saved_p ={n: p._value for n, p in model.named_parameters()}
                saved_b = {n: b._value for n, b in model.named_buffers()}
                model.load_param_pytree(params)
                model.load_buffer_pytree(buffers)
                from contextlib import nullcontext

                from .parallel.mesh import trace_mesh as _trace_mesh_scope
                from .parallel.ring import sequence_parallel as _sp_scope

                sp_ctx = (_sp_scope(*self._sequence_parallel,
                                    mesh=self._mesh)
                          if self._sequence_parallel else nullcontext())
                # mark the mesh governing this trace (+ the axes batch
                # rows shard over) so non-shard_map pallas kernels
                # (fused_xent) can shard_map themselves or self-gate
                mesh_ctx = _trace_mesh_scope(self._mesh,
                                             self._batch_row_axes())
                try:
                    with tape_mod.no_grad(), rng_scope(key), sp_ctx, \
                            mesh_ctx:
                        out = loss_fn(model, *[_wrap_in(b) for b in batch])
                    loss = out[0] if isinstance(out, (tuple, list)) else out
                    aux = out[1:] if isinstance(out, (tuple, list)) else ()
                    new_buffers = {n: b._value for n, b in model.named_buffers()}
                    loss_arr = _unwrap_out(loss)
                    aux_arr = _tree.tree_map(
                        _unwrap_out, tuple(aux),
                        is_leaf=lambda x: isinstance(x, Tensor))
                finally:
                    for n, p in model.named_parameters():
                        p._value = saved_p[n]
                    for n, b in model.named_buffers():
                        b._value = saved_b[n]
                return loss_arr, (new_buffers, aux_arr)

            (loss, (new_buffers, aux)), grads = jax.value_and_grad(
                loss_of, has_aux=True)(params)
            new_params, new_opt_state = optimizer.apply_gradients_fn(
                grads, params, opt_state, lr)
            return loss, aux, new_params, new_buffers, new_opt_state

        # params + optimizer state are donated: XLA updates the (large)
        # parameter/moment buffers in place instead of allocating a fresh
        # set per step. donate=False keeps every input buffer readable.
        jit_kwargs = {"donate_argnums": (0, 2)} if self._donate else {}
        self._compiled = jax.jit(pure_step, **jit_kwargs)

    def __call__(self, *batch):
        model = self.model
        params = {n: p.value for n, p in model.named_parameters()
                  if p.trainable}
        buffers = model.buffer_pytree()
        if self._opt_state is None:
            self._opt_state = self.optimizer.init_state(
                params, {n: p for n, p in model.named_parameters()
                         if p.trainable})
        if self._compiled is None:
            self._build()
        from . import profiler

        # device lr scalar is cached on its float value: an unchanged lr
        # costs zero per-step h2d transfers (schedulers invalidate it)
        lr_val = float(self.optimizer.get_lr())
        if self._lr_cache is None or self._lr_cache[0] != lr_val:
            self._lr_cache = (lr_val, jnp.asarray(lr_val, jnp.float32))
            profiler.bump_counter("h2d_bytes", 4)
        lr = self._lr_cache[1]
        batch_arrays = tuple(
            _tree.tree_map(_unwrap_out, b,
                           is_leaf=lambda x: isinstance(x, Tensor))
            for b in batch)
        sig = tuple(
            (tuple(getattr(a, "shape", ())), str(getattr(a, "dtype", "")))
            for a in _tree.tree_leaves(batch_arrays))
        new_sig = sig not in self._seen_sigs
        if new_sig:
            self._seen_sigs.add(sig)
        profiler.bump_counter(
            "compile_cache_misses" if new_sig else "compile_cache_hits")
        profiler.bump_counter("executor_steps")
        if self._mesh is not None:
            params, buffers, batch_arrays = self._place_spmd(
                params, buffers, batch_arrays)
        if self._donate:
            if new_sig or self._donated_nbytes is None:
                # O(param leaves) walk only on a fresh signature — the
                # donated set is invariant across steady-state steps
                self._donated_nbytes = sum(
                    int(getattr(a, "nbytes", 0) or 0)
                    for tree in (params, self._opt_state)
                    for a in _tree.tree_leaves(tree))
            profiler.bump_counter("donated_bytes", self._donated_nbytes)
        loss, aux, new_params, new_buffers, new_opt_state = self._compiled(
            params, buffers, self._opt_state, lr, batch_arrays)
        for n, p in model.named_parameters():
            if n in new_params:
                p._value = new_params[n]
            # mirror device-side slots into the optimizer's eager store so
            # optimizer.state_dict() (Model.save) sees trained moments
            if n in new_opt_state["slots"]:
                self.optimizer._slots[id(p)] = new_opt_state["slots"][n]
        model.load_buffer_pytree(new_buffers)
        self._opt_state = new_opt_state
        # host-side counter: no device sync per step (async dispatch stays
        # ahead of the chip; the device-side step lives in opt_state)
        self.optimizer._step_count += 1
        if aux:
            return (Tensor(loss),) + tuple(_tree.tree_map(_wrap_in, a) for a in aux)
        return Tensor(loss)

    @property
    def opt_state(self):
        return self._opt_state


def _check_save_load_config(config):
    """SaveLoadConfig knobs the StableHLO export does not implement
    must fail LOUDLY, not round-trip into the void (r5 review): the
    export always carries all forward outputs under the default
    .pdmodel/.pdiparams names."""
    cfg = config.pop("config", None)
    if config:
        raise TypeError(f"unknown jit.save/load options {sorted(config)}")
    if cfg is None:
        return
    unsupported = []
    if getattr(cfg, "output_spec", None):
        unsupported.append("output_spec (all outputs are exported; "
                           "select at call time)")
    for knob in ("model_filename", "params_filename"):
        if getattr(cfg, knob, None):
            unsupported.append(f"{knob} (fixed .pdmodel/.pdiparams "
                               "naming)")
    if unsupported:
        raise NotImplementedError(
            "SaveLoadConfig knobs not supported by the StableHLO "
            "export: " + "; ".join(unsupported))


def _merge_configs_alias(config, configs):
    """Reference signature parity: jit.save/load take the knob container
    as ``configs=`` (fluid/dygraph/jit.py); ``config=`` is the historical
    keyword this port accepted. Either spelling lands in the same checked
    slot; passing both is ambiguous and refused."""
    if configs is not None:
        if config.get("config") is not None:
            raise TypeError(
                "pass the SaveLoadConfig as either config= or configs=, "
                "not both")
        config["config"] = configs
    return config


def save(layer, path, input_spec=None, configs=None, **config):
    """jit.save parity: persist params + a StableHLO export of forward."""
    from .io.serialization import save_inference_model

    _check_save_load_config(_merge_configs_alias(config, configs))
    save_inference_model(path, layer, input_spec)


def load(path, configs=None, **config):
    from .io.serialization import load_inference_model

    _check_save_load_config(_merge_configs_alias(config, configs))
    return load_inference_model(path)


class SaveLoadConfig:
    """jit.SaveLoadConfig parity (reference fluid/dygraph/jit.py:270):
    knob container for jit.save/load. output_spec selects forward
    outputs to keep; model/params filenames name the export files;
    separate_params/keep_name_table are storage-layout knobs the
    StableHLO export does not need but keeps for API compatibility."""

    def __init__(self):
        self._output_spec = None
        self._model_filename = None
        self._params_filename = None
        self._separate_params = False
        self._keep_name_table = False

    @property
    def output_spec(self):
        return self._output_spec

    @output_spec.setter
    def output_spec(self, spec):
        self._output_spec = spec

    @property
    def model_filename(self):
        return self._model_filename

    @model_filename.setter
    def model_filename(self, filename):
        self._model_filename = filename

    @property
    def params_filename(self):
        return self._params_filename

    @params_filename.setter
    def params_filename(self, filename):
        self._params_filename = filename

    @property
    def separate_params(self):
        return self._separate_params

    @separate_params.setter
    def separate_params(self, value):
        self._separate_params = bool(value)

    @property
    def keep_name_table(self):
        return self._keep_name_table

    @keep_name_table.setter
    def keep_name_table(self, value):
        self._keep_name_table = bool(value)


def __getattr__(name):
    """Lazy paddle.jit surface re-exports (import-cycle-free):
    TracedLayer lives in dygraph.py, ProgramTranslator in dy2static,
    TranslatedLayer in io.serialization."""
    if name == "TracedLayer":
        from .dygraph import TracedLayer

        return TracedLayer
    if name == "ProgramTranslator":
        from .dy2static import ProgramTranslator

        return ProgramTranslator
    if name == "TranslatedLayer":
        from .io.serialization import TranslatedLayer

        return TranslatedLayer
    raise AttributeError(f"module 'paddle_tpu.jit' has no attribute {name!r}")
